"""Telemetry-driven autotuning (ISSUE 9, mxnet_tpu/autotune/).

Coverage demanded by the issue:
- winner-store invalidation is CORRUPTION-SAFE (mirrors test_aot_cache):
  a stale jax/jaxlib version fingerprint and a changed device kind each
  produce a silent miss + re-search (never a stale winner), and a
  truncated or garbage store file never crashes;
- persistence acceptance: a second search against a warm store performs
  ZERO new measurements;
- the searcher measures the hand-tuned default first and keeps it on a
  tie — adopting a winner can never regress shipped behavior;
- ``MXNET_AUTOTUNE`` unset => byte-identical behavior: the dconv grid
  ignores persisted winners, the Engine ladder selection never imports
  the package, no store file is read;
- the ladder tuner's replay objective and never-worse proposal;
- dconv numeric parity across tuned block sizes;
- the ``--gate-warmup`` / ``--prune-baseline`` tool satellites.
"""
import json
import math
import os

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (conftest seeding imports it anyway)
from mxnet_tpu import autotune
from mxnet_tpu.autotune import costmodel as cm
from mxnet_tpu.autotune import ladder as lt
from mxnet_tpu.autotune import measure as ms
from mxnet_tpu.autotune import space as sps
from mxnet_tpu.autotune import store as st
from mxnet_tpu.telemetry import instrument as tin

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load_tool(relpath):
    from mxnet_tpu.test_utils import load_module_by_path

    return load_module_by_path(os.path.join(REPO, relpath))


@pytest.fixture
def at_on(tmp_path, monkeypatch):
    """Autotuning ON against a private store file; counters reset."""
    monkeypatch.setenv("MXNET_AUTOTUNE", "1")
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE", str(tmp_path / "at.json"))
    st._reset_stats_for_tests()
    ms._reset_stats_for_tests()
    yield str(tmp_path / "at.json")
    st._reset_stats_for_tests()
    ms._reset_stats_for_tests()


@pytest.fixture
def at_off(tmp_path, monkeypatch):
    """Gate unset but a store file PRESENT — the off path must never read
    it."""
    monkeypatch.delenv("MXNET_AUTOTUNE", raising=False)
    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE", str(tmp_path / "at.json"))
    st._reset_stats_for_tests()
    yield str(tmp_path / "at.json")
    st._reset_stats_for_tests()


@pytest.fixture
def tel_enabled(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    tin._reset_for_tests()
    yield
    tin._reset_for_tests()


def _counter_total(name, **labels):
    m = tin.registry().get(name)
    if m is None:
        return 0
    return sum(v["value"] for v in m.samples()
               if all(v["labels"].get(k) == lv for k, lv in labels.items()))


# -- winner store -------------------------------------------------------------
class TestStore:
    def test_record_lookup_roundtrip(self, at_on):
        assert autotune.lookup("k", "sig-a") is None
        autotune.record("k", "sig-a", {"nblk": 64}, score=0.5)
        assert autotune.lookup("k", "sig-a") == {"nblk": 64}
        assert autotune.lookup("k", "sig-b") is None  # other sig untouched
        s = autotune.stats()
        assert s["hits"] == 1 and s["misses"] == 2 and s["errors"] == 0

    def test_counters_reach_registry(self, at_on, tel_enabled):
        autotune.record("k", "s", {"x": 1})
        autotune.lookup("k", "s")
        autotune.lookup("k", "other")
        assert _counter_total("autotune_cache_hits_total", kernel="k") == 1
        assert _counter_total("autotune_cache_misses_total", kernel="k") == 1

    def test_stale_jax_version_is_silent_miss(self, at_on, monkeypatch):
        autotune.record("k", "s", {"nblk": 32})
        assert autotune.lookup("k", "s") == {"nblk": 32}
        # "restart" onto a different jax/jaxlib build
        monkeypatch.setattr(st, "_versions", lambda: ("0.0.0", "0.0.0"))
        assert autotune.lookup("k", "s") is None  # rejected, not crashed
        s = autotune.stats()
        assert s["errors"] == 1
        # the re-search overwrites under the new fingerprint: hits again
        autotune.record("k", "s", {"nblk": 64})
        assert autotune.lookup("k", "s") == {"nblk": 64}

    def test_device_kind_change_is_clean_miss(self, at_on, monkeypatch):
        real_kind = st._device_kind
        autotune.record("k", "s", {"nblk": 32})
        monkeypatch.setattr(st, "_device_kind", lambda: "TPU v5e")
        # different device kind = different key: a miss, then its own entry
        assert autotune.lookup("k", "s") is None
        autotune.record("k", "s", {"nblk": 256})
        assert autotune.lookup("k", "s") == {"nblk": 256}
        monkeypatch.setattr(st, "_device_kind", real_kind)
        # the original device kind's winner survived alongside
        assert autotune.lookup("k", "s") == {"nblk": 32}

    def test_truncated_store_never_crashes(self, at_on):
        autotune.record("k", "s", {"nblk": 64})
        with open(at_on, "rb") as f:
            blob = f.read()
        with open(at_on, "wb") as f:
            f.write(blob[:16])  # torn write
        assert autotune.lookup("k", "s") is None
        assert autotune.stats()["errors"] >= 1
        # re-record repairs the file
        autotune.record("k", "s", {"nblk": 64})
        assert autotune.lookup("k", "s") == {"nblk": 64}

    def test_garbage_store_never_crashes(self, at_on):
        with open(at_on, "w") as f:
            f.write("\x00 not json at all")
        assert autotune.lookup("k", "s") is None
        autotune.record("k2", "s2", {"a": 1})
        assert autotune.lookup("k2", "s2") == {"a": 1}

    def test_malformed_entry_config_rejected(self, at_on):
        autotune.record("k", "s", {"nblk": 64})
        with open(at_on) as f:
            payload = json.load(f)
        key = next(iter(payload["entries"]))
        payload["entries"][key]["config"] = "not-a-dict"
        with open(at_on, "w") as f:
            json.dump(payload, f)
        assert autotune.lookup("k", "s") is None
        assert autotune.stats()["errors"] == 1

    def test_clear_by_kernel(self, at_on):
        autotune.record("a", "s", {"x": 1})
        autotune.record("b", "s", {"x": 2})
        assert autotune.clear(kernel="a") == 1
        assert autotune.lookup("a", "s") is None
        assert autotune.lookup("b", "s") == {"x": 2}
        assert autotune.clear() == 1
        assert autotune.entries() == {}

    def test_override_wins_without_store_read(self, at_on):
        autotune.record("k", "s", {"nblk": 128})
        with autotune.override("k", {"nblk": 32}):
            assert autotune.config_for("k", "s") == {"nblk": 32}
        assert autotune.config_for("k", "s") == {"nblk": 128}


# -- the MXNET_AUTOTUNE off path ----------------------------------------------
class TestOffPath:
    def test_lookup_never_touches_store(self, at_off):
        with open(at_off, "w") as f:
            f.write("garbage that would count an error if read")
        assert autotune.lookup("k", "s") is None
        assert autotune.stats() == {"hits": 0, "misses": 0, "errors": 0}

    def test_dconv_grid_ignores_winner(self, at_off, monkeypatch):
        from mxnet_tpu.ops import pallas_kernels as pk

        monkeypatch.setenv("MXNET_AUTOTUNE", "1")
        autotune.record("dconv_col_pallas",
                        autotune.dconv_shape_sig(512, 2432, 512, 4),
                        {"nblk": 64})
        assert pk._dconv_grid(512, 2432, 512, 4) == (64, 512)
        monkeypatch.delenv("MXNET_AUTOTUNE")
        # gate off: the persisted winner is invisible — no store read at all
        monkeypatch.setattr(st, "lookup",
                            lambda *a, **k: pytest.fail("store read on the "
                                                        "off path"))
        assert pk._dconv_grid(512, 2432, 512, 4) == (128, 512)

    def test_engine_keeps_default_ladder(self, at_off, monkeypatch):
        from mxnet_tpu.serving import Engine
        from mxnet_tpu.test_utils import tiny_mlp_checkpoint

        monkeypatch.setenv("MXNET_AUTOTUNE", "1")
        autotune.record(autotune.LADDER_KERNEL,
                        autotune.ladder_sig({"data": (8,)}),
                        {"batch_sizes": [1, 3, 6]})
        monkeypatch.delenv("MXNET_AUTOTUNE")
        sym, params = tiny_mlp_checkpoint()
        eng = Engine(sym, params, {"data": (8,)}, start=False)
        assert eng.ladder.batch_sizes == (1, 2, 4, 8)
        eng.close()


# -- dconv wiring -------------------------------------------------------------
class TestDconvWiring:
    def test_tuned_grid_and_numeric_parity(self, at_on):
        """A tuned block size changes the grid, not the numbers: outputs
        and gradients across nblk in {32, 128} are identical (interpret
        mode; padded rows carry lf=0 so block layout is value-neutral)."""
        import jax
        import jax.numpy as jnp

        from mxnet_tpu.ops import pallas_kernels as pk

        BG, N, H, W, C = 2, 70, 5, 8, 16
        HW = H * W
        rng = np.random.RandomState(0)
        y0 = jnp.asarray(rng.randint(0, H - 1, (BG, N)).astype(np.int32))
        y1 = jnp.minimum(y0 + 1, H - 1)
        x0 = jnp.asarray(rng.randint(0, W - 1, (BG, N)).astype(np.int32))
        x1 = jnp.minimum(x0 + 1, W - 1)
        ly = jnp.asarray(rng.rand(BG, N).astype(np.float32))
        lx = jnp.asarray(rng.rand(BG, N).astype(np.float32))
        lf = jnp.asarray((rng.rand(BG, N) > 0.2).astype(np.float32))
        ft = jnp.asarray(rng.randn(BG, HW, C).astype(np.float32))
        g = jnp.asarray(rng.randn(BG, N, C).astype(np.float32))

        def run(nblk):
            with autotune.override("dconv_col_pallas", {"nblk": nblk}):
                assert pk._dconv_grid(N, HW, C, 4)[0] == min(nblk, N)

                def loss(ly, lx, lf, ft):
                    out = pk.dconv_col_pallas(y0, y1, x0, x1, ly, lx, lf,
                                              ft, (H, W), True)
                    return jnp.sum(out * g)

                out = pk.dconv_col_pallas(y0, y1, x0, x1, ly, lx, lf, ft,
                                          (H, W), True)
                grads = jax.grad(loss, argnums=(0, 1, 2, 3))(ly, lx, lf, ft)
                return out, grads

        out_a, g_a = run(32)
        out_b, g_b = run(128)
        np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                                   rtol=1e-6, atol=1e-6)
        for ga, gb in zip(g_a, g_b):
            np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                       rtol=1e-5, atol=1e-6)

    def test_winner_revalidated_against_current_vmem_budget(
            self, at_on, monkeypatch):
        """A winner persisted under a larger MXNET_DCONV_VMEM_MB must not
        be adopted once the budget shrinks below its working set — the
        guard re-decides at adoption time, so a stale winner can never
        hard-fail Mosaic (it falls back to the hand-tuned default)."""
        from mxnet_tpu.ops import pallas_kernels as pk

        N, HW, C, itemsize = 4096, 2432, 512, 2
        with autotune.override("dconv_col_pallas", {"nblk": 512}):
            # generous budget: the pinned winner is adopted
            monkeypatch.setenv("MXNET_DCONV_VMEM_MB", "256")
            assert pk._dconv_grid(N, HW, C, itemsize)[0] == 512
            # shrunk budget: same winner now exceeds the backward working
            # set -> default, not a crash
            monkeypatch.setenv("MXNET_DCONV_VMEM_MB", "24")
            assert not pk.dconv_fits_vmem(HW, C, itemsize, nblk=512)
            assert pk._dconv_grid(N, HW, C, itemsize)[0] == pk._DCONV_NBLK

    def test_space_constraint_is_the_vmem_guard(self):
        sp = autotune.get_space("dconv_col_pallas")
        # north-star res5: 256/512-row blocks blow the backward VMEM budget
        cfgs = sp.configs(N=2432, HW=2432, C=512, itemsize=2)
        nblks = {c["nblk"] for c in cfgs}
        assert 128 in nblks and 512 not in nblks
        # tiny problems admit everything
        assert len(sp.configs(N=128, HW=32, C=16, itemsize=4)) == 5


# -- searcher -----------------------------------------------------------------
class TestSearch:
    def _space(self, choices=(32, 64, 128), default=128):
        return autotune.TuningSpace("k", {"nblk": choices},
                                    {"nblk": default})

    def test_default_wins_ties(self):
        best, results = autotune.run_search(self._space(),
                                            lambda cfg: 1.0)  # all tie
        assert best == {"nblk": 128}
        assert results[0]["config"] == {"nblk": 128}  # measured first

    def test_strictly_better_candidate_wins(self):
        best, results = autotune.run_search(
            self._space(), lambda cfg: 0.5 if cfg["nblk"] == 64 else 1.0)
        assert best == {"nblk": 64}
        assert len(results) == 3

    def test_greedy_descent_beyond_max_trials(self):
        space = autotune.TuningSpace(
            "k", {"a": tuple(range(8)), "b": tuple(range(8))},
            {"a": 0, "b": 0})

        def measure(cfg):  # separable bowl, optimum (5, 3)
            return (cfg["a"] - 5) ** 2 + (cfg["b"] - 3) ** 2 + 1.0

        best, results = autotune.run_search(space, measure, max_trials=40)
        assert best == {"a": 5, "b": 3}
        assert len(results) <= 40

    def test_measure_candidate_counts_trials(self, at_on, tel_enabled):
        import jax.numpy as jnp

        before = autotune.measurements()
        t = autotune.measure_candidate(
            "k", {"nblk": 1}, lambda: (lambda x: x + 1),
            (jnp.ones((4,)),), warmup=1, repeat=2)
        assert t > 0
        assert autotune.measurements() == before + 1
        assert _counter_total("autotune_trials_total", kernel="k") == 1
        assert tin.summary()["autotune_trials"] == 1


# -- ladder tuner -------------------------------------------------------------
def _mk_trace(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return str(path)


def _rec(t, n, shape=(8,), klass="open"):
    return {"t": t, "n": n, "shapes": {"data": list(shape)}, "class": klass}


class TestLadder:
    def test_objective_hand_computed(self):
        # two n=1 requests 1 ms apart coalesce (within max_wait); one n=3
        # a second later is its own batch.  Ladder (2, 4): batch of 2 is
        # exact, batch of 3 pads to 4.  vol(sample) = 8.
        recs = [_rec(0.0, 1), _rec(0.001, 1), _rec(1.0, 3)]
        # padded = 2*8 + 4*8 = 48; real = 5*8 = 40; compiles = 2
        assert lt.objective((2, 4), recs) == pytest.approx(48 / 40 * 2)
        # single rung 4: (4+4)*8 / 40 * 1
        assert lt.objective((4,), recs) == pytest.approx(64 / 40)

    def test_oversize_goes_direct(self):
        recs = [_rec(0.0, 9), _rec(1.0, 1)]
        # n=9 > top rung 4: exact one-off (no padding, inflation stays 1)
        # but its own compile — 2 rungs + 1 direct signature
        assert lt.objective((1, 4), recs) == pytest.approx(3.0)

    def test_propose_beats_default_on_skewed_traffic(self, tmp_path):
        recs = [_rec(i * 0.05, n) for i, n in enumerate([3, 5, 6] * 20)]
        tuned, rep = lt.propose(recs)
        assert rep["objective_tuned"] < rep["objective_default"]
        assert lt.objective(tuned, recs) == pytest.approx(
            rep["objective_tuned"])

    def test_propose_never_worse_keeps_default(self):
        # traffic the default ladder serves exactly: all n=8, far apart
        recs = [_rec(i * 1.0, 8) for i in range(10)]
        tuned, rep = lt.propose(recs, default=(8,))
        assert tuned == (8,)
        assert rep["objective_tuned"] == rep["objective_default"]

    def test_load_trace_validates(self, tmp_path):
        p = _mk_trace(tmp_path / "t.jsonl", [_rec(0.0, 1)])
        assert len(lt.load_trace(p)) == 1
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"t": 0.0, "n": 0, "shapes": {}, "class": "x"}\n')
        with pytest.raises(ValueError):
            lt.load_trace(str(bad))
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        with pytest.raises(ValueError):
            lt.load_trace(str(empty))

    def test_ladder_sig_matches_engine_side(self):
        recs = [_rec(0.0, 1, shape=(3, 4)), _rec(0.1, 2, shape=(3, 4))]
        shapes = lt.trace_sample_shapes(recs)
        assert lt.ladder_sig(shapes) == lt.ladder_sig({"data": (3, 4)})


# -- engine adoption ----------------------------------------------------------
class TestEngineAdoption:
    def test_tuned_ladder_adopted(self, at_on):
        from mxnet_tpu.serving import BucketLadder, Engine
        from mxnet_tpu.test_utils import tiny_mlp_checkpoint

        autotune.record(autotune.LADDER_KERNEL,
                        autotune.ladder_sig({"data": (8,)}),
                        {"batch_sizes": [1, 3, 6]})
        sym, params = tiny_mlp_checkpoint()
        eng = Engine(sym, params, {"data": (8,)}, start=False)
        assert eng.ladder.batch_sizes == (1, 3, 6)
        eng.close()
        # an explicit ladder argument always wins over the store
        eng2 = Engine(sym, params, {"data": (8,)},
                      ladder=BucketLadder((1, 2)), start=False)
        assert eng2.ladder.batch_sizes == (1, 2)
        eng2.close()

    def test_malformed_ladder_winner_falls_back(self, at_on):
        from mxnet_tpu.serving import Engine
        from mxnet_tpu.test_utils import tiny_mlp_checkpoint

        autotune.record(autotune.LADDER_KERNEL,
                        autotune.ladder_sig({"data": (8,)}),
                        {"batch_sizes": "garbage"})
        sym, params = tiny_mlp_checkpoint()
        eng = Engine(sym, params, {"data": (8,)}, start=False)
        assert eng.ladder.batch_sizes == (1, 2, 4, 8)
        eng.close()

    def test_aot_fingerprint_keys_store_state(self, at_on, monkeypatch):
        """Adopted winners shape traced programs, so the AOT-cache env
        fingerprint must fold the store state in while the gate is on —
        and stay byte-identical to a pre-autotune build when it is off
        (an executable traced under one winner set can never restore
        under another, nor cross the gate boundary)."""
        from mxnet_tpu import compile_cache

        fp_on = compile_cache._env_fingerprint()
        assert fp_on["autotune"] == autotune.store.state_digest()
        autotune.record("dconv_col_pallas", "sigX", {"nblk": 256})
        fp_after = compile_cache._env_fingerprint()
        assert fp_after["autotune"] != fp_on["autotune"]
        monkeypatch.delenv("MXNET_AUTOTUNE")
        fp_off = compile_cache._env_fingerprint()
        assert "autotune" not in fp_off

    def test_numeric_string_winner_rejected(self, at_on):
        # "248" would iterate into rungs (2, 4, 8) if types weren't
        # checked — a malformed winner must keep the default, not adopt a
        # ladder nobody proposed
        autotune.record(autotune.LADDER_KERNEL,
                        autotune.ladder_sig({"data": (9,)}),
                        {"batch_sizes": "248"})
        assert autotune.tuned_ladder({"data": (9,)}) is None


# -- CLI ----------------------------------------------------------------------
class TestCLI:
    def test_dconv_search_then_warm_store_zero_measurements(self, at_on):
        at = _load_tool("tools/autotune.py")
        argv = ["search", "--kernel", "dconv_col_pallas",
                "--n", "64", "--h", "4", "--w", "8", "--c", "16",
                "--warmup", "1", "--repeat", "1"]
        assert at.main(list(argv)) == 0
        first = autotune.measurements()
        assert first > 0
        sig = autotune.dconv_shape_sig(64, 32, 16, 4)
        winner = autotune.lookup("dconv_col_pallas", sig)
        assert winner is not None and "nblk" in winner
        # persistence acceptance: the second run measures NOTHING
        assert at.main(list(argv)) == 0
        assert autotune.measurements() == first
        # --force re-searches
        assert at.main(list(argv) + ["--force"]) == 0
        assert autotune.measurements() > first

    def test_ladder_search_roundtrip(self, at_on, tmp_path, capsys):
        at = _load_tool("tools/autotune.py")
        trace = _mk_trace(tmp_path / "t.jsonl",
                          [_rec(i * 0.05, n)
                           for i, n in enumerate([3, 5, 6] * 10)])
        assert at.main(["search", "--trace", trace]) == 0
        line = [l for l in capsys.readouterr().out.splitlines()
                if l.startswith("AUTOTUNE ")][-1]
        payload = json.loads(line[len("AUTOTUNE "):])
        assert payload["objective_tuned"] < payload["objective_default"]
        tuned = autotune.tuned_ladder({"data": (8,)})
        assert tuned == tuple(payload["config"]["batch_sizes"])
        # warm second run, then show + clear
        assert at.main(["search", "--trace", trace]) == 0
        line2 = [l for l in capsys.readouterr().out.splitlines()
                 if l.startswith("AUTOTUNE ")][-1]
        assert json.loads(line2[len("AUTOTUNE "):])["cached"] is True
        assert at.main(["show"]) == 0
        assert "bucket_ladder" in capsys.readouterr().out
        assert at.main(["clear"]) == 0
        assert autotune.entries() == {}

    def test_show_features_surface(self, at_on, capsys):
        at = _load_tool("tools/autotune.py")
        autotune.record(
            "dconv_col_pallas", "N64-HW32-C16-i4", {"nblk": 64}, score=1e-4,
            meta={"strategy": "grid", "grid": 5,
                  "cost": {"flops": 3.0},
                  "trial_costs": [{"config": {"nblk": 64}, "seconds": 1e-4,
                                   "cost": {"flops": 3.0}}]})
        assert at.main(["show"]) == 0
        plain = capsys.readouterr().out
        assert "cost:" not in plain and "trial rows:" not in plain
        assert at.main(["show", "--features"]) == 0
        out = capsys.readouterr().out
        assert 'cost: {"flops": 3.0}' in out
        assert "trial rows: 1 (strategy=grid, grid=5)" in out

    def test_predict_strategy_in_process(self, at_on, monkeypatch, capsys):
        """Grid-seed one shape under MXNET_COSTPLANE (the trial rows the
        model trains on), then a predict search at a FRESH shape measures
        only default + top-1 and surfaces trials_saved."""
        monkeypatch.setenv("MXNET_COSTPLANE", "1")
        at = _load_tool("tools/autotune.py")

        def lines():
            return [json.loads(l[len("AUTOTUNE "):])
                    for l in capsys.readouterr().out.splitlines()
                    if l.startswith("AUTOTUNE ")]

        # two seeded shapes: the runner dedups by EFFECTIVE (N-capped)
        # nblk, so N64 measures 2 configs and N96 measures 3 — 5 rows
        for n in ("64", "96"):
            assert at.main(["search", "--kernel", "dconv_col_pallas",
                            "--n", n, "--h", "4", "--w", "8", "--c", "16",
                            "--strategy", "grid",
                            "--warmup", "0", "--repeat", "1"]) == 0
            seeded = lines()[-1]
            assert seeded["strategy"] == "grid" and not seeded["cached"]
        from mxnet_tpu.autotune import costmodel as cmod

        assert len(cmod.training_rows("dconv_col_pallas")) >= cmod.MIN_ROWS
        assert at.main(["search", "--kernel", "dconv_col_pallas",
                        "--n", "128", "--h", "4", "--w", "8", "--c", "16",
                        "--strategy", "predict",
                        "--top-k", "1", "--warmup", "0",
                        "--repeat", "1"]) == 0
        pred = lines()[-1]
        assert pred["strategy"] == "predict"
        assert pred["measurements"] == 2 and pred["grid"] == 3
        assert pred["trials_saved"] == 1
        # never-worse: a non-default winner strictly beat the default
        default_cfg = autotune.get_space("dconv_col_pallas").default
        assert pred["config"] == default_cfg \
            or pred["best_s"] < pred["default_s"]


# -- tool satellites ----------------------------------------------------------
class TestToolSatellites:
    def test_bench_compare_gate_warmup_opt_in(self, tmp_path):
        bc = _load_tool("tools/bench_compare.py")

        def capture(path, warmup_s):
            json.dump({"metric": "m", "value": 100.0, "unit": "img/s",
                       "telemetry": {"compile_s": 1.0,
                                     "peak_hbm_bytes": None,
                                     "data_wait_frac": 0.0,
                                     "warmup_s": warmup_s}},
                      open(path, "w"))
            return path

        base = capture(str(tmp_path / "b.json"), 1.0)
        slow = capture(str(tmp_path / "s.json"), 2.0)
        # default: Δwarmup% shown, never gated
        assert bc.main([base, slow, "--threshold", "5"]) == 0
        # opt-in gate trips on the doubled warmup
        assert bc.main([base, slow, "--threshold", "5",
                        "--gate-warmup"]) == 1
        # regression-free pair passes with the gate on
        ok = capture(str(tmp_path / "ok.json"), 1.02)
        assert bc.main([base, ok, "--threshold", "5", "--gate-warmup"]) == 0

    def test_mxlint_prune_baseline(self, tmp_path, capsys):
        from mxnet_tpu.analysis import source_lint

        lint = _load_tool("tools/mxlint.py")
        src = tmp_path / "m.py"
        src.write_text("import jax\n\n@jax.jit\ndef f(x):\n"
                       "    return float(x)\n")
        # same root the CLI lints with, so fingerprints line up
        (f,) = source_lint.lint_paths([str(src)], root=REPO)
        bl = tmp_path / "baseline.txt"
        bl.write_text("# header comment\n"
                      "%s  # justified, must survive\n"
                      "m.py::gone@dead line::some-rule\n" % f.fingerprint)
        # pruning the SHARED default baseline from a partial lint is
        # refused (out-of-scope entries would all look stale), and the
        # baseline file is left untouched
        rc = lint.main([str(src), "--prune-baseline"])
        assert rc == 2
        rc = lint.main([str(src), "--baseline", str(bl),
                        "--prune-baseline"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "pruned 1 stale" in out
        text = bl.read_text()
        assert f.fingerprint in text and "justified, must survive" in text
        assert "gone@dead line" not in text
        assert text.startswith("# header comment")
        # second prune: nothing stale left
        assert lint.main([str(src), "--baseline", str(bl),
                          "--prune-baseline"]) == 0
        assert "no stale entries" in capsys.readouterr().out


# -- learned cost model (ISSUE 18) --------------------------------------------
def _synthetic_rows(sigs=(64, 128, 256), nblks=(32, 64, 128, 256)):
    """Training rows whose latency grows with the block size at every
    shape — any sane fit must rank small blocks first."""
    rows = []
    for n in sigs:
        for nblk in nblks:
            rows.append({"kernel": "k", "device_kind": "cpu",
                         "sig": "N%d-HW32-C16-i4" % n,
                         "config": {"nblk": nblk},
                         "seconds": 1e-6 * nblk * (1.0 + n / 512.0),
                         "cost": None})
    return rows


class TestCostModel:
    def test_fit_ranks_monotone_cost(self):
        m = cm.CostModel().fit(_synthetic_rows())
        assert m.ready
        ranked = m.rank("N128-HW32-C16-i4",
                        [{"nblk": b} for b in (256, 32, 128, 64)])
        assert [c["nblk"] for c in ranked] == [32, 64, 128, 256]

    def test_transfer_to_unseen_shape(self):
        """Shape-signature features carry the fit to a sig never searched:
        the model still orders blocks by cost at N512."""
        m = cm.CostModel().fit(_synthetic_rows(sigs=(64, 128, 256)))
        unseen = "N512-HW32-C16-i4"
        assert m.predict_one(unseen, {"nblk": 32}) \
            < m.predict_one(unseen, {"nblk": 256})

    def test_training_rows_filters_junk(self, at_on):
        autotune.record("k", "N64-HW32-C16-i4", {"nblk": 64}, score=1e-4,
                        meta={"trial_costs": [
                            {"config": {"nblk": 64}, "seconds": 1e-4,
                             "cost": {"flops": 2.0}},
                            {"config": {"nblk": 32},
                             "seconds": float("inf")},   # failed sentinel
                            {"config": {"nblk": 16}, "seconds": -1.0},
                            {"config": "junk", "seconds": 1e-4},
                            "not-a-dict"]})
        autotune.record("other", "sigY", {"x": 1}, meta={"trial_costs": [
            {"config": {"x": 1}, "seconds": 2e-4}]})
        rows = cm.training_rows("k")
        assert len(rows) == 1
        assert rows[0]["config"] == {"nblk": 64}
        assert rows[0]["cost"] == {"flops": 2.0}
        # no kernel filter: both kernels' usable rows
        assert len(cm.training_rows()) == 2

    def test_model_for_needs_min_rows(self, at_on):
        autotune.record("k", "N64-HW32-C16-i4", {"nblk": 64}, meta={
            "trial_costs": [{"config": {"nblk": b}, "seconds": 1e-6 * b}
                            for b in (32, 64)]})
        assert cm.model_for("k") is None  # 2 < MIN_ROWS
        autotune.record("k", "N128-HW32-C16-i4", {"nblk": 64}, meta={
            "trial_costs": [{"config": {"nblk": b}, "seconds": 2e-6 * b}
                            for b in (32, 64, 128)]})
        m = cm.model_for("k")
        assert m is not None and m.ready

    def test_default_top_k(self, monkeypatch):
        monkeypatch.delenv("MXNET_AUTOTUNE_TOPK", raising=False)
        assert cm.default_top_k(8) == 2
        assert cm.default_top_k(3) == 1   # never zero
        monkeypatch.setenv("MXNET_AUTOTUNE_TOPK", "3")
        assert cm.default_top_k(100) == 3
        monkeypatch.setenv("MXNET_AUTOTUNE_TOPK", "garbage")
        assert cm.default_top_k(8) == 2   # unparsable = unset

    def test_model_enabled_gate(self, monkeypatch):
        monkeypatch.delenv("MXNET_AUTOTUNE_MODEL", raising=False)
        assert cm.model_enabled()          # default ON (advisory)
        monkeypatch.setenv("MXNET_AUTOTUNE_MODEL", "0")
        assert not cm.model_enabled()


class TestPredictThenMeasure:
    def _space(self):
        return autotune.TuningSpace("k", {"nblk": (32, 64, 128, 256)},
                                    {"nblk": 128})

    def test_default_first_and_measurement_budget(self):
        measured = []

        def measure(cfg):
            measured.append(cfg["nblk"])
            return 1e-6 * cfg["nblk"]

        best, results, rep = autotune.predict_then_measure(
            self._space(), measure, lambda c: 1e-6 * c["nblk"], top_k=1)
        assert measured[0] == 128            # default, before any ranking
        assert measured == [128, 32]         # + only the top-1 prediction
        assert rep == {"candidates": 4, "measured": 2, "saved": 2}
        assert best == {"nblk": 32}

    def test_tie_keeps_default(self):
        best, results, rep = autotune.predict_then_measure(
            self._space(), lambda cfg: 1.0, lambda c: c["nblk"], top_k=3)
        assert best == {"nblk": 128}
        assert results[0]["config"] == {"nblk": 128}

    def test_strictly_better_candidate_wins(self):
        best, _, _ = autotune.predict_then_measure(
            self._space(),
            lambda cfg: 0.5 if cfg["nblk"] == 32 else 1.0,
            lambda c: c["nblk"], top_k=1)
        assert best == {"nblk": 32}

    def test_failed_candidate_never_wins(self):
        """A ranked candidate whose measurement comes back as the failed
        sentinel (+inf) can never displace the measured default."""
        best, results, _ = autotune.predict_then_measure(
            self._space(),
            lambda cfg: ms.FAILED_TRIAL if cfg["nblk"] != 128 else 1.0,
            lambda c: c["nblk"], top_k=2)
        assert best == {"nblk": 128}
        assert sum(1 for r in results if math.isinf(r["seconds"])) == 2

    def test_prediction_raise_ranks_last(self):
        """predict() raising for one candidate must not kill the search —
        that candidate ranks last and is simply not measured under a small
        top_k."""
        measured = []

        def predict(cfg):
            if cfg["nblk"] == 32:
                raise RuntimeError("no features for this one")
            return 1e-6 * cfg["nblk"]

        def measure(cfg):
            measured.append(cfg["nblk"])
            return 1.0

        best, _, rep = autotune.predict_then_measure(
            self._space(), measure, predict, top_k=1)
        assert 32 not in measured and rep["measured"] == 2
        assert best == {"nblk": 128}

    def test_counters_and_summary_surface(self, at_on, tel_enabled):
        autotune.predict_then_measure(
            self._space(), lambda cfg: 1e-6 * cfg["nblk"],
            lambda c: c["nblk"], top_k=1)
        assert _counter_total("autotune_predicted_trials_total",
                              kernel="k") == 4
        assert _counter_total("autotune_measured_trials_total",
                              kernel="k") == 2
        assert tin.summary()["trials_saved"] == 2


class TestStoreFormatBump:
    def test_format_is_v2(self):
        # the ISSUE 18 bump: v2 entries guarantee the trial_costs schema
        assert st._FORMAT == 2

    def test_v1_entry_is_silent_miss_and_no_training_row(self, at_on):
        autotune.record("k", "s", {"nblk": 64}, meta={"trial_costs": [
            {"config": {"nblk": 64}, "seconds": 1e-4}]})
        assert autotune.lookup("k", "s") == {"nblk": 64}
        assert len(cm.training_rows("k")) == 1
        with open(at_on) as f:
            payload = json.load(f)
        for ent in payload["entries"].values():
            ent["env"]["format"] = 1   # "restart" onto a pre-v2 store
        with open(at_on, "w") as f:
            json.dump(payload, f)
        st._reset_stats_for_tests()
        assert autotune.lookup("k", "s") is None   # rejected, not crashed
        assert autotune.stats()["errors"] == 1
        assert cm.training_rows("k") == []         # model never sees v1 rows
        # the re-search overwrites under the current format: whole again
        autotune.record("k", "s", {"nblk": 32}, meta={"trial_costs": [
            {"config": {"nblk": 32}, "seconds": 1e-4}]})
        assert autotune.lookup("k", "s") == {"nblk": 32}
        assert len(cm.training_rows("k")) == 1


# -- the widened space registry (ISSUE 18) ------------------------------------
class TestNewSpaces:
    def test_nms_lane_alignment(self):
        sp = autotune.get_space("nms_alive_pallas")
        assert not sp.admits({"tile": 100}, N=512)   # not lane-aligned
        assert sp.admits({"tile": 512}, N=512)
        assert sp.default == {"tile": 256}

    def test_nms_vmem_prunes_under_shrunk_budget(self, monkeypatch):
        sp = autotune.get_space("nms_alive_pallas")
        assert {c["tile"] for c in sp.configs(N=1024)} == {128, 256, 512,
                                                           1024}
        # a 4 MB budget rejects the 1024-tile's ~12.5 MB working set
        monkeypatch.setenv("MXNET_DCONV_VMEM_MB", "4")
        tiles = {c["tile"] for c in sp.configs(N=1024)}
        assert 1024 not in tiles and {128, 256, 512} <= tiles

    def test_abuild_vmem_prunes_big_blocks(self):
        sp = autotune.get_space("psroi_abuild_pallas")
        # big bin maps: 256 rois/step ≈ 151 MB backward working set
        rbs = {c["rb"] for c in sp.configs(N=512, S=16, H=256, W=256,
                                           itemsize=4)}
        assert 256 not in rbs and 128 not in rbs
        assert 16 in rbs and 32 in rbs
        assert 64 in rbs   # the default is always admitted
        # tiny bin maps admit the whole grid
        assert len(sp.configs(N=512, S=4, H=7, W=7, itemsize=4)) == 5

    def test_quant_constraint(self):
        assert not sps._quant_constraint({"block": 0})
        # uncapped huge block blows the budget...
        assert not sps._quant_constraint({"block": 1 << 20})
        # ...but the dispatch site caps at rows, so admission judges the
        # EFFECTIVE block
        assert sps._quant_constraint({"block": 1 << 20}, rows=256)

    def test_fused_zero_pruned_off_mesh(self):
        sp = autotune.get_space("fused_step_layout")
        off = sp.configs(mesh=False)
        assert all(c["zero"] == 0 for c in off) and len(off) == 4
        on = sp.configs(mesh=True)
        assert len(on) == 8
        assert off[0] == on[0] == {"zero": 0, "prefetch": 2}  # default first


# -- new kernel dispatch wiring (ISSUE 18) ------------------------------------
class TestNewKernelWiring:
    def test_off_path_never_reads_store(self, at_off, monkeypatch):
        from mxnet_tpu.ops import pallas_kernels as pk

        monkeypatch.setattr(st, "lookup",
                            lambda *a, **k: pytest.fail("store read on the "
                                                        "off path"))
        assert pk._nms_tile(1, 512) == pk._NMS_TILE
        assert pk._abuild_rb(96, 4, 7, 7, 4) == pk._ABUILD_RB
        assert pk._quant_block("quantize_int8_pallas", 1024, 4, 1) == 512
        assert pk._quant_block(None, 100, 4, 1) == 100  # un-keyed: rows cap

    def test_nms_tile_adoption_and_revalidation(self, at_on, monkeypatch):
        from mxnet_tpu.ops import pallas_kernels as pk

        sig = autotune.nms_shape_sig(1, 1024)
        autotune.record("nms_alive_pallas", sig, {"tile": 1024})
        assert pk._nms_tile(1, 1024) == 1024
        # a shrunk budget rejects the same persisted winner at trace time
        monkeypatch.setenv("MXNET_DCONV_VMEM_MB", "4")
        assert pk._nms_tile(1, 1024) == pk._NMS_TILE
        monkeypatch.delenv("MXNET_DCONV_VMEM_MB")
        # misaligned and malformed winners keep the default
        autotune.record("nms_alive_pallas", sig, {"tile": 100})
        assert pk._nms_tile(1, 1024) == pk._NMS_TILE
        autotune.record("nms_alive_pallas", sig, {"tile": "garbage"})
        assert pk._nms_tile(1, 1024) == pk._NMS_TILE

    def test_abuild_rb_adoption_caps_at_n(self, at_on):
        from mxnet_tpu.ops import pallas_kernels as pk

        autotune.record("psroi_abuild_pallas",
                        autotune.psroi_shape_sig(256, 4, 7, 7, 4),
                        {"rb": 128})
        assert pk._abuild_rb(256, 4, 7, 7, 4) == 128
        autotune.record("psroi_abuild_pallas",
                        autotune.psroi_shape_sig(96, 4, 7, 7, 4),
                        {"rb": 128})
        assert pk._abuild_rb(96, 4, 7, 7, 4) == 96   # effective block
        autotune.record("psroi_abuild_pallas",
                        autotune.psroi_shape_sig(96, 4, 7, 7, 4),
                        {"rb": "garbage"})
        assert pk._abuild_rb(96, 4, 7, 7, 4) == pk._ABUILD_RB

    def test_quant_block_adoption(self, at_on):
        from mxnet_tpu.ops import pallas_kernels as pk

        sig = autotune.quant_shape_sig(1024, 4)
        autotune.record("quantize_int8_pallas", sig, {"block": 256})
        assert pk._quant_block("quantize_int8_pallas", 1024, 4, 1) == 256
        autotune.record("quantize_int8_pallas", sig, {"block": -8})
        assert pk._quant_block("quantize_int8_pallas", 1024, 4, 1) == 512

    def test_quantize_parity_across_blocks(self, at_on):
        """A tuned row block changes the grid, never the values — and the
        module-level jit wrapper's cache is cleared so each pin actually
        retraces (the CLI runner depends on the same idiom)."""
        import jax.numpy as jnp

        from mxnet_tpu.ops import pallas_kernels as pk

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(16, 128).astype(np.float32))
        got = {}
        for blk in (2, 16):
            with autotune.override("quantize_int8_pallas", {"block": blk}):
                pk.quantize_int8_pallas.clear_cache()
                q = np.asarray(pk.quantize_int8_pallas(x, 4.0,
                                                       interpret=True))
            with autotune.override("dequantize_int8_pallas",
                                   {"block": blk}):
                pk.dequantize_int8_pallas.clear_cache()
                d = np.asarray(pk.dequantize_int8_pallas(
                    jnp.asarray(q), 4.0, interpret=True))
            got[blk] = (q, d)
        pk.quantize_int8_pallas.clear_cache()
        pk.dequantize_int8_pallas.clear_cache()
        np.testing.assert_array_equal(got[2][0], got[16][0])
        np.testing.assert_allclose(got[2][1], got[16][1], rtol=0, atol=0)

    def test_failed_trial_sentinel(self, at_on, tel_enabled):
        """A candidate whose build raises is a FAILED trial, not a search
        abort: +inf sentinel, its own counter, no timing counted, and its
        cost features scrubbed so the model never trains on it."""
        def bad_build():
            raise RuntimeError("mosaic said no")

        before = autotune.measurements()
        t = autotune.measure_candidate("k", {"nblk": 1}, bad_build, (),
                                       warmup=0, repeat=1)
        assert t == ms.FAILED_TRIAL and math.isinf(t)
        assert autotune.measurements() == before     # not a counted timing
        assert ms.failed_measurements() == 1
        assert _counter_total("autotune_failed_trials_total",
                              kernel="k") == 1
        assert ms.features_for("k", {"nblk": 1}) is None


# -- serving bucket stats (ISSUE 9 satellite) ---------------------------------
class TestBucketStats:
    def test_stats_expose_per_bucket_waste_and_hits(self):
        from mxnet_tpu.serving import BucketLadder, Engine
        from mxnet_tpu.test_utils import tiny_mlp_checkpoint

        sym, params = tiny_mlp_checkpoint()
        eng = Engine(sym, params, {"data": (8,)},
                     ladder=BucketLadder((1, 4)), start=True)
        try:
            eng.predict({"data": np.zeros((3, 8), np.float32)})
            eng.predict({"data": np.zeros((4, 8), np.float32)})
            eng.predict({"data": np.zeros((1, 8), np.float32)})
            s = eng.stats()
            bs = s["bucket_stats"]
            b4 = bs["b4[data=8]"]
            b1 = bs["b1[data=8]"]
            assert b1["batches"] == b1["requests"] == 1
            assert b1["padding_waste"] == 0.0
            assert b4["batches"] == 2 and b4["requests"] == 2
            # the n=3 batch wasted 1/4 of its rows, the n=4 none → mean 1/8
            assert b4["padding_waste"] == pytest.approx(0.125, abs=1e-4)
            # back-compat: "buckets" still maps label -> batch count
            assert s["buckets"] == {"b4[data=8]": 2, "b1[data=8]": 1}
        finally:
            eng.close()
