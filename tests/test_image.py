"""Image pipeline tests — mirrors reference tests/python/unittest/test_image.py."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as mimg
from mxnet_tpu import recordio


def _gradient(h, w, phase=0.0):
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    r = (xx / max(1, w - 1)) * 255
    g = (yy / max(1, h - 1)) * 255
    b = ((xx + yy + phase) % 255)
    return np.stack([r, g, b], axis=-1).astype(np.uint8)


@pytest.fixture(scope="module")
def jpeg_bytes():
    from io import BytesIO

    from PIL import Image

    buf = BytesIO()
    Image.fromarray(_gradient(40, 30)).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def test_imdecode_imread(tmp_path, jpeg_bytes):
    img = mimg.imdecode(jpeg_bytes)
    assert img.shape == (40, 30, 3)
    gray = mimg.imdecode(jpeg_bytes, flag=0)
    assert gray.shape == (40, 30, 1)
    p = tmp_path / "x.jpg"
    p.write_bytes(jpeg_bytes)
    img2 = mimg.imread(str(p))
    np.testing.assert_array_equal(img, img2)
    with pytest.raises(mx.MXNetError):
        mimg.imread(str(tmp_path / "missing.jpg"))


def test_resize_and_crops():
    img = _gradient(48, 64)
    assert mimg.resize_short(img, 24).shape == (24, 32, 3)
    assert mimg.resize_short(img.transpose(1, 0, 2), 24).shape == (32, 24, 3)
    assert mimg.imresize(img, 10, 20).shape == (20, 10, 3)
    out = mimg.fixed_crop(img, 4, 6, 16, 12)
    np.testing.assert_array_equal(out, img[6:18, 4:20])
    out, (x0, y0, w, h) = mimg.random_crop(img, (20, 10))
    assert out.shape == (10, 20, 3) and (w, h) == (20, 10)
    np.testing.assert_array_equal(out, img[y0 : y0 + 10, x0 : x0 + 20])
    out, (x0, y0, w, h) = mimg.center_crop(img, (32, 24))
    assert out.shape == (24, 32, 3) and x0 == 16 and y0 == 12
    # requested crop bigger than source: scaled down then resized up
    out, _ = mimg.random_crop(img, (128, 100))
    assert out.shape == (100, 128, 3)


def test_scale_down():
    assert mimg.scale_down((640, 480), (720, 120)) == (640, 106)
    assert mimg.scale_down((360, 1000), (480, 500)) == (360, 375)


def test_color_normalize():
    img = _gradient(8, 8)
    out = mimg.color_normalize(img, mean=np.array([1.0, 2.0, 3.0]), std=np.array([2.0, 2.0, 2.0]))
    np.testing.assert_allclose(out[..., 0], (img[..., 0] - 1.0) / 2.0, rtol=1e-6)


def test_augmenters_shapes_and_types():
    img = _gradient(32, 32)
    for aug in [
        mimg.BrightnessJitterAug(0.3),
        mimg.ContrastJitterAug(0.3),
        mimg.SaturationJitterAug(0.3),
        mimg.HueJitterAug(0.1),
        mimg.LightingAug(0.1, np.array([55.46, 4.794, 1.148]), np.random.rand(3, 3)),
        mimg.ColorNormalizeAug(np.array([1.0, 1.0, 1.0]), np.array([2.0, 2.0, 2.0])),
        mimg.RandomGrayAug(1.0),
        mimg.HorizontalFlipAug(1.0),
        mimg.CastAug(),
    ]:
        out = aug(img)
        assert out.shape == img.shape, type(aug).__name__
    flipped = mimg.HorizontalFlipAug(1.0)(img)
    np.testing.assert_array_equal(flipped, img[:, ::-1])
    gray = mimg.RandomGrayAug(1.0)(img)
    assert np.allclose(gray[..., 0], gray[..., 1])


def test_create_augmenter_pipeline():
    augs = mimg.CreateAugmenter(
        (3, 24, 24), resize=30, rand_crop=True, rand_mirror=True, mean=True, std=True,
        brightness=0.1, contrast=0.1, saturation=0.1, hue=0.1, pca_noise=0.1, rand_gray=0.05,
    )
    img = _gradient(50, 40)
    for aug in augs:
        img = aug(img)
    assert img.shape == (24, 24, 3)
    assert img.dtype == np.float32


def _write_rec(tmp_path, n=8, h=30, w=26, det=False):
    prefix = str(tmp_path / "imgs")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        img = _gradient(h, w, phase=i * 10)
        if det:
            # [header_width=2, object_width=5, cls,x1,y1,x2,y2] * objects
            nobj = 1 + i % 3
            objs = []
            for j in range(nobj):
                objs += [float(j), 0.1 + 0.05 * j, 0.2, 0.6 + 0.05 * j, 0.8]
            label = np.array([2, 5] + objs, dtype=np.float32)
        else:
            label = float(i)
        rec.write_idx(i, recordio.pack_img(recordio.IRHeader(0, label, i, 0), img))
    rec.close()
    return prefix + ".rec"


def test_image_iter_rec(tmp_path):
    rec = _write_rec(tmp_path, n=8)
    it = mimg.ImageIter(
        batch_size=4, data_shape=(3, 24, 24), path_imgrec=rec, shuffle=False
    )
    b = it.next()
    assert b.data[0].shape == (4, 3, 24, 24)
    assert b.label[0].asnumpy().tolist() == [0.0, 1.0, 2.0, 3.0]
    b2 = it.next()
    assert b2.label[0].asnumpy().tolist() == [4.0, 5.0, 6.0, 7.0]
    with pytest.raises(StopIteration):
        it.next()


def test_image_iter_imglist(tmp_path):
    from PIL import Image

    root = tmp_path / "imgs"
    os.makedirs(root)
    imglist = []
    for i in range(4):
        Image.fromarray(_gradient(20, 20, i * 5)).save(root / ("%d.jpg" % i))
        imglist.append([float(i), "%d.jpg" % i])
    it = mimg.ImageIter(
        batch_size=2, data_shape=(3, 20, 20), imglist=imglist, path_root=str(root)
    )
    b = it.next()
    assert b.data[0].shape == (2, 3, 20, 20)
    assert b.label[0].asnumpy().tolist() == [0.0, 1.0]


def test_image_iter_pad_last_batch(tmp_path):
    rec = _write_rec(tmp_path, n=5)
    it = mimg.ImageIter(batch_size=4, data_shape=(3, 24, 24), path_imgrec=rec)
    it.next()
    b = it.next()
    assert b.pad == 3


def test_det_iter(tmp_path):
    rec = _write_rec(tmp_path, n=6, det=True)
    it = mimg.ImageDetIter(
        batch_size=3, data_shape=(3, 24, 24), path_imgrec=rec, shuffle=False
    )
    assert it.max_objects == 3
    b = it.next()
    assert b.data[0].shape == (3, 3, 24, 24)
    lab = b.label[0].asnumpy()
    assert lab.shape == (3, 3, 5)
    # image 0 has 1 object, rest padded with -1
    assert lab[0, 0, 0] == 0.0
    assert (lab[0, 1:] == -1).all()
    # image 2 has 3 objects
    assert (lab[2, :, 0] == [0.0, 1.0, 2.0]).all()
    np.testing.assert_allclose(lab[2, 1, 1:], [0.15, 0.2, 0.65, 0.8], rtol=1e-5)


def test_det_flip_updates_boxes():
    img = _gradient(20, 20)
    label = np.array([[0, 0.1, 0.2, 0.4, 0.8]], dtype=np.float32)
    out, lab = mimg.DetHorizontalFlipAug(1.0)(img, label)
    np.testing.assert_allclose(lab[0], [0, 0.6, 0.2, 0.9, 0.8], rtol=1e-5)
    np.testing.assert_array_equal(out, img[:, ::-1])


def test_det_random_crop_keeps_objects():
    np.random.seed(0)
    img = _gradient(64, 64)
    label = np.array([[1, 0.3, 0.3, 0.7, 0.7]], dtype=np.float32)
    aug = mimg.DetRandomCropAug(min_object_covered=0.5, area_range=(0.3, 0.9))
    for _ in range(5):
        out, lab = aug(img, label)
        assert lab.shape[1] == 5
        assert (lab[:, 1:] >= 0).all() and (lab[:, 1:] <= 1).all()


def test_det_random_pad_updates_boxes():
    img = _gradient(20, 20)
    label = np.array([[0, 0.0, 0.0, 1.0, 1.0]], dtype=np.float32)
    aug = mimg.DetRandomPadAug(area_range=(2.0, 3.0))
    out, lab = aug(img, label)
    assert out.shape[0] >= 20 and out.shape[1] >= 20
    # original image box must still bound a smaller normalized region
    assert lab[0, 3] - lab[0, 1] < 1.0 or lab[0, 4] - lab[0, 2] < 1.0


def test_create_det_augmenter_runs():
    augs = mimg.CreateDetAugmenter(
        (3, 24, 24), rand_crop=0.5, rand_pad=0.5, rand_mirror=True, mean=True, std=True,
        brightness=0.1, contrast=0.1, saturation=0.1,
    )
    img = _gradient(40, 40)
    label = np.array([[0, 0.2, 0.2, 0.8, 0.8]], dtype=np.float32)
    for _ in range(3):
        im, lab = img, label
        for aug in augs:
            im, lab = aug(im, lab)
        assert im.shape == (24, 24, 3)
        assert lab.shape[1] == 5


def test_contrast_jitter_identity_mean():
    """Contrast blend must preserve a uniform image's level (review regression:
    the gray-mean term was 3x too large)."""
    img = np.full((4, 4, 3), 100.0, dtype=np.float32)
    aug = mimg.ContrastJitterAug(0.5)
    for _ in range(5):
        out = aug(img)
        np.testing.assert_allclose(out, 100.0, atol=0.5)


def test_imdecode_positional_flag(jpeg_bytes):
    """Reference argument order: imdecode(buf, flag) — flag=0 is grayscale."""
    gray = mimg.imdecode(jpeg_bytes, 0)
    assert gray.shape == (40, 30, 1)
