"""Autograd tape tests — modeled on reference tests/python/unittest/test_autograd.py."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain_rule():
    x = nd.array(np.random.rand(3, 4).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x)
        z = (y * 2).sum()
    z.backward()
    assert_almost_equal(x.grad, 2 * np.exp(x.asnumpy()), rtol=1e-4, atol=1e-5)


def test_grad_through_slicing_reshape():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    x.attach_grad()
    with autograd.record():
        y = x[1:].reshape((1, 8)).sum()
    y.backward()
    expected = np.zeros((3, 4), dtype=np.float32)
    expected[1:] = 1
    assert_almost_equal(x.grad, expected)


def test_multi_variable():
    a = nd.array([2.0])
    b = nd.array([3.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = a * b + a
    c.backward()
    assert_almost_equal(a.grad, np.array([4.0]))  # b + 1
    assert_almost_equal(b.grad, np.array([2.0]))  # a


def test_head_grads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(out_grad=nd.array([10.0, 100.0]))
    assert_almost_equal(x.grad, np.array([30.0, 300.0]))


def test_grad_add_req():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    assert_almost_equal(x.grad, np.array([6.0]))


def test_pause_and_modes():
    x = nd.array([1.0])
    x.attach_grad()
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
        y = x * 2
    y.backward()
    assert_almost_equal(x.grad, np.array([2.0]))


def test_detach_blocks_grad():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = nd.BlockGrad(y) * x
    z.backward()
    assert_almost_equal(x.grad, np.array([6.0]))  # only through the second factor


def test_autograd_grad_function():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    (g,) = autograd.grad([y], [x])
    assert_almost_equal(g, np.array([12.0]))


def test_softmax_grad_numeric():
    check_numeric_gradient(
        lambda x: nd.softmax(x, axis=-1).sum(axis=-1).sum() + (nd.softmax(x) * nd.softmax(x)).sum(),
        [np.random.rand(2, 3)],
        rtol=5e-2,
    )


def test_matmul_grad_numeric():
    check_numeric_gradient(
        lambda a, b: nd.dot(a, b).sum(),
        [np.random.rand(2, 3), np.random.rand(3, 2)],
        rtol=5e-2,
    )


def test_softmax_output_backward():
    # SoftmaxOutput grad = (p - onehot) * scale, ignoring label grad
    data = nd.array(np.random.rand(4, 5).astype(np.float32))
    label = nd.array(np.array([0, 1, 2, 3], dtype=np.float32))
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, label)
    out.backward()
    p = np.exp(data.asnumpy()) / np.exp(data.asnumpy()).sum(axis=1, keepdims=True)
    onehot = np.eye(5, dtype=np.float32)[label.asnumpy().astype(int)]
    assert_almost_equal(data.grad, p - onehot, rtol=1e-4, atol=1e-5)


def test_training_flag_dropout():
    x = nd.ones((100, 100))
    with autograd.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
    frac_zero = 1.0 - (y.asnumpy() != 0).mean()
    assert 0.3 < frac_zero < 0.7
    with autograd.record(train_mode=False):
        y2 = nd.Dropout(x, p=0.5)
    assert (y2.asnumpy() == 1).all()
    y3 = nd.Dropout(x, p=0.5)  # outside record: inference
    assert (y3.asnumpy() == 1).all()


def test_grad_create_graph_second_order():
    """create_graph=True (reference autograd.py:270): grad of grad.
    y = x^3: dy/dx = 3x^2, d2y/dx2 = 6x."""
    x = nd.array(np.array([1.0, 2.0, -3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        (g,) = autograd.grad([y], [x], create_graph=True)
        z = (g * g).sum()  # sum (3x^2)^2 -> dz/dx = 2*3x^2*6x = 36x^3
    z.backward()
    np.testing.assert_allclose(
        x.grad.asnumpy(), 36.0 * np.array([1.0, 2.0, -3.0]) ** 3, rtol=1e-5)


def test_grad_create_graph_via_grad_twice():
    """Second order via two grad() calls (no backward)."""
    x = nd.array(np.array([0.5, 1.5], np.float32))
    with autograd.record():
        y = nd.exp(x) * x
        (g,) = autograd.grad([y], [x], create_graph=True)  # (x+1)e^x
        (g2,) = autograd.grad([g], [x], create_graph=False)  # (x+2)e^x
    xv = np.array([0.5, 1.5])
    np.testing.assert_allclose(g2.asnumpy(), (xv + 2) * np.exp(xv), rtol=1e-5)


def test_grad_retain_defaults_match_reference():
    """retain_graph defaults to create_graph (reference autograd.py:270)."""
    x = nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x
    (g,) = autograd.grad([y], [x])  # create_graph=False -> graph freed
    np.testing.assert_allclose(g.asnumpy(), [4.0])
    import pytest as _pytest
    with _pytest.raises(Exception):
        autograd.backward([y])  # tape gone


def test_wgan_gp_style_gradient_penalty_trains():
    """WGAN-GP pattern: penalty (||dD/dx|| - 1)^2 trains through
    second-order autograd; the penalty decreases under SGD."""
    import mxnet_tpu as mx

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = mx.gluon.nn.Dense(1)
    net.initialize()
    x = nd.array(rng.randn(8, 4).astype(np.float32))
    net(x)  # materialize params
    params = list(net.collect_params().values())
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1})
    losses = []
    for step in range(12):
        xi = nd.array(rng.randn(8, 4).astype(np.float32))
        xi.attach_grad()
        with autograd.record():
            out = net(xi).sum()
            (gx,) = autograd.grad([out], [xi], create_graph=True)
            gnorm = nd.sqrt((gx * gx).sum(axis=1) + 1e-12)
            penalty = ((gnorm - 1.0) ** 2).mean()
        penalty.backward()
        trainer.step(1)
        losses.append(float(penalty.asnumpy()))
    assert losses[-1] < losses[0] * 0.5, losses


def test_grad_create_graph_survives_retain_false():
    """create_graph=True + retain_graph=False: heads' graph is freed but the
    recorded grad op survives, so the promised differentiable gradients work."""
    x = nd.array(np.array([2.0, -1.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        (g,) = autograd.grad([y], [x], create_graph=True, retain_graph=False)
        z = (g * g).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               36.0 * np.array([2.0, -1.0]) ** 3, rtol=1e-5)


def test_grad_create_graph_extra_inputs_exclude_intermediates():
    """The recorded grad op's traced inputs are variables + true leaves only
    — tape-produced intermediates must not be pinned as dead inputs."""
    from mxnet_tpu import autograd as ag

    x = nd.array(np.array([1.0, 2.0], np.float32))
    w = nd.array(np.array([3.0, 4.0], np.float32))  # a leaf "parameter"
    with autograd.record():
        t = x * w
        for _ in range(10):
            t = t + t * 0.5  # 20 taped intermediates
        (g,) = autograd.grad([t], [x], create_graph=True)
    entry = ag._st().tape[-1]
    # inputs: x (variable) + w (leaf) only
    assert len(entry.inputs) == 2, [id(i) for i in entry.inputs]


def test_grad_wrt_tape_produced_intermediate():
    """grad w.r.t. an intermediate gives its partial derivative (leaf
    semantics — the reference's attach_grad detaches history)."""
    x = nd.array(np.array([2.0], np.float32))
    with autograd.record():
        t = x * 3.0
        y = t * t
        (g,) = autograd.grad([y], [t])
    np.testing.assert_allclose(g.asnumpy(), 2 * 3 * 2.0 * np.ones(1), rtol=1e-6)


def test_grad_create_graph_then_mixed_head_loss():
    """After create_graph=True (+ retain_graph=False), a loss mixing the
    returned gradient with pre-grad intermediates still differentiates
    through BOTH paths: d/dx[y*g] for y=x^3, g=3x^2 is 18x^4 -> 15x^4... """
    x = nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        (g,) = autograd.grad([y], [x], create_graph=True, retain_graph=False)
        loss = (y * g).sum()  # = 3x^5  ->  d/dx = 15x^4
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [15.0 * 2.0 ** 4], rtol=1e-5)
