"""Gluon tests — mirrors reference tests/python/unittest/test_gluon.py patterns."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)
    assert p.list_ctx() is not None


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()


def test_paramdict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    params.save("/tmp/test_paramdict.npz")
    params.load("/tmp/test_paramdict.npz", mx.cpu())


def test_dense():
    model = nn.Dense(128, activation="tanh", in_units=10, flatten=False, prefix="test_")
    inputs = nd.array(np.random.rand(2, 3, 10).astype(np.float32))
    model.initialize()
    assert set(model.collect_params().keys()) == {"test_weight", "test_bias"}
    out = model(inputs)
    assert out.shape == (2, 3, 128)

    model2 = nn.Dense(128, activation="relu", in_units=30, prefix="test2_")
    inputs2 = nd.array(np.random.rand(17, 2, 5, 3).astype(np.float32))
    model2.initialize()
    out2 = model2(inputs2)
    assert out2.shape == (17, 128)


def test_sequential_and_getitem():
    net = nn.Sequential()
    net.add(nn.Dense(10), nn.Dense(10), nn.Dense(10))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    assert len(net[0:2]) == 2


def test_hybrid_eager_consistency():
    """Hybridized (CachedOp/jit) output must match eager output exactly."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, 3, padding=1), nn.BatchNorm(), nn.Activation("relu"),
                nn.MaxPool2D(2), nn.Dense(8))
    net.initialize()
    x = nd.array(np.random.rand(2, 3, 8, 8).astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-5, atol=1e-5)


def test_hybrid_grad_consistency():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = nd.array(np.random.rand(3, 8).astype(np.float32))

    def grads():
        with autograd.record():
            y = net(x).sum()
        y.backward()
        return [p.grad().asnumpy().copy() for p in net.collect_params().values()]

    g_eager = grads()
    net.hybridize()
    g_hybrid = grads()
    for a, b in zip(g_eager, g_hybrid):
        assert_almost_equal(a, b, rtol=1e-5, atol=1e-5)


def test_batchnorm_running_stats():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = nd.array(np.random.rand(8, 4, 5, 5).astype(np.float32) * 3 + 1)
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    rv = bn.running_var.data().asnumpy()
    mean = x.asnumpy().mean(axis=(0, 2, 3))
    var = x.asnumpy().var(axis=(0, 2, 3))
    assert_almost_equal(rm, 0.1 * mean, rtol=1e-3, atol=1e-3)
    assert_almost_equal(rv, 0.9 + 0.1 * var, rtol=1e-3, atol=1e-3)
    # eval mode uses running stats
    out = bn(x).asnumpy()
    expect = (x.asnumpy() - rm.reshape(1, -1, 1, 1)) / np.sqrt(rv.reshape(1, -1, 1, 1) + 1e-5)
    assert_almost_equal(out, expect, rtol=1e-3, atol=1e-3)


def test_dropout_modes():
    d = nn.Dropout(0.5)
    x = nd.array(np.random.rand(10, 10).astype(np.float32) + 1)
    # eval: identity
    assert_almost_equal(d(x).asnumpy(), x.asnumpy())
    # train: some zeros
    with autograd.record():
        out = d(x).asnumpy()
    assert (out == 0).sum() > 0


def test_hybrid_dropout_fresh_randomness():
    """Jitted dropout must not bake the mask as a constant."""
    d = nn.Dropout(0.5)
    d.hybridize()
    x = nd.array(np.ones((100,), np.float32))
    with autograd.record():
        m1 = d(x).asnumpy()
        m2 = d(x).asnumpy()
    assert (m1 == 0).sum() > 10
    assert not np.array_equal(m1, m2)


def test_losses_numpy():
    pred = np.random.rand(5, 4).astype(np.float32)
    label_idx = np.array([0, 1, 2, 3, 0], dtype=np.float32)
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    out = loss(nd.array(pred), nd.array(label_idx)).asnumpy()
    logp = pred - pred.max(1, keepdims=True)
    logp = logp - np.log(np.exp(logp).sum(1, keepdims=True))
    expect = -logp[np.arange(5), label_idx.astype(int)]
    assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)

    l2 = gluon.loss.L2Loss()
    a, b = np.random.rand(4, 3).astype(np.float32), np.random.rand(4, 3).astype(np.float32)
    assert_almost_equal(l2(nd.array(a), nd.array(b)).asnumpy(), (0.5 * (a - b) ** 2).mean(1), rtol=1e-5)

    l1 = gluon.loss.L1Loss()
    assert_almost_equal(l1(nd.array(a), nd.array(b)).asnumpy(), np.abs(a - b).mean(1), rtol=1e-5)

    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    lbl = (np.random.rand(4, 3) > 0.5).astype(np.float32)
    out = bce(nd.array(a), nd.array(lbl)).asnumpy()
    p = 1 / (1 + np.exp(-a))
    expect = -(lbl * np.log(p) + (1 - lbl) * np.log(1 - p)).mean(1)
    assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)

    hinge = gluon.loss.HingeLoss()
    sl = np.sign(np.random.rand(4, 3).astype(np.float32) - 0.5)
    out = hinge(nd.array(a), nd.array(sl)).asnumpy()
    assert_almost_equal(out, np.maximum(0, 1 - a * sl).mean(1), rtol=1e-5)


def test_trainer_convergence():
    """Linear regression converges (reference test pattern: small real train)."""
    w_true = np.array([[2.0, -3.4]], dtype=np.float32)
    b_true = 4.2
    xs = np.random.normal(size=(200, 2)).astype(np.float32)
    ys = xs @ w_true.T + b_true

    net = nn.Dense(1)
    net.initialize(init=mx.init.Normal(0.1))
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    loss_fn = gluon.loss.L2Loss()
    for _ in range(60):
        for i in range(0, 200, 50):
            x = nd.array(xs[i : i + 50])
            y = nd.array(ys[i : i + 50])
            with autograd.record():
                l = loss_fn(net(x), y)
            l.backward()
            trainer.step(50)
    w = net.weight.data().asnumpy()
    b = net.bias.data().asnumpy()
    assert_almost_equal(w, w_true, rtol=1e-2, atol=1e-2)
    assert_almost_equal(b, np.array([b_true]), rtol=1e-2, atol=1e-2)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4), nn.Dense(4, in_units=8))
    net.initialize()
    x = nd.array(np.random.rand(2, 4).astype(np.float32))
    out1 = net(x).asnumpy()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)

    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(8, in_units=4), nn.Dense(4, in_units=8))
    net2.load_parameters(f)
    out2 = net2(x).asnumpy()
    assert_almost_equal(out1, out2)


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(4, in_units=4)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    x = nd.array(np.random.rand(2, 4).astype(np.float32))
    with autograd.record():
        l = net(x).sum()
    l.backward()
    tr.step(2)
    f = str(tmp_path / "tr.states")
    tr.save_states(f)
    tr.load_states(f)
    with autograd.record():
        l = net(x).sum()
    l.backward()
    tr.step(2)


def test_rnn_cell_vs_fused_lstm():
    """Unrolled LSTMCell must match the fused lax.scan LSTM layer."""
    T, N, I, H = 4, 2, 3, 5
    x = np.random.rand(T, N, I).astype(np.float32)

    layer = gluon.rnn.LSTM(H, input_size=I)
    layer.initialize()
    out_fused = layer(nd.array(x)).asnumpy()

    cell = gluon.rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    # copy fused layer params into the cell
    lp = {k[len(layer.prefix):]: v for k, v in layer.collect_params().items()}
    for name, p in cell.collect_params().items():
        short = name[len(cell.prefix):]
        p.set_data(lp["l0_" + short].data())
    out_cell, _ = cell.unroll(T, nd.array(x), layout="TNC", merge_outputs=True)
    assert_almost_equal(out_fused, out_cell.asnumpy(), rtol=1e-4, atol=1e-5)


def test_bidirectional_gru_shapes():
    layer = gluon.rnn.GRU(7, num_layers=2, bidirectional=True, input_size=3)
    layer.initialize()
    x = nd.array(np.random.rand(6, 2, 3).astype(np.float32))
    out, states = layer(x, layer.begin_state(2))
    assert out.shape == (6, 2, 14)
    assert states[0].shape == (4, 2, 7)


def test_model_zoo_runs():
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.get_model("resnet18_v1", classes=10)
    net.initialize()
    x = nd.array(np.random.rand(1, 3, 32, 32).astype(np.float32))
    assert net(x).shape == (1, 10)


def test_dataloader_and_dataset():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    data = np.random.rand(20, 3).astype(np.float32)
    label = np.arange(20, dtype=np.int32)
    ds = ArrayDataset(data, label)
    assert len(ds) == 20
    dl = DataLoader(ds, batch_size=6, last_batch="keep")
    batches = list(dl)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 3)
    assert batches[-1][0].shape == (2, 3)

    dl2 = DataLoader(ds, batch_size=6, last_batch="discard", num_workers=2)
    assert len(list(dl2)) == 3

    seen = np.concatenate([b[1].asnumpy() for b in dl])
    assert np.array_equal(np.sort(seen), label)


def test_dataset_transform():
    from mxnet_tpu.gluon.data import ArrayDataset

    ds = ArrayDataset(np.ones((4, 2), np.float32), np.zeros(4, np.int32))
    ds2 = ds.transform_first(lambda x: x * 2)
    x, y = ds2[0]
    assert float(np.asarray(x).sum()) == 4.0


def test_block_repr_and_collect():
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
    params = net.collect_params()
    assert all(k.startswith("model_") for k in params.keys())
    sel = net.collect_params(".*weight")
    assert all("weight" in k for k in sel.keys())
    repr(net)


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = nd.array(np.array([0, 3, 9], dtype=np.float32))
    out = emb(idx)
    assert out.shape == (3, 4)
    w = emb.weight.data().asnumpy()
    assert_almost_equal(out.asnumpy(), w[[0, 3, 9]])


def test_conv_transpose_shape():
    net = nn.Conv2DTranspose(4, kernel_size=4, strides=2, padding=1, in_channels=3)
    net.initialize()
    x = nd.array(np.random.rand(1, 3, 8, 8).astype(np.float32))
    assert net(x).shape == (1, 4, 16, 16)


def test_shared_block_symbolic_capture_unique_names():
    """Round-5 naming fix: a weight-shared sub-block invoked twice in one
    symbolic capture (siamese towers) must produce a graph where both
    invocations survive serialization — per-call name-prefix ordinals keep
    node names unique (the serializer walk dedupes by name)."""
    import json

    import numpy as np

    net = gluon.nn.HybridSequential()
    enc = gluon.nn.Dense(4)

    class Siamese(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.enc = gluon.nn.Dense(4)

        def hybrid_forward(self, F, a, b):
            return self.enc(a) + 2.0 * self.enc(b)

    net = Siamese()
    net.initialize()
    a = mx.nd.array(np.ones((2, 3), np.float32))
    b = mx.nd.array(np.full((2, 3), 3.0, np.float32))
    eager = net(a, b).asnumpy()

    inputs, out = net._get_graph(a, b)
    js = json.loads(out.tojson())
    fc = [n for n in js["nodes"] if n["op"] == "FullyConnected"]
    assert len(fc) == 2, [n["name"] for n in js["nodes"]]
    assert len({n["name"] for n in fc}) == 2, fc

    # the symbolic graph computes the same thing (both towers live)
    exe = out.bind(None, {inputs[0].name: a, inputs[1].name: b,
                          **{k: v.data() for k, v in net.collect_params().items()}})
    got = exe.forward()[0].asnumpy()
    np.testing.assert_allclose(got, eager, rtol=1e-5, atol=1e-6)


def test_reentrant_symbolic_capture_keeps_outer_ordinals():
    """ADVICE round 5: ``_get_graph`` must save/restore the ambient
    ``_SYM_CAPTURE.counts`` instead of clobbering it to None — a NESTED
    capture mid-body (here: a sub-block's ``_get_graph`` called from the
    outer ``hybrid_forward``) would otherwise reset the outer capture's
    per-call ordinals, so a weight-shared block invoked again AFTER the
    nested capture collides with its first invocation's node names."""
    import json

    import numpy as np

    class Outer(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.enc = gluon.nn.Dense(4)
                self.probe = gluon.nn.Dense(2)

        def hybrid_forward(self, F, a, b):
            x = self.enc(a)
            # reentrant capture between the two shared-enc invocations
            # (e.g. a helper building a side graph for shape inference)
            self.probe._get_graph(mx.nd.zeros((1, 3)))
            return x + 2.0 * self.enc(b)

    net = Outer()
    net.initialize()
    a = mx.nd.array(np.ones((2, 3), np.float32))
    b = mx.nd.array(np.full((2, 3), 3.0, np.float32))
    eager = net(a, b).asnumpy()

    net._cached_graph = ()  # fresh capture (eager ran the nested one too)
    inputs, out = net._get_graph(a, b)
    js = json.loads(out.tojson())
    fc = [n for n in js["nodes"] if n["op"] == "FullyConnected"]
    assert len(fc) == 2, [n["name"] for n in js["nodes"]]
    assert len({n["name"] for n in fc}) == 2, fc

    exe = out.bind(None, {inputs[0].name: a, inputs[1].name: b,
                          **{k: v.data() for k, v in
                             net.collect_params().items()
                             if not k.startswith(net.probe.prefix)}})
    got = exe.forward()[0].asnumpy()
    np.testing.assert_allclose(got, eager, rtol=1e-5, atol=1e-6)
