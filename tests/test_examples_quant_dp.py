"""Quantization and distributed-DP example tests."""
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _run(subdir, script, args, timeout=900, devices=8):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=%d" % devices)
    return subprocess.run(
        [sys.executable, script] + args,
        cwd=os.path.join(REPO, "examples", subdir), env=env,
        capture_output=True, text=True, timeout=timeout)


def test_quantization_example():
    res = _run("quantization", "quantize_model.py",
               ["--num-train", "512", "--num-val", "256", "--epochs", "2"],
               devices=1)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "QUANTIZATION EXAMPLE OK" in res.stdout


def test_dp_training_example():
    res = _run("distributed_training", "train_dp.py",
               ["--steps", "20", "--batch-per-device", "4", "--lr", "0.05"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "DP TRAINING OK" in res.stdout
    assert "devices=8" in res.stdout


def test_ring_attention_lm_example():
    res = _run("long_context", "train_ring_attention.py",
               ["--seq-len", "256", "--steps", "60"], timeout=1200)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "RING ATTENTION LM OK" in res.stdout
    assert "8-way sequence parallelism" in res.stdout


def test_dcgan_example():
    res = _run("gluon", "dcgan.py",
               ["--epochs", "2", "--batches-per-epoch", "6"], devices=1)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "DCGAN OK" in res.stdout
