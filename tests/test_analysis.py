"""Static-analysis suite tests (ISSUE 8): graph-IR analyzers, the mxlint
source lint, and the lock-discipline checker — seeded violations must trip,
clean code must not, and every gate's off path must be zero-overhead."""
import os
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import analysis
from mxnet_tpu.analysis import lockcheck, source_lint
from mxnet_tpu.analysis.diagnostics import (Diagnostic, ERROR, INFO, WARNING,
                                            worst_severity)
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import BucketLadder, Engine
from mxnet_tpu.telemetry import instrument as tin
from mxnet_tpu.test_utils import tiny_mlp_checkpoint


@pytest.fixture
def lc_state():
    """Fresh lockcheck global state (order graph + violations) per test."""
    lockcheck.reset()
    yield
    lockcheck.reset()


@pytest.fixture
def tel_disabled(monkeypatch):
    monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
    tin._reset_for_tests()
    yield
    tin._reset_for_tests()


def _bind(sym, **arrays):
    return sym.bind(None, {k: nd.array(v) for k, v in arrays.items()})


# -- diagnostics --------------------------------------------------------------
class TestDiagnostics:
    def test_severity_validation_and_order(self):
        with pytest.raises(ValueError):
            Diagnostic("x", "fatal", "nope")
        assert worst_severity([]) is None
        diags = [Diagnostic("a", INFO, "i"), Diagnostic("b", ERROR, "e"),
                 Diagnostic("c", WARNING, "w")]
        assert worst_severity(diags) == ERROR
        diags.sort(key=Diagnostic._sort_key)
        assert [d.severity for d in diags] == [ERROR, WARNING, INFO]

    def test_str_carries_where(self):
        d = Diagnostic("prng-shared-stream", ERROR, "msg", where="d1,d2")
        assert "prng-shared-stream" in str(d) and "[d1,d2]" in str(d)


# -- graph-IR analyzers -------------------------------------------------------
class TestGraphAnalyzers:
    def test_key_reusing_dropouts_trip_prng_analyzer(self):
        """ISSUE 8 seeded violation: two dropouts folding the SAME explicit
        key draw identical masks — must be an ERROR."""
        x = mx.sym.var("data")
        k = np.zeros(2, np.uint32)
        d1 = mx.sym.Dropout(x, p=0.5, key=k, name="d1")
        d2 = mx.sym.Dropout(x, p=0.5, key=k, name="d2")
        exe = _bind(d1 + d2, data=np.ones((2, 4), np.float32))
        diags = exe.check(is_train=True)
        shared = [d for d in diags if d.code == "prng-shared-stream"]
        assert len(shared) == 1 and shared[0].severity == ERROR
        assert "d1" in shared[0].message and "d2" in shared[0].message
        # sorted most-severe first: the ERROR leads
        assert diags[0].code == "prng-shared-stream"

    def test_distinct_dropouts_are_clean(self):
        x = mx.sym.var("data")
        out = mx.sym.Dropout(x, p=0.5, name="a") \
            + mx.sym.Dropout(x, p=0.5, name="b")
        exe = _bind(out, data=np.ones((2, 4), np.float32))
        assert [d for d in exe.check(is_train=True)
                if d.code.startswith("prng")] == []

    def test_stochastic_node_in_eval_plan_warns(self):
        """ISSUE 8 seeded violation: a mode="always" dropout survives the
        inference rewrite and samples at inference — warned, not errored
        (MC-dropout is legitimate)."""
        x = mx.sym.var("data")
        exe = _bind(mx.sym.Dropout(x, p=0.5, mode="always"),
                    data=np.ones((2, 4), np.float32))
        diags = exe.check(is_train=False)
        assert [d.code for d in diags] == ["prng-eval-stochastic"]
        assert diags[0].severity == WARNING
        # the same dropout in TRAIN mode is normal — no warning
        assert [d for d in exe.check(is_train=True)
                if d.code == "prng-eval-stochastic"] == []

    def test_clean_mlp_predictor_checks_clean(self):
        sym, params = tiny_mlp_checkpoint()
        pred = Predictor(sym, params, {"data": (2, 8)})
        assert pred.check() == []

    def test_dead_code_analyzer_flags_unconsumed_bindings(self):
        from mxnet_tpu.analysis.graph_analyzers import dead_code
        from mxnet_tpu.graph_passes import Graph
        from mxnet_tpu.graph_passes.ir import PlanNode, SynthOp

        node = PlanNode(SynthOp("exp", lambda x: x), {}, "n0")
        g = Graph([(node, ("a",))], ["n0_output"])
        ctx = analysis.GraphContext(g, arg_names=["a", "b"],
                                    aux_names=["bn_mean"])
        codes = sorted(d.code for d in dead_code(ctx))
        assert codes == ["dead-aux", "unused-input"]

    def test_pass_drift_detected_between_raw_and_optimized(self):
        """A (synthetic) pass that changes a head's shape must be flagged
        as breaking the plan contract."""
        import jax
        import jax.numpy as jnp
        from mxnet_tpu.graph_passes import Graph
        from mxnet_tpu.graph_passes.ir import PlanNode, SynthOp

        raw = Graph([(PlanNode(SynthOp("exp", jnp.exp), {}, "n"), ("a",))],
                    ["n_output"])
        bad = Graph([(PlanNode(SynthOp("sum", jnp.sum), {}, "n"), ("a",))],
                    ["n_output"])  # scalar head: shape drifted
        ctx = analysis.GraphContext(
            bad, raw=raw, arg_names=["a"], aux_names=[],
            arg_avals={"a": jax.ShapeDtypeStruct((3,), np.float32)},
            aux_avals={})
        drift = [d for d in analysis.analyze(ctx) if d.code == "pass-drift"]
        assert len(drift) == 1 and drift[0].severity == ERROR
        # a pass that DROPS a head entirely must also be flagged (zip alone
        # would truncate silently)
        node = PlanNode(SynthOp("exp", jnp.exp), {}, "n")
        two_heads = Graph([(node, ("a",))], ["n_output", "n_output"])
        ctx2 = analysis.GraphContext(
            Graph([(node, ("a",))], ["n_output"]), raw=two_heads,
            arg_names=["a"], aux_names=[],
            arg_avals={"a": jax.ShapeDtypeStruct((3,), np.float32)},
            aux_avals={})
        drops = [d for d in analysis.analyze(ctx2) if d.code == "pass-drift"]
        assert len(drops) == 1 and "COUNT" in drops[0].message

    def test_failing_analyzer_degrades_to_info(self, monkeypatch):
        def boom(ctx):
            raise RuntimeError("kaboom")
        monkeypatch.setattr(analysis, "_ANALYZERS",
                            [("boom", 1, boom)] + analysis._ANALYZERS)
        x = mx.sym.var("data")
        exe = _bind(mx.sym.exp(x), data=np.ones((2,), np.float32))
        diags = exe.check()
        failed = [d for d in diags if d.code == "analyzer-failed"]
        assert len(failed) == 1 and failed[0].severity == INFO
        assert "kaboom" in failed[0].message

    def test_analyzer_pipeline_registered_in_order(self):
        names = [n for n, _ in analysis.analyzer_pipeline()]
        assert names == ["prng_safety", "shape_dtype", "dead_code",
                         "numerics"]


# -- source lint --------------------------------------------------------------
class TestSourceLint:
    def _codes(self, src):
        return [f.code for f in source_lint.lint_source(src)]

    def test_np_call_on_traced_param_flagged(self):
        src = ("import numpy as np\nimport jax\n\n"
               "@jax.jit\ndef f(x):\n    return np.log(x)\n")
        assert self._codes(src) == ["np-in-traced"]

    def test_np_on_statics_is_exempt(self):
        src = ("import numpy as np\nimport jax\n\n"
               "@jax.jit\ndef f(x):\n"
               "    n = np.prod(x.shape)\n"          # .shape is static
               "    m = np.ceil(len(x) / 2)\n"       # len() is static
               "    return x * n * m\n")
        assert self._codes(src) == []

    def test_scalar_coerce_and_sync_methods(self):
        src = ("import jax\n\n@jax.jit\ndef f(x):\n"
               "    a = float(x)\n    b = x.item()\n    return a + b\n")
        assert sorted(self._codes(src)) == ["scalar-coerce-in-traced"] * 2

    def test_branch_on_traced_param(self):
        src = ("import jax\n\n@jax.jit\ndef f(x, y):\n"
               "    if y is None:\n        return x\n"   # static: exempt
               "    if x > 0:\n        return x\n"       # traced: flagged
               "    return -x\n")
        assert self._codes(src) == ["branch-on-traced-param"]

    def test_time_and_bare_except(self):
        src = ("import time\nimport jax\n\n@jax.jit\ndef f(x):\n"
               "    return x + time.time()\n\n"
               "def g():\n    try:\n        return 1\n"
               "    except:\n        return 0\n")
        assert sorted(self._codes(src)) == ["bare-except", "time-in-traced"]

    def test_donated_jit_needs_cache_wiring(self):
        bare = ("import jax\n\ndef build(fn):\n"
                "    return jax.jit(fn, donate_argnums=(0,))\n")
        wired = ("import jax\nfrom mxnet_tpu import compile_cache\n\n"
                 "def build(fn):\n"
                 "    step = jax.jit(fn, donate_argnums=(0,))\n"
                 "    return compile_cache.CachedFunction(step, 'k')\n")
        assert self._codes(bare) == ["donated-jit-unkeyed"]
        assert self._codes(wired) == []

    def test_module_scope_donated_jit_flagged(self):
        """The PR 6 shape at import time — no enclosing def at all."""
        src = ("import jax\n\ndef step(x):\n    return x\n\n"
               "run = jax.jit(step, donate_argnums=(0,))\n")
        findings = source_lint.lint_source(src, path="m.py")
        assert [f.code for f in findings] == ["donated-jit-unkeyed"]
        assert "<module>" in findings[0].fingerprint

    def test_nested_donated_jit_once_and_outer_wiring_suppresses(self):
        nested = ("import jax\n\ndef outer(fn):\n"
                  "    def inner():\n"
                  "        return jax.jit(fn, donate_argnums=(0,))\n"
                  "    return inner\n")
        findings = source_lint.lint_source(nested, path="m.py")
        # exactly ONE finding, attributed to the innermost def
        assert [f.code for f in findings] == ["donated-jit-unkeyed"]
        assert "outer.inner" in findings[0].fingerprint
        wired = ("import jax\nfrom mxnet_tpu import compile_cache\n\n"
                 "def outer(fn):\n"
                 "    def inner():\n"
                 "        return jax.jit(fn, donate_argnums=(0,))\n"
                 "    return compile_cache.CachedFunction(inner(), 'k')\n")
        # wiring in the enclosing scope suppresses the inner finding
        assert source_lint.lint_source(wired) == []

    def test_untraced_function_not_linted(self):
        src = ("import numpy as np\n\ndef f(x):\n"
               "    return float(np.log(x))\n")  # eager host code: fine
        assert self._codes(src) == []

    def test_fn_passed_to_tracer_is_traced(self):
        src = ("import jax\nimport numpy as np\n\n"
               "def step(x):\n    return np.log(x)\n\n"
               "run = jax.jit(step)\n")
        assert self._codes(src) == ["np-in-traced"]

    def test_host_callback_body_is_exempt(self):
        src = ("import jax\nimport numpy as np\n\n"
               "@jax.jit\ndef f(x):\n"
               "    def host(v):\n        return np.log(v)\n"
               "    return jax.pure_callback(host, x, x)\n")
        assert self._codes(src) == []

    def test_inline_ignore_suppresses_one_line(self):
        src = ("import jax\n\n@jax.jit\ndef f(x):\n"
               "    a = float(x)  # mxlint: ignore[scalar-coerce-in-traced]\n"
               "    b = int(x)\n    return a + b\n")
        findings = source_lint.lint_source(src)
        assert len(findings) == 1 and findings[0].line == 6  # the int(x)

    def test_ignore_on_any_line_of_multiline_construct(self):
        """A jit call spanning lines accepts the ignore comment where
        trailing comments naturally go — the closing-paren line."""
        src = ("import jax\n\ndef build(fn):\n"
               "    return jax.jit(fn,\n"
               "                   donate_argnums=(0,),"
               "  # mxlint: ignore[donated-jit-unkeyed]\n"
               "                   )\n")
        assert source_lint.lint_source(src) == []

    def test_fingerprints_survive_edits_above(self):
        """The baseline keys on path::qualname@line-text::rule — inserting
        lines above a justified site must not churn its fingerprint."""
        body = ("@jax.jit\ndef f(x):\n    return float(x)\n")
        a = source_lint.lint_source("import jax\n\n" + body, path="m.py")
        b = source_lint.lint_source(
            "import jax\n\n\n# comment\nX = 1\n\n" + body, path="m.py")
        assert a[0].fingerprint == b[0].fingerprint
        assert a[0].line != b[0].line

    def test_split_baseline(self, tmp_path):
        src = ("import jax\n\n@jax.jit\ndef f(x):\n"
               "    return float(x) + int(x)\n")
        findings = source_lint.lint_source(src, path="m.py")
        assert len(findings) == 2
        bl = tmp_path / "baseline.txt"
        bl.write_text("# header\n%s  # justified\nm.py::gone::rule\n"
                      % findings[0].fingerprint)
        new, suppressed, stale = source_lint.split_baseline(
            findings, source_lint.load_baseline(str(bl)))
        assert new == [findings[1]]
        assert suppressed == [findings[0]]
        assert stale == ["m.py::gone::rule"]

    def test_repo_is_clean_against_committed_baseline(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        findings = source_lint.lint_paths(
            [os.path.join(repo, "mxnet_tpu")], root=repo)
        baseline = source_lint.load_baseline(
            os.path.join(repo, "ci", "mxlint_baseline.txt"))
        new = [f for f in findings if f.fingerprint not in baseline]
        assert not new, "new lint findings (fix or baseline with a " \
            "justification):\n%s" % "\n".join(str(f) for f in new)


# -- lock-discipline checker --------------------------------------------------
class TestLockcheck:
    def test_seeded_inversion_raises_under_pytest(self, lc_state):
        """ISSUE 8 seeded violation: A->B observed, then B->A must trip the
        inversion detector (and raise, since we run under pytest)."""
        a = lockcheck.CheckedLock("A")
        b = lockcheck.CheckedLock("B")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(lockcheck.LockDisciplineError,
                               match="inversion"):
                with a:
                    pass
        assert [d.code for d in lockcheck.violations()] == ["lock-inversion"]

    def test_three_lock_cycle_detected(self, lc_state):
        """A->B, B->C, C->A deadlocks three threads with no direct reverse
        edge — the detector must catch N-lock cycles, not just pairs."""
        a = lockcheck.CheckedLock("A")
        b = lockcheck.CheckedLock("B")
        c = lockcheck.CheckedLock("C")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with pytest.raises(lockcheck.LockDisciplineError,
                               match="inversion"):
                with a:
                    pass

    def test_consistent_order_is_clean(self, lc_state):
        a = lockcheck.CheckedLock("A")
        b = lockcheck.CheckedLock("B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert lockcheck.violations() == []

    def test_trylock_is_exempt_from_order_graph(self, lc_state):
        """The deadlock-avoidance idiom (trylock, back off on failure)
        cannot deadlock — it must not poison the global order graph."""
        a = lockcheck.CheckedLock("A")
        b = lockcheck.CheckedLock("B")
        with a:
            with b:
                pass
        with b:
            assert a.acquire(blocking=False)  # trylock: no B->A edge
            a.release()
        with a:  # the blocking A->B order is still the only one recorded
            with b:
                pass
        assert lockcheck.violations() == []

    def test_cross_thread_release_detected(self, lc_state):
        a = lockcheck.CheckedLock("A")
        a.acquire()
        caught = []

        def stray_release():
            try:
                a.release()
            except lockcheck.LockDisciplineError as e:
                caught.append(e)

        t = threading.Thread(target=stray_release)
        t.start()
        t.join()
        assert len(caught) == 1 and "does not hold" in str(caught[0])
        assert [d.code for d in lockcheck.violations()] \
            == ["lock-bad-release"]
        assert a.held()  # ownership survived the stray release attempt
        a.release()

    def test_reentry_detected(self, lc_state):
        a = lockcheck.CheckedLock("A")
        with a:
            with pytest.raises(lockcheck.LockDisciplineError,
                               match="re-acquires"):
                a.acquire()

    def test_reentry_raises_even_outside_pytest(self, lc_state,
                                                monkeypatch):
        """Canary mode records-and-continues for every kind EXCEPT reentry:
        continuing there would block forever on the non-reentrant lock."""
        monkeypatch.delenv("PYTEST_CURRENT_TEST", raising=False)
        a = lockcheck.CheckedLock("A")
        with a:
            with pytest.raises(lockcheck.LockDisciplineError):
                a.acquire()

    def test_unguarded_mutation_detected(self, lc_state):
        mu = lockcheck.CheckedLock("mu")
        d = lockcheck.guard({"k": 1}, mu, "_stats")
        with mu:
            d["k"] = 2          # guarded: fine
            d.update(j=3)
        assert d["k"] == 2 and len(d) == 2 and "j" in d
        assert dict(d) == {"k": 2, "j": 3}  # mapping protocol intact
        with pytest.raises(lockcheck.LockDisciplineError,
                           match="unguarded"):
            d["k"] = 3
        with pytest.raises(lockcheck.LockDisciplineError):
            d.pop("j")

    def test_field_reassignment_detected(self, lc_state):
        class Box:
            pass
        box = Box()
        box.mu = lockcheck.CheckedLock("mu")
        box.data = None
        lockcheck.instrument_fields(box, {"data": "mu"})
        assert isinstance(box, Box)  # subclass swap keeps isinstance
        with box.mu:
            box.data = {"ok": 1}    # held: fine
        with pytest.raises(lockcheck.LockDisciplineError,
                           match="reassigned"):
            box.data = {}

    def test_engine_burst_under_lockcheck_is_clean(self, lc_state,
                                                   tel_disabled,
                                                   monkeypatch):
        """The real engine's documented discipline holds: a concurrent
        burst under MXNET_LOCKCHECK=1 records zero violations (any
        violation would raise out of the engine thread's _report under
        pytest and surface as a failed request below)."""
        monkeypatch.setenv("MXNET_LOCKCHECK", "1")
        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)},
                    ladder=BucketLadder((1, 2))) as eng:
            assert isinstance(eng._cache_mu, lockcheck.CheckedLock)
            errors = []

            def client():
                try:
                    for _ in range(5):
                        r = eng.submit(
                            {"data": np.zeros((1, 8), np.float32)})
                        r.result(30.0)
                except Exception as e:
                    errors.append(e)

            threads = [threading.Thread(target=client) for _ in range(3)]
            for t in threads:
                t.start()
            eng.stats()
            for t in threads:
                t.join()
            stats = eng.stats()
        assert not errors
        assert stats["completed"] == 15
        assert lockcheck.violations() == []

    def test_violation_counts_into_telemetry(self, lc_state, monkeypatch,
                                             tmp_path):
        monkeypatch.setenv("MXNET_TELEMETRY", "1")
        monkeypatch.setenv("MXNET_TELEMETRY_FILE",
                           str(tmp_path / "t.jsonl"))
        tin._reset_for_tests()
        try:
            mu = lockcheck.CheckedLock("mu")
            d = lockcheck.guard({}, mu, "_f")
            with pytest.raises(lockcheck.LockDisciplineError):
                d["x"] = 1
            c = tin.registry().get("lockcheck_violations_total")
            assert c is not None
            assert c.value(kind="unguarded-mutation") == 1
        finally:
            tin._reset_for_tests()


# -- off-path guards (style of test_noop_guard_tracing) -----------------------
class TestOffPathsAreFree:
    def test_lockcheck_off_is_plain_locks(self, monkeypatch, tel_disabled):
        """MXNET_LOCKCHECK unset: the engine's mutexes stay vanilla
        threading.Lock, the containers stay builtin dict/set, and the
        analysis package never wraps anything — byte-identical behavior."""
        monkeypatch.delenv("MXNET_LOCKCHECK", raising=False)
        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)},
                    ladder=BucketLadder((1, 2)), start=False) as eng:
            lock_t = type(threading.Lock())
            assert type(eng._cache_mu) is lock_t
            assert type(eng._device_mu) is lock_t
            assert type(eng._stats_mu) is lock_t
            assert type(eng._stats) is dict
            assert type(eng._compiled) is set
            assert type(eng).__name__ == "Engine"  # no subclass swap

    def test_analyzers_off_warmup_rows_carry_none(self, monkeypatch,
                                                  tel_disabled):
        monkeypatch.delenv("MXNET_GRAPH_ANALYZERS", raising=False)
        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)},
                    ladder=BucketLadder((1,)), start=False) as eng:
            report = eng.warmup()
            assert all(r["check_warnings"] is None for r in report)
            assert eng.stats()["warmup"]["check_warnings"] is None

    def test_analyzers_on_warmup_rows_count(self, monkeypatch,
                                            tel_disabled):
        monkeypatch.setenv("MXNET_GRAPH_ANALYZERS", "1")
        sym, params = tiny_mlp_checkpoint()
        with Engine(sym, params, {"data": (8,)},
                    ladder=BucketLadder((1,)), start=False) as eng:
            report = eng.warmup()
            assert all(r["check_warnings"] == 0 for r in report)
            assert eng.stats()["warmup"]["check_warnings"] == 0
