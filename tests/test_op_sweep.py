"""Numpy-oracle sweep over the registry's long tail + coverage assertion.

The reference's test discipline checks nearly every operator numerically
(tests/python/unittest/test_operator.py, 6,278 LoC driving numpy oracles +
finite differences).  This file sweeps every registered op family that the
feature-focused test files don't already exercise, then asserts — as a
test — that NO canonical registry name is silently untested: each must be
mentioned by some test file or carry an explicit exemption with a reason.
"""
import glob
import os
import re

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401 — populates the registry
from mxnet_tpu.ops import registry
_R = np.random.RandomState(42)


def _d(*shape, lo=-2.0, hi=2.0):
    return (_R.rand(*shape) * (hi - lo) + lo).astype(np.float32)


def _call(name, *args, **attrs):
    import jax.numpy as jnp

    jargs = [jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args]
    return registry.get(name)(*jargs, **attrs)


def _grad_check(name, x, **attrs):
    """jax.grad of sum(op(x)) vs central differences (reference
    check_numeric_gradient discipline, test_utils.py:792)."""
    import jax
    import jax.numpy as jnp

    f = lambda a: jnp.sum(registry.get(name)(a, **attrs))
    g = np.asarray(jax.grad(f)(jnp.asarray(x)))
    eps = 1e-2
    num = np.zeros_like(x)
    flat = x.reshape(-1)
    nf = num.reshape(-1)
    for i in range(flat.size):
        xp, xm = flat.copy(), flat.copy()
        xp[i] += eps
        xm[i] -= eps
        nf[i] = (float(f(jnp.asarray(xp.reshape(x.shape))))
                 - float(f(jnp.asarray(xm.reshape(x.shape))))) / (2 * eps)
    np.testing.assert_allclose(g, num, rtol=8e-2, atol=1e-2)


# --------------------------------------------------------------------------
# unary math: (op, numpy oracle, input, smooth-for-gradcheck)
# --------------------------------------------------------------------------
UNARY = [
    ("sin", np.sin, _d(3, 4), True),
    ("cos", np.cos, _d(3, 4), True),
    ("tan", np.tan, _d(3, 4, lo=-1.0, hi=1.0), True),
    ("arcsin", np.arcsin, _d(3, 4, lo=-0.9, hi=0.9), True),
    ("arccos", np.arccos, _d(3, 4, lo=-0.9, hi=0.9), True),
    ("arctan", np.arctan, _d(3, 4), True),
    ("sinh", np.sinh, _d(3, 4), True),
    ("cosh", np.cosh, _d(3, 4), True),
    ("arcsinh", np.arcsinh, _d(3, 4), True),
    ("arccosh", np.arccosh, _d(3, 4, lo=1.5, hi=4.0), True),
    ("arctanh", np.arctanh, _d(3, 4, lo=-0.9, hi=0.9), True),
    ("degrees", np.degrees, _d(3, 4), True),
    ("radians", np.radians, _d(3, 4), True),
    ("log2", np.log2, _d(3, 4, lo=0.5, hi=4.0), True),
    ("log10", np.log10, _d(3, 4, lo=0.5, hi=4.0), True),
    ("log1p", np.log1p, _d(3, 4, lo=-0.5, hi=2.0), True),
    ("expm1", np.expm1, _d(3, 4), True),
    ("rsqrt", lambda x: 1 / np.sqrt(x), _d(3, 4, lo=0.5, hi=4.0), True),
    ("rcbrt", lambda x: 1 / np.cbrt(x), _d(3, 4, lo=0.5, hi=4.0), True),
    ("reciprocal", lambda x: 1 / x, _d(3, 4, lo=0.5, hi=4.0), True),
    ("rint", np.rint, _d(3, 4), False),
    ("fix", np.fix, _d(3, 4), False),
    ("trunc", np.trunc, _d(3, 4), False),
    ("logical_not", lambda x: (~(x != 0)).astype(np.float32), _d(3, 4), False),
    ("softsign", lambda x: x / (1 + np.abs(x)), _d(3, 4), True),
    ("gammaln", None, _d(3, 4, lo=0.5, hi=5.0), True),  # oracle via scipy-free check below
    ("erfinv", None, _d(3, 4, lo=-0.8, hi=0.8), True),
]


@pytest.mark.parametrize("name,oracle,x,smooth", UNARY, ids=[u[0] for u in UNARY])
def test_unary_oracle(name, oracle, x, smooth):
    got = np.asarray(_call(name, x))
    if oracle is not None:
        np.testing.assert_allclose(got, oracle(x), rtol=2e-5, atol=2e-5)
    else:  # inverse-pair identities for the special functions
        if name == "erfinv":
            from math import erf
            back = np.vectorize(erf)(got.astype(np.float64))
            np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)
        elif name == "gammaln":
            # Γ(x+1) = x·Γ(x)  ⇒  lgamma(x+1) − lgamma(x) = log(x)
            g1 = np.asarray(_call(name, x + 1.0))
            np.testing.assert_allclose(g1 - got, np.log(x), rtol=1e-3, atol=1e-3)
    if smooth:
        _grad_check(name, x)


# --------------------------------------------------------------------------
# broadcast + elemwise binary
# --------------------------------------------------------------------------
_BA = _d(2, 1, 4)
_BB = _d(1, 3, 4, lo=0.5, hi=2.0)
BINARY = [
    ("broadcast_sub", np.subtract),
    ("broadcast_div", np.divide),
    ("broadcast_maximum", np.maximum),
    ("broadcast_minimum", np.minimum),
    ("broadcast_power", np.power),
    ("broadcast_mod", lambda a, b: np.mod(a, b)),
    ("broadcast_hypot", np.hypot),
    ("broadcast_equal", lambda a, b: (a == b).astype(np.float32)),
    ("broadcast_not_equal", lambda a, b: (a != b).astype(np.float32)),
    ("broadcast_greater", lambda a, b: (a > b).astype(np.float32)),
    ("broadcast_greater_equal", lambda a, b: (a >= b).astype(np.float32)),
    ("broadcast_lesser", lambda a, b: (a < b).astype(np.float32)),
    ("broadcast_lesser_equal", lambda a, b: (a <= b).astype(np.float32)),
    ("broadcast_logical_and", lambda a, b: ((a != 0) & (b != 0)).astype(np.float32)),
    ("broadcast_logical_or", lambda a, b: ((a != 0) | (b != 0)).astype(np.float32)),
    ("broadcast_logical_xor", lambda a, b: ((a != 0) ^ (b != 0)).astype(np.float32)),
]


@pytest.mark.parametrize("name,oracle", BINARY, ids=[b[0] for b in BINARY])
def test_binary_broadcast_oracle(name, oracle):
    a = np.abs(_BA) + 0.5 if "power" in name else _BA
    got = np.asarray(_call(name, a, _BB))
    np.testing.assert_allclose(got, oracle(a, _BB), rtol=2e-5, atol=2e-5)


ELEMWISE = [
    ("elemwise_sub", np.subtract),
    ("_equal", lambda a, b: (a == b).astype(np.float32)),
    ("_not_equal", lambda a, b: (a != b).astype(np.float32)),
    ("_greater", lambda a, b: (a > b).astype(np.float32)),
    ("_greater_equal", lambda a, b: (a >= b).astype(np.float32)),
    ("_lesser", lambda a, b: (a < b).astype(np.float32)),
    ("_lesser_equal", lambda a, b: (a <= b).astype(np.float32)),
    ("_logical_and", lambda a, b: ((a != 0) & (b != 0)).astype(np.float32)),
    ("_logical_or", lambda a, b: ((a != 0) | (b != 0)).astype(np.float32)),
    ("_logical_xor", lambda a, b: ((a != 0) ^ (b != 0)).astype(np.float32)),
    ("_power", np.power),
    ("_hypot", np.hypot),
]


@pytest.mark.parametrize("name,oracle", ELEMWISE, ids=[e[0] for e in ELEMWISE])
def test_elemwise_binary_oracle(name, oracle):
    a, b = _d(3, 4), _d(3, 4)
    if "power" in name:
        a = np.abs(a) + 0.5
    got = np.asarray(_call(name, a, b))
    np.testing.assert_allclose(got, oracle(a, b), rtol=2e-5, atol=2e-5)


SCALAR = [
    ("_plus_scalar", lambda x, s: x + s),
    ("_minus_scalar", lambda x, s: x - s),
    ("_rminus_scalar", lambda x, s: s - x),
    ("_mul_scalar", lambda x, s: x * s),
    ("_div_scalar", lambda x, s: x / s),
    ("_rdiv_scalar", lambda x, s: s / x),
    ("_mod_scalar", lambda x, s: np.mod(x, s)),
    ("_rmod_scalar", lambda x, s: np.mod(s, x)),
    ("_power_scalar", lambda x, s: np.power(x, s)),
    ("_rpower_scalar", lambda x, s: np.power(s, x)),
    ("_maximum_scalar", np.maximum),
    ("_minimum_scalar", np.minimum),
    ("_hypot_scalar", np.hypot),
    ("_equal_scalar", lambda x, s: (x == s).astype(np.float32)),
    ("_not_equal_scalar", lambda x, s: (x != s).astype(np.float32)),
    ("_greater_scalar", lambda x, s: (x > s).astype(np.float32)),
    ("_greater_equal_scalar", lambda x, s: (x >= s).astype(np.float32)),
    ("_lesser_scalar", lambda x, s: (x < s).astype(np.float32)),
    ("_lesser_equal_scalar", lambda x, s: (x <= s).astype(np.float32)),
    ("_logical_and_scalar", lambda x, s: ((x != 0) & (s != 0)).astype(np.float32)),
    ("_logical_or_scalar", lambda x, s: ((x != 0) | (s != 0)).astype(np.float32)),
    ("_logical_xor_scalar", lambda x, s: ((x != 0) ^ (s != 0)).astype(np.float32)),
]


@pytest.mark.parametrize("name,oracle", SCALAR, ids=[s[0] for s in SCALAR])
def test_scalar_op_oracle(name, oracle):
    x = _d(3, 4, lo=0.5, hi=3.0)
    got = np.asarray(_call(name, x, scalar=1.5))
    np.testing.assert_allclose(got, oracle(x, 1.5), rtol=2e-5, atol=2e-5)


def test_maximum_mask_scalar():
    x = _d(3, 4)
    got = np.asarray(_call("_maximum_mask_scalar", x, scalar=0.5))
    np.testing.assert_allclose(got, (x >= 0.5).astype(np.float32))


# --------------------------------------------------------------------------
# reductions / shape ops
# --------------------------------------------------------------------------


def test_reductions_oracle():
    x = _d(2, 3, 4)
    np.testing.assert_allclose(np.asarray(_call("prod", x, axis=1)),
                               x.prod(axis=1), rtol=1e-5)
    xn = x.copy()
    xn[0, 0, 0] = np.nan
    np.testing.assert_allclose(np.asarray(_call("nansum", xn, axis=2)),
                               np.nansum(xn, axis=2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(_call("nanprod", xn, axis=2)),
                               np.nanprod(xn, axis=2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(_call("argmin", x, axis=1)),
                               x.argmin(axis=1).astype(np.float32))
    mean, var = _call("moments", x, axes=(0, 2))
    np.testing.assert_allclose(np.asarray(mean), x.mean(axis=(0, 2)), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(var), x.var(axis=(0, 2)), rtol=1e-4, atol=1e-5)


def test_argmax_channel_and_softmin():
    x = _d(3, 5, 4)
    np.testing.assert_allclose(np.asarray(_call("argmax_channel", x)),
                               x.argmax(axis=1).astype(np.float32))
    sm = np.asarray(_call("softmin", x, axis=1))
    e = np.exp(-x - (-x).max(axis=1, keepdims=True))
    np.testing.assert_allclose(sm, e / e.sum(axis=1, keepdims=True), rtol=1e-5, atol=1e-6)


def test_softmax_cross_entropy():
    x = _d(4, 5)
    lab = np.array([0, 3, 2, 4], np.float32)
    got = np.asarray(_call("softmax_cross_entropy", x, lab))
    e = np.exp(x - x.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    exp = -np.log(p[np.arange(4), lab.astype(int)]).sum()
    np.testing.assert_allclose(got, exp, rtol=1e-5)


def test_shape_manipulation_ops():
    x = _d(2, 3, 4)
    assert np.asarray(_call("expand_dims", x, axis=1)).shape == (2, 1, 3, 4)
    assert np.asarray(_call("squeeze", x[None])).shape == (2, 3, 4)
    np.testing.assert_allclose(np.asarray(_call("slice_axis", x, axis=1, begin=1, end=3)),
                               x[:, 1:3])
    np.testing.assert_allclose(np.asarray(_call("slice_like", x, np.zeros((2, 2, 2)))),
                               x[:2, :2, :2])
    np.testing.assert_allclose(
        np.asarray(_call("broadcast_axis", x[:, :1], axis=1, size=5)),
        np.broadcast_to(x[:, :1], (2, 5, 4)))
    np.testing.assert_allclose(
        np.asarray(_call("broadcast_like", x[:, :1], np.zeros((2, 3, 4)))),
        np.broadcast_to(x[:, :1], (2, 3, 4)))
    np.testing.assert_allclose(np.asarray(_call("shape_array", x)), [2, 3, 4])
    assert int(np.asarray(_call("size_array", x))[0]) == 24
    np.testing.assert_allclose(np.asarray(_call("SwapAxis", x, dim1=0, dim2=2)),
                               x.swapaxes(0, 2))
    parts = _call("split_v2", x, indices_or_sections=3, axis=1)
    for i, p in enumerate(parts):
        np.testing.assert_allclose(np.asarray(p), x[:, i:i + 1])
    np.testing.assert_allclose(np.asarray(_call("_linspace", start=0.0, stop=1.0, num=5)),
                               np.linspace(0, 1, 5), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(_call("_copyto", x)), x)
    np.testing.assert_allclose(
        np.asarray(_call("_identity_with_attr_like_rhs", x, np.zeros((2, 3, 4)))), x)


def test_depth_space_ops():
    x = _d(1, 8, 2, 3)
    d2s = np.asarray(_call("depth_to_space", x, block_size=2))
    assert d2s.shape == (1, 2, 4, 6)
    back = np.asarray(_call("space_to_depth", d2s, block_size=2))
    np.testing.assert_allclose(back, x)


def test_indexing_ops():
    x = _d(3, 4)
    idx = np.array([2, 0, 1], np.float32)
    np.testing.assert_allclose(np.asarray(_call("batch_take", x, idx)),
                               x[np.arange(3), idx.astype(int)])
    ind = np.array([[0, 2], [1, 3]], np.float32)  # (2, N) -> gathers (0,1),(2,3)
    np.testing.assert_allclose(np.asarray(_call("gather_nd", x, ind)),
                               x[[0, 2], [1, 3]])
    data = np.array([9.0, 8.0], np.float32)
    got = np.asarray(_call("scatter_nd", data, ind, shape=(3, 4)))
    exp = np.zeros((3, 4), np.float32)
    exp[0, 1] = 9.0
    exp[2, 3] = 8.0
    np.testing.assert_allclose(got, exp)
    got2 = np.asarray(_call("_scatter_set_nd", x, ind, data, shape=(3, 4)))
    exp2 = x.copy()
    exp2[0, 1] = 9.0
    exp2[2, 3] = 8.0
    np.testing.assert_allclose(got2, exp2)


def test_batch_dot():
    a, b = _d(3, 2, 4), _d(3, 4, 5)
    np.testing.assert_allclose(np.asarray(_call("batch_dot", a, b)),
                               np.einsum("bij,bjk->bik", a, b), rtol=1e-5, atol=1e-5)


def test_scatter_internal_helpers():
    x = _d(3, 4, lo=0.5, hi=2.0)
    np.testing.assert_allclose(np.asarray(_call("_scatter_elemwise_div", x, x)),
                               np.ones_like(x))
    np.testing.assert_allclose(np.asarray(_call("_scatter_plus_scalar", x, scalar=2.0)),
                               x + 2.0)
    np.testing.assert_allclose(np.asarray(_call("_scatter_minus_scalar", x, scalar=2.0)),
                               x - 2.0)


# --------------------------------------------------------------------------
# NN long tail
# --------------------------------------------------------------------------


def test_regression_outputs_and_svm():
    x, lab = _d(4, 3), _d(4, 3)
    np.testing.assert_allclose(np.asarray(_call("LinearRegressionOutput", x, lab)), x)
    np.testing.assert_allclose(np.asarray(_call("MAERegressionOutput", x, lab)), x)
    np.testing.assert_allclose(np.asarray(_call("LogisticRegressionOutput", x, lab)),
                               1 / (1 + np.exp(-x)), rtol=1e-5)
    lab_svm = np.array([0, 2, 1, 0], np.float32)
    np.testing.assert_allclose(np.asarray(_call("SVMOutput", x, lab_svm)), x)
    np.testing.assert_allclose(np.asarray(_call("MakeLoss", x)), x)


def test_sequence_ops():
    x = _d(4, 3, 2)  # (T, B, F)
    slen = np.array([2, 4, 1], np.float32)
    m = np.asarray(_call("SequenceMask", x, slen, use_sequence_length=True, value=-1.0))
    exp = x.copy()
    for b, l in enumerate(slen.astype(int)):
        exp[l:, b] = -1.0
    np.testing.assert_allclose(m, exp)
    last = np.asarray(_call("SequenceLast", x, slen, use_sequence_length=True))
    np.testing.assert_allclose(last, x[slen.astype(int) - 1, np.arange(3)])
    rev = np.asarray(_call("SequenceReverse", x, slen, use_sequence_length=True))
    exp = x.copy()
    for b, l in enumerate(slen.astype(int)):
        exp[:l, b] = x[:l, b][::-1]
    np.testing.assert_allclose(rev, exp)


def test_lrn_instance_l2_leaky():
    x = _d(2, 6, 4, 4)
    out = np.asarray(_call("LRN", x, nsize=3, alpha=1e-3, beta=0.75, knorm=2.0))
    # oracle: cross-channel sum of squares over the window
    exp = np.empty_like(x)
    for c in range(6):
        lo, hi = max(0, c - 1), min(6, c + 2)
        denom = (2.0 + 1e-3 / 3 * (x[:, lo:hi] ** 2).sum(axis=1)) ** 0.75
        exp[:, c] = x[:, c] / denom
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-5)

    g, b = np.ones(6, np.float32), np.zeros(6, np.float32)
    inorm = np.asarray(_call("InstanceNorm", x, g, b, eps=1e-3))
    mu = x.mean(axis=(2, 3), keepdims=True)
    var = x.var(axis=(2, 3), keepdims=True)
    np.testing.assert_allclose(inorm, (x - mu) / np.sqrt(var + 1e-3), rtol=1e-4, atol=1e-4)

    l2 = np.asarray(_call("L2Normalization", x, mode="instance"))
    nrm = np.sqrt((x.reshape(2, -1) ** 2).sum(axis=1) + 1e-10).reshape(2, 1, 1, 1)
    np.testing.assert_allclose(l2, x / nrm, rtol=1e-5, atol=1e-6)

    lk = np.asarray(_call("LeakyReLU", x, act_type="leaky", slope=0.1))
    np.testing.assert_allclose(lk, np.where(x > 0, x, 0.1 * x), rtol=1e-6)
    el = np.asarray(_call("LeakyReLU", x, act_type="elu", slope=0.3))
    np.testing.assert_allclose(el, np.where(x > 0, x, 0.3 * np.expm1(x)), rtol=1e-5, atol=1e-6)


def test_softmax_activation():
    x = _d(3, 5)
    got = np.asarray(_call("SoftmaxActivation", x))
    e = np.exp(x - x.max(axis=1, keepdims=True))
    np.testing.assert_allclose(got, e / e.sum(axis=1, keepdims=True), rtol=1e-5)
    xc = _d(2, 4, 3, 3)
    gotc = np.asarray(_call("SoftmaxActivation", xc, mode="channel"))
    ec = np.exp(xc - xc.max(axis=1, keepdims=True))
    np.testing.assert_allclose(gotc, ec / ec.sum(axis=1, keepdims=True), rtol=1e-5)


def test_upsampling_and_deconvolution():
    x = _d(1, 2, 3, 3)
    up = np.asarray(_call("UpSampling", x, scale=2, sample_type="nearest"))
    np.testing.assert_allclose(up, x.repeat(2, axis=2).repeat(2, axis=3))
    # deconvolution == transpose of convolution: check via identity kernel
    w = np.zeros((2, 2, 1, 1), np.float32)
    w[0, 0] = w[1, 1] = 1.0
    dc = np.asarray(_call("Deconvolution", x, w, kernel=(1, 1), num_filter=2,
                          no_bias=True))
    np.testing.assert_allclose(dc, x, rtol=1e-5)
    # stride-2 1x1 deconv scatters inputs on the even grid
    dc2 = np.asarray(_call("Deconvolution", x, w, kernel=(1, 1), num_filter=2,
                           stride=(2, 2), no_bias=True))
    assert dc2.shape == (1, 2, 5, 5)
    np.testing.assert_allclose(dc2[:, :, ::2, ::2], x, rtol=1e-5)


def test_spatial_transformer_family():
    x = _d(1, 1, 4, 4)
    # identity affine -> identity sampling
    loc = np.array([[1, 0, 0, 0, 1, 0]], np.float32)
    st = np.asarray(_call("SpatialTransformer", x, loc, target_shape=(4, 4),
                          transform_type="affine", sampler_type="bilinear"))
    np.testing.assert_allclose(st, x, rtol=1e-4, atol=1e-5)
    grid = np.asarray(_call("GridGenerator", loc, transform_type="affine",
                            target_shape=(4, 4)))
    assert grid.shape == (1, 2, 4, 4)
    bs = np.asarray(_call("BilinearSampler", x, grid))
    np.testing.assert_allclose(bs, x, rtol=1e-4, atol=1e-5)


def test_adaptive_avg_pooling():
    x = _d(1, 3, 6, 6)
    out = np.asarray(_call("_contrib_AdaptiveAvgPooling2D", x, output_size=(2, 2)))
    exp = x.reshape(1, 3, 2, 3, 2, 3).mean(axis=(3, 5))
    np.testing.assert_allclose(out, exp, rtol=1e-5)


def test_quantized_ops():
    x = (_R.rand(2, 4, 4, 4).astype(np.float32) - 0.5) * 2
    import jax.numpy as jnp
    q, mn, mx_ = _call("_contrib_quantize", x, np.float32(-1), np.float32(1),
                       out_type="int8")
    act, amn, amx = _call("_contrib_quantized_act", q, mn, mx_, act_type="relu")
    assert np.asarray(act).dtype == np.int8
    assert (np.asarray(act) >= 0).all()
    fl, fmn, fmx = _call("_contrib_quantized_flatten", q, mn, mx_)
    assert np.asarray(fl).shape == (2, 64)
    pl, pmn, pmx = _call("_contrib_quantized_pooling", q, mn, mx_,
                         kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert np.asarray(pl).shape == (2, 4, 2, 2)
    # dequantized max-pool matches float max-pool of dequantized input
    deq = np.asarray(q).astype(np.float32) * (1.0 / 127.0)
    exp = deq.reshape(2, 4, 2, 2, 2, 2).max(axis=(3, 5))
    got = np.asarray(pl).astype(np.float32) * (1.0 / 127.0)
    np.testing.assert_allclose(got, exp, atol=1e-2)


def test_random_samplers_statistics():
    import jax

    key_attrs = dict(shape=(4000,), key=jax.random.PRNGKey(0))
    exp = np.asarray(_call("_random_exponential", lam=2.0, **key_attrs))
    assert abs(exp.mean() - 0.5) < 0.05
    gam = np.asarray(_call("_random_gamma", alpha=3.0, beta=2.0, **key_attrs))
    assert abs(gam.mean() - 6.0) < 0.3
    poi = np.asarray(_call("_random_poisson", lam=4.0, **key_attrs))
    assert abs(poi.mean() - 4.0) < 0.2
    nb = np.asarray(_call("_random_negative_binomial", k=5, p=0.5, **key_attrs))
    assert abs(nb.mean() - 5.0) < 0.4  # mean k(1-p)/p
    gnb = np.asarray(_call("_random_generalized_negative_binomial",
                           mu=2.0, alpha=0.3, **key_attrs))
    assert abs(gnb.mean() - 2.0) < 0.3
    smn = np.asarray(_call("_sample_multinomial",
                           np.array([[0.2, 0.8]], np.float32),
                           shape=(2000,), key=jax.random.PRNGKey(1)))
    assert abs((smn == 1).mean() - 0.8) < 0.05
    sgnb = np.asarray(_call("_sample_generalized_negative_binomial",
                            np.array([3.0], np.float32),
                            np.array([0.2], np.float32),
                            shape=(2000,), key=jax.random.PRNGKey(2)))
    assert abs(sgnb.mean() - 3.0) < 0.4


def test_mp_sgd_mom_update():
    w = _d(4).astype(np.float16)
    w32 = w.astype(np.float32)
    g = _d(4).astype(np.float16)
    mom = np.zeros(4, np.float32)
    out = _call("mp_sgd_mom_update", w, g, mom, w32, lr=0.1, momentum=0.9, wd=0.0)
    outs = out if isinstance(out, tuple) else (out,)
    new_w = np.asarray(outs[0])
    exp32 = w32 - 0.1 * (0.9 * 0 + g.astype(np.float32))
    np.testing.assert_allclose(new_w.astype(np.float32), exp32, rtol=1e-3, atol=1e-3)


# --------------------------------------------------------------------------
# the coverage assertion itself
# --------------------------------------------------------------------------

# ops that cannot carry a numeric oracle here, each with the reason
EXEMPT = {}


def test_every_canonical_op_is_exercised_or_exempt():
    """No silent untested ops: every canonical registry name must be
    mentioned by some test file (this sweep included) or carry an explicit
    exemption with a reason (reference discipline: test_operator.py covers
    nearly every registered op)."""
    src = ""
    for f in glob.glob(os.path.join(os.path.dirname(__file__), "*.py")):
        src += open(f).read()
    missing = []
    seen_defs = set()
    for name, od in registry._REGISTRY.items():
        if id(od) in seen_defs:
            continue
        seen_defs.add(id(od))
        names = {od.name, *od.aliases}
        forms = set()
        for n in names:
            forms.add(n)
            forms.add(n.lstrip("_"))
            if n.startswith("_contrib_"):
                forms.add(n[len("_contrib_"):])
        if any(re.search(r"\b%s\b" % re.escape(f), src) for f in forms):
            continue
        if od.name in EXEMPT:
            continue
        missing.append(od.name)
    assert not missing, (
        "untested ops with no exemption (add a numeric test or an EXEMPT "
        "entry with a reason): %s" % sorted(missing))
