"""Fused Module train step (ISSUE 3, module/fused_step.py).

Coverage demanded by the issue:
- fused-vs-legacy numerical parity after N steps for sgd, momentum sgd and
  adam — including BatchNorm aux updates and a Dropout graph (same
  per-node folded key on both paths);
- the fallback cases (monitor installed, grad_req mix, kvstore update)
  still route through the legacy path;
- acceptance: one training step on the fused path issues exactly ONE
  compiled device dispatch (jit cache entries + telemetry counters).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import module as mod_mod
from mxnet_tpu.io import DataBatch
from mxnet_tpu.module import fused_step
from mxnet_tpu.telemetry import instrument as tin

STEPS = 5
BATCH = 8


def _sym(bn=True, dropout=True):
    data = mx.sym.var("data")
    # no_bias under BN: a bias there has an exactly-zero true gradient, and
    # adam turns float noise on a zero gradient into arbitrary-signed
    # +-lr*step drift on ANY two differently-compiled runs — a degenerate
    # parametrization, not a path difference (docs/PERF_NOTES.md)
    x = mx.sym.FullyConnected(data, name="fc1", num_hidden=16, no_bias=bn)
    if bn:
        x = mx.sym.BatchNorm(x, name="bn1")
    x = mx.sym.Activation(x, name="relu1", act_type="relu")
    if dropout:
        x = mx.sym.Dropout(x, name="drop1", p=0.5)
    x = mx.sym.FullyConnected(x, name="fc2", num_hidden=4)
    return mx.sym.SoftmaxOutput(x, name="softmax")


def _batches(steps=STEPS, batch=BATCH, dim=8):
    rng = np.random.RandomState(7)
    return [
        DataBatch(data=[mx.nd.array(rng.randn(batch, dim).astype(np.float32))],
                  label=[mx.nd.array(rng.randint(0, 4, (batch,)).astype(np.float32))])
        for _ in range(steps)
    ]


def _make_module(sym=None, **kwargs):
    mod = mod_mod.Module(sym if sym is not None else _sym(), **kwargs)
    mod.bind(data_shapes=[("data", (BATCH, 8))],
             label_shapes=[("softmax_label", (BATCH,))])
    rng = np.random.RandomState(3)
    shapes = {n: a.shape for n, a in mod._exec.arg_dict.items()}
    arg = {n: mx.nd.array(rng.randn(*shapes[n]).astype(np.float32) * 0.1)
           for n in sorted(mod._param_names)}
    mod.init_params(arg_params=arg)
    return mod


def _train(monkeypatch, fused, optimizer, opt_params, sym=None, steps=STEPS):
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1" if fused else "0")
    mx.random.seed(11)  # same per-step key sequence on both paths
    mod = _make_module(sym)
    mod.init_optimizer(optimizer=optimizer, optimizer_params=dict(opt_params))
    for b in _batches(steps):
        mod.forward_backward(b)
        mod.update()
    arg_params, aux_params = mod.get_params()
    return ({n: v.asnumpy() for n, v in arg_params.items()},
            {n: v.asnumpy() for n, v in aux_params.items()},
            mod.get_outputs()[0].asnumpy(), mod)


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
], ids=["sgd", "sgd_mom", "adam"])
def test_fused_legacy_parity(monkeypatch, optimizer, opt_params):
    """Identical params after N steps — BatchNorm aux and Dropout included
    (both paths consume one RNG key per step and fold the same per-node
    crc32 streams, so the masks match)."""
    arg_f, aux_f, out_f, mod_f = _train(monkeypatch, True, optimizer, opt_params)
    arg_l, aux_l, out_l, mod_l = _train(monkeypatch, False, optimizer, opt_params)
    assert mod_f._fused is not None, "fused path never engaged"
    assert mod_l._fused is None, "legacy run built a fused stepper"
    for n in arg_f:
        np.testing.assert_allclose(arg_f[n], arg_l[n], rtol=2e-5, atol=1e-6,
                                   err_msg="param %s" % n)
    for n in aux_f:
        np.testing.assert_allclose(aux_f[n], aux_l[n], rtol=2e-5, atol=1e-6,
                                   err_msg="aux %s" % n)
    np.testing.assert_allclose(out_f, out_l, rtol=2e-5, atol=1e-6)
    # aux actually moved (BatchNorm stats trained, not just preserved)
    assert any(np.abs(v).max() > 1e-4 for v in aux_f.values())


def test_momentum_state_matches_legacy_updater(monkeypatch):
    """Fused steps maintain the very Updater states save_optimizer_states
    pickles — switching paths mid-run stays consistent."""
    _, _, _, mod_f = _train(monkeypatch, True, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    _, _, _, mod_l = _train(monkeypatch, False, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    for i in mod_l._updater.states:
        np.testing.assert_allclose(mod_f._updater.states[i].asnumpy(),
                                   mod_l._updater.states[i].asnumpy(),
                                   rtol=2e-5, atol=1e-6)
    assert mod_f._optimizer.num_update == mod_l._optimizer.num_update


# -- fallback routing ---------------------------------------------------------
def _assert_legacy_step(mod, batch):
    """forward_backward must execute immediately (legacy), not stage."""
    mod.forward_backward(batch)
    assert not mod._fused_pending
    assert mod._fused is None
    mod.update()
    assert mod._fused is None


def test_fallback_env_disabled(monkeypatch):
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "0")
    assert not fused_step.fused_enabled()
    mod = _make_module()
    mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.1})
    _assert_legacy_step(mod, _batches(1)[0])


def test_fallback_monitor_all(monkeypatch):
    """monitor_all=True is the un-jitted escape hatch (ISSUE 12): the
    executor callback observes every node, forcing the legacy path.  A
    default pattern-filtered Monitor now rides the fused step instead
    (tests/test_trainhealth.py::test_monitor_rides_fused_step)."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    mod = _make_module()
    mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.1})
    mod.install_monitor(mx.monitor.Monitor(1, stat_func=lambda x: x,
                                           pattern=".*", monitor_all=True))
    assert fused_step.fused_ineligible_reason(mod) == "monitor"
    _assert_legacy_step(mod, _batches(1)[0])


def test_fallback_grad_req_mix(monkeypatch):
    """fixed_param_names makes grad_req a write/null mix — legacy path, and
    the fixed param must stay fixed."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    mod = _make_module(_sym(bn=False), fixed_param_names=["fc1_weight"])
    mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 1.0})
    assert fused_step.fused_ineligible_reason(mod) == "grad_req"
    before = mod.get_params()[0]["fc1_weight"].asnumpy()
    _assert_legacy_step(mod, _batches(1)[0])
    np.testing.assert_allclose(mod.get_params()[0]["fc1_weight"].asnumpy(),
                               before)


def test_fallback_kvstore(monkeypatch):
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    mod = _make_module()
    mod.init_optimizer(kvstore=mx.kv.create("local"), optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    assert fused_step.fused_ineligible_reason(mod) == "kvstore"
    w0 = mod.get_params()[0]["fc2_weight"].asnumpy()
    _assert_legacy_step(mod, _batches(1)[0])
    assert not np.allclose(mod.get_params()[0]["fc2_weight"].asnumpy(), w0)


def test_fallback_unsupported_optimizer(monkeypatch):
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    mod = _make_module()
    mod.init_optimizer(optimizer="rmsprop",
                       optimizer_params={"learning_rate": 0.01})
    assert fused_step.fused_ineligible_reason(mod) == "optimizer"
    _assert_legacy_step(mod, _batches(1)[0])


def test_interleaved_access_flushes_through_legacy(monkeypatch):
    """get_outputs between forward_backward and update materializes the
    staged step on the legacy path; the whole step still matches a pure
    legacy run."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    mx.random.seed(11)
    mod = _make_module()
    mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.1})
    b = _batches(1)[0]
    mod.forward_backward(b)
    assert mod._fused_pending
    out = mod.get_outputs()[0]          # interleaved read: flush
    assert not mod._fused_pending
    assert out.shape == (BATCH, 4)
    mod.update()                        # legacy loop on the flushed grads
    arg_i = {n: v.asnumpy() for n, v in mod.get_params()[0].items()}

    arg_l, _, out_l, _ = _train(monkeypatch, False, "sgd",
                                {"learning_rate": 0.1}, steps=1)
    for n in arg_i:
        np.testing.assert_allclose(arg_i[n], arg_l[n], rtol=2e-5, atol=1e-6,
                                   err_msg=n)
    np.testing.assert_allclose(out.asnumpy(), out_l, rtol=2e-5, atol=1e-6)


def test_fit_uses_fused_path(monkeypatch):
    """The stock fit loop (forward_backward -> update -> update_metric)
    engages the fused path and still trains to threshold."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    from mxnet_tpu.io import NDArrayIter

    rng = np.random.RandomState(0)
    X = rng.randn(200, 8).astype(np.float32)
    W = rng.randn(8, 4).astype(np.float32)
    y = np.argmax(X @ W, axis=1).astype(np.float32)
    train = NDArrayIter(X, y, batch_size=50, shuffle=True,
                        label_name="softmax_label")
    mod = mod_mod.Module(_sym(bn=False, dropout=False))
    mod.fit(train, optimizer="adam", optimizer_params={"learning_rate": 0.02},
            num_epoch=10)
    assert mod._fused is not None, "fit never took the fused path"
    score = mod.score(NDArrayIter(X, y, batch_size=50,
                                  label_name="softmax_label"), "acc")[0][1]
    assert score > 0.8, score


# -- acceptance: one dispatch per step, counted ------------------------------
def test_fused_single_dispatch_per_step(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    tin._reset_for_tests()
    try:
        mx.random.seed(11)
        mod = _make_module()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "momentum": 0.9})
        steps = 6
        for b in _batches(steps):
            mod.forward_backward(b)
            mod.update()
        r = tin.registry()
        assert r.get("train_steps_total").value(path="fused") == steps
        # THE acceptance criterion: one compiled dispatch per fused step
        assert r.get("step_dispatches_total").value(path="fused") == steps
        assert r.get("step_dispatches_total").value(path="legacy") == 0
        # one executable for the one shape signature
        assert mod._fused.cache_size() == 1
        assert r.get("jit_compiles_total").value(fn="module_fused_step") == 1
        assert r.get("jit_cache_hits_total").value(fn="module_fused_step") \
            == steps - 1
        assert r.get("module_fused_fallback_total") is None
        # and the bench summary exposes the ratio
        assert tin.summary()["dispatches_per_step"] == 1.0
    finally:
        tin._reset_for_tests()


def test_legacy_dispatch_count_counted(monkeypatch, tmp_path):
    """Legacy step = 2 (fwd+bwd) + P optimizer dispatches — the storm the
    fused path removes, kept measurable for bench regression tracking."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "0")
    tin._reset_for_tests()
    try:
        mx.random.seed(11)
        mod = _make_module()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        for b in _batches(2):
            mod.forward_backward(b)
            mod.update()
        r = tin.registry()
        nparams = len(mod._param_names)
        assert r.get("train_steps_total").value(path="legacy") == 2
        assert r.get("step_dispatches_total").value(path="legacy") \
            == 2 * (2 + nparams)
        assert r.get("module_fused_fallback_total").value(reason="disabled") == 2
        assert tin.summary()["dispatches_per_step"] == 2 + nparams
    finally:
        tin._reset_for_tests()


# -- non-finite sentinel (ISSUE 4 satellite, MXNET_NANCHECK) ------------------
def _nan_batch():
    x = np.random.RandomState(5).randn(BATCH, 8).astype(np.float32)
    x[0, 0] = np.nan
    from mxnet_tpu.io import DataBatch as DB

    return DB(data=[mx.nd.array(x)],
              label=[mx.nd.array(np.zeros(BATCH, np.float32))])


def _nancheck_module(monkeypatch, fused):
    monkeypatch.setenv("MXNET_NANCHECK", "1")
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1" if fused else "0")
    mod = _make_module(_sym(bn=False, dropout=False))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    return mod


def test_nancheck_fused_raises_one_step_late(monkeypatch):
    """The flag is folded into the fused dispatch outputs and read before
    the NEXT dispatch (no per-step sync) — the raise names the bad step."""
    from mxnet_tpu.base import MXNetError

    mod = _nancheck_module(monkeypatch, fused=True)
    mod.forward_backward(_nan_batch())
    mod.update()  # step 1 dispatches; flag not yet read
    mod.forward_backward(_batches(1)[0])
    with pytest.raises(MXNetError, match="step 1"):
        mod.update()
    assert mod._fused is not None and mod._fused._nancheck


def test_nancheck_legacy_raises_before_update(monkeypatch):
    from mxnet_tpu.base import MXNetError

    mod = _nancheck_module(monkeypatch, fused=False)
    before = {n: v.asnumpy() for n, v in mod._exec.arg_dict.items()
              if n in mod._param_names}
    mod.forward_backward(_nan_batch())
    with pytest.raises(MXNetError, match="step 1"):
        mod.update()
    # the check fires BEFORE the optimizer writes nan into the weights
    for n, v in before.items():
        assert np.isfinite(mod._exec.arg_dict[n].asnumpy()).all(), n


def test_nancheck_off_is_inert(monkeypatch):
    monkeypatch.delenv("MXNET_NANCHECK", raising=False)
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    mod = _make_module(_sym(bn=False, dropout=False))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    for _ in range(2):  # nan flows through silently, as before
        mod.forward_backward(_nan_batch())
        mod.update()
    assert mod._fused is not None and not mod._fused._nancheck


def test_nancheck_counter_and_stale_rebuild(monkeypatch, tmp_path):
    """A trip bumps nonfinite_total{where}; flipping MXNET_NANCHECK mid-run
    rebuilds the stepper (the flag changes the step's output structure)."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    tin._reset_for_tests()
    try:
        from mxnet_tpu.base import MXNetError

        mod = _nancheck_module(monkeypatch, fused=False)
        mod.forward_backward(_nan_batch())
        with pytest.raises(MXNetError):
            mod.update()
        assert tin.registry().get("nonfinite_total").value(where="legacy") == 1

        monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
        monkeypatch.delenv("MXNET_NANCHECK", raising=False)
        mod2 = _make_module(_sym(bn=False, dropout=False))
        mod2.init_optimizer(optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1})
        mod2.forward_backward(_batches(1)[0])
        mod2.update()
        first = mod2._fused
        assert not first._nancheck
        monkeypatch.setenv("MXNET_NANCHECK", "1")
        mod2.forward_backward(_batches(1)[0])
        mod2.update()
        assert mod2._fused is not first and mod2._fused._nancheck
    finally:
        tin._reset_for_tests()


def test_nancheck_last_step_drains_at_get_params(monkeypatch):
    """The deferred fused flag is checked at Module.get_params() (fit's
    epoch-end sync) so a run whose FINAL step went non-finite still raises."""
    from mxnet_tpu.base import MXNetError

    mod = _nancheck_module(monkeypatch, fused=True)
    mod.forward_backward(_nan_batch())
    mod.update()  # last step of the "run": flag pending, nothing read yet
    with pytest.raises(MXNetError, match="step 1"):
        mod.get_params()


def test_nancheck_stale_rebuild_does_not_swallow_flag(monkeypatch):
    """Swapping the optimizer (stale stepper -> rebuild) must drain the
    pending flag, not discard it with the old stepper."""
    from mxnet_tpu.base import MXNetError

    mod = _nancheck_module(monkeypatch, fused=True)
    mod.forward_backward(_nan_batch())
    mod.update()
    with pytest.raises(MXNetError, match="step 1"):
        mod.init_optimizer(optimizer="adam",
                           optimizer_params={"learning_rate": 0.01},
                           force_init=True)
