"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE jax import, per the
reference's pattern of simulating a cluster with local processes
(SURVEY §4.1 — tools/launch.py local tracker); here virtual XLA host devices
play the role of the N processes.  Real-TPU runs are the driver's job.
"""
import os

# Force CPU (overriding any ambient JAX_PLATFORMS, e.g. a tunnelled TPU) unless
# the user explicitly opts into device testing with MXNET_TEST_DEVICE=tpu.
if not os.environ.get("MXNET_TEST_DEVICE", "").startswith(("tpu", "gpu")):
    os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # The env var alone is not honored under tunnelled-TPU plugins (axon);
    # the config knob is, as long as it's set before backend init.
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from tier-1 (-m 'not slow') and "
        "the unit CI tier; run explicitly with -m slow")


@pytest.fixture(autouse=True)
def _seed_all(request):
    """Per-test deterministic seeding (reference tests/python/unittest/common.py:97
    @with_seed).  Seed is derived from the test name; printed on failure via -v."""
    import mxnet_tpu as mx

    import zlib

    # stable across processes (str hash() is PYTHONHASHSEED-randomized)
    seed = zlib.crc32(request.node.nodeid.encode()) % (2**31)
    seed = int(os.environ.get("MXNET_TEST_SEED", seed))
    np.random.seed(seed)
    mx.random.seed(seed)
    yield


def load_example_module(name, path):
    """Load an example file under a UNIQUE sys.modules name (several example
    dirs ship a ``train.py``; a bare ``import train`` resolves to whichever
    one another test cached first — order-dependent failures).  Cached by
    name so repeated loads don't re-execute top-level work.  The load itself
    is ``mxnet_tpu.test_utils.load_module_by_path`` (the one shared
    implementation, which also owns the failed-exec cleanup)."""
    import sys

    if name in sys.modules:
        return sys.modules[name]
    from mxnet_tpu.test_utils import load_module_by_path

    return load_module_by_path(path, name)
