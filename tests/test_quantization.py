"""Quantization tests — mirrors reference tests/python/quantization/
test_quantization.py (quantize/dequantize/requantize ops, quantized conv/fc,
quantize_model graph pass with none/naive/entropy calibration)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.contrib.quantization import (
    quantize_model, _get_optimal_threshold, _quantize_symbol,
)
from mxnet_tpu.io import NDArrayIter


@pytest.fixture
def rng():
    return np.random.RandomState(0)


class TestQuantizeOps:
    def test_int8_roundtrip(self, rng):
        x = rng.randn(4, 6).astype(np.float32) * 3
        q, mn, mx_ = nd.contrib.quantize(
            nd.array(x), nd.array([x.min()]), nd.array([x.max()]), out_type="int8"
        )
        assert q.asnumpy().dtype == np.int8
        back = nd.contrib.dequantize(q, mn, mx_)
        assert np.abs(back.asnumpy() - x).max() < np.abs(x).max() / 127 * 1.5

    def test_uint8_roundtrip(self, rng):
        x = rng.rand(4, 6).astype(np.float32) * 5 + 1
        q, mn, mx_ = nd.contrib.quantize(
            nd.array(x), nd.array([x.min()]), nd.array([x.max()]), out_type="uint8"
        )
        assert q.asnumpy().dtype == np.uint8
        back = nd.contrib.dequantize(q, mn, mx_)
        assert np.abs(back.asnumpy() - x).max() < (x.max() - x.min()) / 255 * 1.5

    def test_requantize_calibrated(self, rng):
        # int32 values representing floats in [-10, 10]
        f = rng.randn(8).astype(np.float32) * 3
        int32_max = float(2**31 - 1)
        data = (f / 10.0 * int32_max).astype(np.int64).astype(np.int32)
        q, mn, mx_ = nd.contrib.requantize(
            nd.array(data.astype(np.float32)).astype("int32"),
            nd.array([-10.0]), nd.array([10.0]),
            min_calib_range=-9.0, max_calib_range=9.0,
        )
        back = q.asnumpy().astype(np.float32) * 9.0 / 127
        np.testing.assert_allclose(back, np.clip(f, -9, 9), atol=9.0 / 127 + 1e-3)

    def test_quantized_fc_matches_float(self, rng):
        x = rng.randn(4, 16).astype(np.float32)
        w = rng.randn(8, 16).astype(np.float32) * 0.5
        qd, mnd, mxd = nd.contrib.quantize(nd.array(x), nd.array([x.min()]), nd.array([x.max()]), out_type="int8")
        qw, mnw, mxw = nd.contrib.quantize(nd.array(w), nd.array([w.min()]), nd.array([w.max()]), out_type="int8")
        out, omn, omx = nd.contrib.quantized_fully_connected(
            qd, qw, mnd, mxd, mnw, mxw, num_hidden=8, no_bias=True
        )
        assert out.asnumpy().dtype == np.int32
        fout = nd.contrib.dequantize(out, omn, omx).asnumpy()
        ref = x @ w.T
        assert np.abs(fout - ref).max() / np.abs(ref).max() < 0.03


class TestKLCalibration:
    def test_threshold_on_gaussian(self, rng):
        arr = rng.randn(20000).astype(np.float32)
        amin, amax, div, th = _get_optimal_threshold(arr)
        assert 0 < th <= max(abs(amin), abs(amax))
        assert np.isfinite(div)

    def test_threshold_zero_array(self):
        assert _get_optimal_threshold(np.zeros(100, np.float32))[3] == 0.0


def _small_net():
    data = sym.Variable("data")
    c1 = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1), name="conv1")
    r1 = sym.Activation(c1, act_type="relu", name="relu1")
    p1 = sym.Pooling(r1, kernel=(2, 2), stride=(2, 2), pool_type="max", name="pool1")
    fl = sym.Flatten(p1, name="flatten1")
    return sym.FullyConnected(fl, num_hidden=10, name="fc1")


def _params_for(net, rng, shape=(2, 3, 8, 8)):
    arg_shapes, _, _ = net.infer_shape(data=shape)
    return {
        n: nd.array((rng.randn(*s) * 0.2).astype(np.float32))
        for n, s in zip(net.list_arguments(), arg_shapes) if n != "data"
    }


def _fwd(net, params, X):
    exe = net.simple_bind(data=X.shape)
    for k, v in params.items():
        if k in exe.arg_dict:
            exe.arg_dict[k][:] = v
    (out,) = exe.forward(is_train=False, data=nd.array(X))
    return out.asnumpy(), exe


class TestQuantizeModel:
    @pytest.mark.parametrize("calib_mode", ["none", "naive", "entropy"])
    def test_quantized_net_close_to_fp32(self, rng, calib_mode):
        net = _small_net()
        params = _params_for(net, rng)
        X = rng.randn(2, 3, 8, 8).astype(np.float32)
        ref, _ = _fwd(net, params, X)
        kwargs = {}
        if calib_mode != "none":
            kwargs["calib_data"] = NDArrayIter(
                rng.randn(32, 3, 8, 8).astype(np.float32), batch_size=8
            )
        qsym, qargs, _ = quantize_model(net, params, {}, calib_mode=calib_mode, **kwargs)
        got, qexe = _fwd(qsym, qargs, X)
        rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert rel < 0.12, (calib_mode, rel)
        # weights are genuinely int8 in the bound executor
        assert qexe.arg_dict["conv1_weight_quantize"].dtype in (np.int8, "int8")

    def test_no_bias_conv_and_fc(self, rng):
        data = sym.Variable("data")
        c1 = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                             no_bias=True, name="conv1")
        fl = sym.Flatten(c1, name="fl")
        net = sym.FullyConnected(fl, num_hidden=6, no_bias=True, name="fc1")
        params = _params_for(net, rng)
        X = rng.randn(2, 3, 8, 8).astype(np.float32)
        ref, _ = _fwd(net, params, X)
        qsym, qargs, _ = quantize_model(net, params, {}, calib_mode="none")
        got, _ = _fwd(qsym, qargs, X)
        rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert rel < 0.1, rel

    def test_uint8_data_zero_point(self, rng):
        net = _small_net()
        params = _params_for(net, rng)
        X = rng.randn(2, 3, 8, 8).astype(np.float32)  # has negative values
        ref, _ = _fwd(net, params, X)
        qsym, qargs, _ = quantize_model(
            net, params, {}, calib_mode="none", quantized_dtype="uint8"
        )
        got, _ = _fwd(qsym, qargs, X)
        rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert rel < 0.12, rel

    def test_multi_output_group_with_calibration(self, rng):
        data = sym.Variable("data")
        c1 = sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1), name="conv1")
        c2 = sym.Convolution(c1, kernel=(1, 1), num_filter=4, name="conv2")
        net = sym.Group([c1, c2])
        params = _params_for(net, rng)
        calib = NDArrayIter(rng.randn(16, 3, 8, 8).astype(np.float32), batch_size=8)
        qsym, qargs, _ = quantize_model(net, params, {}, calib_mode="naive", calib_data=calib)
        X = rng.randn(2, 3, 8, 8).astype(np.float32)
        exe = qsym.simple_bind(data=X.shape)
        for k, v in qargs.items():
            if k in exe.arg_dict:
                exe.arg_dict[k][:] = v
        outs = exe.forward(is_train=False, data=nd.array(X))
        assert len(outs) == 2

    def test_avg_pool_count_include_pad(self, rng):
        data = sym.Variable("data")
        c1 = sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1), name="conv1")
        p1 = sym.Pooling(c1, kernel=(2, 2), stride=(2, 2), pool_type="avg",
                         count_include_pad=False, pad=(1, 1), name="pool1")
        net = sym.FullyConnected(sym.Flatten(p1, name="fl"), num_hidden=4, name="fc1")
        params = _params_for(net, rng)
        X = rng.randn(2, 3, 8, 8).astype(np.float32)
        ref, _ = _fwd(net, params, X)
        qsym, qargs, _ = quantize_model(net, params, {}, calib_mode="none")
        got, _ = _fwd(qsym, qargs, X)
        rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert rel < 0.15, rel

    def test_excluded_layer_stays_float(self, rng):
        net = _small_net()
        params = _params_for(net, rng)
        qsym, qargs, _ = quantize_model(
            net, params, {}, calib_mode="none", excluded_sym_names=["fc1"]
        )
        opnames = [n.op.name for n in qsym._walk() if n.op is not None]
        assert "FullyConnected" in opnames
        assert "_contrib_quantized_fully_connected" not in opnames
        assert "_contrib_quantized_conv" in opnames

    def test_calibration_sets_requantize_attrs(self, rng):
        net = _small_net()
        params = _params_for(net, rng)
        calib = NDArrayIter(rng.randn(16, 3, 8, 8).astype(np.float32), batch_size=8)
        qsym, _, _ = quantize_model(net, params, {}, calib_mode="naive", calib_data=calib)
        req = [n for n in qsym._walk() if n.op is not None and n.op.name == "_contrib_requantize"]
        assert req and all("min_calib_range" in n.attrs for n in req)
