"""Telemetry subsystem (ISSUE 1): registry types/labels, sink round-trips,
the MXNET_TELEMETRY=0 no-op guarantee, memory-gauge CPU fallback, profiler
satellites (metadata drop, Counter thread safety), custom-call cost
registry, the trace_summary CLI golden output, and the bench schema lint."""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.telemetry import (Histogram, JsonlSink, MetricError,
                                 PrometheusSink, ProfilerSink, Registry,
                                 render_prometheus)
from mxnet_tpu.telemetry import instrument as tin

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture
def tel_enabled(monkeypatch, tmp_path):
    """Fresh global registry with telemetry ON, JSONL in tmp."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    tin._reset_for_tests()
    yield tmp_path / "t.jsonl"
    tin._reset_for_tests()


@pytest.fixture
def tel_disabled(monkeypatch):
    monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
    tin._reset_for_tests()
    yield
    tin._reset_for_tests()


# -- registry types / labels -------------------------------------------------
class TestRegistry:
    def test_counter_labels_and_totals(self):
        r = Registry()
        c = r.counter("steps_total", "steps", ("fn",))
        c.inc(fn="a")
        c.inc(2.5, fn="a")
        c.inc(fn="b")
        assert c.value(fn="a") == 3.5
        assert r.total("steps_total") == 4.5
        assert r.counter("steps_total", labelnames=("fn",)) is c  # idempotent

    def test_counter_misuse(self):
        r = Registry()
        c = r.counter("c", labelnames=("fn",))
        with pytest.raises(MetricError):
            c.inc(-1, fn="a")          # counters are monotonic
        with pytest.raises(MetricError):
            c.inc(1)                   # missing label
        with pytest.raises(MetricError):
            c.inc(1, fn="a", extra="x")  # unknown label
        with pytest.raises(MetricError):
            r.gauge("c")               # type conflict on the same name
        with pytest.raises(MetricError):
            r.counter("c", labelnames=("other",))  # label-set conflict

    def test_gauge(self):
        r = Registry()
        g = r.gauge("hbm", labelnames=("device",))
        g.set(100, device="tpu:0")
        g.inc(5, device="tpu:0")
        g.dec(1, device="tpu:0")
        g.set(7, device="tpu:1")
        assert g.value(device="tpu:0") == 104
        assert r.max_value("hbm") == 104

    def test_histogram_buckets(self):
        r = Registry()
        h = r.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        (s,) = h.samples()
        assert s["count"] == 5 and s["sum"] == pytest.approx(56.05)
        assert s["buckets"] == [[0.1, 1], [1.0, 3], [10.0, 4], ["+Inf", 5]]
        assert r.hist_sum("lat") == pytest.approx(56.05)

    def test_histogram_bucket_conflict(self):
        r = Registry()
        r.histogram("h", buckets=(1.0, 2.0))
        r.histogram("h")                       # no buckets requested: ok
        r.histogram("h", buckets=(2.0, 1.0))   # same set, order-insensitive
        with pytest.raises(MetricError):
            r.histogram("h", buckets=(0.5,))

    def test_counter_thread_safety(self):
        r = Registry()
        c = r.counter("n")
        threads = [threading.Thread(
            target=lambda: [c.inc() for _ in range(1000)]) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == 8000


# -- profiler satellites -----------------------------------------------------
class TestProfilerSatellites:
    def test_counter_increment_thread_safe(self):
        from mxnet_tpu import profiler

        ctr = profiler.Counter(None, "hammer")
        threads = [threading.Thread(
            target=lambda: [ctr.increment() for _ in range(1000)])
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert ctr._value.get() == 8000

    def test_domain_metadata_survives_late_start(self, tmp_path):
        """A Domain created BEFORE set_state('run') must still name its pid
        in the dump (satellite: unconditional metadata recording)."""
        from mxnet_tpu import profiler

        dom = profiler.Domain("early_domain")  # profiler NOT running yet
        fname = str(tmp_path / "p.json")
        profiler.set_config(filename=fname)
        profiler.set_state("run")
        with dom.new_task("work"):
            pass
        profiler.set_state("stop")
        profiler.dump()
        evs = json.load(open(fname))["traceEvents"]
        metas = [e for e in evs if e.get("ph") == "M"
                 and e.get("name") == "process_name"
                 and e.get("args", {}).get("name") == "early_domain"]
        assert metas and metas[0]["pid"] == dom.pid
        assert any(e.get("name") == "work" for e in evs)


# -- sinks -------------------------------------------------------------------
class TestSinks:
    def test_jsonl_round_trip(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        r = Registry()
        r.add_sink(JsonlSink(path))
        r.counter("c", labelnames=("k",)).inc(3, k="x")
        r.event("compile", fn="step", seconds=1.5)
        r.flush()
        lines = [json.loads(l) for l in open(path)]
        assert lines[0]["kind"] == "compile" and lines[0]["seconds"] == 1.5
        snap = lines[1]
        assert snap["kind"] == "metrics"
        (c,) = [m for m in snap["metrics"] if m["name"] == "c"]
        assert c["samples"] == [{"labels": {"k": "x"}, "value": 3.0}]

    def test_prometheus_render_and_file(self, tmp_path):
        r = Registry()
        r.counter("req_total", "requests", ("code",)).inc(4, code="200")
        r.gauge("temp").set(1.5)
        h = Histogram("lat", buckets=(1.0,))
        h.observe(0.5)
        text = render_prometheus(r.collect() + [h.snapshot()])
        assert '# TYPE req_total counter' in text
        assert 'req_total{code="200"} 4.0' in text
        assert "temp 1.5" in text
        assert 'lat_bucket{le="1.0"} 1' in text
        assert 'lat_bucket{le="+Inf"} 1' in text
        assert "lat_count 1" in text
        path = str(tmp_path / "metrics.prom")
        r.add_sink(PrometheusSink(path))
        r.flush()
        assert 'req_total{code="200"} 4.0' in open(path).read()

    def test_prometheus_label_escaping(self):
        """Exposition-format label values escape backslash, double-quote and
        line feed — in that order, so nothing double-escapes (ISSUE 4
        satellite: quotes/backslashes/newlines in label values)."""
        r = Registry()
        c = r.counter("esc_total", "", ("path",))
        c.inc(1, path='C:\\dir "quoted"\nnext')
        text = render_prometheus(r.collect())
        assert ('esc_total{path="C:\\\\dir \\"quoted\\"\\nnext"} 1.0'
                in text)
        # and the escaped line stays one physical line
        (line,) = [l for l in text.splitlines() if l.startswith("esc_total{")]
        assert "\n" not in line

    def test_prometheus_help_escaping(self):
        """HELP text escapes only backslash and line feed; quotes pass
        through verbatim (the old shared escaper emitted an undefined \\"
        sequence there — the escaping fix this test demanded)."""
        r = Registry()
        r.counter('q_total', 'says "hi" with \\ and\nnewline')
        text = render_prometheus(r.collect())
        assert ('# HELP q_total says "hi" with \\\\ and\\nnewline'
                in text)

    def test_jsonl_unwritable_path_never_raises(self, tmp_path):
        """A bad MXNET_TELEMETRY_FILE must not kill the training step: the
        sink warns once and disables itself."""
        blocker = tmp_path / "f"
        blocker.write_text("")  # a FILE where a directory is needed
        r = Registry()
        r.add_sink(JsonlSink(str(blocker / "sub" / "t.jsonl")))
        r.event("compile", fn="s", seconds=1.0)  # swallowed, no raise
        r.counter("c").inc()
        r.flush()

    def test_profiler_bridge_sink(self, tmp_path):
        from mxnet_tpu import profiler

        fname = str(tmp_path / "p.json")
        profiler.set_config(filename=fname)
        profiler.set_state("run")
        r = Registry()
        r.add_sink(ProfilerSink())
        r.counter("tel_c", labelnames=("fn",)).inc(5, fn="a")
        r.flush()
        profiler.set_state("stop")
        evs = json.loads(profiler.dumps(reset=True))["traceEvents"]
        samples = [e for e in evs if e.get("ph") == "C"
                   and e.get("name") == "tel_c{fn=a}"]
        assert samples and samples[-1]["args"]["tel_c{fn=a}"] == 5.0


# -- gating / no-op guarantee ------------------------------------------------
class TestGating:
    def test_noop_guard_helpers(self, tel_disabled):
        import jax

        assert not tin.enabled()
        f = jax.jit(lambda x: x + 1)
        assert tin.instrument_step(f) is f        # step object unchanged
        assert tin.step_probe("fit") is None
        assert tin.summary() is None
        assert tin.event("x") is None
        assert tin.sample_memory() == {}

    def test_noop_guard_make_train_step(self, tel_disabled):
        """With MXNET_TELEMETRY unset the mesh-jitted train step is returned
        unwrapped (acceptance criterion: step object and timings unchanged)."""
        import jax

        from mxnet_tpu import gluon, parallel
        from mxnet_tpu.gluon.functional import make_train_step

        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(4))
        net.initialize()
        net(mx.nd.zeros((2, 8)))
        mesh = parallel.make_mesh({"dp": len(jax.devices())})
        step, _state, _meta = make_train_step(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), mesh=mesh)
        assert not hasattr(step, "_telemetry_instrumented")  # no wrapper
        assert hasattr(step, "lower")             # still the raw jitted fn

    def test_no_jsonl_written_when_disabled(self, tel_disabled, tmp_path,
                                            monkeypatch):
        monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "no.jsonl"))
        tin.registry().counter("c").inc()
        assert not (tmp_path / "no.jsonl").exists()

    def test_late_enable_attaches_jsonl_sink(self, monkeypatch, tmp_path):
        """A registry first touched while disabled must still gain the JSONL
        sink when MXNET_TELEMETRY is enabled later in-process."""
        monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
        monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "l.jsonl"))
        tin._reset_for_tests()
        try:
            tin.registry().counter("early").inc()   # disabled: no sink
            assert not tin.registry().sinks()
            monkeypatch.setenv("MXNET_TELEMETRY", "1")
            tin.event("compile", fn="late", seconds=1.0)
            tin.flush()
            kinds = [json.loads(l)["kind"] for l in open(tmp_path / "l.jsonl")]
            assert kinds == ["compile", "metrics"]
        finally:
            tin._reset_for_tests()

    def test_instrument_step_counts_compiles(self, tel_enabled):
        import jax

        f = tin.instrument_step(jax.jit(lambda x: x * 2), name="t",
                                batch_size=4)
        assert f._telemetry_instrumented is not None
        f(np.float32(1.0))   # compile
        f(np.float32(2.0))   # cache hit
        f(np.ones((2,), np.float32))  # new shape -> recompile
        r = tin.registry()
        assert r.total("jit_compiles_total") == 2
        assert r.total("jit_cache_hits_total") == 1
        assert r.total("jit_compile_seconds_total") > 0
        assert r.total("samples_total") == 12

    def test_memory_gauge_cpu_fallback(self, tel_enabled):
        """CPU devices report no memory_stats: empty reading, no gauges,
        summary carries peak_hbm_bytes=None — never an error."""
        assert tin.sample_memory() == {}
        s = tin.summary()
        assert s["peak_hbm_bytes"] is None
        assert s["data_wait_frac"] == 0.0

    def test_summary_and_jsonl_event_log(self, tel_enabled):
        tin.note_compile(2.0, fn="step")
        probe = tin.step_probe("fit", batch_size=8)
        probe.record_data_wait(1.0)
        probe.record_step(3.0, loss=0.5)
        s = tin.summary()
        assert s["compile_s"] == 2.0
        assert s["data_wait_frac"] == pytest.approx(1.0 / 6.0, abs=1e-4)
        tin.flush()
        lines = [json.loads(l) for l in open(tel_enabled)]
        assert [l["kind"] for l in lines[:-1]] == ["compile"]
        names = {m["name"] for m in lines[-1]["metrics"]}
        assert {"jit_compile_seconds_total", "data_wait_seconds_total",
                "step_seconds", "samples_per_sec", "last_loss"} <= names

    def test_summary_dispatches_per_step(self, tel_enabled):
        """ISSUE 3 regression surface: dispatches/step ratio from the
        train-step counters — null with no producer, 1.0 fused, 2+P
        legacy."""
        assert tin.summary()["dispatches_per_step"] is None
        tin.note_train_step("legacy")
        tin.note_dispatch(2, path="legacy")  # fwd+bwd
        tin.note_dispatch(4, path="legacy")  # per-param optimizer storm
        assert tin.summary()["dispatches_per_step"] == 6.0
        tin.note_train_step("fused")
        tin.note_dispatch(1, path="fused")
        assert tin.summary()["dispatches_per_step"] == 3.5
        tin.note_fused_fallback("monitor")
        assert tin.registry().get("module_fused_fallback_total") \
            .value(reason="monitor") == 1

    def test_note_helpers_noop_when_disabled(self, tel_disabled):
        tin.note_dispatch(3, path="legacy")
        tin.note_train_step("fused")
        tin.note_fused_fallback("monitor")
        tin._reset_for_tests()
        assert tin.registry().get("step_dispatches_total") is None


# -- wiring ------------------------------------------------------------------
class TestWiring:
    def test_speedometer_reports_data_wait(self, tel_enabled, caplog):
        import logging

        from mxnet_tpu.callback import Speedometer
        from mxnet_tpu.model import BatchEndParam

        r = tin.registry()
        wait = r.counter("data_wait_seconds_total", labelnames=("loop",))
        sp = Speedometer(batch_size=4, frequent=2, auto_reset=False)
        with caplog.at_level(logging.INFO):
            sp(BatchEndParam(epoch=0, nbatch=0, eval_metric=None, locals={}))
            wait.inc(0.25, loop="module_fit")
            sp(BatchEndParam(epoch=0, nbatch=1, eval_metric=None, locals={}))
            sp(BatchEndParam(epoch=0, nbatch=2, eval_metric=None, locals={}))
        lines = [rec.message for rec in caplog.records
                 if "samples/sec" in rec.message]
        assert lines and "data-wait=" in lines[-1]
        assert r.max_value("speedometer_samples_per_sec") > 0

    def test_speedometer_format_unchanged_when_disabled(self, tel_disabled,
                                                        caplog):
        import logging

        from mxnet_tpu.callback import Speedometer
        from mxnet_tpu.model import BatchEndParam

        sp = Speedometer(batch_size=4, frequent=1)
        with caplog.at_level(logging.INFO):
            sp(BatchEndParam(epoch=0, nbatch=0, eval_metric=None, locals={}))
            sp(BatchEndParam(epoch=0, nbatch=1, eval_metric=None, locals={}))
        (line,) = [rec.message for rec in caplog.records
                   if "samples/sec" in rec.message]
        assert "data-wait" not in line
        assert line.startswith("Iter[0] Batch [1]\tSpeed:")

    def test_kvstore_bytes_counters(self, tel_enabled):
        kv = mx.kv.create("local")
        kv.init("w", mx.nd.zeros((4, 8)))
        kv.push("w", mx.nd.ones((4, 8)))
        out = mx.nd.zeros((4, 8))
        kv.pull("w", out=out)
        r = tin.registry()
        assert r.total("kvstore_bytes_pushed_total") == 4 * 8 * 4
        assert r.total("kvstore_bytes_pulled_total") == 4 * 8 * 4


# -- custom-call cost registry ----------------------------------------------
class TestCostRegistry:
    def test_cost_fns_positive(self):
        from mxnet_tpu.ops import pallas_kernels as pk

        fns = pk.cost_fns()
        assert {"quantize_int8_pallas", "nms_alive_pallas",
                "psroi_abuild_pallas_fwd", "dconv_col_pallas_fwd",
                "dconv_col_pallas_bwd"} <= set(fns)
        for cost in (fns["quantize_int8_pallas"]((8, 128)),
                     fns["nms_alive_pallas"](2, 6000),
                     fns["psroi_abuild_pallas_fwd"](128, 16, 38, 64),
                     fns["dconv_col_pallas_fwd"](8, 1024, 2432, 256, 2)):
            assert cost["flops"] > 0 and cost["bytes_accessed"] > 0

    def test_trace_time_recording_and_multishape_mean(self):
        # recording fires at TRACE time only: reset, then use shapes no
        # other test traces, so a jit cache hit cannot hide the record
        import jax.numpy as jnp

        from mxnet_tpu.ops import pallas_kernels as pk

        pk.reset_traced_costs()
        x = jnp.asarray(np.random.randn(56, 128).astype(np.float32))
        pk.quantize_int8_pallas(x, jnp.float32(3.0), interpret=True)
        ent = pk.traced_costs()["quantize_int8_pallas"]
        assert ent["flops"] == 5 * 56 * 128
        assert ent["shape"] == [56, 128]
        assert ent["calls"] == 1 and ent["shapes"] == 1
        # a second traced shape: per-invocation cost becomes the mean, not
        # last-shape-wins (one price must cover shapeless trace events)
        x2 = jnp.asarray(np.random.randn(168, 128).astype(np.float32))
        pk.quantize_int8_pallas(x2, jnp.float32(3.0), interpret=True)
        ent = pk.traced_costs()["quantize_int8_pallas"]
        assert ent["calls"] == 2 and ent["shapes"] == 2
        assert ent["flops"] == (5 * 56 * 128 + 5 * 168 * 128) // 2

    def test_profiler_dump_embeds_costs(self, tmp_path):
        import jax.numpy as jnp

        from mxnet_tpu import profiler
        from mxnet_tpu.ops import pallas_kernels as pk

        x = jnp.asarray(np.ones((40, 128), np.float32))
        pk.quantize_int8_pallas(x, jnp.float32(1.0), interpret=True)
        evs = json.loads(profiler.dumps())["traceEvents"]
        (meta,) = [e for e in evs if e.get("name") == "custom_call_costs"]
        assert meta["args"]["quantize_int8_pallas"]["bytes_accessed"] > 0


# -- trace_summary CLI -------------------------------------------------------
GOLDEN_TRACE = {
    "traceEvents": [
        {"name": "custom_call_costs", "ph": "M", "pid": 0, "args": {
            "dconv_col_pallas_fwd": {"flops": 2_000_000,
                                     "bytes_accessed": 1_000_000, "calls": 1},
            "nms_alive_pallas": {"flops": 500_000,
                                 "bytes_accessed": 4_000_000, "calls": 1},
        }},
        # 3 invocations of the dconv kernel at 1 ms each
        {"name": "dconv_col_pallas_fwd", "ph": "X", "ts": 0, "dur": 1000,
         "pid": 0, "tid": 1},
        {"name": "dconv_col_pallas_fwd", "ph": "X", "ts": 2000, "dur": 1000,
         "pid": 0, "tid": 1},
        {"name": "dconv_col_pallas_fwd", "ph": "X", "ts": 4000, "dur": 1000,
         "pid": 0, "tid": 1},
        {"name": "unregistered_op", "ph": "X", "ts": 0, "dur": 500,
         "pid": 0, "tid": 1},
    ],
    "displayTimeUnit": "ms",
}


class TestTraceSummary:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "trace_summary.py")]
            + list(argv), capture_output=True, text=True, timeout=300)

    def test_golden_table(self, tmp_path):
        trace = tmp_path / "trace.json"
        trace.write_text(json.dumps(GOLDEN_TRACE))
        res = self._run(str(trace))
        assert res.returncode == 0, res.stderr[-500:]
        out = res.stdout
        # registered custom calls appear with non-zero FLOPs/bytes
        # (acceptance criterion: no longer invisible to cost accounting)
        dconv = [l for l in out.splitlines()
                 if l.startswith("dconv_col_pallas_fwd")]
        assert dconv, out
        # 3 calls x 2 MFLOP in 3 ms -> 2.0 GFLOP/s; 3 MB moved -> 1.0 GB/s
        assert "2.0" in dconv[0] and "1.00" in dconv[0]
        # cost-only row for the kernel with no trace events
        assert any(l.startswith("nms_alive_pallas") for l in out.splitlines())
        assert "2 registered custom call(s)" in out

    def test_golden_json(self, tmp_path):
        trace = tmp_path / "trace.json"
        trace.write_text(json.dumps(GOLDEN_TRACE))
        res = self._run(str(trace), "--json")
        assert res.returncode == 0, res.stderr[-500:]
        rows = {r["op"]: r for r in json.loads(res.stdout)["rows"]}
        d = rows["dconv_col_pallas_fwd"]
        assert d["calls"] == 3 and d["total_ms"] == pytest.approx(3.0)
        assert d["flops"] == 6_000_000 and d["bytes"] == 3_000_000
        assert d["gflops_s"] == pytest.approx(2.0)
        assert d["gb_s"] == pytest.approx(1.0)
        assert d["intensity"] == pytest.approx(2.0)
        assert d["bound"] == "memory"
        n = rows["nms_alive_pallas"]         # cost-only row
        assert n["total_ms"] is None and n["flops"] == 500_000
        u = rows["unregistered_op"]          # timed but costless
        assert u["flops"] is None and u["total_ms"] == pytest.approx(0.5)

    def test_match_prefers_exact_then_longest(self, tmp_path):
        """dequantize ops must not be billed at the quantize cost (substring
        trap), nor backward kernels at the forward alias's cost."""
        trace = {"traceEvents": [
            {"name": "custom_call_costs", "ph": "M", "pid": 0, "args": {
                "quantize_int8_pallas": {"flops": 50, "bytes_accessed": 10},
                "dequantize_int8_pallas": {"flops": 20, "bytes_accessed": 10},
                "psroi_abuild_pallas_fwd": {"flops": 100, "bytes_accessed": 10},
                "psroi_abuild_pallas_bwd": {"flops": 200, "bytes_accessed": 10},
            }},
            {"name": "custom-call.dequantize_int8", "ph": "X", "ts": 0,
             "dur": 10, "pid": 0, "tid": 1},
            {"name": "psroi_abuild_pallas_bwd", "ph": "X", "ts": 20,
             "dur": 10, "pid": 0, "tid": 1},
        ]}
        f = tmp_path / "t.json"
        f.write_text(json.dumps(trace))
        res = self._run(str(f), "--json")
        assert res.returncode == 0, res.stderr[-500:]
        rows = {r["op"]: r for r in json.loads(res.stdout)["rows"]}
        assert rows["custom-call.dequantize_int8"]["flops"] == 20
        assert rows["psroi_abuild_pallas_bwd"]["flops"] == 200

    def test_costs_from_telemetry_jsonl(self, tmp_path):
        trace = tmp_path / "trace.json"
        trace.write_text(json.dumps({"traceEvents": [
            {"name": "psroi_abuild_pallas_fwd", "ph": "X", "ts": 0,
             "dur": 100, "pid": 0, "tid": 1}]}))
        jl = tmp_path / "tel.jsonl"
        jl.write_text(json.dumps(
            {"ts": 1, "kind": "custom_call_cost",
             "name": "psroi_abuild_pallas_fwd", "flops": 1000,
             "bytes_accessed": 2000}) + "\n")
        res = self._run(str(trace), "--costs", str(jl), "--json")
        assert res.returncode == 0, res.stderr[-500:]
        (row,) = json.loads(res.stdout)["rows"]
        assert row["flops"] == 1000 and row["bytes"] == 2000


# -- bench schema lint -------------------------------------------------------
class TestBenchSchema:
    def test_self_test_and_captures(self):
        import glob

        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "ci", "check_bench_schema.py"),
             "--self-test"] + sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))),
            capture_output=True, text=True, timeout=300)
        assert res.returncode == 0, res.stdout + res.stderr

    def test_validate_line_rejects_bad_telemetry(self):
        from mxnet_tpu.test_utils import load_module_by_path

        cbs = load_module_by_path(
            os.path.join(REPO, "ci", "check_bench_schema.py"),
            "check_bench_schema")
        good = {"metric": "m", "value": 1.0, "unit": "img/s",
                "vs_baseline": None,
                "telemetry": {"compile_s": 22.7, "peak_hbm_bytes": None,
                              "data_wait_frac": 0.0}}
        cbs.validate_line(good)
        bad = dict(good, telemetry={"compile_s": "fast",
                                    "peak_hbm_bytes": None,
                                    "data_wait_frac": 0.0})
        with pytest.raises(cbs.SchemaError):
            cbs.validate_line(bad)
