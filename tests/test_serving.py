"""Serving subsystem (ISSUE 2): bucket-ladder shape math, micro-batcher
edge cases (empty deadline flush, oversize direct dispatch, mid-queue
timeout), admission shedding, cancellation, graceful degradation, the
compile-per-bucket guarantee (telemetry counter), warmup idempotence, and
the loadgen SERVE_BENCH line against the schema lint."""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving
from mxnet_tpu.serving import (BucketLadder, Engine, MicroBatcher, Request,
                               RequestCancelled, RequestTimeout, ServerBusy,
                               pow2_ladder)
from mxnet_tpu.telemetry import instrument as tin

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _mlp_engine(**kw):
    """Tiny MLP (8 -> 16 -> 4 softmax) engine, in-process params — the
    same ``test_utils.tiny_mlp_checkpoint`` model loadgen drives."""
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    kw.setdefault("ladder", BucketLadder((1, 2, 4)))
    kw.setdefault("max_wait_ms", 2.0)
    kw.setdefault("max_queue", 64)
    return Engine(sym, params, {"data": (8,)}, **kw), sym, params


@pytest.fixture
def tel_enabled(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    tin._reset_for_tests()
    yield
    tin._reset_for_tests()


@pytest.fixture
def tel_disabled(monkeypatch):
    monkeypatch.delenv("MXNET_TELEMETRY", raising=False)
    tin._reset_for_tests()
    yield
    tin._reset_for_tests()


# -- bucket ladder (pure shape math) ------------------------------------------
class TestBucketing:
    def test_pow2_ladder(self):
        assert pow2_ladder(8) == (1, 2, 4, 8)
        assert pow2_ladder(12) == (1, 2, 4, 8, 12)
        assert pow2_ladder(1) == (1,)
        with pytest.raises(ValueError):
            pow2_ladder(0)

    def test_pad_batch(self):
        lad = BucketLadder((1, 2, 4, 8))
        assert lad.pad_batch(1) == 1
        assert lad.pad_batch(3) == 4
        assert lad.pad_batch(8) == 8
        assert lad.pad_batch(9) is None
        assert lad.max_batch == 8

    def test_pad_shape_exact_class_without_buckets(self):
        lad = BucketLadder((1, 2))
        assert lad.pad_shape("data", (8,), (8,)) == (8,)
        assert lad.pad_shape("data", (9,), (8,)) is None  # no bucket fits

    def test_pad_shape_spatial_buckets(self):
        lad = BucketLadder((1, 2), shape_buckets={
            "data": [(3, 32, 32), (3, 64, 64)]})
        assert lad.pad_shape("data", (3, 20, 32), (3, 32, 32)) == (3, 32, 32)
        assert lad.pad_shape("data", (3, 33, 10), (3, 32, 32)) == (3, 64, 64)
        assert lad.pad_shape("data", (3, 65, 65), (3, 32, 32)) is None

    def test_signatures_cartesian(self):
        lad = BucketLadder((1, 4), shape_buckets={"data": [(16,), (32,)]})
        sigs = lad.signatures({"data": (16,)})
        assert len(sigs) == 4
        assert len(set(sigs)) == 4  # hashable + distinct
        lad2 = BucketLadder((1, 2, 4))
        assert len(lad2.signatures({"data": (8,)})) == 3

    def test_mixed_rank_buckets_rejected(self):
        with pytest.raises(ValueError):
            BucketLadder((1,), shape_buckets={"d": [(3, 4), (3, 4, 5)]})


# -- micro-batcher edge cases -------------------------------------------------
def _req(n=1, shapes=None, deadline=None, direct=False):
    shapes = shapes or {"data": (8,)}
    inputs = {k: np.zeros((n,) + s, np.float32) for k, s in shapes.items()}
    return Request(inputs, n, shapes, deadline=deadline, direct=direct)


class TestMicroBatcher:
    def test_empty_flush_on_deadline(self):
        """A deadline wave that expires the whole queue must produce NO
        batch — the consumer keeps waiting and the next live request goes
        through (the 'empty flush' edge case)."""
        drops = []
        b = MicroBatcher(BucketLadder((1, 2, 4)), max_wait_s=5.0,
                         on_drop=lambda r, why: drops.append(why))
        r1 = _req(deadline=time.monotonic() - 0.01)  # already expired
        r2 = _req(deadline=time.monotonic() - 0.01)
        b.put(r1)
        b.put(r2)
        got = []
        t = threading.Thread(target=lambda: got.append(b.next_batch()),
                             daemon=True)
        t.start()
        with pytest.raises(RequestTimeout):
            r1.result(timeout=2)
        with pytest.raises(RequestTimeout):
            r2.result(timeout=2)
        assert drops == ["timeout", "timeout"]
        assert not got  # no batch was formed from the expired wave
        live = _req(n=4)  # fills the top bucket -> immediate flush
        b.put(live)
        t.join(timeout=5)
        assert got and got[0] is not None
        reqs, bucket = got[0]
        assert reqs == [live] and bucket.batch == 4
        b.close()

    def test_partial_flush_after_max_wait(self):
        b = MicroBatcher(BucketLadder((1, 2, 4)), max_wait_s=0.05)
        r = _req(n=1)
        b.put(r)
        t0 = time.monotonic()
        reqs, bucket = b.next_batch()
        assert reqs == [r]
        assert bucket.batch == 1  # padded to the smallest fitting bucket
        assert 0.03 <= time.monotonic() - t0 < 2.0
        b.close()

    def test_direct_request_dispatches_alone(self):
        b = MicroBatcher(BucketLadder((1, 2, 4)), max_wait_s=5.0)
        big = _req(n=9, direct=True)
        b.put(big)
        reqs, bucket = b.next_batch()  # no wait: direct bypasses batching
        assert reqs == [big] and bucket.direct and bucket.batch == 9
        b.close()

    def test_unservable_request_rejected_at_put(self):
        """A non-direct request above the top bucket can never form a
        batch — put() must reject it instead of letting the consumer spin
        on an unservable queue head."""
        b = MicroBatcher(BucketLadder((1, 2, 4)), max_wait_s=0.01)
        with pytest.raises(ValueError, match="exceeds the top bucket"):
            b.put(_req(n=9, direct=False))
        b.close()

    def test_shape_classes_never_mix(self):
        b = MicroBatcher(BucketLadder((1, 2, 4)), max_wait_s=0.02)
        ra = _req(shapes={"data": (8,)})
        rb = _req(shapes={"data": (16,)})
        b.put(ra)
        b.put(rb)
        reqs1, bucket1 = b.next_batch()
        reqs2, bucket2 = b.next_batch()
        assert [reqs1, reqs2] == [[ra], [rb]]
        assert bucket1.sample_shape("data") == (8,)
        assert bucket2.sample_shape("data") == (16,)
        b.close()

    def test_no_cross_class_head_of_line_blocking(self):
        """A full batch of class B must dispatch immediately even when a
        younger class-A request sits at the queue head with its flush
        window still open — formation scans every shape class."""
        b = MicroBatcher(BucketLadder((1, 2, 4)), max_wait_s=5.0)
        young_head = _req(shapes={"data": (8,)})
        b.put(young_head)
        full = [_req(shapes={"data": (16,)}) for _ in range(4)]
        for r in full:
            b.put(r)
        t0 = time.monotonic()
        reqs, bucket = b.next_batch()
        assert reqs == full
        assert bucket.sample_shape("data") == (16,)
        assert time.monotonic() - t0 < 1.0  # not the head's 5s window
        b.close()

    def test_cancel_dispatch_race_settles(self):
        """cancel() and the batcher's dispatch claim settle under the
        request lock: whichever wins, the other side sees False — cancel()
        returning True really means the request never runs."""
        r = _req()
        assert r.mark_dispatched() is True
        assert r.cancel() is False          # too late: already claimed
        assert r.cancelled() is False
        r2 = _req()
        assert r2.cancel() is True
        assert r2.mark_dispatched() is False  # batcher must drop it

    def test_cancel_before_dispatch(self):
        b = MicroBatcher(BucketLadder((1, 2)), max_wait_s=0.02)
        r = _req()
        b.put(r)
        assert r.cancel() is True
        live = _req()
        b.put(live)
        reqs, _ = b.next_batch()
        assert reqs == [live]
        with pytest.raises(RequestCancelled):
            r.result(timeout=1)
        b.close()


# -- engine ------------------------------------------------------------------
class TestEngine:
    def test_predict_matches_predictor_oracle(self, tel_disabled):
        eng, sym, params = _mlp_engine()
        with eng:
            x = np.random.RandomState(1).rand(3, 8).astype(np.float32)
            out = eng.predict({"data": x})
            assert out[0].shape == (3, 4)
            from mxnet_tpu.predictor import Predictor

            ref = Predictor(sym, params, {"data": (3, 8)})
            expect = ref.forward(data=x)[0].asnumpy()
            np.testing.assert_allclose(out[0], expect, rtol=1e-5, atol=1e-6)
            # telemetry off: no probe object, no registry traffic
            assert eng._probe is None

    def test_mixed_stream_compiles_once_per_bucket(self, tel_enabled):
        """Acceptance: a mixed-shape stream through the engine triggers
        exactly ONE XLA compile per configured bucket, asserted via the
        telemetry serve compile counter."""
        eng, _, _ = _mlp_engine()
        with eng:
            ladder_len = len(eng.ladder.signatures(eng.sample_shapes))
            assert ladder_len == 3
            rng = np.random.RandomState(2)
            for n in (1, 2, 3, 4, 1, 2, 3, 1, 4, 2):
                out = eng.predict(
                    {"data": rng.rand(n, 8).astype(np.float32)})
                assert out[0].shape == (n, 4)
            c = tin.registry().get("serve_compiles_total")
            assert c is not None
            total = sum(s["value"] for s in c.samples())
            assert total == ladder_len
            assert eng.stats()["compiles"] == ladder_len
            assert eng.stats()["cache_hits"] >= 7

    def test_warmup_precompiles_everything(self, tel_enabled):
        eng, _, _ = _mlp_engine(start=False)
        report = eng.warmup()
        assert [r["fresh"] for r in report] == [True, True, True]
        assert all(r["compile_s"] > 0 for r in report)
        # idempotent: a second warmup is all cache hits
        assert all(not r["fresh"] for r in eng.warmup())
        eng.start()
        rng = np.random.RandomState(3)
        for n in (1, 2, 3, 4):
            eng.predict({"data": rng.rand(n, 8).astype(np.float32)})
        assert eng.stats()["compiles"] == 3  # stream added ZERO compiles
        c = tin.registry().get("serve_compiles_total")
        assert sum(s["value"] for s in c.samples()) == 3
        eng.close()

    def test_oversize_direct_dispatch(self, tel_disabled):
        eng, _, _ = _mlp_engine()
        with eng:
            x = np.random.RandomState(4).rand(9, 8).astype(np.float32)
            out = eng.predict({"data": x})  # 9 > top bucket 4
            assert out[0].shape == (9, 4)
            s = eng.stats()
            assert s["direct"] == 1 and s["completed"] == 1
            assert s["compiles"] == 1  # the one-off exact signature
            # repeat hits the cached direct signature
            eng.predict({"data": x})
            assert eng.stats()["compiles"] == 1

    def test_direct_cache_is_bounded(self, tel_disabled):
        """Client-controlled oversize signatures must not grow executables
        without bound: the direct cache is a small LRU, while ladder
        signatures stay pinned."""
        from mxnet_tpu.serving.engine import _DIRECT_CACHE_MAX

        eng, _, _ = _mlp_engine()
        with eng:
            for n in range(5, 5 + _DIRECT_CACHE_MAX + 4):  # all > top bucket
                out = eng.predict({"data": np.zeros((n, 8), np.float32)})
                assert out[0].shape == (n, 4)
            s = eng.stats()
            assert s["direct"] == _DIRECT_CACHE_MAX + 4
            assert s["compiles"] == _DIRECT_CACHE_MAX + 4  # honest count
            assert s["cache_size"] <= 3 + _DIRECT_CACHE_MAX
            # an evicted signature recompiles on return, counted again
            eng.predict({"data": np.zeros((5, 8), np.float32)})
            assert eng.stats()["compiles"] == _DIRECT_CACHE_MAX + 5

    def test_timeout_mid_queue(self, tel_disabled):
        """A queued request whose deadline fires before the flush window
        closes is dropped ON TIME (the batcher wakes at the deadline, not
        at the 10s flush), and the loop keeps serving."""
        eng, _, _ = _mlp_engine(max_wait_ms=10000.0)
        with eng:
            req = eng.submit({"data": np.zeros((1, 8), np.float32)},
                             timeout=0.05)
            t0 = time.monotonic()
            with pytest.raises(RequestTimeout):
                req.result(timeout=5)
            assert time.monotonic() - t0 < 2.0  # not the 10s flush window
            assert eng.stats()["timeouts"] == 1
            # a full bucket flushes immediately -> loop demonstrably alive
            out = eng.predict({"data": np.zeros((4, 8), np.float32)})
            assert out[0].shape == (4, 4)
            assert eng.stats()["in_flight"] == 0

    def test_cancel_wakes_batcher_promptly(self, tel_disabled):
        """cancel() must wake the sleeping batcher so the request is failed
        (and its queue slot freed) NOW, not at the end of a long flush
        window."""
        eng, _, _ = _mlp_engine(max_wait_ms=10000.0)
        with eng:
            req = eng.submit({"data": np.zeros((1, 8), np.float32)})
            assert req.cancel() is True
            t0 = time.monotonic()
            with pytest.raises(RequestCancelled):
                req.result(timeout=5)
            assert time.monotonic() - t0 < 2.0  # not the 10s flush window
            s = eng.stats()
            assert s["cancelled"] == 1 and s["in_flight"] == 0
            assert s["queue_depth"] == 0  # the slot was released

    def test_admission_shed_and_recovery(self, tel_disabled):
        eng, _, _ = _mlp_engine(max_queue=2, start=False)
        r1 = eng.submit({"data": np.zeros((1, 8), np.float32)})
        r2 = eng.submit({"data": np.zeros((1, 8), np.float32)})
        with pytest.raises(ServerBusy):
            eng.submit({"data": np.zeros((1, 8), np.float32)})
        assert eng.stats()["shed"] == 1
        eng.start()  # drain: both queued requests complete
        assert r1.result(timeout=10)[0].shape == (1, 4)
        assert r2.result(timeout=10)[0].shape == (1, 4)
        assert eng.stats()["completed"] == 2
        eng.close()

    def test_model_error_degrades_gracefully(self, tel_disabled):
        eng, _, _ = _mlp_engine()
        with eng:
            eng.predict({"data": np.zeros((1, 8), np.float32)})
            boom = RuntimeError("injected model failure")
            orig = eng._assemble

            def bad_assemble(reqs, bucket):
                eng._assemble = orig
                raise boom

            eng._assemble = bad_assemble
            req = eng.submit({"data": np.zeros((1, 8), np.float32)})
            with pytest.raises(RuntimeError, match="injected"):
                req.result(timeout=10)
            assert eng.stats()["failed"] == 1
            # the device loop survived the failure
            out = eng.predict({"data": np.zeros((2, 8), np.float32)})
            assert out[0].shape == (2, 4)
            assert eng.stats()["in_flight"] == 0

    def test_failed_first_forward_recounts_compile(self, tel_disabled):
        """A signature whose FIRST forward fails never compiled — the
        successful retry must pay and count the real compile (the
        acceptance counter tracks actual XLA compiles)."""
        eng, _, _ = _mlp_engine()
        with eng:
            orig = eng._assemble

            def bad_assemble(reqs, bucket):
                eng._assemble = orig
                raise RuntimeError("first-forward failure")

            eng._assemble = bad_assemble
            req = eng.submit({"data": np.zeros((1, 8), np.float32)})
            with pytest.raises(RuntimeError, match="first-forward"):
                req.result(timeout=10)
            assert eng.stats()["compiles"] == 0  # nothing actually compiled
            eng.predict({"data": np.zeros((1, 8), np.float32)})
            assert eng.stats()["compiles"] == 1  # the retry counts it

    def test_predict_requires_running_loop(self, tel_disabled):
        """Synchronous predict() on an engine with no device loop would
        hang forever (deadlines are enforced by the loop) — it must fail
        fast instead; async submit stays legal for warmup-first flows."""
        eng, _, _ = _mlp_engine(start=False)
        with pytest.raises(serving.EngineClosed, match="not serving"):
            eng.predict({"data": np.zeros((1, 8), np.float32)})
        req = eng.submit({"data": np.zeros((1, 8), np.float32)})
        eng.start()
        assert req.result(timeout=10)[0].shape == (1, 4)
        eng.close()

    def test_closed_engine_rejects_and_fails_pending(self, tel_disabled):
        eng, _, _ = _mlp_engine(start=False)
        req = eng.submit({"data": np.zeros((1, 8), np.float32)})
        eng.close()
        with pytest.raises(serving.EngineClosed):
            req.result(timeout=1)
        with pytest.raises(serving.EngineClosed):
            eng.submit({"data": np.zeros((1, 8), np.float32)})

    def test_input_validation(self, tel_disabled):
        eng, _, _ = _mlp_engine()
        with eng:
            with pytest.raises(ValueError, match="!= declared"):
                eng.submit({"bogus": np.zeros((1, 8), np.float32)})
            with pytest.raises(ValueError, match="leading sample dim"):
                eng.submit({"data": np.zeros((8,), np.float32)})
            with pytest.raises(ValueError, match="at least one sample"):
                eng.submit({"data": np.zeros((0, 8), np.float32)})
            # one huge request would stall the single device loop for all
            # callers — beyond 4x the top bucket the client must chunk
            with pytest.raises(ValueError, match="max_direct_batch"):
                eng.submit({"data": np.zeros((17, 8), np.float32)})

    def test_telemetry_metrics_populated(self, tel_enabled):
        eng, _, _ = _mlp_engine()
        with eng:
            rng = np.random.RandomState(5)
            for n in (1, 3, 2):
                eng.predict({"data": rng.rand(n, 8).astype(np.float32)})
            r = tin.registry()
            assert r.total("serve_requests_total") == 3
            fill = r.get("serve_batch_fill")
            (s,) = fill.samples()
            assert s["count"] == eng.stats()["batches"]
            q = r.get("serve_queue_seconds")
            assert sum(x["count"] for x in q.samples()) == 3
            assert r.get("serve_padding_waste") is not None

    def test_spatial_bucketing_pads_and_slices(self, tel_disabled):
        """Spatial shape buckets: a shorter sample is zero-padded up to its
        bucket; output rows are sliced back per request (non-batch dims
        stay at the bucket shape, documented contract)."""
        data = mx.sym.Variable("data")
        sym = mx.sym.Activation(data, act_type="relu", name="r")
        ladder = BucketLadder((1, 2), shape_buckets={"data": [(4,), (8,)]})
        eng = Engine(sym, {}, {"data": (4,)}, ladder=ladder, max_wait_ms=2.0)
        with eng:
            x = np.array([[-1.0, 2.0, -3.0]], np.float32)  # sample shape (3,)
            out = eng.predict({"data": x})
            assert out[0].shape == (1, 4)  # padded into the (4,) bucket
            np.testing.assert_allclose(out[0][0, :3], [0.0, 2.0, 0.0])
            np.testing.assert_allclose(out[0][0, 3:], 0.0)
            y = np.ones((1, 7), np.float32)  # -> the (8,) bucket
            out2 = eng.predict({"data": y})
            assert out2[0].shape == (1, 8)
            assert len(eng.ladder.signatures(eng.sample_shapes)) == 4


# -- loadgen / SERVE_BENCH ----------------------------------------------------
@pytest.mark.slow
def test_loadgen_emits_schema_valid_serve_bench(tmp_path):
    """Acceptance: tools/loadgen.py against the tiny-symbol engine on CPU
    emits schema-valid SERVE_BENCH lines with nonzero throughput and p99."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--smoke", "--duration", "0.4"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [l for l in out.stdout.splitlines()
             if l.startswith("SERVE_BENCH ")]
    assert len(lines) == 2  # closed + open
    sys.path.insert(0, os.path.join(REPO, "ci"))
    try:
        import check_bench_schema as cbs
    finally:
        sys.path.pop(0)
    import json

    for line in lines:
        obj = json.loads(line[len("SERVE_BENCH "):])
        cbs.validate_serve_line(obj, "loadgen")
        assert obj["throughput_rps"] > 0
        assert obj["latency_ms_p99"] > 0
        # compiles is a per-RUN delta: warmup took the ladder's 3, so the
        # traffic itself must add zero
        assert obj["compiles"] == 0
