"""Faster-RCNN detection-quality regression gate (VERDICT round-3 item 1).

Runs the full jit-fused Faster-RCNN synthetic-VOC recipe
(examples/quality/eval_frcnn_map.py) at the calibrated nightly config and
fails if mAP drops below the floor.  Same discipline as the R-FCN gate
(tests/test_quality_map.py): seeded train stream, init, and held-out
n=500 eval stream, so a drop means a real pipeline change, not noise.

Floor 0.04 is provisional (sanity-level: an untrained pipeline scores
~0.00x); the 3-seed calibration runs are queued and the final floor —
worst seed − ~20%, with the three mAP values recorded in QUALITY.md §3 —
replaces it when they land.
"""
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SCRIPT = os.path.join(REPO, "examples", "quality", "eval_frcnn_map.py")


def test_frcnn_synthetic_map_floor():
    res = subprocess.run(
        [sys.executable, SCRIPT, "--steps", "1200", "--eval-images", "500",
         "--lr", "0.02", "--map-floor", "0.04"],
        capture_output=True, text=True, timeout=5400)
    tail = "\n".join(res.stdout.splitlines()[-5:]) + res.stderr[-2000:]
    assert res.returncode == 0, tail
    assert "FINAL frcnn" in res.stdout, tail
