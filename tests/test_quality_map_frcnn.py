"""Faster-RCNN detection-quality regression gate (VERDICT round-3 item 1).

Runs the full jit-fused Faster-RCNN synthetic-VOC recipe
(examples/quality/eval_frcnn_map.py) at the calibrated nightly config and
fails if mAP drops below the floor.  Same discipline as the R-FCN gate
(tests/test_quality_map.py): seeded train stream, init, and held-out
n=500 eval stream, so a drop means a real pipeline change, not noise.

Calibration (this config, CPU, round 4): seeds 0/1/2 → mAP 0.0319 /
0.0354 / 0.0285 at the script-default lr 2e-3 (the 0.02-lr probe
collapsed on 2 of 3 seeds: 0.004 vs 0.026).  Floor 0.022 = worst seed −
~23% — far above a broken pipeline (~0.000 at 60 steps).
"""
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SCRIPT = os.path.join(REPO, "examples", "quality", "eval_frcnn_map.py")


def test_frcnn_synthetic_map_floor():
    res = subprocess.run(
        [sys.executable, SCRIPT, "--steps", "1200", "--eval-images", "500",
         "--map-floor", "0.022"],
        capture_output=True, text=True, timeout=5400)
    tail = "\n".join(res.stdout.splitlines()[-5:]) + res.stderr[-2000:]
    assert res.returncode == 0, tail
    assert "FINAL frcnn" in res.stdout, tail
