"""Optimizer/metric/io/initializer tests — modeled on reference
tests/python/unittest/{test_optimizer,test_metric,test_io,test_init}.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import optimizer as opt
from mxnet_tpu import metric as metric_mod
from mxnet_tpu.io import NDArrayIter, PrefetchingIter, ResizeIter
from mxnet_tpu.test_utils import assert_almost_equal


def _loss_and_grad(w):
    # f(w) = 0.5*||w||^2 -> grad = w ; minimum at 0
    return w


def test_sgd_converges():
    w = nd.array([10.0, -10.0])
    sgd = opt.SGD(learning_rate=0.5, momentum=0.0)
    state = sgd.create_state(0, w)
    for _ in range(30):
        sgd.update(0, w, _loss_and_grad(w), state)
    assert float(nd.norm(w).asscalar()) < 1e-3


def test_sgd_momentum_matches_formula():
    w = nd.array([1.0])
    g = nd.array([1.0])
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9)
    state = sgd.create_state(0, w)
    sgd.update(0, w, g, state)  # mom = -0.1; w = 0.9
    assert_almost_equal(w, np.array([0.9], dtype=np.float32))
    sgd.update(0, w, g, state)  # mom = 0.9*-0.1 - 0.1 = -0.19; w = 0.71
    assert_almost_equal(w, np.array([0.71], dtype=np.float32), rtol=1e-5)


@pytest.mark.parametrize(
    "name,kwargs",
    [
        ("sgd", {"learning_rate": 0.3, "momentum": 0.9}),
        ("nag", {"learning_rate": 0.2, "momentum": 0.9}),
        ("adam", {"learning_rate": 0.3}),
        ("adagrad", {"learning_rate": 0.9}),
        ("rmsprop", {"learning_rate": 0.3}),
        ("adadelta", {"learning_rate": 1.0, "rho": 0.9, "epsilon": 1e-2}),
        ("adamax", {"learning_rate": 0.4}),
        ("nadam", {"learning_rate": 0.3}),
        ("ftrl", {"learning_rate": 2.0}),
        ("signum", {"learning_rate": 0.02}),
        ("ftml", {"learning_rate": 0.3}),
        ("test", {"learning_rate": 0.3}),
    ],
)
def test_optimizers_reduce_quadratic(name, kwargs):
    np.random.seed(0)
    w = nd.array(np.random.rand(5).astype(np.float32) * 4 + 1)
    o = opt.create(name, **kwargs)
    state = o.create_state(0, w)
    start = float(nd.norm(w).asscalar())
    for _ in range(100):
        o.update(0, w, w.copy(), state)
    end = float(nd.norm(w).asscalar())
    assert end < start * 0.5, "%s did not reduce ||w||: %f -> %f" % (name, start, end)


def test_lr_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler, MultiFactorScheduler, PolyScheduler

    s = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(0) == 1.0 and s(10) == 0.5 and s(20) == 0.25
    m = MultiFactorScheduler(step=[5, 15], factor=0.1, base_lr=1.0)
    assert m(0) == 1.0 and abs(m(6) - 0.1) < 1e-9 and abs(m(16) - 0.01) < 1e-9
    p = PolyScheduler(max_update=100, base_lr=1.0, pwr=1)
    assert p(0) == 1.0 and abs(p(50) - 0.5) < 1e-6 and p(100) == 0.0


def test_updater_and_serialization():
    w = nd.array([4.0])
    g = nd.array([1.0])
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(o)
    upd(0, g, w)
    st = upd.get_states()
    upd2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9))
    upd2.set_states(st)
    upd(0, g, w)
    assert 0 in upd2.states


def test_accuracy_metric():
    m = metric_mod.create("acc")
    pred = nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = nd.array([1, 0, 0])
    m.update([label], [pred])
    assert abs(m.get()[1] - 2.0 / 3) < 1e-6
    m.reset()
    assert np.isnan(m.get()[1])


def test_topk_f1_mse():
    m = metric_mod.create("top_k_accuracy", top_k=2)
    pred = nd.array([[0.1, 0.5, 0.4], [0.7, 0.2, 0.1]])
    label = nd.array([0, 1])  # row0: top2={1,2} miss; row1: top2={0,1} hit
    m.update([label], [pred])
    assert abs(m.get()[1] - 0.5) < 1e-6
    mse = metric_mod.create("mse")
    mse.update([nd.array([1.0, 2.0])], [nd.array([1.5, 2.5])])
    assert abs(mse.get()[1] - 0.25) < 1e-6


def test_composite_metric():
    m = metric_mod.create(["acc", "mse"])
    assert isinstance(m, metric_mod.CompositeEvalMetric)


def test_custom_metric():
    m = metric_mod.np(lambda label, pred: float(np.abs(label - pred).mean()))
    m.update([nd.array([1.0])], [nd.array([2.0])])
    assert abs(m.get()[1] - 1.0) < 1e-6


def test_ndarray_iter():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = NDArrayIter(data, label, batch_size=3, shuffle=False, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4  # ceil(10/3)
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 4
    # discard mode
    it2 = NDArrayIter(data, label, batch_size=3, last_batch_handle="discard")
    assert len(list(it2)) == 3
    # dict data
    it3 = NDArrayIter({"a": data}, {"lab": label}, batch_size=5)
    assert it3.provide_data[0].name == "a"
    assert it3.provide_data[0].shape == (5, 4)


def test_resize_and_prefetch_iter():
    data = np.random.rand(8, 2).astype(np.float32)
    base = NDArrayIter(data, batch_size=2)
    r = ResizeIter(NDArrayIter(data, batch_size=2), size=2)
    assert len(list(r)) == 2
    p = PrefetchingIter(NDArrayIter(data, batch_size=2))
    batches = list(p)
    assert len(batches) == 4
    p.reset()
    assert len(list(p)) == 4


def test_initializers():
    from mxnet_tpu import initializer as init

    w = nd.zeros((4, 4))
    init.Xavier()("fc_weight", w)
    assert float(nd.norm(w).asscalar()) > 0
    b = nd.ones((4,))
    init.Xavier()("fc_bias", b)
    assert float(nd.norm(b).asscalar()) == 0  # bias -> zero
    g = nd.zeros((4,))
    init.Uniform()("bn_gamma", g)
    assert (g.asnumpy() == 1).all()  # gamma -> one
    c = nd.zeros((2, 2))
    init.Constant(3.0)("custom_weight", c)
    assert (c.asnumpy() == 3).all()
    o = nd.zeros((4, 8))
    init.Orthogonal()("q_weight", o)
    q = o.asnumpy()
    assert_almost_equal(q @ q.T, (1.414**2) * np.eye(4), rtol=1e-3, atol=1e-4)


def test_mixed_initializer():
    from mxnet_tpu import initializer as init

    w1 = nd.zeros((2, 2))
    w2 = nd.zeros((2, 2))
    mixed = init.Mixed([".*special.*", ".*"], [init.Constant(7.0), init.Zero()])
    mixed("special_weight", w1)
    mixed("plain_weight", w2)
    assert (w1.asnumpy() == 7).all()
    assert (w2.asnumpy() == 0).all()
