"""Graph-pass layer tests (ISSUE 7, mxnet_tpu/graph_passes/).

The contract under test:

* parity — representative symbols (MLP, conv+BN, multi-output heads,
  dropout/stochastic nodes, Group graphs) produce identical outputs,
  gradients, and aux-state updates with passes on vs off, in both modes;
* the inference rewrites fire (BatchNorm -> affine, Dropout deleted) on
  eval plans only;
* stochastic nodes are NEVER deduped — each keeps its own PRNG stream;
* ``MXNET_GRAPH_PASSES=0`` lowers the raw captured plan untouched and
  produces pre-pass AOT cache keys, byte-identical;
* pass results surface in ``pass_stats``, the telemetry summary block, and
  ``debug_str``.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def _fill_params(exe, seed=1):
    prng = np.random.RandomState(seed)
    for n, arr in exe.arg_dict.items():
        if n != "data" and not n.endswith("_label"):
            arr[:] = (prng.rand(*arr.shape).astype(np.float32) - 0.5) * 0.2


def _run_both(symbol, feeds, monkeypatch, train, grad_wrt=(), seed=7,
              label=None):
    """Bind + forward (+ backward) under each gate value.
    -> {gate: (outputs, grads, aux, exe)} with identical inputs/params/RNG."""
    results = {}
    for gate in ("0", "1"):
        monkeypatch.setenv("MXNET_GRAPH_PASSES", gate)
        mx.random.seed(seed)
        shapes = {k: v.shape for k, v in feeds.items()}
        exe = symbol.simple_bind(grad_req="write" if grad_wrt else "null",
                                 **shapes)
        _fill_params(exe)
        for k, v in feeds.items():
            exe.arg_dict[k][:] = v
        if label is not None:
            exe.arg_dict[label[0]][:] = label[1]
        outs = [o.asnumpy() for o in exe.forward(is_train=train)]
        grads = {}
        if grad_wrt:
            exe.backward()
            grads = {n: exe.grad_dict[n].asnumpy() for n in grad_wrt}
        aux = {k: v.asnumpy() for k, v in exe.aux_dict.items()}
        results[gate] = (outs, grads, aux, exe)
    return results


def _assert_parity(results, exact=True):
    o0, g0, a0, _ = results["0"]
    o1, g1, a1, _ = results["1"]
    cmp = (np.array_equal if exact
           else lambda a, b: np.allclose(a, b, rtol=1e-5, atol=1e-6))
    for i, (x, y) in enumerate(zip(o0, o1)):
        assert cmp(x, y), "output %d diverged (max %g)" % (
            i, np.abs(x - y).max())
    assert g0.keys() == g1.keys()
    for n in g0:
        assert cmp(g0[n], g1[n]), "grad %s diverged (max %g)" % (
            n, np.abs(g0[n] - g1[n]).max())
    assert a0.keys() == a1.keys()
    for n in a0:
        assert cmp(a0[n], a1[n]), "aux %s diverged" % n


def _plan_ops(exe, train):
    plan, _, _ = exe._opt_plan(train)
    return [n.op.name for n, _ in plan]


# -- parity sweep -------------------------------------------------------------

def _mlp():
    data = sym.var("data")
    h = sym.Activation(sym.FullyConnected(data, name="fc1", num_hidden=16),
                       name="a1", act_type="relu")
    return sym.SoftmaxOutput(
        sym.FullyConnected(h, name="fc2", num_hidden=4), name="softmax")


@pytest.mark.parametrize("train", [False, True])
def test_mlp_parity(monkeypatch, train):
    rng = np.random.RandomState(0)
    feeds = {"data": rng.rand(3, 8).astype(np.float32)}
    label = ("softmax_label", np.array([0.0, 1.0, 2.0], np.float32))
    res = _run_both(_mlp(), feeds, monkeypatch, train,
                    grad_wrt=("fc1_weight", "fc2_bias") if train else (),
                    label=label)
    _assert_parity(res, exact=True)


def _conv_bn(dropout=False):
    data = sym.var("data")
    c = sym.Convolution(data, name="conv", kernel=(3, 3), num_filter=4,
                        pad=(1, 1))
    b = sym.BatchNorm(c, name="bn", fix_gamma=False, momentum=0.8)
    h = sym.Activation(b, name="act", act_type="relu")
    if dropout:
        h = sym.Dropout(h, name="drop", p=0.5)
    f = sym.FullyConnected(sym.Flatten(h, name="fl"), name="fc",
                           num_hidden=3)
    return sym.SoftmaxOutput(f, name="softmax")


@pytest.mark.parametrize("train", [False, True])
def test_conv_bn_parity_and_aux(monkeypatch, train):
    rng = np.random.RandomState(1)
    feeds = {"data": rng.rand(2, 3, 6, 6).astype(np.float32)}
    label = ("softmax_label", np.array([0.0, 1.0], np.float32))
    res = _run_both(_conv_bn(), feeds, monkeypatch, train,
                    grad_wrt=("conv_weight",) if train else (), label=label)
    # train mode must update moving_mean/moving_var identically on both
    # gates (aux compared inside _assert_parity); the inference BN-affine
    # rewrite is allclose by contract (identical expression sequence makes
    # it exact in practice, but the contract is the looser one)
    _assert_parity(res, exact=train)
    if train:
        aux = res["1"][2]
        assert not np.allclose(aux["bn_moving_mean"], 0.0), \
            "train forward should have updated BN moving stats"


def test_bn_affine_rewrite_fires_eval_only(monkeypatch):
    rng = np.random.RandomState(2)
    feeds = {"data": rng.rand(2, 3, 6, 6).astype(np.float32)}
    label = ("softmax_label", np.zeros(2, np.float32))
    res = _run_both(_conv_bn(dropout=True), feeds, monkeypatch, train=False,
                    label=label)
    exe = res["1"][3]
    eval_ops = _plan_ops(exe, False)
    assert "_bn_affine" in eval_ops and "BatchNorm" not in eval_ops
    assert "Dropout" not in eval_ops
    # the raw captured plan still carries both
    raw = [n.op.name for n, _ in exe._plan]
    assert "BatchNorm" in raw and "Dropout" in raw
    _assert_parity(res, exact=False)
    # train plan keeps the real BatchNorm (aux updates are a side effect)
    assert "BatchNorm" in _plan_ops(exe, True)
    assert "_bn_affine" not in _plan_ops(exe, True)


def test_eval_plan_stochastic_survivor_trips_analyzer(monkeypatch):
    """ISSUE 8 satellite: a mode="always" Dropout survives the inference
    rewrite (by design — MC-dropout), and the graph-IR analyzer flags
    exactly that survivor in the eval plan; the plain Dropout next to it is
    deleted and stays silent."""
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    data = sym.var("data")
    out = sym.Dropout(sym.Dropout(data, name="plain", p=0.5),
                      name="mc", p=0.5, mode="always")
    exe = out.bind(None, {"data": nd.array(np.ones((2, 4), np.float32))})
    assert _plan_ops(exe, False) == ["Dropout"]  # only the forced one
    diags = exe.check(is_train=False)
    assert [(d.code, d.where) for d in diags] \
        == [("prng-eval-stochastic", "mc")]
    # the analyzer sees the plan the passes actually produce: with the
    # forced dropout removed the eval plan is clean
    clean = sym.Dropout(data, name="plain2", p=0.5).bind(
        None, {"data": nd.array(np.ones((2, 4), np.float32))})
    assert clean.check(is_train=False) == []


def test_multi_output_heads_group_parity(monkeypatch):
    data = sym.var("data")
    sl = sym.SliceChannel(data, name="sl", num_outputs=2, axis=1)
    a = sym.exp(sl[0], name="e")
    b = sym.sqrt(sl[1] + 1.0, name="s")
    g = sym.Group([a, b, sl[1]])
    rng = np.random.RandomState(3)
    feeds = {"data": rng.rand(2, 4).astype(np.float32)}
    res = _run_both(g, feeds, monkeypatch, train=False)
    _assert_parity(res, exact=True)
    assert len(res["1"][0]) == 3


@pytest.mark.parametrize("train", [False, True])
def test_dropout_stream_parity(monkeypatch, train):
    """Dropout masks must be identical with passes on/off (per-node-name
    PRNG folding survives the pipeline untouched)."""
    data = sym.var("data")
    d = sym.Dropout(data, name="d1", p=0.5)
    out = sym.FullyConnected(d, name="fc", num_hidden=4)
    rng = np.random.RandomState(4)
    feeds = {"data": rng.rand(8, 8).astype(np.float32)}
    res = _run_both(out, feeds, monkeypatch, train,
                    grad_wrt=("fc_weight",) if train else ())
    _assert_parity(res, exact=True)


# -- individual passes --------------------------------------------------------

def test_cse_merges_and_dce_sweeps(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    data = sym.var("data")
    out = sym.sqrt(sym.exp(data)) * sym.sqrt(sym.exp(data))
    exe = out.bind(None, {"data": nd.array(np.ones((2, 2), np.float32))})
    assert len(exe._plan) == 5
    plan, heads, _ = exe._opt_plan(False)
    assert len(plan) == 3, [n.name for n, _ in plan]
    r = exe.forward()[0].asnumpy()
    assert np.allclose(r, np.e), r


def test_cse_never_merges_stochastic(monkeypatch):
    """Two structurally identical Dropout nodes fold DISTINCT PRNG keys —
    the pass layer must keep both, and their masks must differ."""
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    data = sym.var("data")
    d1 = sym.Dropout(data, name="da", p=0.5)
    d2 = sym.Dropout(data, name="db", p=0.5)
    g = sym.Group([d1, d2])
    exe = g.bind(None, {"data": nd.array(np.ones((64, 64), np.float32))})
    mx.random.seed(0)
    o1, o2 = [o.asnumpy() for o in exe.forward(is_train=True)]
    ops = _plan_ops(exe, True)
    assert ops.count("Dropout") == 2
    assert not np.array_equal(o1, o2), \
        "stochastic nodes got merged: identical dropout masks"


def test_constant_fold_bakes_zero_input_subgraph(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    data = sym.var("data")
    const = sym.arange(0, 4, name="ar") + sym.ones((4,), name="on")
    out = data + const
    exe = out.bind(None, {"data": nd.array(np.zeros((2, 4), np.float32))})
    plan, _, const_env = exe._opt_plan(False)
    # arange, ones, and their add all fold; only the data add remains
    assert len(plan) == 1 and const_env
    r = exe.forward()[0].asnumpy()
    assert np.allclose(r, np.arange(4, dtype=np.float32) + 1.0)


def test_dead_aux_node_kept_in_train_mode(monkeypatch):
    """An aux-updating node must survive DCE in train plans even when no
    head consumes it: its moving-stat fold is a real side effect."""
    from mxnet_tpu import graph_passes

    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    data = sym.var("data")
    b = sym.BatchNorm(sym.Convolution(data, name="conv", kernel=(1, 1),
                                      num_filter=2), name="bn")
    plan, heads = graph_passes.capture(b)
    # simulate a dead BN by pointing heads elsewhere (conv output)
    g, _ = graph_passes.optimize(plan, ["conv_output"], is_train=True)
    assert any(n.op.name == "BatchNorm" for n, _ in g.entries)
    g, _ = graph_passes.optimize(plan, ["conv_output"], is_train=False)
    assert not any(n.op.name == "BatchNorm" for n, _ in g.entries)


# -- gate / cache-key / surfaces ---------------------------------------------

def test_gate_off_raw_plan_and_prepass_cache_key(monkeypatch):
    from mxnet_tpu import compile_cache, graph_passes

    monkeypatch.setenv("MXNET_GRAPH_PASSES", "0")
    out = _mlp()
    exe = out.simple_bind(data=(2, 8))
    plan, heads, const_env = exe._opt_plan(False)
    assert plan is exe._plan and heads is exe._head_names \
        and const_env is None
    assert exe.pass_stats() == {}
    # pre-pass-era logical key, byte for byte
    monkeypatch.setenv("MXNET_AOT_CACHE", "")
    assert graph_passes.pipeline_fingerprint() is None
    key_parts = ("executor_fwd", "abc", False)
    f = compile_cache.CachedFunction(lambda x: x, key_parts,
                                     name="executor_fwd")
    assert f._key == repr(key_parts)


def test_gate_on_key_carries_pipeline_fingerprint(monkeypatch):
    from mxnet_tpu import compile_cache, graph_passes

    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    monkeypatch.setenv("MXNET_AOT_CACHE", "")
    fp = graph_passes.pipeline_fingerprint()
    assert fp and "common_subexpr_merge:1" in fp
    f = compile_cache.CachedFunction(lambda x: x, ("executor_fwd", "abc"),
                                     name="executor_fwd")
    assert f._key == repr((("executor_fwd", "abc")
                           + (("graph_passes", fp),)))
    # explicit snapshot wins over the live gate
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "0")
    f2 = compile_cache.CachedFunction(lambda x: x, ("k",), passes_on=True)
    assert "graph_passes" in f2._key


def test_env_fingerprint_carries_pipeline(monkeypatch):
    from mxnet_tpu import compile_cache, graph_passes

    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    env = compile_cache._env_fingerprint()
    assert env["passes"] == graph_passes.pipeline_fingerprint()
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "0")
    assert compile_cache._env_fingerprint()["passes"] is None


def test_telemetry_summary_graph_keys(monkeypatch, tmp_path):
    from mxnet_tpu import telemetry
    from mxnet_tpu.telemetry import instrument as tin

    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    tin._reset_for_tests()
    try:
        s = telemetry.summary()
        assert s["graph_nodes_pre"] is None and s["pass_time_s"] is None
        exe = _conv_bn(dropout=True).simple_bind(data=(2, 3, 6, 6))
        exe.forward(is_train=False)
        s = telemetry.summary()
        assert s["graph_nodes_pre"] == 7
        assert s["graph_nodes_post"] == 6  # dropout left the eval plan
        assert s["pass_time_s"] >= 0
    finally:
        tin._reset_for_tests()


def test_debug_str_and_print_summary_report_counts(monkeypatch, capsys):
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    data = sym.var("data")
    out = sym.Dropout(sym.exp(data, name="e"), name="d", p=0.5)
    s = out.debug_str()
    assert "Total ops: 2 captured, 1 after graph passes (eval plan)" in s
    from mxnet_tpu import visualization

    visualization.print_summary(out, shape={"data": (2, 4)})
    printed = capsys.readouterr().out
    assert "2 captured, 1 after graph passes" in printed
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "0")
    assert "after graph passes" not in out.debug_str()
    assert "Total ops: 2 captured" in out.debug_str()


def test_monitor_sees_raw_plan(monkeypatch):
    """The monitor debug path reports every captured node even when the
    compiled path lowers the optimized plan."""
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    data = sym.var("data")
    out = sym.Dropout(sym.exp(data, name="e"), name="d", p=0.5)
    exe = out.bind(None, {"data": nd.array(np.ones((2, 2), np.float32))})
    seen = []
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    exe.forward(is_train=False)
    assert "d_output" in seen and "e_output" in seen


def test_predictor_pass_stats_and_reshape(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPH_PASSES", "1")
    from mxnet_tpu.predictor import Predictor

    s = _conv_bn(dropout=True)
    arg_shapes, _, aux_shapes = s.infer_shape(data=(2, 3, 6, 6),
                                              softmax_label=(2,))
    rng = np.random.RandomState(5)
    params = {}
    for n, sh in zip(s.list_arguments(), arg_shapes):
        if n not in ("data", "softmax_label"):
            params[n] = nd.array(rng.rand(*sh).astype(np.float32) * 0.1)
    for n, sh in zip(s.list_auxiliary_states(), aux_shapes):
        params["aux:" + n] = nd.array(
            np.ones(sh, np.float32) if n.endswith("_var")
            else np.zeros(sh, np.float32))
    pred = Predictor(s, params, {"data": (2, 3, 6, 6),
                                 "softmax_label": (2,)})
    assert pred.pass_stats() == {}  # nothing lowered yet
    pred.forward(data=rng.rand(2, 3, 6, 6).astype(np.float32),
                 softmax_label=np.zeros(2, np.float32))
    st = pred.pass_stats()["eval"]
    assert st["nodes_post"] == st["nodes_pre"] - 1  # dropout dropped
