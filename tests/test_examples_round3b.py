"""Round-3 example families, second wave (VERDICT round-2 missing item 1):
numpy-ops, module, python-howto, profiler, captcha, cnn_visualization,
deep-embedded-clustering, multivariate_time_series, rnn-time-major,
kaggle-ndsb1/2, memcost, cnn_chinese_text_classification, adversarial_vae.
Each test is the family's synthetic E2E run at reduced scale (nightly
tier)."""
import os
import sys

import numpy as np
import pytest

EX = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "examples"))


def _load(family, fname):
    """Load an example module by explicit path (several families reuse
    file names, so sys.path imports would collide)."""
    from mxnet_tpu.test_utils import load_module_by_path

    return load_module_by_path(
        os.path.join(EX, family, fname),
        "_ex_%s_%s" % (family.replace("-", "_"), fname[:-3]))


def test_numpy_ops_custom_softmax_learns():
    m = _load("numpy-ops", "custom_softmax.py")
    assert m.main(epochs=8) > 0.9


def test_numpy_ops_weighted_logistic_grads():
    m = _load("numpy-ops", "weighted_logistic_regression.py")
    # pos/neg grad scale 5.0/0.1 must actually skew the gradient magnitudes
    assert m.main(pos=5.0, neg=0.1) > 5.0


def test_module_mnist_mlp_checkpoint_roundtrip():
    m = _load("module", "mnist_mlp.py")
    assert m.main(epochs=6) > 0.9


def test_module_python_loss_hinge():
    m = _load("module", "python_loss.py")
    assert m.main(epochs=8) > 0.9


def test_python_howto_trio():
    mo = _load("python-howto", "multiple_outputs.py")
    feats, probs = mo.main()
    assert feats == (4, 128)
    dc = _load("python-howto", "debug_conv.py")
    assert dc.main().shape == (1, 1, 5, 5)
    mw = _load("python-howto", "monitor_weights.py")
    seen = mw.main(batches=4)
    assert any("weight" in n for n in seen)


def test_python_howto_data_iter_rec_pipeline():
    di = _load("python-howto", "data_iter.py")
    assert di.main() == 48


def test_profiler_traces():
    pm = _load("profiler", "profiler_matmul.py")
    assert pm.main(iter_num=8, begin=2, end=6, n=64) > 0
    pn = _load("profiler", "profiler_ndarray.py")
    assert pn.main() > 0


def test_captcha_multi_digit():
    m = _load("captcha", "captcha_recognition.py")
    per_digit, _per_captcha = m.main(epochs=5, n_train=1024, n_val=128)
    assert per_digit > 0.8


def test_cnn_visualization_gradcam():
    m = _load("cnn_visualization", "gradcam.py")
    cam, sal = m.main()
    assert cam.shape == (1, 16, 16)
    # the class-evidence peak must land in the bright quadrant
    iy, ix = np.unravel_index(cam[0].argmax(), cam[0].shape)
    assert iy >= 6 and ix >= 6, (iy, ix)
    assert sal.shape == (1, 3, 32, 32)


def test_dec_clusters_blobs():
    m = _load("deep-embedded-clustering", "dec.py")
    assert m.main(n=900, max_iter=8) > 0.6


def test_lstnet_beats_persistence():
    m = _load("multivariate_time_series", "lstnet.py")
    mse, naive = m.main(epochs=5)
    assert mse < naive * 0.25, (mse, naive)


def test_rnn_time_major_lm():
    m = _load("rnn-time-major", "rnn_cell_demo.py")
    ppl = m.main(epochs=3)
    assert ppl < 6.0, ppl  # uniform = vocab = 12


def test_ndsb1_plankton_shapes():
    m = _load("kaggle-ndsb1", "train_dsb.py")
    assert m.main(epochs=8, n_train=512, n_val=96) > 0.7


def test_ndsb2_cdf_crps():
    m = _load("kaggle-ndsb2", "Train.py")
    crps, base = m.main(epochs=8, n_train=256, n_val=64)
    assert crps < base, (crps, base)


def test_memcost_mirror_tradeoff():
    m = _load("memcost", "inception_memcost.py")
    (f0, _), (f1, _) = m.main()
    assert f1 > f0 * 1.1  # recompute engaged


def test_chinese_char_cnn():
    m = _load("cnn_chinese_text_classification", "text_cnn.py")
    assert m.main(epochs=6) > 0.85


def test_adversarial_vae_learned_similarity():
    m = _load("adversarial_vae", "vaegan.py")
    mse, base = m.main(epochs=4, n=384)
    assert mse < base, (mse, base)
