"""Frontend parity surfaces added in round 3: NDArray fluent methods,
module-level arithmetic helpers, positional random-sampler args,
mx.random re-exports, Monitor(monitor_all=), symbolic profiler events
(reference python/mxnet/{ndarray/ndarray.py,random.py,monitor.py}
fluent/ufunc/sampler sets)."""
import json
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_fluent_methods_match_functions():
    x = nd.array(np.linspace(0.5, 2.0, 12).reshape(3, 4).astype(np.float32))
    for name in ["log", "exp", "sqrt", "square", "sigmoid", "tanh", "relu",
                 "floor", "ceil", "round", "log1p", "expm1", "rsqrt"]:
        np.testing.assert_allclose(
            getattr(x, name)().asnumpy(),
            getattr(nd, name)(x).asnumpy(), rtol=1e-6,
            err_msg=name)


def test_fluent_with_args_and_chaining():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(x.sum(axis=1).asnumpy(), [6, 22, 38])
    assert x.topk(k=2).shape == (3, 2)
    chained = x.square().sum(axis=0).sqrt()
    np.testing.assert_allclose(chained.asnumpy(),
                               np.sqrt((np.arange(12).reshape(3, 4) ** 2)
                                       .sum(0)), rtol=1e-6)


def test_module_level_arith_helpers():
    a = nd.array([2.0, 4.0])
    np.testing.assert_allclose(nd.divide(a, 2.0).asnumpy(), [1, 2])
    np.testing.assert_allclose(nd.divide(8.0, a).asnumpy(), [4, 2])
    np.testing.assert_allclose(nd.power(a, 2).asnumpy(), [4, 16])
    np.testing.assert_allclose(nd.subtract(a, a).asnumpy(), [0, 0])
    np.testing.assert_allclose(nd.modulo(nd.array([5.0]), 3.0).asnumpy(), [2])
    # scalar-scalar returns a plain python number (reference _ufunc_helper)
    r = nd.multiply(3.0, 4.0)
    assert isinstance(r, float) and r == 12.0


def test_random_positional_args_and_reexports():
    mx.random.seed(11)
    u = mx.random.uniform(-2.0, -1.0, shape=(100,))
    arr = u.asnumpy()
    assert (arr >= -2).all() and (arr <= -1).all()
    n = nd.random.normal(10.0, 0.1, (200,))
    assert abs(float(n.asnumpy().mean()) - 10.0) < 0.1
    r = mx.random.randn(3, 4)
    assert r.shape == (3, 4)
    ri = mx.random.randint(5, 8, shape=(50,))
    vals = set(ri.asnumpy().astype(int).tolist())
    assert vals.issubset({5, 6, 7})
    # exponential takes the MEAN (scale), converted to the op's rate
    # (reference random.py exponential: lam = 1/scale)
    e = mx.random.exponential(4.0, shape=(4000,))
    assert abs(float(e.asnumpy().mean()) - 4.0) < 0.5
    with pytest.raises(TypeError):
        nd.random.uniform(0.0, 1.0, low=0.5)  # duplicate param


def test_monitor_all_reports_weights():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=4)
    exe = net.simple_bind(mx.cpu(), data=(2, 3), grad_req="null")
    exe.arg_dict["data"][:] = 1.0
    seen = []
    mon = mx.monitor.Monitor(1, pattern=".*", monitor_all=True)
    mon.install(exe)
    mon.tic()
    exe.forward(is_train=False)
    names = [n for _s, n, _v in mon.toc()]
    assert "fc1_weight" in names  # inputs reported only with monitor_all
    mon2 = mx.monitor.Monitor(1, pattern=".*")  # outputs only
    mon2.install(exe)
    mon2.tic()
    exe.forward(is_train=False)
    names2 = [n for _s, n, _v in mon2.toc()]
    assert "fc1_weight" not in names2
    assert any("output" in n for n in names2)


def test_profile_symbolic_executor_events():
    fname = os.path.join(tempfile.gettempdir(), "prof_sym_test.json")
    mx.profiler.set_config(profile_symbolic=True, filename=fname)
    mx.profiler.set_state("run")
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=4)
    exe = y.simple_bind(mx.cpu(), x=(2, 3))
    exe.arg_dict["x"][:] = 1.0
    exe.forward(is_train=True)
    exe.backward()
    mx.profiler.set_state("stop")
    mx.profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    names = [e.get("name") for e in events if isinstance(e, dict)]
    assert "Executor::Forward" in names
    assert "Executor::Backward" in names


def test_module_forward_duck_typed_batch():
    """Any object with .data is a batch (reference debug_conv.py idiom)."""

    class SimpleData:
        def __init__(self, data):
            self.data = data

    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=1)
    mod = mx.mod.Module(conv, label_names=())
    mod.bind(data_shapes=[("data", (1, 3, 5, 5))])
    mod.init_params()
    mod.forward(SimpleData([nd.ones((1, 3, 5, 5))]), is_train=False)
    assert mod.get_outputs()[0].shape == (1, 1, 5, 5)
