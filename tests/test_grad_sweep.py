"""Finite-difference gradient sweep over core operators — the reference's
central test discipline (tests/python/unittest/test_operator.py drives
check_numeric_gradient on nearly every op, test_utils.py:792). Small shapes
keep the O(n) central differences cheap."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_numeric_gradient


def _rand(*shape, scale=1.0, shift=0.0):
    rng = np.random.RandomState(hash(shape) % (2**31))
    return (rng.randn(*shape) * scale + shift).astype(np.float32)


UNARY_CASES = [
    ("sigmoid", lambda x: nd.sigmoid(x), _rand(3, 4)),
    ("tanh", lambda x: nd.tanh(x), _rand(3, 4)),
    ("relu_offset", lambda x: nd.relu(x), _rand(3, 4, shift=3.0)),  # away from kink
    ("exp", lambda x: nd.exp(x), _rand(3, 4, scale=0.5)),
    ("log", lambda x: nd.log(x), np.abs(_rand(3, 4)) + 1.0),
    ("sqrt", lambda x: nd.sqrt(x), np.abs(_rand(3, 4)) + 1.0),
    ("square", lambda x: nd.square(x), _rand(3, 4)),
    ("softmax", lambda x: nd.softmax(x, axis=-1), _rand(3, 5)),
    ("log_softmax", lambda x: nd.log_softmax(x, axis=-1), _rand(3, 5)),
    ("hard_sigmoid_interior", lambda x: nd.hard_sigmoid(x), _rand(3, 4, scale=0.3)),
    ("smooth_l1", lambda x: nd.smooth_l1(x, scalar=1.0), _rand(3, 4, scale=0.3)),
    ("LayerNorm-ish_mean", lambda x: nd.mean(x, axis=1), _rand(4, 5)),
    ("norm", lambda x: nd.norm(x), np.abs(_rand(3, 4)) + 0.5),
    ("transpose_sum", lambda x: nd.transpose(x) * nd.transpose(x), _rand(3, 4)),
    ("quadratic", lambda x: nd.quadratic(x, a=0.5, b=-1.0, c=2.0), _rand(3, 4)),
    ("erf", lambda x: nd.erf(x), _rand(3, 4, scale=0.5)),
    ("div_sqrt_dim", lambda x: nd.div_sqrt_dim(x), _rand(3, 8)),
    ("linalg_sumlogdiag", lambda x: nd.linalg_sumlogdiag(x),
     np.eye(4, dtype=np.float32) * 2 + np.abs(_rand(4, 4, scale=0.05))),
]


# eps ~ cbrt(fp32 machine epsilon): central differences on fp32 evaluations
# need a much larger step than the harness's fp64-era default
EPS = 1e-2
RTOL = 5e-2


@pytest.mark.parametrize("name,fn,x", UNARY_CASES, ids=[c[0] for c in UNARY_CASES])
def test_unary_grads(name, fn, x):
    check_numeric_gradient(fn, [x.copy()], eps=EPS, rtol=RTOL)


BINARY_CASES = [
    ("add", lambda a, b: a + b),
    ("mul", lambda a, b: a * b),
    ("div", lambda a, b: a / (b * b + 1.0)),
    ("dot", lambda a, b: nd.dot(a, b)),
    ("broadcast_mul", lambda a, b: a * b.reshape((1, -1))[:, :4]),
    ("maximum_apart", lambda a, b: nd.maximum(a, b + 10.0)),
]


@pytest.mark.parametrize("name,fn", BINARY_CASES, ids=[c[0] for c in BINARY_CASES])
def test_binary_grads(name, fn):
    a = _rand(4, 4)
    b = _rand(4, 4, shift=0.5)
    check_numeric_gradient(fn, [a, b], eps=EPS, rtol=RTOL)


def test_fc_grads():
    x = _rand(3, 6)
    w = _rand(4, 6, scale=0.5)
    b = _rand(4, scale=0.1)
    check_numeric_gradient(
        lambda x_, w_, b_: nd.FullyConnected(x_, w_, b_, num_hidden=4),
        [x, w, b], eps=EPS, rtol=RTOL)


def test_conv_grads():
    x = _rand(1, 2, 5, 5, scale=0.5)
    w = _rand(3, 2, 3, 3, scale=0.3)
    check_numeric_gradient(
        lambda x_, w_: nd.Convolution(x_, w_, kernel=(3, 3), num_filter=3,
                                      pad=(1, 1), no_bias=True),
        [x, w], eps=EPS, rtol=RTOL)


def test_pooling_grads():
    x = _rand(1, 2, 6, 6)
    check_numeric_gradient(
        lambda x_: nd.Pooling(x_, kernel=(2, 2), stride=(2, 2), pool_type="avg"),
        [x], eps=EPS, rtol=RTOL)


def test_batchnorm_inference_grads():
    x = _rand(3, 4, scale=0.5)
    g = np.abs(_rand(4, scale=0.2)) + 1.0
    b = _rand(4, scale=0.2)
    mm = _rand(4, scale=0.1)
    mv = np.abs(_rand(4, scale=0.1)) + 1.0

    def f(x_, g_, b_):
        return nd.BatchNorm(x_.reshape((3, 4, 1, 1)), g_, b_, nd.array(mm), nd.array(mv),
                            fix_gamma=False, use_global_stats=True)

    check_numeric_gradient(f, [x, g, b], eps=EPS, rtol=RTOL)


def test_embedding_take_grads():
    w = _rand(5, 4, scale=0.5)
    idx = np.array([0, 2, 4], np.float32)
    check_numeric_gradient(
        lambda w_: nd.Embedding(nd.array(idx), w_, input_dim=5, output_dim=4),
        [w], eps=EPS, rtol=RTOL)


def test_deformable_conv_grads():
    """The north-star op: gradients through data, offsets, and weights.

    Bilinear sampling is only piecewise smooth: kinks sit on integer sample
    coordinates and at the live-region border. pad=0 keeps all taps interior
    and the +0.3 offset keeps samples a safe margin from integer crossings,
    so central differences see the smooth region autograd differentiates."""
    x = _rand(1, 2, 7, 7, scale=0.5)
    off = np.full((1, 18, 5, 5), 0.3, np.float32) + _rand(1, 18, 5, 5, scale=0.05)
    w = _rand(2, 2, 3, 3, scale=0.3)
    check_numeric_gradient(
        lambda x_, o_, w_: nd.contrib.DeformableConvolution(
            x_, o_, w_, kernel=(3, 3), num_filter=2, no_bias=True),
        [x, off, w], eps=5e-3, rtol=8e-2, atol=1e-2)
