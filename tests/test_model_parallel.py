"""Model parallelism over the mesh (reference test_model_parallel.py +
example/model-parallel; here sharding annotations replace group2ctx, see
examples/model_parallel/lstm_mp.py)."""
import os
import subprocess
import sys

import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_model_parallel_lstm_example():
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    res = subprocess.run(
        [sys.executable, "lstm_mp.py", "--check-replicated",
         "--steps", "200", "--lr", "1.0"],
        cwd=os.path.join(REPO, "examples", "model_parallel"), env=env,
        capture_output=True, text=True, timeout=1200)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "MODEL PARALLEL LSTM OK" in res.stdout
    assert "sharded-vs-replicated loss match" in res.stdout
    assert "mp=8" in res.stdout


def test_sharded_matmul_matches_replicated():
    """Minimal group2ctx analog: the same FC computed with mp-sharded weights
    equals the replicated computation (reference test_model_parallel.py checks
    cross-device exec returns identical numbers)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxnet_tpu import parallel

    mesh = parallel.make_mesh({"mp": len(jax.devices())})
    rng = np.random.RandomState(0)
    x = rng.rand(4, 16).astype(np.float32)
    w = rng.rand(16, 32).astype(np.float32)

    w_sh = jax.device_put(w, NamedSharding(mesh, P(None, "mp")))
    y_sh = jax.jit(lambda a, b: a @ b)(jnp.asarray(x), w_sh)
    np.testing.assert_allclose(np.asarray(y_sh), x @ w, rtol=2e-5)


def test_pipeline_mlp_example():
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    res = subprocess.run(
        [sys.executable, "pipeline_mlp.py", "--steps", "120"],
        cwd=os.path.join(REPO, "examples", "model_parallel"), env=env,
        capture_output=True, text=True, timeout=900)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "PIPELINE MLP OK" in res.stdout
    assert "pp=8" in res.stdout
