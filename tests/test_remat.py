"""Block.set_remat — memory-saving recomputation (reference
MXNET_BACKWARD_DO_MIRROR, docs/faq/env_var.md:93 + gradient-mirror path in
src/executor/graph_executor.cc InitFullGraph; here jax.checkpoint over the
block's subgraph)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import loss as loss_mod
from mxnet_tpu.gluon.functional import make_train_step


def _build(remat):
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        body = nn.HybridSequential()
        with body.name_scope():
            body.add(nn.Dense(32, activation="tanh"),
                     nn.BatchNorm(),
                     nn.Dense(32, activation="relu"))
        net.add(body)
        net.add(nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((2, 16)))
    if remat:
        net[1].set_remat(True)
    return net


def test_remat_numerics_match():
    """Same loss trajectory and BN-stat updates with and without remat."""
    import jax

    x = np.random.RandomState(0).rand(16, 16).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, (16,)).astype(np.float32)
    results = []
    for remat in (False, True):
        net = _build(remat)
        step, state, _ = make_train_step(
            net, loss_mod.SoftmaxCrossEntropyLoss(), learning_rate=0.1)
        jstep = jax.jit(step)
        s = state
        losses = []
        for i in range(4):
            s, loss = jstep(s, x, y, jax.random.PRNGKey(i))
            losses.append(float(loss))
        results.append((losses, [np.asarray(v)
                                 for v in jax.tree_util.tree_leaves(s)]))
    (l0, s0), (l1, s1) = results
    np.testing.assert_allclose(l0, l1, rtol=1e-5)
    for a, b in zip(s0, s1):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_remat_inference_unchanged():
    net = _build(True)
    x = mx.nd.array(np.random.RandomState(2).rand(3, 16).astype(np.float32))
    a = net(x).asnumpy()
    net[1].set_remat(False)
    b = net(x).asnumpy()
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_set_remat_returns_self_and_flags():
    net = _build(False)
    assert net[1].set_remat(True) is net[1]
    assert net[1]._remat is True
    net[1].set_remat(False)
    assert net[1]._remat is False
