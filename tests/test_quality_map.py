"""Detection-quality regression gate (VERDICT round-2 item 5).

Runs the full Deformable R-FCN synthetic-VOC recipe
(examples/quality/eval_rfcn_map.py) at the calibrated nightly config and
fails if mAP drops below the floor.  Everything is seeded — train stream,
init, eval stream (n=500, which is what makes the number meaningful: the
round-2 "3000 vs 6000 step regression" was n=48 eval noise, see
QUALITY.md) — so on one platform the score is reproducible and a drop
means a real detection-pipeline change, not sampling luck.

Calibration (this config, CPU, round 4): seeds 0/1/2 → mAP 0.0468 /
0.0440 / 0.0591.  Floor 0.035 = worst seed − ~20% margin (VERDICT round-3
item 5: with n=500 the old 2× slack was unjustified) — still far above a
broken pipeline (~0.002 at 120 steps, ~0 untrained).
"""
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SCRIPT = os.path.join(REPO, "examples", "quality", "eval_rfcn_map.py")


def test_rfcn_synthetic_map_floor():
    res = subprocess.run(
        [sys.executable, SCRIPT, "--steps", "1200", "--eval-images", "500",
         "--live-bn", "--map-floor", "0.035"],
        capture_output=True, text=True, timeout=5400)
    tail = "\n".join(res.stdout.splitlines()[-5:]) + res.stderr[-2000:]
    assert res.returncode == 0, tail
    assert "FINAL rfcn" in res.stdout, tail
