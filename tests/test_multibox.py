"""MultiBox (SSD) + generic box op tests vs numpy oracles (reference
tests/python/unittest/test_operator.py multibox cases + test_bounding_box)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def np_multibox_prior(H, W, sizes, ratios, clip, steps, offsets):
    step_y = 1.0 / H if steps[0] <= 0 else steps[0]
    step_x = 1.0 / W if steps[1] <= 0 else steps[1]
    out = []
    for r in range(H):
        cy = (r + offsets[0]) * step_y
        for c in range(W):
            cx = (c + offsets[1]) * step_x
            for s in sizes:
                w, h = s * H / W / 2, s / 2
                out.append([cx - w, cy - h, cx + w, cy + h])
            for rt in ratios[1:]:
                sq = np.sqrt(rt)
                w, h = sizes[0] * H / W * sq / 2, sizes[0] / sq / 2
                out.append([cx - w, cy - h, cx + w, cy + h])
    out = np.array(out, np.float32)[None]
    return np.clip(out, 0, 1) if clip else out


def np_iou(a, b):
    tl = np.maximum(a[:2], b[:2])
    br = np.minimum(a[2:], b[2:])
    wh = np.maximum(br - tl, 0)
    inter = wh[0] * wh[1]
    u = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return 0.0 if u <= 0 else inter / u


def np_multibox_target(anchors, labels, cls_preds, overlap=0.5, ignore=-1.0,
                       neg_ratio=-1.0, neg_thresh=0.5, variances=(0.1, 0.1, 0.2, 0.2)):
    B, L, _ = labels.shape
    A = anchors.shape[0]
    loc_t = np.zeros((B, A * 4), np.float32)
    loc_m = np.zeros((B, A * 4), np.float32)
    cls_t = np.full((B, A), ignore, np.float32)
    for b in range(B):
        lab = labels[b]
        nvalid = 0
        for i in range(L):
            if lab[i, 0] == -1:
                break
            nvalid += 1
        if nvalid == 0:
            continue
        ious = np.array([[np_iou(anchors[j], lab[k, 1:5]) for k in range(nvalid)] for j in range(A)])
        gt_flags = np.zeros(nvalid, bool)
        flags = np.full(A, -1, np.int8)
        match = np.full(A, -1, np.int32)
        match_iou = np.full(A, -1.0, np.float32)
        num_pos = 0
        while not gt_flags.all():
            best = (-1, -1, 1e-6)
            for j in range(A):
                if flags[j] == 1:
                    continue
                for k in range(nvalid):
                    if gt_flags[k]:
                        continue
                    if ious[j, k] > best[2]:
                        best = (j, k, ious[j, k])
            if best[0] == -1:
                break
            j, k, v = best
            match[j], match_iou[j] = k, v
            gt_flags[k] = True
            flags[j] = 1
            num_pos += 1
        if overlap > 0:
            for j in range(A):
                if flags[j] == 1:
                    continue
                k = int(np.argmax(ious[j])) if nvalid else -1
                if k >= 0:
                    match[j], match_iou[j] = k, ious[j, k]
                    if ious[j, k] > overlap:
                        flags[j] = 1
                        num_pos += 1
        if neg_ratio > 0:
            num_neg = min(int(num_pos * neg_ratio), A - num_pos)
            if num_neg > 0:
                cand = []
                for j in range(A):
                    if flags[j] != 1 and match_iou[j] < neg_thresh:
                        z = cls_preds[b, :, j]
                        p = np.exp(z - z.max())
                        cand.append((-(p[0] / p.sum()), j))
                cand.sort(key=lambda t: t[0], reverse=True)
                for i in range(num_neg):
                    flags[cand[i][1]] = 0
        else:
            flags[flags != 1] = 0
        vx, vy, vw, vh = variances
        for j in range(A):
            if flags[j] == 1:
                g = lab[match[j]]
                cls_t[b, j] = g[0] + 1
                al, at, ar, ab_ = anchors[j]
                aw, ah = ar - al, ab_ - at
                ax, ay = (al + ar) / 2, (at + ab_) / 2
                gw, gh = g[3] - g[1], g[4] - g[2]
                gx, gy = (g[1] + g[3]) / 2, (g[2] + g[4]) / 2
                loc_t[b, j * 4:j * 4 + 4] = [(gx - ax) / aw / vx, (gy - ay) / ah / vy,
                                             np.log(gw / aw) / vw, np.log(gh / ah) / vh]
                loc_m[b, j * 4:j * 4 + 4] = 1
            elif flags[j] == 0:
                cls_t[b, j] = 0
    return loc_t, loc_m, cls_t


def test_multibox_prior():
    data = np.zeros((1, 3, 5, 6), np.float32)
    for sizes, ratios, clip in [((0.5,), (1.0,), False), ((0.3, 0.6), (1.0, 2.0, 0.5), True)]:
        out = nd.contrib.MultiBoxPrior(nd.array(data), sizes=sizes, ratios=ratios, clip=clip).asnumpy()
        exp = np_multibox_prior(5, 6, sizes, ratios, clip, (-1, -1), (0.5, 0.5))
        assert_almost_equal(out, exp, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("neg_ratio", [-1.0, 3.0])
def test_multibox_target(neg_ratio):
    np.random.seed(5)
    A, B, L, C = 20, 2, 4, 3
    # anchors in [0,1] corner format
    ctr = np.random.rand(A, 2)
    wh = 0.1 + 0.2 * np.random.rand(A, 2)
    anchors = np.concatenate([ctr - wh / 2, ctr + wh / 2], axis=1).astype(np.float32)
    labels = -np.ones((B, L, 5), np.float32)
    labels[0, 0] = [1, 0.1, 0.1, 0.4, 0.4]
    labels[0, 1] = [0, 0.5, 0.5, 0.9, 0.8]
    labels[1, 0] = [2, 0.2, 0.3, 0.5, 0.6]
    cls_preds = np.random.randn(B, C, A).astype(np.float32)
    lt, lm, ct = (
        x.asnumpy()
        for x in nd.contrib.MultiBoxTarget(
            nd.array(anchors[None]), nd.array(labels), nd.array(cls_preds),
            overlap_threshold=0.5, negative_mining_ratio=neg_ratio, negative_mining_thresh=0.5,
        )
    )
    elt, elm, ect = np_multibox_target(anchors, labels, cls_preds, 0.5, -1.0, neg_ratio, 0.5)
    assert_almost_equal(lm, elm, rtol=1e-5, atol=1e-6)
    assert_almost_equal(ct, ect, rtol=1e-5, atol=1e-6)
    assert_almost_equal(lt, elt, rtol=1e-4, atol=1e-5)


def test_multibox_detection():
    np.random.seed(11)
    B, C, A = 2, 3, 12
    cls_prob = np.random.rand(B, C, A).astype(np.float32)
    cls_prob /= cls_prob.sum(axis=1, keepdims=True)
    loc_pred = 0.1 * np.random.randn(B, A * 4).astype(np.float32)
    ctr = np.random.rand(A, 2)
    wh = 0.1 + 0.2 * np.random.rand(A, 2)
    anchors = np.concatenate([ctr - wh / 2, ctr + wh / 2], axis=1).astype(np.float32)[None]
    out = nd.contrib.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors),
        threshold=0.2, nms_threshold=0.4, nms_topk=8,
    ).asnumpy()
    assert out.shape == (B, A, 6)
    for b in range(B):
        rows = out[b]
        valid = rows[rows[:, 0] >= 0]
        # sorted by score desc among surviving detections
        assert (np.diff(valid[:, 1]) <= 1e-6).all()
        # every surviving pair of same class has IoU <= nms_threshold
        for i in range(len(valid)):
            for j in range(i + 1, len(valid)):
                if valid[i, 0] == valid[j, 0]:
                    assert np_iou(valid[i, 2:], valid[j, 2:]) <= 0.4 + 1e-6
        # scores >= threshold for valid
        assert (valid[:, 1] >= 0.2 - 1e-6).all()


def test_box_iou():
    np.random.seed(2)
    a = np.random.rand(5, 4).astype(np.float32)
    a[:, 2:] += a[:, :2]
    b = np.random.rand(7, 4).astype(np.float32)
    b[:, 2:] += b[:, :2]
    out = nd.contrib.box_iou(nd.array(a), nd.array(b)).asnumpy()
    exp = np.array([[np_iou(x, y) for y in b] for x in a])
    assert_almost_equal(out, exp, rtol=1e-5, atol=1e-6)


def test_box_nms():
    data = np.array(
        [
            [0, 0.9, 0.1, 0.1, 0.5, 0.5],
            [1, 0.8, 0.1, 0.1, 0.5, 0.5],  # overlaps first, different class
            [0, 0.7, 0.12, 0.12, 0.52, 0.52],  # overlaps first, same class → suppressed
            [0, 0.6, 0.6, 0.6, 0.9, 0.9],
            [0, 0.01, 0.0, 0.0, 0.1, 0.1],  # below valid_thresh
        ],
        np.float32,
    )
    out = nd.contrib.box_nms(
        nd.array(data[None]), overlap_thresh=0.5, valid_thresh=0.05, id_index=0,
        coord_start=2, score_index=1,
    ).asnumpy()[0]
    # rows sorted by score: row0 kept, row1 kept (other class), row2 -1, row3 kept, row4 -1
    assert out[0, 1] == pytest.approx(0.9)
    assert out[1, 1] == pytest.approx(0.8)
    assert (out[2] == -1).all()
    assert out[3, 1] == pytest.approx(0.6)
    assert (out[4] == -1).all()
    # force_suppress kills cross-class overlap too
    out2 = nd.contrib.box_nms(
        nd.array(data[None]), overlap_thresh=0.5, valid_thresh=0.05, id_index=0,
        coord_start=2, score_index=1, force_suppress=True,
    ).asnumpy()[0]
    assert (out2[1] == -1).all()


def test_bipartite_matching():
    score = np.array([[0.9, 0.2], [0.8, 0.7], [0.1, 0.05]], np.float32)
    rows, cols = nd.contrib.bipartite_matching(nd.array(score[None]), threshold=0.1)
    rows, cols = rows.asnumpy()[0], cols.asnumpy()[0]
    # greedy: (0,0)=0.9 first, then (1,1)=0.7; row2 below threshold
    assert rows.tolist() == [0.0, 1.0, -1.0]
    assert cols.tolist() == [0.0, 1.0]
