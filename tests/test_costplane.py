"""Compile plane — per-executable XLA cost/memory ledger (ISSUE 13).

Covers: the zero-overhead off path (no rows, plain jits, untouched
AOT-cache keys, byte-identical jaxprs), row recording at every compile
site (executor forward, fused train step, CachedFunction), degradation
when ``cost_analysis()``/``memory_analysis()`` return None / raise / drop
keys, the declared-vs-measured drift cross-check, the persistent ledger +
``bench_compare --gate-cost``, warmup report columns, the Engine stats
block, bench-summary keys, and autotune trial cost features.
"""
import json
import os
import re

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.telemetry import costplane


@pytest.fixture(autouse=True)
def _clean_costplane(monkeypatch):
    monkeypatch.delenv("MXNET_COSTPLANE", raising=False)
    monkeypatch.delenv("MXNET_COST_LEDGER", raising=False)
    costplane._reset_for_tests()
    yield
    costplane._reset_for_tests()


def _mlp():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
    return mx.sym.FullyConnected(mx.sym.Activation(fc1, act_type="relu"),
                                 name="fc2", num_hidden=4)


def _norm_jaxpr(fn, args):
    import jax

    # custom_vjp jaxpr params embed transient object addresses that differ
    # between ANY two traces; normalize them so only structure compares
    return re.sub(r"0x[0-9a-f]+", "0xADDR", str(jax.make_jaxpr(fn)(*args)))


# -- off path -----------------------------------------------------------------
def test_off_path_no_rows_plain_jit(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_COST_LEDGER", str(tmp_path / "ledger.jsonl"))
    exe = _mlp().simple_bind(data=(2, 8), grad_req="null")
    exe.forward(is_train=False)
    assert costplane.row_count() == 0
    assert costplane.rows() == []
    import jax

    assert isinstance(exe._fwd_cache[False], type(jax.jit(lambda x: x)))
    assert not (tmp_path / "ledger.jsonl").exists()


def test_off_path_jaxpr_byte_identical(monkeypatch):
    """Gate off vs on lower the SAME jaxpr — named_scope is pure trace-time
    metadata, so the unset path is byte-identical to a pre-costplane
    build (the scope wrapper itself is only entered under the gate)."""
    exe = _mlp().simple_bind(data=(2, 8), grad_req="null")
    args = exe._aot_example_args()
    off = _norm_jaxpr(exe._graph_fn(False), args)
    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    on = _norm_jaxpr(exe._graph_fn(False), args)
    assert off == on


def test_aot_cache_key_unchanged_by_gate(tmp_path, monkeypatch):
    """The gate must not move AOT-cache identity: the same logical key and
    entry path come out whether or not the plane is on."""
    from mxnet_tpu import compile_cache

    monkeypatch.setenv("MXNET_AOT_CACHE", str(tmp_path / "aot"))
    import jax

    fn = jax.jit(lambda x: x + 1)
    keys, paths = [], []
    for gate in ("0", "1"):
        monkeypatch.setenv("MXNET_COSTPLANE", gate)
        cf = compile_cache.CachedFunction(fn, ("k", 1), name="t")
        sig = cf._sig((np.zeros((2, 2), np.float32),))
        keys.append(cf._key)
        paths.append(cf._path(sig))
    assert keys[0] == keys[1]
    assert paths[0] == paths[1]


# -- recording ----------------------------------------------------------------
def test_executor_records_one_row_per_signature(monkeypatch):
    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    exe = _mlp().simple_bind(data=(2, 8), grad_req="null")
    exe.forward(is_train=False)
    exe.forward(is_train=False)  # steady state: no new row
    assert costplane.row_count() == 1
    row = costplane.rows()[0]
    assert row["site"] == "executor_fwd"
    assert row["kind"] == "compile"
    # CPU XLA reports both surfaces (probed in-container); a row carries
    # real numbers and no partial markers here
    assert isinstance(row["flops"], int) and row["flops"] > 0
    assert isinstance(row["bytes_accessed"], int) and row["bytes_accessed"] > 0
    assert isinstance(row["peak_bytes"], int) and row["peak_bytes"] > 0
    assert row["partial"] == []
    assert row["backend"] == "cpu"
    assert row["compile_s"] >= 0
    assert set(row["fingerprints"]) == {"passes", "numerics", "autotune"}
    # second mode = second program = second row
    exe2 = exe.reshape(data=(4, 8))
    exe2.forward(is_train=False)
    assert costplane.row_count() == 2


def test_fused_step_records_row(monkeypatch, tmp_path):
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu.io import DataBatch

    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    sym = mx.sym.SoftmaxOutput(_mlp(), name="softmax")
    mod = mod_mod.Module(sym)
    mod.bind(data_shapes=[("data", (6, 8))],
             label_shapes=[("softmax_label", (6,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    for _ in range(3):
        b = DataBatch(data=[nd.array(rng.randn(6, 8).astype(np.float32))],
                      label=[nd.array(rng.randint(0, 4, (6,))
                                      .astype(np.float32))])
        mod.forward_backward(b)
        mod.update()
    rows = [r for r in costplane.rows() if r["site"] == "fused_step"]
    assert len(rows) == 1  # one signature, one row across 3 steps
    assert rows[0]["flops"] > 0


def test_cached_function_records_compile_then_restore_row(tmp_path,
                                                          monkeypatch):
    """CachedFunction: a fresh XLA compile records a ``compile`` row; a
    disk restore built nothing but still publishes a ``restore`` row
    (compile_s 0.0, the entry's STORED cost fingerprint — ISSUE 20: a
    warm pod restart must give the cross-rank ledger diff something to
    diff).  ``load_ledger`` keeps skipping restore rows — the persisted
    ledger remains a record of what was *built*."""
    from mxnet_tpu import compile_cache

    monkeypatch.setenv("MXNET_AOT_CACHE", str(tmp_path / "aot"))
    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    import jax

    fn = jax.jit(lambda x: x * 2.0)
    x = np.ones((3, 3), np.float32)
    cf = compile_cache.CachedFunction(fn, ("cp", 1), name="cp_t")
    cf(x)
    assert costplane.row_count() == 1
    compiled = costplane.rows()[0]
    assert compiled["site"] == "cp_t" and compiled["kind"] == "compile"
    # second instance, same key: restores from disk — a restore row, not
    # a second compile row
    cf2 = compile_cache.CachedFunction(fn, ("cp", 1), name="cp_t")
    info = cf2.prepare(x)
    assert info["source"] == "disk"
    assert costplane.row_count() == 2
    restored = costplane.rows()[1]
    assert restored["kind"] == "restore"
    assert restored["key"] == compiled["key"]
    assert restored["compile_s"] == 0.0
    assert restored["flops"] == compiled["flops"]
    assert restored["bytes_accessed"] == compiled["bytes_accessed"]
    assert [r["kind"] for r in costplane.rows()].count("compile") == 1


def test_ledger_roundtrip_last_wins(tmp_path, monkeypatch):
    path = tmp_path / "ledger.jsonl"
    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    monkeypatch.setenv("MXNET_COST_LEDGER", str(path))
    sym = _mlp()
    for _ in range(2):  # two binds, same program: same ledger key twice
        exe = sym.simple_bind(data=(2, 8), grad_req="null")
        exe.forward(is_train=False)
    assert costplane.row_count() == 2
    assert len(path.read_text().strip().splitlines()) == 2
    led = costplane.load_ledger(str(path))
    assert len(led) == 1  # keyed by fingerprint, last row wins
    (row,) = led.values()
    assert row["flops"] > 0


def test_ledger_reader_skips_garbage(tmp_path):
    path = tmp_path / "ledger.jsonl"
    good = {"kind": "compile", "key": "a-1", "flops": 10}
    path.write_text("not json\n" + json.dumps(good) + "\n"
                    + json.dumps({"kind": "other"}) + "\n")
    assert list(costplane.load_ledger(str(path))) == ["a-1"]


# -- degradation --------------------------------------------------------------
class _Stub:
    def __init__(self, cost, memory):
        self._cost, self._memory = cost, memory

    def cost_analysis(self):
        if isinstance(self._cost, Exception):
            raise self._cost
        return self._cost

    def memory_analysis(self):
        if isinstance(self._memory, Exception):
            raise self._memory
        return self._memory


class _Mem:
    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


@pytest.mark.parametrize("cost,memory,partial", [
    (None, RuntimeError("no mem"), ["cost", "memory"]),
    (RuntimeError("boom"), RuntimeError("boom"), ["cost", "memory"]),
    ([], None, ["cost", "memory"]),          # empty list + None-attrs object
    ({"unrelated": 1.0}, _Mem(), ["cost", "memory"]),   # missing keys/attrs
    ({"flops": 8.0}, _Mem(temp_size_in_bytes=4), []),   # partial-but-usable
    ({"flops": float("nan"), "bytes accessed": -3}, _Mem(), ["cost",
                                                             "memory"]),
])
def test_extract_degrades_never_raises(cost, memory, partial):
    feat, got_partial = costplane.extract(_Stub(cost, memory))
    assert got_partial == partial
    for v in feat.values():
        assert v is None or isinstance(v, int)


def test_partial_row_still_recorded(monkeypatch):
    """A backend reporting nothing yields a PARTIAL row, never a crash and
    never a dropped row — the degradation acceptance."""
    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    row = costplane.record_compile("site_x", ("k",), "sig",
                                   _Stub(RuntimeError("unimplemented"),
                                         RuntimeError("unimplemented")),
                                   0.1)
    assert row is not None
    assert row["flops"] is None and row["peak_bytes"] is None
    assert sorted(row["partial"]) == ["cost", "memory"]
    assert costplane.row_count() == 1
    assert costplane.status()["partial"] == {"cost": 1, "memory": 1}
    assert costplane.totals() == {"flops": None, "peak_bytes": None,
                                  "rows": 1}


def test_record_compile_off_gate_noop():
    assert costplane.record_compile("s", ("k",), "sig",
                                    _Stub(None, None), 0.0) is None
    assert costplane.row_count() == 0


# -- declared-vs-measured cross-check ----------------------------------------
def test_crosscheck_flags_inflated_declarations():
    feat = {"flops": 1000, "bytes_accessed": 5000}
    honest = {"k1": {"calls": 2, "flops": 100, "bytes": 400}}
    assert costplane.crosscheck(feat, honest) == []
    inflated = {"k1": {"calls": 2, "flops": 100, "bytes": 400},
                "k2": {"calls": 1, "flops": 5000, "bytes": 10}}
    assert costplane.crosscheck(feat, inflated) == ["k2"]
    # backend measured nothing on an axis -> that axis never flags
    assert costplane.crosscheck({"flops": None, "bytes_accessed": None},
                                inflated) == []


def test_drift_counted_in_row_and_status(monkeypatch):
    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    monkeypatch.setattr(
        costplane, "kernel_delta",
        lambda snap: {"fake_kernel": {"calls": 1, "flops": 10**15,
                                      "bytes": 1}})
    row = costplane.record_compile(
        "s", ("k",), "sig",
        _Stub({"flops": 100.0, "bytes accessed": 100.0}, _Mem()), 0.0,
        tc0={})
    assert row["drift"] == ["fake_kernel"]
    assert costplane.status()["drift"] == {"fake_kernel": 1}


def test_overlapping_trace_brackets_degrade_to_no_attribution(monkeypatch):
    """Concurrent lowers (the warmup thread pool) share one process-global
    Pallas registry: overlapping brackets cannot attribute kernel calls to
    their own executable, so both degrade to an empty delta — no declared
    row, no false drift — instead of cross-attributing."""
    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    fake = {"k": {"flops_sum": 100, "bytes_sum": 10, "calls": 1,
                  "per_shape": {1: None}, "shape": None}}

    def fake_snapshot():
        return {k: v["calls"] for k, v in fake.items()}

    monkeypatch.setattr(costplane, "kernel_snapshot", fake_snapshot)
    # _delta_since reads the REAL process-global registry — fake it too,
    # or kernels traced by earlier test files (autotune's dconv trials)
    # leak into this bracket's delta and the test becomes order-dependent
    from mxnet_tpu.ops import pallas_kernels

    monkeypatch.setattr(
        pallas_kernels, "traced_costs",
        lambda: {k: {"flops": v["flops_sum"], "bytes_accessed":
                     v["bytes_sum"], "calls": v["calls"]}
                 for k, v in fake.items()})
    a = costplane.open_trace_bracket()
    assert not a.dirty
    b = costplane.open_trace_bracket()  # overlaps a -> both dirty
    assert a.dirty and b.dirty
    costplane.close_trace_bracket(a)
    costplane.close_trace_bracket(b)
    assert costplane.kernel_delta(a) == {}
    assert costplane.kernel_delta(b) == {}
    # a clean, non-overlapping bracket still attributes
    c = costplane.open_trace_bracket()
    assert not c.dirty and c.snap == {"k": 1}
    costplane.close_trace_bracket(c)
    assert costplane.kernel_delta(c) == {}  # nothing new traced


def test_instrument_jit_concurrent_first_call_single_row(monkeypatch):
    """Two threads racing the same new signature through an instrumented
    jit must produce ONE compile and ONE ledger row."""
    import threading

    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    fn = costplane.instrument_jit(jax.jit(lambda x: jnp.tanh(x).sum()),
                                  "race_site", ("race",))
    x = np.ones((4, 4), np.float32)
    barrier = threading.Barrier(2)
    outs = []

    def call():
        barrier.wait()
        outs.append(float(fn(x)))

    ts = [threading.Thread(target=call) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(outs) == 2 and outs[0] == outs[1]
    assert costplane.row_count() == 1
    assert fn._cache_size() == 1


# -- surfaces -----------------------------------------------------------------
def test_engine_stats_and_warmup_columns(monkeypatch):
    from mxnet_tpu import serving
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    eng = serving.Engine(sym, params, {"data": (8,)}, start=False,
                         name="cp_eng")
    try:
        assert eng.stats()["costplane"] is None  # gate off
        monkeypatch.setenv("MXNET_COSTPLANE", "1")
        report = eng.warmup()
        fresh = [r for r in report if r["fresh"]]
        assert fresh and all(r["xla_flops"] is not None
                             and r["xla_peak_bytes"] is not None
                             for r in fresh)
        st = eng.stats()
        assert st["costplane"]["rows"] >= len(fresh)
        assert st["costplane"]["by_site"]["executor_fwd"] >= len(fresh)
        assert st["warmup"]["xla_flops"] == sum(r["xla_flops"]
                                                for r in fresh)
        assert st["warmup"]["xla_peak_bytes"] == max(r["xla_peak_bytes"]
                                                     for r in fresh)
        # re-warm: already live, no new rows, columns None
        report2 = eng.warmup()
        assert all(not r["fresh"] and r["xla_flops"] is None
                   for r in report2)
    finally:
        eng.close()


def test_warmup_columns_none_when_off():
    from mxnet_tpu import serving
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    eng = serving.Engine(sym, params, {"data": (8,)}, start=False)
    try:
        report = eng.warmup()
        assert all(r["xla_flops"] is None and r["xla_peak_bytes"] is None
                   for r in report)
        assert eng.stats()["warmup"]["xla_flops"] is None
    finally:
        eng.close()


def test_summary_keys(monkeypatch, tmp_path):
    from mxnet_tpu import telemetry
    from mxnet_tpu.telemetry import instrument as tin

    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    tin._reset_for_tests()
    try:
        s = telemetry.summary()
        assert s["xla_flops"] is None and s["xla_peak_bytes"] is None
        monkeypatch.setenv("MXNET_COSTPLANE", "1")
        exe = _mlp().simple_bind(data=(2, 8), grad_req="null")
        exe.forward(is_train=False)
        s = telemetry.summary()
        assert isinstance(s["xla_flops"], int) and s["xla_flops"] > 0
        assert isinstance(s["xla_peak_bytes"], int) and s["xla_peak_bytes"] > 0
        # the row also hit the registry mirror
        assert tin.registry().get("compile_rows_total").value(
            site="executor_fwd") == 1
    finally:
        tin._reset_for_tests()


# -- autotune trial features --------------------------------------------------
def test_measure_candidate_features(monkeypatch):
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.autotune import measure

    measure._reset_stats_for_tests()
    x = jnp.ones((8, 8), jnp.float32)

    def build():
        return jax.jit(lambda a: jnp.tanh(a @ a).sum())

    cfg = {"nblk": 64}
    measure.measure_candidate("cp_test_kernel", cfg, build, (x,),
                              warmup=1, repeat=1)
    assert measure.features_for("cp_test_kernel", cfg) is None  # gate off
    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    measure.measure_candidate("cp_test_kernel", cfg, build, (x,),
                              warmup=1, repeat=1)
    feats = measure.features_for("cp_test_kernel", cfg)
    assert feats is not None and feats["flops"] > 0
    # ISSUE 18 widened the trial feature vector: compile_s and the
    # declared-vs-measured drift count feed the learned cost model
    assert set(feats) == {"flops", "bytes_accessed", "temp_bytes",
                          "peak_bytes", "compile_s", "drift"}
    assert feats["compile_s"] >= 0 and feats["drift"] >= 0
    assert measure.measurements() == 2
    measure._reset_stats_for_tests()


# -- ledger diff gate ---------------------------------------------------------
def _write_ledger(path, rows):
    with open(path, "w", encoding="utf-8") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def _ledger_row(key, flops, peak, compile_s=0.5, site="executor_fwd"):
    return {"kind": "compile", "key": key, "site": site, "flops": flops,
            "bytes_accessed": flops * 4 if flops else None,
            "peak_bytes": peak, "compile_s": compile_s}


def test_bench_compare_gate_cost(tmp_path, capsys):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import bench_compare

    base = str(tmp_path / "base.jsonl")
    same = str(tmp_path / "same.jsonl")
    worse = str(tmp_path / "worse.jsonl")
    rows = [_ledger_row("a-1", 1000, 4096), _ledger_row("b-2", 500, 2048)]
    _write_ledger(base, rows)
    _write_ledger(same, rows)
    _write_ledger(worse, [_ledger_row("a-1", 2000, 4096),   # flops doubled
                          _ledger_row("b-2", 500, 8192)])   # peak x4
    # identical -> silent pass, even gated
    assert bench_compare.main([base, same, "--gate-cost"]) == 0
    out = capsys.readouterr().out
    assert "REGRESSION" not in out
    # seeded regression -> nonzero ONLY under --gate-cost
    assert bench_compare.main([base, worse]) == 0
    assert bench_compare.main([base, worse, "--gate-cost"]) == 1
    out = capsys.readouterr().out
    assert "flops" in out and "peak_bytes" in out
    # gate demands ledgers; mixing kinds is a usage error (a real bench
    # capture, written here — a missing file would exit 2 for the wrong
    # reason and mask a broken kind check)
    bench = str(tmp_path / "bench.json")
    with open(bench, "w") as f:
        json.dump({"metric": "m", "value": 1.0, "unit": "img/s"}, f)
    assert bench_compare.main([bench, base, "--gate-cost"]) == 2


def test_bench_compare_ledger_added_removed(tmp_path, capsys):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import bench_compare

    base = str(tmp_path / "base.jsonl")
    new = str(tmp_path / "new.jsonl")
    _write_ledger(base, [_ledger_row("a-1", 1000, 4096)])
    _write_ledger(new, [_ledger_row("c-3", 900, 1024)])
    assert bench_compare.main([base, new, "--gate-cost"]) == 0
    out = capsys.readouterr().out
    assert "added" in out and "removed" in out


def test_trace_summary_ledger_totals(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import trace_summary

    path = str(tmp_path / "l.jsonl")
    _write_ledger(path, [
        _ledger_row("a-1", 1000, 4096),
        _ledger_row("a-1", 1200, 5000),   # same key: last wins
        _ledger_row("b-2", None, None),   # partial row, null-safe
    ])
    # make the partial row detectable
    with open(path) as f:
        lines = f.read().splitlines()
    row = json.loads(lines[-1])
    row["partial"] = ["cost", "memory"]
    lines[-1] = json.dumps(row)
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    t = trace_summary.ledger_totals(path)
    assert t == {"flops": 1200, "bytes_accessed": 4800, "peak_bytes": 5000,
                 "rows": 2, "partial_rows": 1}
