"""Deformable R-FCN example test — the BASELINE config-3 model family
(DeformableConvolution + MultiProposal + DeformablePSROIPooling two-stage
pooling) trains end-to-end on synthetic data."""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd

EXDIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "examples", "deformable_rfcn"))
sys.path.insert(0, EXDIR)


def _load(name, fname):
    from conftest import load_example_module

    return load_example_module(name, os.path.join(EXDIR, fname))


def test_forward_shapes():
    from deformable_rfcn import DeformableRFCN

    net = DeformableRFCN(num_classes=2, rpn_post_nms=16)
    net.initialize()
    data = nd.zeros((2, 3, 64, 64))
    im_info = nd.array(np.tile(np.array([64, 64, 1.0], np.float32), (2, 1)))
    rois, cls_score, bbox_pred, rpn_cls, rpn_bbox = net(data, im_info)
    assert rois.shape == (2 * 16, 5)
    assert cls_score.shape == (32, 3)  # C+1
    assert bbox_pred.shape == (32, 4)


def test_loss_decreases():
    from deformable_rfcn import DeformableRFCN, rfcn_losses, rpn_losses
    synthetic_batches = _load("dfrfcn_train", "train.py").synthetic_batches

    net = DeformableRFCN(num_classes=2)
    net.initialize()
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.02, "momentum": 0.9})
    batches = list(synthetic_batches(2, (3, 64, 64), 3, 2, seed=0))
    losses = []
    for _ in range(5):
        tot = 0.0
        for data, im_info, labels in batches:
            with autograd.record():
                rois, cs, bp, rc, rb = net(data, im_info)
                cl, bl = rfcn_losses(rois, cs, bp, labels, 2)
                rcl, rbl = rpn_losses(net, rc, rb, labels, im_info)
                loss = cl + bl + rcl + rbl
            loss.backward()
            tr.step(2)
            tot += float(loss.asnumpy())
        losses.append(tot / len(batches))
    assert losses[-1] < losses[0] * 0.85, losses


def test_all_branches_get_gradients():
    """Deformable offsets, psroi trans, AND the RPN must receive gradients
    (the ROI round() blocks the pooled path to the RPN; rpn_losses covers it)."""
    from deformable_rfcn import DeformableRFCN, rfcn_losses, rpn_losses
    synthetic_batches = _load("dfrfcn_train", "train.py").synthetic_batches

    net = DeformableRFCN(num_classes=2)
    net.initialize()
    data, im_info, labels = next(iter(synthetic_batches(2, (3, 64, 64), 1, 2)))
    with autograd.record():
        rois, cs, bp, rc, rb = net(data, im_info)
        cl, bl = rfcn_losses(rois, cs, bp, labels, 2)
        rcl, rbl = rpn_losses(net, rc, rb, labels, im_info)
        (cl + bl + rcl + rbl).backward()
    params = net.collect_params()

    def gsum(frag):
        ps = [p for n, p in params.items() if frag in n and n.endswith("weight")]
        assert ps, frag
        return float(np.abs(ps[0].grad().asnumpy()).sum())

    assert gsum("pstrans_") > 0
    assert gsum("rpn_") > 0  # would be exactly 0 without rpn_losses
    assert gsum("offset_") >= 0  # zero-init offsets may have tiny grads
    assert gsum("pscls_") > 0
