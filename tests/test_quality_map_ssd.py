"""SSD detection-quality regression gate at REAL resolution (VERDICT
round-3 item 4).

Runs the seeded synthetic-VOC SSD-300 recipe
(examples/quality/eval_ssd_map.py) at the calibrated nightly config —
width-0.25 trunk but the REAL 8,732-anchor menu at 300², so a
MultiBoxTarget/Detection bug at real anchor shapes fails CI — and gates
on the mAP floor.

Calibration (this config, CPU, round 4, with lr warmup): seeds 0/1/2 →
mAP 0.0603 / 0.0164 / 0.2133.  The w0.25 600-step config is intrinsically
high-variance (warmup rescued the full-width chip config's collapsed seed
but not this narrow one), so the floor is worst seed − ~27% = **0.012** —
still 20× above a broken target assignment (~0.0005 at smoke length),
which is the failure mode this gate exists to catch.
"""
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SCRIPT = os.path.join(REPO, "examples", "quality", "eval_ssd_map.py")


def test_ssd_synthetic_map_floor():
    res = subprocess.run(
        [sys.executable, SCRIPT, "--steps", "600", "--eval-images", "500",
         "--map-floor", "0.012"],
        capture_output=True, text=True, timeout=7200)
    tail = "\n".join(res.stdout.splitlines()[-5:]) + res.stderr[-2000:]
    assert res.returncode == 0, tail
    assert "FINAL ssd300" in res.stdout, tail
