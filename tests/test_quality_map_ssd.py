"""SSD detection-quality regression gate at REAL resolution (VERDICT
round-3 item 4).

Runs the seeded synthetic-VOC SSD-300 recipe
(examples/quality/eval_ssd_map.py) at the calibrated nightly config —
width-0.25 trunk but the REAL 8,732-anchor menu at 300², so a
MultiBoxTarget/Detection bug at real anchor shapes fails CI — and gates
on the mAP floor.

Floor: pre-warmup seeds spread 0.0172-0.1149 (600 steps is the
high-variance regime); lr warmup (added after chip seed 0 collapsed
0.90→0.35 without it) is expected to tighten this — the floor below is
provisional catastrophic-only (a broken target assignment scores ~0.000x)
until the warmup 3-seed recalibration lands in QUALITY.md §3.
"""
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SCRIPT = os.path.join(REPO, "examples", "quality", "eval_ssd_map.py")


def test_ssd_synthetic_map_floor():
    res = subprocess.run(
        [sys.executable, SCRIPT, "--steps", "600", "--eval-images", "500",
         "--map-floor", "0.012"],
        capture_output=True, text=True, timeout=7200)
    tail = "\n".join(res.stdout.splitlines()[-5:]) + res.stderr[-2000:]
    assert res.returncode == 0, tail
    assert "FINAL ssd300" in res.stdout, tail
