"""Bucketing-LM and sparse linear-classification example tests (reference
example/rnn/bucketing + example/sparse/linear_classification families)."""
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _run(script, args, cwd, timeout=600):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    return subprocess.run(
        [sys.executable, script] + args, cwd=cwd, env=env,
        capture_output=True, text=True, timeout=timeout)


def test_lstm_bucketing_perplexity_drops():
    cwd = os.path.join(REPO, "examples", "rnn")
    res = _run("lstm_bucketing.py",
               ["--num-epochs", "3", "--num-sentences", "400",
                "--batch-size", "16", "--num-hidden", "48",
                "--num-embed", "24", "--disp-batches", "1000"], cwd)
    assert res.returncode == 0, res.stdout + res.stderr
    import re

    ppl = [float(m) for m in re.findall(r"Train-perplexity=([0-9.]+)",
                                        res.stdout + res.stderr)]
    assert len(ppl) == 3 and ppl[-1] < ppl[0] * 0.7, ppl


def test_sparse_linear_classification():
    cwd = os.path.join(REPO, "examples", "sparse")
    res = _run("linear_classification.py",
               ["--epochs", "6", "--num-samples", "256"], cwd)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "SPARSE LINEAR OK" in res.stdout
