"""Wrapper running the native C++ unit tests (reference tests/cpp/ —
engine/storage/op C++ tests run under ctest; here `make -C src test`)."""
import os
import shutil
import subprocess

import pytest

SRC = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "src"))


def test_native_cpp_suite():
    cxx = os.environ.get("CXX", "g++")
    if shutil.which("make") is None or shutil.which(cxx) is None:
        pytest.skip("native toolchain unavailable")
    res = subprocess.run(["make", "-C", SRC, "test"],
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "ALL NATIVE TESTS PASSED" in res.stdout
