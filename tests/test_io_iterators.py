"""MNISTIter, LibSVMIter, and the process-worker DataLoader path.

Reference counterparts: ``src/io/iter_mnist.cc:80`` (idx-ubyte reader),
``src/io/iter_libsvm.cc`` (+ sparse prefetcher stack), and the forked
DataLoader workers (``python/mxnet/gluon/data/dataloader.py:239,26-97``).
"""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _write_mnist(tmp_path, n=30, rows=6, cols=5, seed=0):
    rng = np.random.RandomState(seed)
    imgs = rng.randint(0, 256, (n, rows, cols), dtype=np.uint8)
    labs = rng.randint(0, 10, n).astype(np.uint8)
    ip = tmp_path / "images-idx3-ubyte"
    lp = tmp_path / "labels-idx1-ubyte"
    with open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, rows, cols))
        f.write(imgs.tobytes())
    with open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labs.tobytes())
    return str(ip), str(lp), imgs, labs


def test_mnist_iter_reads_idx_format(tmp_path):
    ip, lp, imgs, labs = _write_mnist(tmp_path)
    it = mx.io.MNISTIter(image=ip, label=lp, batch_size=8)
    batch = next(iter([b for b in it][0:1]))
    assert batch.data[0].shape == (8, 1, 6, 5)
    np.testing.assert_allclose(
        batch.data[0].asnumpy(), imgs[:8, None].astype(np.float32) / 256.0)
    np.testing.assert_allclose(batch.label[0].asnumpy(), labs[:8])


def test_mnist_iter_flat_shuffle_parts(tmp_path):
    ip, lp, imgs, labs = _write_mnist(tmp_path)
    flat = mx.io.MNISTIter(image=ip, label=lp, batch_size=4, flat=True)
    b = next(iter(flat))
    assert b.data[0].shape == (4, 30)
    # seeded shuffle is deterministic
    a1 = next(iter(mx.io.MNISTIter(image=ip, label=lp, batch_size=8,
                                   shuffle=True, seed=3))).label[0].asnumpy()
    a2 = next(iter(mx.io.MNISTIter(image=ip, label=lp, batch_size=8,
                                   shuffle=True, seed=3))).label[0].asnumpy()
    np.testing.assert_allclose(a1, a2)
    # num_parts partitions are disjoint and cover the (seeded) stream
    seen = []
    for part in range(3):
        it = mx.io.MNISTIter(image=ip, label=lp, batch_size=10, shuffle=True,
                             seed=1, num_parts=3, part_index=part)
        for b in it:
            seen.append(b.data[0].asnumpy())
    seen = np.concatenate(seen)
    assert seen.shape[0] == 30
    full = np.sort(imgs.reshape(30, -1).astype(np.float32).sum(1))
    got = np.sort((seen * 256.0).reshape(30, -1).sum(1))
    np.testing.assert_allclose(got, full, rtol=1e-4)


def test_mnist_iter_bad_magic(tmp_path):
    p = tmp_path / "bad"
    p.write_bytes(struct.pack(">IIII", 1234, 1, 2, 2) + b"\x00" * 4)
    with pytest.raises(mx.base.MXNetError):
        mx.io.MNISTIter(image=str(p), label=str(p), batch_size=1)


def test_libsvm_iter_sparse_batches(tmp_path):
    p = tmp_path / "train.libsvm"
    p.write_text(
        "1 0:1.5 3:2.0\n"
        "0 1:0.5\n"
        "2 0:3.0 2:1.0 4:0.5\n"
        "1\n"          # empty row
        "0 4:2.5\n"
    )
    it = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(5,), batch_size=2)
    batches = list(it)
    assert len(batches) == 3  # 5 rows, round_batch wraps the last
    from mxnet_tpu.ndarray.sparse import CSRNDArray

    b0 = batches[0]
    assert isinstance(b0.data[0], CSRNDArray)
    dense0 = b0.data[0].todense().asnumpy()
    np.testing.assert_allclose(dense0, [[1.5, 0, 0, 2.0, 0], [0, 0.5, 0, 0, 0]])
    np.testing.assert_allclose(b0.label[0].asnumpy(), [1, 0])
    # wrap-around batch repeats from the start and REPORTS the pad
    d2 = batches[2].data[0].todense().asnumpy()
    np.testing.assert_allclose(d2[0], [0, 0, 0, 0, 2.5])
    np.testing.assert_allclose(d2[1], [1.5, 0, 0, 2.0, 0])
    assert batches[2].pad == 1 and batches[0].pad == 0
    # round_batch=False still emits the padded tail batch (reference
    # iter_batchloader.h:102-125 returns it with num_batch_padd set)
    it2 = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(5,), batch_size=2,
                           round_batch=False)
    b2 = list(it2)
    assert len(b2) == 3 and b2[-1].pad == 1 and b2[0].pad == 0
    # reset replays identically
    it.reset()
    again = next(iter(it)).data[0].todense().asnumpy()
    np.testing.assert_allclose(again, dense0)


def test_libsvm_iter_trains_sparse_linear(tmp_path):
    """The sparse path end-to-end: LibSVM batches into a dot-based linear
    model (reference sparse examples use exactly this pairing)."""
    rng = np.random.RandomState(0)
    w_true = np.zeros(20, np.float32)
    w_true[[2, 7, 11]] = [1.0, -2.0, 3.0]
    lines = []
    for _ in range(60):
        nz = rng.choice(20, 4, replace=False)
        v = rng.randn(4).astype(np.float32)
        yv = 1.0 if (w_true[nz] * v).sum() > 0 else 0.0
        lines.append("%g " % yv + " ".join("%d:%g" % (i, x) for i, x in zip(nz, v)))
    p = tmp_path / "sp.libsvm"
    p.write_text("\n".join(lines))

    from mxnet_tpu import autograd

    w = nd.zeros((20, 1))
    w.attach_grad()
    losses = []
    for epoch in range(30):
        it = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(20,), batch_size=10)
        tot = 0.0
        for batch in it:
            x = batch.data[0].todense()
            y = batch.label[0]
            with autograd.record():
                z = nd.dot(x, w).reshape((-1,))
                loss = nd.mean(nd.log(1 + nd.exp(-(2 * y - 1) * z)))
            loss.backward()
            w._rebind((w - 1.0 * w.grad)._data)
            w.attach_grad()
            tot += float(loss.asnumpy())
        losses.append(tot)
    # loss halves-ish and the learned weights classify the training set
    # well above chance (labels come from a 3-feature ground truth)
    assert losses[-1] < 0.6 * losses[0], losses
    it = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(20,), batch_size=10)
    correct = total = 0
    for batch in it:
        x = batch.data[0].todense().asnumpy()
        y = batch.label[0].asnumpy()
        pred = (x @ w.asnumpy() > 0).ravel()
        correct += (pred == (y > 0)).sum()
        total += len(y)
    assert correct / total > 0.8, correct / total


class _GILBoundDataset:
    """Pure-Python __getitem__ that HOLDS the GIL (the workload class that
    motivates process workers)."""

    def __init__(self, n=64, dim=8):
        self.n, self.dim = n, dim

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        acc = 0.0
        for k in range(200):  # deliberate Python-loop work
            acc += (i * 31 + k) % 7
        x = np.full((self.dim,), float(i), np.float32)
        x[0] = acc
        return x, np.float32(i % 3)


@pytest.mark.parametrize("workers,threads", [(0, True), (2, True), (2, False)])
def test_dataloader_worker_models_agree(workers, threads):
    """Sequential, thread-pool, and process-pool loaders must produce
    identical batches in identical order."""
    from mxnet_tpu.gluon.data import DataLoader

    ds = _GILBoundDataset()
    loader = DataLoader(ds, batch_size=8, shuffle=False, num_workers=workers,
                        thread_pool=threads)
    got = [(d.asnumpy(), l.asnumpy()) for d, l in loader]
    ref_loader = DataLoader(ds, batch_size=8, shuffle=False, num_workers=0)
    ref = [(d.asnumpy(), l.asnumpy()) for d, l in ref_loader]
    assert len(got) == len(ref) == 8
    for (gd, gl), (rd, rl) in zip(got, ref):
        np.testing.assert_allclose(gd, rd)
        np.testing.assert_allclose(gl, rl)


def test_dataloader_process_workers_custom_batchify():
    from mxnet_tpu.gluon.data import DataLoader

    ds = _GILBoundDataset(n=16)

    def batchify(samples):
        xs, ys = zip(*samples)
        return (nd.array(np.stack([np.asarray(x) for x in xs])),
                nd.array(np.asarray(ys)))

    loader = DataLoader(ds, batch_size=4, num_workers=2, thread_pool=False,
                        batchify_fn=batchify)
    out = list(loader)
    assert len(out) == 4
    x0, y0 = out[0]
    assert x0.shape == (4, 8) and y0.shape == (4,)
    np.testing.assert_allclose(y0.asnumpy(), [0, 1, 2, 0])


class _RaggedDataset:
    """Variable-length samples — the canonical custom-batchify case."""

    def __len__(self):
        return 8

    def __getitem__(self, i):
        return np.arange(i + 1, dtype=np.float32), np.float32(i)


def test_dataloader_process_workers_ragged_custom_batchify():
    from mxnet_tpu.gluon.data import DataLoader

    def pad_batchify(samples):
        # samples arrive as the dataset's raw (numpy) structure in EVERY
        # worker mode — the same batchify works sequential/thread/process
        xs, ys = zip(*samples)
        L = max(np.asarray(x).shape[0] for x in xs)
        out = np.zeros((len(xs), L), np.float32)
        for j, x in enumerate(xs):
            out[j, :np.asarray(x).shape[0]] = np.asarray(x)
        return nd.array(out), nd.array(np.asarray(ys))

    for workers, threads in [(0, True), (2, True), (2, False)]:
        loader = DataLoader(_RaggedDataset(), batch_size=4, num_workers=workers,
                            thread_pool=threads, batchify_fn=pad_batchify)
        b0, b1 = list(loader)
        x0, y0 = b0
        assert x0.shape == (4, 4)
        np.testing.assert_allclose(x0.asnumpy()[3], [0, 1, 2, 3])
        np.testing.assert_allclose(y0.asnumpy(), [0, 1, 2, 3])


class _JaxReturningDataset:
    """Returns jax-backed NDArrays — forbidden inside process workers
    (module-level so spawn can pickle it; the rejection must come from the
    worker-side guard, not a pickling accident)."""

    def __len__(self):
        return 4

    def __getitem__(self, i):
        return nd.zeros((2,))


def test_dataloader_process_workers_reject_jax_samples():
    from mxnet_tpu.gluon.data import DataLoader

    with pytest.raises(Exception) as e:
        list(DataLoader(_JaxReturningDataset(), batch_size=2, num_workers=2,
                        thread_pool=False))
    assert "thread_pool" in str(e.value) or "NDArray" in str(e.value)


def test_new_iterators_follow_dataiter_protocol(tmp_path):
    """iter_next/getdata/getlabel/getpad — the DataIter contract consumers
    like ResizeIter/module code rely on."""
    ip, lp, _, labs = _write_mnist(tmp_path)
    it = mx.io.MNISTIter(image=ip, label=lp, batch_size=8)
    assert it.iter_next()
    assert it.getdata()[0].shape == (8, 1, 6, 5)
    np.testing.assert_allclose(it.getlabel()[0].asnumpy(), labs[:8])
    assert it.getpad() == 0

    p = tmp_path / "t.libsvm"
    p.write_text("1 0:1.0\n0 1:2.0\n1 2:3.0\n")
    sv = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(3,), batch_size=2)
    assert sv.iter_next()
    np.testing.assert_allclose(sv.getdata()[0].todense().asnumpy(),
                               [[1, 0, 0], [0, 2, 0]])
    assert sv.getpad() == 0
    assert sv.iter_next()
    assert sv.getpad() == 1  # wrapped final batch reports its padding
    assert not sv.iter_next()


def test_libsvm_label_count_mismatch(tmp_path):
    d = tmp_path / "d.libsvm"
    d.write_text("1 0:1.0\n0 1:2.0\n")
    l = tmp_path / "l.libsvm"
    l.write_text("0:1.0\n0:2.0\n0:3.0\n")
    with pytest.raises(mx.base.MXNetError):
        mx.io.LibSVMIter(data_libsvm=str(d), data_shape=(3,), batch_size=1,
                         label_libsvm=str(l))
