"""Symbol + Executor tests — modeled on reference tests/python/unittest/test_symbol.py
and parts of test_operator.py's symbolic checks."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import assert_almost_equal


def test_variable_and_compose():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a + b
    assert set(c.list_arguments()) == {"a", "b"}
    assert c.list_outputs() == [c.name + "_output"]


def test_mlp_structure():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=64, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=10, name="fc2")
    out = sym.SoftmaxOutput(fc2, name="softmax")
    args = out.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias", "softmax_label"]


def test_infer_shape():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=64, name="fc1")
    arg_shapes, out_shapes, aux_shapes = fc1.infer_shape(data=(32, 100))
    assert arg_shapes == [(32, 100), (64, 100), (64,)]
    assert out_shapes == [(32, 64)]
    assert aux_shapes == []


def test_infer_shape_conv_bn():
    data = sym.Variable("data")
    conv = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1), name="conv1")
    bn = sym.BatchNorm(conv, name="bn1")
    act = sym.Activation(bn, act_type="relu")
    arg_shapes, out_shapes, aux_shapes = act.infer_shape(data=(2, 3, 16, 16))
    assert out_shapes == [(2, 8, 16, 16)]
    d = dict(zip(act.list_arguments(), arg_shapes))
    assert d["conv1_weight"] == (8, 3, 3, 3)
    assert d["bn1_gamma"] == (8,)
    assert dict(zip(act.list_auxiliary_states(), aux_shapes))["bn1_moving_mean"] == (8,)


def test_simple_bind_forward_backward():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a * b + a
    exe = c.simple_bind(ctx=mx.cpu(), a=(3,), b=(3,))
    exe.arg_dict["a"][:] = nd.array([1.0, 2.0, 3.0])
    exe.arg_dict["b"][:] = nd.array([4.0, 5.0, 6.0])
    outs = exe.forward()
    assert_almost_equal(outs[0], np.array([5, 12, 21], dtype=np.float32))
    exe.backward(out_grads=nd.ones((3,)))
    assert_almost_equal(exe.grad_dict["a"], np.array([5, 6, 7], dtype=np.float32))
    assert_almost_equal(exe.grad_dict["b"], np.array([1, 2, 3], dtype=np.float32))


def test_backward_honors_eval_mode_forward():
    """ISSUE 3 satellite: backward after forward(is_train=False) must
    differentiate the EVAL-mode graph (Dropout = identity, BatchNorm on
    moving stats) — the recorded mode keys the backward cache, so flipping
    modes can't reuse the wrong executable."""
    data = sym.Variable("data")
    out = sym.Dropout(data, p=0.5, name="do")
    exe = out.simple_bind(ctx=mx.cpu(), data=(64,))
    exe.arg_dict["data"][:] = nd.ones((64,))
    exe.forward(is_train=False)
    exe.backward(out_grads=nd.ones((64,)))
    # eval-mode dropout is identity: grad == 1 everywhere (the old code
    # hardcoded the train graph and produced a 0/2 mask here)
    assert_almost_equal(exe.grad_dict["data"], np.ones(64, np.float32))
    exe.forward(is_train=True)
    exe.backward(out_grads=nd.ones((64,)))
    g = exe.grad_dict["data"].asnumpy()
    assert set(np.round(np.unique(g), 4)) <= {0.0, 2.0}
    assert 0.0 in g and 2.0 in g  # train-mode mask actually applied


def test_executor_mlp_forward():
    np.random.seed(0)
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="fc")
    out = sym.softmax(fc, name="sm")
    exe = out.simple_bind(ctx=mx.cpu(), data=(2, 3))
    exe.arg_dict["data"][:] = nd.array(np.random.rand(2, 3))
    exe.arg_dict["fc_weight"][:] = nd.array(np.random.rand(4, 3))
    exe.arg_dict["fc_bias"][:] = nd.array(np.random.rand(4))
    outs = exe.forward()
    x = exe.arg_dict["data"].asnumpy()
    w = exe.arg_dict["fc_weight"].asnumpy()
    b = exe.arg_dict["fc_bias"].asnumpy()
    logits = x @ w.T + b
    p = np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)
    assert_almost_equal(outs[0], p, rtol=1e-4, atol=1e-5)


def test_batchnorm_aux_update():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data, name="bn", momentum=0.5, fix_gamma=False)
    exe = bn.simple_bind(ctx=mx.cpu(), data=(4, 2))
    exe.arg_dict["bn_gamma"][:] = 1.0
    exe.aux_dict["bn_moving_var"][:] = 1.0
    x = np.random.rand(4, 2).astype(np.float32) * 3
    exe.arg_dict["data"][:] = nd.array(x)
    exe.forward(is_train=True)
    mm = exe.aux_dict["bn_moving_mean"].asnumpy()
    assert_almost_equal(mm, 0.5 * x.mean(axis=0), rtol=1e-3, atol=1e-4)
    # eval mode uses moving stats, does not update them
    exe.forward(is_train=False)
    assert_almost_equal(exe.aux_dict["bn_moving_mean"].asnumpy(), mm)


def test_group_and_internals():
    a = sym.Variable("a")
    b = a * 2
    c = b + 1
    g = sym.Group([b, c])
    assert len(g.list_outputs()) == 2
    internals = c.get_internals()
    assert any("_output" in n or n == "a" for n in internals.list_outputs())


def test_save_load_json(tmp_path):
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = sym.Activation(fc, act_type="relu", name="act1")
    js = act.tojson()
    act2 = sym.load_json(js)
    assert act2.list_arguments() == act.list_arguments()
    f = str(tmp_path / "sym.json")
    act.save(f)
    act3 = sym.load(f)
    assert act3.list_arguments() == act.list_arguments()
    # behavioral equivalence
    exe = act3.simple_bind(ctx=mx.cpu(), data=(2, 4))
    exe.arg_dict["data"][:] = 1.0
    exe.arg_dict["fc1_weight"][:] = 0.5
    out = exe.forward()[0]
    assert out.shape == (2, 8)
    assert_almost_equal(out, np.full((2, 8), 2.0))


def test_multi_output_slicechannel():
    data = sym.Variable("data")
    parts = sym.SliceChannel(data, num_outputs=2, axis=1, name="slice")
    assert len(parts.list_outputs()) == 2
    first = parts[0]
    exe = first.simple_bind(ctx=mx.cpu(), data=(2, 4))
    exe.arg_dict["data"][:] = nd.array(np.arange(8).reshape(2, 4))
    out = exe.forward()[0]
    assert out.shape == (2, 2)
    assert_almost_equal(out, np.array([[0, 1], [4, 5]], dtype=np.float32))


def test_scalar_ops_on_symbols():
    a = sym.Variable("a")
    c = (a + 1) * 3 - 0.5
    exe = c.simple_bind(ctx=mx.cpu(), a=(2,))
    exe.arg_dict["a"][:] = nd.array([1.0, 2.0])
    assert_almost_equal(exe.forward()[0], np.array([5.5, 8.5], dtype=np.float32))


def test_dropout_deterministic_under_seed():
    data = sym.Variable("data")
    d = sym.Dropout(data, p=0.5, name="drop")
    exe = d.simple_bind(ctx=mx.cpu(), data=(50, 50))
    exe.arg_dict["data"][:] = 1.0
    mx.random.seed(7)
    o1 = exe.forward(is_train=True)[0].asnumpy()
    mx.random.seed(7)
    o2 = exe.forward(is_train=True)[0].asnumpy()
    assert np.array_equal(o1, o2)
    o3 = exe.forward(is_train=False)[0].asnumpy()
    assert (o3 == 1).all()
