"""AOT compilation + persistent executable cache (ISSUE 6, compile_cache.py).

Coverage demanded by the issue:
- compile-once acceptance: a second Engine warming the same ladder against
  the same cache dir restores every bucket from disk — zero fresh compiles
  (misses), all hits — and still serves correctly;
- cache invalidation is CORRUPTION-SAFE: a stale jax/jaxlib version key, a
  mesh-descriptor mismatch, and a truncated cache file each produce a clean
  miss + recompile (counted in ``aot_cache_errors_total{reason}``), never a
  crash, and the bad entry is overwritten;
- the warmup lowering split: report rows carry ``lower_s``/``compile_s``
  and ``Engine.stats()`` gains the ``warmup`` block, with and without the
  cache;
- the cache-off path is untouched: no CachedFunction in the executor, no
  cache rows in the warmup report;
- the CPU donation guard: ``donated=True`` callables never read or write
  disk entries on the CPU backend (restored donated executables compute
  wrong trajectories there — compile_cache.py docstring).
"""
import glob
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compile_cache as cc
from mxnet_tpu import serving
from mxnet_tpu.serving import BucketLadder, Engine
from mxnet_tpu.telemetry import instrument as tin


@pytest.fixture
def aot_dir(tmp_path, monkeypatch):
    d = tmp_path / "aot"
    monkeypatch.setenv("MXNET_AOT_CACHE", str(d))
    cc._reset_stats_for_tests()
    yield str(d)
    cc._reset_stats_for_tests()


@pytest.fixture
def aot_off(monkeypatch):
    monkeypatch.delenv("MXNET_AOT_CACHE", raising=False)
    cc._reset_stats_for_tests()
    yield
    cc._reset_stats_for_tests()


@pytest.fixture
def tel_enabled(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    tin._reset_for_tests()
    yield
    tin._reset_for_tests()


def _mlp_engine(**kw):
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    kw.setdefault("ladder", BucketLadder((1, 2, 4)))
    kw.setdefault("start", False)
    return Engine(sym, params, {"data": (8,)}, **kw)


def _exec_entries(aot_dir):
    return sorted(glob.glob(os.path.join(aot_dir, "exec", "*.jx")))


# -- engine warm restart ------------------------------------------------------
class TestEngineWarmRestart:
    def test_cold_warmup_populates_cache(self, aot_dir):
        eng = _mlp_engine()
        report = eng.warmup()
        assert [r["cache"] for r in report] == ["miss", "miss", "miss"]
        assert all(r["fresh"] for r in report)
        s = cc.stats()
        assert (s["hits"], s["misses"], s["errors"]) == (0, 3, 0)
        assert len(_exec_entries(aot_dir)) == 3
        w = eng.stats()["warmup"]
        assert w["buckets"] == 3 and w["cache_misses"] == 3
        assert w["cache_hits"] == 0 and w["total_s"] > 0
        eng.close()

    def test_second_engine_compiles_zero_fresh_modules(self, aot_dir):
        eng1 = _mlp_engine()
        eng1.warmup()
        eng1.close()
        before = cc.stats()
        eng2 = _mlp_engine()
        report = eng2.warmup()
        after = cc.stats()
        # the acceptance: every bucket restored, ZERO fresh compiles
        assert [r["cache"] for r in report] == ["hit", "hit", "hit"]
        assert after["misses"] == before["misses"]  # no new compile
        assert after["hits"] == before["hits"] + 3
        assert after["errors"] == 0
        w = eng2.stats()["warmup"]
        assert w["cache_hits"] == 3 and w["cache_misses"] == 0
        # disk restores are not XLA compiles: the warm restart reports 0
        assert eng2.stats()["compiles"] == 0
        # ...and the restored executables actually serve, with parity
        eng2.start()
        x = np.random.RandomState(0).rand(2, 8).astype(np.float32)
        out = eng2.predict({"data": x})
        eng2.close()
        eng3 = _mlp_engine(start=True)  # no cache entries consumed: fresh jit
        os.environ.pop("MXNET_AOT_CACHE")
        np.testing.assert_allclose(out[0],
                                   eng3.predict({"data": x})[0], atol=1e-6)
        eng3.close()

    def test_rewarmup_reports_no_phantom_hits(self, aot_dir):
        eng = _mlp_engine()
        eng.warmup()
        hits_before = cc.stats()["hits"]
        report = eng.warmup()  # same process: everything already live
        # in-process "cached" is neither a disk restore nor a compile
        assert [r["cache"] for r in report] == [None, None, None]
        assert not any(r["fresh"] for r in report)
        w = eng.stats()["warmup"]
        assert w["cache_hits"] == 0 and w["cache_misses"] == 0
        assert cc.stats()["hits"] == hits_before
        eng.close()

    def test_report_splits_lower_and_compile(self, aot_dir):
        eng = _mlp_engine()
        report = eng.warmup()
        # phase 1 (concurrent trace+lower) is reported per bucket,
        # separately from the device-exclusive compile+forward
        assert all(r["lower_s"] > 0 for r in report)
        assert all(r["compile_s"] > 0 for r in report)
        eng.close()

    def test_warmup_stats_block_without_cache(self, aot_off):
        eng = _mlp_engine()
        report = eng.warmup()
        assert [r["cache"] for r in report] == [None, None, None]
        w = eng.stats()["warmup"]
        assert w["buckets"] == 3 and w["fresh"] == 3
        assert w["cache_hits"] == 0 and w["cache_misses"] == 0
        assert w["total_s"] > 0
        assert cc.stats()["misses"] == 0  # cache never touched
        eng.close()

    def test_off_path_uses_plain_jit(self, aot_off):
        eng = _mlp_engine()
        fwd = eng._proto._exec._compiled(False)
        assert not isinstance(fwd, cc.CachedFunction)
        assert eng._proto.aot_lower() is None
        eng.close()


# -- invalidation: every bad entry is a clean miss + recompile ----------------
def _cached_fn(key=("t",), name="t", mesh_desc=None, donated=False):
    import jax

    return cc.CachedFunction(jax.jit(lambda x: x * 2 + 1), key, name=name,
                             mesh_desc=mesh_desc, donated=donated)


class TestInvalidation:
    def test_stale_jax_version_key(self, aot_dir, tel_enabled, monkeypatch):
        import jax.numpy as jnp

        x = jnp.ones((4,))
        f1 = _cached_fn()
        np.testing.assert_allclose(f1(x), 3.0)
        assert cc.stats()["misses"] == 1
        # "restart" onto a different jax/jaxlib build
        monkeypatch.setattr(cc, "_versions", lambda: ("0.0.0", "0.0.0"))
        f2 = _cached_fn()
        np.testing.assert_allclose(f2(x), 3.0)  # recompiled, not crashed
        s = cc.stats()
        assert s["errors"] == 1 and s["misses"] == 2
        err = tin.registry().get("aot_cache_errors_total")
        assert sum(v["value"] for v in err.samples()
                   if v["labels"]["reason"] == "key_mismatch") == 1
        # the stale entry was overwritten: a third consumer (same stubbed
        # version) now hits
        f3 = _cached_fn()
        np.testing.assert_allclose(f3(x), 3.0)
        assert cc.stats()["hits"] == 1

    def test_mesh_shape_mismatch(self, aot_dir, tel_enabled):
        import jax.numpy as jnp

        x = jnp.ones((4,))
        f1 = _cached_fn(mesh_desc={"axes": ["dp"], "shape": [8]})
        f1(x)
        assert cc.stats()["misses"] == 1
        # restart onto a different topology: same logical key, mesh differs
        f2 = _cached_fn(mesh_desc={"axes": ["dp"], "shape": [4]})
        np.testing.assert_allclose(f2(x), 3.0)
        s = cc.stats()
        assert s["errors"] == 1 and s["misses"] == 2 and s["hits"] == 0
        err = tin.registry().get("aot_cache_errors_total")
        assert sum(v["value"] for v in err.samples()
                   if v["labels"]["reason"] == "key_mismatch") == 1

    def test_truncated_cache_file(self, aot_dir, tel_enabled):
        import jax.numpy as jnp

        x = jnp.ones((4,))
        _cached_fn()(x)
        (entry,) = _exec_entries(aot_dir)
        with open(entry, "rb") as f:
            blob = f.read()
        with open(entry, "wb") as f:
            f.write(blob[:64])  # torn write / disk corruption
        f2 = _cached_fn()
        np.testing.assert_allclose(f2(x), 3.0)
        s = cc.stats()
        assert s["errors"] == 1 and s["misses"] == 2
        err = tin.registry().get("aot_cache_errors_total")
        assert sum(v["value"] for v in err.samples()
                   if v["labels"]["reason"] == "deserialize") == 1
        # recompile re-stored a good entry: next consumer hits
        f3 = _cached_fn()
        f3(x)
        assert cc.stats()["hits"] == 1

    def test_garbage_file_never_crashes(self, aot_dir):
        import jax.numpy as jnp

        x = jnp.ones((4,))
        _cached_fn()(x)
        (entry,) = _exec_entries(aot_dir)
        with open(entry, "wb") as f:
            f.write(b"\x00not a pickle")
        np.testing.assert_allclose(_cached_fn()(x), 3.0)
        assert cc.stats()["errors"] == 1

    def test_hit_and_miss_counters_reach_registry(self, aot_dir, tel_enabled):
        import jax.numpy as jnp

        x = jnp.ones((4,))
        _cached_fn()(x)
        _cached_fn()(x)
        r = tin.registry()
        miss = r.get("aot_cache_misses_total")
        hit = r.get("aot_cache_hits_total")
        assert sum(v["value"] for v in miss.samples()
                   if v["labels"]["tier"] == "exec") == 1
        assert sum(v["value"] for v in hit.samples()
                   if v["labels"]["tier"] == "exec") == 1


# -- fused stepper ------------------------------------------------------------
def _tiny_module():
    from mxnet_tpu import module as mod_mod

    data = mx.sym.var("data")
    x = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    x = mx.sym.Activation(x, name="relu1", act_type="relu")
    x = mx.sym.FullyConnected(x, name="fc2", num_hidden=4)
    sym = mx.sym.SoftmaxOutput(x, name="softmax")
    mod = mod_mod.Module(sym)
    mod.bind(data_shapes=[("data", (8, 8))],
             label_shapes=[("softmax_label", (8,))])
    rng = np.random.RandomState(3)
    mod.init_params(arg_params={
        n: mx.nd.array(rng.randn(*a.shape).astype(np.float32) * 0.1)
        for n, a in mod._exec.arg_dict.items()
        if n not in ("data", "softmax_label")})
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    return mod


def _steps(mod, n=2):
    from mxnet_tpu.io import DataBatch

    rng = np.random.RandomState(7)
    for _ in range(n):
        b = DataBatch(
            data=[mx.nd.array(rng.randn(8, 8).astype(np.float32))],
            label=[mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))])
        mod.forward_backward(b)
        mod.update()
    return mod.get_outputs()[0].asnumpy()


class TestFusedStepper:
    def test_fused_step_wrapped_and_parity(self, aot_dir):
        mx.random.seed(11)
        mod = _tiny_module()
        out_aot = _steps(mod)
        assert isinstance(mod._fused._jit, cc.CachedFunction)
        os.environ.pop("MXNET_AOT_CACHE")
        mx.random.seed(11)
        out_plain = _steps(_tiny_module())
        np.testing.assert_allclose(out_aot, out_plain, atol=1e-6)

    def test_donated_cpu_guard_skips_disk(self, aot_dir):
        """Restored donated executables are unsound on XLA:CPU (wrong
        trajectories under load — compile_cache.py docstring), so the
        fused step must neither write nor read disk entries here, while
        the in-memory AOT split still dispatches correctly."""
        mx.random.seed(11)
        mod = _tiny_module()
        _steps(mod)
        fused_entries = [p for p in _exec_entries(aot_dir)
                         if "fused_step" in os.path.basename(p)]
        assert fused_entries == []
        s = cc.stats()
        assert s["hits"] == 0 and s["misses"] == 0 and s["errors"] == 0

    def test_cache_size_tracks_signatures(self, aot_dir):
        mx.random.seed(11)
        mod = _tiny_module()
        _steps(mod)
        assert mod._fused.cache_size() == 1  # one shape signature, once


# -- predictor surface --------------------------------------------------------
class TestPredictorAOT:
    def test_aot_warm_roundtrip(self, aot_dir):
        from mxnet_tpu.predictor import Predictor
        from mxnet_tpu.test_utils import tiny_mlp_checkpoint

        sym, params = tiny_mlp_checkpoint()
        p1 = Predictor(sym, params, {"data": (2, 8)})
        row = p1.aot_warm()
        assert row["source"] == "compile" and row["compile_s"] > 0
        x = np.random.RandomState(1).rand(2, 8).astype(np.float32)
        ref = p1.forward(data=x)[0].asnumpy()
        # "restart": a sibling predictor restores the executable
        p2 = Predictor(sym, params, {"data": (2, 8)})
        row2 = p2.aot_warm()
        assert row2["source"] == "disk"
        np.testing.assert_allclose(p2.forward(data=x)[0].asnumpy(), ref,
                                   atol=1e-6)
        assert cc.stats()["hits"] == 1
