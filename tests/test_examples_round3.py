"""Round-3 example families (VERDICT round-2 missing item 1): the
highest-value reference example directories still unported after round 2 —
stochastic-depth, capsnet, dsd, bayesian-methods (SGLD), speech_recognition
(bucketed CTC), gan (conditional GAN).  Each test is the family's synthetic
E2E run at reduced scale (nightly tier)."""
import os
import sys

import numpy as np
import pytest

EX = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "examples"))
for sub in ("stochastic-depth", "capsnet", "dsd", "bayesian-methods",
            "speech_recognition", "gan"):
    p = os.path.join(EX, sub)
    if p not in sys.path:
        sys.path.insert(0, p)


def test_stochastic_depth_trains_and_gates():
    import sd_cifar10

    acc = sd_cifar10.main(epochs=8, death_rate=0.5)
    assert acc > 0.9, acc
    # death_rate=1: the compute branch must be fully dead at train time —
    # its conv params get exactly zero gradient through the gate
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd

    mx.random.seed(0)
    blk = sd_cifar10.StochasticDepthBlock(4, death_rate=1.0)
    blk.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).randn(2, 4, 8, 8).astype(np.float32))
    with autograd.record():
        out = blk(x)
    out.backward()
    g = blk.body[0].weight.grad().asnumpy()
    assert np.allclose(g, 0.0), np.abs(g).max()


def test_capsnet_routing_learns_digits():
    import capsulenet

    acc = capsulenet.main(epochs=8)
    assert acc > 0.85, acc


def test_capsnet_squash_norm_bound():
    """Squash must map any capsule to length < 1, preserving direction."""
    import jax.numpy as jnp
    import capsulenet
    import mxnet_tpu.ndarray as F

    from mxnet_tpu import nd

    s = nd.array(np.random.RandomState(0).randn(4, 3, 8).astype(np.float32) * 10)
    v = capsulenet.squash(F, s, axis=2).asnumpy()
    lens = np.linalg.norm(v, axis=2)
    assert (lens < 1.0).all() and (lens > 0.5).all()  # big inputs -> ~1
    cos = (v * s.asnumpy()).sum(2) / (
        np.linalg.norm(v, axis=2) * np.linalg.norm(s.asnumpy(), axis=2))
    np.testing.assert_allclose(cos, 1.0, atol=1e-5)


def test_dsd_sparse_phase_prunes_and_recovers():
    import mlp as dsd_mlp

    acc, opt = dsd_mlp.main(epochs_per_phase=4, sparsity=60.0)
    assert acc > 0.9, acc
    # the sparse phase (phase 1) must have pruned ~60% of each fc weight,
    # and the final phase (2) lifted the mask (dense again)
    sparse = {k: v for k, v in opt.mask_history.items() if k[1] == 1 and v > 0}
    assert sparse, opt.mask_history
    assert all(0.5 < v < 0.7 for v in sparse.values()), sparse
    assert all(opt.mask_history.get((k[0], 2), 0.0) == 0.0 for k in sparse)
    assert any(p == 2 for p in opt._mask_phase.values()), opt._mask_phase


def test_dsd_mask_semantics_unit():
    """SparseSGD masks weight/grad/momentum every update (reference
    sparse_sgd.py preprocessing) — pruned entries stay exactly zero."""
    from sparse_sgd import SparseSGD
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    mx.random.seed(0)
    w = nd.array(np.array([[5.0, 0.01, 3.0, 0.02]], np.float32))
    opt = SparseSGD(pruning_switch_epoch=[0], batches_per_epoch=1,
                    weight_sparsity=[50.0], bias_sparsity=[0.0],
                    learning_rate=0.1, momentum=0.9)
    state = opt.create_state(0, w)
    for _ in range(3):
        g = nd.array(np.ones((1, 4), np.float32))
        opt.update(0, w, g, state)
    out = w.asnumpy()
    assert out[0, 1] == 0.0 and out[0, 3] == 0.0, out   # pruned
    assert out[0, 0] != 0.0 and out[0, 2] != 0.0, out   # survivors train


def test_sgld_recovers_bimodal_posterior():
    import sgld_demo

    S = sgld_demo.main(n_samples=4000, burn_in=800)
    lo = (S[:, 0] < 0.4).mean()
    hi = (S[:, 0] > 0.6).mean()
    # both posterior modes visited (the Welling & Teh property; a plain
    # SGD would collapse into one)
    assert lo > 0.05 and hi > 0.05, (lo, hi)
    assert np.isfinite(S).all()


def test_deepspeech_ctc_buckets_learn():
    import deepspeech

    losses, acc = deepspeech.main(steps=120)
    assert np.mean(losses[-10:]) < 0.5 * np.mean(losses[:10]), (
        losses[:3], losses[-3:])
    assert acc > 0.5, acc  # chance is ~1/6 per token


def test_cgan_conditional_fidelity():
    import cgan

    acc = cgan.main(steps=1200)
    assert acc > 0.4, acc  # chance 0.10; conditioning must clearly bind


def test_ssd_fused_real_graph_smoke():
    """The VGG16-reduced SSD fused train step (examples/ssd/train_fused.py)
    at reduced size but the REAL graph: loss finite and decreasing."""
    import subprocess

    r = subprocess.run(
        [sys.executable, os.path.join(EX, "ssd", "train_fused.py"),
         "--steps", "6"],
        capture_output=True, text=True, timeout=1200,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "SSD FUSED TRAIN OK" in r.stdout
