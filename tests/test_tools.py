"""Tooling ports (reference ``tools/``): parse_log markdown tables,
rec2idx index reconstruction, kill-mxnet command construction,
diagnose report."""
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
TOOLS = os.path.join(REPO, "tools")


def _load(fname):
    from mxnet_tpu.test_utils import load_module_by_path

    return load_module_by_path(os.path.join(TOOLS, fname))


def test_parse_log_markdown():
    pl = _load("parse_log.py")
    lines = [
        "INFO:root:Epoch[0] Train-accuracy=0.5",
        "INFO:root:Epoch[0] Validation-accuracy=0.4",
        "INFO:root:Epoch[0] Time cost=1.5",
        "INFO:root:Epoch[1] Train-accuracy=0.8",
        "noise line",
    ]
    d = pl.parse(lines)
    assert d[0] == [0.5, 0.4, 1.5]
    assert d[1][0] == 0.8
    md = pl.to_markdown(d)
    assert md.splitlines()[0].startswith("| epoch |")


def test_rec2idx_roundtrip(tmp_path):
    from mxnet_tpu import recordio

    rec = str(tmp_path / "a.rec")
    w = recordio.MXRecordIO(rec, "w")
    for i in range(7):
        w.write(b"payload%d" % i)
    w.close()
    r2i = _load("rec2idx.py")
    assert r2i.create_index(rec, rec + ".idx") == 7
    r = recordio.MXIndexedRecordIO(rec + ".idx", rec, "r")
    assert r.read_idx(5) == b"payload5"
    r.close()


def test_kill_mxnet_command():
    km = _load("kill-mxnet.py")
    cmd = km.kill_command("bob", "train.py")
    # shlex-quoted fixed-string grep (round-4 hardening): metachars inert
    assert "grep -F -- train.py" in cmd and "u=bob" in cmd and "kill -9" in cmd
    import shlex
    hostile = "x'; rm -rf /; '"
    assert shlex.quote(hostile) in km.kill_command("bob", hostile)


def test_diagnose_runs():
    res = subprocess.run([sys.executable, os.path.join(TOOLS, "diagnose.py")],
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr[-500:]
    assert "Framework Info" in res.stdout
    assert "jax" in res.stdout


# -- per-rank trace merging (ISSUE 12) ---------------------------------------
def _rank_trace(tmp_path, rank, name, via):
    """A tiny chrome trace carrying its rank via clock_sync args, event
    args, or only the filename."""
    import json

    events = [{"name": "clock_sync", "ph": "M", "pid": 0,
               "args": {"unix_ts": 1000.0 + rank, "trace_ts_us": 0.0}},
              {"name": name, "ph": "X", "ts": 10.0, "dur": 5.0, "pid": 0,
               "tid": 1, "args": {}}]
    if via == "clock_sync":
        events[0]["args"]["rank"] = rank
        fname = "trace-%s.json" % name
    elif via == "args":
        events[1]["args"]["rank"] = rank
        fname = "trace-%s.json" % name
    else:  # filename only
        fname = "trace-rank%d-%s.json" % (rank, name)
    path = tmp_path / fname
    path.write_text(json.dumps({"traceEvents": events}))
    return str(path)


def test_trace_merge_merges_on_rank_label(tmp_path):
    """Per-rank files land on rank-labeled pid namespaces: two files of
    the SAME rank share one track group, different ranks get their own,
    and every non-meta event gains the queryable args.rank."""
    import json

    tm = _load("trace_merge.py")
    out = str(tmp_path / "merged.json")
    f0a = _rank_trace(tmp_path, 0, "step_a", "clock_sync")
    f0b = _rank_trace(tmp_path, 0, "step_b", "args")
    f1 = _rank_trace(tmp_path, 1, "step_c", "filename")
    assert tm.main([f0a, f0b, f1, "-o", out]) == 0
    merged = json.load(open(out))["traceEvents"]
    slices = {ev["name"]: ev for ev in merged if ev.get("ph") == "X"}
    # same rank -> same pid namespace; different rank -> different
    assert slices["step_a"]["pid"] == slices["step_b"]["pid"]
    assert slices["step_c"]["pid"] != slices["step_a"]["pid"]
    assert slices["step_a"]["args"]["rank"] == 0
    assert slices["step_c"]["args"]["rank"] == 1
    labels = {ev["pid"]: ev["args"]["name"] for ev in merged
              if ev.get("ph") == "M" and ev.get("name") == "process_name"}
    assert labels[slices["step_a"]["pid"]] == "rank 0"
    assert labels[slices["step_c"]["pid"]] == "rank 1"


def test_trace_merge_mixed_rank_file_keeps_own_namespace(tmp_path):
    """A file carrying SEVERAL event ranks (e.g. a previous merge output
    fed back in) has no single file rank — it must keep its own pid
    namespace instead of collapsing every rank into the first one."""
    import json

    tm = _load("trace_merge.py")
    events = [{"name": "a", "ph": "X", "ts": 1.0, "dur": 1.0, "pid": 0,
               "tid": 1, "args": {"rank": 0}},
              {"name": "b", "ph": "X", "ts": 2.0, "dur": 1.0, "pid": 1,
               "tid": 1, "args": {"rank": 1}}]
    mixed = tmp_path / "remerged.json"
    mixed.write_text(json.dumps({"traceEvents": events}))
    assert tm.file_rank(str(mixed), events) is None
    # mixed clock_sync records (two flightrec dumps merged) are equally
    # rank-less — the first clock_sync must not claim the file
    syncs = [{"name": "clock_sync", "ph": "M", "pid": 0,
              "args": {"unix_ts": 1.0, "trace_ts_us": 0.0, "rank": r}}
             for r in (0, 1)]
    assert tm.file_rank("remerged2.json", syncs + events) is None
    f1 = _rank_trace(tmp_path, 1, "step_c", "clock_sync")
    out = str(tmp_path / "m.json")
    assert tm.main([str(mixed), f1, "-o", out]) == 0
    merged = json.load(open(out))["traceEvents"]
    by_name = {ev["name"]: ev for ev in merged if ev.get("ph") == "X"}
    # the mixed file's ranks keep their original (namespaced) pids and
    # were NOT folded into rank 1's track group
    assert by_name["a"]["args"]["rank"] == 0
    assert by_name["b"]["args"]["rank"] == 1
    assert by_name["step_c"]["pid"] not in (by_name["a"]["pid"],
                                            by_name["b"]["pid"])


def test_trace_merge_labels_every_pid_track(tmp_path):
    """Profiler-style dumps use one pid per domain — the rank label must
    land on EVERY pid track the file contributes, without overriding an
    embedded process_name."""
    import json

    tm = _load("trace_merge.py")
    events = [{"name": "process_name", "ph": "M", "pid": 2,
               "args": {"name": "my domain"}},
              {"name": "a", "ph": "X", "ts": 1.0, "dur": 1.0, "pid": 0,
               "tid": 1, "args": {}},
              {"name": "b", "ph": "X", "ts": 2.0, "dur": 1.0, "pid": 2,
               "tid": 1, "args": {}}]
    f = tmp_path / "trace-rank3-prof.json"
    f.write_text(json.dumps({"traceEvents": events}))
    out = str(tmp_path / "m.json")
    assert tm.main([str(f), "-o", out]) == 0
    merged = json.load(open(out))["traceEvents"]
    labels = {}
    for ev in merged:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            labels.setdefault(ev["pid"], ev["args"]["name"])
    by_name = {ev["name"]: ev for ev in merged if ev.get("ph") == "X"}
    assert labels[by_name["a"]["pid"]] == "rank 3"
    assert labels[by_name["b"]["pid"]] == "my domain"  # not overridden


def test_trace_merge_explicit_rank_flag(tmp_path):
    import json

    tm = _load("trace_merge.py")
    out = str(tmp_path / "merged.json")
    # file with a stale EMBEDDED per-event rank: --rank must override it
    # everywhere — track label and event args agree
    f = _rank_trace(tmp_path, 0, "step_x", "args")
    assert tm.main([f, "-o", out, "--rank", "3"]) == 0
    merged = json.load(open(out))["traceEvents"]
    sl = [ev for ev in merged if ev.get("ph") == "X"][0]
    assert sl["args"]["rank"] == 3
    labels = [ev["args"]["name"] for ev in merged
              if ev.get("ph") == "M" and ev.get("name") == "process_name"]
    assert "rank 3" in labels


def test_trace_summary_accepts_per_rank_files(tmp_path, capsys):
    ts = _load("trace_summary.py")
    f0 = _rank_trace(tmp_path, 0, "op_shared", "clock_sync")
    f1 = _rank_trace(tmp_path, 1, "op_shared", "filename")
    # merged accounting: one row with both ranks' calls
    assert ts.main([f0, f1]) == 0
    out = capsys.readouterr().out
    assert "ranks 0,1 over 2 file(s)" in out
    import re

    row = [l for l in out.splitlines() if l.startswith("op_shared")]
    assert row and re.search(r"\s2\s", row[0]), row  # 2 calls merged
    # --per-rank keeps them apart
    assert ts.main([f0, f1, "--per-rank"]) == 0
    out = capsys.readouterr().out
    assert any(l.startswith("r0/op_shared") for l in out.splitlines())
    assert any(l.startswith("r1/op_shared") for l in out.splitlines())
