"""Tooling ports (reference ``tools/``): parse_log markdown tables,
rec2idx index reconstruction, kill-mxnet command construction,
diagnose report."""
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
TOOLS = os.path.join(REPO, "tools")


def _load(fname):
    from mxnet_tpu.test_utils import load_module_by_path

    return load_module_by_path(os.path.join(TOOLS, fname))


def test_parse_log_markdown():
    pl = _load("parse_log.py")
    lines = [
        "INFO:root:Epoch[0] Train-accuracy=0.5",
        "INFO:root:Epoch[0] Validation-accuracy=0.4",
        "INFO:root:Epoch[0] Time cost=1.5",
        "INFO:root:Epoch[1] Train-accuracy=0.8",
        "noise line",
    ]
    d = pl.parse(lines)
    assert d[0] == [0.5, 0.4, 1.5]
    assert d[1][0] == 0.8
    md = pl.to_markdown(d)
    assert md.splitlines()[0].startswith("| epoch |")


def test_rec2idx_roundtrip(tmp_path):
    from mxnet_tpu import recordio

    rec = str(tmp_path / "a.rec")
    w = recordio.MXRecordIO(rec, "w")
    for i in range(7):
        w.write(b"payload%d" % i)
    w.close()
    r2i = _load("rec2idx.py")
    assert r2i.create_index(rec, rec + ".idx") == 7
    r = recordio.MXIndexedRecordIO(rec + ".idx", rec, "r")
    assert r.read_idx(5) == b"payload5"
    r.close()


def test_kill_mxnet_command():
    km = _load("kill-mxnet.py")
    cmd = km.kill_command("bob", "train.py")
    # shlex-quoted fixed-string grep (round-4 hardening): metachars inert
    assert "grep -F -- train.py" in cmd and "u=bob" in cmd and "kill -9" in cmd
    import shlex
    hostile = "x'; rm -rf /; '"
    assert shlex.quote(hostile) in km.kill_command("bob", hostile)


def test_diagnose_runs():
    res = subprocess.run([sys.executable, os.path.join(TOOLS, "diagnose.py")],
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr[-500:]
    assert "Framework Info" in res.stdout
    assert "jax" in res.stdout
