"""Tutorial companion scripts (docs/tutorials/ — VERDICT round-3 item 9)
run end-to-end in the nightly tier: the code the docs show is code that
works."""
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.mark.parametrize("script,marker", [
    ("finetune.py", "FINETUNE TUTORIAL OK"),
    ("bucketing.py", "BUCKETING TUTORIAL OK"),
    ("multi_devices.py", "MULTI-DEVICES TUTORIAL OK"),
    ("new_op.py", "NEW-OP TUTORIAL OK"),
    ("gluon_intro.py", "GLUON-INTRO TUTORIAL OK"),
    ("perf_tuning.py", "PERF-TUNING TUTORIAL OK"),
    ("sparse_howto.py", "SPARSE TUTORIAL OK"),
    ("recordio_pipeline.py", "RECORDIO TUTORIAL OK"),
    ("int8_workflow.py", "INT8 TUTORIAL OK"),
    ("profiler_howto.py", "PROFILER TUTORIAL OK"),
])
def test_tutorial_script(script, marker):
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "tutorials", script)],
        capture_output=True, text=True, timeout=1800)
    tail = "\n".join(res.stdout.splitlines()[-8:]) + res.stderr[-2000:]
    assert res.returncode == 0, "%s failed:\n%s" % (script, tail)
    assert marker in res.stdout, tail
