"""Inference quality plane tests (ISSUE 16) —
``mxnet_tpu/telemetry/qualityplane.py``: env parsing, the systematic
shadow sampler, the divergence math and violation edge, the windowed
drift sketch + per-site drift accounting, output-distribution
accumulators, the bounded ring, the off-path no-op contract, and the
engine-level shadow-sampling end-to-end path."""
import math
import threading
import time

import numpy as np
import pytest

from mxnet_tpu.serving import BucketLadder, Engine
from mxnet_tpu.telemetry import qualityplane


def _mlp_engine(**kw):
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint()
    kw.setdefault("ladder", BucketLadder((1, 2)))
    kw.setdefault("max_wait_ms", 2.0)
    kw.setdefault("max_queue", 64)
    kw.setdefault("name", "qualplane")
    return Engine(sym, params, {"data": (8,)}, **kw)


@pytest.fixture
def quality_off(monkeypatch):
    """The zero-overhead off path: every ISSUE 16 gate unset."""
    for var in ("MXNET_QUALITYPLANE", "MXNET_QUALITY_SAMPLE",
                "MXNET_QUALITY_DRIFT", "MXNET_QUALITY_RING"):
        monkeypatch.delenv(var, raising=False)
    qualityplane._reset_for_tests()
    yield
    qualityplane._reset_for_tests()


@pytest.fixture
def quality_on(monkeypatch):
    monkeypatch.setenv("MXNET_QUALITYPLANE", "1")
    monkeypatch.setenv("MXNET_QUALITY_SAMPLE", "1.0")
    for var in ("MXNET_QUALITY_DRIFT", "MXNET_QUALITY_RING"):
        monkeypatch.delenv(var, raising=False)
    qualityplane._reset_for_tests()
    yield
    qualityplane._reset_for_tests()


# -- env parsing --------------------------------------------------------------
class TestEnvParsing:
    def test_sample_rate(self, monkeypatch):
        monkeypatch.delenv("MXNET_QUALITY_SAMPLE", raising=False)
        assert qualityplane.sample_rate() == 0.1
        monkeypatch.setenv("MXNET_QUALITY_SAMPLE", "0.25")
        assert qualityplane.sample_rate() == 0.25
        monkeypatch.setenv("MXNET_QUALITY_SAMPLE", "7")
        assert qualityplane.sample_rate() == 1.0   # clamped
        monkeypatch.setenv("MXNET_QUALITY_SAMPLE", "-3")
        assert qualityplane.sample_rate() == 0.0
        monkeypatch.setenv("MXNET_QUALITY_SAMPLE", "lots")
        assert qualityplane.sample_rate() == 0.1   # malformed: default

    def test_drift_threshold(self, monkeypatch):
        monkeypatch.delenv("MXNET_QUALITY_DRIFT", raising=False)
        assert qualityplane.drift_threshold() == 1.5
        monkeypatch.setenv("MXNET_QUALITY_DRIFT", "3.0")
        assert qualityplane.drift_threshold() == 3.0
        # a ratio gate at or below 1.0 would trip on in-envelope traffic
        monkeypatch.setenv("MXNET_QUALITY_DRIFT", "0.5")
        assert qualityplane.drift_threshold() == 1.5
        monkeypatch.setenv("MXNET_QUALITY_DRIFT", "nope")
        assert qualityplane.drift_threshold() == 1.5

    def test_ring_cap(self, monkeypatch):
        monkeypatch.delenv("MXNET_QUALITY_RING", raising=False)
        assert qualityplane.ring_cap() == 256
        monkeypatch.setenv("MXNET_QUALITY_RING", "8")
        assert qualityplane.ring_cap() == 8
        monkeypatch.setenv("MXNET_QUALITY_RING", "-1")
        assert qualityplane.ring_cap() == 256
        monkeypatch.setenv("MXNET_QUALITY_RING", "many")
        assert qualityplane.ring_cap() == 256


# -- systematic sampler -------------------------------------------------------
class TestSampler:
    def test_floor_rule_even_spacing(self, monkeypatch):
        monkeypatch.setenv("MXNET_QUALITY_SAMPLE", "0.25")
        p = qualityplane.QualityPlane()
        takes = [p.should_sample() for _ in range(100)]
        assert sum(takes) == 25
        # floor(n*r) advances exactly at every 4th request: deterministic,
        # evenly spaced — not a coin flip
        assert takes == [(i + 1) % 4 == 0 for i in range(100)]
        st = p.status()
        assert st["seen"] == 100 and st["sampled"] == 25
        # reproducible across identical streams
        p2 = qualityplane.QualityPlane()
        assert [p2.should_sample() for _ in range(100)] == takes

    def test_rate_edges(self, monkeypatch):
        monkeypatch.setenv("MXNET_QUALITY_SAMPLE", "0")
        p = qualityplane.QualityPlane()
        assert not any(p.should_sample() for _ in range(50))
        monkeypatch.setenv("MXNET_QUALITY_SAMPLE", "1.0")
        p = qualityplane.QualityPlane()
        assert all(p.should_sample() for _ in range(50))

    def test_note_shed(self, quality_off):
        p = qualityplane.QualityPlane()
        p.note_shed(3)
        p.note_shed()
        assert p.status()["shed"] == 4


# -- divergence math ----------------------------------------------------------
TOL = {"atol": 0.5, "rtol": 0.0}  # denom == 0.5 everywhere: exact fracs


class TestCompareOutputs:
    def test_exact_fracs(self):
        ref = [np.zeros((2, 3), np.float32)]
        live = [np.full((2, 3), 0.25, np.float32)]
        row = qualityplane.compare_outputs(live, ref, TOL)
        assert row["max_abs"] == pytest.approx(0.25)
        assert row["contract_frac"] == pytest.approx(0.5)
        assert row["head"] == 0

    def test_rtol_term(self):
        ref = [np.array([10.0], np.float64)]
        live = [np.array([10.2], np.float64)]
        row = qualityplane.compare_outputs(
            live, ref, {"atol": 0.0, "rtol": 0.01})
        # |a-b| / (rtol*|b|) = 0.2 / 0.1
        assert row["contract_frac"] == pytest.approx(2.0)

    def test_top1_agreement_classification_heads_only(self):
        ref = [np.array([[0.1, 0.9], [0.8, 0.2]], np.float32)]
        live = [np.array([[0.1, 0.9], [0.2, 0.8]], np.float32)]  # row 1 flips
        row = qualityplane.compare_outputs(live, ref, TOL)
        assert row["top1_agree"] == pytest.approx(0.5)
        # 1-D head: argmax agreement is not defined
        row = qualityplane.compare_outputs(
            [np.zeros(4)], [np.zeros(4)], TOL)
        assert row["top1_agree"] is None

    def test_worst_head_wins(self):
        ref = [np.zeros(2), np.zeros(2)]
        live = [np.full(2, 0.1), np.full(2, 0.4)]
        row = qualityplane.compare_outputs(live, ref, TOL)
        assert row["head"] == 1
        assert row["contract_frac"] == pytest.approx(0.8)
        assert [h["head"] for h in row["heads"]] == [0, 1]

    def test_degenerate_heads(self):
        # shape mismatch and empty heads score zero instead of crashing
        row = qualityplane.compare_outputs(
            [np.zeros((2, 2)), np.zeros(0)],
            [np.zeros((3, 2)), np.zeros(0)], TOL)
        assert row["max_abs"] == 0.0 and row["contract_frac"] == 0.0
        row = qualityplane.compare_outputs([], [], TOL)
        assert row["head"] is None and row["heads"] == []

    def test_nonfinite_divergence_is_infinite_frac(self):
        row = qualityplane.compare_outputs(
            [np.array([np.nan])], [np.zeros(1)], TOL)
        assert not math.isfinite(row["contract_frac"])


class TestRecordDivergence:
    def test_violation_edge_is_strictly_above_one(self, quality_off):
        p = qualityplane.QualityPlane()
        # frac exactly 1.0: at the contract boundary, NOT a violation
        e = p.record_divergence("bf16", "b1", [np.array([0.5])],
                                [np.zeros(1)], TOL)
        assert e["contract_frac"] == pytest.approx(1.0)
        assert e["violation"] is False
        e = p.record_divergence("bf16", "b1", [np.array([0.51])],
                                [np.zeros(1)], TOL)
        assert e["violation"] is True
        # non-finite divergence (NaN output) always violates
        e = p.record_divergence("bf16", "b1", [np.array([np.nan])],
                                [np.zeros(1)], TOL)
        assert e["violation"] is True and e["contract_frac"] is None
        st = p.status()
        assert st["violations"] == 2
        assert st["divergence"]["bf16"]["n"] == 3
        assert st["divergence"]["bf16"]["violations"] == 2

    def test_sketch_quantiles_and_ring(self, quality_off):
        p = qualityplane.QualityPlane(cap=4)
        for _ in range(9):
            p.record_divergence("bf16", "b1", [np.array([0.005])],
                                [np.zeros(1)], TOL)  # frac 0.01
        p.record_divergence("bf16", "b2", [np.array([0.4])],
                            [np.zeros(1)], TOL)      # frac 0.8
        s = p.divergence_summary()["bf16"]
        assert s["n"] == 10 and s["violations"] == 0
        assert s["p99"] >= s["p50"] > 0
        # p50 sits in the 0.01 body, p99 reaches the 0.8 tail (log-bucket
        # quantization: within one GAMMA=2 octave)
        assert s["p50"] <= 0.04 and s["p99"] >= 0.4
        # ring is bounded and keeps the newest rows
        rows = p.rows()
        assert len(rows) == 4 and p.status()["rows"] == 4
        assert rows[-1]["bucket"] == "b2"
        assert all(r["tier"] == "bf16" for r in rows)


# -- drift sketch / per-site drift --------------------------------------------
class TestRangeSketch:
    def test_merge_and_window(self):
        s = qualityplane.RangeSketch(window_s=60.0)  # sub-window = 10 s
        assert s.range(now=0.0) is None
        s.observe(-1.0, 1.0, now=0.0)
        s.observe(-2.0, 3.0, now=5.0)   # same sub-window: merges
        assert s.range(now=5.0) == (-2.0, 3.0)
        s.observe(0.0, 0.5, now=35.0)
        assert s.range(now=35.0) == (-2.0, 3.0)
        # the t=0 spike ages out once its epoch leaves the window; the
        # t=35 observation survives
        assert s.range(now=65.0) == (0.0, 0.5)
        # fully past the window: empty again
        assert s.range(now=300.0) is None

    def test_memory_bound(self):
        s = qualityplane.RangeSketch(window_s=60.0)
        for t in range(500):
            s.observe(-1.0, 1.0, now=float(t))
        assert len(s._subs) <= qualityplane.NSUB + 1


class TestDrift:
    SITES = {"conv0_q": {"input": "data", "lo": -1.0, "hi": 1.0,
                         "a_scale": 1.0 / 127.0}}

    def test_observe_site_against_baseline(self, quality_off):
        p = qualityplane.QualityPlane()
        p.set_drift_baseline(self.SITES)
        assert p.drift_sites() == {"conv0_q": "data"}
        # live traffic inside the calibrated envelope: no trip.  (Real
        # monotonic `now` throughout: status() reads the sketch at the
        # current time, so synthetic epochs would look expired.)
        assert p.observe_site("conv0_q", -0.5, 0.9) is False
        d = p.status()["drift"]["conv0_q"]
        assert d["ratio"] == pytest.approx(0.9) and d["trips"] == 0
        assert d["calib"] == [-1.0, 1.0] and d["live"] == [-0.5, 0.9]
        # 5x hotter than calibration: past the 1.5x default threshold
        assert p.observe_site("conv0_q", -0.2, 5.0) is True
        d = p.status()["drift"]["conv0_q"]
        assert d["ratio"] == pytest.approx(5.0) and d["trips"] == 1
        # unknown site: ignored, never trips
        assert p.observe_site("nope", 0.0, 99.0) is False

    def test_threshold_from_env(self, monkeypatch):
        monkeypatch.setenv("MXNET_QUALITY_DRIFT", "10.0")
        p = qualityplane.QualityPlane()
        p.set_drift_baseline(self.SITES)
        assert p.observe_site("conv0_q", -5.0, 5.0) is False
        assert p.observe_site("conv0_q", -11.0, 11.0) is True

    def test_rebaseline_resets_live_state(self, quality_off):
        p = qualityplane.QualityPlane()
        p.set_drift_baseline(self.SITES)
        assert p.observe_site("conv0_q", -9.0, 9.0) is True
        # a re-calibrated twin re-anchors: new calib range, fresh sketch,
        # trip count reset — the comparison follows the NEW table
        p.set_drift_baseline({"conv0_q": {"input": "data", "lo": -10.0,
                                          "hi": 10.0, "a_scale": 10 / 127.0}})
        d = p.status()["drift"]["conv0_q"]
        assert d["calib"] == [-10.0, 10.0]
        assert d["live"] is None and d["ratio"] is None and d["trips"] == 0
        assert p.observe_site("conv0_q", -9.0, 9.0) is False


# -- output-distribution accumulators ----------------------------------------
class TestOutputStats:
    def test_streaming_merge(self, quality_off):
        p = qualityplane.QualityPlane()
        p.note_outputs("bf16", [np.array([1.0, 3.0], np.float32)])
        p.note_outputs("bf16", [np.array([5.0, 7.0], np.float32)])
        o = p.status()["outputs"]["bf16"]["0"]
        assert o["n"] == 4 and o["mean"] == pytest.approx(4.0)
        assert o["std"] == pytest.approx(np.std([1.0, 3.0, 5.0, 7.0]))
        assert o["min"] == 1.0 and o["max"] == 7.0

    def test_non_float_and_empty_heads_skipped(self, quality_off):
        p = qualityplane.QualityPlane()
        p.note_outputs(None, [np.array([1, 2], np.int32),
                              np.zeros(0, np.float32)])
        assert p.status()["outputs"] is None
        # tier None folds under the fp32 label
        p.note_outputs(None, [np.ones(2, np.float32)])
        assert set(p.status()["outputs"]) == {"fp32"}


# -- off path -----------------------------------------------------------------
class TestOffPath:
    def test_gate_off_no_plane(self, quality_off):
        assert qualityplane.enabled() is False
        assert qualityplane.plane() is None
        assert qualityplane.status() is None

    def test_gate_off_engine_is_noop(self, quality_off):
        eng = _mlp_engine()
        try:
            assert eng._quality is None
            assert not hasattr(eng, "_quality_q")
            eng.predict({"data": np.zeros((1, 8), np.float32)})
            assert eng.stats()["quality"] is None
            assert not [t for t in threading.enumerate()
                        if t.name.startswith("mxnet-quality")]
        finally:
            eng.close()
        assert qualityplane.status() is None  # nothing leaked a plane

    def test_gate_is_runtime_only_no_key_or_plan_shift(self, quality_off,
                                                       monkeypatch):
        # the plane is pure observation: flipping the gate must not move
        # the executor's plan or AOT key parts (the byte-identical
        # contract; ci/check_quality_plane.py proves it on the full
        # lowered jaxpr)
        eng = _mlp_engine(start=False)
        try:
            exe = eng._proto._exec
            plan_off = exe._opt_plan(False)
            parts_off = exe._tier_key_parts(False)
            monkeypatch.setenv("MXNET_QUALITYPLANE", "1")
            qualityplane._reset_for_tests()
            assert exe._opt_plan(False) is plan_off
            assert exe._tier_key_parts(False) == parts_off
        finally:
            eng.close()


# -- engine end-to-end --------------------------------------------------------
class TestEngineShadowSampling:
    def test_bf16_twin_shadow_divergence(self, quality_on):
        eng = _mlp_engine(name="qual-e2e")
        try:
            eng._proto._exec.set_precision_tier("bf16")
            eng.warmup()
            # satellite: per-bucket tier map + warmup rows carry the tier
            st = eng.stats()
            assert st["precision_tiers"] and \
                set(st["precision_tiers"].values()) == {"bf16"}
            assert st["precision_tier"] == "bf16"
            for _ in range(6):
                eng.predict({"data": np.random.RandomState(0)
                             .rand(1, 8).astype(np.float32)})
            # rate 1.0: every request is queued for shadow replay; the
            # worker runs at lower priority — poll for the verdicts
            deadline = time.monotonic() + 60.0
            q = qualityplane.status()
            while time.monotonic() < deadline and not (
                    q and q["rows"] and q["divergence"]):
                time.sleep(0.05)
                q = qualityplane.status()
            assert q["divergence"] and "bf16" in q["divergence"]
            assert q["sampled"] >= 1
            # the engine's stats surface is the same plane
            sq = eng.stats()["quality"]
            assert sq is not None and sq["seen"] == q["seen"]
            # per-tier output stats accumulate on the live path, shadow
            # or not
            assert q["outputs"] and "bf16" in q["outputs"]
        finally:
            eng.close()
        # close() joins the shadow thread: verdicts are final.  A bf16
        # MLP on fp32-computed CPU ops sits far inside its tolerance
        # contract — zero violations, all rows in-contract.
        q = qualityplane.status()
        assert q["violations"] == 0
        for row in qualityplane.plane().rows():
            assert row["tier"] == "bf16" and row["violation"] is False
            assert row["contract_frac"] is not None \
                and row["contract_frac"] <= 1.0
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("mxnet-quality")]

    def test_fp32_engine_never_samples(self, quality_on):
        eng = _mlp_engine(name="qual-fp32")
        try:
            for _ in range(3):
                eng.predict({"data": np.zeros((1, 8), np.float32)})
            q = qualityplane.status()
            # nothing to diverge from: no sampling, no shadow thread —
            # only the output-distribution stats accumulate
            assert q["seen"] == 0 and q["sampled"] == 0
            assert q["divergence"] is None
            assert q["outputs"] and "fp32" in q["outputs"]
            assert getattr(eng, "_quality_thread", None) is None
        finally:
            eng.close()
