"""Facade modules: engine, profiler, monitor, visualization, name/attribute,
executor_manager (reference test models: tests/python/unittest/test_profiler.py,
test_engine.py-style checks)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def test_engine_bulk_and_wait():
    from mxnet_tpu import engine

    assert engine.engine_type() in ("ThreadedEnginePerDevice", "NaiveEngine")
    old = engine.set_bulk_size(4)
    with engine.bulk(8):
        pass
    engine.set_bulk_size(old)
    a = mx.nd.ones((4, 4))
    b = a * 2
    engine.wait_all()
    assert b.asnumpy().sum() == 32


def test_naive_engine_toggle():
    from mxnet_tpu import engine

    engine.naive_engine(True)
    try:
        assert engine.is_naive()
        x = mx.nd.ones((2, 2)) + 1
        assert x.asnumpy().sum() == 8
    finally:
        engine.naive_engine(False)
    assert not engine.is_naive()


def test_profiler_chrome_trace(tmp_path):
    from mxnet_tpu import profiler

    fname = str(tmp_path / "profile.json")
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    dom = profiler.Domain("testdomain")
    task = dom.new_task("mytask")
    with task:
        mx.nd.ones((8, 8)).asnumpy()
    ctr = dom.new_counter("loss", 10)
    ctr.increment(5)
    dom.new_marker("epoch_end").mark()
    profiler.pause()
    with dom.new_task("hidden"):
        pass
    profiler.resume()
    profiler.set_state("stop")
    profiler.dump()
    data = json.load(open(fname))
    names = [e.get("name") for e in data["traceEvents"]]
    assert "mytask" in names
    assert "loss" in names
    assert "epoch_end" in names
    assert "hidden" not in names


def test_monitor_collects_stats():
    from mxnet_tpu.monitor import Monitor

    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    exe = fc.simple_bind(data=(2, 4))
    mon = Monitor(1, sort=True)
    mon.install(exe)
    mon.tic()
    exe.forward(is_train=False, data=mx.nd.ones((2, 4)))
    res = mon.toc()
    assert any("fc_output" in k for _, k, _ in res)


def test_print_summary_param_count(capsys):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=10, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=2, name="fc2")
    total = mx.viz.print_summary(fc2, shape={"data": (1, 5)})
    # fc1: 5*10+10 = 60, fc2: 10*2+2 = 22
    assert total == 82
    out = capsys.readouterr().out
    assert "fc1" in out and "fc2" in out


def test_name_and_attribute_paths():
    from mxnet_tpu.name import NameManager, Prefix
    from mxnet_tpu.attribute import AttrScope

    with Prefix("pre_"):
        s = mx.sym.Variable("x")
        fc = mx.sym.FullyConnected(s, num_hidden=2)
        assert fc.name.startswith("pre_")
    with AttrScope(ctx_group="dev1"):
        v = mx.sym.Variable("y")


def test_split_input_slice():
    from mxnet_tpu.executor_manager import _split_input_slice

    slices = _split_input_slice(10, [1, 1])
    assert slices == [slice(0, 5), slice(5, 10)]
    slices = _split_input_slice(9, [2, 1])
    assert slices[0] == slice(0, 6)


class TestProfilerOpEvents:
    def test_chrome_trace_records_operators(self, tmp_path):
        import json
        import mxnet_tpu as mx
        from mxnet_tpu import nd

        fn = str(tmp_path / "profile.json")
        mx.profiler.set_config(profile_all=True, filename=fn)
        mx.profiler.set_state("run")
        x = nd.ones((32, 32))
        x = nd.dot(x, x)
        x = nd.relu(x)
        x.wait_to_read()
        mx.profiler.set_state("stop")
        mx.profiler.dump()
        j = json.load(open(fn))
        names = [e["name"] for e in j["traceEvents"]]
        assert "dot" in names and "relu" in names
        # duration events carry the chrome-trace complete-event fields
        ev = next(e for e in j["traceEvents"] if e["name"] == "dot")
        assert ev["ph"] == "X" and ev["dur"] >= 0 and ev["cat"] == "operator"

    def test_pause_resume(self, tmp_path):
        import json
        import mxnet_tpu as mx
        from mxnet_tpu import nd

        fn = str(tmp_path / "p2.json")
        mx.profiler.set_config(profile_all=True, filename=fn)
        mx.profiler.set_state("run")
        mx.profiler.pause()
        nd.tanh(nd.ones((4, 4))).wait_to_read()
        mx.profiler.resume()
        nd.sigmoid(nd.ones((4, 4))).wait_to_read()
        mx.profiler.set_state("stop")
        mx.profiler.dump()
        names = [e["name"] for e in json.load(open(fn))["traceEvents"]]
        assert "sigmoid" in names and "tanh" not in names
