"""NDArray basics — modeled on reference tests/python/unittest/test_ndarray.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, same


def test_creation():
    a = nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert a.asnumpy().sum() == 0
    b = nd.ones((2, 3), dtype="int32")
    assert b.dtype == np.int32
    c = nd.full((2, 2), 7.0)
    assert (c.asnumpy() == 7).all()
    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    e = nd.arange(0, 10, 2)
    assert same(e, np.arange(0, 10, 2).astype(np.float32))


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert_almost_equal(a + b, np.array([[6, 8], [10, 12]]))
    assert_almost_equal(a - b, np.array([[-4, -4], [-4, -4]]))
    assert_almost_equal(a * b, np.array([[5, 12], [21, 32]]))
    assert_almost_equal(b / a, np.array([[5, 3], [7 / 3, 2]]))
    assert_almost_equal(a + 1, np.array([[2, 3], [4, 5]]))
    assert_almost_equal(1 - a, np.array([[0, -1], [-2, -3]]))
    assert_almost_equal(2 / a, 2 / a.asnumpy())
    assert_almost_equal(a**2, a.asnumpy() ** 2)
    assert_almost_equal(-a, -a.asnumpy())


def test_inplace():
    a = nd.ones((2, 2))
    a += 2
    assert (a.asnumpy() == 3).all()
    a *= 2
    assert (a.asnumpy() == 6).all()
    a /= 3
    assert (a.asnumpy() == 2).all()


def test_comparisons():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([3.0, 2.0, 1.0])
    assert same(a == b, np.array([0, 1, 0], dtype=np.float32))
    assert same(a > b, np.array([0, 0, 1], dtype=np.float32))
    assert same(a <= b, np.array([1, 1, 0], dtype=np.float32))


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a[1].shape == (3, 4)
    assert a[1, 2].shape == (4,)
    assert float(a[1, 2, 3].asscalar()) == 23
    assert a[:, 1:3].shape == (2, 2, 4)
    a[0] = 0
    assert a.asnumpy()[0].sum() == 0
    a[1, 2] = 9
    assert (a.asnumpy()[1, 2] == 9).all()


def test_setitem_array():
    a = nd.zeros((3, 3))
    a[1] = nd.array([1.0, 2.0, 3.0])
    assert same(a[1], np.array([1, 2, 3], dtype=np.float32))


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)
    assert a.reshape(2, 12).shape == (2, 12)


def test_transpose_and_dot():
    a = nd.array(np.random.rand(3, 4))
    b = nd.array(np.random.rand(4, 5))
    c = nd.dot(a, b)
    assert_almost_equal(c, np.dot(a.asnumpy(), b.asnumpy()), rtol=1e-5, atol=1e-5)
    assert a.T.shape == (4, 3)
    d = nd.dot(a, b.T, transpose_b=True)
    assert_almost_equal(d, np.dot(a.asnumpy(), b.asnumpy()), rtol=1e-5, atol=1e-5)


def test_reductions():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    a = nd.array(x)
    assert_almost_equal(a.sum(), x.sum(), rtol=1e-5, atol=1e-5)
    assert_almost_equal(a.sum(axis=1), x.sum(axis=1), rtol=1e-5, atol=1e-5)
    assert_almost_equal(nd.sum(a, axis=(0, 2)), x.sum(axis=(0, 2)), rtol=1e-5, atol=1e-5)
    assert_almost_equal(nd.sum(a, axis=1, exclude=True), x.sum(axis=(0, 2)), rtol=1e-5, atol=1e-5)
    assert_almost_equal(a.mean(axis=2, keepdims=True), x.mean(axis=2, keepdims=True), rtol=1e-5, atol=1e-5)
    assert_almost_equal(a.max(), x.max())
    assert_almost_equal(a.min(axis=0), x.min(axis=0))
    assert_almost_equal(nd.norm(a), np.sqrt((x**2).sum()), rtol=1e-5, atol=1e-5)


def test_broadcast_ops():
    a = nd.array(np.random.rand(2, 1, 4).astype(np.float32))
    b = nd.array(np.random.rand(1, 3, 1).astype(np.float32))
    assert nd.broadcast_add(a, b).shape == (2, 3, 4)
    assert nd.broadcast_mul(a, b).shape == (2, 3, 4)
    c = nd.broadcast_to(nd.array([[1.0], [2.0]]), shape=(2, 3))
    assert same(c, np.array([[1, 1, 1], [2, 2, 2]], dtype=np.float32))


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.Concat(a, b, dim=0)
    assert c.shape == (4, 3)
    c2 = nd.concat(a, b, dim=1)
    assert c2.shape == (2, 6)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_unary_math():
    x = np.random.rand(5).astype(np.float32) + 0.5
    a = nd.array(x)
    assert_almost_equal(nd.sqrt(a), np.sqrt(x), rtol=1e-5, atol=1e-6)
    assert_almost_equal(nd.exp(a), np.exp(x), rtol=1e-5, atol=1e-6)
    assert_almost_equal(nd.log(a), np.log(x), rtol=1e-5, atol=1e-6)
    assert_almost_equal(nd.tanh(a), np.tanh(x), rtol=1e-5, atol=1e-6)
    assert_almost_equal(nd.sigmoid(a), 1 / (1 + np.exp(-x)), rtol=1e-5, atol=1e-6)
    assert_almost_equal(nd.relu(nd.array([-1.0, 1.0])), np.array([0, 1], dtype=np.float32))
    assert_almost_equal(nd.clip(a, a_min=0.6, a_max=1.0), np.clip(x, 0.6, 1.0))


def test_take_embedding_onehot():
    w = nd.array(np.arange(12).reshape(4, 3).astype(np.float32))
    idx = nd.array([0, 2], dtype="int32")
    out = nd.take(w, idx)
    assert same(out, w.asnumpy()[[0, 2]])
    emb = nd.Embedding(idx, w, input_dim=4, output_dim=3)
    assert same(emb, w.asnumpy()[[0, 2]])
    oh = nd.one_hot(idx, depth=4)
    assert same(oh, np.eye(4, dtype=np.float32)[[0, 2]])


def test_topk_sort():
    x = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], dtype=np.float32)
    a = nd.array(x)
    v = nd.topk(a, k=2, ret_typ="value")
    assert same(v, np.array([[3, 2], [5, 4]], dtype=np.float32))
    s = nd.sort(a, axis=1)
    assert same(s, np.sort(x, axis=1))
    ags = nd.argsort(a, axis=1)
    assert same(ags, np.argsort(x, axis=1).astype(np.float32))
    assert same(nd.argmax(a, axis=1), np.array([0, 1], dtype=np.float32))


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrs.npz")
    d = {"w": nd.ones((2, 2)), "b": nd.zeros((3,))}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded.keys()) == {"w", "b"}
    assert same(loaded["w"], d["w"].asnumpy())


def test_random_ops():
    mx.random.seed(42)
    a = nd.random.uniform(low=0, high=1, shape=(100,))
    assert a.shape == (100,)
    assert 0 <= float(a.min().asscalar()) and float(a.max().asscalar()) <= 1
    mx.random.seed(42)
    b = nd.random.uniform(low=0, high=1, shape=(100,))
    assert same(a, b)  # determinism under seeding
    n = nd.random.normal(loc=5.0, scale=0.1, shape=(1000,))
    assert abs(float(n.mean().asscalar()) - 5.0) < 0.1


def test_cast_and_dtype():
    a = nd.ones((2, 2), dtype="float32")
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = nd.cast(a, dtype="float16")
    assert c.dtype == np.float16
    bf = a.astype("bfloat16")
    assert "bfloat16" in str(bf.dtype)


def test_context():
    a = nd.zeros((2, 2), ctx=mx.cpu(0))
    assert a.context.device_type == "cpu"
    b = a.as_in_context(mx.cpu(0))
    assert b.context == mx.cpu(0)


def test_where_pick():
    cond = nd.array([1.0, 0.0, 1.0])
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([4.0, 5.0, 6.0])
    assert same(nd.where(cond, x, y), np.array([1, 5, 3], dtype=np.float32))
    data = nd.array([[1.0, 2.0], [3.0, 4.0]])
    idx = nd.array([0, 1])
    assert same(nd.pick(data, idx, axis=1), np.array([1, 4], dtype=np.float32))
