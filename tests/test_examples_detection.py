"""Smoke tests for the detection example models (reference example/ssd,
example/rcnn — SURVEY §2.4 required end-to-end capability)."""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon

_EX = os.path.join(os.path.dirname(__file__), "..", "examples")


def _load(name, path):
    from conftest import load_example_module

    return load_example_module(name, path)


@pytest.fixture(scope="module")
def ssd_mod():
    return _load("ssd_example", os.path.join(_EX, "ssd", "ssd.py"))


@pytest.fixture(scope="module")
def ssd_train_mod(ssd_mod):
    sys.path.insert(0, os.path.join(_EX, "ssd"))
    return _load("ssd_train_example", os.path.join(_EX, "ssd", "train.py"))


@pytest.fixture(scope="module")
def rcnn_mod():
    sys.path.insert(0, os.path.join(_EX, "rcnn"))
    return _load("rcnn_example", os.path.join(_EX, "rcnn", "faster_rcnn.py"))


def test_ssd_forward_shapes(ssd_mod):
    net = ssd_mod.SSD(num_classes=3, num_scales=3)
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.rand(2, 3, 64, 64).astype(np.float32))
    anchors, cls_preds, box_preds = net(x)
    A = anchors.shape[1]
    assert anchors.shape == (1, A, 4)
    assert cls_preds.shape == (2, A, 4)
    assert box_preds.shape == (2, A * 4)


def test_ssd_train_step_decreases_loss(ssd_mod, ssd_train_mod):
    net = ssd_mod.SSD(num_classes=2, num_scales=3)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.2, "momentum": 0.9})
    loss_fn = ssd_mod.SSDLoss()
    losses = []
    for i in range(4):
        batches = ssd_train_mod.synthetic_batches(4, (3, 64, 64), 2, 2, seed=i)
        tot = n = 0
        for data, labels in batches:
            with autograd.record():
                anchors, cls_preds, box_preds = net(data)
                bt, bm, ct = ssd_mod.training_targets(anchors, cls_preds, labels)
                loss = loss_fn(cls_preds, box_preds, ct, bt, bm)
            loss.backward()
            trainer.step(4)
            tot += float(loss.asnumpy())
            n += 1
        losses.append(tot / n)
    assert losses[-1] < losses[0]


def test_ssd_detect_output(ssd_mod):
    net = ssd_mod.SSD(num_classes=2, num_scales=3)
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.rand(1, 3, 64, 64).astype(np.float32))
    dets = ssd_mod.detect(net, x, threshold=0.0)
    assert dets.shape[0] == 1 and dets.shape[2] == 6
    d = dets.asnumpy()[0]
    valid = d[d[:, 0] >= 0]
    assert (valid[:, 1] >= 0).all() and (valid[:, 1] <= 1).all()


def test_ssd_map_metric():
    metric = _load("ssd_metric_example", os.path.join(_EX, "ssd", "metric.py"))
    m = metric.VOCMApMetric()
    dets = np.array([[[0, 0.9, 0.1, 0.1, 0.5, 0.5], [1, 0.8, 0.6, 0.6, 0.9, 0.9]]])
    labels = np.array([[[0, 0.1, 0.1, 0.5, 0.5], [1, 0.6, 0.6, 0.9, 0.9]]])
    m.update(dets, labels)
    name, val = m.get()
    assert name == "mAP" and val == 1.0
    m.reset()
    # detection matching the wrong class -> AP 0
    dets_bad = np.array([[[1, 0.9, 0.1, 0.1, 0.5, 0.5]]])
    labels2 = np.array([[[0, 0.1, 0.1, 0.5, 0.5]]])
    m.update(dets_bad, labels2)
    assert m.get()[1] == 0.0


def test_rcnn_anchor_target(rcnn_mod):
    rng = np.random.RandomState(0)
    gt = np.array([[0, 8.0, 8.0, 40.0, 40.0], [-1, -1, -1, -1, -1]], np.float32)
    lab, bt, bw = rcnn_mod.assign_anchor((8, 8), gt, (64, 64, 1.0), stride=8, rng=rng)
    assert lab.shape == (8 * 8 * 9,)
    assert set(np.unique(lab)).issubset({-1.0, 0.0, 1.0})
    fg = lab == 1
    assert fg.sum() >= 1
    assert (bw[fg] == 1).all()
    assert np.isfinite(bt).all()


def test_rcnn_end_to_end_loss_decreases(rcnn_mod):
    train = _load("rcnn_train_example", os.path.join(_EX, "rcnn", "train_end2end.py"))
    net = rcnn_mod.FasterRCNN(num_classes=2)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05, "momentum": 0.9})
    rng = np.random.RandomState(0)
    losses = []
    for epoch in range(3):
        tot = n = 0
        for data, im_info, labels in train.synthetic_batches(2, (3, 64, 64), 2, 2, seed=epoch):
            with autograd.record():
                loss, parts = rcnn_mod.rcnn_losses(net, data, im_info, labels, anchor_rng=rng)
            loss.backward()
            trainer.step(2)
            tot += float(loss.asnumpy())
            n += 1
        losses.append(tot / n)
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)


def test_rcnn_inference_path(rcnn_mod):
    net = rcnn_mod.FasterRCNN(num_classes=2)
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.rand(1, 3, 64, 64).astype(np.float32))
    im_info = nd.array(np.array([[64, 64, 1.0]], np.float32))
    rois, cls_score, bbox_pred = net(x, im_info)
    assert rois.shape[1] == 5
    assert cls_score.shape == (rois.shape[0], 3)
    assert bbox_pred.shape == (rois.shape[0], 12)


def test_proposal_target_custom_op(rcnn_mod):
    rois = np.zeros((8, 5), np.float32)
    rois[:, 1:] = np.array([4, 4, 28, 28], np.float32) + np.arange(8)[:, None]
    gt = np.array([[[1, 6.0, 6.0, 30.0, 30.0], [-1, -1, -1, -1, -1]]], np.float32)
    out = nd.Custom(
        nd.array(rois), nd.array(gt), op_type="proposal_target",
        num_classes="3", batch_images="1", batch_rois="8", fg_fraction="0.5",
    )
    rois_out, label, bt, bw = out
    assert rois_out.shape == (8, 5)
    assert label.shape == (8,)
    assert bt.shape == (8, 12) and bw.shape == (8, 12)
    lab = label.asnumpy()
    assert (lab >= 0).all() and (lab <= 2).all()
    # fg rois carry class 2 (gt cls 1 + 1) and nonzero weights in that slot
    fg = np.where(lab == 2)[0]
    assert fg.size > 0
    w = bw.asnumpy()
    assert (w[fg][:, 8:12] == 1).all()
