"""Jit-fused Deformable R-FCN (model_zoo.detection) — the north-star path.

Covers: model build (train + inference forwards), the single-XLA-module
train step (examples/deformable_rfcn/train_fused.py make_rfcn_train_step),
gradient flow into every head, and loss decrease over a few steps.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

EXDIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "examples", "deformable_rfcn"))


def _train_fused():
    # load by unique module name: three example dirs ship a train_fused.py
    # and a bare import races for the sys.modules slot (same fix as
    # test_frcnn_fused.py)
    from mxnet_tpu.test_utils import load_module_by_path

    return load_module_by_path(os.path.join(EXDIR, "train_fused.py"),
                               "_rfcn_train_fused_tests")


def _tiny_net(**kw):
    from mxnet_tpu.gluon.model_zoo.detection import DeformableRFCN

    cfg = dict(classes=3, image_shape=(64, 96), units=(1, 1, 1, 1),
               scales=(1, 2), ratios=(0.5, 1, 2), rpn_pre_nms=200,
               rpn_post_nms=32, batch_rois=16, rpn_batch=32, max_gts=8)
    cfg.update(kw)
    net = DeformableRFCN(**cfg)
    net.initialize()
    return net


def test_model_forward_shapes_train_and_infer():
    mx.random.seed(0)
    net = _tiny_net()
    rng = np.random.RandomState(0)
    B = 2
    x = nd.array(rng.randn(B, 3, 64, 96).astype(np.float32))
    info = nd.array(np.array([[64, 96, 1.0]] * B, np.float32))
    gt = np.full((B, 8, 5), -1.0, np.float32)
    gt[0, 0] = [1, 4, 4, 40, 40]
    gt[1, 0] = [0, 10, 20, 60, 60]
    Hf, Wf = net.feat_shape
    A = net.num_anchors
    nz1 = nd.array(rng.rand(B, Hf * Wf * A, 2).astype(np.float32))
    nz2 = nd.array(rng.rand(B, net.rpn_post_nms + 8, 2).astype(np.float32))
    outs = net(x, info, nd.array(gt), nz1, nz2)
    assert outs[0].shape == (B, 2 * A, Hf, Wf)      # rpn_cls
    assert outs[5].shape == (B * 16, 5)             # sampled rois
    assert outs[9].shape == (B * 16, net.classes + 1)   # cls_score
    assert outs[10].shape == (B * 16, 8)            # class-agnostic deltas
    rois, prob, deltas = net(x, info)               # inference path
    assert rois.shape == (B * net.rpn_post_nms, 5)
    assert prob.shape == (B * net.rpn_post_nms, net.classes + 1)
    np.testing.assert_allclose(prob.asnumpy().sum(-1), 1.0, rtol=1e-4)


def test_fused_step_gradients_reach_every_head():
    import jax

    tf = _train_fused()
    make_rfcn_train_step, synthetic_coco = tf.make_rfcn_train_step, tf.synthetic_coco

    mx.random.seed(1)
    net = _tiny_net()
    rng = np.random.RandomState(1)
    data, im_info, gt = synthetic_coco(rng, 1, (64, 96), 3, net.max_gts)
    net(mx.nd.array(data), mx.nd.array(im_info))  # materialise params

    from mxnet_tpu.gluon.functional import functionalize
    apply, names, vals, aux_names = functionalize(net, train=True)
    aux_set = set(aux_names)
    learn_names = [n for n in names if n not in aux_set]

    step, state = make_rfcn_train_step(net, 1, learning_rate=0.01, momentum=0.9)
    jstep = jax.jit(step)
    new_state, loss, parts = jstep(state, data, im_info, gt, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
    # momentum after one step == gradient; check each head received signal
    grads = {n: np.asarray(g) for n, g in zip(learn_names, new_state[1])}
    got = {k: any(np.abs(v).max() > 0 for n, v in grads.items() if k in n)
           for k in ("rpn_cls", "rpn_bbox", "rfcn_cls", "rfcn_bbox",
                     "rfcn_trans", "conv_new", "res5", "res4", "res3")}
    assert all(got.values()), got
    # frozen trunk: conv1/res2 gradients are exactly zero (BlockGrad)
    frozen = [np.abs(v).max() for n, v in grads.items()
              if ("conv1" in n or "res2_" in n) and "gamma" not in n and "beta" not in n]
    assert frozen and max(frozen) == 0.0


def test_fused_step_trains():
    import jax

    tf = _train_fused()
    make_rfcn_train_step, synthetic_coco = tf.make_rfcn_train_step, tf.synthetic_coco

    mx.random.seed(2)
    net = _tiny_net()
    rng = np.random.RandomState(2)
    data, im_info, gt = synthetic_coco(rng, 1, (64, 96), 3, net.max_gts)
    net(mx.nd.array(data), mx.nd.array(im_info))
    step, state = make_rfcn_train_step(net, 1, learning_rate=0.01, momentum=0.9)
    jstep = jax.jit(step, donate_argnums=(0,))
    key = jax.random.PRNGKey(0)
    losses = []
    for s in range(8):
        data, im_info, gt = synthetic_coco(rng, 1, (64, 96), 3, net.max_gts)
        state, loss, parts = jstep(state, data, im_info, gt, jax.random.fold_in(key, s))
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    # rpn learns fastest on synthetic blobs; total should come down too
    assert np.mean(losses[-3:]) < np.mean(losses[:3]), losses
