"""Pipeline parallelism (parallel/pipeline.py gpipe) — correctness vs the
sequential oracle, and trainability via jax.grad.  Reference had only
non-overlapping per-layer placement (SURVEY §2.2 PP row: absent)."""
import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401 — forces the CPU-mesh conftest
from mxnet_tpu import parallel
from mxnet_tpu.parallel import gpipe, stack_stage_params


def _setup():
    import jax

    n = len(jax.devices())
    mesh = parallel.make_mesh({"pp": n})
    rng = np.random.RandomState(0)
    dim = 16
    stages = [{"w": rng.randn(dim, dim).astype(np.float32) * 0.3,
               "b": rng.randn(dim).astype(np.float32) * 0.1}
              for _ in range(n)]
    return mesh, stages, rng, dim, n


def _stage_fn(p, x):
    import jax

    return jax.nn.tanh(x @ p["w"] + p["b"])


def test_gpipe_matches_sequential():
    import jax
    import jax.numpy as jnp

    mesh, stages, rng, dim, n = _setup()
    M, mb = 4 * n, 3
    xs = rng.randn(M, mb, dim).astype(np.float32)

    stacked = stack_stage_params(stages)
    out = jax.jit(lambda sp, x: gpipe(_stage_fn, sp, x, mesh=mesh))(
        stacked, jnp.asarray(xs))

    ref = xs.copy()
    for p in stages:  # sequential oracle
        ref = np.tanh(ref @ p["w"] + p["b"])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=1e-6)


def test_gpipe_differentiable_and_trains():
    import jax
    import jax.numpy as jnp

    mesh, stages, rng, dim, n = _setup()
    M, mb = 2 * n, 4
    xs = jnp.asarray(rng.randn(M, mb, dim).astype(np.float32))
    tgt = jnp.asarray(rng.randn(M, mb, dim).astype(np.float32) * 0.1)
    stacked = stack_stage_params(stages)

    def loss_fn(sp):
        out = gpipe(_stage_fn, sp, xs, mesh=mesh)
        return jnp.mean((out - tgt) ** 2)

    vg = jax.jit(jax.value_and_grad(loss_fn))
    l0, g = vg(stacked)
    assert all(np.abs(np.asarray(leaf)).max() > 0
               for leaf in jax.tree_util.tree_leaves(g))
    sp = stacked
    for _ in range(25):
        l, g = vg(sp)
        sp = jax.tree_util.tree_map(lambda p, gg: p - 0.3 * gg, sp, g)
    assert float(l) < float(l0) * 0.7, (float(l0), float(l))


def test_gpipe_grad_matches_sequential_grad():
    """d(loss)/d(stage params) equals the unpipelined model's gradient."""
    import jax
    import jax.numpy as jnp

    mesh, stages, rng, dim, n = _setup()
    M, mb = n, 2
    xs = jnp.asarray(rng.randn(M, mb, dim).astype(np.float32))
    stacked = stack_stage_params(stages)

    def pipe_loss(sp):
        return jnp.sum(gpipe(_stage_fn, sp, xs, mesh=mesh) ** 2)

    def seq_loss(sp):
        def body(x, p):
            return _stage_fn(p, x)
        out = xs
        for s in range(n):
            out = _stage_fn(jax.tree_util.tree_map(lambda a: a[s], sp), out)
        return jnp.sum(out ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(stacked)
    g_seq = jax.jit(jax.grad(seq_loss))(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)
