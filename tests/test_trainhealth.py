"""Training health plane (ISSUE 12, telemetry/trainhealth.py).

Coverage demanded by the issue:
- no-op guard: gate off ⇒ no staged stats, no plane, fused jit key and
  output structure byte-identical (the key gains a marker ONLY when on);
- healthy steps report real numbers: the drained global grad norm matches
  a numpy recomputation from the executor's own grad buffers, per-group
  norms/ratios are finite and positive — on the single-device AND the
  mesh fused step;
- a seeded-NaN divergence produces a census blaming the right verdict
  class, fires ``precision_verdict_violations_total`` for blessed classes,
  and dumps the flight recorder naming the first non-finite group;
- ``MXNET_NANCHECK`` trips also dump (the satellite wiring);
- ``Monitor`` routes onto the in-graph stats on a fused Module
  (pattern-filtered), with ``monitor_all=True`` as the un-jitted legacy
  escape hatch;
- a 2-process launch (slow tier) shows rank-tagged samples and a live
  straggler gauge on rank 0.
"""
import glob
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import module as mod_mod
from mxnet_tpu.io import DataBatch
from mxnet_tpu.module import fused_step
from mxnet_tpu.telemetry import flightrec, trainhealth
from mxnet_tpu.telemetry import instrument as tin

BATCH = 8
DIM = 8

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
LAUNCH = os.path.join(REPO, "tools", "launch.py")


@pytest.fixture
def th_env(monkeypatch, tmp_path):
    """MXNET_TRAINHEALTH + telemetry on, fresh global state, cleanup."""
    monkeypatch.setenv("MXNET_TRAINHEALTH", "1")
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    tin._reset_for_tests()
    trainhealth._reset_for_tests()
    flightrec._reset_for_tests()
    yield tmp_path
    tin._reset_for_tests()
    trainhealth._reset_for_tests()
    flightrec._reset_for_tests()


def _sym():
    data = mx.sym.var("data")
    x = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    x = mx.sym.Activation(x, name="relu1", act_type="relu")
    return mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(x, name="fc2", num_hidden=4), name="softmax")


def _module(batch=BATCH, mesh=None):
    mod = mod_mod.Module(_sym(), mesh=mesh)
    mod.bind(data_shapes=[("data", (batch, DIM))],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    return mod


def _batch(rng, batch=BATCH, nan=False):
    x = rng.randn(batch, DIM).astype(np.float32)
    if nan:
        x[0, 0] = np.nan
    return DataBatch(
        data=[mx.nd.array(x)],
        label=[mx.nd.array(rng.randint(0, 4, (batch,)).astype(np.float32))])


def _step(mod, rng, nan=False, batch=BATCH):
    mod.forward_backward(_batch(rng, batch=batch, nan=nan))
    mod.update()


# -- no-op guard --------------------------------------------------------------
def test_noop_guard_trainhealth(monkeypatch, tmp_path):
    """Gate off: no stats staged, no plane, no registry series — and the
    AOT key is byte-identical to pre-trainhealth entries (the marker is
    APPENDED only when on, never a present-but-false flag)."""
    monkeypatch.delenv("MXNET_TRAINHEALTH", raising=False)
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_AOT_CACHE", str(tmp_path / "aot"))
    trainhealth._reset_for_tests()
    rng = np.random.RandomState(0)
    mod = _module()
    _step(mod, rng)
    assert mod._fused is not None
    assert mod._fused._health_groups is None
    assert mod._fused._last_health is None
    assert mod._fused.pop_health() is None
    assert trainhealth.plane() is None
    assert trainhealth.status() is None
    assert mod.trainer_stats() is None
    key_off = mod._fused._aot_key
    assert key_off is not None and "trainhealth" not in key_off
    # flip the gate: the stepper is stale, rebuilds, and the key gains
    # exactly the appended marker
    monkeypatch.setenv("MXNET_TRAINHEALTH", "1")
    assert mod._fused.stale(mod)
    _step(mod, rng)
    key_on = mod._fused._aot_key
    assert key_on == key_off + ("trainhealth",)
    assert mod._fused._health_groups is not None
    trainhealth._reset_for_tests()


# -- healthy-step stats -------------------------------------------------------
def test_stats_match_executor_grads(th_env):
    rng = np.random.RandomState(0)
    mod = _module()
    for i in range(2):
        _step(mod, rng)
        row = trainhealth.plane().drain(mod, epoch=0, step=i)
    assert row is not None and row["step"] == 2
    assert row["heads_finite"] and not row["nonfinite_groups"]
    # the drained global grad norm equals numpy over the executor's own
    # grad buffers (same dispatch, same values)
    tot, per_group = 0.0, {}
    for n in mod._param_names:
        g = mod._exec.grad_dict[n].asnumpy().astype(np.float64)
        sq = float((g ** 2).sum())
        tot += sq
        group = n.rsplit("_", 1)[0]
        per_group[group] = per_group.get(group, 0.0) + sq
    assert np.isclose(row["global_grad_norm"], np.sqrt(tot), rtol=1e-4)
    assert set(row["groups"]) == set(per_group)
    for g, s in row["groups"].items():
        assert np.isclose(s["grad_norm"], np.sqrt(per_group[g]), rtol=1e-4)
        assert s["param_norm"] > 0 and np.isfinite(s["update_ratio"])
        # FC-consumed params carry the PR 11 REDUCE verdict
        assert s["verdict"] == "fp32_accum"
    # a second drain of the same step returns nothing (stats are popped)
    assert trainhealth.plane().drain(mod) is None
    assert mod.trainer_stats()["step"] == 2


@pytest.mark.skipif(
    os.environ.get("MXNET_TEST_DEVICE", "").startswith(("tpu", "gpu")),
    reason="virtual 8-dev mesh is a CPU-tier fixture")
def test_stats_on_mesh_fused_step(th_env):
    from mxnet_tpu import parallel

    rng = np.random.RandomState(0)
    mesh = parallel.make_mesh({"dp": 8})
    mod = _module(batch=16, mesh=mesh)
    for i in range(2):
        _step(mod, rng, batch=16)
        row = trainhealth.plane().drain(mod, step=i)
    assert mod._fused is not None and mod._fused.mesh is not None
    assert row is not None and row["global_grad_norm"] > 0
    assert row["heads_finite"] and not row["nonfinite_groups"]
    assert set(row["groups"]) == {"fc1", "fc2"}


# -- divergence: census, violations, dump -------------------------------------
def test_census_blames_verdict_class(th_env):
    rng = np.random.RandomState(0)
    mod = _module()
    _step(mod, rng)
    trainhealth.plane().drain(mod, step=0)
    _step(mod, rng, nan=True)
    row = trainhealth.plane().drain(mod, step=1)
    assert row["nonfinite_groups"], "NaN step flagged no group"
    # the census buckets exactly the non-finite groups by THEIR verdicts
    expect = {}
    for g in row["nonfinite_groups"]:
        v = row["groups"][g]["verdict"]
        expect[v] = expect.get(v, 0) + 1
    assert row["nonfinite_census"] == expect
    # FC params are fp32_accum (blessed) — the contradiction counter fires
    r = tin.registry()
    pvv = r.get("precision_verdict_violations_total")
    assert pvv is not None
    assert pvv.value(verdict="fp32_accum", rank="0") >= 1
    assert r.get("trainhealth_nonfinite_total").value(
        verdict="fp32_accum", rank="0") >= 1


def test_divergence_dumps_flightrec(th_env, monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path / "frec"))
    flightrec._reset_for_tests()
    rng = np.random.RandomState(0)
    mod = _module()
    _step(mod, rng)
    trainhealth.plane().drain(mod, step=0)
    _step(mod, rng, nan=True)
    row = trainhealth.plane().drain(mod, step=1)
    dumps = glob.glob(str(tmp_path / "frec" / "flightrec-*-trainhealth.json"))
    assert dumps, "divergence wrote no dump"
    raw = open(dumps[0]).read()
    # STRICT JSON even though the payload describes non-finite values:
    # python's encoder would emit bare NaN/Infinity tokens that Perfetto's
    # JSON.parse import rejects — _safe() nulls them instead
    payload = json.loads(raw, parse_constant=lambda c: pytest.fail(
        "dump carries non-strict JSON token %r" % c))
    meta = payload["flightrec"]
    # the dump NAMES the first offending group and carries health rows
    assert meta["group"] == row["nonfinite_groups"][0]
    assert meta["verdict"] == row["groups"][meta["group"]]["verdict"]
    assert len(meta["health_rows"]) >= 2  # the healthy row rode along
    assert any(ev.get("name") == "trainhealth"
               for ev in payload["traceEvents"])


def test_nancheck_trip_dumps_flightrec(th_env, monkeypatch, tmp_path):
    """The MXNET_NANCHECK raise is preceded by a flight-recorder dump
    carrying the recent health rows (ISSUE 12 satellite)."""
    monkeypatch.setenv("MXNET_NANCHECK", "1")
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path / "frec"))
    flightrec._reset_for_tests()
    rng = np.random.RandomState(0)
    mod = _module()
    _step(mod, rng)
    trainhealth.plane().drain(mod, step=0)
    _step(mod, rng, nan=True)
    trainhealth.plane().drain(mod, step=1)
    with pytest.raises(mx.base.MXNetError, match="MXNET_NANCHECK"):
        _step(mod, rng)  # the folded flag is read one step later
    dumps = glob.glob(str(tmp_path / "frec" / "flightrec-*-nancheck.json"))
    assert dumps, "nancheck trip wrote no dump"
    meta = json.load(open(dumps[0]))["flightrec"]
    assert meta["where"] == "fused"
    assert meta["health_rows"], "dump carries no health rows"


# -- registry / JSONL / statusz surfaces --------------------------------------
def test_rank_labels_and_jsonl(th_env):
    rng = np.random.RandomState(0)
    mod = _module()
    _step(mod, rng)
    trainhealth.plane().drain(mod, epoch=0, step=0)
    r = tin.registry()
    # every trainhealth sample carries the rank label (0 single-process)
    for name in ("trainhealth_global_grad_norm", "trainhealth_loss"):
        samples = r.get(name).samples()
        assert samples and all(s["labels"]["rank"] == "0" for s in samples)
    assert r.get("trainhealth_group_grad_norm").value(
        group="fc1", rank="0") > 0
    tin.flush()
    lines = [json.loads(l) for l in
             open(tin.jsonl_path(), encoding="utf-8")]
    th_lines = [l for l in lines if l.get("kind") == "trainhealth"]
    assert th_lines and all(l["rank"] == 0 for l in th_lines)
    assert "groups" in th_lines[-1] and "fc1" in th_lines[-1]["groups"]


def test_statusz_mirrors_trainer_stats(th_env):
    from mxnet_tpu.telemetry import ops_server

    rng = np.random.RandomState(0)
    mod = _module()
    _step(mod, rng)
    trainhealth.plane().drain(mod, step=0)
    block = ops_server._statusz()["trainhealth"]
    assert block is not None
    assert block["last"]["step"] == mod.trainer_stats()["step"]
    assert block["rows"] == 1 and block["trips"] == 0


# -- Monitor routing (ISSUE 12 satellite) -------------------------------------
def test_monitor_rides_fused_step(monkeypatch):
    """A default (pattern-filtered) Monitor no longer forces the legacy
    path: it observes the in-graph stats and training stays fused."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.delenv("MXNET_TRAINHEALTH", raising=False)
    rng = np.random.RandomState(0)
    mod = _module()
    mon = mx.monitor.Monitor(1, stat_func=lambda x: float(x),
                             pattern=".*grad_norm")
    mod.install_monitor(mon)
    assert mod._stat_monitor is mon
    assert fused_step.fused_ineligible_reason(mod) is None
    mon.tic()
    _step(mod, rng)
    assert mod._fused is not None, "monitor forced the legacy path"
    assert mod._fused._health_groups is not None
    rows = mon.toc()
    names = [k for _n, k, _v in rows]
    assert "fc1:grad_norm" in names and "global:grad_norm" in names
    # the pattern filtered out non-matching stats
    assert not any("param_norm" in n or n == "loss" for n in names)


def test_monitor_all_is_the_unjitted_escape_hatch(monkeypatch):
    """monitor_all=True keeps the reference semantics: un-jitted executor
    callback observing every node, legacy path."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    rng = np.random.RandomState(0)
    mod = _module()
    mon = mx.monitor.Monitor(1, stat_func=lambda x: np.abs(x).mean(),
                             monitor_all=True)
    mod.install_monitor(mon)
    assert mod._stat_monitor is None
    assert fused_step.fused_ineligible_reason(mod) == "monitor"
    mon.tic()
    mod.forward_backward(_batch(rng))
    assert not mod._fused_pending  # legacy: executed immediately
    mod.update()
    rows = mon.toc()
    # the un-jitted route sees actual NODE outputs (and inputs)
    assert any("fc1_output" in k for _n, k, _v in rows)


def test_monitor_tensor_pattern_takes_unjitted_route(monkeypatch):
    """A monitor whose pattern targets TENSOR names (matches no in-graph
    stat row) must keep the pre-ISSUE-12 un-jitted route instead of going
    silently blind on the fused step."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    rng = np.random.RandomState(0)
    mod = _module()
    mon = mx.monitor.Monitor(1, stat_func=lambda x: np.abs(x).mean(),
                             pattern="fc1_weight")
    mod.install_monitor(mon)
    # routed straight to the executor: the pattern can only match tensors
    assert mod._stat_monitor is None and mod._exec._monitor is not None
    assert fused_step.fused_ineligible_reason(mod) == "monitor"
    mon.tic()
    mod.forward_backward(_batch(rng))
    mod.update()
    rows = mon.toc()
    assert rows == [], rows  # outputs-only callback; weights need _all
    mon2 = mx.monitor.Monitor(1, stat_func=lambda x: np.abs(x).mean(),
                              pattern="fc1_weight", monitor_all=True)
    mod2 = _module()
    mod2.install_monitor(mon2)
    mon2.tic()
    mod2.forward_backward(_batch(rng))
    mod2.update()
    assert any(k == "fc1_weight" for _n, k, _v in mon2.toc())


def test_monitor_on_fused_ineligible_module_falls_back(monkeypatch):
    """A Module whose steps are fused-ineligible for another reason
    (unsupported optimizer) must not leave a default monitor blind: the
    first legacy forward_backward re-routes it onto the un-jitted
    executor callback (the pre-ISSUE-12 behavior)."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    rng = np.random.RandomState(0)
    mod = mod_mod.Module(_sym())
    mod.bind(data_shapes=[("data", (BATCH, DIM))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params()
    mod.init_optimizer(optimizer="rmsprop",
                       optimizer_params={"learning_rate": 0.01})
    assert fused_step.fused_ineligible_reason(mod) == "optimizer"
    mon = mx.monitor.Monitor(1, stat_func=lambda x: np.abs(x).mean())
    mod.install_monitor(mon)
    assert mod._stat_monitor is mon  # in-graph route chosen at install
    mon.tic()
    _step(mod, rng)
    # re-routed: executor callback installed, in-graph handle cleared
    assert mod._stat_monitor is None and mod._exec._monitor is not None
    rows = mon.toc()
    assert any("fc1_output" in k for _n, k, _v in rows), rows


def test_monitor_detach_unstales(monkeypatch):
    """Attaching the in-graph monitor rebuilds the stepper (output
    structure changed); gate-off + no monitor rebuilds back."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.delenv("MXNET_TRAINHEALTH", raising=False)
    rng = np.random.RandomState(0)
    mod = _module()
    _step(mod, rng)
    first = mod._fused
    assert first._health_groups is None
    mod.install_monitor(mx.monitor.Monitor(1))
    assert first.stale(mod)
    _step(mod, rng)
    assert mod._fused is not first
    assert mod._fused._health_groups is not None


# -- 2-process pod telemetry (slow tier) --------------------------------------
WORKER_RANKS = textwrap.dedent("""
    import os, json, time
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["MXNET_TRAINHEALTH"] = "1"
    os.environ["MXNET_TRAINHEALTH_HB_S"] = "0"  # publish every drain
    os.environ["MXNET_TELEMETRY"] = "1"
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu.parallel import dist
    from mxnet_tpu.telemetry import trainhealth, instrument as tin

    dist.init()
    r, n = dist.rank(), dist.size()
    os.environ["MXNET_TELEMETRY_FILE"] = os.environ["TH_DIR"] + \\
        "/telemetry-rank%d.jsonl" % r

    data = mx.sym.var("data")
    x = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(x, name="fc2", num_hidden=4), name="softmax")
    mod = mod_mod.Module(sym)
    mod.bind(data_shapes=[("data", (8, 8))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(r)

    def step(i):
        b = DataBatch(
            data=[mx.nd.array(rng.randn(8, 8).astype(np.float32))],
            label=[mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))])
        mod.forward_backward(b)
        mod.update()
        return trainhealth.plane().drain(mod, epoch=0, step=i)

    # both ranks run 3 steps; rank 1 then STOPS (the straggler) while
    # rank 0 runs 2 more and reads the lag off the heartbeat exchange
    for i in range(3):
        row = step(i)
        assert row["rank"] == r, row
    dist.barrier("th_phase1", timeout_ms=60000)
    if r == 0:
        for i in range(3, 5):
            row = step(i)
        status = trainhealth.plane().status()
        print("RANK0_STATUS %s" % json.dumps(status["ranks"]), flush=True)
        reg = tin.registry()
        lag = reg.get("rank_step_lag_steps")
        print("RANK0_LAG %s" % json.dumps(lag.samples()), flush=True)
    tin.flush()
    dist.barrier("th_done", timeout_ms=60000)
    print("RANK%d_ROWS %d" % (r, len(trainhealth.plane().rows())), flush=True)
    dist.shutdown()
""")


@pytest.mark.slow
@pytest.mark.skipif(sys.platform != "linux",
                    reason="local fake cluster uses fork/Gloo")
def test_two_process_rank_labels_and_straggler(tmp_path):
    """The acceptance pod check: a seeded 2-process launch (the
    test_launch_dist.py machinery) shows rank-tagged samples/JSONL on both
    ranks and a live straggler gauge on rank 0 (rank 1 trails by 2)."""
    worker = tmp_path / "worker_th.py"
    worker.write_text(WORKER_RANKS)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               TH_DIR=str(tmp_path))
    env.pop("MXNET_TELEMETRY_FILE", None)
    for attempt in range(3):
        res = subprocess.run(
            [sys.executable, LAUNCH, "-n", "2", "--launcher", "local",
             sys.executable, str(worker)],
            env=env, capture_output=True, text=True, timeout=420)
        if res.returncode == 0:
            break
    assert res.returncode == 0, res.stdout + res.stderr
    out = res.stdout
    status_line = [l for l in out.splitlines() if "RANK0_STATUS" in l]
    assert status_line, out
    ranks = json.loads(status_line[0].split("RANK0_STATUS ")[1])
    # rank 0 at step 5, rank 1 parked at 3 → lag 2 (heartbeat may land a
    # drain late under load; accept >= 1)
    assert ranks["1"]["lag_steps"] is not None \
        and ranks["1"]["lag_steps"] >= 1, ranks
    assert ranks["0"]["lag_steps"] == 0, ranks
    lag_line = [l for l in out.splitlines() if "RANK0_LAG" in l]
    samples = json.loads(lag_line[0].split("RANK0_LAG ")[1])
    by_rank = {s["labels"]["rank"]: s["value"] for s in samples}
    assert by_rank.get("1", 0) >= 1, samples
    # per-rank JSONL files carry their own rank field
    for r in (0, 1):
        lines = [json.loads(l) for l in
                 open(tmp_path / ("telemetry-rank%d.jsonl" % r))]
        th = [l for l in lines if l.get("kind") == "trainhealth"]
        assert th and all(l["rank"] == r for l in th), (r, len(th))
