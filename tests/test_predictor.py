"""Deployment Predictor (reference c_predict_api) + bandwidth tool tests.

Reference: `src/c_api/c_predict_api.cc` (MXPredCreate/SetInput/Forward/
GetOutput/Reshape/PartialOut), `tools/bandwidth/measure.py`.
"""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.predictor import Predictor, create, load_ndarray_file

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def _mlp_symbol():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _trained_checkpoint(tmp_path):
    sym = _mlp_symbol()
    exe = sym.simple_bind(grad_req="null", data=(2, 8))
    rng = np.random.RandomState(0)
    args = {n: nd.array(rng.randn(*a.shape).astype(np.float32))
            for n, a in exe.arg_dict.items()
            if n not in ("data", "softmax_label")}
    prefix = str(tmp_path / "mlp")
    mx.model.save_checkpoint(prefix, 3, sym, args, {})
    return prefix, args


def test_predictor_matches_executor(tmp_path):
    prefix, args = _trained_checkpoint(tmp_path)
    pred = create(prefix + "-symbol.json", prefix + "-0003.params",
                  {"data": (2, 8)})
    x = np.random.RandomState(1).rand(2, 8).astype(np.float32)
    pred.set_input("data", x)
    pred.forward()
    out = pred.get_output(0)
    assert out.shape == (2, 4)
    assert pred.get_output_shape(0) == (2, 4)
    np.testing.assert_allclose(out.sum(axis=1), np.ones(2), rtol=1e-5)

    # oracle: the training-side executor on the same params
    sym, arg_params, aux_params = mx.model.load_checkpoint(prefix, 3)
    exe = sym.simple_bind(grad_req="null", data=(2, 8))
    exe.copy_params_from(arg_params, aux_params, allow_extra_params=True)
    ref = exe.forward(is_train=False, data=nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_predictor_forward_kwargs_and_errors(tmp_path):
    prefix, _ = _trained_checkpoint(tmp_path)
    pred = Predictor(prefix + "-symbol.json", prefix + "-0003.params",
                     {"data": (3, 8)})
    x = np.zeros((3, 8), np.float32)
    pred.forward(data=x)
    assert pred.get_output(0).shape == (3, 4)
    with pytest.raises(KeyError):
        pred.set_input("bogus", x)
    with pytest.raises(ValueError):
        pred.set_input("data", np.zeros((1, 8), np.float32))


def test_predictor_reshape(tmp_path):
    prefix, _ = _trained_checkpoint(tmp_path)
    pred = Predictor(prefix + "-symbol.json", prefix + "-0003.params",
                     {"data": (2, 8)})
    a = pred.forward(data=np.ones((2, 8), np.float32))[0].asnumpy()
    pred.reshape({"data": (5, 8)})
    b = pred.forward(data=np.ones((5, 8), np.float32))[0].asnumpy()
    assert b.shape == (5, 4)
    np.testing.assert_allclose(a[0], b[0], rtol=1e-5)


def test_predictor_partial_out(tmp_path):
    """MXPredCreatePartialOut: read an internal layer's activations."""
    prefix, _ = _trained_checkpoint(tmp_path)
    pred = Predictor(prefix + "-symbol.json", prefix + "-0003.params",
                     {"data": (2, 8)}, output_names=["relu1"])
    out = pred.forward(data=np.random.rand(2, 8).astype(np.float32))
    relu = out[0].asnumpy()
    assert relu.shape == (2, 16)
    assert (relu >= 0).all()


def test_load_ndarray_file(tmp_path):
    f = str(tmp_path / "mean.nd")
    nd.save(f, {"mean_img": nd.ones((3, 4))})
    d = load_ndarray_file(f)
    np.testing.assert_allclose(d["mean_img"].asnumpy(), np.ones((3, 4)))


def test_bandwidth_tool_runs():
    """tools/bandwidth.py measures psum busbw over the 8-dev CPU mesh."""
    import bandwidth

    res = bandwidth.measure([1 << 16], iters=2, warmup=1)
    (r,) = res
    assert r["n_devices"] >= 1
    assert r["busbw_GBps"] > 0
    if r["n_devices"] > 1:
        assert r["collective"] == "psum"
    assert bandwidth._parse_size("16M") == 16 << 20


def test_predictor_bfloat16(tmp_path):
    """dtype='bfloat16' really computes in bf16 (weights cast on copy)."""
    prefix, _ = _trained_checkpoint(tmp_path)
    pred = Predictor(prefix + "-symbol.json", prefix + "-0003.params",
                     {"data": (2, 8)}, dtype="bfloat16")
    assert str(pred._exec.arg_dict["fc1_weight"].dtype) == "bfloat16"
    x = np.random.RandomState(2).rand(2, 8).astype(np.float32)
    out = pred.forward(data=x)[0].asnumpy()
    ref = Predictor(prefix + "-symbol.json", prefix + "-0003.params",
                    {"data": (2, 8)}).forward(data=x)[0].asnumpy()
    np.testing.assert_allclose(out.astype(np.float32), ref, rtol=0.1, atol=0.05)
    pred.reshape({"data": (4, 8)})
    assert str(pred._exec.arg_dict["data"].dtype) == "bfloat16"
    assert pred.forward(data=np.zeros((4, 8), np.float32))[0].shape == (4, 4)
