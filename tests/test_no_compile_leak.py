"""Executable-cache stability for control-flow ops (regression for the
per-step compile leak: eagerly-called lax.scan/fori_loop ops re-traced per
call, leaking one XLA executable per training step until vm.max_map_count
killed the process — fixed by ops.registry.stable_eager)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def _nmaps():
    try:
        with open("/proc/%d/maps" % os.getpid()) as f:
            return sum(1 for _ in f)
    except OSError:  # non-linux
        pytest.skip("needs /proc/<pid>/maps")


def _assert_stable(step, warmup=3, iters=12, budget=8):
    for _ in range(warmup):
        step()
    base = _nmaps()
    for _ in range(iters):
        step()
    grown = _nmaps() - base
    assert grown <= budget, "leaked %d mappings over %d iters" % (grown, iters)


def test_lstm_train_loop_stable():
    from mxnet_tpu.gluon import rnn

    layer = rnn.LSTM(8, num_layers=1, bidirectional=True, layout="NTC")
    layer.initialize()
    x_np = np.random.RandomState(0).rand(2, 6, 4).astype(np.float32)

    def step():
        x = nd.array(x_np)
        x.attach_grad()
        with autograd.record():
            out = layer(x).mean()
        out.backward()

    _assert_stable(step)


def test_ctc_loss_train_loop_stable():
    rng = np.random.RandomState(0)
    acts = rng.randn(10, 4, 6).astype(np.float32)
    y = nd.array(rng.randint(1, 6, (4, 3)).astype(np.float32))

    def step():
        x = nd.array(acts)
        x.attach_grad()
        with autograd.record():
            loss = nd.ctc_loss(x, y).mean()
        loss.backward()

    _assert_stable(step)


def test_box_nms_loop_stable():
    dets = np.random.RandomState(0).rand(1, 30, 6).astype(np.float32)

    def step():
        nd.contrib.box_nms(nd.array(dets)).asnumpy()

    _assert_stable(step)
