"""Executable-cache stability for control-flow ops (regression for the
per-step compile leak: eagerly-called lax.scan/fori_loop ops re-traced per
call, leaking one XLA executable per training step until vm.max_map_count
killed the process — fixed by ops.registry.stable_eager)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def _nmaps():
    try:
        with open("/proc/%d/maps" % os.getpid()) as f:
            return sum(1 for _ in f)
    except OSError:  # non-linux
        pytest.skip("needs /proc/<pid>/maps")


def _assert_stable(step, warmup=3, iters=12, budget=8):
    for _ in range(warmup):
        step()
    base = _nmaps()
    for _ in range(iters):
        step()
    grown = _nmaps() - base
    assert grown <= budget, "leaked %d mappings over %d iters" % (grown, iters)


def test_lstm_train_loop_stable():
    from mxnet_tpu.gluon import rnn

    layer = rnn.LSTM(8, num_layers=1, bidirectional=True, layout="NTC")
    layer.initialize()
    x_np = np.random.RandomState(0).rand(2, 6, 4).astype(np.float32)

    def step():
        x = nd.array(x_np)
        x.attach_grad()
        with autograd.record():
            out = layer(x).mean()
        out.backward()

    _assert_stable(step)


def test_ctc_loss_train_loop_stable():
    rng = np.random.RandomState(0)
    acts = rng.randn(10, 4, 6).astype(np.float32)
    y = nd.array(rng.randint(1, 6, (4, 3)).astype(np.float32))

    def step():
        x = nd.array(acts)
        x.attach_grad()
        with autograd.record():
            loss = nd.ctc_loss(x, y).mean()
        loss.backward()

    _assert_stable(step)


def test_box_nms_loop_stable():
    dets = np.random.RandomState(0).rand(1, 30, 6).astype(np.float32)

    def step():
        nd.contrib.box_nms(nd.array(dets)).asnumpy()

    _assert_stable(step)


def test_fused_module_step_compiles_once_per_shape(monkeypatch, tmp_path):
    """ISSUE 3: the fused Module step (module/fused_step.py) compiles
    exactly ONCE per shape signature across epochs, and a Module.reshape to
    a new batch shape costs exactly one recompile — asserted via the
    telemetry jit-compile counter (instrument_step watches the jit
    executable cache)."""
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu.telemetry import instrument as tin

    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    tin._reset_for_tests()
    try:
        data = mx.sym.var("data")
        fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
        s = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(fc1, name="fc2", num_hidden=4),
            name="softmax")
        mod = mod_mod.Module(s)
        mod.bind(data_shapes=[("data", (6, 8))],
                 label_shapes=[("softmax_label", (6,))])
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        rng = np.random.RandomState(0)

        def epoch(batch):
            for _ in range(3):
                b = DataBatch(
                    data=[nd.array(rng.randn(batch, 8).astype(np.float32))],
                    label=[nd.array(rng.randint(0, 4, (batch,))
                                    .astype(np.float32))])
                mod.forward_backward(b)
                mod.update()

        compiles = lambda: tin.registry().get("jit_compiles_total") \
            .value(fn="module_fused_step")
        epoch(6)
        epoch(6)  # second epoch, same signature: no growth
        assert compiles() == 1, compiles()
        assert mod._fused.cache_size() == 1
        epoch(4)  # forward_backward reshapes to batch 4: exactly one recompile
        assert compiles() == 2, compiles()
        assert mod._fused.cache_size() == 2
        epoch(4)
        epoch(6)  # back to the first signature: cache hit, still 2
        assert compiles() == 2, compiles()
        assert mod._fused.cache_size() == 2
    finally:
        tin._reset_for_tests()


def test_costplane_scopes_add_zero_retraces(monkeypatch, tmp_path):
    """ISSUE 13: MXNET_COSTPLANE wraps every plan node in jax.named_scope
    (HLO attribution) and routes plain-jit sites through the AOT split —
    neither may change retrace behavior: the fused step still compiles
    exactly once per shape signature, a reshape costs exactly one row."""
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu.telemetry import costplane

    monkeypatch.setenv("MXNET_COSTPLANE", "1")
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    costplane._reset_for_tests()
    try:
        data = mx.sym.var("data")
        fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
        s = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(fc1, name="fc2", num_hidden=4),
            name="softmax")
        mod = mod_mod.Module(s)
        mod.bind(data_shapes=[("data", (6, 8))],
                 label_shapes=[("softmax_label", (6,))])
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        rng = np.random.RandomState(0)

        def epoch(batch):
            for _ in range(3):
                b = DataBatch(
                    data=[nd.array(rng.randn(batch, 8).astype(np.float32))],
                    label=[nd.array(rng.randint(0, 4, (batch,))
                                    .astype(np.float32))])
                mod.forward_backward(b)
                mod.update()

        fused_rows = lambda: sum(1 for r in costplane.rows()
                                 if r["site"] == "fused_step")
        epoch(6)
        epoch(6)  # second epoch, same signature: no new executable
        assert fused_rows() == 1, costplane.rows()
        assert mod._fused.cache_size() == 1
        epoch(4)  # reshape to batch 4: exactly one new executable
        assert fused_rows() == 2
        assert mod._fused.cache_size() == 2
        epoch(6)  # back to the first signature: cache hit, still 2
        assert fused_rows() == 2
        assert mod._fused.cache_size() == 2
    finally:
        costplane._reset_for_tests()


@pytest.mark.parametrize("passes", ["0", "1"])
def test_graph_passes_add_zero_retraces(monkeypatch, passes):
    """ISSUE 7: the pass pipeline runs once per (executor, mode) and its
    result is cached, so repeated forwards/backwards retrace exactly as
    often as the pre-pass executor did — once per mode, per shape."""
    monkeypatch.setenv("MXNET_GRAPH_PASSES", passes)
    data = mx.sym.var("data")
    h = mx.sym.Dropout(
        mx.sym.Activation(mx.sym.FullyConnected(data, name="fc1",
                                                num_hidden=8),
                          name="a1", act_type="relu"), name="dr", p=0.5)
    s = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, name="fc2", num_hidden=4), name="softmax")
    exe = s.simple_bind(data=(4, 8), grad_req="write")
    x = np.random.RandomState(0).rand(4, 8).astype(np.float32)
    exe.arg_dict["data"][:] = x
    for train in (False, True):
        for _ in range(4):
            exe.forward(is_train=train)
            if train:
                exe.backward()
    # one jitted executable per mode, one backward jit per cache key —
    # identical to the pre-pass counts (the jit wrapper caches per shape
    # signature; the optimized plan is a stable per-mode object)
    assert exe._fwd_cache[False]._cache_size() == 1
    assert exe._fwd_cache[True]._cache_size() == 1
    assert len(exe._bwd_cache) == 1
    for fn in exe._bwd_cache.values():
        assert fn._cache_size() == 1
    # the pipeline itself ran at most once per mode
    if passes == "1":
        assert set(exe.pass_stats()) == {"train", "eval"}
    else:
        assert exe.pass_stats() == {}


def test_mesh_fused_module_step_compiles_once_per_shape(monkeypatch, tmp_path):
    """ISSUE 5: the SHARDED fused Module step (mesh path) also compiles
    exactly once per shape signature, and a reshape to a new batch shape
    costs exactly one retrace — the sharding annotations must not defeat
    the executable cache."""
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu import parallel
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu.telemetry import instrument as tin

    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_FUSED_ZERO", "0")
    tin._reset_for_tests()
    try:
        data = mx.sym.var("data")
        fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
        s = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(fc1, name="fc2", num_hidden=4),
            name="softmax")
        mod = mod_mod.Module(s, mesh=parallel.make_mesh({"dp": 8}))
        mod.bind(data_shapes=[("data", (16, 8))],
                 label_shapes=[("softmax_label", (16,))])
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        rng = np.random.RandomState(0)

        def epoch(batch):
            for _ in range(3):
                b = DataBatch(
                    data=[nd.array(rng.randn(batch, 8).astype(np.float32))],
                    label=[nd.array(rng.randint(0, 4, (batch,))
                                    .astype(np.float32))])
                mod.forward_backward(b)
                mod.update()

        compiles = lambda: tin.registry().get("jit_compiles_total") \
            .value(fn="module_fused_step")
        epoch(16)
        epoch(16)  # same signature: no growth
        assert compiles() == 1, compiles()
        assert mod._fused.cache_size() == 1
        epoch(8)  # reshape to batch 8 (dp still divides it): ONE recompile
        assert compiles() == 2, compiles()
        assert mod._fused.cache_size() == 2
        epoch(16)  # back: cache hit, still 2
        assert compiles() == 2, compiles()
        assert mod._fused.cache_size() == 2
    finally:
        tin._reset_for_tests()
