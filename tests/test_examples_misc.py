"""Misc example-family tests: recommenders MF, text CNN, FGSM adversary,
VAE, bi-LSTM sort, multi-task, neural-style, REINFORCE (reference
example/{recommenders,cnn_text_classification,adversary,vae,bi-lstm-sort,
multi-task,neural-style,reinforcement-learning})."""
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _run(subdir, script, args, timeout=900, devices=1):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=%d" % devices)
    return subprocess.run(
        [sys.executable, script] + args,
        cwd=os.path.join(REPO, "examples", subdir), env=env,
        capture_output=True, text=True, timeout=timeout)


def test_matrix_factorization_example():
    res = _run("recommenders", "matrix_fact.py", ["--epochs", "6"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "MATRIX FACTORIZATION OK" in res.stdout


def test_text_cnn_example():
    res = _run("cnn_text_classification", "train.py", ["--epochs", "4"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "TEXT CNN OK" in res.stdout


def test_fgsm_adversary_example():
    res = _run("adversary", "fgsm.py", [])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "FGSM ADVERSARY OK" in res.stdout


def test_vae_example():
    res = _run("vae", "train_vae.py", ["--epochs", "15"], timeout=1200)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "VAE OK" in res.stdout


def test_bi_lstm_sort_example():
    res = _run("bi-lstm-sort", "sort_lstm.py",
               ["--epochs", "8", "--seq-len", "6", "--hidden", "48"],
               timeout=1800)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "BI-LSTM SORT OK" in res.stdout


def test_multitask_example():
    res = _run("multi-task", "train_multitask.py", [])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "MULTI-TASK OK" in res.stdout


def test_neural_style_example():
    res = _run("neural-style", "neural_style.py", ["--iters", "80"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "NEURAL STYLE OK" in res.stdout


def test_reinforce_example():
    res = _run("reinforcement-learning", "reinforce_gridworld.py",
               ["--iters", "100"], timeout=1200)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "REINFORCE OK" in res.stdout


def test_ctc_ocr_example():
    res = _run("ctc", "ocr_ctc.py",
               ["--epochs", "6", "--min-exact", "0.5"], timeout=1500)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "CTC OCR OK" in res.stdout
