"""Detection op tests — numpy oracles implementing the reference kernels'
documented semantics (reference tests live in
tests/python/unittest/test_operator.py::test_roipooling / test_proposal etc.;
oracles here are written from the algorithm, independent of both codebases).
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


# ---------------------------------------------------------------------------
# numpy oracles
# ---------------------------------------------------------------------------


def np_roi_pooling(data, rois, pooled, scale):
    B, C, H, W = data.shape
    PH, PW = pooled
    R = rois.shape[0]
    out = np.zeros((R, C, PH, PW), data.dtype)
    for r in range(R):
        b = int(rois[r, 0])
        xs = int(round(rois[r, 1] * scale))
        ys = int(round(rois[r, 2] * scale))
        xe = int(round(rois[r, 3] * scale))
        ye = int(round(rois[r, 4] * scale))
        rh, rw = max(ye - ys + 1, 1), max(xe - xs + 1, 1)
        for ph in range(PH):
            for pw in range(PW):
                hs = min(max(int(np.floor(ph * rh / PH)) + ys, 0), H)
                he = min(max(int(np.ceil((ph + 1) * rh / PH)) + ys, 0), H)
                ws = min(max(int(np.floor(pw * rw / PW)) + xs, 0), W)
                we = min(max(int(np.ceil((pw + 1) * rw / PW)) + xs, 0), W)
                if he <= hs or we <= ws:
                    continue
                out[r, :, ph, pw] = data[b, :, hs:he, ws:we].max(axis=(1, 2))
    return out


def np_bilinear(plane, y, x):
    H, W = plane.shape
    y, x = min(max(y, 0.0), H - 1.0), min(max(x, 0.0), W - 1.0)
    y0, x0 = int(np.floor(y)), int(np.floor(x))
    y1, x1 = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
    ly, lx = y - y0, x - x0
    return (
        plane[y0, x0] * (1 - ly) * (1 - lx)
        + plane[y0, x1] * (1 - ly) * lx
        + plane[y1, x0] * ly * (1 - lx)
        + plane[y1, x1] * ly * lx
    )


def np_roi_align(data, rois, pooled, scale, ratio):
    B, C, H, W = data.shape
    PH, PW = pooled
    R = rois.shape[0]
    out = np.zeros((R, C, PH, PW), np.float64)
    for r in range(R):
        b = int(rois[r, 0])
        x1, y1, x2, y2 = rois[r, 1:] * scale
        rw, rh = max(x2 - x1, 1.0), max(y2 - y1, 1.0)
        bh, bw = rh / PH, rw / PW
        gh = ratio if ratio > 0 else int(np.ceil(rh / PH))
        gw = ratio if ratio > 0 else int(np.ceil(rw / PW))
        for ph in range(PH):
            for pw in range(PW):
                acc = np.zeros(C)
                for iy in range(gh):
                    yy = y1 + ph * bh + (iy + 0.5) * bh / gh
                    for ix in range(gw):
                        xx = x1 + pw * bw + (ix + 0.5) * bw / gw
                        if yy < -1.0 or yy > H or xx < -1.0 or xx > W:
                            continue
                        acc += np.array([np_bilinear(data[b, c], yy, xx) for c in range(C)])
                out[r, :, ph, pw] = acc / (gh * gw)
    return out


def np_psroi_pooling(data, rois, scale, output_dim, pooled, group):
    B, C, H, W = data.shape
    R = rois.shape[0]
    out = np.zeros((R, output_dim, pooled, pooled), np.float64)
    for r in range(R):
        b = int(rois[r, 0])
        xs = round(rois[r, 1]) * scale
        ys = round(rois[r, 2]) * scale
        xe = (round(rois[r, 3]) + 1.0) * scale
        ye = (round(rois[r, 4]) + 1.0) * scale
        rw, rh = max(xe - xs, 0.1), max(ye - ys, 0.1)
        bh, bw = rh / pooled, rw / pooled
        for ct in range(output_dim):
            for ph in range(pooled):
                for pw in range(pooled):
                    hs = min(max(int(np.floor(ph * bh + ys)), 0), H)
                    he = min(max(int(np.ceil((ph + 1) * bh + ys)), 0), H)
                    ws = min(max(int(np.floor(pw * bw + xs)), 0), W)
                    we = min(max(int(np.ceil((pw + 1) * bw + xs)), 0), W)
                    gh = min(max(ph * group // pooled, 0), group - 1)
                    gw = min(max(pw * group // pooled, 0), group - 1)
                    c = (ct * group + gh) * group + gw
                    if he <= hs or we <= ws:
                        continue
                    out[r, ct, ph, pw] = data[b, c, hs:he, ws:we].mean()
    return out


def np_deformable_psroi(data, rois, trans, scale, output_dim, group, pooled, part, spp, trans_std, no_trans):
    B, C, H, W = data.shape
    R = rois.shape[0]
    out = np.zeros((R, output_dim, pooled, pooled), np.float64)
    num_classes = 1 if no_trans else trans.shape[1] // 2
    cpc = output_dim // num_classes
    for r in range(R):
        b = int(rois[r, 0])
        xs = round(rois[r, 1]) * scale - 0.5
        ys = round(rois[r, 2]) * scale - 0.5
        xe = (round(rois[r, 3]) + 1.0) * scale - 0.5
        ye = (round(rois[r, 4]) + 1.0) * scale - 0.5
        rw, rh = max(xe - xs, 0.1), max(ye - ys, 0.1)
        bh, bw = rh / pooled, rw / pooled
        sub_h, sub_w = bh / spp, bw / spp
        for ct in range(output_dim):
            cls = ct // cpc
            for ph in range(pooled):
                for pw in range(pooled):
                    p_h = int(np.floor(float(ph) / pooled * part))
                    p_w = int(np.floor(float(pw) / pooled * part))
                    tx = 0.0 if no_trans else trans[r, cls * 2, p_h, p_w] * trans_std
                    ty = 0.0 if no_trans else trans[r, cls * 2 + 1, p_h, p_w] * trans_std
                    wst = pw * bw + xs + tx * rw
                    hst = ph * bh + ys + ty * rh
                    gh = min(max(ph * group // pooled, 0), group - 1)
                    gw = min(max(pw * group // pooled, 0), group - 1)
                    c = (ct * group + gh) * group + gw
                    acc, cnt = 0.0, 0
                    for ih in range(spp):
                        for iw in range(spp):
                            w_ = wst + iw * sub_w
                            h_ = hst + ih * sub_h
                            if w_ < -0.5 or w_ > W - 0.5 or h_ < -0.5 or h_ > H - 0.5:
                                continue
                            acc += np_bilinear(data[b, c], h_, w_)
                            cnt += 1
                    out[r, ct, ph, pw] = 0.0 if cnt == 0 else acc / cnt
    return out


def np_deformable_conv(data, offset, weight, bias, kernel, stride, dilate, pad, groups, dg):
    B, C, H, W = data.shape
    F = weight.shape[0]
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    col = np.zeros((B, C, kh * kw, Ho, Wo))
    for b in range(B):
        for c in range(C):
            g = c // (C // dg)
            for i in range(kh):
                for j in range(kw):
                    t = i * kw + j
                    for ho in range(Ho):
                        for wo in range(Wo):
                            oy = offset[b, g * 2 * kh * kw + 2 * t, ho, wo]
                            ox = offset[b, g * 2 * kh * kw + 2 * t + 1, ho, wo]
                            y = ho * sh - ph + i * dh + oy
                            x = wo * sw - pw + j * dw + ox
                            if y < 0 or y >= H or x < 0 or x >= W:
                                continue
                            col[b, c, t, ho, wo] = np_bilinear(data[b, c], y, x)
    cpg = C // groups
    fpg = F // groups
    out = np.zeros((B, F, Ho, Wo))
    for b in range(B):
        for g in range(groups):
            w_ = weight[g * fpg:(g + 1) * fpg].reshape(fpg, -1)
            c_ = col[b, g * cpg:(g + 1) * cpg].reshape(cpg * kh * kw, -1)
            out[b, g * fpg:(g + 1) * fpg] = (w_ @ c_).reshape(fpg, Ho, Wo)
    if bias is not None:
        out += bias[None, :, None, None]
    return out


def np_multi_proposal(cls_prob, bbox_pred, im_info, stride, scales, ratios, pre_nms, post_nms, thresh, min_size):
    # anchors
    base = np.array([0, 0, stride - 1, stride - 1], np.float32)
    w0 = base[2] - base[0] + 1
    h0 = base[3] - base[1] + 1
    cx, cy = base[0] + 0.5 * (w0 - 1), base[1] + 0.5 * (h0 - 1)
    size = w0 * h0
    anchors = []
    for r in ratios:
        sr = np.floor(size / r)
        nw = np.floor(np.sqrt(sr) + 0.5)
        nh = np.floor(nw * r + 0.5)
        for s in scales:
            ws, hs = nw * s, nh * s
            anchors.append([cx - 0.5 * (ws - 1), cy - 0.5 * (hs - 1), cx + 0.5 * (ws - 1), cy + 0.5 * (hs - 1)])
    anchors = np.array(anchors, np.float32)
    A = anchors.shape[0]
    B, _, Hf, Wf = cls_prob.shape
    rois_all, scores_all = [], []
    for b in range(B):
        im_h, im_w, im_scale = im_info[b]
        props = []
        for h in range(Hf):
            for w in range(Wf):
                for a in range(A):
                    box = anchors[a] + np.array([w * stride, h * stride, w * stride, h * stride])
                    bw = box[2] - box[0] + 1
                    bh = box[3] - box[1] + 1
                    bcx = box[0] + 0.5 * (bw - 1)
                    bcy = box[1] + 0.5 * (bh - 1)
                    dx, dy, dw_, dh_ = bbox_pred[b, 4 * a:4 * a + 4, h, w]
                    pcx, pcy = dx * bw + bcx, dy * bh + bcy
                    pw_, ph_ = np.exp(dw_) * bw, np.exp(dh_) * bh
                    x1 = np.clip(pcx - 0.5 * (pw_ - 1), 0, im_w - 1)
                    y1 = np.clip(pcy - 0.5 * (ph_ - 1), 0, im_h - 1)
                    x2 = np.clip(pcx + 0.5 * (pw_ - 1), 0, im_w - 1)
                    y2 = np.clip(pcy + 0.5 * (ph_ - 1), 0, im_h - 1)
                    score = cls_prob[b, A + a, h, w]
                    if h >= int(im_h / stride) or w >= int(im_w / stride):
                        score = -1.0
                    ms = min_size * im_scale
                    if (x2 - x1 + 1) < ms or (y2 - y1 + 1) < ms:
                        x1, y1, x2, y2 = x1 - ms / 2, y1 - ms / 2, x2 + ms / 2, y2 + ms / 2
                        score = -1.0
                    props.append([x1, y1, x2, y2, score])
        props = np.array(props, np.float32)
        order = np.argsort(-props[:, 4], kind="stable")[: min(pre_nms, len(props))]
        ordered = props[order]
        # greedy NMS, +1 areas
        area = (ordered[:, 2] - ordered[:, 0] + 1) * (ordered[:, 3] - ordered[:, 1] + 1)
        suppressed = np.zeros(len(ordered), bool)
        keep = []
        for i in range(len(ordered)):
            if len(keep) >= post_nms:
                break
            if suppressed[i]:
                continue
            keep.append(i)
            xx1 = np.maximum(ordered[i, 0], ordered[i + 1:, 0])
            yy1 = np.maximum(ordered[i, 1], ordered[i + 1:, 1])
            xx2 = np.minimum(ordered[i, 2], ordered[i + 1:, 2])
            yy2 = np.minimum(ordered[i, 3], ordered[i + 1:, 3])
            inter = np.maximum(0, xx2 - xx1 + 1) * np.maximum(0, yy2 - yy1 + 1)
            iou = inter / (area[i] + area[i + 1:] - inter)
            suppressed[i + 1:] |= iou > thresh
        out = np.zeros((post_nms, 5), np.float32)
        osc = np.zeros((post_nms, 1), np.float32)
        for i in range(post_nms):
            idx = keep[i] if i < len(keep) else keep[i % len(keep)]
            out[i, 0] = b
            out[i, 1:] = ordered[idx, :4]
            osc[i, 0] = ordered[idx, 4]
        rois_all.append(out)
        scores_all.append(osc)
    return np.concatenate(rois_all), np.concatenate(scores_all)


# ---------------------------------------------------------------------------
# tests
# ---------------------------------------------------------------------------


def test_roi_pooling():
    data = np.random.randn(2, 3, 12, 9).astype(np.float32)
    rois = np.array(
        [
            [0, 0, 0, 16, 16],
            [1, 2, 3, 15, 13],
            [0, 7, 3, 24, 22],  # exceeds the map after scaling
            [1, 5, 5, 5, 5],  # degenerate single-pixel roi
        ],
        np.float32,
    )
    out = nd.ROIPooling(nd.array(data), nd.array(rois), pooled_size=(3, 3), spatial_scale=0.5).asnumpy()
    exp = np_roi_pooling(data, rois, (3, 3), 0.5)
    assert_almost_equal(out, exp, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("ratio", [2, -1])
def test_roi_align(ratio):
    data = np.random.randn(2, 4, 10, 10).astype(np.float32)
    rois = np.array([[0, 1, 1, 8, 8], [1, 0, 0, 18, 12], [0, 3.3, 2.2, 6.1, 7.9]], np.float32)
    out = nd.contrib.ROIAlign(
        nd.array(data), nd.array(rois), pooled_size=(2, 2), spatial_scale=0.5, sample_ratio=ratio
    ).asnumpy()
    exp = np_roi_align(data, rois, (2, 2), 0.5, ratio)
    assert_almost_equal(out, exp, rtol=1e-4, atol=1e-5)


def test_psroi_pooling():
    group, od = 3, 4
    data = np.random.randn(2, group * group * od, 9, 9).astype(np.float32)
    rois = np.array([[0, 0, 0, 14, 14], [1, 2, 4, 17, 15]], np.float32)
    out = nd.contrib.PSROIPooling(
        nd.array(data), nd.array(rois), spatial_scale=0.5, output_dim=od, pooled_size=group, group_size=group
    ).asnumpy()
    exp = np_psroi_pooling(data, rois, 0.5, od, group, group)
    assert_almost_equal(out, exp, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("no_trans", [True, False])
def test_deformable_psroi_pooling(no_trans):
    group = pooled = part = 3
    od = 2
    data = np.random.randn(2, group * group * od, 9, 9).astype(np.float32)
    rois = np.array([[0, 0, 0, 14, 14], [1, 2, 4, 17, 15]], np.float32)
    trans = (np.random.rand(2, 2, part, part).astype(np.float32) - 0.5)
    out = nd.contrib.DeformablePSROIPooling(
        nd.array(data),
        nd.array(rois),
        nd.array(trans),
        spatial_scale=0.5,
        output_dim=od,
        group_size=group,
        pooled_size=pooled,
        part_size=part,
        sample_per_part=2,
        trans_std=0.1,
        no_trans=no_trans,
    ).asnumpy()
    exp = np_deformable_psroi(data, rois, trans, 0.5, od, group, pooled, part, 2, 0.1, no_trans)
    assert_almost_equal(out, exp, rtol=1e-4, atol=1e-5)


def test_deformable_convolution_matches_conv_at_zero_offset():
    data = np.random.randn(1, 4, 7, 7).astype(np.float32)
    weight = np.random.randn(6, 4, 3, 3).astype(np.float32)
    bias = np.random.randn(6).astype(np.float32)
    offset = np.zeros((1, 2 * 9, 5, 5), np.float32)
    out = nd.contrib.DeformableConvolution(
        nd.array(data), nd.array(offset), nd.array(weight), nd.array(bias), kernel=(3, 3), num_filter=6
    ).asnumpy()
    ref = nd.Convolution(
        nd.array(data), nd.array(weight), nd.array(bias), kernel=(3, 3), num_filter=6
    ).asnumpy()
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


def test_deformable_convolution():
    B, C, H, W = 2, 4, 6, 5
    kernel, stride, dilate, pad = (3, 3), (2, 2), (1, 1), (1, 1)
    dg = 2
    Ho = (H + 2 - 3) // 2 + 1
    Wo = (W + 2 - 3) // 2 + 1
    data = np.random.randn(B, C, H, W).astype(np.float32)
    weight = np.random.randn(4, C, 3, 3).astype(np.float32)
    offset = np.random.randn(B, 2 * dg * 9, Ho, Wo).astype(np.float32)
    out = nd.contrib.DeformableConvolution(
        nd.array(data), nd.array(offset), nd.array(weight),
        kernel=kernel, num_filter=4, stride=stride, dilate=dilate, pad=pad,
        num_deformable_group=dg, no_bias=True,
    ).asnumpy()
    exp = np_deformable_conv(data, offset, weight, None, kernel, stride, dilate, pad, 1, dg)
    assert_almost_equal(out, exp, rtol=1e-3, atol=1e-4)


def test_deformable_convolution_grad():
    # jax AD of the gather formulation vs finite differences (replaces the
    # reference's hand-written deformable_col2im backward)
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import get as get_op

    op = get_op("_contrib_DeformableConvolution")
    data = np.random.randn(1, 2, 5, 5).astype(np.float32)
    weight = np.random.randn(3, 2, 3, 3).astype(np.float32)
    offset = 0.3 * np.random.randn(1, 18, 5, 5).astype(np.float32)

    def f(d, o, w):
        return op.fn(d, o, w, None, kernel=(3, 3), num_filter=3, pad=(1, 1), no_bias=True).sum()

    g_data, g_off, g_w = jax.grad(f, argnums=(0, 1, 2))(data, offset, weight)
    eps = np.float32(1e-2)  # float32 finite differences
    for arr, g, name in [(data, g_data, "data"), (offset, g_off, "offset"), (weight, g_w, "weight")]:
        idx = tuple(np.unravel_index(np.argmax(np.abs(np.asarray(g))), arr.shape))
        p = arr.copy()
        p[idx] += eps
        m = arr.copy()
        m[idx] -= eps
        args_p = [p if name == "data" else data, p if name == "offset" else offset, p if name == "weight" else weight]
        num = (f(*args_p) - f(*[m if name == "data" else data, m if name == "offset" else offset, m if name == "weight" else weight])) / (2 * eps)
        assert_almost_equal(np.asarray(g)[idx], np.asarray(num), rtol=2e-2, atol=1e-2, names=(name, "fd"))


def test_roi_pooling_grouped_path_matches_ungrouped():
    """The gather-free grouped path (``rois_per_image`` hint, the
    Faster-RCNN head's layout) must match the general path bit-for-bit in
    forward and gradients for batch-major rois."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.detection import roi_pooling

    rng = np.random.RandomState(5)
    B, C, H, W, Rb = 3, 8, 12, 16, 10
    R = B * Rb
    data = jnp.asarray(rng.rand(B, C, H, W).astype(np.float32))
    rois = np.zeros((R, 5), np.float32)
    rois[:, 0] = np.repeat(np.arange(B), Rb)
    rois[:, 1:3] = rng.rand(R, 2) * 100
    rois[:, 3:5] = rois[:, 1:3] + rng.rand(R, 2) * 100 + 8
    kw = dict(pooled_size=4, spatial_scale=1 / 8)
    base = roi_pooling(data, jnp.asarray(rois), **kw)
    grouped = roi_pooling(data, jnp.asarray(rois), rois_per_image=Rb, **kw)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(grouped))
    g0 = jax.grad(lambda d: (roi_pooling(d, jnp.asarray(rois), **kw) ** 2
                             ).sum())(data)
    g1 = jax.grad(lambda d: (roi_pooling(d, jnp.asarray(rois),
                                         rois_per_image=Rb, **kw) ** 2
                             ).sum())(data)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                               rtol=1e-6, atol=1e-6)


def test_deformable_convolution_matmul_path():
    """The separable one-hot-matmul sampling path (engaged above the
    N·H·W size threshold; the TPU north-star res5 runs through it) must
    match the numpy oracle in forward and finite differences in grad."""
    import jax
    from mxnet_tpu.ops.registry import get as get_op

    np.random.seed(7)
    op = get_op("_contrib_DeformableConvolution")
    B, C, H, W, dg, F = 1, 4, 28, 28, 2, 4
    # K2·Ho·Wo·H·W = 9·784·784 ≈ 5.5M ≥ 2^22 → matmul path
    data = np.random.randn(B, C, H, W).astype(np.float32)
    weight = np.random.randn(F, C, 3, 3).astype(np.float32)
    offset = 0.5 * np.random.randn(B, 2 * dg * 9, H, W).astype(np.float32)
    out = np.asarray(op.fn(data, offset, weight, None, kernel=(3, 3),
                           num_filter=F, pad=(1, 1),
                           num_deformable_group=dg, no_bias=True))
    exp = np_deformable_conv(data, offset, weight, None, (3, 3), (1, 1),
                             (1, 1), (1, 1), 1, dg)
    assert_almost_equal(out, exp, rtol=1e-3, atol=1e-4)

    def f(d, o, w):
        return op.fn(d, o, w, None, kernel=(3, 3), num_filter=F,
                     pad=(1, 1), num_deformable_group=dg, no_bias=True).sum()

    g_data, g_off, g_w = jax.grad(f, argnums=(0, 1, 2))(data, offset, weight)
    eps = np.float32(1e-2)
    for arr, g, name in [(data, g_data, "data"), (offset, g_off, "offset"),
                         (weight, g_w, "weight")]:
        idx = tuple(np.unravel_index(
            np.argmax(np.abs(np.asarray(g))), arr.shape))
        p = arr.copy(); p[idx] += eps
        m = arr.copy(); m[idx] -= eps
        pick = lambda v: (v if name == "data" else data,
                          v if name == "offset" else offset,
                          v if name == "weight" else weight)
        num = (f(*pick(p)) - f(*pick(m))) / (2 * eps)
        assert_almost_equal(np.asarray(g)[idx], np.asarray(num),
                            rtol=2e-2, atol=1e-2, names=(name, "fd"))


def test_deformable_convolution_vmem_guard_fallback(monkeypatch):
    """ADVICE round 5: with the estimated backward footprint over the VMEM
    budget, the auto branch must take the plain XLA scan directly (no
    platform_dependent / no Pallas build attempt) and produce identical
    values.  The guard consult and the path taken are both asserted."""
    import jax

    from mxnet_tpu.ops import pallas_kernels
    from mxnet_tpu.ops.registry import get as get_op

    np.random.seed(8)
    op = get_op("_contrib_DeformableConvolution")
    B, C, H, W, dg, F = 1, 4, 28, 28, 2, 4  # matmul path (≥ 2^22)
    data = np.random.randn(B, C, H, W).astype(np.float32)
    weight = np.random.randn(F, C, 3, 3).astype(np.float32)
    offset = 0.5 * np.random.randn(B, 2 * dg * 9, H, W).astype(np.float32)
    kw = dict(kernel=(3, 3), num_filter=F, pad=(1, 1),
              num_deformable_group=dg, no_bias=True)

    verdicts = []
    real_fits = pallas_kernels.dconv_fits_vmem
    monkeypatch.setattr(
        pallas_kernels, "dconv_fits_vmem",
        lambda *a: verdicts.append(real_fits(*a)) or verdicts[-1])
    pd_calls = []
    real_pd = jax.lax.platform_dependent

    def spy_pd(*a, **k):
        pd_calls.append(1)
        return real_pd(*a, **k)

    monkeypatch.setattr(jax.lax, "platform_dependent", spy_pd)

    monkeypatch.delenv("MXNET_DCONV_VMEM_MB", raising=False)
    base = np.asarray(op.fn(data, offset, weight, None, **kw))
    assert verdicts == [True] and pd_calls  # fused path considered

    verdicts.clear()
    pd_calls.clear()
    monkeypatch.setenv("MXNET_DCONV_VMEM_MB", "0.001")  # force fallback
    fell_back = np.asarray(op.fn(data, offset, weight, None, **kw))
    assert verdicts == [False] and not pd_calls  # xla_col taken directly
    assert_almost_equal(base, fell_back, rtol=1e-6, atol=0)


def test_multi_proposal():
    np.random.seed(3)
    B, A, Hf, Wf = 2, 9, 4, 4
    stride = 16
    scales, ratios = (8, 16, 32), (0.5, 1, 2)
    cls_prob = np.random.rand(B, 2 * A, Hf, Wf).astype(np.float32)
    bbox_pred = (0.2 * np.random.randn(B, 4 * A, Hf, Wf)).astype(np.float32)
    im_info = np.array([[64, 64, 1.5], [48, 64, 2.0]], np.float32)
    post = 8
    rois, scores = nd.contrib.MultiProposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        feature_stride=stride, scales=scales, ratios=ratios,
        rpn_pre_nms_top_n=60, rpn_post_nms_top_n=post, threshold=0.7,
        rpn_min_size=8, output_score=True,
    )
    exp_rois, exp_scores = np_multi_proposal(
        cls_prob, bbox_pred, im_info, stride, scales, ratios, 60, post, 0.7, 8
    )
    assert_almost_equal(rois.asnumpy(), exp_rois, rtol=1e-4, atol=1e-4)
    assert_almost_equal(scores.asnumpy(), exp_scores, rtol=1e-4, atol=1e-4)


def test_proposal_symbol():
    # symbolic-path smoke: Proposal inside a Symbol graph
    from mxnet_tpu import sym

    cls = sym.Variable("cls")
    bbox = sym.Variable("bbox")
    info = sym.Variable("info")
    p = sym.contrib.MultiProposal(cls, bbox, info, rpn_post_nms_top_n=4, rpn_pre_nms_top_n=12,
                                  scales=(8,), ratios=(1.0,), feature_stride=16)
    exe = p.simple_bind(mx.cpu(), cls=(1, 2, 3, 3), bbox=(1, 4, 3, 3), info=(1, 3))
    exe.arg_dict["cls"][:] = nd.array(np.random.rand(1, 2, 3, 3).astype(np.float32))
    exe.arg_dict["bbox"][:] = nd.array(0.1 * np.random.randn(1, 4, 3, 3).astype(np.float32))
    exe.arg_dict["info"][:] = nd.array(np.array([[48, 48, 1.0]], np.float32))
    out = exe.forward()[0]
    assert out.shape == (4, 5)
    assert np.isfinite(out.asnumpy()).all()


def np_greedy_nms_alive(boxes, thresh, plus_one=1.0, valid=None, ids=None,
                        force_suppress=True):
    """Sequential greedy NMS survivor mask — oracle for the blocked kernel."""
    N = len(boxes)
    alive = np.ones(N, bool) if valid is None else valid.copy()
    area = np.maximum(boxes[:, 2] - boxes[:, 0] + plus_one, 0) * np.maximum(
        boxes[:, 3] - boxes[:, 1] + plus_one, 0)
    for i in range(N):
        if not alive[i]:
            continue
        tl = np.maximum(boxes[i, :2], boxes[:, :2])
        br = np.minimum(boxes[i, 2:], boxes[:, 2:])
        wh = np.maximum(br - tl + plus_one, 0)
        inter = wh[:, 0] * wh[:, 1]
        union = area[i] + area - inter
        iou = np.where(union <= 0, 0, inter / np.maximum(union, 1e-12))
        sup = (np.arange(N) > i) & (iou > thresh)
        if ids is not None and not force_suppress:
            sup &= ids == ids[i]
        alive &= ~sup
    return alive


@pytest.mark.parametrize("n,tile", [(37, 256), (300, 64), (1000, 256), (6000, 256)])
def test_nms_blocked_matches_sequential_greedy(n, tile):
    """The blocked NMS (N/tile sequential steps) must produce byte-identical
    survivor sets to the sequential greedy scan at every size incl. the
    reference's rpn_pre_nms_top_n=6000 (multi_proposal.cc:221-273)."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.detection import _nms_alive_blocked

    rng = np.random.RandomState(n)
    # heavy-overlap regime: many suppression chains cross tile boundaries
    ctr = rng.rand(n, 2) * 80
    wh = rng.rand(n, 2) * 60 + 10
    boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2], 1).astype(np.float32)
    ref = np_greedy_nms_alive(boxes, 0.7, plus_one=1.0)
    got = np.asarray(_nms_alive_blocked(jnp.asarray(boxes), 0.7, tile=tile, plus_one=1.0))
    assert (ref == got).all()


def test_nms_blocked_ids_and_valid():
    """Per-class suppression + pre-dead rows (box_nms / MultiBoxDetection path)."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.detection import _nms_alive_blocked

    rng = np.random.RandomState(11)
    n = 700
    ctr = rng.rand(n, 2) * 100
    wh = rng.rand(n, 2) * 30 + 2
    boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2], 1).astype(np.float32)
    ids = rng.randint(0, 4, n).astype(np.float32)
    valid = rng.rand(n) > 0.2
    ref = np_greedy_nms_alive(boxes, 0.5, plus_one=0.0, valid=valid, ids=ids,
                              force_suppress=False)
    got = np.asarray(_nms_alive_blocked(
        jnp.asarray(boxes), 0.5, tile=128, plus_one=0.0,
        valid=jnp.asarray(valid), ids=jnp.asarray(ids), force_suppress=False))
    assert (ref == got).all()


def test_nms_blocked_empty():
    import jax.numpy as jnp
    from mxnet_tpu.ops.detection import _nms_alive_blocked

    assert _nms_alive_blocked(jnp.zeros((0, 4)), 0.5).shape == (0,)
    out = nd.contrib.box_nms(nd.array(np.zeros((1, 0, 6), np.float32)))
    assert out.shape == (1, 0, 6)


def test_deformable_psroi_matmul_path_matches_gather_path():
    """The one-hot-matmul hot path (engaged above the size threshold,
    detection.py) must match the gather path in forward AND gradients —
    the TPU headline runs through it."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import detection as D

    rng = np.random.RandomState(0)
    B, OD, g = 2, 6, 3
    C, H, W = OD * g * g, 12, 16
    data = jnp.asarray(rng.rand(B, C, H, W).astype(np.float32))
    R = 40
    rois = np.zeros((R, 5), np.float32)
    rois[:, 0] = rng.randint(0, B, R)
    rois[:, 1:3] = rng.rand(R, 2) * 100
    rois[:, 3:5] = rois[:, 1:3] + rng.rand(R, 2) * 120 + 8
    trans = jnp.asarray(0.3 * rng.randn(R, 2, 3, 3).astype(np.float32))
    kw = dict(spatial_scale=1 / 8, output_dim=OD, group_size=g,
              pooled_size=3, part_size=3, trans_std=0.1)
    small = D.deformable_psroi_pooling(data, jnp.asarray(rois), trans, **kw)
    # tile ROIs 40x to cross the 1<<16 threshold -> matmul path
    roisL = jnp.asarray(np.tile(rois, (40, 1)))
    transL = jnp.asarray(np.tile(np.asarray(trans), (40, 1, 1, 1)))
    big = D.deformable_psroi_pooling(data, roisL, transL, **kw)
    np.testing.assert_allclose(np.asarray(big[:R]), np.asarray(small),
                               rtol=1e-5, atol=1e-5)

    f_small = lambda d, t: jnp.sum(
        D.deformable_psroi_pooling(d, jnp.asarray(rois), t, **kw) ** 2)
    f_big = lambda d, t: jnp.sum(
        D.deformable_psroi_pooling(d, roisL, t, **kw)[:R] ** 2)
    gs = jax.grad(f_small, argnums=(0, 1))(data, trans)
    gb = jax.grad(f_big, argnums=(0, 1))(data, transL)
    np.testing.assert_allclose(np.asarray(gs[0]), np.asarray(gb[0]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gs[1]), np.asarray(gb[1][:R]),
                               rtol=1e-4, atol=1e-5)


def test_deformable_psroi_grouped_path_matches_ungrouped():
    """The block-diagonal batch-major path (``rois_per_image`` hint, the
    O(B) batch-scaling fix) must match the general path bit-for-bit in
    forward and gradients for grouped rois."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import detection as D

    rng = np.random.RandomState(1)
    B, OD, g = 3, 6, 3
    C, H, W = OD * g * g, 12, 16
    data = jnp.asarray(rng.rand(B, C, H, W).astype(np.float32))
    Rb = 40
    R = B * Rb
    rois = np.zeros((R, 5), np.float32)
    rois[:, 0] = np.repeat(np.arange(B), Rb)  # batch-major grouping
    rois[:, 1:3] = rng.rand(R, 2) * 100
    rois[:, 3:5] = rois[:, 1:3] + rng.rand(R, 2) * 120 + 8
    trans = jnp.asarray(0.3 * rng.randn(R, 2, 3, 3).astype(np.float32))
    roisj = jnp.asarray(rois)
    kw = dict(spatial_scale=1 / 8, output_dim=OD, group_size=g,
              pooled_size=3, part_size=3, trans_std=0.1)
    # R*K*PH*PW*spp2*cpc = 120*1*9*16*6 = 103,680 >= 1<<16 = 65,536 -> both
    # runs take the matmul path (shrinking OD below 4 would drop under the
    # threshold and test the gather path vacuously)
    plain = D.deformable_psroi_pooling(data, roisj, trans, **kw)
    grouped = D.deformable_psroi_pooling(data, roisj, trans,
                                         rois_per_image=Rb, **kw)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(plain),
                               rtol=1e-5, atol=1e-6)

    f_p = lambda d, t: jnp.sum(
        D.deformable_psroi_pooling(d, roisj, t, **kw) ** 2)
    f_g = lambda d, t: jnp.sum(
        D.deformable_psroi_pooling(d, roisj, t, rois_per_image=Rb, **kw) ** 2)
    gp = jax.grad(f_p, argnums=(0, 1))(data, trans)
    gg = jax.grad(f_g, argnums=(0, 1))(data, trans)
    np.testing.assert_allclose(np.asarray(gg[0]), np.asarray(gp[0]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gg[1]), np.asarray(gp[1]),
                               rtol=1e-4, atol=1e-5)
    # a wrong rois_per_image (not matching R) safely falls back to general
    fallback = D.deformable_psroi_pooling(data, roisj, trans,
                                          rois_per_image=7, **kw)
    np.testing.assert_allclose(np.asarray(fallback), np.asarray(plain),
                               rtol=1e-6, atol=0)


def test_grouped_roi_hint_misuse_raises_in_debug_mode():
    """VERDICT r4 item 7: the ``rois_per_image`` grouped layout is a trusted
    hint on the fused path, but the synchronous debug engine (the
    reference's ``MXNET_ENGINE_TYPE=NaiveEngine`` story) validates it —
    shuffled/interleaved rois raise instead of silently pooling from the
    wrong image."""
    from mxnet_tpu import engine

    data = np.random.randn(2, 8, 8, 8).astype(np.float32)
    good = np.array(
        [[0, 0, 0, 7, 7], [0, 1, 1, 6, 6], [1, 0, 0, 7, 7], [1, 2, 2, 5, 5]],
        np.float32)
    bad = good[[2, 1, 0, 3]]  # interleaved batch indices
    kw = dict(pooled_size=(2, 2), spatial_scale=1.0, rois_per_image=2)

    # fused/trusted path: no validation, no cost — documents the contract
    nd.ROIPooling(nd.array(data), nd.array(bad), **kw).asnumpy()

    engine.naive_engine(True)
    try:
        # correct grouping passes and matches the ungrouped result
        out = nd.ROIPooling(nd.array(data), nd.array(good), **kw).asnumpy()
        exp = nd.ROIPooling(nd.array(data), nd.array(good),
                            pooled_size=(2, 2), spatial_scale=1.0).asnumpy()
        assert_almost_equal(out, exp, rtol=1e-6, atol=0)
        with pytest.raises(ValueError, match="batch-major"):
            nd.ROIPooling(nd.array(data), nd.array(bad), **kw)
        # an all-ZEROS (unfilled) batch_idx column is NOT misuse — the
        # documented contract lets positional groupers leave it at 0
        zeroed = good.copy(); zeroed[:, 0] = 0
        nd.ROIPooling(nd.array(data), nd.array(zeroed), **kw).asnumpy()
        # but only the zero constant is exempt: a constant NONZERO column
        # carries real indices (every roi claims image 1) inconsistent
        # with r // Rb, and must raise like any filled column (ADVICE r5)
        ones = good.copy(); ones[:, 0] = 1
        with pytest.raises(ValueError, match="batch-major"):
            nd.ROIPooling(nd.array(data), nd.array(ones), **kw)
        # same contract on the deformable pooling's hint
        drois = np.array([[1, 0, 0, 14, 14], [0, 2, 4, 17, 15]], np.float32)
        with pytest.raises(ValueError, match="batch-major"):
            nd.contrib.DeformablePSROIPooling(
                nd.array(data), nd.array(drois), spatial_scale=0.5,
                output_dim=2, group_size=2, pooled_size=2, no_trans=True,
                rois_per_image=1)
    finally:
        engine.naive_engine(False)


def test_psroi_abuild_pallas_matches_einsum():
    """Round-5 A-build kernel: the Pallas MXU formulation must equal the
    einsum-HIGHEST formulation (values and grads) — interpret mode here;
    the chip consistency tier covers the compiled kernel."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import psroi_abuild_pallas

    rng = np.random.RandomState(3)
    N, S, H, W = 70, 16, 13, 21   # N deliberately not a block multiple
    yv = jnp.asarray(rng.rand(N, S, H).astype(np.float32))
    xv = jnp.asarray(rng.rand(N, S, W).astype(np.float32))

    ref = jnp.einsum("nsh,nsw->nhw", yv, xv,
                     precision=jax.lax.Precision.HIGHEST)
    out = psroi_abuild_pallas(yv, xv, jnp.float32, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)

    g = jnp.asarray(rng.rand(N, H, W).astype(np.float32))
    f_ref = lambda y, x: jnp.sum(jnp.einsum(
        "nsh,nsw->nhw", y, x, precision=jax.lax.Precision.HIGHEST) * g)
    f_pal = lambda y, x: jnp.sum(psroi_abuild_pallas(y, x, jnp.float32, True) * g)
    gy_r, gx_r = jax.grad(f_ref, argnums=(0, 1))(yv, xv)
    gy_p, gx_p = jax.grad(f_pal, argnums=(0, 1))(yv, xv)
    np.testing.assert_allclose(np.asarray(gy_p), np.asarray(gy_r),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gx_p), np.asarray(gx_r),
                               rtol=1e-5, atol=1e-6)


def test_dconv_col_pallas_matches_xla_formulation():
    """Round-5 fused dconv sampling kernel: VMEM-resident A (and dA) must
    equal the XLA one-hot-matmul formulation, values and all grads —
    interpret mode here; the chip consistency tier covers the compiled
    kernel and `bench.py` the in-module win (33.8 → 35.3 img/s)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import dconv_col_pallas

    BG, N, H, W, C = 3, 70, 9, 11, 16   # N not a block multiple
    HW = H * W
    rng = np.random.RandomState(0)
    y0 = jnp.asarray(rng.randint(0, H - 1, (BG, N)).astype(np.int32))
    y1 = jnp.minimum(y0 + 1, H - 1)
    x0 = jnp.asarray(rng.randint(0, W - 1, (BG, N)).astype(np.int32))
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly = jnp.asarray(rng.rand(BG, N).astype(np.float32))
    lx = jnp.asarray(rng.rand(BG, N).astype(np.float32))
    lf = jnp.asarray((rng.rand(BG, N) > 0.2).astype(np.float32))
    ft = jnp.asarray(rng.randn(BG, HW, C).astype(np.float32))

    def ref(y0, y1, x0, x1, ly, lx, lf, ft):
        iy = jnp.arange(H)
        ix = jnp.arange(W)
        yv = ((1 - ly)[..., None] * (y0[..., None] == iy)
              + ly[..., None] * (y1[..., None] == iy))
        xv = lf[..., None] * ((1 - lx)[..., None] * (x0[..., None] == ix)
                              + lx[..., None] * (x1[..., None] == ix))
        a = jnp.einsum("bnh,bnw->bnhw", yv, xv).reshape(BG, N, HW)
        return jnp.einsum("bnp,bpc->bnc", a, ft)

    r = ref(y0, y1, x0, x1, ly, lx, lf, ft)
    o = dconv_col_pallas(y0, y1, x0, x1, ly, lx, lf, ft, (H, W), True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(r),
                               rtol=1e-5, atol=1e-5)

    g = jnp.asarray(rng.randn(BG, N, C).astype(np.float32))
    fr = lambda *a: jnp.sum(ref(y0, y1, x0, x1, *a) * g)
    fp = lambda *a: jnp.sum(
        dconv_col_pallas(y0, y1, x0, x1, *a, (H, W), True) * g)
    gr = jax.grad(fr, argnums=(0, 1, 2, 3))(ly, lx, lf, ft)
    gp = jax.grad(fp, argnums=(0, 1, 2, 3))(ly, lx, lf, ft)
    for i in range(4):
        np.testing.assert_allclose(np.asarray(gp[i]), np.asarray(gr[i]),
                                   rtol=1e-4, atol=1e-4)


def test_deformable_conv_impl_env_override():
    """MXNET_DCONV_IMPL=pallas runs the fused kernel (interpret on CPU)
    and must match the default XLA path on the big-path shapes."""
    import jax.numpy as jnp
    from mxnet_tpu.ops import detection as D

    rng = np.random.RandomState(1)
    # N*H*W = (9*32*32)*(32*32) = 9.4M >= 1<<22: the ONE-HOT path (where
    # the impl dispatch lives), not the small-shape gather fallback
    B, C, H, W = 1, 8, 32, 32
    F = 8
    data = jnp.asarray(rng.randn(B, C, H, W).astype(np.float32))
    off = jnp.asarray(0.4 * rng.randn(B, 2 * 9 * 2, H, W).astype(np.float32))
    wt = jnp.asarray(rng.randn(F, C, 3, 3).astype(np.float32) * 0.1)
    kw = dict(kernel=(3, 3), num_filter=F, pad=(1, 1),
              num_deformable_group=2, no_bias=True)
    base = D.deformable_convolution(data, off, wt, **kw)
    os.environ["MXNET_DCONV_IMPL"] = "pallas"
    try:
        pal = D.deformable_convolution(data, off, wt, **kw)
    finally:
        del os.environ["MXNET_DCONV_IMPL"]
    np.testing.assert_allclose(np.asarray(pal), np.asarray(base),
                               rtol=1e-4, atol=1e-4)
