"""Mixed-precision training (reference tests/python/train/test_dtype.py:
fp16 training convergence; here bf16 — TPU's native compute dtype, via
make_train_step(compute_dtype='bfloat16') with fp32 master weights)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.gluon import nn, loss as loss_mod
from mxnet_tpu.gluon.functional import make_train_step


def _net():
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.BatchNorm(), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, 8)))
    return net


def test_bf16_training_converges_and_masters_stay_fp32():
    import jax
    import jax.numpy as jnp

    net = _net()
    step, state, (names, learn_idx, aux_idx) = make_train_step(
        net, loss_mod.SoftmaxCrossEntropyLoss(), learning_rate=0.1,
        momentum=0.9, compute_dtype="bfloat16")
    learn_vals, mom_vals, aux_vals = state
    assert all(v.dtype == jnp.float32 for v in learn_vals)  # master weights

    rng = np.random.RandomState(0)
    X = rng.rand(64, 8).astype(np.float32)
    Y = (X.sum(axis=1) * 0.5).astype(int) % 4
    jstep = jax.jit(step)
    losses = []
    s = state
    for i in range(25):
        s, loss = jstep(s, X, Y.astype(np.float32), jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::8]
    # updated params remain fp32 masters
    assert all(v.dtype == jnp.float32 for v in s[0])


def test_bf16_and_fp32_training_agree_roughly():
    """bf16 path follows the fp32 trajectory (loose tolerance — reference
    test_dtype checked fp16 reaches comparable accuracy, not bit equality)."""
    import jax

    traj = {}
    for dt in (None, "bfloat16"):
        net = _net()
        step, state, _ = make_train_step(
            net, loss_mod.SoftmaxCrossEntropyLoss(), learning_rate=0.05,
            compute_dtype=dt)
        rng = np.random.RandomState(1)
        X = rng.rand(32, 8).astype(np.float32)
        Y = (X[:, 0] > 0.5).astype(np.float32)
        jstep = jax.jit(step)
        s = state
        for i in range(10):
            s, loss = jstep(s, X, Y, jax.random.PRNGKey(i))
        traj[dt] = float(loss)
    assert abs(traj[None] - traj["bfloat16"]) < 0.25 * max(traj[None], 0.1), traj
