"""Image-classification example tests — symbol zoo builds/infers, the shared
fit harness trains (mirrors reference tests/python/train + example configs)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

EXDIR = os.path.join(os.path.dirname(__file__), "..", "examples", "image-classification")
sys.path.insert(0, os.path.abspath(EXDIR))


class TestSymbols:
    @pytest.mark.parametrize("depth,img,expect_bottleneck", [
        (20, 28, False), (110, 28, False), (164, 28, True),
        (18, 224, False), (50, 224, True),
    ])
    def test_resnet_shapes(self, depth, img, expect_bottleneck):
        from symbols import resnet

        s = resnet.get_symbol(num_classes=10, num_layers=depth, image_shape="3,%d,%d" % (img, img))
        _, out, _ = s.infer_shape(data=(2, 3, img, img), softmax_label=(2,))
        assert out[0] == (2, 10)

    def test_other_symbols(self):
        from symbols import mlp, lenet, alexnet, vgg

        s = mlp.get_symbol(num_classes=10)
        _, out, _ = s.infer_shape(data=(2, 1, 28, 28), softmax_label=(2,))
        assert out[0] == (2, 10)
        s = lenet.get_symbol(num_classes=10)
        _, out, _ = s.infer_shape(data=(2, 1, 28, 28), softmax_label=(2,))
        assert out[0] == (2, 10)
        s = alexnet.get_symbol(num_classes=1000)
        _, out, _ = s.infer_shape(data=(1, 3, 224, 224), softmax_label=(1,))
        assert out[0] == (1, 1000)
        s = vgg.get_symbol(num_classes=1000, num_layers=11, batch_norm=True)
        _, out, _ = s.infer_shape(data=(1, 3, 224, 224), softmax_label=(1,))
        assert out[0] == (1, 1000)


class TestFitHarness:
    def test_mnist_mlp_sgd_learns(self, tmp_path):
        """End-to-end: synthetic MNIST + mlp + sgd via the example CLI path."""
        import argparse
        import train_mnist
        from common import fit

        parser = argparse.ArgumentParser()
        parser.add_argument("--num-classes", type=int, default=10)
        parser.add_argument("--num-examples", type=int, default=1000)
        parser.add_argument("--data-path", type=str, default=str(tmp_path / "none.npz"))
        fit.add_fit_args(parser)
        args = parser.parse_args([
            "--network", "mlp", "--batch-size", "50", "--num-epochs", "2",
            "--lr", "0.1", "--disp-batches", "100",
            "--model-prefix", str(tmp_path / "mnist"),
        ])
        from symbols import mlp

        sym = mlp.get_symbol(num_classes=10)
        model = fit.fit(args, sym, train_mnist.get_mnist_iter)
        train, val = train_mnist.get_mnist_iter(args, None)
        metric = mx.metric.Accuracy()
        model.score(val, metric)
        assert metric.get()[1] > 0.9, metric.get()
        # checkpoint written by epoch-end callback
        assert os.path.exists(str(tmp_path / "mnist-0002.params"))

    def test_resnet20_synthetic_step(self):
        """ResNet-20 CIFAR shape runs a couple of fit batches (benchmark path)."""
        import argparse
        from common import data, fit
        from symbols import resnet

        parser = argparse.ArgumentParser()
        fit.add_fit_args(parser)
        data.add_data_args(parser)
        data.add_data_aug_args(parser)
        args = parser.parse_args([
            "--benchmark", "1", "--num-classes", "10", "--num-layers", "20",
            "--image-shape", "3,28,28", "--batch-size", "4", "--num-epochs", "1",
            "--num-examples", "200", "--lr", "0.05", "--disp-batches", "1000",
        ])
        sym = resnet.get_symbol(num_classes=10, num_layers=20, image_shape="3,28,28")

        def tiny_loader(a, kv):
            train, _ = data.get_rec_iter(a, kv)
            train.max_iter = 3  # keep the smoke run short
            return train, None

        model = fit.fit(args, sym, tiny_loader)
        assert model is not None
