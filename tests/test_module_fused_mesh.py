"""Sharded fused Module train step (ISSUE 5, module/fused_step.py mesh path).

Coverage demanded by the issue:
- mesh-fused vs legacy-mesh numerical parity after N steps for sgd,
  momentum sgd and adam — BatchNorm aux fold and per-parameter lr/wd
  vectors (``lr_mult``/``wd_mult``) included;
- ZeRO-1 mode (``MXNET_FUSED_ZERO=1``) matches the replicated-state
  results while each device holds only 1/dp of the optimizer state;
- acceptance: one compiled dispatch per mesh step
  (``train_steps_total{path="fused_mesh"}``, ``dispatches_per_step == 1``);
- fallback reasons distinguish mesh-unsupported-feature tags from the old
  blanket ``"mesh"``; a local kvstore under a dp mesh folds into the
  in-step psum;
- the prefetch path (``Module.prepare``) pre-stages the next batch's
  sharded feed and ``_stage_batch`` consumes it without re-staging.

Runs on the 8 virtual CPU host devices conftest.py forces via
``XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import module as mod_mod
from mxnet_tpu import parallel
from mxnet_tpu.io import DataBatch
from mxnet_tpu.module import fused_step
from mxnet_tpu.telemetry import instrument as tin

STEPS = 4
BATCH = 16  # divisible by dp=8
DIM = 8
DP = 8


def _mesh():
    return parallel.make_mesh({"dp": DP})


def _sym(bn=True, dropout=False):
    data = mx.sym.var("data")
    # no_bias under BN: see test_module_fused.py / docs/PERF_NOTES.md (a
    # zero-true-gradient bias drifts under adam on ANY two compilations)
    x = mx.sym.FullyConnected(data, name="fc1", num_hidden=16, no_bias=bn)
    if bn:
        x = mx.sym.BatchNorm(x, name="bn1")
    x = mx.sym.Activation(x, name="relu1", act_type="relu")
    if dropout:
        x = mx.sym.Dropout(x, name="drop1", p=0.5)
    x = mx.sym.FullyConnected(x, name="fc2", num_hidden=4)
    return mx.sym.SoftmaxOutput(x, name="softmax")


def _batches(steps=STEPS, batch=BATCH, dim=DIM):
    rng = np.random.RandomState(7)
    return [
        DataBatch(data=[mx.nd.array(rng.randn(batch, dim).astype(np.float32))],
                  label=[mx.nd.array(rng.randint(0, 4, (batch,)).astype(np.float32))])
        for _ in range(steps)
    ]


def _make_module(sym=None, mesh=None, **kwargs):
    mod = mod_mod.Module(sym if sym is not None else _sym(),
                         mesh=mesh if mesh is not None else _mesh(), **kwargs)
    mod.bind(data_shapes=[("data", (BATCH, DIM))],
             label_shapes=[("softmax_label", (BATCH,))])
    rng = np.random.RandomState(3)
    shapes = {n: a.shape for n, a in mod._exec.arg_dict.items()}
    arg = {n: mx.nd.array(rng.randn(*shapes[n]).astype(np.float32) * 0.1)
           for n in sorted(mod._param_names)}
    mod.init_params(arg_params=arg)
    return mod


def _train(monkeypatch, fused, optimizer, opt_params, sym=None, steps=STEPS,
           zero=False, lr_mult=None, wd_mult=None):
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1" if fused else "0")
    monkeypatch.setenv("MXNET_FUSED_ZERO", "1" if zero else "0")
    mx.random.seed(11)  # same per-step key sequence on both paths
    mod = _make_module(sym)
    mod.init_optimizer(optimizer=optimizer, optimizer_params=dict(opt_params))
    if lr_mult:
        mod._optimizer.set_lr_mult(lr_mult)
    if wd_mult:
        mod._optimizer.set_wd_mult(wd_mult)
    for b in _batches(steps):
        mod.forward_backward(b)
        mod.update()
    arg_params, aux_params = mod.get_params()
    return ({n: v.asnumpy() for n, v in arg_params.items()},
            {n: v.asnumpy() for n, v in aux_params.items()},
            mod.get_outputs()[0].asnumpy(), mod)


def _assert_params_close(a, b, **kw):
    for n in a:
        np.testing.assert_allclose(a[n], b[n], rtol=2e-5, atol=1e-6,
                                   err_msg=n, **kw)


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
], ids=["sgd", "sgd_mom", "adam"])
def test_mesh_fused_legacy_parity(monkeypatch, optimizer, opt_params):
    """Identical params/aux/outputs after N steps on the dp mesh — the
    fused step's in-graph psum + optimizer matches the legacy sharded
    forward + eager updater loop."""
    arg_f, aux_f, out_f, mod_f = _train(monkeypatch, True, optimizer, opt_params)
    arg_l, aux_l, out_l, mod_l = _train(monkeypatch, False, optimizer, opt_params)
    assert mod_f._fused is not None, "mesh fused path never engaged"
    assert mod_f._fused.mesh is not None and not mod_f._fused.zero
    assert mod_l._fused is None, "legacy run built a fused stepper"
    _assert_params_close(arg_f, arg_l)
    _assert_params_close(aux_f, aux_l)
    np.testing.assert_allclose(out_f, out_l, rtol=2e-5, atol=1e-6)
    # aux actually moved (BatchNorm stats trained under the mesh feed)
    assert any(np.abs(v).max() > 1e-4 for v in aux_f.values())


def test_mesh_fused_per_param_lr_wd(monkeypatch):
    """Per-parameter lr/wd vectors (lr_mult/wd_mult) flow into the sharded
    fused step as traced vectors and match the legacy-mesh updater."""
    mults = dict(lr_mult={"fc1_weight": 0.5},
                 wd_mult={"fc2_weight": 2.0, "fc2_bias": 0.0})
    opt_params = {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-3}
    arg_f, _, _, mod_f = _train(monkeypatch, True, "sgd", opt_params, **mults)
    arg_l, _, _, _ = _train(monkeypatch, False, "sgd", opt_params, **mults)
    assert mod_f._fused is not None and mod_f._fused.mesh is not None
    _assert_params_close(arg_f, arg_l)


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
], ids=["sgd_mom", "adam"])
def test_zero1_matches_replicated(monkeypatch, optimizer, opt_params):
    """MXNET_FUSED_ZERO=1: same numbers as the replicated-state mesh run,
    while every dp-divisible optimizer-state leaf is held as a 1/dp shard
    per device."""
    arg_z, aux_z, out_z, mod_z = _train(monkeypatch, True, optimizer,
                                        opt_params, zero=True)
    arg_r, aux_r, out_r, _ = _train(monkeypatch, True, optimizer, opt_params)
    assert mod_z._fused is not None and mod_z._fused.zero
    _assert_params_close(arg_z, arg_r)
    _assert_params_close(aux_z, aux_r)
    np.testing.assert_allclose(out_z, out_r, rtol=2e-5, atol=1e-6)

    # memory ledger: each device holds only its shard of the state
    sharded_leaves = 0
    for i, n in enumerate(mod_z._param_names):
        st = mod_z._updater.states[i]
        if st is None:
            continue
        leaves = [st] if not isinstance(st, (tuple, list)) else list(st)
        for leaf in leaves:
            arr = leaf._data
            shard = arr.sharding.shard_shape(arr.shape)
            if arr.shape[0] % DP == 0 and arr.shape[0] >= DP:
                assert int(np.prod(shard)) * DP == int(np.prod(arr.shape)), \
                    (n, arr.shape, shard)
                sharded_leaves += 1
    assert sharded_leaves > 0, "no optimizer-state leaf was actually sharded"
    total = parallel.zero1_state_bytes(
        [st._data if not isinstance(st, (tuple, list)) else
         [leaf._data for leaf in st]
         for st in mod_z._updater.states.values() if st is not None])
    full = sum(
        int(np.prod(leaf.shape)) * 4
        for st in mod_z._updater.states.values() if st is not None
        for leaf in ([st] if not isinstance(st, (tuple, list)) else st))
    assert total < full, (total, full)


def test_zero_gate_flip_rebuilds_stepper(monkeypatch):
    """Flipping MXNET_FUSED_ZERO mid-run rebuilds the stepper (the state
    layout changes) and training continues consistently."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_FUSED_ZERO", "0")
    mx.random.seed(11)
    mod = _make_module()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    b1, b2 = _batches(2)
    mod.forward_backward(b1)
    mod.update()
    first = mod._fused
    assert first is not None and not first.zero
    monkeypatch.setenv("MXNET_FUSED_ZERO", "1")
    mod.forward_backward(b2)
    mod.update()
    assert mod._fused is not first and mod._fused.zero
    for _, v in mod.get_params()[0].items():
        assert np.isfinite(v.asnumpy()).all()


# -- fallback-reason taxonomy -------------------------------------------------
def test_mesh_without_dp_axis_falls_back(monkeypatch):
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    mod = _make_module(mesh=parallel.make_mesh({"tp": DP}))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    assert fused_step.fused_ineligible_reason(mod) == "mesh_no_dp"


def test_mesh_unsupported_feature_reason_not_blanket_mesh(monkeypatch):
    """A mesh Module with an unfusable optimizer reports the FEATURE reason
    ("optimizer"), not the old blanket "mesh"."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    mod = _make_module()
    mod.init_optimizer(optimizer="rmsprop",
                       optimizer_params={"learning_rate": 0.01})
    assert fused_step.fused_ineligible_reason(mod) == "optimizer"


def test_local_kvstore_folds_into_mesh_step(monkeypatch):
    """kvstore='local' (and a plain local KVStore instance) under a dp mesh
    folds into the in-step psum: the fused path engages and matches the
    storeless mesh run."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_FUSED_ZERO", "0")
    mx.random.seed(11)
    mod = _make_module()
    mod.init_optimizer(kvstore=mx.kv.create("local"), optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    assert mod._kvstore is not None
    assert not mod._update_on_kvstore
    assert fused_step.fused_ineligible_reason(mod) is None
    for b in _batches(2):
        mod.forward_backward(b)
        mod.update()
    assert mod._fused is not None and mod._fused.mesh is not None
    arg_kv = {n: v.asnumpy() for n, v in mod.get_params()[0].items()}
    arg_ref, _, _, _ = _train(monkeypatch, True, "sgd",
                              {"learning_rate": 0.1}, steps=2)
    _assert_params_close(arg_kv, arg_ref)


def test_kvstore_with_store_updater_keeps_legacy(monkeypatch):
    """A store that runs its own updater does real work per push — it must
    NOT fold, even under a mesh."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    kv = mx.kv.create("local")
    kv.set_updater(lambda k, recv, stored: None)
    assert not kv.folds_into_fused_step()
    mod = _make_module()
    mod.init_optimizer(kvstore=kv, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    assert fused_step.fused_ineligible_reason(mod) == "kvstore"


# -- acceptance: one dispatch per mesh step, counted --------------------------
def test_mesh_fused_single_dispatch_per_step(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_FUSED_ZERO", "0")
    tin._reset_for_tests()
    try:
        mx.random.seed(11)
        mod = _make_module()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "momentum": 0.9})
        steps = 5
        for b in _batches(steps):
            mod.forward_backward(b)
            mod.update()
        r = tin.registry()
        assert r.get("train_steps_total").value(path="fused_mesh") == steps
        # THE acceptance criterion: one compiled dispatch per mesh step
        assert r.get("step_dispatches_total").value(path="fused_mesh") == steps
        assert r.get("step_dispatches_total").value(path="legacy") == 0
        assert mod._fused.cache_size() == 1
        assert r.get("jit_compiles_total").value(fn="module_fused_step") == 1
        assert r.get("module_fused_fallback_total") is None
        # summary() covers the mesh path (satellite): 1 dispatch per step
        assert tin.summary()["dispatches_per_step"] == 1.0
        # the GSPMD-derived in-step collective is declared to telemetry
        assert r.get("collective_bytes_total").value(op="psum_grads") > 0
    finally:
        tin._reset_for_tests()


def test_zero_collectives_declared(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_FUSED_ZERO", "1")
    tin._reset_for_tests()
    try:
        mx.random.seed(11)
        mod = _make_module()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "momentum": 0.9})
        b = _batches(1)[0]
        mod.forward_backward(b)
        mod.update()
        r = tin.registry()
        assert r.get("train_steps_total").value(path="fused_mesh") == 1
        assert r.get("collective_bytes_total").value(op="reduce_scatter") > 0
        assert r.get("collective_bytes_total").value(op="allgather") > 0
    finally:
        tin._reset_for_tests()


# -- prefetch (ISSUE 5 satellite) --------------------------------------------
def test_prepare_prestages_sharded_feed(monkeypatch):
    """Module.prepare issues the sharded device_put early; _stage_batch
    consumes that very feed (no second staging) and the executor ends up
    holding the pre-staged arrays."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_FUSED_ZERO", "0")
    mod = _make_module()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    b = _batches(1)[0]
    mod.prepare(b)
    assert mod._prestaged is not None and mod._prestaged[0] is b
    feed = dict(mod._prestaged[1])
    from jax.sharding import NamedSharding

    for v in feed.values():  # already committed dp-sharded, pre-dispatch
        assert isinstance(v._data.sharding, NamedSharding)
    mod.forward_backward(b)
    assert mod._prestaged is None  # consumed, not rebuilt
    for k, v in feed.items():
        assert mod._exec.arg_dict[k] is v
    mod.update()
    assert mod._fused is not None


def test_prepare_skips_reshaping_batch(monkeypatch):
    """A batch whose shape differs is left to _stage_batch's reshape path —
    prepare must not re-bind mid-flight."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    mod = _make_module()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    small = DataBatch(
        data=[mx.nd.array(rng.randn(8, DIM).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))])
    exec_before = mod._exec
    mod.prepare(small)
    assert mod._prestaged is None
    assert mod._exec is exec_before
    # the reshape then happens at staging time, and the step still runs
    mod.forward_backward(small)
    mod.update()
    assert mod._fused is not None


def test_fit_mesh_prefetch_and_counters(monkeypatch, tmp_path):
    """The stock fit loop on a mesh Module: fused_mesh path engages, one
    dispatch per step, and prepare() pre-staging is exercised end-to-end."""
    monkeypatch.setenv("MXNET_TELEMETRY", "1")
    monkeypatch.setenv("MXNET_TELEMETRY_FILE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_FUSED_ZERO", "0")
    tin._reset_for_tests()
    try:
        from mxnet_tpu.io import NDArrayIter

        rng = np.random.RandomState(0)
        X = rng.randn(96, DIM).astype(np.float32)
        W = rng.randn(DIM, 4).astype(np.float32)
        y = np.argmax(X @ W, axis=1).astype(np.float32)
        train = NDArrayIter(X, y, batch_size=BATCH, shuffle=True,
                            label_name="softmax_label")
        mod = mod_mod.Module(_sym(bn=False), mesh=_mesh())
        mod.fit(train, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                num_epoch=2)
        assert mod._fused is not None and mod._fused.mesh is not None
        r = tin.registry()
        steps = r.get("train_steps_total").value(path="fused_mesh")
        assert steps == 12  # 6 batches x 2 epochs
        assert r.get("step_dispatches_total").value(path="fused_mesh") == steps
        assert tin.summary()["dispatches_per_step"] == 1.0
    finally:
        tin._reset_for_tests()
