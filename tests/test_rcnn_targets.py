"""On-device RPN anchor / proposal target ops vs the host-numpy oracles.

The oracles are the example-level numpy implementations
(examples/rcnn/faster_rcnn.py assign_anchor / ProposalTarget CustomOp),
which themselves mirror the reference's host pipeline
(rcnn/io/rpn.py assign_anchor, rcnn/symbol/proposal_target.py sample_rois).
Randomized subsampling can't match draw-for-draw, so the comparisons check
the deterministic parts exactly (candidate partition, counts, targets for
forced selections) and distributional invariants for the sampled parts.
"""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples", "rcnn"))


def _rand_gt(rng, B, G, im_h, im_w, valid_counts):
    gt = np.full((B, G, 5), -1.0, np.float32)
    for b in range(B):
        for g in range(valid_counts[b]):
            x1 = rng.uniform(0, im_w - 40)
            y1 = rng.uniform(0, im_h - 40)
            w = rng.uniform(16, min(120, im_w - x1 - 1))
            h = rng.uniform(16, min(120, im_h - y1 - 1))
            gt[b, g] = [rng.randint(0, 3), x1, y1, x1 + w, y1 + h]
    return gt


def test_rpn_anchor_target_matches_numpy_partition():
    import faster_rcnn as fr

    rng = np.random.RandomState(0)
    B, Hf, Wf = 2, 8, 11
    stride, scales, ratios = 16, (4, 8), (0.5, 1, 2)
    A = len(scales) * len(ratios)
    im_info = np.array([[Hf * stride, Wf * stride, 1.0]] * B, np.float32)
    gt = _rand_gt(rng, B, 4, Hf * stride, Wf * stride, [3, 1])

    # huge batch_rois => no subsampling => deterministic, comparable exactly
    label, bt, bw = nd.contrib.rpn_anchor_target(
        nd.array(gt), nd.array(im_info),
        feat_height=Hf, feat_width=Wf, feature_stride=stride,
        scales=scales, ratios=ratios, batch_rois=10_000, fg_fraction=0.5,
    )
    label, bt, bw = label.asnumpy(), bt.asnumpy(), bw.asnumpy()
    for b in range(B):
        lab_np, bt_np, bw_np = fr.assign_anchor(
            (Hf, Wf), gt[b], im_info[b], stride=stride, scales=scales,
            ratios=ratios, batch_rois=10_000, fg_fraction=0.5,
            rng=np.random.RandomState(1),
        )
        # fg_fraction*batch_rois >> candidates => oracle never subsamples
        assert (label[b] == lab_np).all(), (
            np.where(label[b] != lab_np), label[b][label[b] != lab_np],
            lab_np[label[b] != lab_np])
        np.testing.assert_allclose(bt[b], bt_np, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(bw[b], bw_np, rtol=1e-6, atol=0)


def test_rpn_anchor_target_subsampling_counts():
    rng = np.random.RandomState(3)
    B, Hf, Wf = 1, 16, 16
    stride = 8
    im_info = np.array([[Hf * stride, Wf * stride, 1.0]], np.float32)
    gt = _rand_gt(rng, B, 6, Hf * stride, Wf * stride, [6])
    noise = rng.rand(B, Hf * Wf * 9, 2).astype(np.float32)
    label, bt, bw = nd.contrib.rpn_anchor_target(
        nd.array(gt), nd.array(im_info), nd.array(noise),
        feat_height=Hf, feat_width=Wf, feature_stride=stride,
        scales=(2, 4, 8), ratios=(0.5, 1, 2), batch_rois=64, fg_fraction=0.5,
    )
    lab = label.asnumpy()[0]
    n_fg = (lab == 1).sum()
    n_bg = (lab == 0).sum()
    assert n_fg <= 32
    assert n_fg + n_bg == 64
    # weights exactly mark fg anchors
    w = bw.asnumpy()[0]
    assert ((w[:, 0] == 1) == (lab == 1)).all()
    # two different noises give different subsets (randomness flows through)
    noise2 = rng.rand(B, Hf * Wf * 9, 2).astype(np.float32)
    lab2 = nd.contrib.rpn_anchor_target(
        nd.array(gt), nd.array(im_info), nd.array(noise2),
        feat_height=Hf, feat_width=Wf, feature_stride=stride,
        scales=(2, 4, 8), ratios=(0.5, 1, 2), batch_rois=64, fg_fraction=0.5,
    )[0].asnumpy()[0]
    assert (lab != lab2).any()


def test_rpn_anchor_target_no_gt():
    im_info = np.array([[128, 128, 1.0]], np.float32)
    gt = np.full((1, 3, 5), -1.0, np.float32)
    label, bt, bw = (
        o.asnumpy() for o in nd.contrib.rpn_anchor_target(
            nd.array(gt), nd.array(im_info),
            feat_height=16, feat_width=16, feature_stride=8,
            scales=(2, 4), ratios=(1.0,), batch_rois=32,
        )
    )
    assert (label[0] == 1).sum() == 0
    assert (label[0] == 0).sum() == 32
    assert (bw == 0).all()


def _np_iou_p1(a, b):
    tl = np.maximum(a[:, None, :2], b[None, :, :2])
    br = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(br - tl + 1, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1)
    area_b = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-12)


@pytest.mark.parametrize("class_agnostic", [False, True])
def test_proposal_target_semantics(class_agnostic):
    rng = np.random.RandomState(5)
    B, post, G = 2, 40, 4
    im_h = im_w = 200
    gt = _rand_gt(rng, B, G, im_h, im_w, [3, 2])
    rois = np.zeros((B * post, 5), np.float32)
    for b in range(B):
        ctr = rng.rand(post, 2) * 160 + 20
        wh = rng.rand(post, 2) * 60 + 10
        rois[b * post:(b + 1) * post, 0] = b
        rois[b * post:(b + 1) * post, 1:3] = np.maximum(ctr - wh / 2, 0)
        rois[b * post:(b + 1) * post, 3:5] = np.minimum(ctr + wh / 2, 199)
    num_classes, batch_rois, fgf = 4, 32, 0.25
    noise = rng.rand(B, post + G, 2).astype(np.float32)
    out_rois, label, bt, bw = (
        o.asnumpy() for o in nd.contrib.proposal_target(
            nd.array(rois), nd.array(gt), nd.array(noise),
            num_classes=num_classes, batch_images=B, batch_rois=batch_rois,
            fg_fraction=fgf, class_agnostic=class_agnostic,
        )
    )
    K = 2 if class_agnostic else num_classes
    per_im = batch_rois // B
    fg_cap = int(round(fgf * per_im))
    assert out_rois.shape == (batch_rois, 5)
    assert bt.shape == (batch_rois, 4 * K) and bw.shape == (batch_rois, 4 * K)
    for b in range(B):
        sl = slice(b * per_im, (b + 1) * per_im)
        sel, lab, t, w = out_rois[sl], label[sl], bt[sl], bw[sl]
        assert (sel[:, 0] == b).all()
        n_fg = (lab > 0).sum()
        assert n_fg <= fg_cap
        gt_b = gt[b][gt[b][:, 0] >= 0]
        iou = _np_iou_p1(sel[:, 1:5], gt_b[:, 1:5])
        max_iou = iou.max(axis=1)
        # fg slots: iou >= 0.5 and class = gt class + 1; bg slots iou < 0.5
        assert (max_iou[lab > 0] >= 0.5 - 1e-6).all()
        assert (max_iou[lab == 0] < 0.5 + 1e-6).all()
        for j in range(per_im):
            if lab[j] > 0:
                k = 1 if class_agnostic else int(lab[j])
                assert w[j, 4 * k:4 * k + 4].sum() == 4
                assert w[j].sum() == 4
                # regression target points at the matched gt
                g = gt_b[iou[j].argmax()]
                ex = sel[j, 1:5]
                ew, eh = ex[2] - ex[0] + 1, ex[3] - ex[1] + 1
                exp_dx = ((g[1] + g[3]) / 2 - (ex[0] + ex[2]) / 2) / ew
                np.testing.assert_allclose(t[j, 4 * k], exp_dx, rtol=1e-3, atol=1e-4)
            else:
                assert w[j].sum() == 0


def test_proposal_target_includes_gt_and_degenerate():
    # gt boxes join the candidate set => with fg noise favoring them they are
    # sampled and get label = cls+1 at IoU 1
    gt = np.array([[[2.0, 10, 10, 60, 60]]], np.float32)
    rois = np.zeros((4, 5), np.float32)
    rois[:, 1:5] = [100, 100, 140, 140]  # no overlap with gt
    noise = np.ones((1, 5, 2), np.float32) * 0.5
    noise[0, 4, 0] = 0.0  # gt candidate wins fg sampling
    out_rois, label, bt, bw = (
        o.asnumpy() for o in nd.contrib.proposal_target(
            nd.array(rois), nd.array(gt), nd.array(noise),
            num_classes=4, batch_images=1, batch_rois=4, fg_fraction=0.25,
        )
    )
    assert label[0] == 3.0  # cls 2 + 1
    np.testing.assert_allclose(out_rois[0, 1:5], [10, 10, 60, 60])
    # fg target vs itself is (0,0,0,0)
    np.testing.assert_allclose(bt[0, 12:16], 0, atol=1e-5)

    # degenerate: no gt at all -> all-bg, zero weights
    gt_e = np.full((1, 2, 5), -1.0, np.float32)
    _, label_e, _, bw_e = (
        o.asnumpy() for o in nd.contrib.proposal_target(
            nd.array(rois), nd.array(gt_e),
            num_classes=4, batch_images=1, batch_rois=4,
        )
    )
    assert (label_e == 0).all() and (bw_e == 0).all()


def test_targets_jit_fuse():
    """Both ops trace into a jitted function (static shapes end-to-end)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.rcnn_targets import rpn_anchor_target, proposal_target

    B, Hf, Wf, G, post = 1, 6, 6, 3, 20

    @jax.jit
    def f(gt, info, rois, nz1, nz2):
        lab, bt, bw = rpn_anchor_target(
            gt, info, nz1, feat_height=Hf, feat_width=Wf, feature_stride=8,
            scales=(4,), ratios=(1.0,), batch_rois=16)
        r, l2, t2, w2 = proposal_target(
            rois, gt, nz2, num_classes=3, batch_images=B, batch_rois=8)
        return lab.sum() + bt.sum() + bw.sum() + r.sum() + l2.sum() + t2.sum() + w2.sum()

    rng = np.random.RandomState(0)
    gt = jnp.asarray(_rand_gt(rng, B, G, 48, 48, [2]))
    info = jnp.asarray(np.array([[48, 48, 1.0]], np.float32))
    rois = jnp.asarray(
        np.concatenate([np.zeros((post, 1)), rng.rand(post, 2) * 20,
                        rng.rand(post, 2) * 20 + 24], axis=1).astype(np.float32))
    v = f(gt, info, rois,
          jnp.asarray(rng.rand(B, Hf * Wf, 2), jnp.float32),
          jnp.asarray(rng.rand(B, post + G, 2), jnp.float32))
    assert np.isfinite(float(v))


def test_proposal_target_bg_starved_pads_with_fg():
    """Every candidate >= fg_overlap: pad slots must repeat sampled fgs WITH
    their true fg labels (reference sample_rois pads by repeating indices),
    never relabel a high-IoU box as background."""
    gt = np.array([[[1.0, 0, 0, 180, 180]]], np.float32)
    rois = np.zeros((6, 5), np.float32)
    rois[:, 1:5] = [2, 2, 178, 178]  # all IoU ~1 with the gt
    out_rois, label, bt, bw = (
        o.asnumpy() for o in nd.contrib.proposal_target(
            nd.array(rois), nd.array(gt),
            num_classes=3, batch_images=1, batch_rois=8, fg_fraction=0.25,
        )
    )
    assert (label == 2.0).all(), label  # cls 1 + 1, no fake backgrounds
    assert (bw.reshape(8, -1).sum(1) == 4).all()
