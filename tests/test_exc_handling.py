"""Async exception propagation (reference tests/python/unittest/
test_exc_handling.py + docs/architecture/exception_handling.md).

The reference's threaded engine catches worker-thread exceptions, stores
them on the opr/var as ``std::exception_ptr``, and rethrows at
``WaitForVar`` (threaded_engine.h:178, ThrowException threaded_engine.cc:464).
The TPU-native analog: jax dispatch is async; host-side errors (CustomOp
callbacks, shape/type inference) and device-side errors surface at the
sync point (``asnumpy``/``wait_to_read``) or at call time for trace-time
checks — and the runtime must stay usable afterwards.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


@mx.operator.register("_raises_fwd")
class _RaisesProp(mx.operator.CustomOpProp):
    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def create_operator(self, ctx, shapes, dtypes):
        class _Raises(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                raise RuntimeError("injected forward failure")

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                raise RuntimeError("injected backward failure")

        return _Raises()


def test_customop_forward_exception_surfaces_at_sync():
    x = nd.ones((2, 2))
    with pytest.raises(Exception, match="injected forward failure"):
        out = nd.Custom(x, op_type="_raises_fwd")
        out.asnumpy()  # sync point — reference: WaitForVar rethrow


def test_engine_usable_after_exception():
    """After a failed op the runtime keeps working (reference test:
    exception must not poison the engine/worker threads)."""
    x = nd.ones((2, 2))
    with pytest.raises(Exception):
        nd.Custom(x, op_type="_raises_fwd").asnumpy()
    y = (x + 1).asnumpy()
    np.testing.assert_array_equal(y, 2 * np.ones((2, 2)))


def test_backward_exception_propagates():
    @mx.operator.register("_raises_bwd")
    class _BwdProp(mx.operator.CustomOpProp):
        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["output"]

        def create_operator(self, ctx, shapes, dtypes):
            class _Op(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0])

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    raise ValueError("injected backward failure")

            return _Op()

    x = nd.ones((2, 2))
    x.attach_grad()
    with pytest.raises(Exception, match="injected backward failure"):
        with autograd.record():
            out = nd.Custom(x, op_type="_raises_bwd")
            loss = out.sum()
        loss.backward()
        x.grad.asnumpy()  # sync


def test_shape_error_raises_at_call():
    """Trace-time errors (shape mismatch) raise immediately — the analog of
    the reference's synchronous infer-shape failures."""
    with pytest.raises(Exception):
        nd.dot(nd.ones((2, 3)), nd.ones((2, 3)))


def test_infer_shape_error_names_missing_arg():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    with pytest.raises(mx.MXNetError, match="data"):
        fc.infer_shape()  # underdetermined: no shapes at all


def test_wait_to_read_and_waitall_rethrow():
    """mx.nd.waitall()-style sync also surfaces pending failures
    (reference: WaitForAll rethrow semantics differ by version; ours
    guarantees the per-array sync raises)."""
    x = nd.ones((4,))
    with pytest.raises(Exception) as ei:
        bad = nd.Custom(x, op_type="_raises_fwd")
        # surfaces at dispatch (eager sync backend) or here at the latest
        bad.wait_to_read()
    # the host failure is carried inside the runtime error (jax wraps the
    # callback traceback, like the reference wrapped std::exception_ptr)
    assert ("injected forward failure" in str(ei.value)
            or "CpuCallback" in str(ei.value))
