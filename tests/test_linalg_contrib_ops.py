"""Linalg / contrib / optimizer-update / multisample op tests — mirrors
reference tests/python/unittest/test_operator.py (test_laop*, test_ctc_loss,
test_quadratic_function, test_correlation, ...)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


@pytest.fixture
def rng():
    return np.random.RandomState(0)


class TestLinalg:
    def test_potrf_potri(self, rng):
        A = rng.randn(2, 3, 3).astype(np.float32)
        spd = np.matmul(A, A.transpose(0, 2, 1)) + 3 * np.eye(3, dtype=np.float32)
        L = nd.linalg_potrf(nd.array(spd))
        np.testing.assert_allclose(
            np.matmul(L.asnumpy(), L.asnumpy().transpose(0, 2, 1)), spd, rtol=1e-4, atol=1e-4
        )
        inv = nd.linalg_potri(L)
        np.testing.assert_allclose(
            np.matmul(inv.asnumpy(), spd),
            np.broadcast_to(np.eye(3, dtype=np.float32), (2, 3, 3)), atol=1e-3,
        )

    def test_gemm_gemm2(self, rng):
        A = rng.randn(2, 3, 3).astype(np.float32)
        B = rng.randn(2, 3, 4).astype(np.float32)
        C = rng.randn(2, 3, 4).astype(np.float32)
        g = nd.linalg_gemm(nd.array(A), nd.array(B), nd.array(C), alpha=2.0, beta=0.5)
        np.testing.assert_allclose(g.asnumpy(), 2 * np.matmul(A, B) + 0.5 * C, rtol=1e-4)
        g2 = nd.linalg_gemm2(nd.array(A), nd.array(B), transpose_a=True)
        np.testing.assert_allclose(g2.asnumpy(), np.matmul(A.transpose(0, 2, 1), B), rtol=1e-4)

    def test_trsm_trmm(self, rng):
        A = rng.randn(2, 3, 3).astype(np.float32)
        spd = np.matmul(A, A.transpose(0, 2, 1)) + 3 * np.eye(3, dtype=np.float32)
        L = nd.linalg_potrf(nd.array(spd))
        B = rng.randn(2, 3, 4).astype(np.float32)
        X = nd.linalg_trsm(L, nd.array(B), alpha=1.5)
        np.testing.assert_allclose(np.matmul(L.asnumpy(), X.asnumpy()), 1.5 * B, rtol=1e-3, atol=1e-3)
        B2 = rng.randn(2, 4, 3).astype(np.float32)
        X2 = nd.linalg_trsm(L, nd.array(B2), rightside=True, alpha=2.0)
        np.testing.assert_allclose(np.matmul(X2.asnumpy(), L.asnumpy()), 2.0 * B2, rtol=1e-3, atol=1e-3)
        X3 = nd.linalg_trsm(L, nd.array(B), transpose=True)
        np.testing.assert_allclose(
            np.matmul(L.asnumpy().transpose(0, 2, 1), X3.asnumpy()), B, rtol=1e-3, atol=1e-3
        )
        tm = nd.linalg_trmm(L, nd.array(B))
        np.testing.assert_allclose(tm.asnumpy(), np.matmul(np.tril(L.asnumpy()), B), rtol=1e-4)

    def test_sumlogdiag_syrk(self, rng):
        A = rng.randn(2, 3, 3).astype(np.float32)
        spd = np.matmul(A, A.transpose(0, 2, 1)) + 3 * np.eye(3, dtype=np.float32)
        sld = nd.linalg_sumlogdiag(nd.array(spd))
        np.testing.assert_allclose(
            sld.asnumpy(), np.log(np.diagonal(spd, axis1=-2, axis2=-1)).sum(-1), rtol=1e-4
        )
        sy = nd.linalg_syrk(nd.array(A), alpha=1.0)
        np.testing.assert_allclose(sy.asnumpy(), np.matmul(A, A.transpose(0, 2, 1)), rtol=1e-4)
        syt = nd.linalg_syrk(nd.array(A), transpose=True, alpha=0.5)
        np.testing.assert_allclose(syt.asnumpy(), 0.5 * np.matmul(A.transpose(0, 2, 1), A), rtol=1e-4)

    def test_gelqf_syevd(self, rng):
        M = rng.randn(3, 5).astype(np.float32)
        Lq, Q = nd.linalg_gelqf(nd.array(M))
        np.testing.assert_allclose(np.matmul(Lq.asnumpy(), Q.asnumpy()), M, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.matmul(Q.asnumpy(), Q.asnumpy().T), np.eye(3), atol=1e-4)
        A = rng.randn(4, 4).astype(np.float32)
        spd = A @ A.T + 4 * np.eye(4, dtype=np.float32)
        U, lam = nd.linalg_syevd(nd.array(spd))
        recon = U.asnumpy().T @ np.diag(lam.asnumpy()) @ U.asnumpy()
        np.testing.assert_allclose(recon, spd, rtol=1e-3, atol=1e-3)

    def test_gelqf_syevd_symbolic_two_outputs(self, rng):
        from mxnet_tpu import sym

        s = sym.linalg_gelqf(sym.Variable("A"))
        assert len(s.list_outputs()) == 2
        exe = s.simple_bind(A=(3, 5))
        M = rng.randn(3, 5).astype(np.float32)
        L, Q = exe.forward(is_train=False, A=nd.array(M))
        np.testing.assert_allclose(np.matmul(L.asnumpy(), Q.asnumpy()), M, rtol=1e-3, atol=1e-3)
        s2 = sym.linalg_syevd(sym.Variable("A"))
        assert len(s2.list_outputs()) == 2

    def test_gemm_grad_flows(self, rng):
        from mxnet_tpu import autograd

        a = nd.array(rng.randn(3, 3).astype(np.float32))
        a.attach_grad()
        with autograd.record():
            y = nd.linalg_gemm2(a, a)
            loss = y.sum()
        loss.backward()
        assert np.abs(a.grad.asnumpy()).sum() > 0


class TestOptimizerUpdateOps:
    def test_sgd_matches_formula(self, rng):
        w0 = rng.randn(5).astype(np.float32)
        g0 = rng.randn(5).astype(np.float32)
        w = nd.array(w0); g = nd.array(g0)
        nd.sgd_update(w, g, out=w, lr=0.1, wd=0.01, rescale_grad=0.5)
        expect = w0 - 0.1 * (0.5 * g0 + 0.01 * w0)
        np.testing.assert_allclose(w.asnumpy(), expect, rtol=1e-5)

    def test_mutation_semantics(self, rng):
        w = nd.array(rng.randn(4).astype(np.float32))
        g = nd.array(rng.randn(4).astype(np.float32))
        m = nd.zeros((4,)); v = nd.zeros((4,))
        w0 = w.asnumpy().copy()
        nd.adam_update(w, g, m, v, out=w, lr=0.1)
        assert not np.allclose(w.asnumpy(), w0)
        assert not np.allclose(m.asnumpy(), 0)
        assert not np.allclose(v.asnumpy(), 0)

    def test_adam_matches_formula(self, rng):
        w0 = rng.randn(5).astype(np.float32); g0 = rng.randn(5).astype(np.float32)
        m0 = rng.randn(5).astype(np.float32); v0 = rng.rand(5).astype(np.float32)
        w = nd.array(w0); g = nd.array(g0); m = nd.array(m0); v = nd.array(v0)
        nd.adam_update(w, g, m, v, out=w, lr=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8)
        me = 0.9 * m0 + 0.1 * g0
        ve = 0.999 * v0 + 0.001 * g0 * g0
        we = w0 - 0.01 * me / (np.sqrt(ve) + 1e-8)
        np.testing.assert_allclose(m.asnumpy(), me, rtol=1e-5)
        np.testing.assert_allclose(v.asnumpy(), ve, rtol=1e-5)
        np.testing.assert_allclose(w.asnumpy(), we, rtol=1e-5)

    def test_adam_clips_after_wd(self, rng):
        """Reference AdamUpdate clips (rescale*grad + wd*weight), not just
        the gradient (optimizer_op-inl.h AdamUpdate)."""
        w0 = np.full(3, 100.0, np.float32)
        g0 = np.zeros(3, np.float32)
        w = nd.array(w0); g = nd.array(g0)
        m = nd.zeros((3,)); v = nd.zeros((3,))
        nd.adam_update(w, g, m, v, out=w, lr=0.1, wd=1.0, clip_gradient=1.0)
        # effective grad = clip(0 + 1.0*100) = 1.0 -> mean = 0.1
        np.testing.assert_allclose(m.asnumpy(), np.full(3, 0.1), rtol=1e-5)

    def test_all_updates_run(self, rng):
        w = nd.array(rng.randn(4).astype(np.float32))
        g = nd.array(rng.randn(4).astype(np.float32))
        nd.sgd_mom_update(w, g, nd.zeros((4,)), out=w, lr=0.1, momentum=0.9)
        nd.ftrl_update(w, g, nd.zeros((4,)), nd.zeros((4,)), out=w, lr=0.1)
        nd.rmsprop_update(w, g, nd.zeros((4,)), out=w, lr=0.01)
        nd.rmspropalex_update(w, g, nd.zeros((4,)), nd.zeros((4,)), nd.zeros((4,)), out=w, lr=0.01)
        nd.signsgd_update(w, g, out=w, lr=0.01)
        nd.signum_update(w, g, nd.zeros((4,)), out=w, lr=0.01, momentum=0.9)
        nd.ftml_update(w, g, nd.zeros((4,)), nd.zeros((4,)), nd.zeros((4,)), out=w, lr=0.01, t=1)
        w16 = nd.cast(w, dtype="float16"); w32 = nd.array(w.asnumpy())
        nd.mp_sgd_update(w16, nd.cast(g, dtype="float16"), w32, out=w16, lr=0.1)
        assert np.isfinite(w.asnumpy()).all() and np.isfinite(w16.asnumpy()).all()


class TestContribOps:
    def test_fft_ifft(self, rng):
        x = rng.randn(2, 8).astype(np.float32)
        f = nd.fft(nd.array(x))
        fr = np.fft.fft(x, axis=-1)
        got = f.asnumpy().reshape(2, 8, 2)
        np.testing.assert_allclose(got[..., 0], fr.real, rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(got[..., 1], fr.imag, rtol=1e-3, atol=1e-3)
        # cuFFT-style unnormalized inverse: ifft(fft(x)) == x * d
        back = nd.ifft(f)
        np.testing.assert_allclose(back.asnumpy(), x * 8, rtol=1e-3, atol=1e-3)

    def test_quadratic_khatri_rao(self, rng):
        x = rng.randn(2, 8).astype(np.float32)
        q = nd.quadratic(nd.array(x), a=1.0, b=2.0, c=3.0)
        np.testing.assert_allclose(q.asnumpy(), x * x + 2 * x + 3, rtol=1e-5)
        A = np.arange(6).reshape(2, 3).astype(np.float32)
        B = rng.randn(4, 3).astype(np.float32)
        kr = nd.khatri_rao(nd.array(A), nd.array(B))
        expect = np.stack([np.kron(A[:, k], B[:, k]) for k in range(3)], axis=1)
        np.testing.assert_allclose(kr.asnumpy(), expect, rtol=1e-5)

    def test_count_sketch(self, rng):
        x = rng.randn(2, 8).astype(np.float32)
        h = rng.randint(0, 5, (8,)).astype(np.float32)
        s = rng.choice([-1.0, 1.0], 8).astype(np.float32)
        cs = nd.count_sketch(nd.array(x), nd.array(h), nd.array(s), out_dim=5)
        ref = np.zeros((2, 5), np.float32)
        for i in range(8):
            ref[:, int(h[i])] += s[i] * x[:, i]
        np.testing.assert_allclose(cs.asnumpy(), ref, rtol=1e-4, atol=1e-5)

    def test_bilinear_resize(self, rng):
        img = rng.randn(1, 2, 4, 4).astype(np.float32)
        bz = nd.BilinearResize2D(nd.array(img), height=8, width=8)
        assert bz.shape == (1, 2, 8, 8)
        # align_corners: endpoints preserved
        np.testing.assert_allclose(bz.asnumpy()[:, :, 0, 0], img[:, :, 0, 0], rtol=1e-5)
        np.testing.assert_allclose(bz.asnumpy()[:, :, 7, 7], img[:, :, 3, 3], rtol=1e-5)

    def test_div_sqrt_dim_crop(self, rng):
        x = rng.randn(2, 8).astype(np.float32)
        np.testing.assert_allclose(nd.div_sqrt_dim(nd.array(x)).asnumpy(), x / np.sqrt(8), rtol=1e-5)
        img = rng.randn(1, 2, 4, 4).astype(np.float32)
        cr = nd.Crop(nd.array(img), offset=(1, 1), h_w=(2, 2))
        np.testing.assert_allclose(cr.asnumpy(), img[:, :, 1:3, 1:3])
        cc = nd.Crop(nd.array(img), h_w=(2, 2), center_crop=True)
        np.testing.assert_allclose(cc.asnumpy(), img[:, :, 1:3, 1:3])

    def test_correlation_naive(self, rng):
        def naive(d1, d2, ks, md, pad):
            n, c, h, w = d1.shape
            d1p = np.pad(d1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
            d2p = np.pad(d2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
            kr = (ks - 1) // 2
            border = md + kr
            ph, pw = h + 2 * pad, w + 2 * pad
            th = int(np.ceil((ph - 2 * border) / 1))
            tw = int(np.ceil((pw - 2 * border) / 1))
            gr = md
            gw = 2 * gr + 1
            out = np.zeros((n, gw * gw, th, tw), np.float32)
            for b in range(n):
                for oi, dy in enumerate(range(-gr, gr + 1)):
                    for oj, dx in enumerate(range(-gr, gr + 1)):
                        for yi, y in enumerate(range(border, ph - border)):
                            for xi, x in enumerate(range(border, pw - border)):
                                acc = 0.0
                                for ky in range(-kr, kr + 1):
                                    for kx in range(-kr, kr + 1):
                                        a = d1p[b, :, y + ky, x + kx]
                                        bb = d2p[b, :, y + ky + dy, x + kx + dx]
                                        acc += (a * bb).sum()
                                out[b, oi * gw + oj, yi, xi] = acc / (ks * ks * c)
            return out

        d1 = rng.randn(1, 2, 6, 6).astype(np.float32)
        d2 = rng.randn(1, 2, 6, 6).astype(np.float32)
        ref = naive(d1, d2, 1, 1, 1)
        got = nd.Correlation(
            nd.array(d1), nd.array(d2), kernel_size=1, max_displacement=1, pad_size=1
        ).asnumpy()
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


class TestCTCLoss:
    def test_matches_torch(self, rng):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F

        T, N, C = 12, 3, 6
        acts = rng.randn(T, N, C).astype(np.float32)
        labels = np.array([[1, 2, 3, 0], [2, 2, 0, 0], [5, 4, 3, 2]], np.float32)
        lab_lens = np.array([3, 2, 4])
        loss = nd.ctc_loss(nd.array(acts), nd.array(labels)).asnumpy()
        lp = torch.log_softmax(torch.tensor(acts), dim=-1)
        tl = F.ctc_loss(
            lp, torch.tensor(labels.astype(np.int64)),
            input_lengths=torch.full((N,), T, dtype=torch.long),
            target_lengths=torch.tensor(lab_lens), blank=0, reduction="none",
        )
        np.testing.assert_allclose(loss, tl.numpy(), rtol=1e-3, atol=1e-3)

    def test_lengths_and_symbol(self, rng):
        from mxnet_tpu import sym

        T, N, C = 10, 2, 5
        acts = rng.randn(T, N, C).astype(np.float32)
        labels = np.array([[1, 2, 3], [2, 1, 1]], np.float32)
        l1 = nd.ctc_loss(
            nd.array(acts), nd.array(labels),
            nd.array(np.array([10, 8], np.float32)), nd.array(np.array([2, 3], np.float32)),
            use_data_lengths=True, use_label_lengths=True,
        )
        assert l1.shape == (2,) and np.isfinite(l1.asnumpy()).all()
        out = sym.ctc_loss(sym.Variable("data"), sym.Variable("label"))
        exe = out.simple_bind(data=(T, N, C), label=(2, 3))
        (y,) = exe.forward(is_train=False, data=nd.array(acts), label=nd.array(labels))
        assert y.shape == (2,)


class TestMultisample:
    def test_sample_uniform_normal(self, rng):
        low = nd.array(np.array([0.0, 10.0], np.float32))
        high = nd.array(np.array([1.0, 20.0], np.float32))
        a = nd.sample_uniform(low, high, shape=(100,)).asnumpy()
        assert a.shape == (2, 100) and a[0].max() <= 1.0 and a[1].min() >= 10.0
        sn = nd.sample_normal(
            nd.array(np.array([0.0, 100.0], np.float32)),
            nd.array(np.array([1.0, 1.0], np.float32)), shape=(50,),
        ).asnumpy()
        assert abs(sn[1].mean() - 100) < 1

    def test_sample_counts(self, rng):
        sp = nd.sample_poisson(nd.array(np.array([1.0, 50.0], np.float32)), shape=(200,)).asnumpy()
        assert abs(sp[1].mean() - 50) < 5
        sg = nd.sample_gamma(
            nd.array(np.array([2.0, 9.0], np.float32)),
            nd.array(np.array([1.0, 0.5], np.float32)), shape=(500,),
        ).asnumpy()
        assert abs(sg[0].mean() - 2.0) < 0.5 and abs(sg[1].mean() - 4.5) < 0.8
        se = nd.sample_exponential(nd.array(np.array([1.0, 10.0], np.float32)), shape=(500,)).asnumpy()
        assert abs(se[0].mean() - 1.0) < 0.3 and abs(se[1].mean() - 0.1) < 0.05
        snb = nd.sample_negative_binomial(
            nd.array(np.array([4.0], np.float32)), nd.array(np.array([0.5], np.float32)), shape=(800,)
        ).asnumpy()
        assert abs(snb.mean() - 4.0) < 1.0
