#!/usr/bin/env python
"""Rebuild the .idx companion of a .rec pack — reference
``tools/rec2idx.py`` (IndexCreator walking the RecordIO framing and
emitting ``key\\toffset`` lines so MXIndexedRecordIO can random-access).

Usage: python tools/rec2idx.py data/train.rec data/train.idx
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from mxnet_tpu import recordio


def create_index(rec_path, idx_path, key_type=int):
    """Sequential scan; record i gets key i at its byte offset (reference
    IndexCreator.create_index)."""
    reader = recordio.MXRecordIO(rec_path, "r")
    counter = 0
    with open(idx_path, "w") as f:
        while True:
            pos = reader.tell()
            item = reader.read()
            if item is None:
                break
            f.write("%s\t%d\n" % (key_type(counter), pos))
            counter += 1
    reader.close()
    return counter


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("record", help="path to the .rec file")
    p.add_argument("index", help="path of the .idx to write")
    args = p.parse_args()
    n = create_index(args.record, args.index)
    print("wrote %d index entries to %s" % (n, args.index))


if __name__ == "__main__":
    main()
