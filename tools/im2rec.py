"""im2rec — pack an image folder into RecordIO (.rec/.idx/.lst).

Reference behavior: ``tools/im2rec.py`` (list generation, multi-worker image
packing into MXIndexedRecordIO).  JPEG re-encode uses PIL; record framing is
the native data plane (mxnet_tpu.recordio).

Usage:
  python tools/im2rec.py PREFIX ROOT --list        # make PREFIX.lst
  python tools/im2rec.py PREFIX ROOT               # pack PREFIX.lst -> .rec/.idx
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu import recordio

_EXTS = (".jpg", ".jpeg", ".png")


def list_image(root, recursive=True):
    """Yields (index, relpath, label) with labels from subfolder order."""
    cat = {}
    i = 0
    if recursive:
        for path, _, files in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(files):
                if os.path.splitext(fname)[1].lower() not in _EXTS:
                    continue
                if path not in cat:
                    cat[path] = len(cat)
                yield i, os.path.relpath(os.path.join(path, fname), root), cat[path]
                i += 1
    else:
        for fname in sorted(os.listdir(root)):
            if os.path.splitext(fname)[1].lower() in _EXTS:
                yield i, fname, 0
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for idx, relpath, label in image_list:
            fout.write("%d\t%f\t%s\n" % (idx, float(label), relpath))


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            # idx \t label(s)... \t path
            yield int(float(parts[0])), [float(x) for x in parts[1:-1]], parts[-1]


def pack_list(prefix, root, resize=0, quality=95):
    """Packs PREFIX.lst into PREFIX.rec + PREFIX.idx."""
    from PIL import Image

    record = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, labels, relpath in read_list(prefix + ".lst"):
        img = Image.open(os.path.join(root, relpath)).convert("RGB")
        if resize:
            w, h = img.size
            scale = resize / min(w, h)
            img = img.resize((max(1, int(w * scale)), max(1, int(h * scale))))
        label = labels[0] if len(labels) == 1 else np.array(labels, dtype=np.float32)
        header = recordio.IRHeader(0, label, idx, 0)
        record.write_idx(idx, recordio.pack_img(header, np.asarray(img), quality=quality))
        count += 1
    record.close()
    return count


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix")
    p.add_argument("root")
    p.add_argument("--list", action="store_true", help="generate PREFIX.lst only")
    p.add_argument("--recursive", action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--shuffle", type=int, default=1)
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--quality", type=int, default=95)
    args = p.parse_args()
    if args.list:
        images = list(list_image(args.root, args.recursive))
        if args.shuffle:
            random.seed(100)
            random.shuffle(images)
        write_list(args.prefix + ".lst", images)
        print("wrote %d entries to %s.lst" % (len(images), args.prefix))
    else:
        if not os.path.isfile(args.prefix + ".lst"):
            images = list(list_image(args.root, args.recursive))
            if args.shuffle:
                random.seed(100)
                random.shuffle(images)
            write_list(args.prefix + ".lst", images)
        n = pack_list(args.prefix, args.root, resize=args.resize, quality=args.quality)
        print("packed %d images into %s.rec" % (n, args.prefix))


if __name__ == "__main__":
    main()
