#!/usr/bin/env python
"""Load generator for the serving engine (ISSUE 2) — SERVE_BENCH emitter.

Drives a ``mxnet_tpu.serving.Engine`` with synthetic traffic and prints one
``SERVE_BENCH {json}`` line per run (schema linted by
``ci/check_bench_schema.py``; docs/SERVING.md documents every field).

Two generator modes, the standard pair:

* **closed loop** (``--mode closed``): ``--concurrency`` workers each
  submit-and-wait in a tight loop — measures capacity (the system sets the
  rate; latency stays near service time).
* **open loop** (``--mode open``): one dispatcher fires requests on a
  Poisson clock at ``--rate`` req/s regardless of completions — measures
  behavior under offered load, including queueing delay and shedding
  (closed-loop load generators famously hide both).

``--mode both`` runs closed then open and emits two lines.  Request sizes
are drawn from ``--sizes`` (mixed-shape stream exercising the whole bucket
ladder); ``--smoke`` is the CI preset: tiny MLP, short run, CPU-safe.

Mixed-priority traffic (ISSUE 17): ``--class-mix paid:0.2,best_effort:0.8``
stamps each request with a priority class drawn at those weights, and
``--slo-ms`` then also accepts per-priority targets
(``paid:25,best_effort:100``).  The SERVE_BENCH line gains a ``priority``
block (per-class requests/completed/``sheds``/``downgrades``/percentiles/
goodput).  ``--router degrade|shed`` serves through a
``serving.Router`` over fp32+bf16 twin pools (``--replicas`` engines per
tier) instead of a bare Engine — ``downgrades`` counts completions whose
reply tier label differs from the native tier.

Examples::

    python tools/loadgen.py --smoke
    python tools/loadgen.py --mode both --duration 2 --rate 300 \
        --batch-ladder 1,2,4,8 --concurrency 8
    python tools/loadgen.py --symbol m-symbol.json --params m-0000.params \
        --input data:3,224,224 --mode open --rate 50
    python tools/loadgen.py --mode open --rate 2000 --router degrade \
        --class-mix paid:0.2,best_effort:0.8 \
        --slo-ms paid:25,best_effort:100
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..")))


def _tiny_engine(args):
    """Default workload: the test-suite MLP (8 -> 16 -> 4 softmax) with
    random params, no checkpoint files needed — the CPU smoke target."""
    from mxnet_tpu import serving
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    sym, params = tiny_mlp_checkpoint(seed=args.seed)
    return serving.Engine(
        sym, params, {"data": (8,)},
        ladder=serving.BucketLadder(args.ladder),
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        name="loadgen", start=True), {"data": (8,)}


def _input_shapes(args):
    shapes = {}
    for spec in args.input:
        name, _, dims = spec.partition(":")
        shapes[name] = tuple(int(d) for d in dims.split(",") if d)
    return shapes


def _file_engine(args):
    from mxnet_tpu import serving

    shapes = _input_shapes(args)
    return serving.Engine(
        args.symbol, args.params, shapes,
        ladder=serving.BucketLadder(args.ladder),
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        name="loadgen", start=True), shapes


def _router_target(args):
    """--router: fp32+bf16 twin pools behind a serving.Router (ISSUE 17).
    The policy mode comes from the flag (degrade-first vs the shed-only
    baseline), replica count per tier from --replicas."""
    from mxnet_tpu import serving
    from mxnet_tpu.test_utils import tiny_mlp_checkpoint

    if args.symbol:
        sym, params, shapes = args.symbol, args.params, _input_shapes(args)
    else:
        sym, params = tiny_mlp_checkpoint(seed=args.seed)
        shapes = {"data": (8,)}
    reg = serving.ModelRegistry()
    model = reg.register("loadgen", sym, params, shapes,
                         tiers=("fp32", "bf16"),
                         ladder=serving.BucketLadder(args.ladder),
                         max_wait_ms=args.max_wait_ms,
                         max_queue=args.max_queue)
    return serving.Router(model, replicas=args.replicas, policy=args.router,
                          name="loadgen"), shapes


def _parse_class_mix(spec):
    """'paid:0.2,best_effort:0.8' -> normalized [(priority, weight)]."""
    if not spec:
        return None
    mix = []
    for item in str(spec).split(","):
        item = item.strip()
        if not item:
            continue
        name, _, w = item.partition(":")
        mix.append((name.strip(), float(w or 1.0)))
    total = sum(w for _, w in mix)
    if not mix or total <= 0:
        raise ValueError("--class-mix needs positive weights, got %r"
                         % (spec,))
    return [(n, w / total) for n, w in mix]


def _draw_priority(mix, u):
    """u in [0,1) -> priority class at the mix's weights."""
    acc = 0.0
    for name, w in mix:
        acc += w
        if u < acc:
            return name
    return mix[-1][0]


def _parse_slo(spec):
    """--slo-ms value -> (scalar_ms, {priority: ms}).  A bare number is
    the classic single target; 'paid:25,best_effort:100' sets per-priority
    targets (scalar 0, so unlisted traffic always counts as good)."""
    s = str(spec if spec is not None else "").strip()
    if not s:
        return 0.0, {}
    if ":" not in s:
        return float(s), {}
    out = {}
    for item in s.split(","):
        item = item.strip()
        if not item:
            continue
        name, _, v = item.partition(":")
        if name.strip():
            out[name.strip()] = float(v)
    return 0.0, out


def _make_request(shapes, sizes, rng):
    n = rng.choice(sizes)
    return {name: np.asarray(
        rng.standard_normal((n,) + tuple(s)), dtype=np.float32)
        for name, s in shapes.items()}


class _Collector:
    """Thread-safe latency/outcome accumulator; optionally records the
    request trace (``--save-trace``, ISSUE 9): one (arrival time, size,
    shapes, class) record per submission attempt, the offline input the
    bucket-ladder tuner replays (``mxnet_tpu.autotune.ladder``)."""

    def __init__(self, trace_log=None, t_origin=None, slo_ms=0.0,
                 class_slo=None, native_tier="fp32"):
        self.mu = threading.Lock()
        self.latencies = []
        self.by_class = {}     # size class (str(n)) -> [latencies]
        self.good = 0          # completions meeting --slo-ms (all, if 0)
        self.slo_ms = float(slo_ms or 0.0)
        self.class_slo = dict(class_slo or {})  # priority -> target ms
        self.native_tier = native_tier or "fp32"
        # priority class -> accumulators (ISSUE 17): downgrades counts
        # completions whose reply tier label differs from the native tier
        self.by_priority = {}
        self.submitted = 0
        self.shed = 0
        self.timeouts = 0
        self.errors = 0
        self.in_window = None  # open loop: completions inside the window
        self.trace_log = trace_log
        self.t_origin = t_origin

    def _prio(self, priority):
        e = self.by_priority.get(priority)
        if e is None:
            e = self.by_priority[priority] = {
                "latencies": [], "submitted": 0, "sheds": 0,
                "downgrades": 0, "good": 0}
        return e

    def slo_for(self, priority):
        """The goodput target for one completion: the per-priority target
        when declared, else the scalar --slo-ms."""
        if priority is not None and priority in self.class_slo:
            return self.class_slo[priority]
        return self.slo_ms

    def ok(self, seconds, klass=None, in_window=True, priority=None,
           tier=None):
        """One completion.  ``klass`` buckets the per-class percentiles
        (ISSUE 10 / ROADMAP item 1: per-class P50/P99 + goodput);
        ``in_window`` gates goodput in the open loop (late-drain
        completions report latency but not phantom goodput, same rule as
        throughput); ``priority``/``tier`` feed the per-priority block
        (ISSUE 17 — tier is the reply's served-tier label)."""
        with self.mu:
            self.latencies.append(seconds)
            if klass is not None:
                self.by_class.setdefault(str(klass), []).append(seconds)
            target = self.slo_for(priority)
            good = in_window and (target <= 0 or seconds * 1e3 <= target)
            if good:
                self.good += 1
            if priority is not None:
                e = self._prio(priority)
                e["latencies"].append(seconds)
                if good:
                    e["good"] += 1
                if tier is not None and tier != self.native_tier:
                    e["downgrades"] += 1

    def count(self, field, n=1):
        with self.mu:
            setattr(self, field, getattr(self, field) + n)

    def prio_count(self, priority, field, n=1):
        with self.mu:
            self._prio(priority)[field] += n

    def trace(self, inputs, klass):
        """Record one request's trace line (no-op without --save-trace).
        ``t`` is seconds since the FIRST mode's start — one clock across a
        --mode both run, so replay ordering stays meaningful."""
        if self.trace_log is None:
            return
        n = next(iter(inputs.values())).shape[0]
        rec = {"t": round(time.monotonic() - self.t_origin, 6), "n": int(n),
               "shapes": {name: list(a.shape[1:])
                          for name, a in inputs.items()},
               "class": klass}
        with self.mu:
            self.trace_log.append(rec)


def _run_closed(engine, shapes, args, collector):
    from mxnet_tpu.serving import RequestTimeout, ServerBusy

    stop = time.monotonic() + args.duration
    mix = getattr(args, "class_mix", None)

    def worker(seed):
        rng = np.random.default_rng(seed)
        while time.monotonic() < stop:
            req_inputs = _make_request(shapes, args.sizes, rng)
            n = next(iter(req_inputs.values())).shape[0]
            prio = _draw_priority(mix, rng.random()) if mix else None
            collector.count("submitted")
            if prio is not None:
                collector.prio_count(prio, "submitted")
            collector.trace(req_inputs, prio or "closed")
            t0 = time.perf_counter()
            try:
                # submit + wait (not predict): the Request carries the
                # reply tier label the priority block's downgrades need
                req = engine.submit(req_inputs, timeout=args.timeout_s,
                                    klass=prio or str(n))
                req.result(None)
                collector.ok(time.perf_counter() - t0, klass=n,
                             priority=prio,
                             tier=getattr(req, "tier", None))
            except ServerBusy:
                collector.count("shed")
                if prio is not None:
                    collector.prio_count(prio, "sheds")
            except RequestTimeout:
                collector.count("timeouts")
            except Exception:
                collector.count("errors")

    threads = [threading.Thread(target=worker, args=(args.seed + i,),
                                daemon=True)
               for i in range(args.concurrency)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(args.duration + 30)
    return time.perf_counter() - t_start


def _run_open(engine, shapes, args, collector):
    from mxnet_tpu.serving import RequestTimeout, ServerBusy

    rng = np.random.default_rng(args.seed)
    jitter = random.Random(args.seed)
    mix = getattr(args, "class_mix", None)
    pending = []
    stop = time.monotonic() + args.duration
    t_start = time.perf_counter()
    next_fire = time.monotonic()
    while time.monotonic() < stop:
        now = time.monotonic()
        if now < next_fire:
            time.sleep(min(next_fire - now, 0.005))
            continue
        # Poisson arrivals: exponential inter-arrival gaps at --rate
        next_fire += jitter.expovariate(args.rate)
        prio = _draw_priority(mix, jitter.random()) if mix else None
        collector.count("submitted")
        if prio is not None:
            collector.prio_count(prio, "submitted")
        req_inputs = _make_request(shapes, args.sizes, rng)
        n = next(iter(req_inputs.values())).shape[0]
        collector.trace(req_inputs, prio or "open")
        try:
            pending.append((engine.submit(req_inputs, timeout=args.timeout_s,
                                          klass=prio or str(n)), n, prio))
        except ServerBusy:
            collector.count("shed")
            if prio is not None:
                collector.prio_count(prio, "sheds")
    # throughput window CLOSES here: the post-window drain below must not
    # deflate throughput_rps (completed/duration) in the overload regime
    # the open loop exists to measure
    duration = time.perf_counter() - t_start
    window_end = time.monotonic()
    collector.in_window = 0
    for req, n, prio in pending:
        try:
            req.result(timeout=30)
            # latency stamped at completion, not at this (late) harvest
            in_window = req.t_done <= window_end
            collector.ok(req.latency_s, klass=n, in_window=in_window,
                         priority=prio, tier=getattr(req, "tier", None))
            if in_window:
                collector.in_window += 1
        except RequestTimeout:
            collector.count("timeouts")
        except Exception:
            collector.count("errors")
    return duration


def _first_request_latencies(engine, shapes, sizes):
    """One serial request per size class, before any load traffic — the
    first-request latency an operator's health check (or first real user)
    sees.  After ``--no-warmup`` this measures the COLD path, compiles
    included — the restart metric the AOT cache (`MXNET_AOT_CACHE`,
    docs/PERF_NOTES.md "Restart warm") exists to collapse; after warmup it
    measures the all-hot floor.  → {str(n): ms}."""
    out = {}
    for n in sorted(set(sizes)):
        inputs = {name: np.zeros((n,) + tuple(s), np.float32)
                  for name, s in shapes.items()}
        t0 = time.perf_counter()
        engine.predict(inputs, timeout=60.0)
        out[str(n)] = round((time.perf_counter() - t0) * 1e3, 3)
    return out


def _priority_block(collector, duration):
    """SERVE_BENCH ``priority`` key: {priority: {requests, completed,
    sheds, downgrades, p50_ms, p99_ms, goodput_rps[, slo_ms]}}."""
    out = {}
    for prio, d in sorted(collector.by_priority.items()):
        lats = np.asarray(sorted(d["latencies"]), np.float64)
        entry = {
            "requests": d["submitted"],
            "completed": len(lats),
            "sheds": d["sheds"],
            "downgrades": d["downgrades"],
            "p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 3)
            if len(lats) else 0.0,
            "p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 3)
            if len(lats) else 0.0,
            "goodput_rps": round(d["good"] / duration, 2)
            if duration else 0.0,
        }
        target = collector.slo_for(prio)
        if target > 0:
            entry["slo_ms"] = target
        out[prio] = entry
    return out


def run(engine, shapes, args, mode, first_request_ms=None, warmup_s=None,
        trace_log=None, t_origin=None):
    stats0 = engine.stats()
    collector = _Collector(trace_log=trace_log, t_origin=t_origin,
                           slo_ms=getattr(args, "slo_ms", 0.0),
                           class_slo=getattr(args, "class_slo", None),
                           native_tier=stats0.get("precision_tier"))
    compiles_before = stats0["compiles"]
    runner = _run_closed if mode == "closed" else _run_open
    duration = runner(engine, shapes, args, collector)
    lat = np.asarray(sorted(collector.latencies), np.float64)
    completed = len(lat)
    # open loop: rate = completions INSIDE the offered window / window
    # (late drain completions report their latency but not phantom rate)
    thr_n = (collector.in_window if collector.in_window is not None
             else completed)
    # per-class percentiles (ROADMAP item 1, ISSUE 10): class = request
    # sample count — the size-mix axis the bucket ladder serves
    by_class = {
        k: {"p50_ms": round(float(np.percentile(v, 50)) * 1e3, 3),
            "p99_ms": round(float(np.percentile(v, 99)) * 1e3, 3),
            "n": len(v)}
        for k, v in sorted(collector.by_class.items()) if v}
    stats = engine.stats()
    line = {
        "mode": mode,
        "requests": collector.submitted,
        "completed": completed,
        "shed": collector.shed,
        "timeouts": collector.timeouts,
        "errors": collector.errors,
        "shed_rate": (collector.shed / collector.submitted
                      if collector.submitted else 0.0),
        "duration_s": round(duration, 4),
        "throughput_rps": round(thr_n / duration, 2) if duration else 0.0,
        "latency_ms_p50": round(float(np.percentile(lat, 50)) * 1e3, 3)
        if completed else 0.0,
        "latency_ms_p99": round(float(np.percentile(lat, 99)) * 1e3, 3)
        if completed else 0.0,
        # per-RUN delta, not engine-lifetime: a warmed engine reports 0,
        # and --mode both doesn't leak closed-run compiles into the open line
        "compiles": stats["compiles"] - compiles_before,
        "concurrency": args.concurrency if mode == "closed" else None,
        "rate_rps": args.rate if mode == "open" else None,
        # restart metrics (ISSUE 6): measured once per engine, repeated on
        # every mode's line so each SERVE_BENCH stays self-contained
        "first_request_ms": first_request_ms,
        "warmup_s": warmup_s,
        # ops-plane surface (ISSUE 10): per-size-class percentiles plus
        # goodput — completions per second that met --slo-ms (all
        # completions when no target is set, making goodput == useful
        # throughput; under overload the gap vs throughput_rps is the
        # work the server did too late to matter)
        "latency_by_class": by_class or None,
        "goodput_rps": round(collector.good / duration, 2)
        if duration else 0.0,
        "slo_ms": collector.slo_ms if collector.slo_ms > 0 else None,
        # precision-tier discriminator (ISSUE 15): "fp32" unless
        # MXNET_PRECISION_TIER rewrote this engine's plans — bench_compare
        # diffs same-tier rows only, cross-tier rows are display-only
        "tier": stats.get("precision_tier") or "fp32",
        # quality plane (ISSUE 16): per-tier shadow-divergence summary
        # {tier: {p50, p99, n, violations}} over contract fractions —
        # absent when MXNET_QUALITYPLANE is off or nothing was sampled
        # (the None-strip below drops the key, like every optional field)
        "divergence": (stats.get("quality") or {}).get("divergence"),
        # mixed-priority block (ISSUE 17): per-priority outcomes incl. the
        # degrade-vs-shed split — absent without --class-mix
        "priority": _priority_block(collector, duration) or None,
        # which router policy served this line ("degrade"/"shed"; absent
        # for a bare-engine run) — the bench_compare router-table axis
        "router_policy": (getattr(args, "router", None)
                          if getattr(args, "router", "off") not in
                          (None, "off") else None),
    }
    line = {k: v for k, v in line.items() if v is not None}
    print("SERVE_BENCH " + json.dumps(line))
    return line


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--mode", choices=["closed", "open", "both"],
                   default="closed")
    p.add_argument("--duration", type=float, default=2.0,
                   help="seconds of traffic per mode")
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed-loop worker threads")
    p.add_argument("--rate", type=float, default=200.0,
                   help="open-loop offered load, req/s")
    p.add_argument("--sizes", default="1,2,3",
                   help="request sample counts drawn uniformly (mixed-shape "
                        "stream)")
    p.add_argument("--batch-ladder", dest="ladder", default="1,2,4,8")
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--max-queue", type=int, default=512)
    p.add_argument("--timeout-s", type=float, default=10.0)
    p.add_argument("--slo-ms", default="0",
                   help="latency target for goodput accounting: "
                        "completions slower than this don't count toward "
                        "goodput_rps (0 = every completion counts).  With "
                        "--class-mix, also accepts per-priority targets: "
                        "'paid:25,best_effort:100'")
    p.add_argument("--class-mix", default=None,
                   help="mixed-priority traffic (ISSUE 17): "
                        "'paid:0.2,best_effort:0.8' draws each request's "
                        "priority class at those weights and adds the "
                        "per-priority SERVE_BENCH block")
    p.add_argument("--router", choices=["off", "degrade", "shed"],
                   default="off",
                   help="serve through a serving.Router over fp32+bf16 "
                        "twin pools with this policy mode (off = bare "
                        "Engine)")
    p.add_argument("--replicas", type=int, default=1,
                   help="engine replicas per tier pool (--router only)")
    p.add_argument("--symbol", help="*-symbol.json (default: built-in MLP)")
    p.add_argument("--params", help="*.params")
    p.add_argument("--input", action="append", default=[],
                   help="name:d1,d2,... per-sample shape (with --symbol)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip the bucket-ladder precompile (measure cold)")
    p.add_argument("--save-trace", metavar="PATH",
                   help="dump one JSONL record per submitted request "
                        "({t, n, shapes, class}) — the offline traffic "
                        "trace the bucket-ladder tuner replays "
                        "(tools/autotune.py search --trace; schema linted "
                        "by ci/check_bench_schema.py --trace)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="CI preset: tiny MLP, 0.5s closed + 0.5s open")
    args = p.parse_args(argv)
    args.ladder = tuple(int(x) for x in str(args.ladder).split(",") if x)
    args.sizes = tuple(int(x) for x in str(args.sizes).split(",") if x)
    try:
        args.slo_ms, args.class_slo = _parse_slo(args.slo_ms)
        args.class_mix = _parse_class_mix(args.class_mix)
    except ValueError as e:
        p.error(str(e))
    if args.class_slo and not args.class_mix:
        p.error("per-priority --slo-ms targets need --class-mix")
    if args.symbol and not args.input:
        p.error("--symbol requires at least one --input name:d1,d2,...")
    if args.smoke:
        args.mode = "both"
        args.duration = min(args.duration, 0.5)
        args.concurrency = 2
        args.rate = 100.0
        args.ladder = (1, 2, 4)

    if args.router != "off":
        engine, shapes = _router_target(args)
    else:
        engine, shapes = (_file_engine(args) if args.symbol
                          else _tiny_engine(args))
    try:
        warmup_s = None
        if not args.no_warmup:
            t0 = time.perf_counter()
            engine.warmup()
            warmup_s = round(time.perf_counter() - t0, 4)
        first = _first_request_latencies(engine, shapes, args.sizes)
        modes = ["closed", "open"] if args.mode == "both" else [args.mode]
        trace_log = [] if args.save_trace else None
        t_origin = time.monotonic()
        lines = [run(engine, shapes, args, m, first_request_ms=first,
                     warmup_s=warmup_s, trace_log=trace_log,
                     t_origin=t_origin) for m in modes]
        if args.save_trace:
            with open(args.save_trace, "w", encoding="utf-8") as fh:
                for rec in sorted(trace_log, key=lambda r: r["t"]):
                    fh.write(json.dumps(rec) + "\n")
            print("loadgen: wrote %d trace records to %s"
                  % (len(trace_log), args.save_trace), file=sys.stderr)
    finally:
        engine.close()
    # a run with model/engine errors is a FAILED run even if some requests
    # completed — CI must not read a healthy line from a failing engine
    return 0 if all(l["completed"] > 0 and l["errors"] == 0
                    for l in lines) else 1


if __name__ == "__main__":
    sys.exit(main())
