#!/usr/bin/env python
"""Compare bench captures — the automated reader for the BENCH_r* trajectory.

Each input is either a driver per-round capture (``BENCH_r01.json``: an
object with a ``parsed`` bench line) or a bare bench line as printed by
``python bench.py`` and linted by ``ci/check_bench_schema.py``.  The first
file is the baseline; the tool prints a delta table over the headline
metric value and the telemetry block (``dispatches_per_step``,
``compile_s``, ``data_wait_frac``) and exits non-zero when a later capture
regresses beyond ``--threshold`` percent:

* headline ``value`` (higher is better — img/s, rps) dropping more than the
  threshold, or
* ``dispatches_per_step`` (lower is better; the ISSUE 3 regression surface)
  growing more than the threshold.

Captures whose metric NAME differs from the baseline's are shown for
context but never gated — the checked-in trajectory mixes workloads
(resnet50 rounds vs deformable-rfcn rounds), and an img/s delta across
different models is noise, not signal.

Compile-plane **cost ledgers** (``MXNET_COST_LEDGER`` JSONL files of
``kind: "compile"`` rows, ISSUE 13) are detected automatically and diffed
per row key (site + logical key + shape signature — stable across runs):
Δflops / Δpeak-bytes / Δcompile-seconds for keys both ledgers share, plus
added/removed keys for context.  All deltas are shown; only ``--gate-cost``
turns flops or peak-bytes growth beyond ``--threshold`` into a nonzero
exit — a graph-pass or autotune change that silently doubles what XLA
builds fails CI the way pass-drift already fails plan-shape changes.
Identical ledgers compare silent and exit 0.

MULTICHIP captures (``MULTICHIP_r*.json``: the driver's ``dryrun_multichip``
record — ``{n_devices, rc, ok, skipped, tail}``) are detected automatically
and diffed on their own axis: the ``ok`` flag and the set of dryrun
*phases* the tail reports (dp/tp mesh step, pp+sp+ep phases, detection
step, detection ZeRO-sharded state).  A capture that lost the ``ok`` flag
or dropped a phase the baseline had exits non-zero — the multi-chip
equivalent of a headline-value regression.  Bench and multichip captures
cannot be mixed in one invocation.

Usage::

    python tools/bench_compare.py BENCH_r04.json BENCH_r05.json
    python tools/bench_compare.py base.json new.json --threshold 3 --json
    python tools/bench_compare.py MULTICHIP_r04.json MULTICHIP_r05.json
    python tools/bench_compare.py base_ledger.jsonl new_ledger.jsonl \
        --gate-cost
"""
from __future__ import annotations

import argparse
import json
import re
import sys


def load_bench(path, obj):
    """→ normalized row dict from a parsed driver capture or bare bench line."""
    line = obj.get("parsed") if isinstance(obj.get("parsed"), dict) else obj
    if "metric" not in line or "value" not in line:
        raise ValueError("%s: no bench line found (need 'metric'/'value', "
                         "directly or under 'parsed')" % path)
    tel = line.get("telemetry") or {}
    return {"file": path, "metric": str(line["metric"]),
            # precision-tier discriminator (ISSUE 15): captures predating
            # the tier read as fp32; cross-tier rows never gate
            "tier": str(line.get("tier") or "fp32"),
            "value": float(line["value"]), "unit": str(line.get("unit", "")),
            "dispatches_per_step": tel.get("dispatches_per_step"),
            "compile_s": tel.get("compile_s"),
            "data_wait_frac": tel.get("data_wait_frac"),
            "warmup_s": tel.get("warmup_s"),
            "graph_nodes_pre": tel.get("graph_nodes_pre"),
            "graph_nodes_post": tel.get("graph_nodes_post"),
            # pod observability rollup (ISSUE 19): display-only, never
            # gated — fleet health is a verdict, not a percentage delta
            "pod": _norm_pod(tel.get("pod"))}


def _norm_pod(pod):
    """Normalize a telemetry ``pod`` block → int-valued dict, or None when
    absent/malformed (an old or single-process capture must compare, not
    crash)."""
    if not isinstance(pod, dict) or not pod:
        return None
    out = {}
    for k in ("ranks", "max_step_lag", "ledger_divergences", "incidents"):
        v = pod.get(k)
        if v is not None:
            try:
                out[k] = int(v)
            except (TypeError, ValueError):
                return None
    return out or None


def _fmt_pod(pod):
    """Compact pod cell: ``r<ranks>/lag<max>/div<n>/inc<n>`` — ``-`` when
    the capture carried no pod rollup (plane off, or a pusher rank)."""
    if not pod:
        return "-"
    parts = []
    for tag, k in (("r", "ranks"), ("lag", "max_step_lag"),
                   ("div", "ledger_divergences"), ("inc", "incidents")):
        if k in pod:
            parts.append("%s%d" % (tag, pod[k]))
    return "/".join(parts) or "-"


# multichip dryrun phases, as printed by __graft_entry__.dryrun_multichip —
# (label, marker substring searched in the capture's ``tail``)
MULTICHIP_PHASES = (
    ("mesh_step", "mesh dp="),
    ("pp_sp_ep", "all phases OK"),
    ("detection", "detection dp="),
    ("detection_zero", "ZeRO-sharded state"),
)


def _parse_ledger_text(text):
    """Parse JSONL text as a compile-cost ledger → {key: row} (LAST row
    per key wins — a key recompiled during one run supersedes earlier
    rows), or None when the lines are not compile rows."""
    rows = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            return None
        if not isinstance(row, dict) or row.get("kind") != "compile" \
                or "key" not in row:
            return None
        rows[row["key"]] = row
    return rows or None


def load_ledger_file(path):
    """→ {key: row} for one ledger file, {} when it holds no compile rows.
    The standalone-tool twin of ``telemetry.costplane.load_ledger`` —
    tools must parse ledgers without importing the library (and jax);
    ``trace_summary`` imports THIS one so the tools share a single
    definition of "valid ledger row".  Unlike :func:`_parse_ledger_text`
    (the strict file-TYPE detector), this reader skips unparseable lines —
    a line torn by a crashed writer must not zero out the whole file."""
    rows = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict) and row.get("kind") == "compile" \
                    and "key" in row:
                rows[row["key"]] = row
    return rows


def _read_capture(path):
    """Parse one capture file (raises OSError/JSONDecodeError/ValueError so
    a missing or corrupt file surfaces as ITS error, not as a kind
    mismatch).  A cost-ledger JSONL file (several JSON lines, each a
    ``kind: "compile"`` row) parses to ``{"_ledger": {key: row}}``."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        rows = _parse_ledger_text(text)
        if rows is None:
            raise
        return {"_ledger": rows}
    if isinstance(obj, dict) and obj.get("kind") == "compile" \
            and "key" in obj:
        return {"_ledger": {obj["key"]: obj}}  # single-row ledger
    if not isinstance(obj, dict):
        raise ValueError("%s: capture must be a JSON object" % path)
    return obj


def is_ledger(obj):
    """True when a parsed capture is a compile-cost ledger (ISSUE 13)."""
    return "_ledger" in obj


def is_multichip(obj):
    """True when a parsed capture is a driver MULTICHIP record
    (``ok``/``tail``) rather than a bench line."""
    return "ok" in obj and ("tail" in obj or "n_devices" in obj)


def is_serve(obj):
    """True when a parsed capture is a SERVE_BENCH line (tools/loadgen.py)
    saved as JSON — bare or under ``parsed``."""
    line = obj.get("parsed") if isinstance(obj.get("parsed"), dict) else obj
    return "mode" in line and "latency_ms_p99" in line


def load_serve(path, obj):
    """→ normalized row for one SERVE_BENCH capture."""
    line = obj.get("parsed") if isinstance(obj.get("parsed"), dict) else obj
    for req in ("mode", "latency_ms_p99"):
        if req not in line:
            raise ValueError("%s: not a SERVE_BENCH capture (missing %r)"
                             % (path, req))
    return {"file": path, "mode": str(line["mode"]),
            "tier": str(line.get("tier") or "fp32"),
            "throughput_rps": line.get("throughput_rps"),
            "goodput_rps": line.get("goodput_rps"),
            "latency_ms_p50": line.get("latency_ms_p50"),
            "latency_ms_p99": float(line["latency_ms_p99"]),
            "shed_rate": line.get("shed_rate"),
            # quality plane (ISSUE 16): {tier: {p50, p99, n, violations}}
            # over shadow-sampled contract fractions; None for captures
            # predating the plane or taken with MXNET_QUALITYPLANE off
            "divergence": _norm_divergence(line.get("divergence")),
            # router (ISSUE 17): the policy mode the fronting Router ran
            # and the per-priority-class breakdown; None on bare Engine
            # captures (--router off)
            "router_policy": line.get("router_policy"),
            "priority": _norm_priority(line.get("priority"))}


def _norm_priority(pb):
    """Normalize a SERVE_BENCH ``priority`` block → {class: stats} with the
    derived downgrade/shed RATES the router table plots, or None when
    absent/malformed (an old capture must compare, not crash)."""
    if not isinstance(pb, dict) or not pb:
        return None
    out = {}
    for klass, s in pb.items():
        if not isinstance(s, dict):
            return None
        try:
            req, done = int(s["requests"]), int(s["completed"])
            out[str(klass)] = {
                "requests": req, "completed": done,
                "sheds": int(s["sheds"]), "downgrades": int(s["downgrades"]),
                "p50_ms": float(s["p50_ms"]), "p99_ms": float(s["p99_ms"]),
                "goodput_rps": float(s["goodput_rps"]),
                "slo_ms": (float(s["slo_ms"])
                           if s.get("slo_ms") is not None else None),
                "downgrade_rate": (s["downgrades"] / done) if done else 0.0,
                "shed_rate": (s["sheds"] / req) if req else 0.0,
            }
        except (KeyError, TypeError, ValueError):
            return None
    return out


def _norm_divergence(div):
    """Normalize a SERVE_BENCH ``divergence`` block → {tier: summary} with
    float p50/p99 and int n/violations, or None when absent/malformed (an
    old capture must compare, not crash)."""
    if not isinstance(div, dict) or not div:
        return None
    out = {}
    for tier, s in div.items():
        if not isinstance(s, dict):
            return None
        try:
            out[str(tier)] = {"p50": float(s["p50"]), "p99": float(s["p99"]),
                              "n": int(s["n"]),
                              "violations": int(s["violations"])}
        except (KeyError, TypeError, ValueError):
            return None
    return out


def compare_serve(rows, threshold, gate_p99=False, gate_divergence=False,
                  gate_goodput=False):
    """→ (table_rows, regressions).  Baseline = rows[0]; only same-MODE,
    same-TIER, same-ROUTER-POLICY rows are compared (a closed-loop capture
    against an open-loop one — or an fp32 engine against its bf16/int8
    twin, ISSUE 15, or a degrade-policy router run against a shed-only
    one, ISSUE 17 — is a configuration difference, like a metric-name
    mismatch on the bench axis; mismatched rows display for context, never
    gate).  All deltas are shown; only ``--gate-p99`` makes p99 growth
    beyond the threshold a regression (ISSUE 10, mirroring
    ``--gate-warmup``): latency tails are noisy across hosts, so the gate
    is opt-in for pipelines whose runs share a machine + load shape.

    ``--gate-divergence`` (ISSUE 16) gates the quality plane's shadow-
    divergence block the same opt-in way: for each tier BOTH rows report,
    p99 contract-fraction growth beyond the threshold, or new tolerance
    violations where the baseline had none, is a regression.  Rows without
    divergence (plane off, old capture) are shown, never gated — turning
    the plane on must not fail the first comparison against history.

    ``--gate-goodput`` (ISSUE 17, the router axis) mirrors ``--gate-p99``
    with the sign flipped: goodput is higher-better, so an overall
    ``goodput_rps`` DROP beyond the threshold — or a per-priority-class
    drop for any class both captures report — is a regression.  Rows
    without goodput (no --slo-ms target) or without a priority block (no
    --class-mix) are shown, never gated."""
    base = rows[0]
    table, regressions = [], []
    for r in rows:
        same = (r["mode"] == base["mode"] and r["tier"] == base["tier"]
                and r.get("router_policy") == base.get("router_policy"))
        dt = (_pct(r["throughput_rps"], base["throughput_rps"])
              if same and r is not base else None)
        d50 = (_pct(r["latency_ms_p50"], base["latency_ms_p50"])
               if same and r is not base else None)
        d99 = (_pct(r["latency_ms_p99"], base["latency_ms_p99"])
               if same and r is not base else None)
        dgp = (_pct(r["goodput_rps"], base["goodput_rps"])
               if same and r is not base else None)
        ddiv = (_divergence_deltas(r["divergence"], base["divergence"])
                if same and r is not base else None)
        dpri = (_priority_deltas(r["priority"], base["priority"])
                if same and r is not base else None)
        table.append(dict(r, same_mode=same, thr_delta_pct=dt,
                          p50_delta_pct=d50, p99_delta_pct=d99,
                          goodput_delta_pct=dgp, divergence_delta=ddiv,
                          priority_delta=dpri))
        if r is base or not same:
            continue
        if gate_p99 and d99 is not None and d99 > threshold:
            regressions.append(
                "%s: latency_ms_p99 %.4g -> %.4g (+%.1f%% > %g%%, "
                "--gate-p99)" % (r["file"], base["latency_ms_p99"],
                                 r["latency_ms_p99"], d99, threshold))
        if gate_goodput:
            if dgp is not None and dgp < -threshold:
                regressions.append(
                    "%s: goodput_rps %.4g -> %.4g (%.1f%% < -%g%%, "
                    "--gate-goodput)" % (r["file"], base["goodput_rps"],
                                         r["goodput_rps"], dgp, threshold))
            for klass, d in sorted((dpri or {}).items()):
                if d["goodput_delta_pct"] is not None \
                        and d["goodput_delta_pct"] < -threshold:
                    regressions.append(
                        "%s: priority[%s] goodput_rps %.4g -> %.4g "
                        "(%.1f%% < -%g%%, --gate-goodput)"
                        % (r["file"], klass,
                           base["priority"][klass]["goodput_rps"],
                           r["priority"][klass]["goodput_rps"],
                           d["goodput_delta_pct"], threshold))
        if gate_divergence and ddiv:
            for tier, d in sorted(ddiv.items()):
                if d["p99_delta_pct"] is not None \
                        and d["p99_delta_pct"] > threshold:
                    regressions.append(
                        "%s: divergence[%s] p99 %.4g -> %.4g (+%.1f%% > "
                        "%g%%, --gate-divergence)"
                        % (r["file"], tier, base["divergence"][tier]["p99"],
                           r["divergence"][tier]["p99"], d["p99_delta_pct"],
                           threshold))
                if d["new_violations"]:
                    regressions.append(
                        "%s: divergence[%s] violations %d -> %d where "
                        "baseline had none (--gate-divergence)"
                        % (r["file"], tier,
                           base["divergence"][tier]["violations"],
                           r["divergence"][tier]["violations"]))
    return table, regressions


def _divergence_deltas(div, base_div):
    """Per-tier quality deltas for tiers BOTH captures report, or None
    when either side lacks the block.  ``new_violations`` flags a candidate
    with violations where the baseline had zero — the contract break the
    gate exists to catch, independent of percentage math."""
    if not div or not base_div:
        return None
    out = {}
    for tier in sorted(set(div) & set(base_div)):
        b, r = base_div[tier], div[tier]
        out[tier] = {"p99_delta_pct": _pct(r["p99"], b["p99"]),
                     "new_violations": (b["violations"] == 0
                                        and r["violations"] > 0)}
    return out or None


def _priority_deltas(pri, base_pri):
    """Per-class goodput deltas for priority classes BOTH captures report,
    or None when either side lacks the block (bare-Engine capture, no
    --class-mix)."""
    if not pri or not base_pri:
        return None
    out = {}
    for klass in sorted(set(pri) & set(base_pri)):
        out[klass] = {"goodput_delta_pct": _pct(
            pri[klass]["goodput_rps"], base_pri[klass]["goodput_rps"])}
    return out or None


def render_router_table(table):
    """Per-policy-mode / per-priority-class breakdown (ISSUE 17) — one row
    per (capture, class) for every capture that carried a ``priority``
    block.  The degradation ladder's scoreboard: goodput, the fraction of
    a class's replies served by a cheaper twin (dg_rate), and the fraction
    shed at admission."""
    cols = ["file", "policy", "class", "req", "done", "goodput",
            "Δgoodput%", "dg_rate", "shed_rate", "p99_ms", "slo_ms"]
    out = [cols]
    for r in table:
        if not r.get("priority"):
            continue
        policy = str(r.get("router_policy") or "-") \
            + ("" if r["same_mode"] else " (≠ baseline)")
        dpri = r.get("priority_delta") or {}
        for klass in sorted(r["priority"]):
            s = r["priority"][klass]
            out.append([r["file"], policy, klass,
                        "%d" % s["requests"], "%d" % s["completed"],
                        _fmt(s["goodput_rps"], "%.4g"),
                        _fmt(dpri.get(klass, {}).get("goodput_delta_pct"),
                             "%+.1f"),
                        _fmt(s["downgrade_rate"], "%.3g"),
                        _fmt(s["shed_rate"], "%.3g"),
                        _fmt(s["p99_ms"], "%.4g"),
                        _fmt(s["slo_ms"], "%.4g")])
    if len(out) == 1:
        return ""
    widths = [max(len(row[i]) for row in out) for i in range(len(cols))]
    lines = []
    for i, row in enumerate(out):
        lines.append("  ".join(
            c.ljust(widths[j]) if j < 3 else c.rjust(widths[j])
            for j, c in enumerate(row)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def _fmt_divergence(div):
    """Compact ``tier:p99/violations`` cell for the serve table — one
    entry per tier the capture measured, ``-`` when the plane was off."""
    if not div:
        return "-"
    return ",".join("%s:%.3g/%d" % (t, div[t]["p99"], div[t]["violations"])
                    for t in sorted(div))


def render_serve_table(table):
    cols = ["file", "mode", "tier", "rps", "Δrps%", "goodput", "Δgood%",
            "p50_ms", "Δp50%", "p99_ms", "Δp99%", "shed", "div_p99/viol",
            "Δdiv%"]
    out = [cols]
    for r in table:
        mode = r["mode"] + ("" if r["same_mode"] else " (≠ baseline)")
        ddiv = r.get("divergence_delta")
        ddiv_cell = "-" if not ddiv else ",".join(
            "%s:%s" % (t, _fmt(d["p99_delta_pct"], "%+.1f"))
            for t, d in sorted(ddiv.items()))
        out.append([r["file"], mode, r["tier"],
                    _fmt(r["throughput_rps"], "%.4g"),
                    _fmt(r["thr_delta_pct"], "%+.1f"),
                    _fmt(r["goodput_rps"], "%.4g"),
                    _fmt(r.get("goodput_delta_pct"), "%+.1f"),
                    _fmt(r["latency_ms_p50"], "%.4g"),
                    _fmt(r["p50_delta_pct"], "%+.1f"),
                    _fmt(r["latency_ms_p99"], "%.4g"),
                    _fmt(r["p99_delta_pct"], "%+.1f"),
                    _fmt(r["shed_rate"], "%.3g"),
                    _fmt_divergence(r.get("divergence")),
                    ddiv_cell])
    widths = [max(len(row[i]) for row in out) for i in range(len(cols))]
    lines = []
    for i, row in enumerate(out):
        lines.append("  ".join(
            c.ljust(widths[j]) if j < 3 else c.rjust(widths[j])
            for j, c in enumerate(row)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def compare_cost(ledgers, threshold, gate_cost=False):
    """→ (table_rows, regressions) for N parsed ledgers; baseline =
    ledgers[0] = (path, {key: row}).  Per shared key: Δflops / Δpeak_bytes
    / Δcompile_s percent (null-safe — a key whose backend reported nothing
    on either side is shown, never gated).  Keys only in the baseline
    (removed) or only in a candidate (added) are listed for context.
    ``--gate-cost`` makes flops or peak-bytes growth beyond the threshold
    a regression; identical ledgers produce empty regressions."""
    base_file, base = ledgers[0]
    table, regressions = [], []
    for path, rows in ledgers[1:]:
        shared = sorted(set(base) & set(rows))
        for key in shared:
            b, r = base[key], rows[key]
            dfl = _pct(r.get("flops"), b.get("flops"))
            dpk = _pct(r.get("peak_bytes"), b.get("peak_bytes"))
            dcs = _pct(r.get("compile_s"), b.get("compile_s"))
            table.append({"file": path, "key": key, "site": r.get("site"),
                          "flops": r.get("flops"), "flops_delta_pct": dfl,
                          "peak_bytes": r.get("peak_bytes"),
                          "peak_delta_pct": dpk,
                          "compile_s": r.get("compile_s"),
                          "compile_delta_pct": dcs})
            if not gate_cost:
                continue
            if dfl is not None and dfl > threshold:
                regressions.append(
                    "%s: %s flops %.4g -> %.4g (+%.1f%% > %g%%, "
                    "--gate-cost)" % (path, key, b["flops"], r["flops"],
                                      dfl, threshold))
            if dpk is not None and dpk > threshold:
                regressions.append(
                    "%s: %s peak_bytes %.4g -> %.4g (+%.1f%% > %g%%, "
                    "--gate-cost)" % (path, key, b["peak_bytes"],
                                      r["peak_bytes"], dpk, threshold))
        added = sorted(set(rows) - set(base))
        removed = sorted(set(base) - set(rows))
        for key in added:
            table.append({"file": path, "key": key,
                          "site": rows[key].get("site"), "note": "added",
                          "flops": rows[key].get("flops"),
                          "flops_delta_pct": None,
                          "peak_bytes": rows[key].get("peak_bytes"),
                          "peak_delta_pct": None,
                          "compile_s": rows[key].get("compile_s"),
                          "compile_delta_pct": None})
        for key in removed:
            table.append({"file": path, "key": key,
                          "site": base[key].get("site"), "note": "removed",
                          "flops": None, "flops_delta_pct": None,
                          "peak_bytes": None, "peak_delta_pct": None,
                          "compile_s": None, "compile_delta_pct": None})
    return table, regressions


def render_cost_table(table):
    cols = ["key", "site", "GFLOP", "Δflops%", "peak_MB", "Δpeak%",
            "compile_s", "Δcompile%", "note"]
    out = [cols]
    for r in table:
        out.append([r["key"][:44], str(r.get("site") or "-"),
                    _fmt(None if r["flops"] is None
                         else r["flops"] / 1e9, "%.4f"),
                    _fmt(r["flops_delta_pct"], "%+.1f"),
                    _fmt(None if r["peak_bytes"] is None
                         else r["peak_bytes"] / 1e6, "%.3f"),
                    _fmt(r["peak_delta_pct"], "%+.1f"),
                    _fmt(r["compile_s"], "%.3g"),
                    _fmt(r["compile_delta_pct"], "%+.1f"),
                    r.get("note") or "-"])
    widths = [max(len(row[i]) for row in out) for i in range(len(cols))]
    lines = []
    for i, row in enumerate(out):
        lines.append("  ".join(
            c.ljust(widths[j]) if j < 2 else c.rjust(widths[j])
            for j, c in enumerate(row)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def _multichip_processes(obj, tail):
    """Process count for one MULTICHIP capture: the explicit ``processes``
    key when the driver recorded it, else the ``processes=N`` marker
    ``dryrun_multichip`` prints into the tail, else 1 (every capture
    predating pod-scale training was single-process)."""
    if obj.get("processes") is not None:
        return int(obj["processes"])
    m = re.search(r"\bprocesses=(\d+)\b", tail)
    return int(m.group(1)) if m else 1


def load_multichip(path, obj):
    """→ normalized row for one parsed MULTICHIP_r*.json capture."""
    if "ok" not in obj:
        raise ValueError("%s: not a MULTICHIP capture (need 'ok')" % path)
    tail = str(obj.get("tail") or "")
    return {"file": path, "ok": bool(obj.get("ok")),
            "skipped": bool(obj.get("skipped")),
            "n_devices": obj.get("n_devices"),
            "processes": _multichip_processes(obj, tail),
            "phases": {name for name, marker in MULTICHIP_PHASES
                       if marker in tail},
            # pod rollup (ISSUE 19): a driver capture taken with
            # MXNET_POD_METRICS on carries rank 0's fleet summary —
            # display-only, never part of the phase/ok gate
            "pod": _norm_pod(obj.get("pod"))}


def compare_multichip(rows):
    """→ (table_rows, regressions).  Baseline = rows[0]; a later capture
    regresses when it lost ``ok`` or dropped a phase the baseline ran.
    Skipped captures (driver had no devices) are shown but never gated."""
    base = rows[0]
    table, regressions = [], []
    for r in rows:
        missing = sorted(base["phases"] - r["phases"]) if r is not base else []
        table.append(dict(r, phases=sorted(r["phases"]),
                          missing_phases=missing))
        if r is base or r["skipped"]:
            continue
        if r["processes"] != base["processes"]:
            # a 2-process pod capture against a single-process one is a
            # topology difference, not a regression — display-only, the
            # same contract as cross-tier bench rows (ISSUE 20)
            continue
        if base["ok"] and not r["ok"]:
            regressions.append("%s: ok true -> false" % r["file"])
        if missing:
            regressions.append("%s: dropped phase(s) %s"
                               % (r["file"], ", ".join(missing)))
    return table, regressions


def render_multichip_table(table):
    lines = ["file  ok  skipped  n_devices  processes  phases  missing  pod"]
    for r in table:
        lines.append("%s  %s  %s  %s  %s  [%s]  %s  %s" % (
            r["file"], r["ok"], r["skipped"], r["n_devices"],
            r.get("processes", 1),
            ",".join(r["phases"]),
            ",".join(r["missing_phases"]) or "-",
            _fmt_pod(r.get("pod"))))
    return "\n".join(lines)


def _pct(new, base):
    if base in (None, 0) or new is None:
        return None
    return 100.0 * (new - base) / base


def compare(rows, threshold, gate_warmup=False):
    """→ (table_rows, regressions).  Baseline = rows[0]; only same-metric,
    same-TIER rows are gated (ISSUE 15: a bf16/int8 deploy-twin row
    against an fp32 baseline is a configuration difference — shown for
    context, never a regression).  ``gate_warmup`` opts the ``warmup_s``
    delta into the gate (ISSUE 9): shown-only by default because a cold
    capture against a warm one is a configuration difference, but a
    pipeline that pins its cache setup can enforce restart-time
    regressions too."""
    base = rows[0]
    table, regressions = [], []
    for r in rows:
        same = r["metric"] == base["metric"] and r["tier"] == base["tier"]
        dv = _pct(r["value"], base["value"]) if same and r is not base else None
        dd = (_pct(r["dispatches_per_step"], base["dispatches_per_step"])
              if same and r is not base else None)
        dc = (_pct(r["compile_s"], base["compile_s"])
              if same and r is not base else None)
        # warmup_s (ISSUE 6 restart benchmark): shown + deltaed like
        # compile_s, not gated — a cold capture against a warm one is a
        # configuration difference, not a regression
        dw = (_pct(r["warmup_s"], base["warmup_s"])
              if same and r is not base else None)
        # graph-pass node counts (ISSUE 7): displayed, never gated — a
        # capture with passes off (or predating them) against one with
        # passes on is a configuration difference
        dn = (_pct(r["graph_nodes_post"], base["graph_nodes_post"])
              if same and r is not base else None)
        table.append(dict(r, same_metric=same, value_delta_pct=dv,
                          dps_delta_pct=dd, compile_delta_pct=dc,
                          warmup_delta_pct=dw, nodes_delta_pct=dn))
        if r is base or not same:
            continue
        if dv is not None and dv < -threshold:
            regressions.append("%s: %s value %.4g -> %.4g (%.1f%% < -%g%%)"
                               % (r["file"], r["metric"], base["value"],
                                  r["value"], dv, threshold))
        if dd is not None and dd > threshold:
            regressions.append(
                "%s: dispatches_per_step %.3g -> %.3g (+%.1f%% > %g%%)"
                % (r["file"], base["dispatches_per_step"],
                   r["dispatches_per_step"], dd, threshold))
        if gate_warmup and dw is not None and dw > threshold:
            regressions.append(
                "%s: warmup_s %.3g -> %.3g (+%.1f%% > %g%%, --gate-warmup)"
                % (r["file"], base["warmup_s"], r["warmup_s"], dw,
                   threshold))
    return table, regressions


def _fmt(v, spec="%.4g", dash="-"):
    return dash if v is None else spec % v


def _fmt_nodes(r):
    if r["graph_nodes_post"] is None:
        return "-"
    if r["graph_nodes_pre"] is None:
        return "%d" % r["graph_nodes_post"]
    return "%d→%d" % (r["graph_nodes_pre"], r["graph_nodes_post"])


def render_table(table):
    cols = ["file", "metric", "tier", "value", "Δvalue%", "disp/step",
            "Δdisp%", "compile_s", "Δcompile%", "warmup_s", "Δwarmup%",
            "nodes", "Δnodes%", "wait_frac", "pod"]
    out = [cols]
    for r in table:
        metric = r["metric"] + ("" if r["same_metric"] else " (≠ baseline)")
        out.append([r["file"], metric, r["tier"], _fmt(r["value"]),
                    _fmt(r["value_delta_pct"], "%+.1f"),
                    _fmt(r["dispatches_per_step"], "%.3g"),
                    _fmt(r["dps_delta_pct"], "%+.1f"),
                    _fmt(r["compile_s"], "%.3g"),
                    _fmt(r["compile_delta_pct"], "%+.1f"),
                    _fmt(r["warmup_s"], "%.3g"),
                    _fmt(r["warmup_delta_pct"], "%+.1f"),
                    _fmt_nodes(r),
                    _fmt(r["nodes_delta_pct"], "%+.1f"),
                    _fmt(r["data_wait_frac"], "%.3g"),
                    _fmt_pod(r.get("pod"))])
    widths = [max(len(row[i]) for row in out) for i in range(len(cols))]
    lines = []
    for i, row in enumerate(out):
        lines.append("  ".join(
            c.ljust(widths[j]) if j < 3 else c.rjust(widths[j])
            for j, c in enumerate(row)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="delta table + regression gate over BENCH_*.json files")
    p.add_argument("files", nargs="+",
                   help="two or more bench captures; the first is baseline")
    p.add_argument("--threshold", type=float, default=5.0,
                   help="regression gate, percent (default 5): headline "
                        "value drop or dispatches_per_step growth beyond "
                        "this fails")
    p.add_argument("--json", action="store_true",
                   help="emit machine-readable JSON instead of the table")
    p.add_argument("--gate-warmup", action="store_true",
                   help="also fail on warmup_s growth beyond --threshold "
                        "(off by default: cold-vs-warm captures are a "
                        "configuration difference, not a regression — "
                        "opt in when both runs share a cache setup)")
    p.add_argument("--gate-p99", action="store_true",
                   help="fail on SERVE_BENCH latency_ms_p99 growth beyond "
                        "--threshold (off by default: latency tails are "
                        "noisy across hosts — opt in when runs share a "
                        "machine and load shape; requires SERVE_BENCH "
                        "captures)")
    p.add_argument("--gate-cost", action="store_true",
                   help="fail on compile-plane ledger flops or peak-bytes "
                        "growth beyond --threshold (off by default: shown-"
                        "only deltas; requires MXNET_COST_LEDGER JSONL "
                        "captures — ISSUE 13)")
    p.add_argument("--gate-goodput", action="store_true",
                   help="fail on SERVE_BENCH goodput_rps DROP beyond "
                        "--threshold — overall, and per priority class for "
                        "classes both captures report (off by default, "
                        "mirroring --gate-p99 with the sign flipped: "
                        "goodput is higher-better; requires SERVE_BENCH "
                        "captures — ISSUE 17)")
    p.add_argument("--gate-divergence", action="store_true",
                   help="fail on SERVE_BENCH quality-plane divergence "
                        "regressions: per-tier p99 contract-fraction "
                        "growth beyond --threshold, or new tolerance "
                        "violations where the baseline had none (off by "
                        "default; requires SERVE_BENCH captures with a "
                        "divergence block — ISSUE 16)")
    args = p.parse_args(argv)
    if len(args.files) < 2:
        p.error("need at least two files (baseline + candidates)")

    try:
        objs = [(f, _read_capture(f)) for f in args.files]
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print("bench_compare: %s" % e, file=sys.stderr)
        return 2
    kinds = [is_multichip(o) for _, o in objs]
    serve_kinds = [is_serve(o) for _, o in objs]
    ledger_kinds = [is_ledger(o) for _, o in objs]
    if (any(kinds) and not all(kinds)) or (any(serve_kinds)
                                           and not all(serve_kinds)) \
            or (any(ledger_kinds) and not all(ledger_kinds)):
        print("bench_compare: cannot mix bench / MULTICHIP / SERVE_BENCH / "
              "cost-ledger captures in one invocation", file=sys.stderr)
        return 2
    if args.gate_p99 and not all(serve_kinds):
        print("bench_compare: --gate-p99 applies to SERVE_BENCH captures "
              "(a bench line has no latency_ms_p99)", file=sys.stderr)
        return 2
    if args.gate_divergence and not all(serve_kinds):
        print("bench_compare: --gate-divergence applies to SERVE_BENCH "
              "captures (a bench line has no divergence block)",
              file=sys.stderr)
        return 2
    if args.gate_goodput and not all(serve_kinds):
        print("bench_compare: --gate-goodput applies to SERVE_BENCH "
              "captures (a bench line has no goodput_rps)", file=sys.stderr)
        return 2
    if args.gate_cost and not all(ledger_kinds):
        print("bench_compare: --gate-cost applies to compile-plane cost "
              "ledgers (MXNET_COST_LEDGER JSONL)", file=sys.stderr)
        return 2
    if all(ledger_kinds):
        ledgers = [(f, o["_ledger"]) for f, o in objs]
        table, regressions = compare_cost(ledgers, args.threshold,
                                          gate_cost=args.gate_cost)
        if args.json:
            print(json.dumps({"baseline": ledgers[0][0], "rows": table,
                              "threshold_pct": args.threshold,
                              "regressions": regressions}, indent=1))
        else:
            print(render_cost_table(table))
            for msg in regressions:
                print("REGRESSION %s" % msg)
        if regressions:
            if not args.json:
                print("bench_compare: %d cost regression(s) beyond %.3g%%"
                      % (len(regressions), args.threshold), file=sys.stderr)
            return 1
        return 0
    if all(serve_kinds):
        try:
            srows = [load_serve(f, o) for f, o in objs]
        except (ValueError,) as e:
            print("bench_compare: %s" % e, file=sys.stderr)
            return 2
        table, regressions = compare_serve(
            srows, args.threshold, gate_p99=args.gate_p99,
            gate_divergence=args.gate_divergence,
            gate_goodput=args.gate_goodput)
        if args.json:
            print(json.dumps({"baseline": srows[0]["file"], "rows": table,
                              "threshold_pct": args.threshold,
                              "regressions": regressions}, indent=1))
        else:
            print(render_serve_table(table))
            router = render_router_table(table)
            if router:
                print()
                print(router)
            for msg in regressions:
                print("REGRESSION %s" % msg)
        if regressions:
            if not args.json:
                print("bench_compare: %d serve regression(s) beyond %.3g%%"
                      % (len(regressions), args.threshold), file=sys.stderr)
            return 1
        return 0
    try:
        if all(kinds):
            rows = [load_multichip(f, o) for f, o in objs]
            if rows[0]["skipped"] or not rows[0]["ok"]:
                # a degraded baseline has no phases/ok to gate against —
                # say so loudly instead of passing everything vacuously
                print("bench_compare: WARNING baseline %s is %s — "
                      "multichip gate is vacuous for this pair"
                      % (rows[0]["file"],
                         "skipped" if rows[0]["skipped"] else "not ok"),
                      file=sys.stderr)
            table, regressions = compare_multichip(rows)
            if args.json:
                print(json.dumps({"baseline": rows[0]["file"], "rows": table,
                                  "regressions": regressions}, indent=1))
            else:
                print(render_multichip_table(table))
                for msg in regressions:
                    print("REGRESSION %s" % msg)
            if regressions:
                if not args.json:
                    print("bench_compare: %d multichip regression(s)"
                          % len(regressions), file=sys.stderr)
                return 1
            return 0
        rows = [load_bench(f, o) for f, o in objs]
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print("bench_compare: %s" % e, file=sys.stderr)
        return 2
    table, regressions = compare(rows, args.threshold,
                                 gate_warmup=args.gate_warmup)
    if args.json:
        print(json.dumps({"baseline": rows[0]["file"], "rows": table,
                          "threshold_pct": args.threshold,
                          "regressions": regressions}, indent=1))
    else:
        print(render_table(table))
        for msg in regressions:
            print("REGRESSION %s" % msg)
    if regressions:
        if not args.json:
            print("bench_compare: %d regression(s) beyond %.3g%%"
                  % (len(regressions), args.threshold), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
