#!/usr/bin/env python
"""Merge host span traces with profiler/XLA traces onto one timeline.

The span tracer (``mxnet_tpu/telemetry/tracing.py``, ``MXNET_TRACE``) and
``mx.profiler.dump()`` both emit chrome-trace JSON carrying a ``clock_sync``
metadata record — ``{"unix_ts": <time.time()>, "trace_ts_us": <ts>}`` — that
anchors the file's (arbitrary-epoch) trace timestamps to the wall clock.
This tool rebases every input onto unix-epoch microseconds and concatenates
them, so a request's host spans (queue/assemble/execute), the profiler's
user annotations, and a TensorBoard trace-viewer export of the XLA device
timeline land in ONE Perfetto view, still flow-linked and still carrying
their step/request annotations (``args.trace`` / ``args.step``).

Files without a ``clock_sync`` record (e.g. a raw trace-viewer export) fall
back to ``--align start`` (shift so its earliest event matches the first
file's earliest) or an explicit ``--offset-us`` per file.

pids are namespaced per input (file i adds ``i * pid_stride``) and flow/
async event ids are prefixed with the file index, so two files can never
alias each other's tracks or arrows.

**Per-rank merging (ISSUE 12).**  A pod run leaves one trace/flight-dump
per process; each carries its ``rank`` — in the ``clock_sync`` metadata
args (flight-recorder dumps), in per-event ``args.rank`` (trainhealth
records), or simply in the filename (``...rank1...``).  Files that
resolve the SAME rank merge onto one shared pid namespace with a
``process_name`` track labeled ``rank N``, so N ranks produce one
timeline with one track group per rank instead of one per file.
``--rank R`` (repeatable, positional like ``--offset-us``) overrides
detection per file.

Usage::

    python tools/trace_merge.py mxtrace.json profile.json -o merged.json
    python tools/trace_merge.py mxtrace.json tb_export.json --align start
    python tools/trace_merge.py rank0/flightrec-*.json rank1/flightrec-*.json

Workflow (docs/OBSERVABILITY.md "Tracing"): run with ``MXNET_TRACE=1`` and
``mx.profiler`` (or ``use_xla_trace=True`` + a TensorBoard trace-viewer
export) in the same process, export both, merge here, open in Perfetto.
"""
from __future__ import annotations

import argparse
import gzip
import json
import os
import re
import sys

PID_STRIDE = 100000


def load_events(path):
    """Chrome-trace JSON (optionally gzipped; dict or bare array form)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        return data.get("traceEvents", [])
    return data


def clock_anchor(events):
    """→ (unix_ts, trace_ts_us) from the clock_sync metadata, or None."""
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "clock_sync":
            a = ev.get("args") or {}
            if "unix_ts" in a and "trace_ts_us" in a:
                return float(a["unix_ts"]), float(a["trace_ts_us"])
    return None


def file_rank(path, events, explicit=None):
    """The rank this file belongs to, or None for single-process traces.

    Precedence: an explicit ``--rank`` flag, a ``rank`` in the
    ``clock_sync`` metadata args (flight-recorder dumps embed it),
    event-level ``args.rank`` (trainhealth records) — but only when every
    ranked event AGREES (a file carrying several ranks, e.g. a previous
    trace_merge output fed back in, has no single file rank and keeps its
    own namespace) — then a ``rank<N>``/``rank_<N>``/``rank-<N>`` token in
    the file name."""
    if explicit is not None:
        return int(explicit)

    def unanimous(ranks):
        """One agreed rank, None when absent, None when MIXED — a file
        carrying several ranks (a previous merge output) must never be
        collapsed into the first one."""
        if len(ranks) == 1:
            return ranks.pop()
        return None

    sync_ranks, arg_ranks = set(), set()
    for ev in events:
        a = ev.get("args") or {}
        if "rank" not in a:
            continue
        try:
            rank = int(a["rank"])
        except (TypeError, ValueError):
            continue
        if ev.get("ph") == "M" and ev.get("name") == "clock_sync":
            sync_ranks.add(rank)
        else:
            arg_ranks.add(rank)
    if sync_ranks:
        return unanimous(sync_ranks)
    if arg_ranks:
        return unanimous(arg_ranks)
    m = re.search(r"rank[-_]?(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else None


def min_ts(events):
    ts = [ev["ts"] for ev in events
          if isinstance(ev.get("ts"), (int, float))]
    return min(ts) if ts else 0.0


def compute_offset(events, align, base_events, explicit_us):
    """Microseconds to ADD to this file's timestamps.

    clock mode rebases onto unix-epoch us (``unix_ts*1e6 - trace_ts_us``);
    start mode matches earliest events; an explicit offset always wins."""
    if explicit_us is not None:
        return float(explicit_us), "explicit"
    if align == "clock":
        anchor = clock_anchor(events)
        if anchor is not None:
            unix_ts, ts_us = anchor
            return unix_ts * 1e6 - ts_us, "clock"
        if base_events is None:
            return 0.0, "none (no clock_sync; first file keeps its epoch)"
        # fall back per-file: align starts against the (already-shifted) base
        return min_ts(base_events) - min_ts(events), "start (no clock_sync)"
    if align == "start":
        if base_events is None:
            return 0.0, "start (base)"
        return min_ts(base_events) - min_ts(events), "start"
    return 0.0, "none"


def shift_and_namespace(events, offset_us, index, namespace=None, rank=None,
                        force_rank=False):
    """Apply the time offset, namespace pids and flow/async ids.

    ``namespace`` is the pid-namespace slot (defaults to the file index;
    files resolving the same rank share one so a pod run merges onto one
    track group per rank); flow/async ids stay prefixed per FILE so two
    same-rank files can never alias each other's arrows.  With ``rank``
    set, every event's args gain the rank label (queryable in Perfetto);
    ``force_rank`` (an explicit ``--rank`` flag) OVERWRITES embedded
    args.rank values so the track label and the event labels agree."""
    ns = index if namespace is None else namespace
    out = []
    for ev in events:
        ev = dict(ev)
        if isinstance(ev.get("ts"), (int, float)):
            ev["ts"] = ev["ts"] + offset_us
        if isinstance(ev.get("pid"), int):
            ev["pid"] = ev["pid"] + ns * PID_STRIDE
        if "id" in ev and ev.get("ph") in ("s", "t", "f", "b", "n", "e"):
            ev["id"] = "m%d.%s" % (index, ev["id"])
        if rank is not None and ev.get("ph") != "M":
            args = dict(ev.get("args") or {})
            if force_rank:
                args["rank"] = rank
            else:
                args.setdefault("rank", rank)
            ev["args"] = args
        out.append(ev)
    return out


def summarize(path, events):
    xs = [ev for ev in events if ev.get("ph") == "X"]
    traces = {ev.get("args", {}).get("trace") for ev in xs} - {None}
    steps = sum(1 for ev in xs if ev.get("name") == "step")
    reqs = sum(1 for ev in xs if ev.get("name") == "request")
    span_ms = ((max(ev["ts"] + ev.get("dur", 0) for ev in xs)
                - min(ev["ts"] for ev in xs)) / 1e3 if xs else 0.0)
    return ("%s: %d events (%d slices, %.3f ms span), %d traces, "
            "%d step / %d request roots"
            % (path, len(events), len(xs), span_ms, len(traces), steps, reqs))


def main(argv=None):
    p = argparse.ArgumentParser(
        description="merge chrome traces (host spans + profiler/XLA) onto "
                    "one clock")
    p.add_argument("traces", nargs="+",
                   help="chrome-trace JSON files (.json or .json.gz); the "
                        "first defines the output timebase")
    p.add_argument("-o", "--output", default="merged.json")
    p.add_argument("--align", choices=("clock", "start", "none"),
                   default="clock",
                   help="clock: rebase via each file's clock_sync record "
                        "(default; falls back to start for files without "
                        "one); start: align earliest events; none: "
                        "concatenate untouched")
    p.add_argument("--offset-us", action="append", type=float, default=[],
                   metavar="US",
                   help="explicit per-file offset in microseconds "
                        "(repeatable, positional: first flag = first file)")
    p.add_argument("--rank", action="append", type=int, default=[],
                   metavar="R",
                   help="explicit per-file rank (repeatable, positional) — "
                        "overrides clock_sync/args/filename detection; "
                        "same-rank files share one rank-labeled track group")
    args = p.parse_args(argv)

    merged, base = [], None
    namespaces = {}  # ("rank", r) | ("file", i) -> pid-namespace slot
    labeled = set()  # shifted pids already carrying a process_name
    for i, path in enumerate(args.traces):
        try:
            events = load_events(path)
        except (OSError, json.JSONDecodeError) as e:
            print("trace_merge: cannot read %s: %s" % (path, e),
                  file=sys.stderr)
            return 2
        explicit = args.offset_us[i] if i < len(args.offset_us) else None
        explicit_rank = args.rank[i] if i < len(args.rank) else None
        rank = file_rank(path, events, explicit_rank)
        key = ("rank", rank) if rank is not None else ("file", i)
        ns = namespaces.setdefault(key, len(namespaces))
        offset, how = compute_offset(events, args.align, base, explicit)
        shifted = shift_and_namespace(events, offset, i, namespace=ns,
                                      rank=rank,
                                      force_rank=explicit_rank is not None)
        print(summarize(path, shifted))
        print("  offset %+.1f us (%s)%s"
              % (offset, how,
                 "" if rank is None else ", rank %d" % rank))
        if base is None:
            base = shifted
        merged.extend(shifted)
        if rank is not None:
            # label every pid TRACK the file contributed (profiler dumps
            # use one pid per domain, not just pid 0) — but never
            # override a track's own embedded process_name, which for
            # flightrec dumps already carries the rank
            labeled |= {ev.get("pid") for ev in shifted
                        if ev.get("ph") == "M"
                        and ev.get("name") == "process_name"}
            for pid in sorted({ev.get("pid") for ev in shifted
                               if isinstance(ev.get("pid"), int)}
                              - labeled):
                labeled.add(pid)
                merged.append({"name": "process_name", "ph": "M",
                               "pid": pid,
                               "args": {"name": "rank %d" % rank}})

    with open(args.output, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f,
                  indent=1)
    print("wrote %s (%d events from %d traces)"
          % (args.output, len(merged), len(args.traces)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
