#!/usr/bin/env python
"""Distributed job launcher — capability parity with reference
``tools/launch.py`` (dmlc_tracker ssh/mpi/sge/yarn/local, :29,48-115), shaped
for the TPU runtime: instead of scheduler/server/worker roles over ps-lite,
every process is an equal jax.distributed participant; process 0 hosts the
coordination service (SURVEY §5.8 translation: the launcher becomes a thin
multi-host bootstrapper).

Usage (mirrors the reference CLI):

    # N local processes, a fake cluster on one host (the reference's
    # `--launcher local` nightly-test pattern, ci/runtime_functions.sh:673)
    python tools/launch.py -n 4 --launcher local python train.py ...

    # ssh to a host list; each host runs one process
    python tools/launch.py -n 4 -H hostfile --launcher ssh python train.py ...

Every spawned process receives the env contract consumed by
``mxnet_tpu.parallel.dist.init()``:
  MXNET_COORDINATOR, MXNET_NUM_WORKERS, MXNET_WORKER_RANK
(DMLC_* aliases are exported too for scripts reading the reference names).
Observability env (MXNET_TELEMETRY / MXNET_TRACE / MXNET_FLIGHTREC_DIR /
MXNET_POD_METRICS*) set on the launcher is propagated to every worker, and
each worker's stdout/stderr is line-prefixed with ``[rank N]`` so pod logs
stay attributable (ISSUE 19 satellite).
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading

# observability + caching env propagated from the launcher to every worker
# (ISSUE 19/20 satellites): exact names plus prefix families.  The ssh
# launcher builds worker env from scratch (base={}), so without this an
# operator exporting MXNET_TELEMETRY=1 before launch gets silent per-worker
# no-ops.  The MXNET_AOT_CACHE / MXNET_AUTOTUNE prefixes cover the whole
# families (…_MAX_MB, …_CACHE, …_MODEL, …_TOPK): an operator pointing the
# AOT/autotune caches at shared storage must have every rank see them, or
# a pod restart is warm on rank 0 and cold everywhere else.
_PROPAGATE_EXACT = ("MXNET_TELEMETRY", "MXNET_TRACE", "MXNET_FLIGHTREC_DIR")
_PROPAGATE_PREFIX = ("MXNET_POD_METRICS", "MXNET_AOT_CACHE",
                     "MXNET_AUTOTUNE", "MXNET_ELASTIC")


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env_for(rank, n, coordinator, base=None):
    env = dict(base if base is not None else os.environ)
    for k, v in os.environ.items():
        if k in _PROPAGATE_EXACT or k.startswith(_PROPAGATE_PREFIX):
            env.setdefault(k, v)
    env.update({
        "MXNET_COORDINATOR": coordinator,
        "MXNET_NUM_WORKERS": str(n),
        "MXNET_WORKER_RANK": str(rank),
        # reference names, for scripts that read them
        "DMLC_NUM_WORKER": str(n),
        "DMLC_RANK": str(rank),
        "DMLC_ROLE": "worker",
    })
    return env


def _pump(stream, rank, out):
    """Copy one worker's merged stdout/stderr to ``out``, prefixing every
    line with ``[rank N]`` so interleaved pod logs stay attributable."""
    prefix = "[rank %d] " % rank
    for line in iter(stream.readline, ""):
        out.write(prefix + line)
        out.flush()
    stream.close()


def _spawn_prefixed(cmd, rank, env=None):
    """Popen with stderr merged into stdout and a daemon pump thread that
    rank-prefixes every line.  Line-buffered text mode: a worker writing
    whole lines (the logging default) is never split mid-line."""
    p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True, bufsize=1)
    t = threading.Thread(target=_pump, args=(p.stdout, rank, sys.stdout),
                         name="launch-pump-%d" % rank, daemon=True)
    t.start()
    return p, t


def launch_local(n, command, verbose=False):
    """N processes on this host (the reference local tracker)."""
    coordinator = "127.0.0.1:%d" % _free_port()
    procs, pumps = [], []
    try:
        for rank in range(n):
            p, t = _spawn_prefixed(command, rank,
                                   env=_env_for(rank, n, coordinator))
            procs.append(p)
            pumps.append(t)
        codes = [p.wait() for p in procs]
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        raise
    for t in pumps:
        t.join(timeout=5.0)
    bad = [(i, c) for i, c in enumerate(codes) if c != 0]
    if bad:
        raise SystemExit("workers failed: %s" % bad)
    return 0


def launch_ssh(n, hosts, command, verbose=False, port=None):
    """One process per host over ssh (reference ssh launcher, launch.py:48).

    The coordinator address is host0:port. The port must be free ON hosts[0]
    — a locally-probed free port proves nothing about the remote — so a fixed
    default is used and --port overrides it on conflict.
    """
    if len(hosts) < n:
        raise SystemExit("need %d hosts, hostfile has %d" % (n, len(hosts)))
    port = port or 29400
    coordinator = "%s:%d" % (hosts[0], port)
    cmd_str = " ".join("'%s'" % c for c in command)
    procs, pumps = [], []
    for rank in range(n):
        envs = " ".join(
            "%s=%s" % (k, v)
            for k, v in _env_for(rank, n, coordinator, base={}).items()
        )
        full = ["ssh", "-o", "StrictHostKeyChecking=no", hosts[rank],
                "cd %s && env %s %s" % (os.getcwd(), envs, cmd_str)]
        if verbose:
            print("launch:", " ".join(full))
        p, t = _spawn_prefixed(full, rank)
        procs.append(p)
        pumps.append(t)
    codes = [p.wait() for p in procs]
    for t in pumps:
        t.join(timeout=5.0)
    bad = [(hosts[i], c) for i, c in enumerate(codes) if c != 0]
    if bad:
        raise SystemExit("workers failed: %s" % bad)
    return 0


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    parser.add_argument("-n", "--num-workers", required=True, type=int,
                        help="number of worker processes")
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="accepted for reference CLI parity; the collective "
                             "runtime has no server role, so this is ignored")
    parser.add_argument("-H", "--hostfile", type=str,
                        help="file with one hostname per line (ssh launcher)")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh"],
                        help="mpi/sge/yarn launchers of the reference are "
                             "cluster-manager specific; local and ssh cover "
                             "the dev and bare-metal paths")
    parser.add_argument("--port", type=int, default=None,
                        help="coordinator port on host 0 (ssh launcher)")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run on every worker")
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")
    if args.num_servers:
        print("note: -s/--num-servers ignored — collectives replace the "
              "parameter-server role (see SURVEY §5.8)", file=sys.stderr)
    if args.launcher == "local":
        return launch_local(args.num_workers, args.command, args.verbose)
    if not args.hostfile:
        parser.error("--hostfile is required with --launcher ssh")
    hosts = [h.strip() for h in open(args.hostfile) if h.strip()]
    return launch_ssh(args.num_workers, hosts, args.command, args.verbose,
                      port=args.port)


if __name__ == "__main__":
    sys.exit(main())
