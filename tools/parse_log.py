#!/usr/bin/env python
"""Parse a training log into a markdown table — reference
``tools/parse_log.py`` (same regexes over the Speedometer/epoch-callback
log lines this repo's ``mx.callback`` emits).

Usage: python tools/parse_log.py train.log --metric-names accuracy
"""
from __future__ import annotations

import argparse
import re


def parse(lines, metric_names=("accuracy",)):
    """→ {epoch: [train_m0, val_m0, ..., time]} (reference parse loop)."""
    res = ([re.compile(r".*Epoch\[(\d+)\] Train-" + s + r".*=([.\d]+)")
            for s in metric_names]
           + [re.compile(r".*Epoch\[(\d+)\] Validation-" + s + r".*=([.\d]+)")
              for s in metric_names]
           + [re.compile(r".*Epoch\[(\d+)\] Time.*=([.\d]+)")])
    data = {}
    for line in lines:
        for i, r in enumerate(res):
            m = r.match(line)
            if m is not None:
                epoch = int(m.groups()[0])
                val = float(m.groups()[1])
                row = data.setdefault(epoch, [[0.0, 0] for _ in res])
                row[i][0] += val
                row[i][1] += 1
                break
    return {e: [c[0] / c[1] if c[1] else float("nan") for c in row]
            for e, row in sorted(data.items())}


def to_markdown(data, metric_names=("accuracy",)):
    heads = (["epoch"] + ["train-%s" % s for s in metric_names]
             + ["val-%s" % s for s in metric_names] + ["time"])
    out = ["| " + " | ".join(heads) + " |",
           "| " + " | ".join("---" for _ in heads) + " |"]
    for e, vals in data.items():
        out.append("| %d | %s |" % (e, " | ".join("%.4g" % v for v in vals)))
    return "\n".join(out)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("logfile", nargs=1, type=str)
    p.add_argument("--format", type=str, default="markdown",
                   choices=["markdown", "none"])
    p.add_argument("--metric-names", type=str, nargs="+",
                   default=["accuracy"])
    args = p.parse_args()
    with open(args.logfile[0]) as f:
        data = parse(f.readlines(), args.metric_names)
    if args.format == "markdown":
        print(to_markdown(data, args.metric_names))
    return data


if __name__ == "__main__":
    main()
