#!/usr/bin/env python
"""Environment diagnostics — reference ``tools/diagnose.py`` (OS /
hardware / python / framework report users paste into bug reports).  The
network-mirror checks are dropped (no egress here); in their place the
TPU-relevant facts: jax/jaxlib versions, visible devices and platform,
virtual-device env knobs, and whether the native C++ data plane loaded.

Usage: python tools/diagnose.py            (with the ambient TPU env)
       ./dev.sh python tools/diagnose.py   (CPU/virtual-mesh env)
"""
from __future__ import annotations

import os
import platform
import sys


def check_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("Arch         :", platform.architecture())


def check_os():
    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("node         :", platform.node())
    print("release      :", platform.release())
    print("version      :", platform.version())


def check_hardware():
    print("----------Hardware Info----------")
    print("machine      :", platform.machine())
    print("processor    :", platform.processor())
    if platform.system() == "Linux":
        try:
            with open("/proc/cpuinfo") as f:
                cores = sum(1 for ln in f if ln.startswith("processor"))
            print("cpu cores    :", cores)
            with open("/proc/meminfo") as f:
                for ln in f:
                    if ln.startswith(("MemTotal", "MemAvailable")):
                        print(ln.strip())
        except OSError:
            pass


def check_framework():
    print("----------Framework Info----------")
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".."))
    import mxnet_tpu as mx

    print("mxnet_tpu    :", os.path.dirname(mx.__file__))
    import jax
    import jaxlib

    print("jax          :", jax.__version__)
    print("jaxlib       :", jaxlib.__version__)
    print("backend      :", jax.default_backend())
    for d in jax.devices():
        print("device       :", d, "(platform=%s)" % d.platform)
    for knob in ("JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH"):
        print("%-12s : %s" % (knob, os.environ.get(knob, "<unset>")))
    for knob in sorted(k for k in os.environ if k.startswith("MXNET_")):
        print("%-12s : %s" % (knob, os.environ[knob]))
    from mxnet_tpu import _native

    try:
        _native.lib()
        print("native io    : loaded")
    except Exception as e:  # noqa: BLE001 — diagnostics must not crash
        print("native io    : unavailable (%s; pure-python fallback)"
              % type(e).__name__)


def main():
    check_python()
    check_os()
    check_hardware()
    check_framework()


if __name__ == "__main__":
    main()
