#!/usr/bin/env python
"""Kill stray training processes across a cluster — reference
``tools/kill-mxnet.py`` (ssh to every host in a hostfile and kill the
named program).  Matches the ``tools/launch.py`` ssh cluster mode of
``parallel/dist.py``.

Usage: python tools/kill-mxnet.py <hostfile> <user> <prog>
"""
from __future__ import annotations

import shlex
import subprocess
import sys


def kill_command(user, prog_name):
    # shlex.quote: a prog/user containing shell metacharacters must not be
    # able to break out of the remote pipeline
    return (
        "ps aux | "
        "grep -v grep | "
        "grep -F -- " + shlex.quote(prog_name) + " | "
        "awk -v u=" + shlex.quote(user) + " '{if($1==u)print $2;}' | "
        "xargs -r kill -9"
    )


def main(argv):
    if len(argv) != 4:
        print("usage: %s <hostfile> <user> <prog>" % argv[0])
        return 1
    host_file, user, prog_name = argv[1:4]
    cmd = kill_command(user, prog_name)
    print(cmd)
    procs = []
    with open(host_file) as f:
        for host in f:
            host = host.strip()
            if not host:
                continue
            if ":" in host:
                host = host[:host.index(":")]
            print(host)
            procs.append(subprocess.Popen(
                ["ssh", "-oStrictHostKeyChecking=no", host, cmd],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        p.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
