#!/usr/bin/env python
"""Autotuning CLI — search / show / clear the winner store (ISSUE 9).

Searches a kernel's declared tuning space (``mxnet_tpu/autotune/space.py``)
with on-device measurement, or proposes a serving bucket ladder from a
recorded ``tools/loadgen.py --save-trace`` traffic trace, and persists the
winner per (device kind, kernel, shape signature) in the
``MXNET_AUTOTUNE_CACHE`` store.  A warm store short-circuits: a second
``search`` for the same key performs ZERO new measurements (pass
``--force`` to re-search).  Every run prints one machine-readable
``AUTOTUNE {json}`` line (``ci/check_autotune.py`` parses it).

Examples::

    # search dconv_col_pallas block shapes at a concrete problem shape
    python tools/autotune.py search --kernel dconv_col_pallas \\
        --bg 8 --n 2432 --h 38 --w 64 --c 512 --dtype bfloat16

    # propose ladder rungs from recorded traffic, adopted by any Engine
    # started with MXNET_AUTOTUNE=1 for the same sample shapes
    python tools/loadgen.py --mode open --duration 5 --save-trace t.jsonl
    python tools/autotune.py search --trace t.jsonl

    python tools/autotune.py show
    python tools/autotune.py clear --kernel dconv_col_pallas

The CLI itself is the opt-in: it sets ``MXNET_AUTOTUNE=1`` for its own
process so the store and the dispatch-site overrides are live regardless
of the ambient environment.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..")))


def _emit(payload):
    print("AUTOTUNE " + json.dumps(payload, sort_keys=True))


def _search_dconv(args):
    """Measured grid search over the dconv_col_pallas block-shape space at
    one concrete problem shape (fwd + bwd, the kernel's real usage)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu import autotune
    from mxnet_tpu.ops.pallas_kernels import dconv_col_pallas

    H, W, C, BG, N = args.h, args.w, args.c, args.bg, args.n
    HW = H * W
    dtype = jnp.dtype(args.dtype)
    itemsize = dtype.itemsize
    sig = autotune.dconv_shape_sig(N, HW, C, itemsize)
    kernel = "dconv_col_pallas"
    if not args.force:
        winner = autotune.lookup(kernel, sig)
        if winner is not None:
            _emit({"kind": "dconv", "kernel": kernel, "sig": sig,
                   "cached": True, "measurements": 0, "config": winner})
            print("autotune: warm store hit for %s — zero measurements "
                  "(--force to re-search)" % sig)
            return 0

    # the same inputs the parity test builds, deterministic
    rng = np.random.RandomState(args.seed)
    y0 = jnp.asarray(rng.randint(0, max(1, H - 1), (BG, N)).astype(np.int32))
    y1 = jnp.minimum(y0 + 1, H - 1)
    x0 = jnp.asarray(rng.randint(0, max(1, W - 1), (BG, N)).astype(np.int32))
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly = jnp.asarray(rng.rand(BG, N).astype(np.float32))
    lx = jnp.asarray(rng.rand(BG, N).astype(np.float32))
    lf = jnp.asarray((rng.rand(BG, N) > 0.2).astype(np.float32))
    ft = jnp.asarray(rng.randn(BG, HW, C)).astype(dtype)
    g = jnp.asarray(rng.randn(BG, N, C).astype(np.float32))
    # the compiled kernel exists only on TPU; elsewhere measure the
    # interpreter (relative ordering only — label the numbers honestly)
    interpret = jax.default_backend() != "tpu"

    def build():
        # a FRESH jit per candidate: the override pins the config for THIS
        # trace, and no signature cache can hand back another candidate
        @jax.jit
        def step(ly, lx, lf, ft):
            def loss(ly, lx, lf, ft):
                out = dconv_col_pallas(y0, y1, x0, x1, ly, lx, lf, ft,
                                       (H, W), interpret)
                return jnp.sum(out.astype(jnp.float32) * g)

            return jax.grad(loss, argnums=(0, 1, 2, 3))(ly, lx, lf, ft)

        return step

    space = autotune.get_space(kernel)
    ctx = {"N": N, "HW": HW, "C": C, "itemsize": itemsize}
    # dedupe by EFFECTIVE block size (nblk caps at N): measuring the same
    # realized grid twice wastes trials and can only add timer noise
    configs, seen = [], set()
    for cfg in space.configs(**ctx):
        eff = min(int(cfg["nblk"]), N)
        if eff not in seen:
            seen.add(eff)
            configs.append(cfg)
    eff_space = autotune.TuningSpace(
        kernel, {"nblk": tuple(c["nblk"] for c in configs)},
        space.default, space.constraint)

    def measure(cfg):
        return autotune.measure_candidate(
            kernel, cfg, build, (ly, lx, lf, ft),
            warmup=args.warmup, repeat=args.repeat)

    best, results = autotune.run_search(eff_space, measure, ctx=ctx,
                                        max_trials=args.max_trials)
    default_s = results[0]["seconds"]
    best_s = min(r["seconds"] for r in results)
    meta = {"default_s": default_s, "best_s": best_s,
            "trials": len(results), "backend": jax.default_backend(),
            "interpret": interpret, "bg": BG}
    # compile plane (ISSUE 13): under MXNET_COSTPLANE every trial carried
    # measured XLA cost features — persist them with the winner (the
    # learned cost model's training rows, ROADMAP item 4).  Gate off ⇒
    # features_for returns None and the meta stays byte-identical, so
    # readers without the gate never see the keys.
    trial_costs = []
    for r in results:
        feats = autotune.measure.features_for(kernel, r["config"])
        if feats is not None:
            trial_costs.append(dict(config=r["config"],
                                    seconds=round(r["seconds"], 6),
                                    cost=feats))
    if trial_costs:
        meta["cost"] = autotune.measure.features_for(kernel, best)
        meta["trial_costs"] = trial_costs
    autotune.record(kernel, sig, best, score=best_s, meta=meta)
    for r in results:
        print("  %-24s %.6f s%s" % (r["config"], r["seconds"],
                                    "  (default)" if r is results[0] else ""))
    _emit({"kind": "dconv", "kernel": kernel, "sig": sig, "cached": False,
           "measurements": len(results), "config": best,
           "default_s": round(default_s, 6), "best_s": round(best_s, 6),
           "interpret": interpret})
    return 0


def _search_ladder(args):
    """Pure-host ladder proposal from a recorded request trace."""
    from mxnet_tpu import autotune

    recs = autotune.ladder.load_trace(args.trace)
    if args.sample_shape:
        # store under the ENGINE's declared sample shapes: on a
        # variable-size stream the trace's elementwise-max shapes can
        # differ from what Engine(sample_shapes=...) will look up
        shapes = {}
        for spec in args.sample_shape:
            name, _, dims = spec.partition(":")
            shapes[name] = tuple(int(d) for d in dims.split(",") if d)
    else:
        shapes = autotune.ladder.trace_sample_shapes(recs)
    sig = autotune.ladder_sig(shapes)
    print("autotune: ladder signature %r" % sig)
    kernel = autotune.LADDER_KERNEL
    if not args.force:
        winner = autotune.lookup(kernel, sig)
        if winner is not None:
            _emit({"kind": "ladder", "kernel": kernel, "sig": sig,
                   "cached": True, "measurements": 0, "config": winner})
            print("autotune: warm store hit for %s — zero measurements "
                  "(--force to re-search)" % sig)
            return 0
    try:
        default = tuple(sorted({int(x) for x in
                                str(args.default_ladder).split(",")
                                if x.strip()}))
    except ValueError:
        default = ()
    if not default or default[0] < 1:
        print("autotune: --default-ladder must be comma-separated positive "
              "ints, got %r" % args.default_ladder, file=sys.stderr)
        return 2
    tuned, rep = autotune.propose(
        recs, default=default, max_rungs=args.max_rungs,
        max_wait_s=args.max_wait_ms / 1000.0)
    autotune.record(kernel, sig, {"batch_sizes": list(tuned)},
                    score=rep["objective_tuned"],
                    meta={"trace": os.path.basename(args.trace),
                          "requests": rep["requests"],
                          "objective_default": rep["objective_default"],
                          "default": list(default)})
    print("autotune: %d requests  default %s obj %.4f  ->  tuned %s obj %.4f"
          % (rep["requests"], default, rep["objective_default"],
             tuned, rep["objective_tuned"]))
    _emit({"kind": "ladder", "kernel": kernel, "sig": sig, "cached": False,
           "measurements": 0, "config": {"batch_sizes": list(tuned)},
           "objective_default": round(rep["objective_default"], 6),
           "objective_tuned": round(rep["objective_tuned"], 6),
           "requests": rep["requests"]})
    return 0


# kernel name -> measured-search runner; a space registered in
# autotune.space without an entry here is a clean CLI error, not a crash
_KERNEL_RUNNERS = {"dconv_col_pallas": _search_dconv}


def _show(args):
    from mxnet_tpu import autotune

    ent = autotune.entries()
    if not ent:
        print("autotune: store %s is empty" % autotune.store_path())
        return 0
    print("autotune: %d entr%s in %s"
          % (len(ent), "y" if len(ent) == 1 else "ies",
             autotune.store_path()))
    for key in sorted(ent):
        e = ent[key]
        score = e.get("score")
        print("  %-60s %s%s" % (key, e.get("config"),
                                "" if score is None
                                else "  score=%.6g" % score))
    return 0


def _clear(args):
    from mxnet_tpu import autotune

    n = autotune.clear(kernel=args.kernel)
    print("autotune: removed %d entr%s%s" % (
        n, "y" if n == 1 else "ies",
        " for kernel %s" % args.kernel if args.kernel else ""))
    return 0


def main(argv=None):
    # the CLI is the explicit opt-in: its own process always runs tuned
    os.environ["MXNET_AUTOTUNE"] = "1"
    p = argparse.ArgumentParser(prog="autotune",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("search", help="search a kernel space or propose a "
                                      "ladder from a traffic trace")
    s.add_argument("--kernel", default=None,
                   help="registered tuning space to search (e.g. "
                        "dconv_col_pallas); omit with --trace")
    s.add_argument("--trace", default=None,
                   help="loadgen --save-trace JSONL: propose bucket-ladder "
                        "rungs instead of searching a kernel space")
    s.add_argument("--force", action="store_true",
                   help="re-search even on a warm store hit")
    # dconv problem shape (defaults: a CPU-sized smoke problem; use the
    # north-star res5 shape on the chip: --bg 8 --n 2432 --h 38 --w 64
    # --c 512 --dtype bfloat16)
    s.add_argument("--bg", type=int, default=1, help="batch x groups")
    s.add_argument("--n", type=int, default=128, help="sample rows")
    s.add_argument("--h", type=int, default=4)
    s.add_argument("--w", type=int, default=8)
    s.add_argument("--c", type=int, default=16, help="channels per group")
    s.add_argument("--dtype", default="float32")
    s.add_argument("--warmup", type=int, default=2)
    s.add_argument("--repeat", type=int, default=5)
    s.add_argument("--max-trials", type=int, default=64)
    s.add_argument("--seed", type=int, default=0)
    # ladder proposal knobs
    s.add_argument("--default-ladder", default="1,2,4,8",
                   help="the hand-configured ladder the proposal must "
                        "strictly beat (else it is kept)")
    s.add_argument("--max-rungs", type=int, default=4)
    s.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="partial-batch flush deadline assumed by the "
                        "replay (match the Engine's MXNET_SERVE_MAX_WAIT_MS)")
    s.add_argument("--sample-shape", action="append", metavar="NAME:D1,D2",
                   help="store the ladder winner under these declared "
                        "per-sample shapes (repeatable; loadgen --shapes "
                        "syntax) instead of the trace's elementwise-max "
                        "shapes — required when the serving Engine "
                        "declares larger sample_shapes than the recorded "
                        "traffic ever reached, or its lookup would miss")
    s.set_defaults(fn=lambda a: (_search_ladder(a) if a.trace
                                 else _KERNEL_RUNNERS[a.kernel](a)))

    sh = sub.add_parser("show", help="list persisted winners")
    sh.set_defaults(fn=_show)

    c = sub.add_parser("clear", help="drop persisted winners")
    c.add_argument("--kernel", default=None,
                   help="only this kernel's entries (default: everything)")
    c.set_defaults(fn=_clear)

    args = p.parse_args(argv)
    if args.cmd == "search" and not args.trace and not args.kernel:
        p.error("search needs --kernel <space> or --trace <jsonl>")
    if args.cmd == "search" and args.kernel is not None:
        # validate against the live registry, not a frozen list: a newly
        # registered space is rejected only until it gains a measurement
        # runner below
        from mxnet_tpu import autotune

        registered = sorted(autotune.spaces())
        if args.kernel not in registered:
            p.error("unknown kernel %r (registered: %s)"
                    % (args.kernel, ", ".join(registered)))
        if args.kernel not in _KERNEL_RUNNERS:
            p.error("no measurement runner for kernel %r yet (runnable: %s)"
                    % (args.kernel, ", ".join(sorted(_KERNEL_RUNNERS))))
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
