#!/usr/bin/env python
"""Autotuning CLI — search / show / clear the winner store (ISSUE 9).

Searches a kernel's declared tuning space (``mxnet_tpu/autotune/space.py``)
with on-device measurement, or proposes a serving bucket ladder from a
recorded ``tools/loadgen.py --save-trace`` traffic trace, and persists the
winner per (device kind, kernel, shape signature) in the
``MXNET_AUTOTUNE_CACHE`` store.  A warm store short-circuits: a second
``search`` for the same key performs ZERO new measurements (pass
``--force`` to re-search).  Every run prints one machine-readable
``AUTOTUNE {json}`` line (``ci/check_autotune.py`` parses it).

Examples::

    # search dconv_col_pallas block shapes at a concrete problem shape
    python tools/autotune.py search --kernel dconv_col_pallas \\
        --bg 8 --n 2432 --h 38 --w 64 --c 512 --dtype bfloat16

    # propose ladder rungs from recorded traffic, adopted by any Engine
    # started with MXNET_AUTOTUNE=1 for the same sample shapes
    python tools/loadgen.py --mode open --duration 5 --save-trace t.jsonl
    python tools/autotune.py search --trace t.jsonl

    python tools/autotune.py show
    python tools/autotune.py clear --kernel dconv_col_pallas

The CLI itself is the opt-in: it sets ``MXNET_AUTOTUNE=1`` for its own
process so the store and the dispatch-site overrides are live regardless
of the ambient environment.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..")))


def _emit(payload):
    print("AUTOTUNE " + json.dumps(payload, sort_keys=True))


def _warm_hit(kernel, sig, kind, args):
    """Warm-store short-circuit shared by every kernel runner: a persisted
    winner for this key means ZERO new measurements (--force re-searches)."""
    from mxnet_tpu import autotune

    if args.force:
        return False
    winner = autotune.lookup(kernel, sig)
    if winner is None:
        return False
    _emit({"kind": kind, "kernel": kernel, "sig": sig, "cached": True,
           "measurements": 0, "config": winner})
    print("autotune: warm store hit for %s — zero measurements "
          "(--force to re-search)" % sig)
    return True


def _resolve_strategy(kernel, args):
    """--strategy resolution: ``auto`` uses the learned cost model when it
    is enabled AND the store holds enough training rows, else grid.  An
    explicit ``predict`` that cannot be honored degrades to grid with a
    message (never an error: the model is advisory, ISSUE 18)."""
    from mxnet_tpu.autotune import costmodel

    want = getattr(args, "strategy", "auto")
    if want == "grid":
        return "grid", None
    if not costmodel.model_enabled():
        if want == "predict":
            print("autotune: MXNET_AUTOTUNE_MODEL=0 — grid search")
        return "grid", None
    model = costmodel.model_for(kernel)
    if model is None:
        if want == "predict":
            print("autotune: no usable cost model for %s yet (fewer than "
                  "%d stored trial rows) — grid search"
                  % (kernel, costmodel.MIN_ROWS))
        return "grid", None
    return "predict", model


def _run_and_finish(kernel, sig, kind, space_obj, ctx, measure, args,
                    meta_extra=None, emit_extra=None):
    """Shared search tail for every kernel runner: resolve the strategy,
    run grid search or predict-then-measure, persist the winner with its
    trial_costs training rows (finite trials only — a failed candidate's
    +inf sentinel must never teach the model a latency), print the trial
    table, emit the machine-readable AUTOTUNE line."""
    import math

    from mxnet_tpu import autotune
    from mxnet_tpu.autotune import costmodel
    from mxnet_tpu.autotune.store import _device_kind

    strategy, model = _resolve_strategy(kernel, args)
    grid = space_obj.configs(**ctx)
    if strategy == "predict":
        top_k = args.top_k if args.top_k > 0 \
            else costmodel.default_top_k(len(grid))
        dev = _device_kind()
        best, results, report = autotune.predict_then_measure(
            space_obj, measure,
            lambda cfg: model.predict_one(sig, cfg, device_kind=dev),
            ctx=ctx, top_k=top_k)
        saved = report["saved"]
    else:
        best, results = autotune.run_search(space_obj, measure, ctx=ctx,
                                            max_trials=args.max_trials)
        saved = 0
    finite = [r for r in results
              if isinstance(r["seconds"], (int, float))
              and math.isfinite(r["seconds"])]
    failed = len(results) - len(finite)
    if not finite:
        print("autotune: every candidate for %s failed — nothing recorded"
              % kernel, file=sys.stderr)
        _emit({"kind": kind, "kernel": kernel, "sig": sig, "cached": False,
               "measurements": len(results), "failed": failed,
               "strategy": strategy, "config": None})
        return 1
    default_s = results[0]["seconds"]
    default_ok = isinstance(default_s, (int, float)) \
        and math.isfinite(default_s)
    best_s = min(r["seconds"] for r in finite)
    meta = {"default_s": round(default_s, 6) if default_ok else None,
            "best_s": round(best_s, 6), "trials": len(results),
            "strategy": strategy, "grid": len(grid)}
    if failed:
        meta["failed"] = failed
    meta.update(meta_extra or {})
    # compile plane (ISSUE 13): under MXNET_COSTPLANE every successful
    # trial carried measured XLA cost features — persist them with the
    # winner (the learned cost model's training rows).  Gate off ⇒
    # features_for returns None and the meta stays byte-identical.
    trial_costs = []
    for r in finite:
        feats = autotune.measure.features_for(kernel, r["config"])
        if feats is not None:
            trial_costs.append(dict(config=r["config"],
                                    seconds=round(r["seconds"], 6),
                                    cost=feats))
    if trial_costs:
        meta["cost"] = autotune.measure.features_for(kernel, best)
        meta["trial_costs"] = trial_costs
    autotune.record(kernel, sig, best, score=best_s, meta=meta)
    for r in results:
        ok = isinstance(r["seconds"], (int, float)) \
            and math.isfinite(r["seconds"])
        print("  %-28s %s%s" % (
            r["config"],
            "%.6f s" % r["seconds"] if ok else "FAILED",
            "  (default)" if r is results[0] else ""))
    payload = {"kind": kind, "kernel": kernel, "sig": sig, "cached": False,
               "measurements": len(results), "config": best,
               "default_s": round(default_s, 6) if default_ok else None,
               "best_s": round(best_s, 6), "strategy": strategy,
               "grid": len(grid), "trials_saved": saved}
    if failed:
        payload["failed"] = failed
    payload.update(emit_extra or {})
    _emit(payload)
    return 0


def _search_dconv(args):
    """Measured grid search over the dconv_col_pallas block-shape space at
    one concrete problem shape (fwd + bwd, the kernel's real usage)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu import autotune
    from mxnet_tpu.ops.pallas_kernels import dconv_col_pallas

    H, W, C, BG, N = args.h, args.w, args.c, args.bg, args.n
    HW = H * W
    dtype = jnp.dtype(args.dtype)
    itemsize = dtype.itemsize
    sig = autotune.dconv_shape_sig(N, HW, C, itemsize)
    kernel = "dconv_col_pallas"
    if _warm_hit(kernel, sig, "dconv", args):
        return 0

    # the same inputs the parity test builds, deterministic
    rng = np.random.RandomState(args.seed)
    y0 = jnp.asarray(rng.randint(0, max(1, H - 1), (BG, N)).astype(np.int32))
    y1 = jnp.minimum(y0 + 1, H - 1)
    x0 = jnp.asarray(rng.randint(0, max(1, W - 1), (BG, N)).astype(np.int32))
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly = jnp.asarray(rng.rand(BG, N).astype(np.float32))
    lx = jnp.asarray(rng.rand(BG, N).astype(np.float32))
    lf = jnp.asarray((rng.rand(BG, N) > 0.2).astype(np.float32))
    ft = jnp.asarray(rng.randn(BG, HW, C)).astype(dtype)
    g = jnp.asarray(rng.randn(BG, N, C).astype(np.float32))
    # the compiled kernel exists only on TPU; elsewhere measure the
    # interpreter (relative ordering only — label the numbers honestly)
    interpret = jax.default_backend() != "tpu"

    def build():
        # a FRESH jit per candidate: the override pins the config for THIS
        # trace, and no signature cache can hand back another candidate
        @jax.jit
        def step(ly, lx, lf, ft):
            def loss(ly, lx, lf, ft):
                out = dconv_col_pallas(y0, y1, x0, x1, ly, lx, lf, ft,
                                       (H, W), interpret)
                return jnp.sum(out.astype(jnp.float32) * g)

            return jax.grad(loss, argnums=(0, 1, 2, 3))(ly, lx, lf, ft)

        return step

    space = autotune.get_space(kernel)
    ctx = {"N": N, "HW": HW, "C": C, "itemsize": itemsize}
    # dedupe by EFFECTIVE block size (nblk caps at N): measuring the same
    # realized grid twice wastes trials and can only add timer noise
    configs, seen = [], set()
    for cfg in space.configs(**ctx):
        eff = min(int(cfg["nblk"]), N)
        if eff not in seen:
            seen.add(eff)
            configs.append(cfg)
    eff_space = autotune.TuningSpace(
        kernel, {"nblk": tuple(c["nblk"] for c in configs)},
        space.default, space.constraint)

    def measure(cfg):
        return autotune.measure_candidate(
            kernel, cfg, build, (ly, lx, lf, ft),
            warmup=args.warmup, repeat=args.repeat)

    return _run_and_finish(kernel, sig, "dconv", eff_space, ctx, measure,
                           args,
                           meta_extra={"backend": jax.default_backend(),
                                       "interpret": interpret, "bg": BG},
                           emit_extra={"interpret": interpret})


def _search_ladder(args):
    """Pure-host ladder proposal from a recorded request trace."""
    from mxnet_tpu import autotune

    recs = autotune.ladder.load_trace(args.trace)
    if args.sample_shape:
        # store under the ENGINE's declared sample shapes: on a
        # variable-size stream the trace's elementwise-max shapes can
        # differ from what Engine(sample_shapes=...) will look up
        shapes = {}
        for spec in args.sample_shape:
            name, _, dims = spec.partition(":")
            shapes[name] = tuple(int(d) for d in dims.split(",") if d)
    else:
        shapes = autotune.ladder.trace_sample_shapes(recs)
    sig = autotune.ladder_sig(shapes)
    print("autotune: ladder signature %r" % sig)
    kernel = autotune.LADDER_KERNEL
    if not args.force:
        winner = autotune.lookup(kernel, sig)
        if winner is not None:
            _emit({"kind": "ladder", "kernel": kernel, "sig": sig,
                   "cached": True, "measurements": 0, "config": winner})
            print("autotune: warm store hit for %s — zero measurements "
                  "(--force to re-search)" % sig)
            return 0
    try:
        default = tuple(sorted({int(x) for x in
                                str(args.default_ladder).split(",")
                                if x.strip()}))
    except ValueError:
        default = ()
    if not default or default[0] < 1:
        print("autotune: --default-ladder must be comma-separated positive "
              "ints, got %r" % args.default_ladder, file=sys.stderr)
        return 2
    tuned, rep = autotune.propose(
        recs, default=default, max_rungs=args.max_rungs,
        max_wait_s=args.max_wait_ms / 1000.0)
    autotune.record(kernel, sig, {"batch_sizes": list(tuned)},
                    score=rep["objective_tuned"],
                    meta={"trace": os.path.basename(args.trace),
                          "requests": rep["requests"],
                          "objective_default": rep["objective_default"],
                          "default": list(default)})
    print("autotune: %d requests  default %s obj %.4f  ->  tuned %s obj %.4f"
          % (rep["requests"], default, rep["objective_default"],
             tuned, rep["objective_tuned"]))
    _emit({"kind": "ladder", "kernel": kernel, "sig": sig, "cached": False,
           "measurements": 0, "config": {"batch_sizes": list(tuned)},
           "objective_default": round(rep["objective_default"], 6),
           "objective_tuned": round(rep["objective_tuned"], 6),
           "requests": rep["requests"]})
    return 0


def _search_nms(args):
    """Measured search over the blocked-NMS box-tile space at one N."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu import autotune
    from mxnet_tpu.ops.pallas_kernels import nms_alive_pallas

    kernel = "nms_alive_pallas"
    N = args.nms_boxes
    sig = autotune.nms_shape_sig(1, N)
    if _warm_hit(kernel, sig, "nms", args):
        return 0
    rng = np.random.RandomState(args.seed)
    # clustered unit-square corner boxes: enough overlap that suppression
    # actually iterates (an all-disjoint set would measure the no-op path)
    wh = rng.rand(N, 2).astype(np.float32) * 0.2 + 0.05
    xy = rng.rand(N, 2).astype(np.float32) * 0.8
    boxes = jnp.asarray(np.concatenate([xy, xy + wh], axis=1))
    valid = jnp.ones((N,), bool)
    interpret = jax.default_backend() != "tpu"

    def build():
        # fresh jit per candidate; _nms_single's cached custom_vmap fn is
        # NOT jitted, so each outer trace re-reads the pinned tile
        @jax.jit
        def run(b, v):
            return nms_alive_pallas(b, v, None, thresh=0.5,
                                    interpret=interpret)

        return run

    def measure(cfg):
        return autotune.measure_candidate(kernel, cfg, build, (boxes, valid),
                                          warmup=args.warmup,
                                          repeat=args.repeat)

    return _run_and_finish(kernel, sig, "nms", autotune.get_space(kernel),
                           {"N": N}, measure, args,
                           meta_extra={"backend": jax.default_backend(),
                                       "interpret": interpret},
                           emit_extra={"interpret": interpret})


def _search_abuild(args):
    """Measured search over the PSROI accumulation-build roi-block space
    (fwd + bwd through jax.grad — the backward is the pass the VMEM guard
    prunes on)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu import autotune
    from mxnet_tpu.ops.pallas_kernels import psroi_abuild_pallas

    kernel = "psroi_abuild_pallas"
    N, S, H, W = args.ab_n, args.ab_s, args.ab_h, args.ab_w
    sig = autotune.psroi_shape_sig(N, S, H, W, 4)
    if _warm_hit(kernel, sig, "abuild", args):
        return 0
    rng = np.random.RandomState(args.seed)
    yv = jnp.asarray(rng.rand(N, S, H).astype(np.float32))
    xv = jnp.asarray(rng.rand(N, S, W).astype(np.float32))
    g = jnp.asarray(rng.randn(N, H, W).astype(np.float32))
    interpret = jax.default_backend() != "tpu"

    def build():
        @jax.jit
        def step(yv, xv):
            def loss(yv, xv):
                A = psroi_abuild_pallas(yv, xv, jnp.float32, interpret)
                return jnp.sum(A * g)

            return jax.grad(loss, argnums=(0, 1))(yv, xv)

        return step

    def measure(cfg):
        return autotune.measure_candidate(kernel, cfg, build, (yv, xv),
                                          warmup=args.warmup,
                                          repeat=args.repeat)

    ctx = {"N": N, "S": S, "H": H, "W": W, "itemsize": 4}
    return _run_and_finish(kernel, sig, "abuild", autotune.get_space(kernel),
                           ctx, measure, args,
                           meta_extra={"backend": jax.default_backend(),
                                       "interpret": interpret},
                           emit_extra={"interpret": interpret})


def _search_quant(args, kernel):
    """Measured search over one tiled-elementwise int8 row-block space."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu import autotune
    from mxnet_tpu.ops import pallas_kernels as pk

    rows = args.q_rows
    quantize = kernel == "quantize_int8_pallas"
    in_itemsize = 4 if quantize else 1
    out_itemsize = 1 if quantize else 4
    sig = autotune.quant_shape_sig(rows, in_itemsize)
    if _warm_hit(kernel, sig, "quant", args):
        return 0
    rng = np.random.RandomState(args.seed)
    if quantize:
        x = jnp.asarray(rng.randn(rows, pk._LANE).astype(np.float32))
        fn = pk.quantize_int8_pallas
    else:
        x = jnp.asarray(rng.randint(-127, 128,
                                    (rows, pk._LANE)).astype(np.int8))
        fn = pk.dequantize_int8_pallas
    interpret = jax.default_backend() != "tpu"

    def build():
        # the kernel entry is itself module-level @jax.jit: drop its trace
        # cache so THIS candidate's pinned block shapes the inner jaxpr (a
        # same-shape hit would silently reuse the previous candidate's grid)
        try:
            fn.clear_cache()
        except Exception:
            pass

        @jax.jit
        def run(x):
            return fn(x, 4.0, interpret=interpret)

        return run

    def measure(cfg):
        return autotune.measure_candidate(kernel, cfg, build, (x,),
                                          warmup=args.warmup,
                                          repeat=args.repeat)

    ctx = {"rows": rows, "in_itemsize": in_itemsize,
           "out_itemsize": out_itemsize}
    return _run_and_finish(kernel, sig, "quant", autotune.get_space(kernel),
                           ctx, measure, args,
                           meta_extra={"backend": jax.default_backend(),
                                       "interpret": interpret},
                           emit_extra={"interpret": interpret})


def _search_quantize(args):
    return _search_quant(args, "quantize_int8_pallas")


def _search_dequantize(args):
    return _search_quant(args, "dequantize_int8_pallas")


def _search_fused_step(args):
    """Measured search over the NON-kernel fused-step layout space (ISSUE
    18): ZeRO-1 on/off × input prefetch depth, timed end-to-end as a short
    training epoch of a tiny MLP Module.  The winner is adopted by
    operators (set ``MXNET_FUSED_ZERO`` / ``PrefetchingIter(
    prefetch_depth=...)`` from ``show``), not by a trace-time site."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autotune
    from mxnet_tpu import module as mod_mod
    from mxnet_tpu import parallel
    from mxnet_tpu.io import NDArrayIter, PrefetchingIter

    kernel = "fused_step_layout"
    batch, dim = args.fs_batch, args.fs_dim
    ndev = jax.device_count()
    use_mesh = ndev >= 2 and batch % ndev == 0
    sig = autotune.fused_step_sig(batch, dim, ndev if use_mesh else 1)
    if _warm_hit(kernel, sig, "fused_step", args):
        return 0
    os.environ["MXNET_MODULE_FUSED_STEP"] = "1"
    mesh = parallel.make_mesh({"dp": ndev}) if use_mesh else None
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    rows = batch * args.fs_steps
    data = rng.randn(rows, dim).astype(np.float32)
    label = rng.randint(0, 4, (rows,)).astype(np.float32)

    def measure(cfg):
        # the layout knobs are env/wrapper state, not a trace-time store
        # lookup: pin them around a fresh Module per candidate (the fused
        # stepper's stale() check rebuilds on a MXNET_FUSED_ZERO flip)
        prev = os.environ.get("MXNET_FUSED_ZERO")
        os.environ["MXNET_FUSED_ZERO"] = str(int(cfg.get("zero", 0)))
        depth = int(cfg.get("prefetch", 0))
        holder = {}

        def build():
            d = mx.sym.var("data")
            h = mx.sym.FullyConnected(d, name="fc1", num_hidden=16)
            h = mx.sym.Activation(h, name="relu1", act_type="relu")
            sym = mx.sym.SoftmaxOutput(
                mx.sym.FullyConnected(h, name="fc2", num_hidden=4),
                name="softmax")
            mod = mod_mod.Module(sym, mesh=mesh)
            mod.bind(data_shapes=[("data", (batch, dim))],
                     label_shapes=[("softmax_label", (batch,))])
            mod.init_params()
            mod.init_optimizer(optimizer="sgd",
                               optimizer_params={"learning_rate": 0.1,
                                                 "momentum": 0.9})
            base = NDArrayIter(data, label, batch_size=batch)
            # prefetch=0 means NO wrapper: PrefetchingIter's depth-0 queue
            # would be UNBOUNDED, the opposite of "prefetch disabled"
            it = PrefetchingIter(base, prefetch_depth=depth) if depth \
                else base
            holder["it"] = it

            def epoch():
                it.reset()
                out = None
                for b in it:
                    mod.forward_backward(b)
                    mod.update()
                    out = mod.get_outputs()[0]
                return out.asnumpy()

            return epoch

        try:
            return autotune.measure_candidate(
                kernel, cfg, build, (), warmup=args.warmup,
                repeat=args.repeat)
        finally:
            stop = getattr(holder.get("it"), "_stop", None)
            if stop is not None:
                stop.set()  # don't leak a prefetch worker between trials
            if prev is None:
                os.environ.pop("MXNET_FUSED_ZERO", None)
            else:
                os.environ["MXNET_FUSED_ZERO"] = prev

    return _run_and_finish(kernel, sig, "fused_step",
                           autotune.get_space(kernel), {"mesh": use_mesh},
                           measure, args,
                           meta_extra={"backend": jax.default_backend(),
                                       "ndev": ndev,
                                       "steps": args.fs_steps})


# kernel name -> measured-search runner; a space registered in
# autotune.space without an entry here is a clean CLI error, not a crash
_KERNEL_RUNNERS = {
    "dconv_col_pallas": _search_dconv,
    "nms_alive_pallas": _search_nms,
    "psroi_abuild_pallas": _search_abuild,
    "quantize_int8_pallas": _search_quantize,
    "dequantize_int8_pallas": _search_dequantize,
    "fused_step_layout": _search_fused_step,
}


def _show(args):
    from mxnet_tpu import autotune

    ent = autotune.entries()
    if not ent:
        print("autotune: store %s is empty" % autotune.store_path())
        return 0
    print("autotune: %d entr%s in %s"
          % (len(ent), "y" if len(ent) == 1 else "ies",
             autotune.store_path()))
    for key in sorted(ent):
        e = ent[key]
        score = e.get("score")
        print("  %-60s %s%s" % (key, e.get("config"),
                                "" if score is None
                                else "  score=%.6g" % score))
        if getattr(args, "features", False):
            meta = e.get("meta") if isinstance(e.get("meta"), dict) else {}
            cost = meta.get("cost")
            if cost:
                print("      cost: %s" % json.dumps(cost, sort_keys=True))
            tcs = meta.get("trial_costs")
            if tcs:
                print("      trial rows: %d (strategy=%s, grid=%s)"
                      % (len(tcs), meta.get("strategy", "grid"),
                         meta.get("grid")))
    return 0


def _clear(args):
    from mxnet_tpu import autotune

    n = autotune.clear(kernel=args.kernel)
    print("autotune: removed %d entr%s%s" % (
        n, "y" if n == 1 else "ies",
        " for kernel %s" % args.kernel if args.kernel else ""))
    return 0


def _search_cmd(args):
    """search dispatch: ladder trace, one kernel, or --all-kernels; ends
    with one ``AUTOTUNE {"kind": "telemetry", ...}`` line (the bench
    telemetry block, trials_saved included) when telemetry is on."""
    if args.trace:
        rc = _search_ladder(args)
    elif args.all_kernels:
        rc = 0
        for name in sorted(_KERNEL_RUNNERS):
            print("autotune: === %s ===" % name)
            rc = max(rc, _KERNEL_RUNNERS[name](args))
    else:
        rc = _KERNEL_RUNNERS[args.kernel](args)
    from mxnet_tpu.telemetry import instrument as tin

    if tin.enabled():
        _emit({"kind": "telemetry", "telemetry": tin.summary()})
    return rc


def main(argv=None):
    # the CLI is the explicit opt-in: its own process always runs tuned
    os.environ["MXNET_AUTOTUNE"] = "1"
    p = argparse.ArgumentParser(prog="autotune",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("search", help="search a kernel space or propose a "
                                      "ladder from a traffic trace")
    s.add_argument("--kernel", default=None,
                   help="registered tuning space to search (e.g. "
                        "dconv_col_pallas); omit with --trace")
    s.add_argument("--trace", default=None,
                   help="loadgen --save-trace JSONL: propose bucket-ladder "
                        "rungs instead of searching a kernel space")
    s.add_argument("--all-kernels", action="store_true",
                   help="search every runnable kernel space in turn "
                        "(shapes from the per-kernel flags below)")
    s.add_argument("--force", action="store_true",
                   help="re-search even on a warm store hit")
    s.add_argument("--strategy", choices=("auto", "grid", "predict"),
                   default="auto",
                   help="auto (default): predict-then-measure when the "
                        "learned cost model has enough stored rows, else "
                        "exhaustive grid; grid/predict force one (predict "
                        "degrades to grid with a message if unusable)")
    s.add_argument("--top-k", type=int, default=0,
                   help="candidates measured under predict (beyond the "
                        "always-measured default); 0 = MXNET_AUTOTUNE_TOPK "
                        "or a quarter of the grid")
    # dconv problem shape (defaults: a CPU-sized smoke problem; use the
    # north-star res5 shape on the chip: --bg 8 --n 2432 --h 38 --w 64
    # --c 512 --dtype bfloat16)
    s.add_argument("--bg", type=int, default=1, help="batch x groups")
    s.add_argument("--n", type=int, default=128, help="sample rows")
    s.add_argument("--h", type=int, default=4)
    s.add_argument("--w", type=int, default=8)
    s.add_argument("--c", type=int, default=16, help="channels per group")
    s.add_argument("--dtype", default="float32")
    s.add_argument("--warmup", type=int, default=2)
    s.add_argument("--repeat", type=int, default=5)
    s.add_argument("--max-trials", type=int, default=64)
    s.add_argument("--seed", type=int, default=0)
    # nms_alive_pallas problem shape
    s.add_argument("--nms-boxes", type=int, default=512,
                   help="boxes per image for the NMS tile search")
    # psroi_abuild_pallas problem shape (north-star-ish small map)
    s.add_argument("--ab-n", type=int, default=96, help="rois")
    s.add_argument("--ab-s", type=int, default=4, help="sample points/bin")
    s.add_argument("--ab-h", type=int, default=7)
    s.add_argument("--ab-w", type=int, default=7)
    # quantize/dequantize_int8_pallas problem shape
    s.add_argument("--q-rows", type=int, default=1024,
                   help="(rows, 128) flattened tiles for the int8 kernels")
    # fused_step_layout problem shape
    s.add_argument("--fs-batch", type=int, default=16)
    s.add_argument("--fs-dim", type=int, default=8)
    s.add_argument("--fs-steps", type=int, default=4,
                   help="train steps per timed epoch")
    # ladder proposal knobs
    s.add_argument("--default-ladder", default="1,2,4,8",
                   help="the hand-configured ladder the proposal must "
                        "strictly beat (else it is kept)")
    s.add_argument("--max-rungs", type=int, default=4)
    s.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="partial-batch flush deadline assumed by the "
                        "replay (match the Engine's MXNET_SERVE_MAX_WAIT_MS)")
    s.add_argument("--sample-shape", action="append", metavar="NAME:D1,D2",
                   help="store the ladder winner under these declared "
                        "per-sample shapes (repeatable; loadgen --shapes "
                        "syntax) instead of the trace's elementwise-max "
                        "shapes — required when the serving Engine "
                        "declares larger sample_shapes than the recorded "
                        "traffic ever reached, or its lookup would miss")
    s.set_defaults(fn=_search_cmd)

    sh = sub.add_parser("show", help="list persisted winners")
    sh.add_argument("--features", action="store_true",
                    help="also print each winner's persisted cost features "
                         "and trial-row counts (the model's training set)")
    sh.set_defaults(fn=_show)

    c = sub.add_parser("clear", help="drop persisted winners")
    c.add_argument("--kernel", default=None,
                   help="only this kernel's entries (default: everything)")
    c.set_defaults(fn=_clear)

    args = p.parse_args(argv)
    if args.cmd == "search" and not args.trace and not args.kernel \
            and not args.all_kernels:
        p.error("search needs --kernel <space>, --all-kernels, or "
                "--trace <jsonl>")
    if args.cmd == "search" and args.all_kernels and (args.kernel
                                                     or args.trace):
        p.error("--all-kernels replaces --kernel/--trace")
    if args.cmd == "search" and args.kernel is not None:
        # validate against the live registry, not a frozen list: a newly
        # registered space is rejected only until it gains a measurement
        # runner below
        from mxnet_tpu import autotune

        registered = sorted(autotune.spaces())
        if args.kernel not in registered:
            p.error("unknown kernel %r (registered: %s)"
                    % (args.kernel, ", ".join(registered)))
        if args.kernel not in _KERNEL_RUNNERS:
            p.error("no measurement runner for kernel %r yet (runnable: %s)"
                    % (args.kernel, ", ".join(sorted(_KERNEL_RUNNERS))))
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
