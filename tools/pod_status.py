#!/usr/bin/env python
"""Pod observability CLI — render ``/podz`` and correlate incident dumps
(ISSUE 19).

Two surfaces over the pod plane (``mxnet_tpu/telemetry/podplane.py``):

* **status** — fetch rank 0's ``/podz`` ops endpoint (stdlib urllib, no
  deps) and render the per-rank table, fleet rollup, ledger divergences,
  and incident history as aligned text::

      python tools/pod_status.py http://127.0.0.1:9100
      python tools/pod_status.py http://127.0.0.1:9100 --json   # raw block

* **collect** — walk one flight-recorder directory per rank, group the
  ``pod_incident``-tagged dumps by their shared incident id, and merge
  each group onto ONE unix-epoch timeline via the existing
  ``trace_merge`` clock-sync machinery (each dump embeds a ``clock_sync``
  record plus its rank), so the 3 a.m. question "what was every rank
  doing when incident X fired" is one Perfetto load::

      python tools/pod_status.py --collect rank0/frec rank1/frec -o out/

  writes ``out/<incident-id>.json`` per incident (plus a listing of
  un-correlated ``pod_*`` dumps such as rank 0's ledger-divergence
  detail dump, which carries the key and both ranks in its metadata).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import trace_merge  # noqa: E402  (sibling tool, no package)


def fetch_podz(url, timeout_s=5.0):
    """GET <url>/podz → the parsed JSON block."""
    url = url.rstrip("/")
    if not url.endswith("/podz"):
        url += "/podz"
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return json.loads(r.read().decode("utf-8"))


# -- rendering ----------------------------------------------------------------
_RANK_COLS = (
    ("rank", lambda rk, st: rk),
    ("steps", lambda rk, st: st.get("steps")),
    ("lag", lambda rk, st: st.get("lag")),
    ("push_age_s", lambda rk, st: st.get("push_age_s")),
    ("p50_ms", lambda rk, st: st.get("step_p50_ms")),
    ("p99_ms", lambda rk, st: st.get("step_p99_ms")),
    ("healthz", lambda rk, st: {True: "ok", False: "FAIL", None: "-"}
     [st.get("healthz_ok")]),
    ("hb_age_s", lambda rk, st: st.get("heartbeat_age_s")),
    ("frec", lambda rk, st: "arm" if st.get("flightrec") else "-"),
    ("ledger", lambda rk, st: st.get("ledger_keys")),
    ("slo", lambda rk, st: st.get("slo_breaches")),
    ("nonfin", lambda rk, st: st.get("nonfinite")),
    ("verdict", lambda rk, st: ("DEAD" if st.get("dead")
                                else "straggler" if st.get("straggler")
                                else "ok")),
)


def _cell(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return "%.3g" % v
    return str(v)


def render_podz(pz):
    """The /podz block → aligned multi-line text (pure; tested)."""
    if not pz.get("enabled"):
        return "pod plane disabled (MXNET_POD_METRICS unset)"
    lines = []
    if pz.get("role") == "pusher":
        push = pz.get("push") or {}
        lines.append("pod pusher rank %s/%s -> %s"
                     % (pz.get("rank"), pz.get("size"),
                        pz.get("aggregator") or "(no channel)"))
        lines.append("  pushed seq=%s steps=%s failures=%s connected=%s"
                     % (push.get("seq"), push.get("steps"),
                        push.get("push_failures"), push.get("connected")))
        return "\n".join(lines)
    lines.append("pod aggregator: %s/%s ranks reporting"
                 % (pz.get("ranks_reporting"), pz.get("size")))
    rows = [[_cell(fn(rk, st)) for _, fn in _RANK_COLS]
            for rk, st in sorted((pz.get("ranks") or {}).items(),
                                 key=lambda kv: int(kv[0]))]
    headers = [name for name, _ in _RANK_COLS]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    lines.append("  " + "  ".join(h.ljust(w)
                                  for h, w in zip(headers, widths)))
    for r in rows:
        lines.append("  " + "  ".join(c.ljust(w)
                                      for c, w in zip(r, widths)))
    fleet = pz.get("fleet") or {}
    lines.append("fleet: p50=%sms p99=%sms steps=[%s..%s] max_lag=%s"
                 % (_cell(fleet.get("step_p50_ms")),
                    _cell(fleet.get("step_p99_ms")),
                    _cell(fleet.get("steps_min")),
                    _cell(fleet.get("steps_max")),
                    _cell(fleet.get("max_step_lag"))))
    div = pz.get("ledger_divergences") or {}
    lines.append("ledger divergences: %d (stale snapshots dropped: %s, "
                 "straggler verdicts: %s)"
                 % (len(div), pz.get("stale_dropped"),
                    pz.get("straggler_verdicts")))
    for key, detail in sorted(div.items()):
        lines.append("  key %s ranks %s: %s"
                     % (key, detail.get("ranks"),
                        detail.get("fingerprints")))
    skew = (pz.get("skew") or {}).get("compile_s") or {}
    if skew:
        lines.append("compile_s skew (max-min across ranks, top %d):"
                     % len(skew))
        for key, s in skew.items():
            lines.append("  %s: %ss" % (key, _cell(s)))
    incs = pz.get("incidents") or []
    lines.append("incidents: %d" % len(incs))
    for inc in incs:
        lines.append("  %s reason=%s rank=%s %s"
                     % (inc.get("id"), inc.get("reason"), inc.get("rank"),
                        inc.get("meta") or ""))
    return "\n".join(lines)


# -- incident-dump collection -------------------------------------------------
def scan_incident_dumps(dirs):
    """Walk flight-recorder dirs → ({incident_id: [(path, rank)]},
    [other pod_* dump paths]).  The incident id and the observing rank
    live in the dump's ``flightrec`` metadata
    (``PodPlane._observe_incidents`` tags both — a single-host pod run
    has no jax rank in ``clock_sync``, so the observer rank is what
    keeps per-rank tracks separable in the merge)."""
    by_incident, loose = {}, []
    for d in dirs:
        for path in sorted(glob.glob(os.path.join(d, "flightrec-*.json"))):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    meta = (json.load(f).get("flightrec") or {})
            except (OSError, ValueError):
                continue
            iid = meta.get("incident")
            reason = str(meta.get("reason") or "")
            if iid:
                by_incident.setdefault(str(iid), []).append(
                    (path, meta.get("observer_rank")))
            elif reason.startswith("pod_"):
                loose.append(path)
    return by_incident, loose


def collect(dirs, outdir):
    """Merge each incident's per-rank dumps onto one timeline →
    ``outdir/<incident-id>.json`` via trace_merge (clock_sync rebase +
    rank-labeled track groups).  → exit code."""
    by_incident, loose = scan_incident_dumps(dirs)
    if not by_incident and not loose:
        print("no pod incident dumps under: %s" % ", ".join(dirs))
        return 1
    os.makedirs(outdir, exist_ok=True)
    rc = 0
    for iid, entries in sorted(by_incident.items()):
        out = os.path.join(outdir, "%s.json" % iid.replace("/", "_"))
        paths = [p for p, _ in entries]
        print("incident %s: %d dump(s)" % (iid, len(paths)))
        argv = paths + ["-o", out]
        if all(r is not None for _, r in entries):
            # trace_merge --rank flags are positional per file: only
            # usable when every dump in the group knows its observer
            for _, r in entries:
                argv += ["--rank", str(int(r))]
        code = trace_merge.main(argv)
        rc = rc or code
    for path in loose:
        print("related (no incident id): %s" % path)
    return rc


def main(argv=None):
    p = argparse.ArgumentParser(
        description="render /podz and correlate pod incident dumps")
    p.add_argument("url", nargs="?",
                   help="ops-server base URL (e.g. http://host:9100) — "
                        "renders /podz")
    p.add_argument("--json", action="store_true",
                   help="print the raw /podz JSON instead of the table")
    p.add_argument("--collect", nargs="+", metavar="DIR",
                   help="flight-recorder dirs (one per rank) — group "
                        "incident-tagged dumps and merge per incident")
    p.add_argument("-o", "--output", default="pod_incidents",
                   help="output directory for --collect merges")
    args = p.parse_args(argv)
    if args.collect:
        return collect(args.collect, args.output)
    if not args.url:
        p.error("need an ops-server URL or --collect DIR...")
    try:
        pz = fetch_podz(args.url)
    except (OSError, ValueError) as e:
        print("pod_status: cannot fetch %s: %s" % (args.url, e),
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(pz, indent=1, default=str))
    else:
        print(render_podz(pz))
    return 0


if __name__ == "__main__":
    sys.exit(main())
