#!/usr/bin/env python
"""mxlint — JAX-hazard source lint CLI (ISSUE 8; docs/ANALYSIS.md).

Runs ``mxnet_tpu.analysis.source_lint`` over the codebase and diffs the
findings against the committed baseline:

    python tools/mxlint.py                      # lint mxnet_tpu/ vs baseline
    python tools/mxlint.py path/to/file.py      # lint specific paths
    python tools/mxlint.py --no-baseline        # raw findings, no suppression
    python tools/mxlint.py --write-baseline     # accept current findings
    python tools/mxlint.py --list-rules         # rule table

Exit status: 0 = no findings outside the baseline, 1 = new findings (each
printed with its fingerprint, ready to fix or baseline WITH a
justification), 2 = usage error.  Stale baseline entries (matching nothing)
are reported but never fail the run and never auto-pruned — deleting a
justified suppression is a reviewed change, not a side effect.

CI runs this via ``ci/check_lint.py`` in the unit tier.
"""
from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

DEFAULT_BASELINE = os.path.join(_REPO, "ci", "mxlint_baseline.txt")


def _write_baseline(findings, path):
    """Rewrite the baseline as the current finding set, preserving the
    justification comment of every fingerprint already listed; new entries
    get a TODO the reviewer must replace."""
    just = {}
    if os.path.exists(path):
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                if "  #" in line and not line.lstrip().startswith("#"):
                    fp, comment = line.split("  #", 1)
                    just[fp.strip()] = comment.strip()
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# mxlint baseline — justified legacy findings "
                 "(docs/ANALYSIS.md).\n#\n"
                 "# One fingerprint per line; '  # ...' is the "
                 "justification (required).\n"
                 "# Regenerate with: python tools/mxlint.py "
                 "--write-baseline\n\n")
        for f in findings:
            fh.write("%s  # %s\n" % (
                f.fingerprint,
                just.get(f.fingerprint, "TODO: justify or fix")))


def main(argv=None):
    from mxnet_tpu.analysis import source_lint

    ap = argparse.ArgumentParser(prog="mxlint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: mxnet_tpu/)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, suppress nothing")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current finding set into --baseline")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="rewrite --baseline dropping STALE entries "
                         "(fingerprints matching no current finding); "
                         "kept entries and their justifications are "
                         "untouched — the reviewed alternative to "
                         "hand-editing the file")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in source_lint.RULES:
            print(r)
        return 0

    paths = args.paths or [os.path.join(_REPO, "mxnet_tpu")]
    findings = source_lint.lint_paths(paths, root=_REPO)

    if args.write_baseline:
        _write_baseline(findings, args.baseline)
        print("mxlint: wrote %d entr%s to %s" % (
            len(findings), "y" if len(findings) == 1 else "ies",
            os.path.relpath(args.baseline, _REPO)))
        return 0

    if args.prune_baseline:
        if args.paths and args.baseline == DEFAULT_BASELINE:
            # stale = "matches no current finding": a partial lint makes
            # every out-of-scope entry in the SHARED repo baseline look
            # stale, and pruning would destroy its justifications — prune
            # the default baseline only from a full default-root lint
            # (an explicit --baseline scoped to these paths is fine)
            print("mxlint: refusing --prune-baseline of the repo "
                  "baseline from a partial lint (explicit paths given); "
                  "run without path arguments, or point --baseline at a "
                  "file scoped to them", file=sys.stderr)
            return 2
        baseline = source_lint.load_baseline(args.baseline)
        _, _, stale = source_lint.split_baseline(findings, baseline)
        if not stale:
            print("mxlint: baseline has no stale entries")
            return 0
        # drop only the stale fingerprint lines; headers, comments and
        # every live entry (justification included) pass through verbatim
        stale = set(stale)
        with open(args.baseline, "r", encoding="utf-8") as fh:
            lines = fh.readlines()
        kept = [ln for ln in lines
                if ln.split("  #", 1)[0].strip() not in stale]
        with open(args.baseline, "w", encoding="utf-8") as fh:
            fh.writelines(kept)
        for fp in sorted(stale):
            print("mxlint: pruned stale entry %s" % fp)
        print("mxlint: pruned %d stale entr%s from %s" % (
            len(stale), "y" if len(stale) == 1 else "ies",
            os.path.relpath(args.baseline, _REPO)))
        return 0

    baseline = set() if args.no_baseline \
        else source_lint.load_baseline(args.baseline)
    new, suppressed, stale = source_lint.split_baseline(findings, baseline)

    for f in new:
        print(f)
        print("    fingerprint: %s" % f.fingerprint)
    for fp in stale:
        print("mxlint: stale baseline entry (matches nothing — consider "
              "removing): %s" % fp)
    print("mxlint: %d finding%s (%d baselined, %d new)" % (
        len(findings), "" if len(findings) == 1 else "s",
        len(suppressed), len(new)))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
