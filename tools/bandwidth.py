#!/usr/bin/env python
"""Measure allreduce (KVStore push+pull) bandwidth over the device mesh.

The reference ships ``tools/bandwidth/measure.py``: it binds a network's
weight-shaped arrays, runs kvstore push+pull in a loop, and reports per-GPU
bandwidth for a given kvstore type.  The TPU-native equivalent measures the
XLA collective that KVStore lowers to — a ``psum`` over the ICI mesh inside
one jitted module — which is the "KVStore allreduce BW" north-star metric in
BASELINE.md.

Algorithmic bandwidth is reported the standard allreduce way:
``2 * (n-1)/n * bytes / time`` per chip (ring lower bound), plus the naive
``bytes/time`` rate.  On a single chip the collective is the identity; the
tool then reports device-copy bandwidth and says so.

Usage::

    python tools/bandwidth.py [--sizes 1M,16M,64M] [--iters 20] [--dtype float32]

Runs on whatever devices are visible: the real TPU chip(s), or a virtual
8-device CPU mesh under ``./dev.sh``.
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _parse_size(s):
    s = s.strip().upper()
    mult = 1
    if s.endswith("K"):
        mult, s = 1 << 10, s[:-1]
    elif s.endswith("M"):
        mult, s = 1 << 20, s[:-1]
    elif s.endswith("G"):
        mult, s = 1 << 30, s[:-1]
    return int(float(s) * mult)


def measure(sizes, iters=20, dtype="float32", warmup=3):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from mxnet_tpu.parallel.shard_map_compat import shard_map
    except ImportError:  # standalone use outside the repo
        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map

    devs = np.array(jax.devices())
    n = len(devs)
    mesh = Mesh(devs, ("dp",))
    repl = NamedSharding(mesh, P())
    itemsize = jnp.dtype(dtype).itemsize

    results = []
    for size in sizes:
        elems = max(n, size // itemsize // n * n)  # divisible by mesh
        x_host = np.ones((elems,), dtype=dtype)
        # replicated operand: every chip contributes a FULL gradient copy,
        # exactly what kv.push of a per-device gradient does (kvstore.py →
        # parallel/collectives.py); nbytes below is the per-rank message size
        x = jax.device_put(x_host, repl)
        if n > 1:
            f = jax.jit(shard_map(
                lambda v: jax.lax.psum(v, "dp"),
                mesh=mesh, in_specs=P(), out_specs=P()))
        else:
            # single chip: collective is the identity; time a device round
            # trip instead so the tool still reports a number
            f = jax.jit(lambda v: v + 0)
        out = f(x)
        jax.block_until_ready(out)
        for _ in range(warmup):
            out = f(x)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        nbytes = elems * itemsize
        algo_bw = (2 * (n - 1) / max(n, 1)) * nbytes / dt if n > 1 else nbytes / dt
        results.append({
            "size_bytes": nbytes,
            "n_devices": n,
            "avg_time_ms": round(dt * 1e3, 4),
            "busbw_GBps": round(algo_bw / 1e9, 3),
            "algbw_GBps": round(nbytes / dt / 1e9, 3),
            "collective": "psum" if n > 1 else "copy (single device)",
        })
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--sizes", default="1M,16M,64M",
                   help="comma list of payload sizes (K/M/G suffixes)")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--dtype", default="float32",
                   help="float32 | bfloat16 (2-bit-compression analog: "
                        "halve bytes on the wire, reference "
                        "gradient_compression.h)")
    args = p.parse_args(argv)
    sizes = [_parse_size(s) for s in args.sizes.split(",")]
    for r in measure(sizes, iters=args.iters, dtype=args.dtype):
        print(json.dumps(r))


if __name__ == "__main__":
    main()
