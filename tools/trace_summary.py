#!/usr/bin/env python
"""Per-op device-time + roofline summary from a profiler dump.

Merges three sources into one table (ISSUE 1 — restores the roofline
accounting XLA cost analysis loses for Pallas custom calls):

1. a chrome-trace JSON dump (``mx.profiler.dump()`` output, or a
   trace-viewer export; ``.json`` or ``.json.gz``) — per-op wall time from
   its "X" duration events, aggregated by name;
2. the custom-call cost registry — either embedded in the dump itself (the
   profiler inserts a ``custom_call_costs`` metadata event when the Pallas
   module is loaded), read from a telemetry JSONL event log or a plain
   ``{name: {flops, bytes_accessed}}`` JSON via ``--costs``, or pulled live
   from ``mxnet_tpu.ops.pallas_kernels`` with ``--live-registry``;
3. optionally whole-module XLA flops/bytes context — preferably from a
   compile-plane **cost ledger** (``--ledger``, the ``MXNET_COST_LEDGER``
   JSONL the library writes per compiled executable under
   ``MXNET_COSTPLANE=1``; ISSUE 13 — totals are summed over the last row
   per executable key, no hand-saving required), or from a hand-saved
   cost-analysis JSON (``--xla-cost``, the dict from
   ``jitted.lower(...).compile().cost_analysis()`` saved with json.dump).

Ops are matched to registered costs by case-insensitive substring (both
directions, plus each registry entry's aliases).  Registered custom calls
with no matching trace event still get a row (time "-") so declared costs
are always visible — a registered kernel can never be invisible again.

Usage::

    python tools/trace_summary.py profile.json
    python tools/trace_summary.py profile.json --ledger cost_ledger.jsonl
    python tools/trace_summary.py profile.json --costs telemetry.jsonl \
        --peak-flops 197e12 --peak-bw 819e9 --top 20
    python tools/trace_summary.py profile.json --json   # machine-readable
    python tools/trace_summary.py rank0.json rank1.json --per-rank

**Per-rank inputs (ISSUE 12).**  A pod run produces one trace/flight dump
per process; pass them all — each file's rank is detected like
``tools/trace_merge.py`` does (``clock_sync`` args, per-event
``args.rank``, or a ``rank<N>`` filename token) and the op table merges
every rank's events into one accounting.  ``--per-rank`` keeps the ranks
apart instead (rows prefixed ``r<k>/``), which is how a straggler shows
up as one rank's ops running long.

Roofline: intensity = flops/bytes (declared), attainable = min(peak_flops,
intensity * peak_bw); %roof compares achieved FLOP/s (or B/s for zero-flop
ops) against it.  Defaults are one TPU v5e chip: 197 TFLOP/s bf16,
819 GB/s HBM (docs/PERF_NOTES.md).
"""
from __future__ import annotations

import argparse
import gzip
import json
import sys


def load_trace(path):
    """Chrome-trace JSON (optionally gzipped) → list of event dicts."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        return data.get("traceEvents", [])
    return data  # bare event-array form is also legal chrome-trace


def trace_rank(path, events):
    """The rank a per-rank trace belongs to, or None — THE
    ``trace_merge.file_rank`` detection (one implementation, one pod
    workflow: clock_sync args, unanimous event args.rank, filename
    token)."""
    import os

    try:
        import trace_merge
    except ImportError:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import trace_merge
    return trace_merge.file_rank(path, events)


def aggregate_ops(events, ops=None, prefix=""):
    """"X" duration events → {name: {"calls", "total_us"}} — pass ``ops``
    to accumulate several (per-rank) files into one table; ``prefix``
    keys rows per rank for --per-rank mode."""
    ops = {} if ops is None else ops
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        ent = ops.setdefault(prefix + ev.get("name", "?"),
                             {"calls": 0, "total_us": 0.0})
        ent["calls"] += 1
        ent["total_us"] += float(ev["dur"])
    return ops


def _norm_cost(ent):
    return {"flops": int(ent.get("flops", 0)),
            "bytes_accessed": int(ent.get("bytes_accessed", ent.get("bytes", 0))),
            "shape": ent.get("shape")}


def costs_from_trace(events):
    """The profiler-embedded ``custom_call_costs`` metadata event."""
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "custom_call_costs":
            return {k: _norm_cost(v) for k, v in (ev.get("args") or {}).items()}
    return {}


def costs_from_file(path):
    """--costs: telemetry JSONL (custom_call_cost events) or a plain
    {name: {flops, bytes_accessed}} JSON object."""
    with open(path, encoding="utf-8") as f:
        text = f.read().strip()
    if not text:
        return {}
    try:
        obj = json.loads(text)
        # a plain mapping {name: {flops, ...}} — but a single telemetry
        # event line is ALSO one valid JSON object, so require cost-shaped
        # values before treating the whole file as a mapping
        if (isinstance(obj, dict) and "traceEvents" not in obj
                and "kind" not in obj
                and all(isinstance(v, dict) for v in obj.values())):
            return {k: _norm_cost(v) for k, v in obj.items()}
    except json.JSONDecodeError:
        pass
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue
        if ev.get("kind") == "custom_call_cost" and "name" in ev:
            out[ev["name"]] = _norm_cost(ev)
    return out


def _import_bench_compare():
    import os

    try:
        import bench_compare
    except ImportError:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import bench_compare
    return bench_compare


def ledger_totals(path):
    """Whole-module XLA totals from a compile-plane cost ledger (ISSUE 13):
    {"flops", "bytes_accessed", "peak_bytes", "rows", "partial_rows"}.
    Parsing (LAST row per executable key wins — a recompiled key
    supersedes its earlier rows) is ``bench_compare.load_ledger_file``,
    the one tool-side definition of a valid ledger row; keys whose
    backend reported nothing contribute null-safely and are counted in
    ``partial_rows``."""
    rows = _import_bench_compare().load_ledger_file(path)
    fl = [r["flops"] for r in rows.values() if r.get("flops") is not None]
    by = [r["bytes_accessed"] for r in rows.values()
          if r.get("bytes_accessed") is not None]
    pk = [r["peak_bytes"] for r in rows.values()
          if r.get("peak_bytes") is not None]
    return {"flops": sum(fl) if fl else None,
            "bytes_accessed": sum(by) if by else None,
            "peak_bytes": max(pk) if pk else None,
            "rows": len(rows),
            "partial_rows": sum(1 for r in rows.values() if r.get("partial"))}


def _import_pallas_kernels():
    """Import the kernel module whether invoked as `python tools/…` (script
    dir on sys.path, repo root not) or from an installed checkout."""
    import os

    try:
        from mxnet_tpu.ops import pallas_kernels as pk
    except ImportError:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from mxnet_tpu.ops import pallas_kernels as pk
    return pk


def costs_live():
    pk = _import_pallas_kernels()
    return {k: _norm_cost(v) for k, v in pk.traced_costs().items()}


def registry_aliases():
    try:
        return _import_pallas_kernels().registered_custom_calls()
    except Exception:
        return {}


def match_cost(op_name, costs, aliases):
    """Case-insensitive substring match, both directions + aliases.

    Exact name wins outright; otherwise the LONGEST matching name/alias wins
    — dict order must not let "quantize_int8" claim a dequantize op, or a
    forward alias claim the backward kernel."""
    if op_name in costs:
        return op_name, costs[op_name]
    low = op_name.lower()
    best_name, best_score = None, 0
    for name in sorted(costs):
        cands = [name.lower()] + [a.lower() for a in aliases.get(name, ())]
        score = max((len(c) for c in cands if c in low or low in c),
                    default=0)
        if score > best_score:
            best_name, best_score = name, score
    if best_name is None:
        return None, None
    return best_name, costs[best_name]


def summarize(ops, costs, aliases, peak_flops, peak_bw):
    """→ list of row dicts sorted by total time desc, cost-only rows last."""
    rows, matched = [], set()
    for op, ent in ops.items():
        cname, cost = match_cost(op, costs, aliases)
        row = {"op": op, "calls": ent["calls"],
               "total_ms": ent["total_us"] / 1e3,
               "avg_us": ent["total_us"] / max(ent["calls"], 1),
               "flops": None, "bytes": None, "gflops_s": None, "gb_s": None,
               "intensity": None, "bound": None, "pct_roof": None,
               "cost_name": cname}
        if cost is not None:
            matched.add(cname)
            fl = cost["flops"] * ent["calls"]
            by = cost["bytes_accessed"] * ent["calls"]
            row["flops"], row["bytes"] = fl, by
            secs = ent["total_us"] / 1e6
            if secs > 0:
                row["gflops_s"] = fl / secs / 1e9
                row["gb_s"] = by / secs / 1e9
            if by > 0:
                inten = fl / by
                row["intensity"] = inten
                row["bound"] = ("compute" if inten > peak_flops / peak_bw
                                else "memory")
                attain = min(peak_flops, inten * peak_bw)
                if secs > 0:
                    # zero-flop ops: rate their achieved bandwidth instead
                    row["pct_roof"] = (100.0 * (fl / secs) / attain if fl
                                       else 100.0 * (by / secs) / peak_bw)
        rows.append(row)
    rows.sort(key=lambda r: -r["total_ms"])
    # registered costs with no device-time row: keep them visible
    for name, cost in sorted(costs.items()):
        if name in matched:
            continue
        inten = (cost["flops"] / cost["bytes_accessed"]
                 if cost["bytes_accessed"] else None)
        rows.append({"op": name, "calls": None, "total_ms": None,
                     "avg_us": None, "flops": cost["flops"],
                     "bytes": cost["bytes_accessed"], "gflops_s": None,
                     "gb_s": None, "intensity": inten,
                     "bound": (None if inten is None else
                               ("compute" if inten > peak_flops / peak_bw
                                else "memory")),
                     "pct_roof": None, "cost_name": name})
    return rows


def _fmt(v, spec="%.1f", dash="-"):
    return dash if v is None else spec % v


def render_table(rows, top=0):
    cols = ["op", "calls", "total_ms", "avg_us", "GFLOP", "MB",
            "GFLOP/s", "GB/s", "intens", "bound", "%roof"]
    table = [cols]
    shown = rows[:top] if top else rows
    for r in shown:
        table.append([
            r["op"][:48],
            _fmt(r["calls"], "%d"),
            _fmt(r["total_ms"], "%.3f"),
            _fmt(r["avg_us"], "%.1f"),
            _fmt(None if r["flops"] is None else r["flops"] / 1e9, "%.3f"),
            _fmt(None if r["bytes"] is None else r["bytes"] / 1e6, "%.2f"),
            _fmt(r["gflops_s"], "%.1f"),
            _fmt(r["gb_s"], "%.2f"),
            _fmt(r["intensity"], "%.2f"),
            r["bound"] or "-",
            _fmt(r["pct_roof"], "%.1f"),
        ])
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    lines = []
    for i, row in enumerate(table):
        lines.append("  ".join(
            c.ljust(widths[j]) if j == 0 else c.rjust(widths[j])
            for j, c in enumerate(row)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="per-op device-time + roofline table from a trace dump")
    p.add_argument("trace", nargs="+",
                   help="chrome-trace JSON (.json or .json.gz); several "
                        "per-rank files merge into one table")
    p.add_argument("--per-rank", action="store_true",
                   help="keep per-rank files apart (rows prefixed r<k>/) "
                        "instead of merging the ranks' events")
    p.add_argument("--costs", action="append", default=[],
                   help="cost table: telemetry JSONL or {name: {flops, "
                        "bytes_accessed}} JSON (repeatable)")
    p.add_argument("--xla-cost", default=None,
                   help="saved compile().cost_analysis() JSON for module-"
                        "level totals")
    p.add_argument("--ledger", default=None,
                   help="MXNET_COST_LEDGER JSONL (compile plane, ISSUE 13) "
                        "for module-level totals — supersedes --xla-cost, "
                        "no hand-saved cost JSON needed")
    p.add_argument("--live-registry", action="store_true",
                   help="also pull traced costs from the in-process Pallas "
                        "registry (imports jax)")
    p.add_argument("--peak-flops", type=float, default=197e12,
                   help="roofline compute peak, FLOP/s (default v5e bf16)")
    p.add_argument("--peak-bw", type=float, default=819e9,
                   help="roofline HBM peak, B/s (default v5e)")
    p.add_argument("--top", type=int, default=30,
                   help="show only the top-N ops by total time (0 = all)")
    p.add_argument("--json", action="store_true",
                   help="emit machine-readable JSON instead of the table")
    args = p.parse_args(argv)

    ops, costs, ranks = {}, {}, []
    for path in args.trace:
        try:
            events = load_trace(path)
        except (OSError, json.JSONDecodeError) as e:
            print("trace_summary: cannot read %s: %s" % (path, e),
                  file=sys.stderr)
            return 2
        rank = trace_rank(path, events)
        ranks.append(rank)
        prefix = ("r%d/" % rank) if args.per_rank and rank is not None \
            else ""
        aggregate_ops(events, ops=ops, prefix=prefix)
        costs.update(costs_from_trace(events))
    for path in args.costs:
        costs.update(costs_from_file(path))
    if args.live_registry:
        costs.update(costs_live())
    rows = summarize(ops, costs, registry_aliases(), args.peak_flops,
                     args.peak_bw)

    xla_totals = ledger_rows = None
    if args.ledger:
        try:
            lt = ledger_totals(args.ledger)
        except OSError as e:
            print("trace_summary: cannot read %s: %s" % (args.ledger, e),
                  file=sys.stderr)
            return 2
        xla_totals = {"flops": lt["flops"],
                      "bytes_accessed": lt["bytes_accessed"]}
        ledger_rows = lt
    elif args.xla_cost:
        with open(args.xla_cost, encoding="utf-8") as f:
            ca = json.load(f)
        xla_totals = {"flops": ca.get("flops"),
                      "bytes_accessed": ca.get("bytes accessed",
                                               ca.get("bytes_accessed"))}

    if args.json:
        print(json.dumps({"rows": rows, "xla_totals": xla_totals,
                          "ledger": ledger_rows,
                          "peak_flops": args.peak_flops,
                          "peak_bw": args.peak_bw,
                          "ranks": ranks}, indent=1))
        return 0

    total_ms = sum(r["total_ms"] or 0.0 for r in rows)
    print(render_table(rows, args.top))
    seen = sorted({r for r in ranks if r is not None})
    print("\n%d ops, %.3f ms total traced time; %d registered custom "
          "call(s)%s"
          % (sum(1 for r in rows if r["total_ms"] is not None), total_ms,
             len(costs),
             "" if not seen else "; ranks %s over %d file(s)"
             % (",".join(map(str, seen)), len(args.trace))))
    if ledger_rows is not None:
        print("cost ledger: %d executable(s), %d partial row(s)%s"
              % (ledger_rows["rows"], ledger_rows["partial_rows"],
                 "" if ledger_rows["peak_bytes"] is None else
                 "; peak executable %.1f MB"
                 % (ledger_rows["peak_bytes"] / 1e6)))
    if xla_totals and xla_totals["flops"] is not None:
        reg_fl = sum(r["flops"] or 0 for r in rows)
        print("XLA cost analysis: %.3f GFLOP module total; registered custom "
              "calls add %.3f GFLOP the analysis cannot see"
              % (xla_totals["flops"] / 1e9, reg_fl / 1e9))
    ridge = args.peak_flops / args.peak_bw
    print("roofline: peak %.1f TFLOP/s, %.1f GB/s, ridge intensity %.1f "
          "FLOP/B" % (args.peak_flops / 1e12, args.peak_bw / 1e9, ridge))
    return 0


if __name__ == "__main__":
    sys.exit(main())
