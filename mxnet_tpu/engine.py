"""Engine facade — execution-mode control over XLA async dispatch.

TPU-native stand-in for the reference dependency engine's user-visible knobs
(reference ``src/engine/engine.cc:32-44`` factory selected by
``MXNET_ENGINE_TYPE``; bulk mode ``src/engine/threaded_engine.h:410``).

There is no threaded scheduler to configure here: JAX's async dispatch + the
XLA latency-hiding scheduler play that role (SURVEY §7.1).  What remains
meaningful:

- ``NaiveEngine`` ≡ synchronous, un-jitted execution for debugging — mapped
  to ``jax.disable_jit()`` so every op runs eagerly with usable tracebacks
  (reference ``docs/faq/env_var.md:52-56``).
- ``wait_all`` / ``wait_to_read`` block on outstanding device work
  (reference ``Engine::WaitForAll`` / ``WaitForVar``,
  ``src/engine/threaded_engine.cc:367``).
- bulk mode (op fusion across engine pushes) is what ``jax.jit`` does by
  construction; ``set_bulk_size`` is accepted and recorded for API parity.
"""
from __future__ import annotations

import contextlib
import os

__all__ = [
    "engine_type",
    "set_bulk_size",
    "bulk",
    "wait_all",
    "naive_engine",
    "is_naive",
]

_BULK_SIZE = int(os.environ.get("MXNET_EXECUTOR_BULK_EXEC_MAX_NODE_TRAIN", 15))
_NAIVE = os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"
_naive_cm = None


def engine_type():
    """Current engine flavour: 'ThreadedEnginePerDevice' (async XLA dispatch)
    or 'NaiveEngine' (sync, jit disabled)."""
    return "NaiveEngine" if _NAIVE else "ThreadedEnginePerDevice"


def is_naive():
    return _NAIVE


def naive_engine(enable=True):
    """Switch synchronous debug mode on/off at runtime.

    Enabling enters ``jax.disable_jit()`` globally so compiled callables run
    op-by-op; the reference gets the same effect by exporting
    ``MXNET_ENGINE_TYPE=NaiveEngine`` before startup.
    """
    global _NAIVE, _naive_cm
    import jax

    if enable and not _NAIVE:
        _naive_cm = jax.disable_jit()
        _naive_cm.__enter__()
        _NAIVE = True
    elif not enable and _NAIVE:
        if _naive_cm is not None:
            _naive_cm.__exit__(None, None, None)
            _naive_cm = None
        _NAIVE = False


def set_bulk_size(size):
    """Set max ops per bulk segment; returns the previous value.

    XLA fuses whole jitted programs regardless, so this is a recorded
    preference, not a scheduler knob (reference
    ``MXEngineSetBulkSize`` / ``BulkStatus`` threaded_engine.h:410).
    """
    global _BULK_SIZE
    old, _BULK_SIZE = _BULK_SIZE, int(size)
    return old


@contextlib.contextmanager
def bulk(size):
    """Scoped bulk-size override (reference ``mx.engine.bulk``)."""
    old = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(old)


def wait_all():
    """Block until all outstanding device computation finishes
    (reference ``Engine::WaitForAll``)."""
    from .ndarray.ndarray import waitall

    waitall()
