"""Evaluation metrics — reference ``python/mxnet/metric.py`` (1,302 LoC;
EvalMetric base :68, Accuracy :363, TopK, F1, MCC, Perplexity, MAE/MSE/RMSE,
CrossEntropy, NLL, PearsonCorrelation, Loss, CompositeEvalMetric, custom np).
"""
from __future__ import annotations

import math

import numpy

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = [
    "EvalMetric",
    "CompositeEvalMetric",
    "Accuracy",
    "TopKAccuracy",
    "F1",
    "MCC",
    "Perplexity",
    "MAE",
    "MSE",
    "RMSE",
    "CrossEntropy",
    "NegativeLogLikelihood",
    "PearsonCorrelation",
    "Loss",
    "CustomMetric",
    "np",
    "create",
]

_METRIC_REGISTRY = {}


def register(klass, *names):
    for n in names or (klass.__name__.lower(),):
        _METRIC_REGISTRY[n] = klass
    return klass


def create(metric, *args, **kwargs):
    """Create by name/callable/list (reference metric.py create)."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    if metric.lower() not in _METRIC_REGISTRY:
        raise MXNetError("Metric %s not registered (have %s)" % (metric, sorted(_METRIC_REGISTRY)))
    return _METRIC_REGISTRY[metric.lower()](*args, **kwargs)


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else numpy.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if (hasattr(labels, "__len__") and hasattr(preds, "__len__")) and len(labels) != len(preds):
        raise ValueError(
            "Shape of labels %d does not match shape of predictions %d" % (len(labels), len(preds))
        )


class EvalMetric:
    """Base metric (reference metric.py:68)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = dict(self._kwargs)
        config.update(
            {"metric": self.__class__.__name__, "name": self.name, "output_names": self.output_names, "label_names": self.label_names}
        )
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics (reference metric.py CompositeEvalMetric)."""

    def __init__(self, metrics=None, name="composite", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.extend(name if isinstance(name, list) else [name])
            values.extend(value if isinstance(value, list) else [value])
        return (names, values)


@register
class Accuracy(EvalMetric):
    """Classification accuracy (reference metric.py:363)."""

    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if pred.ndim > label.ndim:
                pred = numpy.argmax(pred, axis=self.axis)
            pred = pred.astype(numpy.int32).flatten()
            label = label.astype(numpy.int32).flatten()
            check_label_shapes(label, pred)
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(pred)


@register
class TopKAccuracy(EvalMetric):
    """Top-k accuracy (reference metric.py TopKAccuracy)."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert top_k > 1, "Use Accuracy for top_k=1"
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_np(label).astype(numpy.int32)
            pred = _as_np(pred)
            assert pred.ndim == 2
            topk_idx = numpy.argsort(pred, axis=1)[:, -self.top_k :]
            self.sum_metric += (topk_idx == label.reshape(-1, 1)).any(axis=1).sum()
            self.num_inst += label.shape[0]


@register
class F1(EvalMetric):
    """Binary F1 (reference metric.py F1)."""

    def __init__(self, name="f1", output_names=None, label_names=None, average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = 0.0

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_np(label).flatten().astype(numpy.int32)
            pred = _as_np(pred)
            if pred.ndim > 1:
                pred = numpy.argmax(pred, axis=1)
            pred = pred.flatten().astype(numpy.int32)
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            prec = self._tp / max(self._tp + self._fp, 1e-12)
            rec = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (reference metric.py MCC)."""

    def __init__(self, name="mcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = self._tn = 0.0

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_np(label).flatten().astype(numpy.int32)
            pred = _as_np(pred)
            if pred.ndim > 1:
                pred = numpy.argmax(pred, axis=1)
            pred = pred.flatten().astype(numpy.int32)
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            self._tn += ((pred == 0) & (label == 0)).sum()
            denom = math.sqrt(
                (self._tp + self._fp) * (self._tp + self._fn) * (self._tn + self._fp) * (self._tn + self._fn)
            )
            mcc = ((self._tp * self._tn) - (self._fp * self._fn)) / max(denom, 1e-12)
            self.sum_metric = mcc
            self.num_inst = 1


@register
class Perplexity(EvalMetric):
    """exp(mean NLL) (reference metric.py Perplexity)."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_np(label).astype(numpy.int32).flatten()
            pred = _as_np(pred).reshape(-1, _as_np(pred).shape[-1])
            probs = pred[numpy.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(pred.dtype)
                probs = probs * (1 - ignore) + ignore
                num -= int(ignore.sum())
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


class _RegressionMetric(EvalMetric):
    def _err(self, label, pred):
        raise NotImplementedError

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_np(label)
            pred = _as_np(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += self._err(label, pred)
            self.num_inst += 1


@register
class MAE(_RegressionMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def _err(self, label, pred):
        return numpy.abs(label - pred).mean()


@register
class MSE(_RegressionMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def _err(self, label, pred):
        return ((label - pred) ** 2).mean()


@register
class RMSE(_RegressionMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def _err(self, label, pred):
        return numpy.sqrt(((label - pred) ** 2).mean())


@register
class CrossEntropy(EvalMetric):
    """CE of predicted prob at true class (reference metric.py CrossEntropy)."""

    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel().astype(numpy.int32)
            pred = _as_np(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), label]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None, label_names=None):
        EvalMetric.__init__(self, name, output_names, label_names, eps=eps)
        self.eps = eps


class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel()
            pred = _as_np(pred).ravel()
            self.sum_metric += numpy.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of a loss output (reference metric.py Loss)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for pred in preds:
            loss = _as_np(pred)
            self.sum_metric += loss.sum()
            self.num_inst += loss.size


@register
class CustomMetric(EvalMetric):
    """Wrap a feval(label, pred) numpy function (reference metric.py CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False, output_names=None, label_names=None):
        if name is None:
            name = feval.__name__ if feval.__name__ != "<lambda>" else "custom"
        super().__init__("custom(%s)" % name if "(" not in name else name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_np(label), _as_np(pred))
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Create a CustomMetric from a numpy function (reference metric.py np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


register(NegativeLogLikelihood, "nll_loss")
register(Accuracy, "acc", "accuracy")
register(TopKAccuracy, "top_k_accuracy", "top_k_acc")
register(MSE, "mse")
register(RMSE, "rmse")
register(MAE, "mae")
register(CrossEntropy, "ce", "cross-entropy")
register(F1, "f1")
register(MCC, "mcc")
register(Loss, "loss")
register(Perplexity, "perplexity")
register(PearsonCorrelation, "pearsonr")
register(CompositeEvalMetric, "composite")
