"""Router degradation policy — spend fidelity before availability
(ISSUE 17).

The decision ladder, in order of escalation:

1. **native** — every priority serves its native (registration) tier;
2. **degrade** — overload detected: degradable priorities (everything
   outside the protected set, i.e. ``best_effort``) are rerouted to the
   next cheaper twin.  Paid traffic keeps the native pool to itself;
3. **shed** — the true last resort, and it is not a policy action at all:
   each pool's bounded admission queue sheds its own overflow
   (``ServerBusy``), exactly as a bare Engine always has.  Degradation
   exists to push that point as far out as possible for paid traffic.

Overload is detected from two signals, either sufficient:

* the shared :class:`~mxnet_tpu.telemetry.slo.SLOMonitor`'s windowed
  error-budget **burn rate** (``burn_rates()``, the cached ≤1/s read
  path) reaching ``burn_high`` on ANY objective — the contractual signal;
* native-pool **queue pressure** (depth / max_queue) reaching
  ``pressure`` — the fast path that reacts within one policy tick, before
  a latency window has even filled (and the only signal when MXNET_SLO
  is unset).

**Hysteresis on upgrade**: degradation clears only after the burn rate
has fallen to ``burn_low`` AND pressure to half the trigger level,
continuously for ``hold_s`` — a flapping policy would thrash the twins'
caches and make tier labels useless for debugging.

Two modes (``MXNET_ROUTER_POLICY``):

* ``"degrade"`` (default) — the ladder above;
* ``"shed"`` — the pre-twin baseline, kept as a named mode so A/B bench
  runs and ci/check_router.py can hold the ladder to "strictly better
  paid goodput than shedding alone": every priority stays native and the
  class-blind bounded queue does all the shedding.

:class:`DegradePolicy` is pure decision logic — no threads, no clocks of
its own (``now`` is always passed in), so tests drive it synthetically.
The router owns the loop.  Env knobs are read once at construction
(constructor args win), never on the request path.
"""
from __future__ import annotations

import os

__all__ = ["PolicyConfig", "DegradePolicy", "POLICY_MODES",
           "config_from_env"]

POLICY_MODES = ("degrade", "shed")


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


class PolicyConfig:
    """Knobs for one policy instance (docs/ENV_VARS.md, MXNET_ROUTER_*)."""

    __slots__ = ("mode", "burn_high", "burn_low", "hold_s", "interval_s",
                 "pressure")

    def __init__(self, mode="degrade", burn_high=1.0, burn_low=0.5,
                 hold_s=5.0, interval_s=0.25, pressure=0.5):
        if mode not in POLICY_MODES:
            raise ValueError("policy mode %r not in %s"
                             % (mode, list(POLICY_MODES)))
        if not 0.0 < burn_low <= burn_high:
            raise ValueError("need 0 < burn_low <= burn_high, got %g/%g"
                             % (burn_low, burn_high))
        self.mode = mode
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        self.hold_s = float(hold_s)
        self.interval_s = float(interval_s)
        self.pressure = float(pressure)


def config_from_env(mode=None):
    """PolicyConfig from ``MXNET_ROUTER_*`` (read HERE, at construction —
    a deployment with no router constructs no config and reads nothing).
    Malformed numbers fall back to defaults, an unknown mode falls back
    to ``"degrade"`` — the ``_env_ladder`` never-crash contract."""
    if mode is None:
        mode = (os.environ.get("MXNET_ROUTER_POLICY", "") or
                "degrade").strip().lower()
    if mode not in POLICY_MODES:
        mode = "degrade"
    return PolicyConfig(
        mode=mode,
        burn_high=_env_float("MXNET_ROUTER_BURN_HIGH", 1.0),
        burn_low=min(_env_float("MXNET_ROUTER_BURN_LOW", 0.5),
                     _env_float("MXNET_ROUTER_BURN_HIGH", 1.0)),
        hold_s=_env_float("MXNET_ROUTER_HOLD_S", 5.0),
        interval_s=_env_float("MXNET_ROUTER_INTERVAL_S", 0.25),
        pressure=_env_float("MXNET_ROUTER_PRESSURE", 0.5))


class DegradePolicy:
    """Degrade-first decision state machine (pure logic, router-driven).

    ``step(signals, now)`` -> list of ``(action, priority)`` transitions,
    where action is ``"degrade"`` or ``"restore"``.  ``signals`` is a
    dict with ``"burn"`` (max windowed burn rate across objectives, None
    when unknown) and ``"pressure"`` (native-pool depth/max_queue in
    [0, 1]).
    """

    def __init__(self, config, priorities, protected=("paid",)):
        self.config = config
        self.protected = tuple(p for p in priorities if p in protected)
        self.degradable = tuple(p for p in priorities
                                if p not in protected)
        self.degraded = {}       # priority -> monotonic degrade time
        self._clear_since = None  # start of the current calm stretch
        self.last_signals = {}

    def overloaded(self, signals):
        """Trigger condition (burn OR pressure at the high mark)."""
        burn = signals.get("burn")
        if burn is not None and burn >= self.config.burn_high:
            return True
        pressure = signals.get("pressure") or 0.0
        return (self.config.pressure > 0
                and pressure >= self.config.pressure)

    def _calm(self, signals):
        """Restore condition — stricter than ``not overloaded()`` (the
        hysteresis band): burn at/below burn_low (or unknown) AND
        pressure below half the trigger level."""
        burn = signals.get("burn")
        if burn is not None and burn > self.config.burn_low:
            return False
        pressure = signals.get("pressure") or 0.0
        return pressure < self.config.pressure / 2.0

    def step(self, signals, now):
        self.last_signals = dict(signals)
        actions = []
        if self.config.mode != "degrade":
            return actions  # "shed": admission does everything, class-blind
        if self.overloaded(signals):
            self._clear_since = None
            for p in self.degradable:
                if p not in self.degraded:
                    self.degraded[p] = now
                    actions.append(("degrade", p))
        elif self.degraded:
            if not self._calm(signals):
                # inside the hysteresis band (neither overloaded nor calm):
                # hold the current level and reset the calm clock
                self._clear_since = None
            elif self._clear_since is None:
                self._clear_since = now
            elif now - self._clear_since >= self.config.hold_s:
                for p in sorted(self.degraded):
                    del self.degraded[p]
                    actions.append(("restore", p))
                self._clear_since = None
        else:
            self._clear_since = None
        return actions

    def status(self, now=None):
        """The ``stats()["router"]["policy"]`` block."""
        out = {"mode": self.config.mode,
               "burn_high": self.config.burn_high,
               "burn_low": self.config.burn_low,
               "hold_s": self.config.hold_s,
               "pressure": self.config.pressure,
               "signals": dict(self.last_signals),
               "degraded": sorted(self.degraded)}
        if now is not None and self.degraded:
            out["degraded_for_s"] = {
                p: round(max(0.0, now - t), 3)
                for p, t in self.degraded.items()}
        return out
