"""Dynamic micro-batcher — requests in, shape-bucketed batches out.

Concurrent callers enqueue single requests (each carrying 1..n samples);
ONE consumer — the Engine's device loop — pulls formed batches.  Batch
formation follows the standard dynamic-batching contract (Triton/TF-Serving
style):

* requests are grouped by **shape class** (their ladder-padded per-sample
  shapes) — only same-class requests share an executable;
* a batch flushes when it reaches the top bucket capacity, OR when the
  OLDEST member has waited ``max_wait_s`` (partial-batch flush — bounded
  queueing delay beats perfect fill); every shape class is scanned, so a
  ready class never idles behind another class's open flush window;
* cancelled / deadline-expired requests are dropped at formation time and
  never reach the device (an all-expired wave produces an *empty flush*:
  the consumer simply waits again — tested);
* oversize requests (more samples than the top bucket, or a sample shape no
  ladder bucket dominates) bypass grouping and dispatch alone
  (direct-dispatch path).

The batcher owns the lock + condition; admission policy is injected through
``put(..., admit=...)`` so the queue bound is exact under concurrency, and
drop accounting flows through the ``on_drop`` callback so the Engine can
count timeouts/cancellations without the batcher knowing about telemetry.
"""
from __future__ import annotations

import threading
import time

from .admission import EngineClosed, RequestCancelled, RequestTimeout
from .bucketing import Bucket

__all__ = ["Request", "MicroBatcher"]

_PENDING, _DONE, _CANCELLED = "pending", "done", "cancelled"

# bounded idle wait: an empty-queue consumer re-checks (and heartbeats, when
# the Engine installed on_tick) at least this often instead of blocking
# forever — the /healthz liveness contract (telemetry/ops_server.py)
_IDLE_WAKE_S = 0.25


class Request:
    """One in-flight inference request + its result future.

    ``inputs``: dict name -> array with a LEADING sample-count dim (n >= 1).
    The result (set by the device loop) is the list of per-output arrays
    sliced back to this request's n rows.
    """

    def __init__(self, inputs, n, bucket_shapes, deadline=None, direct=False):
        self.inputs = inputs
        self.n = int(n)
        self.bucket_shapes = bucket_shapes  # padded per-sample shapes (dict)
        # hashable shape-class key: only same-class requests share a batch
        self.class_key = tuple(sorted(
            (str(k), tuple(v)) for k, v in bucket_shapes.items()))
        self.deadline = deadline            # absolute monotonic, or None
        self.direct = bool(direct)
        self.t_enqueue = time.monotonic()
        self.t_done = None
        self._ev = threading.Event()
        self._mu = threading.Lock()
        self._state = _PENDING
        self._dispatched = False
        self._value = None
        self._error = None
        self._waker = None  # set by MicroBatcher.put; called on cancel

    # -- future surface ------------------------------------------------------
    def done(self):
        return self._ev.is_set()

    def cancel(self):
        """Cancel if not yet dispatched.  Returns True when the request will
        never run (the batcher drops it at formation); False when it is
        already (being) computed — the same RUNNING rule as
        ``concurrent.futures`` (``mark_dispatched`` and this method settle
        the race under the request lock, so True really means never-ran)."""
        with self._mu:
            if self._dispatched or self._ev.is_set():
                return False
            self._state = _CANCELLED
        # wake the batcher so the reap (RequestCancelled + queue-slot
        # release) happens NOW, not at the next flush deadline.  Called
        # outside self._mu: the batcher wake takes the condition lock, and
        # the consumer holds that lock while claiming requests (which takes
        # self._mu) — calling under both would be an ABBA deadlock.
        if self._waker is not None:
            self._waker()
        return True

    def mark_dispatched(self):
        """Batcher-side: claim the request for device execution.  False when
        a concurrent ``cancel`` won the race (the batcher then drops it)."""
        with self._mu:
            if self._state == _CANCELLED:
                return False
            self._dispatched = True
            return True

    def cancelled(self):
        with self._mu:
            return self._state == _CANCELLED

    def expired(self, now=None):
        return (self.deadline is not None
                and (now if now is not None else time.monotonic()) > self.deadline)

    def set_result(self, value):
        self._value = value
        self._state = _DONE
        self.t_done = time.monotonic()
        self._ev.set()

    def set_error(self, err):
        self._error = err
        self.t_done = time.monotonic()
        self._ev.set()

    def result(self, timeout=None):
        """Block for the outcome; raises the serving/model error on failure.

        An expired WAIT raises the builtin ``TimeoutError`` (the
        ``concurrent.futures`` convention), NOT ``RequestTimeout`` — the
        latter means the server dropped the request at its deadline, while
        an impatient wait says nothing about the request, which may still
        complete and be counted in ``Engine.stats()['completed']``."""
        if not self._ev.wait(timeout):
            raise TimeoutError("result not ready after %.3fs" % timeout)
        if self._error is not None:
            raise self._error
        return self._value

    @property
    def queue_seconds(self):
        return time.monotonic() - self.t_enqueue

    @property
    def latency_s(self):
        """Submit-to-completion latency (None while pending) — measured at
        the moment the result/error was SET, independent of when the caller
        harvests it (an open-loop load generator harvests late)."""
        return None if self.t_done is None else self.t_done - self.t_enqueue


class MicroBatcher:
    """Bounded FIFO of Requests + the batch-formation algorithm.

    ``on_tick`` (optional) is called at the top of every consumer wait
    cycle — the Engine's device-loop heartbeat hook (ISSUE 10).  The idle
    wait is bounded by ``_IDLE_WAKE_S`` so a healthy loop with an empty
    queue still ticks; the wake itself is a no-op re-check.
    """

    def __init__(self, ladder, max_wait_s=0.005, on_drop=None, on_tick=None):
        self.ladder = ladder
        self.max_wait_s = float(max_wait_s)
        self.on_drop = on_drop or (lambda req, reason: None)
        self.on_tick = on_tick
        self._queue = []
        self._cond = threading.Condition()
        self._closed = False

    def depth(self):
        with self._cond:
            return len(self._queue)

    def put(self, req, admit=None):
        """Enqueue; ``admit(depth)`` runs under the lock and may raise to
        shed (exact bound — no admit/put race between submitter threads)."""
        with self._cond:
            if self._closed:
                raise EngineClosed("engine is closed")
            if not req.direct and req.n > self.ladder.max_batch:
                # formation can never service this (it only packs up to the
                # top bucket); admitting it would spin the consumer forever
                raise ValueError(
                    "request with %d samples exceeds the top bucket (%d); "
                    "mark it direct=True for the direct-dispatch path"
                    % (req.n, self.ladder.max_batch))
            if admit is not None:
                admit(len(self._queue))
            req._waker = self._notify
            self._queue.append(req)
            self._cond.notify_all()

    def _notify(self):
        with self._cond:
            self._cond.notify_all()

    def close(self):
        """Stop accepting work; wake the consumer.  Already-queued requests
        are failed with EngineClosed by the final next_batch() drain."""
        with self._cond:
            self._closed = True
            for req in self._queue:
                # account BEFORE set_error wakes the waiter: a caller that
                # unblocks from result() must see stats already updated
                self.on_drop(req, "closed")
                req.set_error(EngineClosed("engine closed with request queued"))
            self._queue.clear()
            self._cond.notify_all()

    # -- formation -----------------------------------------------------------
    def _reap(self):
        """Drop cancelled/expired requests (lock held).  The empty-flush
        case: a deadline wave can clear the whole queue here, and the
        consumer loop just goes back to waiting."""
        now = time.monotonic()
        keep = []
        for req in self._queue:
            # on_drop (stats) BEFORE set_error (waking the waiter), so a
            # caller unblocking from result() never reads a stale count
            if req.cancelled():
                self.on_drop(req, "cancelled")
                req.set_error(RequestCancelled("cancelled before dispatch"))
            elif req.expired(now):
                self.on_drop(req, "timeout")
                req.set_error(RequestTimeout(
                    "deadline expired after %.3fs in queue" % req.queue_seconds))
            else:
                keep.append(req)
        self._queue = keep

    def _next_wake(self, flush_at):
        """Earliest moment anything changes: the soonest flush deadline or
        any queued request's own deadline (so mid-queue timeouts fire on
        time even when the flush window is long)."""
        wake = flush_at
        for req in self._queue:
            if req.deadline is not None and req.deadline < wake:
                wake = req.deadline
        return wake

    def _formable(self, now):
        """Scan ALL shape classes (FIFO by each class's oldest member) for
        the first dispatchable group -> (take, bucket_shapes, direct,
        earliest_flush_at); ``take`` is None when nothing is ready before
        ``earliest_flush_at``.  Scanning every class — not just the head's —
        keeps a full or expired batch of class B from idling behind a young
        class-A head (no cross-class head-of-line blocking; lock held)."""
        groups, index = [], {}
        for req in self._queue:
            if req.direct:
                groups.append((req.class_key, [req], True))
            elif req.class_key in index:
                groups[index[req.class_key]][1].append(req)
            else:
                index[req.class_key] = len(groups)
                groups.append((req.class_key, [req], False))
        earliest = None
        for _, reqs, direct in groups:
            if direct:
                # oversize one-offs never benefit from waiting
                return reqs, reqs[0].bucket_shapes, True, None
            take, total = [], 0
            for r in reqs:
                if total + r.n <= self.ladder.max_batch:
                    take.append(r)
                    total += r.n
            flush_at = reqs[0].t_enqueue + self.max_wait_s
            if total >= self.ladder.max_batch or now >= flush_at \
                    or self._closed:
                return take, reqs[0].bucket_shapes, False, None
            if earliest is None or flush_at < earliest:
                earliest = flush_at
        return None, None, False, earliest

    def next_batch(self):
        """Block until a batch is ready -> (requests, bucket); None when the
        batcher is closed and drained.  Single consumer."""
        with self._cond:
            while True:
                if self.on_tick is not None:
                    self.on_tick()
                self._reap()
                if not self._queue:
                    if self._closed:
                        return None
                    self._cond.wait(_IDLE_WAKE_S)
                    continue
                now = time.monotonic()
                take, shapes, direct, earliest = self._formable(now)
                if take is None:
                    self._cond.wait(max(0.0, self._next_wake(earliest) - now)
                                    + 1e-4)
                    continue
                batch = []
                for req in take:
                    self._queue.remove(req)
                    if self._claim(req):
                        batch.append(req)
                if not batch:
                    continue  # the whole take cancelled underneath us
                if direct:
                    (req,) = batch
                    return batch, Bucket(req.n, shapes, direct=True)
                return batch, self.ladder.bucket_for(
                    shapes, sum(r.n for r in batch))

    def _claim(self, req):
        """Transition a popped request to dispatched; a concurrently
        cancelled one is failed+counted here instead (lock held)."""
        if req.mark_dispatched():
            return True
        self.on_drop(req, "cancelled")
        req.set_error(RequestCancelled("cancelled before dispatch"))
        return False
