"""Admission control — bounded queueing, deadlines, graceful shedding.

A serving engine that accepts unbounded work converts overload into
unbounded latency and, eventually, OOM.  The controller enforces the
classic triad instead: a **bounded queue** (excess load is shed immediately
with a 503-style error, never buffered), **per-request deadlines** (a
request that cannot be answered in time is dropped from the queue, not run
late), and **typed errors** so callers can distinguish "retry elsewhere"
(``ServerBusy``) from "too slow" (``RequestTimeout``) from "you cancelled"
(``RequestCancelled``).  The model loop itself never sees any of this —
shed/expired requests are filtered before dispatch, so overload can degrade
answers but cannot crash or wedge the device thread.
"""
from __future__ import annotations

import time

from ..base import MXNetError

__all__ = ["ServingError", "ServerBusy", "RequestTimeout", "RequestCancelled",
           "EngineClosed", "AdmissionController"]


class ServingError(MXNetError):
    """Base class for serving-path errors; carries an HTTP-style ``code``
    so an HTTP front end can map it 1:1 onto a status line."""

    code = 500


class ServerBusy(ServingError):
    """Queue at capacity — the request was shed at the door (HTTP 503)."""

    code = 503


class RequestTimeout(ServingError):
    """Deadline expired before the request reached the device (HTTP 504)."""

    code = 504


class RequestCancelled(ServingError):
    """Caller cancelled before dispatch (nginx's 499 convention)."""

    code = 499


class EngineClosed(ServingError):
    """Engine shut down — pending and new requests fail fast (HTTP 503)."""

    code = 503


class AdmissionController:
    """Queue-depth gate + deadline policy.

    ``check(depth)`` runs under the batcher lock (the Engine passes it as
    the ``admit`` hook of ``MicroBatcher.put``), so the bound is exact even
    with many submitter threads.  Shed decisions are counted locally —
    ``shed_total`` feeds ``Engine.stats()`` whether or not telemetry is on.
    """

    def __init__(self, max_queue=256, default_timeout_s=None):
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1, got %r" % (max_queue,))
        self.max_queue = int(max_queue)
        self.default_timeout_s = default_timeout_s
        self.shed_total = 0

    def deadline(self, timeout_s=None):
        """Absolute monotonic deadline for a new request (None = no limit).
        An explicit per-call timeout wins over the engine default."""
        t = timeout_s if timeout_s is not None else self.default_timeout_s
        if t is None or t <= 0:
            return None
        return time.monotonic() + float(t)

    def check(self, depth):
        """Admit or shed a request given the current queue depth (the
        request being admitted is NOT yet counted in ``depth``)."""
        if depth >= self.max_queue:
            self.shed_total += 1
            raise ServerBusy(
                "serving queue full (%d queued, max_queue=%d) — request shed"
                % (depth, self.max_queue))
