"""Model registry — precision-tier twins held as hot request classes
(ISSUE 17).

The router's degradation ladder only works if the cheaper twin is ALREADY
hot when overload hits: building a bf16/int8 plan, calibrating it, and
compiling its buckets takes seconds the overloaded engine does not have.
The registry front-loads all of that at registration time:

* the checkpoint is loaded ONCE into a base fp32 :class:`Predictor`;
  every tier twin comes off it via ``Predictor.with_precision`` (shared
  weight device buffers — N tiers cost ~1x the weights in HBM, PR 15);
* ``"int8"`` twins auto-calibrate from a **seed trace** (an iterable of
  ``{input name -> array}`` batches, e.g. a slice of a loadgen JSONL
  replay) when no explicit :class:`CalibrationTable` is passed — an int8
  tier without either is refused at registration, because the uncalibrated
  rewrite provably serves the fp32 plan at int8's advertised cost
  (ci/check_precision_tier.py);
* :meth:`RegisteredModel.build_engine` spins an Engine replica for any
  tier off the twin (``Engine(proto=...)`` respecializes over the shared
  buffers; with ``MXNET_AOT_CACHE`` set, ``warmup()`` restores each
  bucket's executable from disk, so replica spin-up pays parse/lower
  never backend-compile — PR 6).

The registry itself is passive bookkeeping: no threads, no env gates, no
telemetry.  It is only ever constructed explicitly (by the router or by
user code), so the Engine off-path is untouched.
"""
from __future__ import annotations

import threading

from ..predictor import Predictor

__all__ = ["ModelRegistry", "RegisteredModel", "KNOWN_TIERS"]

# degradation order is REGISTRATION order, but each name must be a tier
# the precision pass list knows (graph_passes/precision._TIER_PASSES) or
# the explicit fp32/native anchor
KNOWN_TIERS = ("fp32", "bf16", "int8")


class RegisteredModel:
    """One model's tier twins + the recipe to build Engine replicas.

    ``tiers`` is ordered: index 0 is the **native** tier (what paid
    traffic gets), later entries are progressively cheaper twins in
    degradation order.  Twins share the base predictor's weight device
    buffers.  Construct through :meth:`ModelRegistry.register`.
    """

    def __init__(self, name, sample_shapes, tiers, twins, calibration,
                 engine_kw):
        self.name = name
        self.sample_shapes = dict(sample_shapes)
        self.tiers = tuple(tiers)
        self._twins = dict(twins)           # tier -> Predictor
        self.calibration = calibration      # CalibrationTable or None
        self._engine_kw = dict(engine_kw)

    @property
    def native_tier(self):
        return self.tiers[0]

    def twin(self, tier):
        """The hot Predictor for one registered tier."""
        try:
            return self._twins[tier]
        except KeyError:
            raise KeyError("model %r has no tier %r (registered: %s)"
                           % (self.name, tier, list(self.tiers)))

    def build_engine(self, tier, name=None, slo_monitor=None, start=True,
                     **overrides):
        """One Engine replica serving ``tier``'s twin.

        Respecializes off the shared-weight twin (``Engine(proto=...)``),
        so a pool of replicas never re-loads the checkpoint; registration-
        time engine kwargs (ladder, queue bounds, ...) apply unless
        overridden here.
        """
        from .engine import Engine

        kw = dict(self._engine_kw)
        kw.update(overrides)
        return Engine(None, None, self.sample_shapes,
                      name=name or "%s-%s" % (self.name, tier),
                      proto=self.twin(tier), slo_monitor=slo_monitor,
                      start=start, **kw)


class ModelRegistry:
    """Named models -> their tier-twin sets.  Thread-safe, passive."""

    def __init__(self):
        self._mu = threading.Lock()
        self._models = {}

    def register(self, name, symbol, params, sample_shapes,
                 tiers=("fp32", "bf16"), calibration=None, seed_trace=None,
                 dtype="float32", ctx=None, output_names=None, **engine_kw):
        """Load a checkpoint once and build its tier twins.

        Parameters
        ----------
        name : str
            Registry key (also the default engine-name prefix).
        symbol, params : as ``Predictor``.
        sample_shapes : dict
            name -> per-sample shape (no batch dim), as ``Engine``.
        tiers : sequence of str
            Degradation ladder, native first (default ``("fp32",
            "bf16")``).  Each must be in :data:`KNOWN_TIERS`.
        calibration : CalibrationTable, optional
            Explicit int8 calibration; wins over ``seed_trace``.
        seed_trace : iterable of dict, optional
            ``{input name -> array}`` batches fed through
            ``graph_passes.precision.calibrate`` on the fp32 base when an
            ``"int8"`` tier is requested without an explicit table.
        **engine_kw :
            Defaults for every :meth:`RegisteredModel.build_engine` call
            (ladder, max_queue, max_wait_ms, ...).
        """
        tiers = tuple(tiers)
        if not tiers:
            raise ValueError("tiers must name at least the native tier")
        for t in tiers:
            if t not in KNOWN_TIERS:
                raise ValueError("unknown tier %r (known: %s)"
                                 % (t, list(KNOWN_TIERS)))
        if len(set(tiers)) != len(tiers):
            raise ValueError("duplicate tier in %s" % (tiers,))
        sample_shapes = {str(k): tuple(int(d) for d in v)
                         for k, v in sample_shapes.items()}
        # one checkpoint load: the fp32 base anchors every twin's weights
        # (batch dim 1 — twins are shape-respecialized per engine bucket,
        # and calibration's structural walk is shape-agnostic)
        base = Predictor(symbol, params,
                         {k: (1,) + v for k, v in sample_shapes.items()},
                         ctx=ctx, output_names=output_names, dtype=dtype)
        if "int8" in tiers and calibration is None:
            if seed_trace is None:
                raise ValueError(
                    "tier 'int8' needs calibration= or seed_trace=: the "
                    "uncalibrated int8 rewrite is a no-op (PR 15), so "
                    "registering it would silently serve fp32 cost under "
                    "an int8 label")
            from ..graph_passes import precision

            calibration = precision.calibrate(base, seed_trace)
        twins = {}
        for t in tiers:
            # "fp32" twins clear the tier explicitly so an ambient
            # MXNET_PRECISION_TIER cannot leak into the native pool
            twins[t] = base.with_precision(
                None if t == "fp32" else t,
                calibration if t == "int8" else None)
        model = RegisteredModel(name, sample_shapes, tiers, twins,
                                calibration, engine_kw)
        with self._mu:
            self._models[name] = model
        return model

    def get(self, name):
        with self._mu:
            try:
                return self._models[name]
            except KeyError:
                raise KeyError("model %r is not registered (have: %s)"
                               % (name, sorted(self._models)))

    def names(self):
        with self._mu:
            return sorted(self._models)

    def unregister(self, name):
        with self._mu:
            self._models.pop(name, None)
