"""mxnet_tpu.serving — online inference over Predictor/Symbol (ISSUE 2).

The deployment story past a single-request ``Predictor``: concurrent
requests are collected by a dynamic micro-batcher into padded **shape
buckets** (finite ladder -> finite XLA compile set, precompilable via
``warmup``), an **admission controller** bounds the queue and sheds
overload with 503-style errors, and ONE device-loop thread owns all XLA
execution.  Queue health (latency, fill, padding waste, sheds, compiles)
flows through ``mxnet_tpu.telemetry`` when ``MXNET_TELEMETRY`` is on.

    from mxnet_tpu import serving
    eng = serving.Engine(symbol, params, sample_shapes={"data": (8,)},
                         ladder=serving.BucketLadder((1, 2, 4, 8)))
    eng.warmup()
    out = eng.predict({"data": x})        # x: (n, 8)

Above a single engine sits the SLO-policy layer (ISSUE 17): a
``ModelRegistry`` holds a model's precision-tier twins hot (PR 15 shared
weights, int8 seed-trace calibration), and a ``Router`` fronts per-tier
replica pools with priority classes, degrading best-effort traffic to
the cheaper twin on SLO burn BEFORE any shedding — docs/SERVING.md
"Router and degradation policy".

Load-test with ``tools/loadgen.py``; docs/SERVING.md has the architecture,
tuning guide, and the SERVE_BENCH schema.
"""
from .admission import (AdmissionController, EngineClosed, RequestCancelled,
                        RequestTimeout, ServerBusy, ServingError)
from .batcher import MicroBatcher, Request
from .bucketing import Bucket, BucketLadder, pow2_ladder
from .engine import Engine
from .model_registry import ModelRegistry, RegisteredModel
from .policy import DegradePolicy, PolicyConfig
from .router import Router, RouterRequest
from .warmup import warmup_engine

__all__ = [
    "AdmissionController", "Bucket", "BucketLadder", "DegradePolicy",
    "Engine", "EngineClosed", "MicroBatcher", "ModelRegistry",
    "PolicyConfig", "RegisteredModel", "Request", "RequestCancelled",
    "RequestTimeout", "Router", "RouterRequest", "ServerBusy",
    "ServingError", "pow2_ladder", "warmup_engine",
]
