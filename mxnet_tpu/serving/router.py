"""SLO-policy serving router — degrade precision before shedding
(ISSUE 17, ROADMAP item 1).

The missing layer between the per-device :class:`Engine` and a latency
contract: a :class:`Router` fronts per-tier pools of Engine replicas
built from one :class:`~mxnet_tpu.serving.model_registry.RegisteredModel`
(precision-tier twins over shared weights, PR 15), threads **priority
classes** (``paid``/``best_effort``) through admission, and runs a policy
loop over the live per-class SLO burn rate (``SLOMonitor.burn_rates()``,
PR 10's signal finally given a consumer) whose FIRST overload response is
rerouting best-effort traffic to the cheaper twin and whose LAST resort
is the bounded queues' own shedding:

* every replica pool feeds ONE router-owned SLO monitor, so burn rates
  aggregate across the fleet;
* paid traffic keeps the native pool: degrading best-effort both serves
  it cheaper AND isolates the native queue for paid latency;
* every reply carries the tier that actually served it (``req.tier``,
  stamped by the engine reply path — the tier-label contract);
* downgrade/shed decisions are counted per priority (``stats()``,
  ``router_*`` telemetry counters) and traced: the route span's context
  is handed to ``Engine.submit(trace_parent=...)`` so one trace covers
  the router→replica thread hop (PR 4 flow links);
* ``stats()`` is Engine-shaped (compiles / precision_tier / quality keys
  loadgen already reads) plus a ``router`` block, mirrored into
  ``/statusz`` under ``"routers"``.

Construction is always explicit — no env var conjures a router, so the
bare-Engine path reads nothing new (the off-path acceptance).  The
``MXNET_ROUTER_*`` knobs are read once inside ``policy.config_from_env``
at router construction.
"""
from __future__ import annotations

import threading
import time

from ..telemetry import flightrec, ops_server, qualityplane, slo, tracing
from .admission import EngineClosed, ServerBusy
from .policy import DegradePolicy, PolicyConfig, config_from_env

__all__ = ["Router", "RouterRequest", "DEFAULT_PRIORITIES"]

DEFAULT_PRIORITIES = ("paid", "best_effort")

# policy-transition history kept for stats()["router"]["transitions"]
_TRANSITION_RING = 32


class RouterRequest:
    """Future returned by :meth:`Router.submit` — the engine
    :class:`~mxnet_tpu.serving.batcher.Request` plus the routing facts:
    which priority the request carried, which tier/engine it was routed
    to, and (once completed) which tier actually served it."""

    __slots__ = ("_req", "priority", "routed_tier", "engine_name")

    def __init__(self, req, priority, routed_tier, engine_name):
        self._req = req
        self.priority = priority
        self.routed_tier = routed_tier
        self.engine_name = engine_name

    @property
    def tier(self):
        """The serving tier label (reply contract): stamped by the engine
        reply path at completion; until then, the routed tier."""
        return getattr(self._req, "tier", self.routed_tier)

    @property
    def n(self):
        return self._req.n

    @property
    def latency_s(self):
        return self._req.latency_s

    @property
    def t_done(self):
        return self._req.t_done

    def result(self, timeout=None):
        return self._req.result(timeout)

    def done(self):
        return self._req.done()

    def cancel(self):
        return self._req.cancel()


class Router:
    """Route requests across a registered model's tier-twin engine pools.

    Parameters
    ----------
    model : RegisteredModel
        The twin set (from :meth:`ModelRegistry.register`).  One Engine
        pool is built per registered tier; ``model.tiers[0]`` is the
        native tier, ``model.tiers[1]`` (when present) the degradation
        target.
    replicas : int or dict
        Engines per pool (a ``{tier: n}`` dict sizes pools separately).
    policy : PolicyConfig or str, optional
        Policy knobs, or just a mode name; default
        ``policy.config_from_env()`` (``MXNET_ROUTER_*``, read here
        once).
    priorities : sequence of str
        Known priority classes, most-protected first.  ``protected``
        priorities are never degraded.
    slo_monitor : SLOMonitor, optional
        Explicit shared monitor; default ``slo.monitor_from_env()``
        (``MXNET_SLO``) — without one the policy falls back to queue
        pressure alone.
    start : bool
        Start replica device loops + the policy loop (default).  With
        ``start=False`` call :meth:`start` later; :meth:`_policy_tick`
        can always be driven manually (tests).
    """

    def __init__(self, model, replicas=1, policy=None, name="router",
                 priorities=DEFAULT_PRIORITIES, protected=("paid",),
                 default_priority=None, slo_monitor=None, start=True):
        from .. import telemetry

        if len(model.tiers) < 2:
            raise ValueError(
                "router needs a degradation target: register the model "
                "with at least two tiers (got %s)" % (model.tiers,))
        self.name = name
        self.model = model
        self.priorities = tuple(priorities)
        if not self.priorities:
            raise ValueError("need at least one priority class")
        self.default_priority = (default_priority
                                 if default_priority is not None
                                 else self.priorities[-1])
        if self.default_priority not in self.priorities:
            raise ValueError("default_priority %r not in priorities %s"
                             % (self.default_priority, self.priorities))
        if isinstance(policy, str):
            policy = config_from_env(mode=policy)
        elif policy is None:
            policy = config_from_env()
        elif not isinstance(policy, PolicyConfig):
            raise TypeError("policy must be a PolicyConfig or mode string")
        self._policy_cfg = policy
        self._policy = DegradePolicy(policy, self.priorities,
                                     protected=protected)
        self._native = model.native_tier
        self._degrade_tier = model.tiers[1]
        self._slo = (slo_monitor if slo_monitor is not None
                     else slo.monitor_from_env())
        self._flightrec = flightrec.recorder()
        self._probe = telemetry.router_probe(name)
        if self._slo is not None:
            # the fleet shares ONE monitor; the router owns its breach hook
            self._slo.on_breach = self._on_slo_breach
        self._mu = threading.Lock()
        self._route = {p: self._native for p in self.priorities}
        self._counters = {p: {"requests": 0, "downgrades": 0, "sheds": 0}
                          for p in self.priorities}
        self._policy_counts = {"degrade": 0, "restore": 0}
        self._transitions = []
        self._last_signals = {}
        self._closed = False
        self._wake = threading.Event()
        self._thread = None
        # replica pools: tier -> [Engine]; every engine shares the router
        # monitor (or its absence) and the twin's weight buffers
        if isinstance(replicas, dict):
            counts = {t: int(replicas.get(t, 1)) for t in model.tiers}
        else:
            counts = {t: int(replicas) for t in model.tiers}
        self._pools = {}
        for tier in model.tiers:
            n = max(1, counts[tier])
            self._pools[tier] = [
                model.build_engine(
                    tier, name="%s-%s-%d" % (name, tier, i),
                    slo_monitor=self._slo, start=start)
                for i in range(n)]
        ops_server.maybe_register_router(self)
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Start replica device loops and the policy loop (idempotent)."""
        if self._closed:
            raise EngineClosed("router is closed")
        for pool in self._pools.values():
            for eng in pool:
                eng.start()
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._policy_loop, name="mxnet-router-%s" % self.name,
                daemon=True)
            self._thread.start()
        return self

    def close(self):
        """Stop the policy loop and close every replica engine."""
        self._closed = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        for pool in self._pools.values():
            for eng in pool:
                eng.close()
        ops_server.unregister_router(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def warmup(self, max_workers=None):
        """Pre-compile every pool's ladder (AOT-cache aware, PR 6) ->
        ``{engine name: per-bucket report list}``."""
        out = {}
        for tier in self.model.tiers:
            for eng in self._pools[tier]:
                out[eng.name] = eng.warmup(max_workers=max_workers)
        return out

    def engines(self, tier=None):
        """Replica engines (one tier's pool, or all)."""
        if tier is not None:
            return list(self._pools[tier])
        return [e for t in self.model.tiers for e in self._pools[t]]

    # -- request path --------------------------------------------------------
    def submit(self, inputs, timeout=None, klass=None, priority=None):
        """Route one request; returns a :class:`RouterRequest`.

        ``priority`` picks the routing class (default
        ``default_priority``; a ``klass`` naming a known priority is
        used when ``priority`` is omitted, so loadgen-style callers pass
        one string).  ``klass`` labels SLO accounting and defaults to
        the priority — per-priority objectives in ``MXNET_SLO`` then
        just work.  Raises ``ServerBusy`` when the routed pool's
        admission queue is full (the shed path — counted per priority).
        """
        if self._closed:
            raise EngineClosed("router is closed")
        prio = priority
        if prio is None and klass in self._route:
            prio = klass
        if prio is None or prio not in self._route:
            prio = self.default_priority
        if klass is None:
            klass = prio
        # the routing decision span: its context rides into Engine.submit
        # so the replica's request/queue/dispatch spans join THIS trace
        # across the thread handoff
        root = tracing.start_trace("route", lane=True, router=self.name,
                                   priority=prio)
        with self._mu:
            tier = self._route[prio]
            downgraded = tier != self._native
            c = self._counters[prio]
            c["requests"] += 1
            if downgraded:
                c["downgrades"] += 1
        eng = self._pick(self._pools[tier])
        if root:
            root.set(tier=tier, engine=eng.name, downgraded=int(downgraded))
        if self._probe:
            self._probe.record_route(prio, tier, downgraded)
        try:
            req = eng.submit(inputs, timeout=timeout, klass=klass,
                             trace_parent=root.context() if root else None)
        except ServerBusy:
            # the LAST resort fired: the routed pool's bounded queue is
            # full.  Count it against the priority so the ladder's
            # "degrade before shed" claim is auditable per class.
            with self._mu:
                self._counters[prio]["sheds"] += 1
            if self._probe:
                self._probe.record_shed(prio)
            if self._flightrec is not None:
                self._flightrec.record("router_shed", router=self.name,
                                       priority=prio, tier=tier,
                                       engine=eng.name)
            if root:
                root.finish(drop="shed")
            raise
        except Exception:
            if root:
                root.finish(drop="rejected")
            raise
        if root:
            root.finish()
        return RouterRequest(req, prio, tier, eng.name)

    def predict(self, inputs, timeout=None, klass=None, priority=None):
        """Synchronous convenience: submit + wait -> output arrays (the
        same contract as ``Engine.predict``)."""
        return self.submit(inputs, timeout=timeout, klass=klass,
                           priority=priority).result(None)

    @staticmethod
    def _pick(pool):
        """Least-loaded replica (queue depth; stable min, so equal-depth
        pools drain in replica order)."""
        if len(pool) == 1:
            return pool[0]
        return min(pool, key=lambda e: e._batcher.depth())

    # -- policy loop ---------------------------------------------------------
    def _on_slo_breach(self, objective, value_s):
        """Shared-monitor breach hook (fired outside the monitor lock):
        mirror into telemetry + the flight recorder, attributed to the
        router rather than any single replica."""
        from .. import telemetry

        telemetry.note_slo_breach(objective.klass, objective.percentile,
                                  value_s * 1e3, objective.target_s * 1e3)
        if self._flightrec is not None:
            self._flightrec.record("slo_breach", router=self.name,
                                   objective=objective.key(),
                                   value_ms=round(value_s * 1e3, 3))

    def _signals(self, now):
        """The policy inputs: max windowed burn rate across objectives
        (None without a monitor or traffic) + native-pool queue
        pressure."""
        burn = None
        if self._slo is not None:
            rates = self._slo.burn_rates(now)
            burns = [r["burn_rate"] for r in rates.values()
                     if r["burn_rate"] is not None]
            if burns:
                burn = max(burns)
        pressure = 0.0
        for eng in self._pools[self._native]:
            cap = float(eng.admission.max_queue) or 1.0
            pressure = max(pressure, eng._batcher.depth() / cap)
        return {"burn": burn, "pressure": round(pressure, 4)}

    def _policy_tick(self, now=None):
        """One policy evaluation (the loop's body; tests drive it with a
        synthetic clock) -> the applied transitions."""
        now = time.monotonic() if now is None else now
        signals = self._signals(now)
        actions = self._policy.step(signals, now)
        for action, prio in actions:
            tier = (self._degrade_tier if action == "degrade"
                    else self._native)
            with self._mu:
                self._route[prio] = tier
                self._policy_counts[action] += 1
                self._transitions.append({
                    "action": action, "priority": prio, "tier": tier,
                    "burn": signals["burn"],
                    "pressure": signals["pressure"],
                    "unix_ts": round(time.time(), 3)})
                del self._transitions[:-_TRANSITION_RING]
            if self._probe:
                self._probe.record_transition(action, prio,
                                              action == "degrade")
            if self._flightrec is not None:
                self._flightrec.record("router_policy", router=self.name,
                                       action=action, priority=prio,
                                       tier=tier, burn=signals["burn"],
                                       pressure=signals["pressure"])
        self._last_signals = signals
        return actions

    def _policy_loop(self):
        interval = max(0.01, self._policy_cfg.interval_s)
        while not self._closed:
            self._wake.wait(interval)
            if self._closed:
                return
            try:
                self._policy_tick()
            except Exception:
                pass  # the policy loop must never die under the router

    # -- introspection -------------------------------------------------------
    def stats(self):
        """Engine-shaped stats (the keys loadgen/bench readers use) plus
        the ``router`` block (/statusz ``"routers"`` mirror)."""
        with self._mu:
            route = dict(self._route)
            counters = {p: dict(c) for p, c in self._counters.items()}
            policy_counts = dict(self._policy_counts)
            transitions = list(self._transitions)
        engines = {}
        compiles = 0
        submitted = completed = shed = 0
        for tier in self.model.tiers:
            for eng in self._pools[tier]:
                es = eng.stats()
                compiles += es["compiles"]
                submitted += es["submitted"]
                completed += es["completed"]
                shed += es["shed"]
                engines[eng.name] = {
                    "tier": tier, "queue_depth": es["queue_depth"],
                    "submitted": es["submitted"],
                    "completed": es["completed"], "shed": es["shed"],
                    "compiles": es["compiles"]}
        out = {
            "submitted": submitted, "completed": completed, "shed": shed,
            "compiles": compiles,
            "requests": sum(c["requests"] for c in counters.values()),
            "downgrades": sum(c["downgrades"] for c in counters.values()),
            "sheds": sum(c["sheds"] for c in counters.values()),
            # the native tier: what un-degraded traffic compiles under —
            # the same discriminator slot Engine.stats() exposes
            "precision_tier": self._native,
            "router": {
                "policy": self._policy.status(now=time.monotonic()),
                "native_tier": self._native,
                "degrade_tier": self._degrade_tier,
                "route": route,
                "priorities": counters,
                "transitions": transitions,
                "policy_counts": policy_counts,
                "signals": dict(self._last_signals),
                "replicas": {t: [e.name for e in self._pools[t]]
                             for t in self.model.tiers}},
            "engines": engines}
        out["slo"] = self._slo.status() if self._slo is not None else None
        out["quality"] = qualityplane.status()
        return out
