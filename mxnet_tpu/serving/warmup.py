"""Startup warmup — take every bucket's XLA compile before traffic does.

A cold serving engine pays each bucket's compile on the first unlucky
request that lands in it — seconds of p99 latency handed to a real user.
The warmup pass runs every ladder signature on zeros at startup instead
(the TVM lesson from PAPERS.md: specialize ahead of time to a finite shape
set, then serving is pure cache hits).  After ``warmup_engine`` a
mixed-shape request stream adds **zero** new compiles (asserted in
tests/test_serving.py).

Recipe (docs/SERVING.md):

    eng = serving.Engine(sym, params, {"data": (8,)}, start=False)
    report = eng.warmup()          # compiles len(ladder.signatures()) graphs
    eng.start()                    # begin serving, all-hot

Warmup respects the device-exclusion lock, so it is also safe on a live
engine (e.g. after enlarging the ladder) — buckets compile between batches.
"""
from __future__ import annotations

__all__ = ["warmup_engine"]


def warmup_engine(engine, buckets=None, verbose=False):
    """Compile ``buckets`` (default: the engine's full ladder signature
    set) by forwarding zeros through each.  Returns the per-bucket report:
    ``[{"bucket", "fresh", "compile_s"}, ...]`` — ``fresh=False`` rows were
    already cached (idempotent; re-running warmup is free)."""
    if buckets is None:
        buckets = engine.ladder.signatures(engine.sample_shapes)
    report = []
    for bucket in buckets:
        row = engine._warm_bucket(bucket)
        report.append(row)
        if verbose:
            print("warmup %-28s %s" % (
                row["bucket"],
                "compiled in %.3fs" % row["compile_s"] if row["fresh"]
                else "cached"))
    return report
