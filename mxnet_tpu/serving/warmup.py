"""Startup warmup — take every bucket's XLA compile before traffic does.

A cold serving engine pays each bucket's compile on the first unlucky
request that lands in it — seconds of p99 latency handed to a real user.
The warmup pass runs every ladder signature on zeros at startup instead
(the TVM lesson from PAPERS.md: specialize ahead of time to a finite shape
set, then serving is pure cache hits).  After ``warmup_engine`` a
mixed-shape request stream adds **zero** new compiles (asserted in
tests/test_serving.py).

With ``MXNET_AOT_CACHE=<dir>`` set (compile_cache.py, ISSUE 6) warmup gets
two upgrades:

* **split pipeline** — every bucket's trace+lower (pure host work) runs
  concurrently in a thread pool *before* the device mutex is taken; only
  the XLA backend compile and the zeros forward serialize.  The report
  splits the cost per bucket as ``lower_s`` vs ``compile_s``.
* **persistent executables** — buckets whose executable is already in the
  cache directory restore from disk (``cache: "hit"``) and the compile
  stage vanishes: a restart warms in the time it takes to read files.

Cache off ⇒ the original serial zeros-forward loop, byte-identical.

Recipe (docs/SERVING.md):

    eng = serving.Engine(sym, params, {"data": (8,)}, start=False)
    report = eng.warmup()          # compiles len(ladder.signatures()) graphs
    eng.start()                    # begin serving, all-hot

Warmup respects the device-exclusion lock, so it is also safe on a live
engine (e.g. after enlarging the ladder) — buckets compile between batches.
"""
from __future__ import annotations

import time

__all__ = ["warmup_engine"]


def warmup_engine(engine, buckets=None, verbose=False, max_workers=None):
    """Compile ``buckets`` (default: the engine's full ladder signature
    set) by forwarding zeros through each.  Returns the per-bucket report:
    ``[{"bucket", "fresh", "compile_s", "lower_s", "cache",
    "graph_nodes_pre", "graph_nodes_post", "check_warnings"}, ...]`` —
    ``fresh=False`` rows were already live in this process (idempotent;
    re-running warmup is free); ``cache`` is ``"hit"``/``"miss"`` against
    the persistent AOT cache, or None when ``MXNET_AOT_CACHE`` is off; the
    ``graph_nodes_*`` pair is the bucket plan's node count before/after the
    graph-pass pipeline (ISSUE 7; None with ``MXNET_GRAPH_PASSES=0``);
    ``check_warnings`` counts this bucket's graph-IR analyzer diagnostics
    (``Predictor.check()``, ISSUE 8; None with ``MXNET_GRAPH_ANALYZERS``
    off) and ``precision_verdicts`` is the bucket plan's cast-plan verdict
    histogram (``Predictor.precision_plan().counts()``, ISSUE 11; same
    gate, None when off); ``precision_tier`` is the tier the bucket's plan
    compiled under (``"fp32"`` unless ``MXNET_PRECISION_TIER`` rewrote it,
    ISSUE 15 — always present, so mixed-tier fleets are inspectable from
    ``/statusz``); ``xla_flops`` / ``xla_peak_bytes`` are the
    XLA-measured cost of the executable this bucket's warm built
    (compile plane, ISSUE 13; None with ``MXNET_COSTPLANE`` off, on a
    cache hit, or when the backend reports nothing).
    The pass is also summarized in ``engine.stats()["warmup"]``."""
    from .. import compile_cache

    if buckets is None:
        buckets = engine.ladder.signatures(engine.sample_shapes)
    buckets = list(buckets)
    t0 = time.perf_counter()
    handles = {}
    # ladder signatures only: a direct (client-shaped) bucket handed in
    # explicitly keeps the old inline path so it never gets pinned
    aot_buckets = [b for b in buckets if not b.direct]
    if compile_cache.active() and aot_buckets:
        from concurrent.futures import ThreadPoolExecutor

        # binds run serially (symbol graph walking is shared state); only
        # the per-bucket jax trace+lower — thread-safe, pure host work —
        # fans out
        preds = [(b, engine._bind_bucket(b)) for b in aot_buckets]
        workers = max_workers or min(8, len(preds))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            for (bucket, _), handle in zip(
                    preds, pool.map(lambda bp: bp[1].aot_lower(), preds)):
                if handle is not None:
                    handles[bucket.key] = handle
    report = []
    for bucket in buckets:
        row = engine._warm_bucket(bucket, handles.get(bucket.key))
        report.append(row)
        if verbose:
            if not row["fresh"]:
                state = "cached"
            elif row["cache"] == "hit":
                state = "restored in %.3fs (lower %.3fs)" % (
                    row["compile_s"], row["lower_s"])
            else:
                state = "compiled in %.3fs (lower %.3fs)" % (
                    row["compile_s"], row["lower_s"])
            if row.get("graph_nodes_post") is not None \
                    and row["graph_nodes_post"] != row["graph_nodes_pre"]:
                state += "  [graph %d->%d nodes]" % (
                    row["graph_nodes_pre"], row["graph_nodes_post"])
            if row.get("check_warnings"):
                state += "  [check: %d diagnostics]" % row["check_warnings"]
            if row.get("xla_flops") is not None:
                state += "  [xla %.3f GFLOP%s]" % (
                    row["xla_flops"] / 1e9,
                    "" if row.get("xla_peak_bytes") is None
                    else ", peak %.1f MB" % (row["xla_peak_bytes"] / 1e6))
            if row.get("precision_verdicts"):
                v = row["precision_verdicts"]
                state += "  [cast-plan: %d bf16_safe / %d fp32_accum / " \
                    "%d fp32_only]" % (v.get("bf16_safe", 0),
                                       v.get("fp32_accum", 0),
                                       v.get("fp32_only", 0))
            if row.get("precision_tier") not in (None, "fp32"):
                state += "  [tier: %s]" % row["precision_tier"]
            print("warmup %-28s %s" % (row["bucket"], state))
    total_s = time.perf_counter() - t0
    engine._note_warmup(report, total_s)
    # flight recorder (ISSUE 10): a warmup pass is a lifecycle landmark —
    # a post-mortem dump should show whether the failing traffic hit a
    # warmed or a cold ladder (one `is None` check when the gate is off)
    if engine._flightrec is not None:
        engine._flightrec.record(
            "warmup", dur_s=total_s, engine=engine.name,
            buckets=len(report),
            fresh=sum(1 for r in report if r["fresh"]),
            cache_hits=sum(1 for r in report if r.get("cache") == "hit"))
    return report
