"""The online inference Engine — queue -> micro-batcher -> device loop.

Layering (docs/SERVING.md has the picture):

* ``submit()`` (any thread) validates the request, stamps its deadline,
  and enqueues through the admission gate — overload is shed HERE with
  ``ServerBusy``, never buffered into unbounded latency;
* ONE device-loop thread pulls shape-bucketed batches from the
  ``MicroBatcher``, so XLA execution is never contended (the same
  single-writer rule the training stack gets from XLA async dispatch —
  docs/ARCHITECTURE.md); model failures fail that batch's requests and the
  loop keeps serving;
* a **compiled-signature cache** maps each ladder bucket to a ``Predictor``
  specialized via ``Predictor.with_shapes`` (weights are shared device
  buffers, not copies) — the whole traffic mix compiles exactly
  ``len(ladder.signatures())`` times, and ``warmup()`` takes those compiles
  at startup instead of on the first unlucky request;
* telemetry (``telemetry.serve_probe``) records queue latency, batch fill,
  padding waste, in-flight/depth gauges, shed/timeout counters and the
  serve compile counter — all zero-overhead when ``MXNET_TELEMETRY`` is off
  (the probe is None and every hook is a single ``if``).

Defaults come from ``MXNET_SERVE_*`` (docs/ENV_VARS.md).
"""
from __future__ import annotations

import collections
import os
import threading
import time

import numpy as np

from ..base import env_flag
from ..predictor import Predictor
from ..telemetry import (costplane, flightrec, ops_server, qualityplane,
                         slo, tracing)
from .admission import AdmissionController, EngineClosed, ServerBusy
from .batcher import MicroBatcher, Request
from .bucketing import BucketLadder, _volume

__all__ = ["Engine"]

# Direct-dispatch (oversize) signatures are client-controlled, so their
# cache must be bounded or a shape-varying stream grows executables without
# limit; ladder signatures are finite by construction and stay pinned.
_DIRECT_CACHE_MAX = 8

# Shadow-replay (quality plane) queue bound, in batches: live dispatch is
# strictly higher priority, so under pressure samples are SHED (counted)
# rather than buffered into memory growth.
_QUALITY_QUEUE_MAX = 8


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


def _env_ladder():
    """MXNET_SERVE_BATCH_LADDER, never-crash: any malformed value — non-
    numeric, zero/negative rungs, empty — falls back to the default."""
    raw = os.environ.get("MXNET_SERVE_BATCH_LADDER", "1,2,4,8")
    try:
        sizes = tuple(int(x) for x in raw.replace(" ", "").split(",") if x)
    except ValueError:
        sizes = ()
    sizes = tuple(s for s in sizes if s > 0)
    return sizes or (1, 2, 4, 8)


class Engine:
    """Serve a (symbol, params) checkpoint to concurrent callers.

    Parameters
    ----------
    symbol, params : as ``Predictor`` (Symbol/json-path, dict/params-path).
    sample_shapes : dict
        name -> PER-SAMPLE shape (no batch dim).  Request arrays always
        carry a leading sample-count dim: ``submit({"data": x})`` with
        ``x.shape == (n,) + sample_shapes["data"]``.
    ladder : BucketLadder, optional
        Defaults to ``BucketLadder(MXNET_SERVE_BATCH_LADDER)`` — batch
        bucketing only.  Pass ``shape_buckets`` in your own ladder for
        spatial bucketing (variable-size images etc.; trailing dims are
        zero-padded up to the bucket, batch rows are sliced back out).
    max_wait_ms / max_queue / timeout_ms :
        Partial-batch flush deadline, admission queue bound, default
        per-request deadline (0 = none).  Env defaults: MXNET_SERVE_*.
    max_direct_batch : int
        Sample-count cap for direct-dispatch (oversize) requests, default
        4x the top bucket.  The device loop is single-threaded, so one
        arbitrarily large client request would stall every other caller
        behind its compile + execution — beyond the cap submit() raises
        ValueError and the client must chunk.
    start : bool
        Start the device loop immediately (default).  ``start=False`` lets
        tests and warmup-first deployments queue/compile before serving.
    proto : Predictor, optional
        Serve an ALREADY-BUILT predictor instead of loading
        ``symbol``/``params`` (which may then be None): the engine
        specializes its buckets off this one via ``with_shapes``, sharing
        its weight device buffers and carrying its precision tier — how
        the model registry (ISSUE 17) spins up N replicas of a tier twin
        without re-loading the checkpoint per replica.
    slo_monitor : SLOMonitor, optional
        Share an external monitor instead of building one from
        ``MXNET_SLO`` — the router feeds every replica into ONE monitor so
        burn rates aggregate across the fleet.  The engine does not
        install its ``on_breach`` hook on a shared monitor (the owner
        wires breach handling once).
    """

    def __init__(self, symbol, params, sample_shapes, ladder=None,
                 max_wait_ms=None, max_queue=None, timeout_ms=None,
                 dtype="float32", ctx=None, output_names=None, name="serve",
                 start=True, max_direct_batch=None, proto=None,
                 slo_monitor=None):
        from .. import telemetry

        self.name = name
        self.sample_shapes = {str(k): tuple(int(d) for d in v)
                              for k, v in sample_shapes.items()}
        if ladder is None:
            # tuned ladder adoption (ISSUE 9): under MXNET_AUTOTUNE, rungs
            # proposed by the trace-replay tuner (tools/autotune.py search
            # --trace) and persisted for this stream's declared sample
            # shapes replace the env/default ladder.  An explicit ladder=
            # argument always wins; gate unset = this one env read and the
            # autotune package is never imported (off path tested).
            tuned = None
            if env_flag("MXNET_AUTOTUNE"):
                from .. import autotune

                tuned = autotune.tuned_ladder(self.sample_shapes)
            ladder = BucketLadder(tuned if tuned is not None
                                  else _env_ladder())
        self.ladder = ladder
        if max_wait_ms is None:
            max_wait_ms = _env_float("MXNET_SERVE_MAX_WAIT_MS", 5.0)
        if max_queue is None:
            max_queue = int(_env_float("MXNET_SERVE_MAX_QUEUE", 256))
        if timeout_ms is None:
            timeout_ms = _env_float("MXNET_SERVE_TIMEOUT_MS", 0.0)
        self.max_direct_batch = (int(max_direct_batch)
                                 if max_direct_batch is not None
                                 else 4 * self.ladder.max_batch)
        self.admission = AdmissionController(
            max_queue=max_queue,
            default_timeout_s=timeout_ms / 1000.0 if timeout_ms > 0 else None)
        self._batcher = MicroBatcher(self.ladder, max_wait_s=max_wait_ms / 1000.0,
                                     on_drop=self._on_drop,
                                     on_tick=self._beat)
        # proto predictor: loads/parses symbol+params ONCE; every bucket
        # specializes off it via with_shapes (shared weight buffers).  It is
        # seeded into the cache as its own bucket's entry — compile
        # accounting is by the separate _compiled set (first forward), so
        # seeding doesn't hide that bucket's one compile.
        proto_bucket = self.ladder.signatures(self.sample_shapes)[0]
        if proto is not None:
            # registry-built tier twin: respecialize over SHARED weight
            # buffers (with_shapes carries tier + calibration), so a pool
            # of replicas costs one checkpoint load total
            self._proto = proto.with_shapes(proto_bucket.input_shapes())
        else:
            self._proto = Predictor(symbol, params,
                                    proto_bucket.input_shapes(),
                                    ctx=ctx, output_names=output_names,
                                    dtype=dtype)
        self._cache = {proto_bucket.key: self._proto}  # ladder sigs, pinned
        self._direct_cache = collections.OrderedDict()  # one-offs, LRU
        self._compiled = set()      # signatures past their first forward
        self._cache_mu = threading.Lock()
        self._device_mu = threading.Lock()  # device loop + warmup exclusion
        self._stats_mu = threading.Lock()
        # "shed" lives on the AdmissionController (stats() merges it in)
        self._stats = {"submitted": 0, "completed": 0, "failed": 0,
                       "timeouts": 0, "cancelled": 0,
                       "direct": 0, "batches": 0, "compiles": 0,
                       "cache_hits": 0, "in_flight": 0}
        # per-bucket dispatch accounting: label -> [batches, requests,
        # padding_waste_sum] (stats()["bucket_stats"] derives means); kept
        # directly on the engine so the ladder tuner and operators read
        # per-bucket hit counts + padding waste without telemetry scraping
        self._bucket_stats = {}
        self._probe = telemetry.serve_probe(name)
        self._warmup = None  # last warmup pass summary (stats() block)
        self._thread = None
        self._closed = False
        # live ops plane (ISSUE 10) — each piece gates on its own env var;
        # all unset costs three env reads HERE and nothing on the request
        # path (every hook below is a single `is None` check, tested):
        # - _heartbeat: monotonic stamp the device loop writes each wait/
        #   dispatch cycle (single writer, read lock-free by /healthz)
        # - _slo: streaming latency objectives fed from the reply path
        # - _flightrec: bounded event ring dumped on failure
        self._heartbeat = None
        # "busy in dispatch" marker (ISSUE 16 satellite): monotonic start
        # of an in-progress device forward, stamped INSIDE _device_mu and
        # cleared on exit — lets /healthz staleness distinguish a long
        # forward (busy, healthy) from a dead loop (not busy, stale).
        # Single writer per mutex-holder, read lock-free (GIL-atomic).
        self._busy_since = None
        self._shared_slo = slo_monitor is not None
        self._slo = slo_monitor if self._shared_slo else slo.monitor_from_env()
        self._flightrec = flightrec.recorder()
        # inference quality plane (ISSUE 16): shadow-sampled twin
        # divergence + calibration drift.  Gate unset ⇒ plane is None,
        # every hook below is one `is None` check, and no shadow thread/
        # queue/ring is ever allocated (tests/test_qualityplane.py).
        self._quality = qualityplane.plane()
        if self._quality is not None:
            self._quality_q = collections.deque()
            self._quality_cv = threading.Condition()
            self._quality_thread = None  # started lazily at first sample
            self._quality_ref = {}       # bucket.key -> fp32 sibling
            self._quality_sites_key = None  # drift-baseline anchor
        if self._slo is not None and not self._shared_slo:
            # a shared (router-owned) monitor keeps ONE breach hook wired
            # by its owner; per-replica installs would race to overwrite it
            self._slo.on_breach = self._on_slo_breach
        ops_server.maybe_register(self)
        # lock-discipline checking (ISSUE 8, MXNET_LOCKCHECK=1): swap the
        # three mutexes for order-recording CheckedLocks and wrap their
        # owned containers.  Off path = this one env_flag read; the
        # analysis package is never imported and the locks above stay
        # vanilla threading.Lock (tests/test_analysis.py asserts).
        if env_flag("MXNET_LOCKCHECK"):
            from ..analysis import lockcheck

            lockcheck.instrument_engine(self)
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        """Start (or restart after ``start=False``) the device loop."""
        if self._closed:
            raise EngineClosed("engine is closed")
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="mxnet-serve-%s" % self.name,
                daemon=True)
            self._thread.start()
        return self

    def close(self):
        """Drain-free shutdown: pending requests fail with EngineClosed,
        the device loop exits after its current batch."""
        self._closed = True
        self._batcher.close()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        qt = getattr(self, "_quality_thread", None)
        if qt is not None:
            with self._quality_cv:
                self._quality_cv.notify_all()
            qt.join(timeout=5.0)
        ops_server.unregister(self)

    def _beat(self):
        """Device-loop heartbeat — called from the batcher's wait cycle and
        around dispatch.  Plain monotonic store, single writer (the loop),
        read lock-free by ``ops_server.engine_health`` (GIL-atomic)."""
        self._heartbeat = time.monotonic()

    def _on_slo_breach(self, objective, value_s):
        """SLO ok→breach edge (``slo.SLOMonitor.on_breach``, fired outside
        the monitor lock): mirror into telemetry and trip the flight
        recorder.  The dump (throttled file I/O) runs on a one-shot helper
        thread — the device loop is already missing its latency target at
        this moment and must not also pay a disk write."""
        from .. import telemetry

        telemetry.note_slo_breach(objective.klass, objective.percentile,
                                  value_s * 1e3, objective.target_s * 1e3)
        if self._flightrec is not None:
            self._flightrec.record("slo_breach", engine=self.name,
                                   objective=objective.key(),
                                   value_ms=round(value_s * 1e3, 3))
            threading.Thread(
                target=self._flightrec.dump, args=("slo_breach",),
                kwargs={"auto": True, "engine": self.name,
                        "objective": objective.key(),
                        "value_ms": round(value_s * 1e3, 3)},
                name="mxnet-flightrec-dump", daemon=True).start()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- request path --------------------------------------------------------
    def submit(self, inputs, timeout=None, klass=None, trace_parent=None):
        """Enqueue one request; returns a future-like ``Request``.

        ``inputs``: dict name -> array with leading sample-count dim n>=1.
        ``timeout``: seconds until the request is dropped if still queued
        (overrides the engine default).  ``klass``: request class for SLO
        accounting (``MXNET_SLO`` objectives; None ⇒ "default" — classes
        change nothing about an un-routed engine's scheduling, they only
        label the latency signal; the router maps priorities onto them).
        ``trace_parent``: a ``tracing.SpanContext`` to join instead of
        starting a fresh trace — the router's route span passes its
        context here so one trace covers the router→replica handoff.
        Raises ``ServerBusy`` when the queue is at capacity,
        ``EngineClosed`` after ``close()``.  Completed requests carry the
        serving precision tier as ``req.tier`` (the reply tier-label
        contract, ISSUE 17).
        """
        # span tracing (MXNET_TRACE, telemetry/tracing.py): the request root
        # lives on a per-trace lane; its context rides on the Request so the
        # device loop's spans flow-link back here across the thread handoff
        if trace_parent is not None:
            root = tracing.span("request", parent=trace_parent, lane=True,
                                engine=self.name)
        else:
            root = tracing.start_trace("request", lane=True,
                                       engine=self.name)
        try:
            with tracing.span("classify", parent=root):
                arrays, n, bucket_shapes, direct = self._classify(inputs)
        except Exception:
            root.finish(drop="invalid")
            raise
        req = Request(arrays, n, bucket_shapes,
                      deadline=self.admission.deadline(timeout), direct=direct)
        req.klass = klass
        if self._flightrec is not None:
            self._flightrec.record("submit", engine=self.name, n=n,
                                   direct=int(direct),
                                   klass=klass or "default")
        if root:
            root.set(n=n, direct=int(direct))
            req._trace_root = root
            req._trace_ctx = root.context()
            req._trace_queue = tracing.span("queue", parent=root, lane=True)
        # stamp stats BEFORE enqueueing (rolled back on rejection): once the
        # request is in the queue the device loop may complete it instantly,
        # and decrement-before-increment would publish in_flight = -1
        with self._stats_mu:
            self._stats["submitted"] += 1
            self._stats["in_flight"] += 1
            if direct:
                self._stats["direct"] += 1
        try:
            self._batcher.put(req, admit=self.admission.check)
        except Exception as e:
            with self._stats_mu:
                self._stats["submitted"] -= 1
                self._stats["in_flight"] -= 1
                if direct:
                    self._stats["direct"] -= 1
            if self._probe and isinstance(e, ServerBusy):
                self._probe.record_drop("shed")
            if isinstance(e, ServerBusy):
                if self._slo is not None:
                    self._slo.record_drop(klass)
                if self._flightrec is not None:
                    self._flightrec.record("drop", engine=self.name,
                                           reason="shed",
                                           klass=klass or "default")
            if root:
                reason = "shed" if isinstance(e, ServerBusy) else "rejected"
                req._trace_queue.finish(drop=reason)
                root.finish(drop=reason)
            raise
        if self._probe:
            with self._stats_mu:
                in_flight = self._stats["in_flight"]
            self._probe.record_submit(self._batcher.depth(), in_flight)
        return req

    def predict(self, inputs, timeout=None, klass=None):
        """Synchronous convenience: submit + wait -> list of output arrays
        (each sliced to this request's n rows on the batch dim).

        ``timeout`` bounds QUEUE time (the admission deadline): a request
        still queued at the deadline raises ``RequestTimeout``.  Once
        dispatched, the wait runs to completion — the result event is
        always set (success, model error, or drop), so this cannot hang on
        a live engine, and client-observed outcomes agree with
        ``stats()`` (a completed request is never double-reported as a
        timeout).  Deadlines are enforced by the device loop, so a
        synchronous wait against an engine with no running loop would hang
        forever — that misuse fails fast here instead (``submit`` stays
        legal on a stopped engine; callers hold the future and start()
        later)."""
        if self._thread is None or not self._thread.is_alive():
            raise EngineClosed(
                "engine is not serving (start() not called, or the device "
                "loop terminated) — a synchronous predict() would never "
                "complete")
        return self.submit(inputs, timeout=timeout,
                           klass=klass).result(None)

    def _classify(self, inputs):
        """Validate one request -> (np arrays, n, padded shape class,
        direct?).  Oversize (n above the top bucket, or a sample shape no
        bucket dominates) goes to the direct-dispatch path with its exact
        shapes as a one-off signature."""
        names = set(self.sample_shapes)
        got = {str(k) for k in inputs}
        if got != names:
            raise ValueError("inputs %s != declared %s"
                             % (sorted(got), sorted(names)))
        arrays, n = {}, None
        for name, a in inputs.items():
            a = np.asarray(a.asnumpy() if hasattr(a, "asnumpy") else a)
            want_rank = len(self.sample_shapes[name]) + 1
            if a.ndim != want_rank:
                raise ValueError(
                    "input %r must carry a leading sample dim: got shape %s "
                    "for sample shape %s" % (name, a.shape,
                                             self.sample_shapes[name]))
            if n is None:
                n = a.shape[0]
            elif a.shape[0] != n:
                raise ValueError("inconsistent sample counts across inputs")
            arrays[name] = a
        if n < 1:
            raise ValueError("request must carry at least one sample")
        if n > self.max_direct_batch:
            raise ValueError(
                "request with %d samples exceeds max_direct_batch=%d "
                "(one oversize request would stall the single device loop "
                "for everyone; chunk the request client-side)"
                % (n, self.max_direct_batch))
        padded = {}
        direct = n > self.ladder.max_batch
        for name, a in arrays.items():
            p = self.ladder.pad_shape(name, a.shape[1:],
                                      self.sample_shapes[name])
            if p is None:
                direct = True
                break
            padded[name] = p
        if direct:
            padded = {name: tuple(a.shape[1:])
                      for name, a in arrays.items()}
        return arrays, n, padded, direct

    # -- device loop ---------------------------------------------------------
    def _loop(self):
        reqs = ()
        try:
            self._beat()  # first heartbeat: the loop is live
            while True:
                item = self._batcher.next_batch()
                if item is None:
                    return
                self._beat()
                reqs, bucket = item
                if not reqs:
                    continue
                try:
                    self._dispatch(reqs, bucket)
                except Exception as e:  # degrade, never crash the loop
                    with self._stats_mu:
                        self._stats["failed"] += len(reqs)
                        self._stats["in_flight"] -= len(reqs)
                    for req in reqs:
                        if not req.done():
                            req.set_error(e)
                        self._finish_trace(req, "error")
                    if self._probe:
                        self._probe.record_drop("error", len(reqs))
                    if self._slo is not None:
                        for req in reqs:
                            self._slo.record_drop(
                                getattr(req, "klass", None))
                    if self._flightrec is not None:
                        # the black-box moment: a batch died under load —
                        # record the failure, then dump the recent past
                        self._flightrec.record("batch_error",
                                               engine=self.name,
                                               error=repr(e),
                                               requests=len(reqs))
                        self._flightrec.dump("batch_error", auto=True,
                                             engine=self.name,
                                             error=repr(e))
                reqs = ()
        except BaseException as e:
            # loop is dying (batcher invariant broke, or a BaseException
            # like SystemExit escaped _dispatch): fail the CURRENT batch —
            # already popped from the queue, so batcher.close() alone would
            # leave its waiters blocked forever — then fail the queue
            undone = [r for r in reqs if not r.done()]
            with self._stats_mu:
                self._stats["failed"] += len(undone)
                self._stats["in_flight"] -= len(undone)
            for req in undone:
                req.set_error(EngineClosed(
                    "device loop terminated: %r" % (e,)))
                self._finish_trace(req, "error")
            self._closed = True
            self._batcher.close()
            raise

    def _dispatch(self, reqs, bucket):
        # queue wait ends HERE, at dispatch — measured before predictor
        # build/compile and the forward, so the queue/execute histogram
        # split stays honest (cold-bucket bind + compile time belongs to
        # serve_execute_seconds, not to queue latency)
        queue_waits = [r.queue_seconds for r in reqs]
        label = self._bucket_label(bucket)
        # spans: the batch joins the FIRST traced request's trace (one batch
        # serves many requests but a chrome args dict carries one trace id);
        # every traced member still gets its queue span closed here and its
        # request root closed at reply, all sharing their own trace ids
        traced = [r for r in reqs if getattr(r, "_trace_root", None)]
        owner = traced[0] if traced else None
        batch_sp = tracing.span("dispatch",
                                parent=owner._trace_ctx if owner else None,
                                bucket=label, requests=len(reqs))
        for r in traced:
            r._trace_queue.finish(bucket=label)
        t0 = time.perf_counter()
        # heartbeat on dispatch ENTRY (not only exit): a single forward
        # longer than MXNET_OPS_STALE_S otherwise leaves the last beat at
        # the previous batch and /healthz flaps 503 mid-forward
        self._beat()
        with batch_sp:
            # busy across the cold-bucket predictor build/compile: a
            # first-request bind + XLA compile routinely exceeds the stale
            # threshold and runs OUTSIDE the device mutex, so without this
            # marker it reads as dead.  Cleared before the mutex wait — a
            # loop frozen waiting on _device_mu must still read stale.
            self._busy_since = time.monotonic()
            try:
                pred, fresh = self._predictor_for(bucket)
            finally:
                self._busy_since = None
            try:
                with tracing.span("assemble"):
                    arrays = self._assemble(reqs, bucket)
                with tracing.span("execute", compile=int(fresh)):
                    with self._device_mu:
                        # busy marker strictly INSIDE the mutex: a loop
                        # blocked waiting on _device_mu is NOT busy — a
                        # frozen engine must still read stale-and-dead
                        self._busy_since = time.monotonic()
                        try:
                            outs = pred.forward(**arrays)
                            outs = [o.asnumpy() for o in outs]  # sync
                        finally:
                            self._busy_since = None
            except Exception:
                self._uncompile(bucket, fresh)
                raise
            dt = time.perf_counter() - t0
            if fresh:
                self._note_compile(bucket, dt)
            total = sum(r.n for r in reqs)
            waste = self._padding_waste(reqs, bucket)
            with tracing.span("reply"):
                served_tier = pred._exec.precision_tier
                off = 0
                for req in reqs:
                    # reply tier label (ISSUE 17): stamped BEFORE the
                    # result event so a waiter that wakes on result() can
                    # immediately read which twin actually served it
                    req.tier = served_tier
                    req.set_result([o[off:off + req.n] for o in outs])
                    off += req.n
        for r in traced:
            r._trace_root.finish()
        self._beat()
        # per-request submit->reply latency: the SLO monitor's feed, the
        # flight-recorder lifecycle record, and the telemetry latency
        # histogram (all `is None`-gated — nothing here when the gates are
        # off beyond building the plain-list latencies for the probe)
        latencies = [r.latency_s for r in reqs]
        if self._slo is not None:
            for r, lat in zip(reqs, latencies):
                self._slo.record(lat, getattr(r, "klass", None))
        if self._flightrec is not None:
            for r, lat in zip(reqs, latencies):
                self._flightrec.record(
                    "serve", dur_s=lat, engine=self.name, n=r.n,
                    bucket=label, klass=getattr(r, "klass", None)
                    or "default")
        with self._stats_mu:
            self._stats["completed"] += len(reqs)
            self._stats["in_flight"] -= len(reqs)
            self._stats["batches"] += 1
            in_flight = self._stats["in_flight"]
            ent = self._bucket_stats.get(label)
            if ent is None:
                ent = self._bucket_stats[label] = [0, 0, 0.0]
            ent[0] += 1
            ent[1] += len(reqs)
            ent[2] += waste
        if self._probe:
            fill = total / float(bucket.batch)
            self._probe.record_batch(
                label, fill, waste, dt, queue_waits,
                in_flight, self._batcher.depth(), latencies=latencies)
        if self._quality is not None:
            try:
                self._quality_observe(reqs, arrays, outs, bucket, label,
                                      pred)
            except Exception:
                pass  # quality observation must never fail a served batch

    # -- quality plane (ISSUE 16) --------------------------------------------
    def _quality_observe(self, reqs, arrays, outs, bucket, label, pred):
        """Fold one completed batch into the quality plane (device loop,
        post-reply): per-tier output-distribution stats over the reply
        buffers the dispatch already materialized (zero extra device
        work), then — for twin-served batches only — systematic
        per-request sampling into the bounded shadow queue.  Never
        blocks: a full queue sheds the sample and counts it."""
        q = self._quality
        tier = pred._exec.precision_tier
        q.note_outputs(tier, outs)
        if tier == "fp32":
            return  # nothing to diverge from
        offsets, off = [], 0
        for req in reqs:
            if q.should_sample():
                offsets.append((off, req.n))
            off += req.n
        if not offsets:
            return
        with self._quality_cv:
            if len(self._quality_q) >= _QUALITY_QUEUE_MAX:
                q.note_shed(len(offsets))
                return
            self._quality_q.append(
                (bucket, label, tier, arrays, outs, offsets, pred))
            if self._quality_thread is None:
                self._quality_thread = threading.Thread(
                    target=self._quality_worker,
                    name="mxnet-quality-%s" % self.name, daemon=True)
                self._quality_thread.start()
            self._quality_cv.notify()

    def _quality_ref_for(self, bucket, pred):
        """The fp32 sibling serving this bucket's shapes — built once per
        bucket off the twin itself (shared weight buffers, so the shadow
        costs no extra HBM for weights; the tier is explicitly cleared,
        so an ambient MXNET_PRECISION_TIER cannot leak back in)."""
        ref = self._quality_ref.get(bucket.key)
        if ref is None:
            ref = pred.with_precision(None)
            self._quality_ref[bucket.key] = ref
        return ref

    def _quality_worker(self):
        """Shadow-replay loop: strictly lower priority than live dispatch
        — defers while the batcher holds queued work, takes ``_device_mu``
        only around its own forward (never on the reply path), and exits
        with the engine."""
        from ..graph_passes import precision as _precision

        q = self._quality
        while True:
            with self._quality_cv:
                while not self._quality_q and not self._closed:
                    self._quality_cv.wait(0.05)
                if self._closed:
                    return
                item = self._quality_q.popleft()
            # live work first: yield until the micro-batcher queue drains
            while not self._closed and self._batcher.depth() > 0:
                time.sleep(0.001)
            if self._closed:
                return
            try:
                self._quality_replay(q, item, _precision)
            except Exception:
                q.note_shed(len(item[5]))  # quality never crashes serving

    def _quality_replay(self, q, item, _precision):
        bucket, label, tier, arrays, outs, offsets, pred = item
        ref = self._quality_ref_for(bucket, pred)
        sites = pred._exec._int8_sites
        with self._device_mu:
            routs = ref.forward(**arrays)
            routs = [o.asnumpy() for o in routs]
            live = None
            if sites:
                # drift baseline follows the twin actually serving: a
                # re-calibrated rebuild changes the calibration
                # fingerprint and re-anchors the plane's baseline here
                cal = pred._exec._calibration
                skey = (id(pred._exec),
                        cal.fingerprint() if cal is not None else None)
                if skey != self._quality_sites_key:
                    q.set_drift_baseline(sites)
                    self._quality_sites_key = skey
                names = {d["input"] for d in sites.values()}
                live = _precision.observe_ranges(ref, arrays, names)
        tol = _precision.tier_tolerance(tier)
        for off, n in offsets:
            q.record_divergence(
                tier, label, [o[off:off + n] for o in outs],
                [o[off:off + n] for o in routs], tol, engine=self.name)
        if live:
            for site, d in sites.items():
                rng = live.get(d["input"])
                if rng is not None:
                    q.observe_site(site, rng[0], rng[1])

    @staticmethod
    def _padding_waste(reqs, bucket):
        """Fraction of padded input elements that carry no request data
        (batch-slot padding + spatial padding combined)."""
        real = sum(r.n * _volume(a.shape[1:])
                   for r in reqs for a in r.inputs.values())
        padded = sum(bucket.batch * _volume(s) for _, s in bucket.shapes)
        return 1.0 - real / padded if padded else 0.0

    def _assemble(self, reqs, bucket):
        arrays = {}
        for name, bshape in bucket.shapes:
            out = np.zeros((bucket.batch,) + bshape, np.float32)
            off = 0
            for req in reqs:
                a = req.inputs[name]
                region = (slice(off, off + req.n),) + tuple(
                    slice(0, d) for d in a.shape[1:])
                out[region] = a
                off += req.n
            arrays[name] = out
        return arrays

    # -- signature cache / warmup --------------------------------------------
    def _predictor_for(self, bucket):
        """-> (Predictor, fresh).  ``fresh`` marks a signature that has not
        taken its first forward yet: the forward about to run is the one
        XLA compile this signature pays (the telemetry compile counter
        counts exactly these).  Ladder signatures are pinned; direct
        (oversize, client-shaped) signatures live in a bounded LRU — an
        evicted one recompiles on return, counted honestly again."""
        with self._cache_mu:
            if bucket.direct:
                pred = self._direct_cache.get(bucket.key)
                if pred is None:
                    pred = self._proto.with_shapes(bucket.input_shapes())
                    self._direct_cache[bucket.key] = pred
                    while len(self._direct_cache) > _DIRECT_CACHE_MAX:
                        old, _ = self._direct_cache.popitem(last=False)
                        self._compiled.discard(old)
                else:
                    self._direct_cache.move_to_end(bucket.key)
            else:
                pred = self._cache.get(bucket.key)
                if pred is None:
                    pred = self._proto.with_shapes(bucket.input_shapes())
                    self._cache[bucket.key] = pred
            fresh = bucket.key not in self._compiled
            if fresh:
                self._compiled.add(bucket.key)
        if not fresh:
            with self._stats_mu:
                self._stats["cache_hits"] += 1
        return pred, fresh

    @staticmethod
    def _bucket_label(bucket):
        """Metric/stats label.  Direct signatures are client-shaped — per
        exact-shape labels would grow metric cardinality without bound
        (exactly the traffic the direct LRU defends against), so they all
        aggregate under one label."""
        return "direct" if bucket.direct else repr(bucket)

    def _note_compile(self, bucket, seconds):
        with self._stats_mu:
            self._stats["compiles"] += 1
        if self._probe:
            self._probe.record_compile(self._bucket_label(bucket), seconds)

    def _uncompile(self, bucket, fresh):
        """A fresh signature whose first forward FAILED never compiled —
        un-mark it so the successful retry's real compile is counted (the
        acceptance counter must track actual XLA compiles)."""
        if fresh:
            with self._cache_mu:
                self._compiled.discard(bucket.key)

    def _bind_bucket(self, bucket):
        """Bind (or fetch) a LADDER bucket's Predictor without touching the
        compile accounting — pure host work (symbol rebind over shared
        weight buffers), safe off the device loop.  The warmup lowering
        phase uses this so trace/lower can run concurrently while
        ``_predictor_for`` keeps sole ownership of freshness marking."""
        with self._cache_mu:
            pred = self._cache.get(bucket.key)
            if pred is None:
                pred = self._proto.with_shapes(bucket.input_shapes())
                self._cache[bucket.key] = pred
            return pred

    def _warm_bucket(self, bucket, handle=None):
        """Compile one bucket by running it on zeros (device-exclusive).
        ``compile_s`` covers bind + first forward, same as live dispatch.
        ``handle`` is an optional pre-lowered (or disk-restored) AOT handle
        from the warmup lowering phase: only its finalize (XLA backend
        compile — or nothing, on a persistent-cache hit) and the zeros
        forward run under the device mutex."""
        t0 = time.perf_counter()
        pred, fresh = self._predictor_for(bucket)
        cache = None
        lower_s = 0.0
        aot_compile_s = 0.0
        cp0 = None
        try:
            with self._device_mu:
                # busy marker (ISSUE 16 satellite): a warmup finalize +
                # first forward can legitimately exceed MXNET_OPS_STALE_S
                # — mark the mutex-holder busy so /healthz reads
                # slow-not-dead while this compiles
                self._busy_since = time.monotonic()
                try:
                    # compile plane (ISSUE 13): bracket this bucket's
                    # compile with the monotonic row counter INSIDE the
                    # device mutex — the window covers exactly this
                    # bucket's finalize + first forward, and the read
                    # below additionally pins rows to this predictor's
                    # executable identity, so a concurrent compile
                    # elsewhere in the process cannot be mis-attributed
                    if costplane.enabled():
                        cp0 = costplane.row_count()
                    if handle is not None:
                        info = pred.aot_finalize(handle)
                        # "cached" = already live in this process (a
                        # re-warmup): neither a disk restore nor a fresh
                        # compile
                        cache = {"compile": "miss", "disk": "hit"}.get(
                            info["source"])
                        lower_s = info.get("lower_s", 0.0)
                        aot_compile_s = info.get("compile_s", 0.0)
                    outs = pred.forward(
                        **{n: np.zeros((bucket.batch,) + s, np.float32)
                           for n, s in bucket.shapes})
                    for o in outs:
                        o.asnumpy()
                    crows = ()
                    if cp0 is not None:
                        # still under _device_mu: rows since cp0 that
                        # carry THIS predictor executable's logical key
                        # are this bucket's compile (a concurrent
                        # train-thread compile has a different key and is
                        # filtered out)
                        fwd = pred._exec._fwd_cache.get(False)
                        want = getattr(fwd, "_key", None)
                        crows = [r for r in costplane.rows_since(
                                     cp0, site="executor_fwd")
                                 if want is None
                                 or r["logical_key"] == want]
                finally:
                    self._busy_since = None
        except Exception:
            self._uncompile(bucket, fresh)
            raise
        dt = time.perf_counter() - t0
        if fresh and cache != "hit":
            # a disk-restored bucket took no XLA compile: stats()["compiles"]
            # and serve_compiles_total count actual compiles only, so a warm
            # restart reports 0 (the restore shows up as warmup cache_hits)
            self._note_compile(bucket, dt)
        # graph-pass result for this bucket's inference plan (ISSUE 7):
        # nodes captured vs nodes compiled — None when MXNET_GRAPH_PASSES
        # is off (the predictor lowered the raw plan)
        ps = pred.pass_stats().get("eval")
        # graph-IR analyzer diagnostics over the same plan (ISSUE 8): the
        # count only — ``pred.check()`` returns the full list on demand;
        # None when MXNET_GRAPH_ANALYZERS is off (check is never invoked
        # and the analysis package is never imported — the off path is
        # this one env read).  Under the same gate the bucket's cast-plan
        # verdict histogram rides along (ISSUE 11): how much of this plan
        # the bf16 twin tier could drop to low precision.
        if env_flag("MXNET_GRAPH_ANALYZERS"):
            from .. import analysis
            from ..analysis import numerics as _numerics

            # one GraphContext for both surfaces: analyze() memoizes the
            # numerics abstract walk on the ctx, so the cast-plan read
            # below reuses it instead of walking the plan a second time
            ctx = analysis.executor_context(pred._exec, is_train=False)
            checked = len(analysis.analyze(ctx))
            try:
                verdicts = _numerics.precision_plan(ctx).counts()
            except Exception:
                # same degradation stance as the analyzers: a plan the
                # numerics walk cannot handle must not fail warmup
                verdicts = None
        else:
            checked = verdicts = None
        # the compile-plane row this warm produced (captured above, inside
        # the mutex + keyed to this executable; a warm restart / re-warm
        # records nothing and the columns stay None)
        xla_flops = xla_peak = None
        if cp0 is not None and crows:
            xla_flops = crows[-1]["flops"]
            xla_peak = crows[-1]["peak_bytes"]
        return {"bucket": repr(bucket), "fresh": fresh,
                "compile_s": round(dt, 4) if fresh else 0.0,
                "lower_s": round(lower_s, 4),
                # pure XLA backend-compile seconds (0 on a disk restore —
                # wall-clock rows above include bind + zeros forward)
                "aot_compile_s": round(aot_compile_s, 4), "cache": cache,
                "graph_nodes_pre": ps["nodes_pre"] if ps else None,
                "graph_nodes_post": ps["nodes_post"] if ps else None,
                "check_warnings": checked,
                "precision_verdicts": verdicts,
                # the tier this bucket's plan compiled under (ISSUE 15):
                # "fp32" unless MXNET_PRECISION_TIER rewrote it — always
                # present, so mixed-tier fleets read straight off /statusz
                "precision_tier": pred._exec.precision_tier,
                # XLA-measured cost of this bucket's executable (ISSUE 13;
                # None with MXNET_COSTPLANE off, on a cache hit, or when
                # the backend reports nothing — the partial-row contract)
                "xla_flops": xla_flops, "xla_peak_bytes": xla_peak}

    def _note_warmup(self, report, total_s):
        """Record the warmup pass for ``stats()["warmup"]`` (always on, so
        operators see restart health without telemetry) and the telemetry
        registry/event stream (when enabled)."""
        hits = sum(1 for r in report if r.get("cache") == "hit")
        misses = sum(1 for r in report if r.get("cache") == "miss")
        checked = [r.get("check_warnings") for r in report]
        n_diags = (sum(v for v in checked if v is not None)
                   if any(v is not None for v in checked) else None)
        # cast-plan verdicts summed across buckets (ISSUE 11) — None when
        # the analyzer gate is off (no row carried a histogram)
        vrows = [r.get("precision_verdicts") for r in report]
        vrows = [v for v in vrows if v]
        verdicts = None
        if vrows:
            verdicts = {}
            for v in vrows:
                for k, n in v.items():
                    verdicts[k] = verdicts.get(k, 0) + n
        # XLA-measured cost across the warmed ladder (ISSUE 13): flops sum
        # + peak max over buckets whose warm produced a compile-plane row —
        # None when no row carried the number (gate off / all cache hits)
        wfl = [r.get("xla_flops") for r in report
               if r.get("xla_flops") is not None]
        wpk = [r.get("xla_peak_bytes") for r in report
               if r.get("xla_peak_bytes") is not None]
        # precision tier across the warmed ladder (ISSUE 15): one value
        # when every bucket compiled the same tier (the normal case —
        # buckets snapshot the same gate), "mixed" if a fleet ever serves
        # heterogeneous twins through one engine
        tiers = {r.get("precision_tier") or "fp32" for r in report}
        with self._stats_mu:
            self._warmup = {
                "buckets": len(report),
                "fresh": sum(1 for r in report if r["fresh"]),
                "cache_hits": hits, "cache_misses": misses,
                "lower_s": round(sum(r.get("lower_s", 0.0) for r in report), 4),
                "compile_s": round(sum(r["compile_s"] for r in report), 4),
                # pure XLA compile seconds this pass paid — the number a
                # warm restart drives to 0.0 (ci/check_aot_cache.py asserts)
                "aot_compile_s": round(sum(r.get("aot_compile_s", 0.0)
                                           for r in report), 4),
                # graph-IR analyzer diagnostics across all warmed buckets
                # (ISSUE 8) — None when MXNET_GRAPH_ANALYZERS is off
                "check_warnings": n_diags,
                # cast-plan verdict histogram across all warmed buckets
                # (ISSUE 11) — same gate, same None-when-off contract
                "precision_verdicts": verdicts,
                # the ladder's compiled tier (ISSUE 15; always present) —
                # the one-value/"mixed" summary string, kept for
                # compatibility; the per-bucket map below is what the
                # quality plane / tier router key on (ISSUE 16 satellite)
                "precision_tier": (set(tiers).pop() if len(tiers) == 1
                                   else "mixed"),
                "precision_tiers": {
                    r["bucket"]: r.get("precision_tier") or "fp32"
                    for r in report},
                "xla_flops": sum(wfl) if wfl else None,
                "xla_peak_bytes": max(wpk) if wpk else None,
                "total_s": round(total_s, 4)}
        if self._probe:
            self._probe.record_warmup(len(report), hits, misses, total_s)

    def warmup(self, buckets=None, max_workers=None):
        """Pre-compile the bucket ladder (see ``serving.warmup`` for the
        module-level helper and recipe) -> per-bucket report list."""
        from .warmup import warmup_engine

        return warmup_engine(self, buckets=buckets, max_workers=max_workers)

    # -- introspection -------------------------------------------------------
    def _on_drop(self, req, reason):
        with self._stats_mu:
            if reason == "timeout":
                self._stats["timeouts"] += 1
            elif reason == "cancelled":
                self._stats["cancelled"] += 1
            if reason in ("timeout", "cancelled", "closed"):
                self._stats["in_flight"] -= 1
        if self._probe:
            self._probe.record_drop(reason)
        # SLO accounting: timeouts/closed are violations the server owns;
        # a client cancel is the client's choice (nginx's 499 stance) and
        # does not burn the error budget.  Sheds are counted at submit.
        if self._slo is not None and reason in ("timeout", "closed"):
            self._slo.record_drop(getattr(req, "klass", None))
        if self._flightrec is not None:
            self._flightrec.record("drop", engine=self.name, reason=reason,
                                   n=req.n,
                                   klass=getattr(req, "klass", None)
                                   or "default")
        self._finish_trace(req, reason)

    @staticmethod
    def _finish_trace(req, drop=None):
        """Close a traced request's open spans; the drop reason lands on the
        span so a reaped 504 is visible as a causal timeline (idempotent —
        already-closed spans ignore it)."""
        root = getattr(req, "_trace_root", None)
        if root is None:
            return
        if drop is None:
            req._trace_queue.finish()
            root.finish()
        else:
            req._trace_queue.finish(drop=drop)
            root.finish(drop=drop)

    def stats(self):
        """Point-in-time engine counters (always available; the telemetry
        registry carries the same signals as proper metrics when enabled)."""
        with self._stats_mu:
            out = dict(self._stats)
            # buckets: label -> batch count (the long-standing surface);
            # bucket_stats: the tuner/operator view (ISSUE 9) — per-bucket
            # request hit counts and mean padding waste, no telemetry
            # scraping required
            out["buckets"] = {k: v[0] for k, v in self._bucket_stats.items()}
            out["bucket_stats"] = {
                k: {"batches": v[0], "requests": v[1],
                    "padding_waste": round(v[2] / v[0], 4) if v[0] else 0.0}
                for k, v in self._bucket_stats.items()}
            out["warmup"] = dict(self._warmup) if self._warmup else None
        out["shed"] = self.admission.shed_total
        out["queue_depth"] = self._batcher.depth()
        with self._cache_mu:
            out["cache_size"] = len(self._cache) + len(self._direct_cache)
        out["ladder"] = [repr(b) for b in
                         self.ladder.signatures(self.sample_shapes)]
        # the tier this engine's plans compile under (ISSUE 15): "fp32"
        # unless MXNET_PRECISION_TIER rewrote them — the SERVE_BENCH /
        # /statusz discriminator.  The per-bucket map (ISSUE 16
        # satellite) exposes what each BOUND ladder bucket's executor
        # actually serves, so the quality plane and the future tier
        # router never re-derive it; the summary string stays one value
        # ("mixed" when heterogeneous) for compatibility.
        with self._cache_mu:
            tier_map = {
                repr(b): self._cache[b.key]._exec.precision_tier
                for b in self.ladder.signatures(self.sample_shapes)
                if b.key in self._cache}
        out["precision_tiers"] = tier_map
        tiers = set(tier_map.values())
        out["precision_tier"] = (tiers.pop() if len(tiers) == 1
                                 else "mixed" if tiers
                                 else self._proto._exec.precision_tier)
        # live ops plane (ISSUE 10): the streaming SLO block (None when
        # MXNET_SLO is off — the monitor never exists) and the device-loop
        # heartbeat age (None until the loop first ticks).  Both read
        # outside _stats_mu: the monitor has its own lock, the heartbeat
        # is a single-writer float.
        out["slo"] = self._slo.status() if self._slo is not None else None
        # compile plane (ISSUE 13): what XLA built in this process — row
        # counts per site, flop/peak aggregates, degradation/drift counts
        # (process-global like flightrec; None when MXNET_COSTPLANE is off
        # — the off path is this one env read)
        out["costplane"] = costplane.status() if costplane.enabled() \
            else None
        # inference quality plane (ISSUE 16): shadow-divergence ring
        # summary + calibration-drift state — None when
        # MXNET_QUALITYPLANE is off (the plane never exists)
        out["quality"] = (self._quality.status()
                          if self._quality is not None else None)
        hb = self._heartbeat
        out["heartbeat_age_s"] = (round(max(0.0, time.monotonic() - hb), 3)
                                  if hb is not None else None)
        busy = self._busy_since
        out["busy_in_dispatch_s"] = (
            round(max(0.0, time.monotonic() - busy), 3)
            if busy is not None else None)
        return out
