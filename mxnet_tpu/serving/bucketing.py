"""Shape-bucket ladder — the pure shape math under the micro-batcher.

TPU serving lives and dies by compile-count: every distinct input shape is a
new XLA executable (SURVEY §7.3), so an online engine that forwarded raw
request shapes would recompile per traffic pattern.  The fix (same move as
TVM's ahead-of-time shape specialization, PAPERS.md) is a finite **bucket
ladder**: each request is padded UP to the nearest configured bucket on the
batch dim (and optionally on per-sample dims), so the whole traffic mix
resolves to ``len(ladder)`` compiled signatures, all precompilable at
startup (``serving.warmup``).

This module is policy-free shape arithmetic: no threads, no jax, no env
vars — the Engine owns those.
"""
from __future__ import annotations

import itertools

__all__ = ["Bucket", "BucketLadder", "pow2_ladder"]


def pow2_ladder(max_value, start=1):
    """Powers of two from ``start`` up to and including ``max_value``
    (``max_value`` itself is appended when it is not a power of two):
    ``pow2_ladder(12) -> (1, 2, 4, 8, 12)``."""
    if max_value < 1:
        raise ValueError("max_value must be >= 1, got %r" % (max_value,))
    out = []
    v = max(1, int(start))
    while v < max_value:
        out.append(v)
        v *= 2
    out.append(int(max_value))
    return tuple(out)


class Bucket:
    """One compiled signature: a batch capacity + per-input padded sample
    shapes (sample shape = the request array shape WITHOUT the leading
    sample-count dim).  Hashable — the signature-cache key."""

    __slots__ = ("batch", "shapes", "direct")

    def __init__(self, batch, shapes, direct=False):
        self.batch = int(batch)
        # canonical order so dict-ordering differences can't split the cache
        self.shapes = tuple(sorted(
            (str(n), tuple(int(d) for d in s)) for n, s in dict(shapes).items()))
        self.direct = bool(direct)

    @property
    def key(self):
        return (self.batch, self.shapes)

    def input_shapes(self):
        """name -> full input shape (batch dim included) for Predictor."""
        return {n: (self.batch,) + s for n, s in self.shapes}

    def sample_shape(self, name):
        return dict(self.shapes)[name]

    def __hash__(self):
        return hash(self.key)

    def __eq__(self, other):
        return isinstance(other, Bucket) and self.key == other.key

    def __repr__(self):
        dims = ",".join("%s=%s" % (n, "x".join(map(str, s)) or "scalar")
                        for n, s in self.shapes)
        return "b%d[%s]%s" % (self.batch, dims,
                              ":direct" if self.direct else "")


class BucketLadder:
    """The configured bucket set.

    Parameters
    ----------
    batch_sizes : sequence of int
        Allowed batch capacities, e.g. ``(1, 2, 4, 8)``.  A formed batch of
        n samples is zero-padded up to the smallest capacity >= n.
    shape_buckets : dict, optional
        ``input name -> sequence of candidate per-sample shapes``.  A request
        sample shape is padded (zeros, trailing) up to the smallest candidate
        that dominates it in every dim.  Inputs without an entry admit only
        their exact base sample shape — one spatial class, zero padding.
    """

    def __init__(self, batch_sizes=(1, 2, 4, 8), shape_buckets=None):
        sizes = sorted({int(b) for b in batch_sizes})
        if not sizes or sizes[0] < 1:
            raise ValueError("batch_sizes must be positive ints, got %r"
                             % (batch_sizes,))
        self.batch_sizes = tuple(sizes)
        self.shape_buckets = {}
        for name, cands in (shape_buckets or {}).items():
            cands = [tuple(int(d) for d in s) for s in cands]
            if not cands:
                raise ValueError("empty shape bucket list for %r" % name)
            ndims = {len(s) for s in cands}
            if len(ndims) != 1:
                raise ValueError(
                    "shape buckets for %r mix ranks: %s" % (name, cands))
            # sorted by volume so "smallest dominating" is a forward scan
            self.shape_buckets[name] = tuple(sorted(
                set(cands), key=lambda s: (_volume(s), s)))

    @property
    def max_batch(self):
        return self.batch_sizes[-1]

    def pad_batch(self, n):
        """Smallest configured capacity >= n; None when n exceeds the top
        bucket (the caller direct-dispatches)."""
        for b in self.batch_sizes:
            if b >= n:
                return b
        return None

    def pad_shape(self, name, shape, base_shape):
        """Padded per-sample shape for one input; None = no bucket fits
        (direct dispatch).  ``base_shape`` is the engine's declared sample
        shape, the only admissible class for un-bucketed inputs."""
        shape = tuple(int(d) for d in shape)
        cands = self.shape_buckets.get(name)
        if cands is None:
            return shape if shape == tuple(base_shape) else None
        for cand in cands:
            if len(cand) == len(shape) and all(
                    c >= d for c, d in zip(cand, shape)):
                return cand
        return None

    def bucket_for(self, sample_shapes, n):
        """The ladder bucket holding ``n`` samples of the given (already
        padded) per-sample shapes; None when n exceeds the top batch."""
        b = self.pad_batch(n)
        if b is None:
            return None
        return Bucket(b, sample_shapes)

    def signatures(self, base_sample_shapes):
        """Every compiled signature this ladder can produce — the warmup
        set, and the exact per-stream compile count the acceptance test
        asserts.  Cartesian product of batch sizes x per-input shape
        candidates (un-bucketed inputs contribute their single base shape)."""
        names = sorted(base_sample_shapes)
        per_input = []
        for n in names:
            cands = self.shape_buckets.get(n)
            per_input.append(cands if cands is not None
                             else (tuple(base_sample_shapes[n]),))
        out = []
        for b in self.batch_sizes:
            for combo in itertools.product(*per_input):
                out.append(Bucket(b, dict(zip(names, combo))))
        return out


def _volume(shape):
    v = 1
    for d in shape:
        v *= int(d)
    return v
