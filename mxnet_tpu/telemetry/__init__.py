"""mxnet_tpu.telemetry — unified runtime-metrics subsystem (ISSUE 1).

One typed registry (Counter / Gauge / Histogram, labeled), pluggable sinks
(JSONL event log, Prometheus text exposition, chrome-trace profiler bridge,
TensorBoard), and instrumentation wired into the hot paths: the gluon
train step and Module fit loop (step wall time, data-wait, samples/s,
loss), jit compile tracking, per-device HBM gauges, and bytes-moved
counters in kvstore/collectives.  The Pallas custom-call cost registry
(``ops/pallas_kernels.py``) plus ``tools/trace_summary.py`` restore
roofline accounting for kernels XLA cost analysis cannot see.

Everything gates on ``MXNET_TELEMETRY`` — unset/0 means every helper is an
identity/no-op and the train-step path is byte-identical to a build without
telemetry.  See docs/OBSERVABILITY.md for the JSONL schema and recipes.
"""
from . import tracing
from .registry import (Counter, Gauge, Histogram, MetricError, Registry,
                       DEFAULT_BUCKETS)
from . import flightrec, ops_server, slo  # live ops plane (ISSUE 10)
from . import trainhealth  # training health plane (ISSUE 12)
from . import costplane  # compile plane (ISSUE 13)
from . import qualityplane  # inference quality plane (ISSUE 16)
from . import podplane  # pod observability plane (ISSUE 19)
from .sinks import (JsonlSink, PrometheusSink, ProfilerSink, Sink,
                    TensorBoardSink, iter_scalar_samples, render_prometheus)
from .instrument import (RouterProbe, ServeProbe, StepProbe, add_sink,
                         array_nbytes,
                         counter, enabled, event, flush, gauge, histogram,
                         instrument_step, interval_s, jsonl_path,
                         note_analysis_finding, note_aot_cache,
                         note_autotune_cache,
                         note_autotune_ranked,
                         note_autotune_trial, note_bytes,
                         note_compile, note_dispatch, note_fused_fallback,
                         note_graph_passes, note_lockcheck_violation,
                         note_nonfinite, note_slo_breach, note_train_step,
                         registry, router_probe, sample_memory, serve_probe,
                         step_probe, summary)

__all__ = [
    "tracing", "flightrec", "ops_server", "slo", "trainhealth", "costplane",
    "qualityplane", "podplane",
    "Counter", "Gauge", "Histogram", "MetricError", "Registry",
    "DEFAULT_BUCKETS",
    "Sink", "JsonlSink", "PrometheusSink", "ProfilerSink", "TensorBoardSink",
    "iter_scalar_samples", "render_prometheus",
    "RouterProbe", "ServeProbe", "StepProbe", "add_sink", "array_nbytes",
    "counter",
    "enabled", "event", "flush", "gauge", "histogram", "instrument_step",
    "interval_s", "jsonl_path", "note_analysis_finding", "note_aot_cache",
    "note_autotune_cache", "note_autotune_ranked",
    "note_autotune_trial", "note_bytes", "note_compile",
    "note_dispatch", "note_fused_fallback", "note_graph_passes",
    "note_lockcheck_violation", "note_nonfinite", "note_slo_breach",
    "note_train_step",
    "registry", "router_probe", "sample_memory",
    "serve_probe", "step_probe", "summary",
]
