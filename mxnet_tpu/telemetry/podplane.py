"""Pod observability plane — cross-rank metric aggregation, ledger-
divergence detection, and fleet-wide incident correlation (ISSUE 19).

Every observability plane before this one (PR 1 registry, PR 4 tracing,
PR 10 ops server/SLO/flightrec, PR 12 health plane, PR 13 costplane) is
process-local: rank 0 sees its own registry plus two lag gauges.  Under
``MXNET_POD_METRICS=1`` on a ``jax.distributed``-initialized pod this
module crosses the process boundary:

* every non-zero rank periodically **pushes a compact snapshot** — the
  registry's counters/gauges, a mergeable log-bucketed step-latency
  histogram (the ``slo.py`` sub-histogram encoding, so quantiles merge
  EXACTLY by vector addition), the ``/healthz`` verdict, the freshest
  engine heartbeat age, the flight-recorder arm state, and the costplane
  ledger's per-stable-key cost fingerprints (flops / bytes / compile
  seconds per ``row_key``) — over one persistent stdlib-socket line
  protocol to rank 0 (``MXNET_POD_METRICS_ADDR``; default derived from
  ``MXNET_COORDINATOR`` host at coordinator-port + 1000).  Push failures
  count into ``pod_push_failures_total`` and degrade — a dead aggregator
  never blocks or fails a training step (the JsonlSink stance).
* **rank 0 aggregates**: pushed counters/gauges become rank-labeled
  ``pod_*`` gauge series on the existing registry, the per-rank state
  feeds a new ``/podz`` ops-server endpoint (per-rank table + fleet
  rollup + skew stats), a **ledger-divergence detector** fires when two
  ranks report different cost fingerprints for the SAME stable key
  (``pod_ledger_divergence_total`` + a flight-recorder dump naming the
  key and both ranks — ROADMAP item 2's "prove every rank compiled the
  same program"), and **straggler verdicts** are emitted as
  edge-triggered events with hysteresis when a rank's step lag or push
  age crosses ``MXNET_POD_STRAGGLER_LAG`` / ``MXNET_POD_STRAGGLER_AGE_S``
  (signal only — the checkpoint-and-rejoin policy stays item 2's work).
* **incident correlation**: a pushed SLO-breach increase, a nonfinite
  census hit, a ledger divergence, or a push-detected rank death mints a
  shared incident id on rank 0; the id rides every push *response* back
  to the fleet, and each rank tags a flight-recorder dump with it —
  ``tools/pod_status.py`` collects and merges those dumps onto one
  timeline via the ``trace_merge`` clock-sync machinery.

Stale snapshots are dropped, not merged: each pusher carries a process
*incarnation* epoch plus a monotonic sequence number, so a restarted rank
supersedes its old series and a late out-of-order push from the previous
incarnation counts into ``pod_snapshots_stale_total`` instead of
clobbering fresh state.

Gating: :func:`plane` returns None when ``MXNET_POD_METRICS`` is unset —
call sites keep one ``is None`` check, no socket and no thread exist, and
the fit-loop step path is byte-identical (the PR 1/4/10 zero-overhead
contract, tested in ``tests/test_podplane.py``).  Pushes happen inline
from ``note_step`` under a throttle (``MXNET_POD_PUSH_S``) — the
trainhealth heartbeat discipline; no background pusher thread exists, so
a rank wedged mid-step stops pushing, which is exactly the straggler /
death signal rank 0 is listening for.
"""
from __future__ import annotations

import collections
import json
import os
import socket
import socketserver
import threading
import time

from ..base import env_flag
from .slo import NBUCKETS, WindowedQuantile, quantile_of_counts

__all__ = ["enabled", "push_interval_s", "straggler_lag_steps",
           "straggler_age_s", "death_age_s", "pod_addr", "build_snapshot",
           "Aggregator", "PodPlane", "plane", "podz", "status",
           "PROTOCOL_V"]

PROTOCOL_V = 1
MAX_LINE_BYTES = 4 << 20    # one pushed snapshot line; larger is dropped
MAX_MIRROR_SERIES = 512     # registry series mirrored per rank (cap)
MAX_INCIDENTS = 64          # bounded incident history on rank 0
INCIDENT_BROADCAST = 8      # most recent ids carried per push response
SOCK_TIMEOUT_S = 2.0        # connect/send/recv bound for one push
MIN_INCIDENT_S = 30.0       # per (rank, reason) mint throttle


def enabled():
    """``MXNET_POD_METRICS`` gate (docs/ENV_VARS.md) — default OFF."""
    return env_flag("MXNET_POD_METRICS")


def push_interval_s():
    """Seconds between snapshot pushes (``MXNET_POD_PUSH_S``, default 5).
    ``0`` pushes on every ``note_step`` (tests/CI)."""
    try:
        v = float(os.environ.get("MXNET_POD_PUSH_S", "5"))
    except ValueError:
        return 5.0
    return v if v >= 0 else 5.0


def straggler_lag_steps():
    """Step-lag threshold for the straggler verdict
    (``MXNET_POD_STRAGGLER_LAG``, default 50 steps behind the fleet
    head).  Recovery requires dropping below HALF this (hysteresis) so a
    rank oscillating at the threshold emits one verdict, not a storm."""
    try:
        v = int(os.environ.get("MXNET_POD_STRAGGLER_LAG", "50"))
    except ValueError:
        return 50
    return v if v > 0 else 50


def straggler_age_s():
    """Push-age threshold for the straggler verdict
    (``MXNET_POD_STRAGGLER_AGE_S``; default ``max(15, 3 x push
    interval)`` so a healthy pusher can never trip it on cadence alone).
    Recovery threshold is half (hysteresis)."""
    raw = os.environ.get("MXNET_POD_STRAGGLER_AGE_S", "").strip()
    if raw:
        try:
            v = float(raw)
            if v > 0:
                return v
        except ValueError:
            pass
    return max(15.0, 3.0 * push_interval_s())


def death_age_s():
    """Push age past which a previously-pushing rank is presumed dead
    (mints a ``rank_death`` incident): 3x the straggler age threshold."""
    return 3.0 * straggler_age_s()


def rejoin_margin_steps():
    """``MXNET_ELASTIC_REJOIN_MARGIN`` (default 8): steps past the fleet
    head at which every rank checkpoints-and-rejoins after a straggler
    incident (ISSUE 20).  The margin buys the slow rank time to observe
    the incident (it rides a push response) while every rank still passes
    through the SAME agreed step boundary — the collective checkpoint
    save needs an identical step index on all ranks."""
    try:
        v = int(os.environ.get("MXNET_ELASTIC_REJOIN_MARGIN", "8"))
    except ValueError:
        return 8
    return v if v > 0 else 8


def pod_addr():
    """(host, port) of the rank-0 aggregation channel, or None.

    ``MXNET_POD_METRICS_ADDR`` (``host:port``) wins; otherwise derived
    from ``MXNET_COORDINATOR`` — the coordinator host (process 0's, which
    is also where the aggregator lives) at coordinator-port + 1000.  A
    malformed value returns None: the plane then runs without a channel
    (rank 0 still aggregates itself; pushers count failures)."""
    raw = os.environ.get("MXNET_POD_METRICS_ADDR", "").strip()
    if not raw:
        coord = os.environ.get("MXNET_COORDINATOR", "").strip()
        if not coord or ":" not in coord:
            return None
        host, _, p = coord.rpartition(":")
        try:
            return (host or "127.0.0.1"), int(p) + 1000
        except ValueError:
            return None
    if ":" not in raw:
        return None
    host, _, p = raw.rpartition(":")
    try:
        return (host or "127.0.0.1"), int(p)
    except ValueError:
        return None


def _dist():
    """(rank, world size) — (0, 1) in single-process runs and whenever
    jax is absent/uninitialized (the trainhealth ``_dist`` stance: the
    plane must never be the thing that initializes a backend)."""
    import sys

    if "jax" not in sys.modules:
        return 0, 1
    try:
        import jax

        n = jax.process_count()
        if n <= 1:
            return 0, 1
        return jax.process_index(), n
    except Exception:
        return 0, 1


# -- snapshot (what one rank ships) -------------------------------------------
def build_snapshot(rank, size, epoch, seq, steps, step_counts,
                   extra_ledger=None):
    """One rank's wire snapshot dict.  Every block degrades independently
    (a failed surface ships as None/empty) — building a snapshot must
    never fail the step that triggered it."""
    from . import costplane, flightrec, instrument, ops_server, trainhealth

    metrics = []
    try:
        if instrument.enabled():
            for m in instrument.registry().collect():
                if m["type"] == "histogram":
                    continue  # scalar series only; quantiles ride step_hist
                for s in m["samples"]:
                    metrics.append([m["name"], m["type"], s["labels"],
                                    s["value"]])
    except Exception:
        metrics = []
    healthz, hb_age, slo_breaches = None, None, 0
    try:
        engines = ops_server._live_engines()
        if engines:
            checks = [ops_server.engine_health(e) for e in engines]
            healthz = {"ok": all(c["ok"] for c in checks),
                       "engines": [{"engine": c["engine"], "ok": c["ok"],
                                    "heartbeat_age_s": c["heartbeat_age_s"]}
                                   for c in checks]}
            ages = [c["heartbeat_age_s"] for c in checks
                    if c["heartbeat_age_s"] is not None]
            hb_age = min(ages) if ages else None
            for e in engines:
                try:
                    for o in (e.stats().get("slo") or {}).get(
                            "objectives", ()):
                        slo_breaches += int(o.get("breaches") or 0)
                except Exception:
                    pass
    except Exception:
        healthz = None
    nonfinite = 0
    try:
        th = trainhealth.status()
        if th and not isinstance(th.get("trips"), dict):
            nonfinite = int(th.get("trips") or 0)
    except Exception:
        nonfinite = 0
    ledger = {}
    try:
        if costplane.enabled():
            for r in costplane.rows():
                ledger[r["key"]] = [r.get("flops"), r.get("bytes_accessed"),
                                    r.get("compile_s")]
    except Exception:
        ledger = {}
    if extra_ledger:
        ledger.update(extra_ledger)
    return {"v": PROTOCOL_V, "rank": int(rank), "size": int(size),
            "epoch": round(float(epoch), 6), "seq": int(seq),
            "unix_ts": round(time.time(), 6), "steps": int(steps),
            "step_hist": list(step_counts), "metrics": metrics,
            "healthz": healthz, "heartbeat_age_s": hb_age,
            "flightrec": flightrec.enabled(), "ledger": ledger,
            "slo_breaches": int(slo_breaches), "nonfinite": int(nonfinite)}


def _fingerprint_differs(a, b):
    """Two ledger entries ([flops, bytes, compile_s]) disagree on program
    COST IDENTITY — flops and bytes only.  compile_s is wall time and
    legitimately differs across hosts; it is carried for the /podz skew
    stats, never for the divergence verdict."""
    return list(a[:2]) != list(b[:2])


# -- rank-0 aggregation state -------------------------------------------------
class Aggregator:
    """Rank 0's fold of every rank's snapshots + the detectors.

    Thread-safe (listener connection threads and the local fit loop both
    ingest).  Keeps its own plain-int counters so /podz is authoritative
    even with ``MXNET_TELEMETRY`` off; mirrors into the registry (and the
    flight recorder / JSONL event stream) only when those gates are on.
    ``now``/monotonic parameters exist so tests drive a synthetic clock.
    """

    def __init__(self, size=1, my_rank=0):
        self._mu = threading.Lock()
        self.size = int(size)
        self.my_rank = int(my_rank)
        self._ranks = {}         # rank -> last accepted snapshot state
        self._diverged = {}      # ledger key -> divergence detail
        self._incidents = collections.deque(maxlen=MAX_INCIDENTS)
        self._last_incident = {}  # (rank, reason) -> monotonic of last mint
        self._inc_seq = 0
        self.stale_dropped = 0
        self.divergences = 0
        self.straggler_verdicts = 0
        self.mirror_dropped = 0

    # -- ingest ---------------------------------------------------------------
    def ingest(self, snap, now=None):
        """Fold one snapshot → {"ok": bool, "reason": ...}.  A snapshot
        from an older incarnation (smaller epoch) or an out-of-order push
        (same epoch, non-increasing seq) is DROPPED with a counter — a
        restarted rank supersedes its past, never the reverse."""
        now = time.monotonic() if now is None else now
        try:
            rank = int(snap["rank"])
            epoch = float(snap["epoch"])
            seq = int(snap["seq"])
        except (KeyError, TypeError, ValueError):
            return {"ok": False, "reason": "malformed"}
        with self._mu:
            prev = self._ranks.get(rank)
            if prev is not None:
                if epoch < prev["epoch"] or (epoch == prev["epoch"]
                                             and seq <= prev["seq"]):
                    self.stale_dropped += 1
                    self._count("pod_snapshots_stale_total",
                                "pushed snapshots dropped as stale (older "
                                "incarnation epoch or out-of-order seq)",
                                rank=str(rank))
                    return {"ok": False, "reason": "stale"}
            self._ranks[rank] = {
                "epoch": epoch, "seq": seq,
                "unix_ts": snap.get("unix_ts"),
                "recv_mono": now,
                "steps": int(snap.get("steps") or 0),
                "step_hist": list(snap.get("step_hist") or ()),
                "healthz": snap.get("healthz"),
                "heartbeat_age_s": snap.get("heartbeat_age_s"),
                "flightrec": bool(snap.get("flightrec")),
                "ledger": dict(snap.get("ledger") or {}),
                "slo_breaches": int(snap.get("slo_breaches") or 0),
                "nonfinite": int(snap.get("nonfinite") or 0),
                "metrics": list(snap.get("metrics") or ()),
                "straggler": (prev or {}).get("straggler", False),
                "dead": False,
                "last_slo": (prev or {}).get("last_slo"),
                "last_nonfinite": (prev or {}).get("last_nonfinite"),
            }
        self._mirror(rank, snap)
        self.detect(now=now)
        return {"ok": True, "reason": None}

    def _mirror(self, rank, snap):
        """Pushed counters/gauges → ``pod_<name>{...orig labels, rank}``
        gauge series on the local registry (counters become gauges: a
        pushed cumulative resets with its rank's incarnation, so rank 0
        must never treat it as locally monotonic).  Bounded per rank;
        overflow counts, never grows without limit."""
        from . import instrument

        if not instrument.enabled():
            return
        r = instrument.registry()
        n = 0
        for item in snap.get("metrics") or ():
            try:
                name, typ, labels, value = item
                if typ not in ("counter", "gauge"):
                    continue
                n += 1
                if n > MAX_MIRROR_SERIES:
                    with self._mu:
                        self.mirror_dropped += 1
                    self._count("pod_series_dropped_total",
                                "pushed series beyond the per-rank mirror "
                                "cap", rank=str(rank))
                    break
                labelnames = tuple(sorted(labels)) + ("rank",)
                g = r.gauge("pod_" + str(name),
                            "rank-pushed series (pod plane mirror)",
                            labelnames)
                g.set(float(value),
                      **dict({str(k): str(v) for k, v in labels.items()},
                             rank=str(rank)))
            except Exception:
                with self._mu:
                    self.mirror_dropped += 1
        return

    def _count(self, name, help, **labels):
        from . import instrument

        try:
            if instrument.enabled():
                instrument.registry().counter(
                    name, help, tuple(sorted(labels))).inc(**labels)
        except Exception:
            pass

    # -- detectors ------------------------------------------------------------
    def detect(self, now=None):
        """Run the divergence / straggler / death detectors over the
        current per-rank state; mint incidents for new findings.  Called
        after every ingest and from every /podz read (the slo.py stance:
        the scrape advances detection when traffic has stopped)."""
        now = time.monotonic() if now is None else now
        events, incidents = [], []
        with self._mu:
            ranks = self._ranks
            head = max((st["steps"] for st in ranks.values()), default=0)
            lag_thr, age_thr = straggler_lag_steps(), straggler_age_s()
            dead_thr = death_age_s()
            for rk, st in sorted(ranks.items()):
                lag = max(0, head - st["steps"])
                age = max(0.0, now - st["recv_mono"])
                st["lag"] = lag
                st["push_age_s"] = round(age, 3)
                behind = lag >= lag_thr or age >= age_thr
                recovered = lag <= lag_thr / 2.0 and age <= age_thr / 2.0
                if behind and not st["straggler"]:
                    st["straggler"] = True
                    self.straggler_verdicts += 1
                    events.append(("straggler", rk, lag, age))
                    # close the control loop (ISSUE 20): the verdict is no
                    # longer signal-only — it mints a fleet incident whose
                    # meta carries the agreed checkpoint-and-rejoin step
                    # (fleet head + margin: a boundary every lockstepped
                    # rank still has ahead of it).  The elastic fit loop
                    # (module/elastic.py) consumes it via pending_rejoin().
                    incidents.append(("straggler", rk, {
                        "lag_steps": int(lag),
                        "push_age_s": round(age, 3),
                        "rejoin_step": int(head + rejoin_margin_steps())}))
                elif st["straggler"] and recovered:
                    st["straggler"] = False
                    self.straggler_verdicts += 1
                    events.append(("recovered", rk, lag, age))
                if age >= dead_thr and not st["dead"]:
                    st["dead"] = True
                    incidents.append(("rank_death", rk,
                                      {"push_age_s": round(age, 3)}))
                elif st["dead"] and age < dead_thr:
                    st["dead"] = False
                # per-rank incident edges: SLO breaches / nonfinite hits
                # INCREASING since the last accepted snapshot
                if st["last_slo"] is not None \
                        and st["slo_breaches"] > st["last_slo"]:
                    incidents.append(("slo_breach", rk,
                                      {"breaches": st["slo_breaches"]}))
                if st["last_nonfinite"] is not None \
                        and st["nonfinite"] > st["last_nonfinite"]:
                    incidents.append(("nonfinite", rk,
                                      {"trips": st["nonfinite"]}))
                st["last_slo"] = st["slo_breaches"]
                st["last_nonfinite"] = st["nonfinite"]
            divergences = self._detect_divergence_locked()
        for verdict, rk, lag, age in events:
            self._emit_straggler(verdict, rk, lag, age)
        for key, detail in divergences:
            self._emit_divergence(key, detail)
            incidents.append(("ledger_divergence", detail["ranks"][0],
                              {"key": key, "ranks": detail["ranks"]}))
        for reason, rk, meta in incidents:
            self.mint_incident(reason, rk, now=now, **meta)

    def _detect_divergence_locked(self):
        """Same stable ledger key, different (flops, bytes) fingerprint on
        two ranks ⇒ the ranks compiled DIFFERENT programs for the same
        site+key+shapes.  Each key fires once (per fingerprint pair) —
        lock held; returns the new findings for emission outside."""
        found = []
        ranks = sorted(self._ranks)
        for i, ra in enumerate(ranks):
            la = self._ranks[ra]["ledger"]
            for rb in ranks[i + 1:]:
                lb = self._ranks[rb]["ledger"]
                for key in la.keys() & lb.keys():
                    if key in self._diverged:
                        continue
                    if _fingerprint_differs(la[key], lb[key]):
                        detail = {"ranks": [ra, rb],
                                  "fingerprints": {str(ra): la[key],
                                                   str(rb): lb[key]}}
                        self._diverged[key] = detail
                        self.divergences += 1
                        found.append((key, detail))
        return found

    def _emit_straggler(self, verdict, rank, lag, age):
        from . import instrument

        self._count("pod_straggler_verdicts_total",
                    "edge-triggered straggler verdict events (with "
                    "hysteresis): a rank crossed the lag/push-age "
                    "threshold, or recovered below half of it",
                    rank=str(rank), verdict=verdict)
        try:
            instrument.event("pod_straggler", rank=int(rank),
                             verdict=verdict, lag_steps=int(lag),
                             push_age_s=round(age, 3),
                             lag_threshold=straggler_lag_steps(),
                             age_threshold_s=straggler_age_s())
        except Exception:
            pass
        from . import flightrec

        frec = flightrec.recorder()
        if frec is not None:
            frec.record("pod_straggler", rank=int(rank), verdict=verdict,
                        lag_steps=int(lag), push_age_s=round(age, 3))

    def _emit_divergence(self, key, detail):
        from . import flightrec, instrument

        self._count("pod_ledger_divergence_total",
                    "stable ledger keys whose cost fingerprint "
                    "(flops/bytes) differs across ranks — the ranks "
                    "compiled different programs for the same site+key+"
                    "shapes; alert on any nonzero rate")
        try:
            instrument.event("pod_ledger_divergence", key=key, **detail)
        except Exception:
            pass
        frec = flightrec.recorder()
        if frec is not None:
            frec.dump("pod_ledger_divergence", auto=True, key=key,
                      ranks=detail["ranks"],
                      fingerprints=detail["fingerprints"])

    # -- incidents ------------------------------------------------------------
    def mint_incident(self, reason, rank, now=None, **meta):
        """Create one shared incident id (throttled per (rank, reason) so
        a sustained breach cannot storm) → the incident dict or None.
        The id rides every subsequent push response; each rank tags a
        flight-recorder dump with it (``PodPlane._observe_incidents``)."""
        now = time.monotonic() if now is None else now
        with self._mu:
            last = self._last_incident.get((rank, reason))
            if last is not None and now - last < MIN_INCIDENT_S:
                return None
            self._last_incident[(rank, reason)] = now
            self._inc_seq += 1
            inc = {"id": "inc-%s-r%s-%d-%d" % (reason, rank, os.getpid(),
                                               self._inc_seq),
                   "reason": str(reason), "rank": int(rank),
                   "unix_ts": round(time.time(), 6), "meta": meta}
            self._incidents.append(inc)
        from . import instrument

        self._count("pod_incidents_total",
                    "fleet incidents minted (shared ids broadcast on the "
                    "pod channel; every rank's flight recorder dumps "
                    "tagged with the id)", reason=str(reason))
        try:
            instrument.event("pod_incident", **inc)
        except Exception:
            pass
        return inc

    def incidents(self, limit=None):
        with self._mu:
            out = list(self._incidents)
        return out if limit is None else out[-limit:]

    # -- read surfaces --------------------------------------------------------
    def fleet_rollup(self):
        """Cross-rank fold of the pushed scalar series: counters with the
        SAME name+labels are SUMMED across ranks (never clobbered — two
        ranks' ``serve_requests_total`` add), gauges report min/max/mean.
        → {"counters": {series: total}, "gauges": {series: {min,max,mean}}}
        with ``series`` = ``name{k=v,...}``."""
        with self._mu:
            states = [dict(st) for st in self._ranks.values()]
        counters, gauges = {}, {}
        for st in states:
            for item in st.get("metrics") or ():
                try:
                    name, typ, labels, value = item
                    series = "%s{%s}" % (name, ",".join(
                        "%s=%s" % (k, labels[k]) for k in sorted(labels)))
                    if typ == "counter":
                        counters[series] = counters.get(series, 0.0) \
                            + float(value)
                    elif typ == "gauge":
                        g = gauges.setdefault(series, [])
                        g.append(float(value))
                except Exception:
                    continue
        return {"counters": counters,
                "gauges": {k: {"min": min(v), "max": max(v),
                               "mean": sum(v) / len(v)}
                           for k, v in gauges.items() if v}}

    def merged_step_counts(self):
        """Vector-sum of every rank's step-latency sub-histogram counts —
        the exact-merge property the slo.py encoding exists for."""
        counts = [0] * (NBUCKETS + 2)
        with self._mu:
            hists = [st["step_hist"] for st in self._ranks.values()]
        for h in hists:
            for i, n in enumerate(h[:len(counts)]):
                if n:
                    counts[i] += n
        return counts

    def podz(self, now=None):
        """The ``/podz`` JSON block: per-rank table + fleet rollup + skew
        stats + divergences + incidents.  Reading runs the detectors —
        the scrape is the heartbeat that advances death/straggler
        detection when every rank has gone quiet."""
        self.detect(now=now)
        with self._mu:
            per_rank = {}
            for rk, st in sorted(self._ranks.items()):
                hist = st["step_hist"]
                p50 = quantile_of_counts(hist, 0.50) if any(hist) else None
                p99 = quantile_of_counts(hist, 0.99) if any(hist) else None
                per_rank[str(rk)] = {
                    "epoch": st["epoch"], "seq": st["seq"],
                    "steps": st["steps"], "lag": st.get("lag"),
                    "push_age_s": st.get("push_age_s"),
                    "straggler": st["straggler"], "dead": st["dead"],
                    "healthz_ok": (st["healthz"] or {}).get("ok"),
                    "heartbeat_age_s": st["heartbeat_age_s"],
                    "flightrec": st["flightrec"],
                    "ledger_keys": len(st["ledger"]),
                    "slo_breaches": st["slo_breaches"],
                    "nonfinite": st["nonfinite"],
                    "step_p50_ms": (round(p50 * 1e3, 3)
                                    if p50 is not None else None),
                    "step_p99_ms": (round(p99 * 1e3, 3)
                                    if p99 is not None else None),
                }
            diverged = {k: dict(v) for k, v in self._diverged.items()}
            stale = self.stale_dropped
            verdicts = self.straggler_verdicts
            compile_skew = self._compile_skew_locked()
        merged = self.merged_step_counts()
        fp50 = quantile_of_counts(merged, 0.50) if any(merged) else None
        fp99 = quantile_of_counts(merged, 0.99) if any(merged) else None
        steps = [r["steps"] for r in per_rank.values()]
        return {
            "enabled": True, "role": "aggregator", "rank": self.my_rank,
            "size": self.size, "ranks_reporting": len(per_rank),
            "ranks": per_rank,
            "fleet": {"step_p50_ms": (round(fp50 * 1e3, 3)
                                      if fp50 is not None else None),
                      "step_p99_ms": (round(fp99 * 1e3, 3)
                                      if fp99 is not None else None),
                      "steps_min": min(steps) if steps else None,
                      "steps_max": max(steps) if steps else None,
                      "max_step_lag": (max(steps) - min(steps)
                                       if steps else None),
                      "rollup": self.fleet_rollup()},
            "skew": {"compile_s": compile_skew},
            "ledger_divergences": diverged,
            "ledger_divergence_count": len(diverged),
            "stale_dropped": stale,
            "straggler_verdicts": verdicts,
            "incidents": self.incidents(),
            "thresholds": {"straggler_lag_steps": straggler_lag_steps(),
                           "straggler_age_s": straggler_age_s(),
                           "death_age_s": death_age_s()},
        }

    def _compile_skew_locked(self):
        """Per shared ledger key: max - min compile seconds across ranks
        (the one fingerprint component EXCLUDED from the divergence
        verdict, surfaced here instead).  Top 8 by skew."""
        per_key = {}
        for st in self._ranks.values():
            for key, fp in st["ledger"].items():
                if len(fp) > 2 and fp[2] is not None:
                    per_key.setdefault(key, []).append(float(fp[2]))
        skew = {k: round(max(v) - min(v), 4)
                for k, v in per_key.items() if len(v) > 1}
        top = sorted(skew.items(), key=lambda kv: -kv[1])[:8]
        return dict(top)


# -- the rank-0 listener ------------------------------------------------------
class _PodServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _PodHandler(socketserver.StreamRequestHandler):
    """One persistent pusher connection: line-in (snapshot JSON), line-out
    ({ok, reason, incidents}).  Any error ends the connection — the
    pusher reconnects on its next tick; the server thread never dies."""

    def handle(self):
        agg = self.server.aggregator
        while True:
            try:
                line = self.rfile.readline(MAX_LINE_BYTES + 1)
            except OSError:
                return
            if not line:
                return
            try:
                if len(line) > MAX_LINE_BYTES:
                    verdict = {"ok": False, "reason": "oversize"}
                else:
                    verdict = agg.ingest(json.loads(line))
            except Exception:
                verdict = {"ok": False, "reason": "malformed"}
            verdict["incidents"] = agg.incidents(limit=INCIDENT_BROADCAST)
            try:
                self.wfile.write((json.dumps(verdict, default=str)
                                  + "\n").encode("utf-8"))
                self.wfile.flush()
            except OSError:
                return


# -- the per-process plane ----------------------------------------------------
class PodPlane:
    """One process's pod-plane endpoint.

    Rank 0 owns an :class:`Aggregator` plus the listener thread; every
    rank (0 included) feeds its own step-latency estimator and snapshot
    builder from ``note_step``.  Non-zero ranks push over one persistent
    socket under the ``MXNET_POD_PUSH_S`` throttle; rank 0 ingests
    locally (no socket for its own data).  Every failure path counts and
    degrades — the plane must never fail or block a training step."""

    def __init__(self, rank=None, size=None, addr=None, start_listener=True):
        if rank is None or size is None:
            drank, dsize = _dist()
            rank = drank if rank is None else rank
            size = dsize if size is None else size
        self.rank, self.size = int(rank), int(size)
        self.addr = pod_addr() if addr is None else addr
        self.epoch = time.time()  # process incarnation for stale detection
        self._mu = threading.Lock()
        self._wq = WindowedQuantile(window_s=300.0)
        self._steps = 0
        self._seq = 0
        self._last_push = None   # monotonic of last tick
        self._sock = None
        self._push_failures = 0
        self._consec_failures = 0
        self._seen_incidents = set()
        # full dicts of incidents observed but not yet consumed by the
        # elastic fit loop (pending_rejoin) — bounded so an embedder that
        # never consumes cannot grow memory
        self._observed_incidents = []
        self._extra_ledger = {}
        self._listener = None
        self._detector = None
        self._detector_stop = None
        self.aggregator = None
        if self.rank == 0:
            self.aggregator = Aggregator(size=self.size, my_rank=0)
            if start_listener and self.size > 1 and self.addr is not None:
                self._start_listener()
            if start_listener and self.size > 1:
                # detection must advance even while rank 0's fit loop is
                # BLOCKED inside a collective (a stalled peer stalls the
                # blocker too, so note_step-driven ticks stop exactly when
                # straggler detection matters most) — a timer thread keeps
                # ingest+detect running (ISSUE 20)
                self._start_detector()

    # -- rank-0 listener ------------------------------------------------------
    def _start_listener(self):
        try:
            # bind all interfaces: pushers connect cross-host; the addr's
            # host part is the CONNECT address (rank 0's hostname)
            srv = _PodServer(("", self.addr[1]), _PodHandler)
        except OSError as e:
            import logging

            logging.warning("podplane: cannot bind pod channel port %s "
                            "(%s) — cross-rank aggregation disabled; "
                            "pushes from other ranks will count failures",
                            self.addr[1], e)
            return
        srv.aggregator = self.aggregator
        self._listener = srv
        t = threading.Thread(target=srv.serve_forever,
                             name="mxnet-pod-metrics", daemon=True)
        t.start()

    def _start_detector(self):
        """Rank-0 daemon timer: periodic ``tick`` (self-ingest + detector
        sweep + incident observation) decoupled from the fit loop's step
        cadence.  Period follows the push interval, floored so ``PUSH_S=0``
        (tests) doesn't busy-spin."""
        stop = threading.Event()
        self._detector_stop = stop

        def loop():
            while not stop.wait(max(0.2, push_interval_s())):
                try:
                    self.tick()
                except Exception:
                    pass

        t = threading.Thread(target=loop, name="mxnet-pod-detect",
                             daemon=True)
        self._detector = t
        t.start()

    # -- seeding (CI / embedders) ---------------------------------------------
    def seed_ledger(self, key, flops=None, bytes_accessed=None,
                    compile_s=None):
        """Inject one extra ledger fingerprint into this rank's snapshots
        (merged over the costplane rows).  The divergence-detector seam:
        ``ci/check_pod_obs.py`` seeds mismatched fingerprints without
        needing a real cross-rank compile difference."""
        with self._mu:
            self._extra_ledger[str(key)] = [flops, bytes_accessed,
                                            compile_s]

    # -- the fit-loop hook ----------------------------------------------------
    def note_step(self, seconds):
        """One fit-loop batch: observe the step latency into the
        mergeable window and run the (throttled) snapshot tick.  The off
        path for this method does not exist — the caller's ``pod is
        None`` check is the gate."""
        now = time.monotonic()
        with self._mu:
            try:
                self._wq.observe(float(seconds), now)
            except (TypeError, ValueError):
                pass
            self._steps += 1
            due = (self._last_push is None
                   or now - self._last_push >= push_interval_s())
            if due:
                self._last_push = now
        if due:
            self.tick(now=now)

    def tick(self, now=None):
        """Build + deliver one snapshot (rank 0: local ingest + detect;
        others: push over the socket).  Never raises."""
        now = time.monotonic() if now is None else now
        try:
            snap = self._snapshot(now)
            if self.rank == 0:
                self.aggregator.ingest(snap, now=now)
                self._observe_incidents(
                    self.aggregator.incidents(limit=INCIDENT_BROADCAST))
            else:
                self._push(snap)
        except Exception:
            with self._mu:
                self._push_failures += 1

    def _snapshot(self, now):
        with self._mu:
            self._seq += 1
            seq = self._seq
            steps = self._steps
            counts = self._wq._merged(now)
            extra = dict(self._extra_ledger)
        return build_snapshot(self.rank, self.size, self.epoch, seq, steps,
                              counts, extra_ledger=extra or None)

    # -- pusher side ----------------------------------------------------------
    def _connect(self):
        if self.addr is None:
            raise OSError("no pod channel address")
        s = socket.create_connection(self.addr, timeout=SOCK_TIMEOUT_S)
        s.settimeout(SOCK_TIMEOUT_S)
        return s

    def _push(self, snap):
        """One snapshot over the persistent channel; read the response
        line and act on broadcast incidents.  Failures close the socket,
        count, and return — the next tick reconnects."""
        line = (json.dumps(snap, default=str) + "\n").encode("utf-8")
        try:
            with self._mu:
                if self._sock is None:
                    self._sock = self._connect()
                sock = self._sock
            sock.sendall(line)
            resp = self._read_line(sock)
        except OSError:
            with self._mu:
                self._push_failures += 1
                self._consec_failures += 1
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
            self._count_failure()
            return
        with self._mu:
            self._consec_failures = 0
        try:
            verdict = json.loads(resp) if resp else {}
        except ValueError:
            verdict = {}
        self._observe_incidents(verdict.get("incidents") or ())

    @staticmethod
    def _read_line(sock):
        buf = bytearray()
        while not buf.endswith(b"\n"):
            chunk = sock.recv(4096)
            if not chunk:
                break
            buf += chunk
            if len(buf) > MAX_LINE_BYTES:
                break
        return bytes(buf)

    def _count_failure(self):
        from . import instrument

        try:
            if instrument.enabled():
                instrument.registry().counter(
                    "pod_push_failures_total",
                    "snapshot pushes that failed (connect/send/recv) — "
                    "the plane degrades, the step path never blocks",
                    ("rank",)).inc(rank=str(self.rank))
        except Exception:
            pass

    # -- incident correlation (every rank) ------------------------------------
    def _observe_incidents(self, incidents):
        """Tag a flight-recorder dump with every incident id this rank
        has not seen yet — the cross-rank correlation handle
        ``tools/pod_status.py`` collects on."""
        from . import flightrec

        for inc in incidents:
            try:
                iid = inc["id"]
            except (TypeError, KeyError):
                continue
            with self._mu:
                if iid in self._seen_incidents:
                    continue
                self._seen_incidents.add(iid)
                if isinstance(inc, dict):
                    self._observed_incidents.append(dict(inc))
                    del self._observed_incidents[:-64]
            frec = flightrec.recorder()
            if frec is not None:
                frec.record("pod_incident", incident=iid,
                            reason=inc.get("reason"),
                            src_rank=inc.get("rank"))
                frec.dump("pod_incident", incident=iid,
                          why=inc.get("reason"),
                          src_rank=inc.get("rank"),
                          observer_rank=self.rank)

    def pending_rejoin(self):
        """Pop the oldest observed incident demanding an elastic response
        (ISSUE 20) → the incident dict or None.  Two reasons qualify: a
        straggler incident carrying a ``rejoin_step`` (the agreed
        checkpoint-and-rejoin boundary) and a ``rank_death`` (the elastic
        fit loop fails fast — a collective save can't include a dead
        rank).  Consumed by ``module/elastic.py`` once per step boundary;
        other incidents stay observation-only and are dropped here."""
        with self._mu:
            while self._observed_incidents:
                inc = self._observed_incidents.pop(0)
                meta = inc.get("meta") or {}
                if inc.get("reason") == "rank_death" \
                        or meta.get("rejoin_step") is not None:
                    return inc
        return None

    # -- read surfaces --------------------------------------------------------
    def push_stats(self):
        with self._mu:
            return {"seq": self._seq, "steps": self._steps,
                    "push_failures": self._push_failures,
                    "consecutive_failures": self._consec_failures,
                    "connected": self._sock is not None,
                    "incidents_seen": len(self._seen_incidents)}

    def podz(self):
        """This process's /podz block: the full aggregation on rank 0, a
        pusher-status pointer elsewhere."""
        if self.aggregator is not None:
            out = self.aggregator.podz()
            out["push"] = self.push_stats()
            return out
        return {"enabled": True, "role": "pusher", "rank": self.rank,
                "size": self.size,
                "aggregator": ("%s:%d" % self.addr
                               if self.addr is not None else None),
                "push": self.push_stats()}

    def close(self):
        stop, self._detector_stop = self._detector_stop, None
        if stop is not None:
            stop.set()
        t, self._detector = self._detector, None
        if t is not None:
            t.join(timeout=2.0)
        with self._mu:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        srv, self._listener = self._listener, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()


# -- process-global plane (mirrors flightrec.recorder) ------------------------
_mu = threading.Lock()
_plane = None


def plane():
    """The process PodPlane, or None when ``MXNET_POD_METRICS`` is unset
    — the caller's one ``is None`` check.  Gate off: one env read, no
    socket, no thread, nothing allocated."""
    global _plane
    if not enabled():
        return None
    with _mu:
        if _plane is None:
            _plane = PodPlane()
        return _plane


def podz():
    """The ``/podz`` endpoint body.  ``{"enabled": False}`` when the gate
    is off — the endpoint stays routable so an operator probing a
    non-pod process gets an answer, not a 404."""
    p = plane()
    if p is None:
        return {"enabled": False}
    return p.podz()


def status():
    """``/statusz``-style compact block, or None when the gate is off."""
    p = plane()
    if p is None:
        return None
    agg = p.aggregator
    return {"rank": p.rank, "size": p.size,
            "role": "aggregator" if agg is not None else "pusher",
            "push": p.push_stats(),
            "ranks_reporting": (len(agg._ranks) if agg is not None
                                else None),
            "divergences": agg.divergences if agg is not None else None,
            "incidents": (len(agg.incidents()) if agg is not None
                          else None)}


def _reset_for_tests():
    global _plane
    with _mu:
        p, _plane = _plane, None
    if p is not None:
        p.close()
