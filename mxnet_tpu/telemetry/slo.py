"""Streaming SLO monitor — in-process latency objectives (ISSUE 10).

Every latency surface this repo had before was post-hoc: loadgen computes
``np.percentile`` over a finished run, the JSONL log is read after the
fact.  An operator watching a LIVE engine needs the P99 *now*, from inside
the serving process, at O(1) memory — that signal is the prerequisite for
SLO-driven shedding (ROADMAP item 1; ``serving/router.py`` consumes it
through :meth:`SLOMonitor.burn_rates` — a bare Engine's default shedding
policy is unchanged).

Three pieces:

* :class:`WindowedQuantile` — the streaming estimator.  A sliding window
  of fixed-size **log-bucketed sub-histograms** (``NSUB`` sub-windows of
  ``window_s / NSUB`` seconds each; expired sub-histograms are dropped on
  rotation, so memory is a constant ``(NSUB+1) × NBUCKETS`` ints no matter
  how long the process runs).  Sub-histograms are mergeable by vector
  addition — per-class estimators merge into the ``"*"`` aggregate for
  free.  Quantiles come back as the geometric midpoint of the rank's
  bucket, so the **documented relative error bound is
  ``RELATIVE_ERROR = sqrt(GAMMA) - 1`` (~4.9 %)** for values inside
  [``MIN_LATENCY_S``, ``MAX_LATENCY_S``] (outside, the estimate clamps to
  the range edge).  The window a query covers is ``window_s`` up to
  ``window_s + window_s/NSUB`` (the partial current sub-window is always
  included) — standard for sub-histogram sliding windows.
* :class:`SLOObjective` — one declared contract: request class, percentile,
  target, window.  Parsed from ``MXNET_SLO``
  (``class:pNN:target_ms[:window_s]``, comma-separated; a bare truthy
  value like ``1`` declares the default ``*:p99:100:60``).  Malformed
  items are skipped, never a crash (the ``_env_ladder`` contract).
* :class:`SLOMonitor` — fed one ``record(latency_s, klass)`` per completed
  request from the Engine reply path (and ``record_drop`` per shed/timeout/
  error).  Tracks per-class windowed quantiles (completed requests only),
  per-objective cumulative goodput, error-budget **burn rate** (window
  bad-fraction / allowed bad-fraction — burn > 1 means the budget is being
  spent faster than the objective affords), and breach edges (ok→breach
  transitions, throttled to one evaluation per second so the reply path
  never pays a quantile walk per request; callbacks fire outside the
  monitor lock).  Drops enter each matching objective's window as
  *infinite latencies* — an outage with zero completions still breaches
  (the reported value clamps to ``MAX_LATENCY_S``) — while the per-class
  quantile blocks stay completed-only.  ``on_breach`` is the
  flight-recorder hook (``telemetry/flightrec.py``).

Gating: everything is reached through :func:`monitor_from_env`, which
returns None when ``MXNET_SLO`` is unset/falsy — the Engine then keeps a
single ``is None`` check on the reply path (the PR 1/4 zero-overhead
contract; tested in tests/test_ops_plane.py).
"""
from __future__ import annotations

import math
import os
import threading
import time

__all__ = ["GAMMA", "MIN_LATENCY_S", "MAX_LATENCY_S", "RELATIVE_ERROR",
           "WindowedQuantile", "SLOObjective", "SLOMonitor",
           "parse_objectives", "monitor_from_env"]

# log-bucket geometry: edges[i] = MIN * GAMMA**i.  gamma=1.1 over
# 0.1 ms .. 120 s is ~147 buckets; a sub-histogram is one int list.
GAMMA = 1.1
MIN_LATENCY_S = 1e-4
MAX_LATENCY_S = 120.0
_LOG_GAMMA = math.log(GAMMA)
NBUCKETS = int(math.ceil(math.log(MAX_LATENCY_S / MIN_LATENCY_S) / _LOG_GAMMA))
# documented estimator bound: a value is reported as its bucket's geometric
# midpoint, at most sqrt(gamma) away from the truth in either direction
RELATIVE_ERROR = math.sqrt(GAMMA) - 1.0

NSUB = 6  # sub-windows per sliding window


def _bucket_index(value):
    """value (seconds) -> bucket index in [0, NBUCKETS+1]: 0 is the
    underflow bucket (< MIN_LATENCY_S), NBUCKETS+1 the overflow bucket."""
    if value < MIN_LATENCY_S:
        return 0
    if value >= MAX_LATENCY_S:
        return NBUCKETS + 1
    return 1 + min(NBUCKETS - 1,
                   int(math.log(value / MIN_LATENCY_S) / _LOG_GAMMA))


def _bucket_value(index):
    """Bucket index -> representative latency (seconds).  Interior buckets
    report their geometric midpoint (the RELATIVE_ERROR bound); the
    underflow/overflow buckets clamp to the range edge."""
    if index <= 0:
        return MIN_LATENCY_S
    if index >= NBUCKETS + 1:
        return MAX_LATENCY_S
    return MIN_LATENCY_S * GAMMA ** (index - 1) * math.sqrt(GAMMA)


class WindowedQuantile:
    """Sliding-window streaming quantiles over log-spaced buckets.

    O(1) memory (at most ``NSUB+1`` fixed-size count vectors), O(1)
    ``observe``, O(NBUCKETS) ``quantile``.  Not internally locked — the
    :class:`SLOMonitor` serializes access; standalone users must too.
    ``now`` parameters exist so tests can drive a synthetic clock.
    """

    __slots__ = ("window_s", "_sub_s", "_subs")

    def __init__(self, window_s=60.0):
        self.window_s = float(window_s)
        self._sub_s = self.window_s / NSUB
        self._subs = []  # [(epoch, counts)] oldest-first, <= NSUB+1 live

    def _rotate(self, now):
        epoch = int(now / self._sub_s)
        floor = epoch - NSUB  # keep the partial current + NSUB past
        self._subs = [(e, c) for e, c in self._subs if e >= floor]
        return epoch

    def observe(self, value, now=None):
        now = time.monotonic() if now is None else now
        epoch = self._rotate(now)
        if not self._subs or self._subs[-1][0] != epoch:
            self._subs.append((epoch, [0] * (NBUCKETS + 2)))
        self._subs[-1][1][_bucket_index(float(value))] += 1

    def _merged(self, now):
        self._rotate(now)
        counts = [0] * (NBUCKETS + 2)
        for _, c in self._subs:
            for i, n in enumerate(c):
                if n:
                    counts[i] += n
        return counts

    def merge_into(self, counts, now=None):
        """Add this window's live counts into ``counts`` (the mergeable
        half of the estimator: class histograms sum into aggregates)."""
        now = time.monotonic() if now is None else now
        for i, n in enumerate(self._merged(now)):
            if n:
                counts[i] += n
        return counts

    def count(self, now=None):
        now = time.monotonic() if now is None else now
        return sum(self._merged(now))

    def quantile(self, q, now=None):
        """q in [0,1] -> estimated latency seconds, or None on an empty
        window."""
        now = time.monotonic() if now is None else now
        return quantile_of_counts(self._merged(now), q)


def quantile_of_counts(counts, q):
    """Shared rank walk over one (possibly merged) count vector."""
    total = sum(counts)
    if total == 0:
        return None
    rank = max(1, int(math.ceil(min(max(q, 0.0), 1.0) * total)))
    cum = 0
    for i, n in enumerate(counts):
        cum += n
        if cum >= rank:
            return _bucket_value(i)
    return _bucket_value(NBUCKETS + 1)


def value_at_rank(counts, rank):
    """Latency at the given 1-based rank of a count vector (None when the
    rank exceeds the population)."""
    cum = 0
    for i, n in enumerate(counts):
        cum += n
        if cum >= rank:
            return _bucket_value(i)
    return None


class _WindowCounter:
    """Sliding-window event counter — the same epoch-ring rotation as
    :class:`WindowedQuantile`, counting drops (requests that never
    completed) so breach/burn detection stays live during an outage that
    produces no latency samples at all."""

    __slots__ = ("window_s", "_sub_s", "_subs")

    def __init__(self, window_s):
        self.window_s = float(window_s)
        self._sub_s = self.window_s / NSUB
        self._subs = []  # [[epoch, count]] oldest-first

    def _rotate(self, now):
        epoch = int(now / self._sub_s)
        floor = epoch - NSUB
        self._subs = [s for s in self._subs if s[0] >= floor]
        return epoch

    def inc(self, now):
        epoch = self._rotate(now)
        if not self._subs or self._subs[-1][0] != epoch:
            self._subs.append([epoch, 0])
        self._subs[-1][1] += 1

    def count(self, now):
        self._rotate(now)
        return sum(s[1] for s in self._subs)


def good_fraction(counts, target_s):
    """Fraction of a count vector at or below ``target_s`` (bucket-
    quantized: a bucket counts as good when its representative midpoint
    meets the target)."""
    total = sum(counts)
    if total == 0:
        return None
    good = sum(n for i, n in enumerate(counts)
               if n and _bucket_value(i) <= target_s)
    return good / total


class SLOObjective:
    """One declared latency contract for a request class."""

    __slots__ = ("klass", "percentile", "target_s", "window_s")

    def __init__(self, klass, percentile, target_ms, window_s=60.0):
        if not 0 < percentile < 100:
            raise ValueError("percentile must be in (0, 100), got %r"
                             % (percentile,))
        if target_ms <= 0 or window_s <= 0:
            raise ValueError("target_ms and window_s must be positive")
        self.klass = str(klass)
        self.percentile = float(percentile)
        self.target_s = float(target_ms) / 1e3
        self.window_s = float(window_s)

    @property
    def budget_frac(self):
        """Allowed bad fraction (the error budget): 1 - p/100."""
        return 1.0 - self.percentile / 100.0

    def key(self):
        return "%s:p%g:%gms" % (self.klass, self.percentile,
                                self.target_s * 1e3)

    def __repr__(self):
        return "SLOObjective(%s:p%g:%gms:%gs)" % (
            self.klass, self.percentile, self.target_s * 1e3, self.window_s)


DEFAULT_OBJECTIVE = ("*", 99.0, 100.0, 60.0)

_FALSY = {"", "0", "false", "no", "off"}


def parse_objectives(spec):
    """``MXNET_SLO`` string -> list of SLOObjective (empty = disabled).

    Format: comma-separated ``class:pNN:target_ms[:window_s]`` items; a
    bare truthy value (``1``/``on``) declares the default ``*:p99:100:60``.
    Malformed items are skipped — a typo degrades that objective, never
    crashes the engine (same contract as ``_env_ladder``); all-malformed
    falls back to the default objective (the variable was clearly meant to
    enable monitoring).
    """
    spec = (spec or "").strip()
    if spec.lower() in _FALSY:
        return []
    out = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        parts = item.split(":")
        if len(parts) < 3:
            continue
        try:
            klass = parts[0] or "*"
            p = parts[1].strip().lower()
            percentile = float(p[1:] if p.startswith("p") else p)
            target_ms = float(parts[2])
            window_s = float(parts[3]) if len(parts) > 3 else 60.0
            out.append(SLOObjective(klass, percentile, target_ms, window_s))
        except (ValueError, IndexError):
            continue
    if not out:
        return [SLOObjective(*DEFAULT_OBJECTIVE)]
    return out


# breach evaluation throttle: the reply path must never pay a quantile
# walk per request; one evaluation per second is plenty for paging
_CHECK_INTERVAL_S = 1.0


class SLOMonitor:
    """Per-class windowed latency estimators + per-objective accounting.

    Thread-safe (one internal lock; the Engine reply path and the ops
    server's status reads both come through here).  ``on_breach(objective,
    value_s)`` fires once per ok→breach edge (debounced per objective) —
    the flight-recorder dump hook.
    """

    def __init__(self, objectives, default_window_s=60.0):
        self.objectives = list(objectives)
        self._mu = threading.Lock()
        self._default_window_s = float(default_window_s)
        # (klass, window_s) -> WindowedQuantile; one estimator serves every
        # objective sharing that class+window, plus a default-window
        # estimator per observed class for the status "classes" block
        self._est = {}
        for o in self.objectives:
            self._est.setdefault((o.klass, o.window_s),
                                 WindowedQuantile(o.window_s))
        # windowed drop counters alongside each objective estimator: a
        # total outage produces no latency samples, so breach/burn must
        # have their own in-window drop signal (drops enter evaluation as
        # infinite latencies)
        self._drops = {key: _WindowCounter(key[1]) for key in self._est}
        # objective key -> [good, bad] cumulative (drops count as bad)
        self._counts = {o.key(): [0, 0] for o in self.objectives}
        self._breached = {o.key(): False for o in self.objectives}
        self._breaches = {o.key(): 0 for o in self.objectives}
        # last ok->breach edge per objective (monotonic + unix; None until
        # the first edge) — the "how long ago did this start hurting"
        # signal policy loops key hysteresis on (ISSUE 17)
        self._last_breach = {o.key(): None for o in self.objectives}
        self._last_breach_unix = {o.key(): None for o in self.objectives}
        # per-objective snapshot of the LAST throttled evaluation: the
        # policy loop's read path (burn_rates()) serves from this cache, so
        # a sub-second polling loop never re-walks quantiles
        self._burn = {}
        self._last_check = 0.0
        self.on_breach = None

    # -- feed ----------------------------------------------------------------
    def _matches(self, obj_klass, klass):
        return obj_klass == "*" or obj_klass == klass

    def record(self, latency_s, klass=None, now=None):
        """One completed request."""
        if latency_s is None:
            return
        klass = klass or "default"
        now = time.monotonic() if now is None else now
        with self._mu:
            key = (klass, self._default_window_s)
            est = self._est.get(key)
            if est is None:
                # class names are caller-controlled: bound the estimator
                # map like the direct-dispatch LRU bounds its signatures —
                # overflow classes lump into "other" instead of growing
                # memory without limit
                if len(self._est) >= 128:
                    key = ("other", self._default_window_s)
                    est = self._est.get(key)
                if est is None:
                    est = self._est[key] = WindowedQuantile(
                        self._default_window_s)
            est.observe(latency_s, now)
            for (k, w), e in self._est.items():
                if e is not est and self._matches(k, klass):
                    e.observe(latency_s, now)
            for o in self.objectives:
                if self._matches(o.klass, klass):
                    good = latency_s <= o.target_s
                    self._counts[o.key()][0 if good else 1] += 1
            fired = self._maybe_check(now)
        self._fire(fired)

    def record_drop(self, klass=None, now=None):
        """One request that never completed (shed/timeout/error): an SLO
        violation for every matching objective.  No latency sample enters
        the per-class quantile blocks (those stay over completed
        requests), but the drop DOES enter each matching objective's
        window as an infinite latency — a total outage with zero
        completions must still breach and burn."""
        klass = klass or "default"
        now = time.monotonic() if now is None else now
        with self._mu:
            for o in self.objectives:
                if self._matches(o.klass, klass):
                    self._counts[o.key()][1] += 1
                    self._drops[(o.klass, o.window_s)].inc(now)
            fired = self._maybe_check(now)
        self._fire(fired)

    def _fire(self, fired):
        """Invoke breach callbacks OUTSIDE the monitor lock: the hook does
        real work (telemetry, a flight-recorder dump) and must not stall
        every concurrent record/status call behind it."""
        if not fired:
            return
        cb = self.on_breach
        if cb is None:
            return
        for o, value in fired:
            try:
                cb(o, value)
            except Exception:
                pass  # a broken hook must never fail the reply path

    # -- evaluation ----------------------------------------------------------
    def _window_counts(self, klass, window_s, now):
        """Count vector for one objective's scope (lock held).  Each
        objective owns an estimator keyed (class, window) that ``record``
        feeds through ``_matches`` — the ``"*"`` estimator already sees
        every class, so no cross-estimator merge (and no double count) is
        needed here."""
        est = self._est.get((klass, window_s))
        return est._merged(now) if est is not None else [0] * (NBUCKETS + 2)

    def _evaluate(self, o, now):
        """→ (value_s|None, met|None, window_n, window_drops,
        window_good_frac) — lock held.  Drops evaluate as latencies above
        any target: when the objective's rank lands in the drop mass the
        reported value clamps to MAX_LATENCY_S and the objective is
        breached, so an outage with zero completions still pages."""
        counts = self._window_counts(o.klass, o.window_s, now)
        n = sum(counts)
        drops = self._drops[(o.klass, o.window_s)].count(now)
        total = n + drops
        if total == 0:
            return None, None, 0, 0, None
        rank = max(1, int(math.ceil(o.percentile / 100.0 * total)))
        if rank > n:  # the percentile falls among the never-completed
            value = MAX_LATENCY_S
        else:
            value = value_at_rank(counts, rank)
        gf = good_fraction(counts, o.target_s)
        overall_good = (gf or 0.0) * n / total
        return value, value <= o.target_s, n, drops, overall_good

    def _maybe_check(self, now):
        """Breach-edge detection, throttled (lock held) → the list of
        (objective, value) edges for the caller to fire outside the
        lock."""
        if now - self._last_check < _CHECK_INTERVAL_S:
            return ()
        self._last_check = now
        fired = []
        for o in self.objectives:
            value, met, n, drops, win_good = self._evaluate(o, now)
            key = o.key()
            # refresh the burn snapshot piggybacked on the throttled walk:
            # burn_rates() callers (the router policy loop) read this cache
            # instead of re-walking quantiles at their own cadence
            self._burn[key] = {
                "class": o.klass,
                "percentile": o.percentile,
                "target_ms": round(o.target_s * 1e3, 3),
                "burn_rate": (round((1.0 - win_good) / o.budget_frac, 3)
                              if win_good is not None else None),
                "met": met,
                "window_n": n,
                "window_drops": drops,
                "checked_at": now,
            }
            if met is None:
                continue
            if not met and not self._breached[key]:
                self._breached[key] = True
                self._breaches[key] += 1
                self._last_breach[key] = now
                self._last_breach_unix[key] = time.time()
                fired.append((o, value))
            elif met:
                self._breached[key] = False
        return fired

    def _burn_snapshot(self, o):
        """Cached evaluation for one objective (lock held); a default
        all-None entry before the first throttled walk has run."""
        snap = self._burn.get(o.key())
        if snap is not None:
            return dict(snap)
        return {"class": o.klass, "percentile": o.percentile,
                "target_ms": round(o.target_s * 1e3, 3), "burn_rate": None,
                "met": None, "window_n": 0, "window_drops": 0,
                "checked_at": None}

    # -- surfaces ------------------------------------------------------------
    def burn_rates(self, now=None):
        """Cheap per-objective burn-rate read path for policy loops
        (ISSUE 17): objective key -> the snapshot of the LAST throttled
        evaluation plus breach bookkeeping.  Within a ``_CHECK_INTERVAL_S``
        window this returns the cached dicts without touching a single
        count vector, so a router polling at 4 Hz costs four dict copies
        per second, not four quantile walks; at most one caller per
        interval pays the (already-throttled) evaluation, same as any
        record/status call would.  ``burn_rate`` is None until the first
        evaluation sees traffic."""
        now = time.monotonic() if now is None else now
        with self._mu:
            fired = self._maybe_check(now)
            out = {}
            for o in self.objectives:
                key = o.key()
                snap = self._burn_snapshot(o)
                last = self._last_breach.get(key)
                snap["breached"] = self._breached[key]
                snap["breaches"] = self._breaches[key]
                snap["last_breach_age_s"] = (round(max(0.0, now - last), 3)
                                             if last is not None else None)
                snap["last_breach_unix_ts"] = self._last_breach_unix.get(key)
                out[key] = snap
        self._fire(fired)
        return out

    def status(self, now=None):
        """The ``Engine.stats()["slo"]`` / ``/statusz`` block.  Status
        reads also run the (throttled) breach-edge check: an outage whose
        drops all land inside one throttle window and then go quiet would
        otherwise never fire — the scrape becomes the heartbeat that
        advances detection when traffic has stopped."""
        now = time.monotonic() if now is None else now
        with self._mu:
            fired = self._maybe_check(now)
            objectives = []
            for o in self.objectives:
                value, met, n, drops, win_good = self._evaluate(o, now)
                good, bad = self._counts[o.key()]
                total = good + bad
                objectives.append({
                    "class": o.klass,
                    "percentile": o.percentile,
                    "target_ms": round(o.target_s * 1e3, 3),
                    "window_s": o.window_s,
                    # clamps to 120000.0 (MAX_LATENCY_S) when the rank
                    # lands among in-window drops — read as "≥"
                    "value_ms": (round(value * 1e3, 3)
                                 if value is not None else None),
                    "met": met,
                    "window_n": n,
                    "window_drops": drops,
                    "budget_frac": round(o.budget_frac, 6),
                    # burn rate: window bad-fraction (slow completions AND
                    # drops) over the allowed bad-fraction; 1.0 = spending
                    # budget exactly as fast as the objective affords,
                    # >1 = on the way to a breach
                    "burn_rate": (round((1.0 - win_good) / o.budget_frac, 3)
                                  if win_good is not None else None),
                    "good": good, "bad": bad,
                    "goodput": round(good / total, 6) if total else None,
                    "breaches": self._breaches[o.key()],
                    # last ok->breach edge (ISSUE 17): age in this clock
                    # domain plus a wall-clock stamp for cross-process logs;
                    # None until the objective has breached at least once
                    "last_breach_age_s": (
                        round(max(0.0, now - self._last_breach[o.key()]), 3)
                        if self._last_breach[o.key()] is not None else None),
                    "last_breach_unix_ts": self._last_breach_unix[o.key()],
                })
            classes = {}
            for (k, w), e in self._est.items():
                if w != self._default_window_s or k == "*":
                    continue
                counts = e._merged(now)
                n = sum(counts)
                if not n:
                    continue
                classes[k] = {
                    "n": n,
                    "p50_ms": round(
                        quantile_of_counts(counts, 0.50) * 1e3, 3),
                    "p95_ms": round(
                        quantile_of_counts(counts, 0.95) * 1e3, 3),
                    "p99_ms": round(
                        quantile_of_counts(counts, 0.99) * 1e3, 3),
                }
            block = {"objectives": objectives, "classes": classes,
                     "relative_error": round(RELATIVE_ERROR, 4)}
        self._fire(fired)
        return block


def monitor_from_env():
    """SLOMonitor from ``MXNET_SLO``, or None when unset/falsy — the
    Engine's one-check gate (byte-identical off path, tested)."""
    objectives = parse_objectives(os.environ.get("MXNET_SLO", ""))
    if not objectives:
        return None
    return SLOMonitor(objectives)
