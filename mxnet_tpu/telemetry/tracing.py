"""Structured span tracing — request/step-scoped causal timelines (ISSUE 4).

The metric registry answers *how much*; this module answers *where one unit
of work spent its time*.  A **trace** is one request or one train step; its
**spans** are the stages (``queue → classify → assemble → execute`` for
serving, ``forward_backward / update / data_wait`` for training), each
stamped with the trace id so a 504-reaped request or a slow fused step is
visible as a causal timeline even when its lifecycle crosses threads
(serving ``submit`` → device loop).

Design, mirroring ``telemetry.instrument``'s gating contract:

- everything gates on ``MXNET_TRACE`` (docs/ENV_VARS.md): unset/0 means
  ``start_trace``/``span`` return the shared ``NULL_SPAN`` singleton — no
  tracer object, no buffer, no file, zero added work on the hot path
  (tested like the ``test_noop_guard_*`` family);
- sampling is per trace root: ``MXNET_TRACE_SAMPLE`` (0..1) keeps that
  fraction of traces via deterministic systematic sampling, and an
  unsampled root propagates nothing — child ``span()`` calls under it are
  ``NULL_SPAN`` too;
- finished spans land in a bounded in-memory ring (``MXNET_TRACE_BUFFER``
  spans, oldest evicted) — tracing a long run can never grow memory without
  limit;
- ``export()`` writes Chrome-trace/Perfetto JSON: ``ph:"X"`` duration
  events plus ``ph:"s"``/``ph:"f"`` flow events linking a trace's spans
  across threads, thread-name metadata, and a ``clock_sync`` record
  (unix time ↔ trace timestamp) so ``tools/trace_merge.py`` can merge the
  host spans with an ``mx.profiler`` / XLA profiler trace on one timeline.
  Timestamps share ``mx.profiler``'s perf_counter epoch, so a profiler dump
  from the same process needs no offset at all.

Cross-thread propagation: the producing thread captures ``span.context()``
and hands the ``SpanContext`` to the consumer; ``span(name, parent=ctx)``
on the consumer thread creates a flow-linked child — the ``"s"`` anchor
(stamped with the producer's track and capture time) and the ``"f"`` bind
are both emitted at bind time, so a captured-but-never-consumed context
leaves no unmatched flow event behind.  Long-lived
cross-thread spans (a serving request's ``queue`` time) use explicit
``finish()`` instead of the context-manager form.

Spans started with ``lane=True`` render on a per-trace synthetic track
instead of their thread's track: concurrent request roots from one submit
thread would otherwise overlap as siblings, which chrome-trace ``X``
nesting forbids (``ci/check_trace.py`` validates this invariant).
"""
from __future__ import annotations

import atexit
import collections
import json
import math
import os
import threading
import time

from ..base import env_flag
from ..profiler import _now_us  # shared host timebase with mx.profiler

__all__ = ["enabled", "sample_rate", "trace_path", "buffer_cap",
           "SpanContext", "Span", "NULL_SPAN", "Tracer", "tracer",
           "start_trace", "span", "current", "export"]

_PID = 0                 # all host spans share one chrome-trace process
_LANE_BASE = 10_000_000  # synthetic per-trace track ids (lane=True spans)

_tls = threading.local()


# -- gates (read per call, like telemetry.instrument) -------------------------
def enabled():
    """``MXNET_TRACE`` gate (base.env_flag falsy-string rule)."""
    return env_flag("MXNET_TRACE")


def sample_rate():
    """``MXNET_TRACE_SAMPLE``: fraction of trace roots kept, clamped 0..1."""
    try:
        r = float(os.environ.get("MXNET_TRACE_SAMPLE", "1"))
    except ValueError:
        r = 1.0
    return min(max(r, 0.0), 1.0)


def trace_path():
    return os.environ.get("MXNET_TRACE_FILE", "mxtrace.json")


def buffer_cap():
    """``MXNET_TRACE_BUFFER``: ring capacity in finished spans."""
    try:
        n = int(os.environ.get("MXNET_TRACE_BUFFER", "16384"))
    except ValueError:
        n = 16384
    return max(n, 1)


def current():
    """Innermost span entered (``with span(...)``) on this thread, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


class SpanContext:
    """Cross-thread handle: ids plus the producer span's track and capture
    time.  Created by ``Span.context()``; consumed by ``span(name,
    parent=ctx)`` on any thread.  The flow ``"s"`` anchor is emitted lazily
    on the FIRST bind (not at capture): a context that is captured but never
    consumed — e.g. a traced request batched behind another trace's owner —
    must not leave an unmatched ``"s"`` in the export."""

    __slots__ = ("trace_id", "span_id", "tid", "ts_us", "emitted")

    def __init__(self, trace_id, span_id, tid, ts_us):
        self.trace_id = trace_id
        self.span_id = span_id
        self.tid = tid
        self.ts_us = ts_us
        self.emitted = False


class Span:
    """One started (possibly still open) span.  Use as a context manager
    for same-thread scoping (enters the thread-local stack so nested
    ``span()`` calls parent automatically), or call ``finish()`` explicitly
    for spans that end on another thread.  ``finish`` is idempotent: drop
    paths and dispatch paths may race to close a request span."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "t0", "dur", "tid", "thread_name", "_tracer", "_ctx")

    def __init__(self, tracer, name, trace_id, parent_id=None, lane=False,
                 attrs=None):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = tracer._new_id()
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}
        self.t0 = _now_us()
        self.dur = None
        if lane:
            self.tid = _LANE_BASE + trace_id
            self.thread_name = "trace-%d" % trace_id
        else:
            self.tid = threading.get_ident() % 1_000_000
            self.thread_name = threading.current_thread().name
        self._ctx = None

    def __bool__(self):
        return True

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def context(self):
        """Cross-thread handle, anchored at this span's track and the
        capture time (inside its eventual slice, so Perfetto binds the flow
        arrow to it).  The ``"s"`` event itself is emitted only when a
        consumer binds the context — see SpanContext."""
        if self._ctx is None:
            self._ctx = SpanContext(self.trace_id, self.span_id, self.tid,
                                    _now_us())
        return self._ctx

    def finish(self, **attrs):
        """Close the span and commit it to the ring (idempotent)."""
        if self.dur is not None:
            return self
        if attrs:
            self.attrs.update(attrs)
        self.dur = max(0.0, _now_us() - self.t0)
        self._tracer._record(self)
        return self

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        stack = getattr(_tls, "stack", None)
        if stack:
            if stack[-1] is self:
                stack.pop()
            elif self in stack:  # unbalanced exit: drop through to self
                del stack[stack.index(self):]
        self.finish()
        return False


class _NullSpan:
    """Shared no-op span: falsy, every method an identity/no-op.  The whole
    disabled/unsampled path allocates nothing."""

    __slots__ = ()

    def __bool__(self):
        return False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def finish(self, **attrs):
        return self

    def context(self):
        return None


NULL_SPAN = _NullSpan()


class Tracer:
    """Id allocation, systematic sampling, the bounded span ring, and the
    Chrome-trace exporter.  Policy-free like ``Registry``: constructing one
    never reads the env gate (tests do); gating lives in the module-level
    helpers."""

    def __init__(self, capacity=None):
        cap = capacity if capacity is not None else buffer_cap()
        self._mu = threading.Lock()
        self._spans = collections.deque(maxlen=cap)
        self._flows = collections.deque(maxlen=2 * cap)
        self._next = 1
        self._seen = 0

    # -- ids / sampling ------------------------------------------------------
    def _new_id(self):
        with self._mu:
            i = self._next
            self._next += 1
            return i

    def _sample(self):
        """Deterministic systematic sampling: over any window of N roots,
        exactly ``floor(N * rate)`` are kept (no RNG, reproducible tests)."""
        with self._mu:
            self._seen += 1
            n = self._seen
        r = sample_rate()
        return math.floor(n * r) > math.floor((n - 1) * r)

    def _record(self, span):
        self._spans.append(span)  # deque append is atomic under the GIL

    def _flow(self, ev):
        self._flows.append(ev)

    # -- span creation -------------------------------------------------------
    def start_trace(self, name, lane=False, **attrs):
        """Root span of a new trace, or NULL_SPAN when sampled out."""
        if not self._sample():
            return NULL_SPAN
        return Span(self, name, self._new_id(), None, lane=lane, attrs=attrs)

    def span(self, name, parent=None, lane=False, **attrs):
        """Child span of ``parent`` (Span | SpanContext | None ⇒ the
        thread-local current span).  No live parent ⇒ NULL_SPAN: only
        explicit roots start traces, so un-rooted hot paths (a bare kvstore
        push, a standalone Predictor call) record nothing."""
        if parent is None:
            parent = current()
        if not parent:
            return NULL_SPAN
        if isinstance(parent, SpanContext):
            sp = Span(self, name, parent.trace_id, parent.span_id, lane=lane,
                      attrs=attrs)
            # the "s" anchor (producer side) rides with the first "f" bind,
            # so s/f always enter the flow ring adjacent and paired
            with self._mu:
                emit_s = not parent.emitted
                parent.emitted = True
            if emit_s:
                self._flow({"name": "handoff", "cat": "flow", "ph": "s",
                            "id": parent.span_id,
                            "ts": round(parent.ts_us, 3), "pid": _PID,
                            "tid": parent.tid})
            # flow bind: arrow lands at this span's start on its thread
            self._flow({"name": "handoff", "cat": "flow", "ph": "f",
                        "bt": "e", "id": parent.span_id,
                        "ts": round(sp.t0, 3), "pid": _PID, "tid": sp.tid})
            return sp
        return Span(self, name, parent.trace_id, parent.span_id, lane=lane,
                    attrs=attrs)

    # -- export --------------------------------------------------------------
    def export_events(self):
        """→ chrome-trace event list: metadata (process/thread names +
        clock_sync), one "X" per finished span, then the flow events."""
        spans = list(self._spans)
        # flow events whose counterpart fell off the bounded ring (a long
        # run evicting oldest-first can cut through an s/f pair) would fail
        # ci/check_trace.py's matched-ids invariant — export only whole pairs
        by_id = {}
        for ev in self._flows:
            by_id.setdefault(ev["id"], set()).add(ev["ph"])
        flows = [ev for ev in self._flows if {"s", "f"} <= by_id[ev["id"]]]
        evs = [{"name": "process_name", "ph": "M", "pid": _PID,
                "args": {"name": "mxnet_tpu host spans"}},
               {"name": "clock_sync", "ph": "M", "pid": _PID,
                "args": {"unix_ts": round(time.time(), 6),
                         "trace_ts_us": round(_now_us(), 3)}}]
        tids = {}
        for s in spans:
            tids.setdefault(s.tid, s.thread_name)
        for tid, tname in sorted(tids.items()):
            evs.append({"name": "thread_name", "ph": "M", "pid": _PID,
                        "tid": tid, "args": {"name": tname}})
        for s in spans:
            args = {"trace": s.trace_id, "span": s.span_id}
            if s.parent_id is not None:
                args["parent"] = s.parent_id
            args.update(s.attrs)
            evs.append({"name": s.name, "cat": "span", "ph": "X",
                        "ts": round(s.t0, 3), "dur": round(s.dur, 3),
                        "pid": _PID, "tid": s.tid, "args": args})
        evs.extend(flows)
        return evs

    def clear(self):
        self._spans.clear()
        self._flows.clear()

    def export(self, path=None, reset=True):
        """Write Chrome-trace JSON → the path written (``trace_path()``
        default).  ``reset`` drains the ring so an atexit export after an
        explicit one never duplicates spans."""
        path = path if path is not None else trace_path()
        data = {"traceEvents": self.export_events(), "displayTimeUnit": "ms"}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=1)
        if reset:
            self.clear()
        return path


# -- process-global tracer (mirrors instrument.registry) ----------------------
_mu = threading.Lock()
_tracer = None
_atexit_registered = False


def tracer():
    """The process-global Tracer (created lazily).  The atexit export to
    ``MXNET_TRACE_FILE`` is armed on the first access that sees tracing
    enabled — same late-enable contract as the telemetry JSONL sink."""
    global _tracer, _atexit_registered
    with _mu:
        if _tracer is None:
            _tracer = Tracer()
        if enabled() and not _atexit_registered:
            atexit.register(_exit_export)
            _atexit_registered = True
        return _tracer


def _exit_export():
    with _mu:
        t = _tracer
    if t is not None and t._spans and enabled():
        try:
            t.export()
        except Exception:  # interpreter teardown: never mask the real exit
            pass


def _reset_for_tests():
    """Drop the global tracer (and any buffered spans)."""
    global _tracer
    with _mu:
        _tracer = None


# -- hot-path API -------------------------------------------------------------
def start_trace(name, lane=False, **attrs):
    """Begin a new sampled trace → its root Span, or NULL_SPAN when tracing
    is off or this root is sampled out.  One env lookup on the off path."""
    if not enabled():
        return NULL_SPAN
    return tracer().start_trace(name, lane=lane, **attrs)


def span(name, parent=None, lane=False, **attrs):
    """Child span under ``parent`` (or the thread-local current span);
    NULL_SPAN when tracing is off or no sampled trace is active here."""
    if not enabled():
        return NULL_SPAN
    if parent is None and current() is None:
        return NULL_SPAN
    return tracer().span(name, parent=parent, lane=lane, **attrs)


def export(path=None, reset=True):
    """Export buffered spans to Chrome-trace JSON; None when nothing was
    ever traced (no tracer exists)."""
    with _mu:
        t = _tracer
    if t is None:
        return None
    return t.export(path, reset=reset)
