"""Compile plane — per-executable XLA cost/memory ledger (ISSUE 13).

The ops plane (ISSUE 10) watches requests, the health plane (ISSUE 12)
watches gradients; this plane watches the **compiler**.  Every compile
site — ``compile_cache.CachedFunction``, ``Executor._compiled`` (and so
``Predictor`` and every serving warmup bucket), ``FusedStepper`` — records
one row per executable XLA actually built: logical key, arg-shape
signature, pass/numerics/autotune fingerprints, backend + device kind,
compile seconds, ``compiled.cost_analysis()`` flops/bytes and
``compiled.memory_analysis()`` temp/arg/output/peak bytes.  A graph-pass
or autotune change that silently doubles a module's FLOPs or peak HBM
becomes a visible delta instead of a mystery regression, and the measured
rows are the training set ROADMAP item 4's learned cost model seeds from
(PAPERS.md 1805.08166 / 1802.04799: TVM's predict-then-measure loop needs
measured cost features per program).

Everything gates on ``MXNET_COSTPLANE`` (docs/ENV_VARS.md) with the PR
1/4/10/12 zero-overhead contract: unset ⇒ every helper is a no-op behind
one env read, jitted programs lower byte-identically (no ``named_scope``
wrapping, no AOT split), AOT-cache keys are untouched, and no ledger I/O
happens (tested in tests/test_costplane.py).

Surfaces, gate on:

* process-local bounded ring (:func:`rows` / :func:`status` /
  :func:`totals`) — always available, no telemetry required (the
  ``compile_cache.stats`` stance);
* registry counters ``compile_rows_total{site}`` /
  ``costplane_partial_total{surface}`` / ``costplane_drift_total{kernel}``
  and a JSONL ``kind: "compile"`` event per row when ``MXNET_TELEMETRY``
  is on;
* ``Engine.stats()["costplane"]`` and the ``/statusz`` "costplane" block;
* per-bucket ``xla_flops`` / ``xla_peak_bytes`` warmup report columns;
* a persistent **ledger** at ``$MXNET_COST_LEDGER`` (JSONL, one row per
  compile, keyed by a stable fingerprint of site + logical key + shape
  signature) that ``tools/bench_compare.py --gate-cost`` diffs across
  builds — compiler regressions gate CI the way pass-drift already gates
  plan-shape changes — and ``tools/trace_summary.py --ledger`` reads for
  roofline module totals.

**Degradation contract.**  ``cost_analysis()`` / ``memory_analysis()``
returning None, raising, or missing keys (CPU backends, exotic runtimes)
yields a PARTIAL row — numeric fields null, ``partial`` naming the
surface that failed — never a crash and never a dropped row (tested).

**Declared-vs-measured cross-check.**  The PR 1 Pallas cost registry
*declares* per-kernel FLOPs/bytes at trace time; XLA *measures* the
module that contains them.  Each row snapshots which registered kernels
were traced while lowering that executable and checks the declared
totals against the measured module totals: a kernel whose declared
FLOPs/bytes exceed what XLA measured for the whole module is an inflated
declaration (XLA's totals include every custom-call operand, so they
dominate any honest kernel declaration) — counted per kernel in
``costplane_drift_total{kernel}`` and named in the row's ``drift`` list,
the pass-drift contract applied to cost metadata.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import weakref

from ..base import env_flag

__all__ = ["enabled", "ledger_path", "extract", "record_compile",
           "kernel_snapshot", "kernel_delta", "open_trace_bracket",
           "close_trace_bracket", "crosscheck", "rows",
           "row_count", "rows_since", "totals", "status", "instrument_jit",
           "candidate_features", "load_ledger"]

_RING_MAX = 512  # rows kept in-process; the ledger file holds everything

_mu = threading.Lock()
_rows = []          # bounded ring of row dicts (insertion order)
_n_rows = 0         # monotonic row counter (ring evictions don't rewind it)
_partial = {}       # surface -> count
_drift = {}         # kernel -> count
_ledger_failed = False


def enabled():
    """``MXNET_COSTPLANE`` gate — read per call so tests can flip it."""
    return env_flag("MXNET_COSTPLANE")


def ledger_path():
    """``MXNET_COST_LEDGER`` file, or None (rows then stay in-process)."""
    p = os.environ.get("MXNET_COST_LEDGER", "").strip()
    return p or None


def _reset_for_tests():
    global _n_rows, _ledger_failed
    with _mu:
        _rows[:] = []
        _n_rows = 0
        _partial.clear()
        _drift.clear()
        _ledger_failed = False


# -- extraction ---------------------------------------------------------------
def _int_or_none(v):
    try:
        if v is None or isinstance(v, bool):
            return None
        f = float(v)
        if f != f or f in (float("inf"), float("-inf")) or f < 0:
            return None
        return int(f)
    except (TypeError, ValueError):
        return None


def extract(compiled):
    """Pull cost/memory features off one compiled executable →
    ``(features, partial)``.

    ``features``: flops, transcendentals, bytes_accessed (cost analysis)
    and temp/arg/output/generated-code/peak bytes (memory analysis), each
    None when the backend does not report it.  ``partial`` lists the
    surfaces ("cost", "memory") that returned nothing usable — a backend
    may support one, both, or neither, and every combination must produce
    a row (the degradation tests feed stubs that return None, raise, and
    drop keys)."""
    feat = {"flops": None, "transcendentals": None, "bytes_accessed": None,
            "temp_bytes": None, "arg_bytes": None, "output_bytes": None,
            "generated_code_bytes": None, "peak_bytes": None}
    partial = []
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            feat["flops"] = _int_or_none(ca.get("flops"))
            feat["transcendentals"] = _int_or_none(ca.get("transcendentals"))
            feat["bytes_accessed"] = _int_or_none(
                ca.get("bytes accessed", ca.get("bytes_accessed")))
        if feat["flops"] is None and feat["bytes_accessed"] is None:
            partial.append("cost")
    except Exception:
        partial.append("cost")
    try:
        ma = compiled.memory_analysis()
        for attr, key in (("temp_size_in_bytes", "temp_bytes"),
                          ("argument_size_in_bytes", "arg_bytes"),
                          ("output_size_in_bytes", "output_bytes"),
                          ("generated_code_size_in_bytes",
                           "generated_code_bytes")):
            feat[key] = _int_or_none(getattr(ma, attr, None))
        # peak = the executable's device-memory high-water proxy: arguments
        # + outputs + temporaries (XLA's CompiledMemoryStats exposes the
        # components, not the schedule's true peak; the sum is its upper
        # bound and moves with the same regressions)
        parts = [feat["temp_bytes"], feat["arg_bytes"], feat["output_bytes"]]
        if all(p is not None for p in parts):
            feat["peak_bytes"] = sum(parts)
        if all(feat[k] is None for k in
               ("temp_bytes", "arg_bytes", "output_bytes")):
            partial.append("memory")
    except Exception:
        partial.append("memory")
    return feat, partial


# -- declared-vs-measured cross-check ----------------------------------------
def kernel_snapshot():
    """{kernel: calls} from the Pallas cost registry, for bracketing one
    trace/lower (→ :func:`kernel_delta`).  {} when the registry is
    unavailable — the plane must work in processes that never import ops."""
    try:
        from ..ops import pallas_kernels

        return {k: v["calls"] for k, v in pallas_kernels.traced_costs()
                .items()}
    except Exception:
        return {}


class _TraceBracket:
    """One trace/lower window's registry snapshot.  The traced-costs
    registry is process-global, so a bracket whose window OVERLAPS another
    open bracket (the warmup thread pool lowers many buckets concurrently)
    cannot attribute new kernel calls to its own executable — overlapping
    brackets mark each other ``dirty`` and their delta degrades to {}
    (no declared row, no drift check) instead of cross-attributing other
    executables' kernels and raising false drift alarms."""

    __slots__ = ("snap", "dirty", "delta", "__weakref__")


# open brackets, weakly held: a lower whose finalize never runs (caller
# dropped the handle) must not poison every future bracket
_open_brackets = weakref.WeakSet()


def open_trace_bracket():
    """Begin bracketing one trace/lower → token for :func:`kernel_delta` /
    :func:`close_trace_bracket`, or None with the gate off."""
    if not enabled():
        return None
    tok = _TraceBracket()
    tok.delta = None
    with _mu:
        tok.dirty = bool(_open_brackets)
        if tok.dirty:
            for other in _open_brackets:
                other.dirty = True
        _open_brackets.add(tok)
    tok.snap = None if tok.dirty else kernel_snapshot()
    return tok


def close_trace_bracket(token):
    """End a bracket (idempotent).  The delta is computed HERE, at the end
    of the trace window — a lower that starts after this close can no
    longer leak its kernels into this token's attribution."""
    if token is None:
        return
    with _mu:
        _open_brackets.discard(token)
    if token.delta is None:
        token.delta = ({} if (token.dirty or token.snap is None)
                       else _delta_since(token.snap))


def _delta_since(snapshot):
    out = {}
    try:
        from ..ops import pallas_kernels

        for name, ent in pallas_kernels.traced_costs().items():
            new = ent["calls"] - snapshot.get(name, 0)
            if new > 0:
                out[name] = {"calls": new, "flops": ent["flops"],
                             "bytes": ent["bytes_accessed"]}
    except Exception:
        return {}
    return out


def kernel_delta(token):
    """Kernels traced inside one bracket →
    ``{kernel: {"calls", "flops", "bytes"}}`` with per-invocation declared
    costs; {} when nothing new traced, no bracket was taken, or the
    bracket's window overlapped another lower (attribution impossible).
    A plain ``{kernel: calls}`` snapshot dict is also accepted (tests,
    single-threaded callers)."""
    if token is None:
        return {}
    if isinstance(token, _TraceBracket):
        close_trace_bracket(token)
        return dict(token.delta)
    return _delta_since(token)


def crosscheck(feat, declared):
    """→ sorted kernels whose DECLARED totals exceed the MEASURED module
    totals — impossible for an honest declaration (the module contains the
    kernel's operand traffic and every other op), so it marks a drifted
    cost model.  Skipped per axis when the backend measured nothing."""
    bad = set()
    for name, d in (declared or {}).items():
        if feat.get("flops") and d["flops"] * d["calls"] > feat["flops"]:
            bad.add(name)
        if feat.get("bytes_accessed") \
                and d["bytes"] * d["calls"] > feat["bytes_accessed"]:
            bad.add(name)
    return sorted(bad)


# -- row assembly -------------------------------------------------------------
def _fingerprints():
    """The program-shaping fingerprints in force when this executable was
    built — the same identities the AOT cache verifies (compile_cache
    ``_env_fingerprint``), so a ledger diff can tell "the compiler changed
    the program" from "we asked for a different program".  Best-effort:
    each piece degrades to None independently."""
    fp = {"passes": None, "numerics": None, "autotune": None}
    try:
        from .. import graph_passes

        fp["passes"] = "|".join("%s:%d" % nv
                                for nv in graph_passes.pipeline())
    except Exception:
        pass
    try:
        from ..analysis import numerics

        fp["numerics"] = numerics.contract_fingerprint()
    except Exception:
        pass
    try:
        if env_flag("MXNET_AUTOTUNE"):
            from ..autotune import store as _at_store

            fp["autotune"] = _at_store.state_digest()
    except Exception:
        pass
    return fp


def _backend():
    try:
        import jax

        devs = jax.devices()
        return jax.default_backend(), str(devs[0].device_kind)
    except Exception:
        return None, None


def row_key(site, key, sig):
    """Stable cross-run row identity: same code + same logical key + same
    shapes hash to the same ledger key, so two builds' ledgers diff
    row-for-row."""
    h = hashlib.sha256(repr((str(site), str(key),
                             str(sig))).encode("utf-8")).hexdigest()[:16]
    return "%s-%s" % (site, h)


def record_compile(site, key, sig, compiled, compile_s, tc0=None):
    """Record one freshly-built executable (the ONE entry point every
    compile site calls).  No-op when the gate is off; never raises —
    a cost-accounting problem must not fail the compile it observed."""
    if not enabled():
        return None
    try:
        return _record(site, key, sig, compiled, compile_s, tc0)
    except Exception:
        return None


def cost_fingerprint(compiled):
    """flops/bytes identity of one compiled executable, for persisting
    alongside an AOT-cache entry (compile_cache ``_store``) → dict or
    None.  Captured at store time — ``deserialize_and_load`` results may
    not answer ``cost_analysis`` — so a restore's ledger row carries the
    program's identity as compiled.  Never raises."""
    try:
        feat, _ = extract(compiled)
        return {"flops": feat.get("flops"),
                "bytes_accessed": feat.get("bytes_accessed")}
    except Exception:
        return None


def record_restore(site, key, sig, cost=None):
    """Ledger row for an executable RESTORED from the AOT cache (ISSUE
    20): ``compile_s`` 0.0, cost identity from the entry's stored
    fingerprint.  A warm pod restart thus still publishes per-rank rows
    the cross-rank ledger-divergence detector can diff — "every rank
    restored the identical program" becomes checkable, not assumed.
    ``kind`` is ``"restore"`` so :func:`load_ledger` (a diff of what was
    *built*) keeps skipping these.  No-op when the gate is off; never
    raises."""
    if not enabled():
        return None
    try:
        global _n_rows
        backend, device_kind = _backend()
        row = {"kind": "restore", "key": row_key(site, key, sig),
               "site": str(site), "logical_key": str(key), "sig": str(sig),
               "backend": backend, "device_kind": device_kind,
               "fingerprints": _fingerprints(), "compile_s": 0.0,
               "flops": (cost or {}).get("flops"),
               "bytes_accessed": (cost or {}).get("bytes_accessed"),
               "peak_bytes": None,  # totals() reads it on every row
               "partial": [] if cost else ["cost"],
               "declared": None, "drift": [],
               "unix_ts": round(time.time(), 3)}
        with _mu:
            _rows.append(row)
            del _rows[:-_RING_MAX]
            _n_rows += 1
        _append_ledger(row)
        from . import instrument

        if instrument.enabled():
            instrument.registry().counter(
                "compile_rows_total",
                "executables the compile plane recorded",
                ("site",)).inc(site=row["site"])
        return row
    except Exception:
        return None


def _record(site, key, sig, compiled, compile_s, tc0):
    global _n_rows
    feat, partial = extract(compiled)
    declared = kernel_delta(tc0)
    drift = crosscheck(feat, declared)
    backend, device_kind = _backend()
    row = {"kind": "compile", "key": row_key(site, key, sig),
           "site": str(site), "logical_key": str(key), "sig": str(sig),
           "backend": backend, "device_kind": device_kind,
           "fingerprints": _fingerprints(),
           "compile_s": round(float(compile_s), 4)}
    row.update(feat)
    row["partial"] = partial
    row["declared"] = declared or None
    row["drift"] = drift
    row["unix_ts"] = round(time.time(), 3)
    with _mu:
        _rows.append(row)
        del _rows[:-_RING_MAX]
        _n_rows += 1
        for s in partial:
            _partial[s] = _partial.get(s, 0) + 1
        for k in drift:
            _drift[k] = _drift.get(k, 0) + 1
    _append_ledger(row)
    from . import instrument

    if instrument.enabled():
        r = instrument.registry()
        r.counter("compile_rows_total",
                  "executables the compile plane recorded", ("site",)).inc(
                      site=row["site"])
        for s in partial:
            r.counter("costplane_partial_total",
                      "cost/memory analysis surfaces that reported nothing "
                      "for a compiled executable (each a partial row)",
                      ("surface",)).inc(surface=s)
        for k in drift:
            r.counter("costplane_drift_total",
                      "Pallas kernels whose declared FLOPs/bytes exceeded "
                      "the measured module totals (inflated cost model)",
                      ("kernel",)).inc(kernel=k)
        r.event("compile", **{k: row[k] for k in
                              ("key", "site", "sig", "backend",
                               "device_kind", "compile_s", "flops",
                               "bytes_accessed", "temp_bytes", "arg_bytes",
                               "output_bytes", "peak_bytes", "partial",
                               "drift")})
    return row


def _append_ledger(row):
    """One JSONL line per row; a write failure warns once and disables the
    ledger (the JsonlSink stance) — in-process surfaces keep working."""
    global _ledger_failed
    path = ledger_path()
    if path is None or _ledger_failed:
        return
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    except OSError:
        _ledger_failed = True
        import logging

        logging.warning("costplane: cannot append to MXNET_COST_LEDGER=%r "
                        "— ledger disabled for this process", path)


def load_ledger(path):
    """Parse a ledger file → {key: row}, LAST row per key wins (a key
    recompiled during one run supersedes its earlier rows).  Unparseable
    and non-compile lines are skipped — a ledger must never crash its
    reader."""
    out = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(row, dict) and row.get("kind") == "compile" \
                    and "key" in row:
                out[row["key"]] = row
    return out


# -- in-process surfaces ------------------------------------------------------
def rows():
    """Snapshot of the in-process row ring (most recent ``_RING_MAX``)."""
    with _mu:
        return [dict(r) for r in _rows]


def row_count():
    """Monotonic count of rows recorded by this process."""
    with _mu:
        return _n_rows


def rows_since(n, site=None):
    """Rows recorded after monotonic count ``n`` (optionally one site) —
    how the serving warmup attributes compile rows to the bucket it just
    warmed.  Rows evicted from the ring before the read are gone (the
    ring far outlasts one warmup pass)."""
    with _mu:
        start = len(_rows) - (_n_rows - n)
        got = [dict(r) for r in _rows[max(0, start):]]
    if site is not None:
        got = [r for r in got if r["site"] == site]
    return got


def totals():
    """Process aggregate → ``{"flops", "peak_bytes", "rows"}`` — flops
    summed and peak maxed over rows that reported them; both None when no
    row carried the number (backend can't report, or no compiles yet).
    The bench telemetry block's ``xla_flops`` / ``xla_peak_bytes``."""
    with _mu:
        fl = [r["flops"] for r in _rows if r["flops"] is not None]
        pk = [r["peak_bytes"] for r in _rows if r["peak_bytes"] is not None]
        n = _n_rows
    return {"flops": sum(fl) if fl else None,
            "peak_bytes": max(pk) if pk else None, "rows": n}


def status():
    """The ``Engine.stats()["costplane"]`` / ``/statusz`` block: row and
    degradation counts, per-site row split, flop/peak aggregates, and the
    most recent row."""
    with _mu:
        by_site = {}
        for r in _rows:
            by_site[r["site"]] = by_site.get(r["site"], 0) + 1
        last = dict(_rows[-1]) if _rows else None
        out = {"rows": _n_rows, "by_site": by_site,
               "partial": dict(_partial), "drift": dict(_drift),
               "ledger": ledger_path() if not _ledger_failed else None,
               "last": last}
    t = totals()
    out["flops_total"] = t["flops"]
    out["peak_bytes_max"] = t["peak_bytes"]
    return out


# -- plain-jit instrumentation ------------------------------------------------
class _InstrumentedJit:
    """AOT split (``lower().compile()``) around a plain jitted callable so
    uncached compile sites still produce ledger rows — the gate-on sibling
    of ``compile_cache.CachedFunction`` minus persistence.  Dispatches
    through the compiled executable per signature; any failure degrades to
    the wrapped jit (slower, never wrong) EXCEPT dispatch errors under
    donation, where the executable may already have consumed its donated
    buffers (the compile_cache stance) — those re-raise."""

    def __init__(self, jit_fn, site, key, donated=False):
        self._jit = jit_fn
        self._site = str(site)
        self._key = repr(tuple(key))
        self._donated = bool(donated)
        self._exes = {}
        self._lock = threading.Lock()
        self.__wrapped__ = jit_fn

    def _cache_size(self):  # instrument_step's compile detector reads this
        return len(self._exes)

    def __call__(self, *args):
        from .. import compile_cache

        sig = compile_cache.CachedFunction._sig(args)
        exe = self._exes.get(sig)
        if exe is None:
            import time as _time

            # compile under the lock (double-checked): two threads racing a
            # new signature must not both pay the XLA compile and both
            # record a ledger row for one executable
            with self._lock:
                exe = self._exes.get(sig)
                if exe is None:
                    tc0 = open_trace_bracket()
                    try:
                        t0 = _time.perf_counter()
                        lowered = self._jit.lower(*args)
                        close_trace_bracket(tc0)  # trace window ends here
                        compiled = lowered.compile()
                        dt = _time.perf_counter() - t0
                        record_compile(
                            self._site, self._key,
                            compile_cache.CachedFunction._sig_str(sig),
                            compiled, dt, tc0=tc0)
                        self._exes[sig] = compiled
                        exe = compiled
                    except Exception:
                        return self._jit(*args)  # unrecordable ≠ unrunnable
                    finally:
                        close_trace_bracket(tc0)
        try:
            return exe(*args)
        except Exception:
            with self._lock:
                self._exes.pop(sig, None)
            if self._donated:
                raise
            return self._jit(*args)


def instrument_jit(jit_fn, site, key, donated=False):
    """Wrap a jitted callable so each new shape signature records a compile
    row.  Callers guard with :func:`enabled` — with the gate off they keep
    the plain jit and this module never runs."""
    return _InstrumentedJit(jit_fn, site, key, donated=donated)


def candidate_features(fn, args):
    """Measured cost features for one autotune trial candidate (ISSUE 13
    item 4): AOT-compile the candidate and extract flops/bytes/peak — the
    per-config feature vector the learned cost model trains on.  → small
    dict or None on ANY problem (a candidate that can't report features
    still gets timed).  The extra compile is absorbed by the measurer's
    warmup calls; only runs under the gate (caller-checked).

    ISSUE 18 widened the vector with two model features: ``compile_s``
    (lower+compile wall seconds — compile cost is itself a latency the
    ranker should know) and ``drift``, the count of Pallas kernels whose
    DECLARED totals exceed the candidate's measured module totals inside
    this trace's bracket (``crosscheck``) — a distrust signal that lets
    the fit discount ledger rows backed by a drifted cost model.  The
    bracket degrades to drift=0 when another lower overlaps (same
    no-cross-attribution contract as compile rows)."""
    tok = None
    try:
        t0 = time.perf_counter()
        tok = open_trace_bracket()
        lowered = fn.lower(*args)
        declared = kernel_delta(tok)  # closes the bracket at trace end
        tok = None
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        feat, _partial = extract(compiled)
        return {"flops": feat["flops"],
                "bytes_accessed": feat["bytes_accessed"],
                "temp_bytes": feat["temp_bytes"],
                "peak_bytes": feat["peak_bytes"],
                "compile_s": round(compile_s, 4),
                "drift": len(crosscheck(feat, declared))}
    except Exception:
        return None
    finally:
        close_trace_bracket(tok)
