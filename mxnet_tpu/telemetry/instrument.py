"""Gating, the process-global registry, and hot-path instrumentation.

Everything here is behind ``MXNET_TELEMETRY`` (docs/ENV_VARS.md): with the
variable unset/0 every helper is an identity/no-op — ``instrument_step``
returns its argument unchanged, ``step_probe`` returns None, nothing opens a
file — so the step path carries **zero** added Python when telemetry is off
(tested in tests/test_telemetry.py).

Instrumented signals (ISSUE 1 tentpole):

- jit compile tracking: ``instrument_step`` wraps an already-jitted step and
  classifies each call as compile (executable-cache growth — first call or a
  shape/dtype change) vs steady-state, accumulating ``jit_compiles_total`` /
  ``jit_compile_seconds_total`` / ``jit_cache_hits_total``.  It deliberately
  does NOT block on the result: XLA's async dispatch is the engine
  (docs/ARCHITECTURE.md) and a per-step ``block_until_ready`` would
  serialize the pipeline it is trying to observe.  True step wall time comes
  from the fit loop, which already syncs once per batch via the metric read.
- step/data-wait/samples: ``StepProbe`` used by ``BaseModule.fit``.
- per-device HBM: ``sample_memory`` via ``device.memory_stats()`` (returns
  {} on backends that expose none, e.g. CPU — the gauges simply stay empty).
- declared collective/kvstore traffic: ``note_bytes``.
"""
from __future__ import annotations

import atexit
import functools
import os
import threading
import time

from ..base import env_flag
from .registry import Registry
from .sinks import JsonlSink

__all__ = ["enabled", "jsonl_path", "interval_s", "registry", "add_sink",
           "counter", "gauge", "histogram", "event", "flush",
           "instrument_step", "note_aot_cache", "note_autotune_cache",
           "note_autotune_trial", "note_compile", "note_bytes",
           "array_nbytes",
           "note_dispatch", "note_train_step", "note_fused_fallback",
           "note_nonfinite", "note_slo_breach",
           "sample_memory", "step_probe", "StepProbe", "summary",
           "serve_probe", "ServeProbe", "SERVE_LATENCY_BUCKETS",
           "FRACTION_BUCKETS"]

_mu = threading.Lock()
_registry = None
_atexit_registered = False


def enabled():
    """MXNET_TELEMETRY gate — read per call so tests can flip it; one dict
    lookup, cheap enough for a per-batch guard (base.env_flag, the shared
    falsy-string rule for all MXNET_* boolean gates)."""
    return env_flag("MXNET_TELEMETRY")


def jsonl_path():
    return os.environ.get("MXNET_TELEMETRY_FILE", "telemetry.jsonl")


def interval_s():
    """Memory-gauge sampling interval (seconds)."""
    try:
        return float(os.environ.get("MXNET_TELEMETRY_INTERVAL", "10"))
    except ValueError:
        return 10.0


def registry():
    """The process-global Registry (created lazily).  The JSONL sink on
    ``MXNET_TELEMETRY_FILE`` (plus a final flush at interpreter exit) is
    attached on the first access that sees telemetry enabled — enabling
    mid-process after an early disabled touch still wires the log."""
    global _registry, _atexit_registered
    with _mu:
        if _registry is None:
            _registry = Registry()
        if enabled() and not any(
                isinstance(s, JsonlSink) for s in _registry.sinks()):
            _registry.add_sink(JsonlSink(jsonl_path()))
            if not _atexit_registered:
                atexit.register(_exit_flush)
                _atexit_registered = True
        return _registry


def _exit_flush():
    with _mu:
        r = _registry
    if r is not None:
        try:
            r.flush()
            r.close()
        except Exception:  # interpreter teardown: never mask the real exit
            pass


def _reset_for_tests():
    """Drop the global registry so a test can re-wire gating/sinks."""
    global _registry
    with _mu:
        old, _registry = _registry, None
    if old is not None:
        old.close()


# -- thin proxies on the global registry ------------------------------------
def counter(name, help="", labelnames=()):
    return registry().counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    return registry().gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None):
    return registry().histogram(name, help, labelnames, buckets)


def add_sink(sink):
    return registry().add_sink(sink)


def event(kind, **fields):
    if not enabled():
        return None
    return registry().event(kind, **fields)


def flush():
    if not enabled():
        return None
    return registry().flush()


# -- jit compile tracking ----------------------------------------------------
def instrument_step(fn, name="train_step", batch_size=None):
    """Wrap a JITTED callable with compile/step accounting.

    Identity when telemetry is disabled — callers may wrap unconditionally
    and the jitted step object (and its timings) are untouched.  Compile
    detection uses the jit executable cache size when the backend exposes it
    (``fn._cache_size``), falling back to first-call-is-compile.
    """
    if not enabled():
        return fn
    r = registry()
    compiles = r.counter("jit_compiles_total",
                         "jit executable compilations", ("fn",))
    compile_s = r.counter("jit_compile_seconds_total",
                          "wall seconds spent in calls that compiled", ("fn",))
    hits = r.counter("jit_cache_hits_total",
                     "steady-state calls (no compilation)", ("fn",))
    dispatch = r.counter("jit_dispatch_seconds_total",
                         "wall seconds in steady-state dispatch", ("fn",))
    steps = r.counter("steps_total", "train-step invocations", ("fn",))
    samples = r.counter("samples_total", "samples processed", ("fn",))
    cache_size = getattr(fn, "_cache_size", None)
    seen = {"calls": 0}

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        before = cache_size() if cache_size is not None else None
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        after = cache_size() if cache_size is not None else None
        compiled = (after > before) if before is not None else seen["calls"] == 0
        seen["calls"] += 1
        if compiled:
            compiles.inc(fn=name)
            compile_s.inc(dt, fn=name)
            r.event("compile", fn=name, seconds=round(dt, 6))
        else:
            hits.inc(fn=name)
            dispatch.inc(dt, fn=name)
        steps.inc(fn=name)
        if batch_size:
            samples.inc(batch_size, fn=name)
        return out

    wrapped.__wrapped__ = fn
    # distinct sentinel: jitted fns already carry __wrapped__ themselves
    wrapped._telemetry_instrumented = fn
    return wrapped


def note_compile(seconds, fn="step"):
    """Record an externally-timed compile (call sites that already bracket
    their own compile+first-step timing, e.g. the example fused benches)."""
    if not enabled():
        return
    r = registry()
    r.counter("jit_compiles_total", "jit executable compilations",
              ("fn",)).inc(fn=fn)
    r.counter("jit_compile_seconds_total",
              "wall seconds spent in calls that compiled",
              ("fn",)).inc(float(seconds), fn=fn)
    r.event("compile", fn=fn, seconds=round(float(seconds), 6))


# -- train-step dispatch accounting (ISSUE 3 fused Module step) --------------
def note_dispatch(n=1, path="legacy"):
    """Count ``n`` compiled device dispatches issued by a train-step path
    (``path``: "fused" = the one donated Module fused-step jit, "legacy" =
    executor forward/backward + the per-parameter optimizer storm).  The
    bench telemetry block derives ``dispatches_per_step`` from this."""
    if not enabled():
        return
    registry().counter("step_dispatches_total",
                       "compiled dispatches issued by train-step paths",
                       ("path",)).inc(n, path=path)


def note_train_step(path):
    """Count one Module training step on the given path (fused|legacy)."""
    if not enabled():
        return
    registry().counter("train_steps_total", "module train steps",
                       ("path",)).inc(path=path)


def note_fused_fallback(reason):
    """Count one forward_backward routed to the legacy path, labeled with
    the eligibility reason (module/fused_step.fused_ineligible_reason)."""
    if not enabled():
        return
    registry().counter("module_fused_fallback_total",
                       "train steps that fell back to the legacy path",
                       ("reason",)).inc(reason=reason)


def note_nonfinite(where):
    """Count one MXNET_NANCHECK trip (``where``: "fused" | "legacy") —
    recorded just before the check raises, so post-mortem telemetry names
    the path that produced the non-finite value."""
    if not enabled():
        return
    registry().counter("nonfinite_total",
                       "non-finite loss/grad detections (MXNET_NANCHECK)",
                       ("where",)).inc(where=where)


def note_lockcheck_violation(kind):
    """Count one MXNET_LOCKCHECK finding (analysis/lockcheck.py, ISSUE 8).
    ``kind``: "inversion" | "reentry" | "unguarded-mutation" |
    "bad-release" — the
    violation itself is also kept on ``analysis.lockcheck.violations()``
    (and raises under pytest), so this counter is the production-canary
    surface, not the only record."""
    if not enabled():
        return
    registry().counter("lockcheck_violations_total",
                       "lock-discipline violations (MXNET_LOCKCHECK)",
                       ("kind",)).inc(kind=kind)


def note_analysis_finding(analyzer, severity, n=1):
    """Count ``n`` static-analysis diagnostics from one analyzer at one
    severity (fed by ``analysis.analyze`` for EVERY registered analyzer —
    numerics included — ISSUE 11).  The full Diagnostic list stays on the
    ``check()`` return value / warmup rows without telemetry; this counter
    is the production-canary surface: a warmed fleet alerting on a nonzero
    ``severity="error"`` rate caught a plan-contract break in the field."""
    if not enabled() or not n:
        return
    registry().counter("analysis_findings_total",
                       "graph-IR analyzer diagnostics recorded by the "
                       "analysis manager",
                       ("analyzer", "severity")).inc(
                           int(n), analyzer=analyzer, severity=severity)


def note_aot_cache(kind, reason=None, tier="exec"):
    """Count one AOT persistent-cache event (compile_cache.py, ISSUE 6).
    ``kind``: "hits" | "misses" | "errors"; errors carry a reason label
    (key_mismatch / deserialize / serialize / dispatch); hits/misses carry
    ``tier`` — "exec" (serialized whole executables, tier 1) or "xla"
    (jax's persistent compilation cache, tier 2).  compile_cache keeps its
    own process-local stats for the no-telemetry path — this is the
    registry mirror."""
    if not enabled():
        return
    r = registry()
    if kind == "errors":
        r.counter("aot_cache_errors_total",
                  "AOT cache entries rejected (stale key, corrupt file, "
                  "unusable executable) — each is a clean miss + recompile",
                  ("reason",)).inc(reason=reason or "unknown")
    elif kind == "hits":
        r.counter("aot_cache_hits_total",
                  "executables/XLA modules restored from the persistent "
                  "AOT cache", ("tier",)).inc(tier=tier)
    else:
        r.counter("aot_cache_misses_total",
                  "executables/XLA modules compiled fresh (and stored)",
                  ("tier",)).inc(tier=tier)


def note_autotune_trial(kernel, seconds=None, failed=False):
    """Count one measured autotuning trial (autotune/measure.py, ISSUE 9):
    a candidate config built fresh and timed on-device.  A healthy warm
    winner store keeps this at zero across restarts — the persistence
    acceptance test asserts exactly that.  ``failed=True`` (ISSUE 18)
    counts a candidate whose build/compile raised instead — sentinel-
    scored by the measurer, excluded from the cost model's training set."""
    if not enabled():
        return
    r = registry()
    if failed:
        r.counter("autotune_failed_trials_total",
                  "autotune candidates whose build/compile raised "
                  "(sentinel-scored, excluded from the model training set)",
                  ("kernel",)).inc(kernel=str(kernel))
        r.event("autotune_trial_failed", kernel=str(kernel))
        return
    r.counter("autotune_trials_total",
              "autotune candidate configs measured on-device",
              ("kernel",)).inc(kernel=str(kernel))
    r.event("autotune_trial", kernel=str(kernel),
            seconds=None if seconds is None else round(float(seconds), 6))


def note_autotune_ranked(kernel, predicted, measured):
    """Count one predict-then-measure search (autotune/search.py, ISSUE
    18): ``predicted`` candidate configs were ranked by the learned cost
    model, ``measured`` of them (default included) actually timed — the
    difference is the measurement the model saved, surfaced as
    ``trials_saved`` in :func:`summary`'s bench telemetry block."""
    if not enabled():
        return
    r = registry()
    r.counter("autotune_predicted_trials_total",
              "candidate configs ranked by the learned cost model",
              ("kernel",)).inc(int(predicted), kernel=str(kernel))
    r.counter("autotune_measured_trials_total",
              "candidates measured under predict-then-measure",
              ("kernel",)).inc(int(measured), kernel=str(kernel))
    r.event("autotune_ranked", kernel=str(kernel),
            predicted=int(predicted), measured=int(measured))


def note_autotune_cache(kind, kernel="?"):
    """Count one winner-store lookup (autotune/store.py): ``kind`` is
    "hits" (persisted winner adopted — a search that did NOT run) or
    "misses" (no usable entry: absent, or rejected on a stale env
    fingerprint — the caller falls back to the hand-tuned default or
    re-searches)."""
    if not enabled():
        return
    name = ("autotune_cache_hits_total" if kind == "hits"
            else "autotune_cache_misses_total")
    help_ = ("winner-store lookups that returned a persisted config"
             if kind == "hits"
             else "winner-store lookups with no usable entry")
    registry().counter(name, help_, ("kernel",)).inc(kernel=str(kernel))


def note_slo_breach(klass, percentile, value_ms, target_ms):
    """Count one SLO ok→breach edge (telemetry/slo.py, ISSUE 10) and emit
    the event — the registry mirror of ``Engine.stats()["slo"]``, which
    stays authoritative (and on) without telemetry."""
    if not enabled():
        return
    r = registry()
    r.counter("slo_breaches_total",
              "SLO objective ok->breach transitions",
              ("class", "percentile")).inc(
        **{"class": str(klass), "percentile": "p%g" % percentile})
    r.event("slo_breach", **{"class": str(klass),
                             "percentile": float(percentile),
                             "value_ms": round(float(value_ms), 3),
                             "target_ms": round(float(target_ms), 3)})


def note_graph_passes(nodes_pre, nodes_post, seconds, mode="eval"):
    """Record one graph-pass pipeline run over an executor plan (ISSUE 7,
    ``Executor._opt_plan``).  Counters accumulate across executors — the
    serving ladder runs the pipeline once per bucket — and the bench
    telemetry block reports the totals as ``graph_nodes_pre`` /
    ``graph_nodes_post`` / ``pass_time_s``."""
    if not enabled():
        return
    r = registry()
    r.counter("graph_nodes_pre_total",
              "captured plan nodes entering the graph-pass pipeline",
              ("mode",)).inc(int(nodes_pre), mode=mode)
    r.counter("graph_nodes_post_total",
              "plan nodes remaining after the graph-pass pipeline",
              ("mode",)).inc(int(nodes_post), mode=mode)
    r.counter("graph_pass_seconds_total",
              "wall seconds spent running graph passes",
              ("mode",)).inc(float(seconds), mode=mode)
    r.event("graph_passes", mode=mode, nodes_pre=int(nodes_pre),
            nodes_post=int(nodes_post), seconds=round(float(seconds), 6))


def note_bytes(counter_name, nbytes, **labels):
    """Accumulate a bytes-moved counter (kvstore push/pull, collectives)."""
    if not enabled() or nbytes <= 0:
        return
    registry().counter(counter_name, "bytes moved",
                       tuple(sorted(labels))).inc(int(nbytes), **labels)


def array_nbytes(arr):
    """Byte size of an NDArray / jax array / tracer / numpy array — the one
    shared implementation behind the kvstore and collective byte counters."""
    data = getattr(arr, "_data", arr)
    nb = getattr(data, "nbytes", None)
    if nb is not None:
        return int(nb)
    import numpy as np

    shape = getattr(data, "shape", ())
    dtype = getattr(data, "dtype", None)
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
    n = 1
    for s in shape:
        n *= int(s)
    return n * itemsize


# -- device memory -----------------------------------------------------------
def sample_memory(devices=None, record_event=False):
    """Read ``device.memory_stats()`` into per-device gauges.

    → {"tpu:0": {"bytes_in_use": ..., "peak_bytes_in_use": ...}, ...}; {}
    when disabled or when no device reports stats (CPU backends return
    None — the fallback is simply an empty reading, never an error)."""
    if not enabled():
        return {}
    import jax

    r = registry()
    in_use = r.gauge("device_bytes_in_use", "live HBM bytes", ("device",))
    peak = r.gauge("device_peak_bytes_in_use", "high-water HBM bytes",
                   ("device",))
    out = {}
    for d in devices if devices is not None else jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        dev = "%s:%d" % (d.platform, d.id)
        b = int(stats.get("bytes_in_use", 0))
        p = int(stats.get("peak_bytes_in_use", b))
        in_use.set(b, device=dev)
        peak.set(p, device=dev)
        out[dev] = {"bytes_in_use": b, "peak_bytes_in_use": p}
    if record_event and out:
        r.event("memory", devices=out)
    return out


# -- fit-loop probe -----------------------------------------------------------
class StepProbe:
    """Per-training-loop handle: step wall time, data-wait, samples/s, loss,
    interval-limited memory sampling.  Construct via ``step_probe`` (None
    when disabled, so the loop guards with a single ``if probe:``)."""

    def __init__(self, loop, batch_size=None):
        self.loop = loop
        self.batch_size = batch_size
        r = registry()
        self._r = r
        self._step_hist = r.histogram("step_seconds",
                                      "per-batch wall time", ("loop",))
        self._wait = r.counter("data_wait_seconds_total",
                               "wall seconds blocked on the input pipeline",
                               ("loop",))
        self._steps = r.counter("steps_total", "train-step invocations",
                                ("fn",))
        self._samples = r.counter("samples_total", "samples processed",
                                  ("fn",))
        self._rate = r.gauge("samples_per_sec", "recent throughput", ("loop",))
        self._loss = r.gauge("last_loss", "last recorded training loss",
                             ("loop",))
        self._last_mem = 0.0

    def record_data_wait(self, seconds):
        self._wait.inc(max(0.0, seconds), loop=self.loop)

    def record_step(self, seconds, nsamples=None, loss=None):
        self._step_hist.observe(seconds, loop=self.loop)
        self._steps.inc(fn=self.loop)
        n = nsamples if nsamples is not None else self.batch_size
        if n:
            self._samples.inc(n, fn=self.loop)
            if seconds > 0:
                self._rate.set(n / seconds, loop=self.loop)
        if loss is not None:
            self._loss.set(float(loss), loop=self.loop)
        self.maybe_sample_memory()

    def record_metric(self, name, value):
        self._r.gauge("train_metric", "eval_metric value",
                      ("loop", "name")).set(value, loop=self.loop, name=name)

    def epoch_event(self, epoch, **fields):
        self._r.event("epoch", loop=self.loop, epoch=epoch, **fields)

    def maybe_sample_memory(self):
        now = time.monotonic()
        if now - self._last_mem >= interval_s():
            self._last_mem = now
            sample_memory()


def step_probe(loop, batch_size=None):
    return StepProbe(loop, batch_size) if enabled() else None


# -- serving probe ------------------------------------------------------------
# online-latency buckets: serving p99s live in the 0.5ms..5s range, far
# below the train-step DEFAULT_BUCKETS' useful resolution
SERVE_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                         0.1, 0.25, 0.5, 1.0, 2.5, 5.0)
FRACTION_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)


class ServeProbe:
    """Per-engine serving metrics (ISSUE 2): queue-latency / batch-fill /
    padding-waste histograms, in-flight + queue-depth gauges, drop counters
    (shed / timeout / cancelled / error), and the serve compile counter the
    acceptance test asserts against.  Construct via ``serve_probe`` — None
    when telemetry is off, so the engine guards with ``if probe:`` and the
    serving hot path carries zero added work disabled."""

    def __init__(self, engine):
        self.engine = engine
        r = registry()
        self._r = r
        self.queue_hist = r.histogram(
            "serve_queue_seconds", "submit->dispatch wait", ("engine",),
            SERVE_LATENCY_BUCKETS)
        # end-to-end request latency (submit->reply) — the SLO surface's
        # registry mirror; summary()'s serve_p50_ms/serve_p99_ms read it
        self.latency_hist = r.histogram(
            "serve_latency_seconds", "submit->reply request latency",
            ("engine",), SERVE_LATENCY_BUCKETS)
        self.exec_hist = r.histogram(
            "serve_execute_seconds", "device forward wall time (synced)",
            ("engine",), SERVE_LATENCY_BUCKETS)
        self.fill_hist = r.histogram(
            "serve_batch_fill", "real samples / bucket capacity", ("engine",),
            FRACTION_BUCKETS)
        self.waste_hist = r.histogram(
            "serve_padding_waste", "padded input elements carrying no data",
            ("engine",), FRACTION_BUCKETS)
        self.in_flight = r.gauge(
            "serve_in_flight", "admitted, not yet completed", ("engine",))
        self.queue_depth = r.gauge(
            "serve_queue_depth", "requests waiting in the batcher", ("engine",))
        self.requests = r.counter(
            "serve_requests_total", "admitted requests", ("engine",))
        self.batches = r.counter(
            "serve_batches_total", "dispatched batches", ("engine", "bucket"))
        self.drops = r.counter(
            "serve_dropped_total", "requests dropped before/at dispatch",
            ("engine", "reason"))
        self.compiles = r.counter(
            "serve_compiles_total", "signature-cache misses (one XLA "
            "compile each)", ("engine", "bucket"))
        self.compile_s = r.counter(
            "serve_compile_seconds_total", "wall seconds in compiling "
            "forwards", ("engine",))

    def record_submit(self, depth, in_flight):
        self.requests.inc(engine=self.engine)
        self.queue_depth.set(depth, engine=self.engine)
        self.in_flight.set(in_flight, engine=self.engine)

    def record_drop(self, reason, n=1):
        self.drops.inc(n, engine=self.engine, reason=reason)

    def record_batch(self, bucket, fill, waste, exec_s, queue_waits,
                     in_flight, depth, latencies=()):
        self.batches.inc(engine=self.engine, bucket=bucket)
        self.fill_hist.observe(fill, engine=self.engine)
        self.waste_hist.observe(waste, engine=self.engine)
        self.exec_hist.observe(exec_s, engine=self.engine)
        for w in queue_waits:
            self.queue_hist.observe(w, engine=self.engine)
        for lat in latencies:
            if lat is not None:
                self.latency_hist.observe(lat, engine=self.engine)
        self.in_flight.set(in_flight, engine=self.engine)
        self.queue_depth.set(depth, engine=self.engine)

    def record_compile(self, bucket, seconds):
        self.compiles.inc(engine=self.engine, bucket=bucket)
        self.compile_s.inc(seconds, engine=self.engine)
        self._r.event("serve_compile", engine=self.engine, bucket=bucket,
                      seconds=round(seconds, 6))

    def record_warmup(self, buckets, cache_hits, cache_misses, seconds):
        """One completed warmup pass (serving/warmup.py): wall-clock plus
        the AOT-cache hit/miss split, so restart health is one event."""
        self._r.counter("warmup_seconds_total",
                        "engine warmup wall-clock",
                        ("engine",)).inc(seconds, engine=self.engine)
        self._r.event("warmup", engine=self.engine, buckets=buckets,
                      cache_hits=cache_hits, cache_misses=cache_misses,
                      seconds=round(seconds, 4))


def serve_probe(engine):
    """ServeProbe for one engine, or None with telemetry disabled."""
    return ServeProbe(engine) if enabled() else None


class RouterProbe:
    """Per-router serving-policy metrics (ISSUE 17): routed-request /
    downgrade / shed counters by priority class, policy-transition
    counter, and a degraded-state gauge per priority.  Same contract as
    ``ServeProbe``: construct via ``router_probe`` — None when telemetry
    is off, the router guards with ``if probe:``, and the routing hot
    path carries zero added work disabled."""

    def __init__(self, router):
        self.router = router
        r = registry()
        self._r = r
        self.requests = r.counter(
            "router_requests_total", "requests routed, by priority class",
            ("router", "priority"))
        self.downgrades = r.counter(
            "router_downgrades_total", "requests routed to a cheaper twin "
            "than their native tier", ("router", "priority", "tier"))
        self.sheds = r.counter(
            "router_sheds_total", "requests shed at the routed pool's "
            "admission gate", ("router", "priority"))
        self.transitions = r.counter(
            "router_policy_transitions_total", "policy-loop tier moves "
            "(degrade / restore edges)", ("router", "action"))
        self.degraded = r.gauge(
            "router_degraded", "1 while a priority class is routed below "
            "its native tier", ("router", "priority"))

    def record_route(self, priority, tier, downgraded):
        self.requests.inc(router=self.router, priority=priority)
        if downgraded:
            self.downgrades.inc(router=self.router, priority=priority,
                                tier=tier)

    def record_shed(self, priority):
        self.sheds.inc(router=self.router, priority=priority)

    def record_transition(self, action, priority, degraded_now):
        self.transitions.inc(router=self.router, action=action)
        self.degraded.set(1.0 if degraded_now else 0.0,
                          router=self.router, priority=priority)
        self._r.event("router_policy", router=self.router, action=action,
                      priority=priority)


def router_probe(router):
    """RouterProbe for one router, or None with telemetry disabled."""
    return RouterProbe(router) if enabled() else None


# -- bench summary ------------------------------------------------------------
def summary():
    """The bench.py ``telemetry`` block: compile_s, peak_hbm_bytes,
    data_wait_frac, dispatches_per_step — None when telemetry is disabled."""
    if not enabled():
        return None
    r = registry()
    compile_s = r.total("jit_compile_seconds_total", 0.0)
    peak = r.max_value("device_peak_bytes_in_use", None)
    wait = r.total("data_wait_seconds_total", 0.0)
    busy = r.hist_sum("step_seconds", 0.0) + r.total(
        "jit_dispatch_seconds_total", 0.0) + r.total(
        "jit_compile_seconds_total", 0.0)
    frac = wait / (wait + busy) if (wait + busy) > 0 else 0.0
    # ISSUE 3 regression surface: fused Module steps dispatch once, legacy
    # steps 2+P (forward + backward + per-parameter optimizer storm); null
    # when no note_train_step/note_dispatch producer ran (e.g. gluon-only
    # benches, whose step is one dispatch by construction)
    steps = r.total("train_steps_total", 0.0)
    disp = r.total("step_dispatches_total", 0.0)
    # warmup_s (ISSUE 6 restart benchmark surface): total engine warmup
    # wall-clock this process paid — null when nothing warmed up
    warm = r.total("warmup_seconds_total", None)
    # graph-pass surface (ISSUE 7): plan nodes in/out of the pipeline and
    # the time it cost, summed over every executor plan this process
    # lowered — null when no pipeline ran (passes off, or no symbolic bind)
    gp_pre = r.total("graph_nodes_pre_total", None)
    gp_post = r.total("graph_nodes_post_total", None)
    gp_s = r.total("graph_pass_seconds_total", None)
    # autotune surface (ISSUE 9): candidate configs measured this process —
    # null when no search ran (steady state: the winner store answers)
    at_trials = r.total("autotune_trials_total", None)
    # predict-then-measure surface (ISSUE 18): measurements the learned
    # cost model saved vs exhaustive grid — null when no ranked search ran
    at_pred = r.total("autotune_predicted_trials_total", None)
    at_meas = r.total("autotune_measured_trials_total", None)
    # serving latency surface (ISSUE 10): submit->reply quantiles from the
    # serve_latency_seconds histogram — null when no serving ran
    sp50 = r.hist_quantile("serve_latency_seconds", 0.50, None)
    sp99 = r.hist_quantile("serve_latency_seconds", 0.99, None)
    # trainhealth surface (ISSUE 12): host seconds the health plane's
    # per-step drain cost this process — THE health-overhead number (the
    # in-graph reductions themselves ride the fused dispatch for free);
    # null when no drain ran (gate off, or no fused training)
    th_s = r.total("trainhealth_drain_seconds_total", None)
    # compile plane surface (ISSUE 13): XLA-measured module flops (summed
    # over every executable this process built) and peak executable bytes
    # (maxed) — null when MXNET_COSTPLANE is off, no compile happened, or
    # the backend reported nothing (the partial-row contract)
    from . import costplane

    cp = costplane.totals() if costplane.enabled() else {}
    xla_fl = cp.get("flops")
    xla_pk = cp.get("peak_bytes")
    # static-analysis surface (ISSUE 11): diagnostics the analyzer manager
    # recorded this process (all analyzers, all severities) — null when
    # nothing was recorded (no check()/warmup ran, or it all came back
    # clean: counters only materialize on the first increment)
    findings = r.total("analysis_findings_total", None)
    return {"compile_s": round(compile_s, 3),
            "peak_hbm_bytes": int(peak) if peak is not None else None,
            "data_wait_frac": round(frac, 4),
            "dispatches_per_step": round(disp / steps, 2) if steps else None,
            "warmup_s": round(warm, 3) if warm is not None else None,
            "graph_nodes_pre": int(gp_pre) if gp_pre is not None else None,
            "graph_nodes_post": int(gp_post) if gp_post is not None else None,
            "pass_time_s": round(gp_s, 4) if gp_s is not None else None,
            "autotune_trials": int(at_trials) if at_trials is not None
            else None,
            "trials_saved": max(0, int(at_pred - (at_meas or 0)))
            if at_pred is not None else None,
            "serve_p50_ms": round(sp50 * 1e3, 3) if sp50 is not None
            else None,
            "serve_p99_ms": round(sp99 * 1e3, 3) if sp99 is not None
            else None,
            "analysis_findings": int(findings) if findings is not None
            else None,
            "trainhealth_drain_s": round(th_s, 4) if th_s is not None
            else None,
            "xla_flops": int(xla_fl) if xla_fl is not None else None,
            "xla_peak_bytes": int(xla_pk) if xla_pk is not None else None}
