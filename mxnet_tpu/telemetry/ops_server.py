"""Live ops HTTP endpoints — /metrics, /healthz, /statusz (ISSUE 10).

One stdlib ``http.server`` on one daemon thread, gated on
``MXNET_OPS_PORT`` (``0`` = ephemeral — the bound port comes back from
:func:`port`).  Nothing to install, nothing running when the gate is
unset: :func:`maybe_register` is the Engine/fit-loop entry point and is a
single env read on the off path (the PR 1/4 zero-overhead contract).

Endpoints:

* ``/metrics``  — the telemetry registry in Prometheus text exposition
  format, rendered by the SAME :func:`telemetry.sinks.render_prometheus`
  the ``PrometheusSink`` textfile collector uses (one formatter, two
  transports — a scrape and the sink can never disagree).
* ``/healthz``  — 200/503 from real liveness signals: every registered
  engine's device-loop **heartbeat** (written each loop iteration; the
  batcher's idle wait is bounded so a healthy-idle loop still beats),
  loop-thread aliveness, and queue depth vs capacity.  Stale threshold:
  ``MXNET_OPS_STALE_S`` (default 5 s).  A forward legitimately longer
  than the threshold does NOT flap health: the engine stamps a "busy in
  dispatch" marker inside the device mutex, so staleness only condemns a
  loop that is neither beating nor executing (frozen), not one that is
  slow (ISSUE 16 satellite — the PR 10 flapping caveat, fixed).
* ``/podz``     — JSON: the pod observability plane (ISSUE 19) —
  per-rank snapshot table, fleet rollup, ledger divergences, and
  incident history on the aggregating rank; pusher status on other
  ranks; ``{"enabled": false}`` when ``MXNET_POD_METRICS`` is off.
* ``/statusz``  — JSON: per-engine ``Engine.stats()`` (SLO + warmup +
  bucket_stats blocks included), health detail, the training-health block
  (``trainhealth.status()`` — last drained row + per-rank heartbeats,
  None when ``MXNET_TRAINHEALTH`` is off), the inference quality block
  (``qualityplane.status()`` — shadow divergence + calibration drift,
  None when ``MXNET_QUALITYPLANE`` is off), per-router routing/policy
  state (``Router.stats()``, ISSUE 17 — routers register separately and
  never enter /healthz, which probes device loops they don't have), and
  process metadata.

Engines self-register at construction and unregister at ``close()``;
registration holds only a weak reference, so a dropped engine never stays
on the health page (or in memory) because an HTTP server saw it once.
Handler errors return 500 and never kill the server thread; a failed bind
warns once and disables the server rather than failing the Engine that
tried to start it (the sink failure contract).
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["enabled", "configured_port", "stale_s", "maybe_start",
           "maybe_register", "register", "unregister",
           "maybe_register_router", "register_router", "unregister_router",
           "port", "active", "stop"]

_mu = threading.Lock()
_server = None
_thread = None
_engines = []   # weakref.ref list, pruned on read
_routers = []   # serving routers (ISSUE 17) — separate list: a router has
                # no device loop/batcher, so engine_health must never see
                # one; its replica engines self-register above as usual
_failed = False


_warned_bad_port = False


def configured_port():
    """``MXNET_OPS_PORT`` → int port (0 = ephemeral) or None when unset or
    malformed.  A malformed value warns ONCE and disables the endpoints —
    the operator must learn monitoring is off before the incident, but the
    Engine constructing must never crash over it."""
    global _warned_bad_port
    raw = os.environ.get("MXNET_OPS_PORT", "").strip()
    if not raw:
        return None
    try:
        p = int(raw)
    except ValueError:
        p = None
    if p is None or not 0 <= p < 65536:
        if not _warned_bad_port:
            _warned_bad_port = True
            import logging

            logging.warning("ops_server: MXNET_OPS_PORT=%r is not a valid "
                            "port — ops endpoints disabled", raw)
        return None
    return p


def enabled():
    return configured_port() is not None


def stale_s():
    """Heartbeat staleness threshold (seconds) for /healthz."""
    try:
        v = float(os.environ.get("MXNET_OPS_STALE_S", "5"))
    except ValueError:
        return 5.0
    return v if v > 0 else 5.0


def _host():
    # loopback by default: metrics/status leak operational detail; opt
    # into other interfaces explicitly (MXNET_OPS_HOST=0.0.0.0)
    return os.environ.get("MXNET_OPS_HOST", "127.0.0.1").strip() \
        or "127.0.0.1"


# -- registration -------------------------------------------------------------
def _live_engines():
    with _mu:
        live, out = [], []
        for ref in _engines:
            e = ref()
            if e is not None:
                live.append(ref)
                out.append(e)
        _engines[:] = live
        return out


def register(engine):
    """Track an engine for /healthz + /statusz (weakly)."""
    with _mu:
        if not any(ref() is engine for ref in _engines):
            _engines.append(weakref.ref(engine))


def unregister(engine):
    with _mu:
        _engines[:] = [ref for ref in _engines
                       if ref() is not None and ref() is not engine]


def maybe_start():
    """Start the server when ``MXNET_OPS_PORT`` is set (idempotent);
    return the bound port or None.  The off path is one env read."""
    p = configured_port()
    if p is None:
        return None
    return _start(p)


def maybe_register(engine):
    """Engine entry point: start-if-gated, then register.  One env read
    when the gate is unset."""
    p = maybe_start()
    if p is None:
        return None
    register(engine)
    return p


def _live_routers():
    with _mu:
        live, out = [], []
        for ref in _routers:
            r = ref()
            if r is not None:
                live.append(ref)
                out.append(r)
        _routers[:] = live
        return out


def register_router(router):
    """Track a serving router for /statusz (weakly) — ISSUE 17.  Routers
    stay out of /healthz: they own no device loop, and their replica
    engines already report liveness individually."""
    with _mu:
        if not any(ref() is router for ref in _routers):
            _routers.append(weakref.ref(router))


def unregister_router(router):
    with _mu:
        _routers[:] = [ref for ref in _routers
                       if ref() is not None and ref() is not router]


def maybe_register_router(router):
    """Router entry point: start-if-gated, then register.  One env read
    when the gate is unset."""
    p = maybe_start()
    if p is None:
        return None
    register_router(router)
    return p


def port():
    """The actually-bound port (resolves MXNET_OPS_PORT=0), or None."""
    with _mu:
        return None if _server is None else _server.server_address[1]


def active():
    with _mu:
        return _server is not None


def _start(p):
    global _server, _thread, _failed
    with _mu:
        if _server is not None:
            return _server.server_address[1]
        if _failed:
            return None
        try:
            srv = ThreadingHTTPServer((_host(), p), _Handler)
            srv.daemon_threads = True
        except OSError as e:
            _failed = True
            import logging

            logging.warning("ops_server: cannot bind %s:%s (%s) — ops "
                            "endpoints disabled", _host(), p, e)
            return None
        _server = srv
        _thread = threading.Thread(target=srv.serve_forever,
                                   name="mxnet-ops-server", daemon=True)
        _thread.start()
        return srv.server_address[1]


def stop():
    """Shut the server down and forget registrations (tests; production
    servers live for the process)."""
    global _server, _thread, _failed
    with _mu:
        srv, th = _server, _thread
        _server = _thread = None
        _engines[:] = []
        _routers[:] = []
        _failed = False
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if th is not None:
        th.join(timeout=5.0)


# -- health -------------------------------------------------------------------
def engine_health(engine, now=None, threshold=None):
    """One engine's liveness verdict (also callable without the server —
    tests and embedders use it directly).

    ok ⇔ device-loop thread alive ∧ (heartbeat younger than the stale
    threshold ∨ a forward is in flight) ∧ queue below capacity.  The
    "busy in dispatch" marker (``Engine._busy_since``, stamped strictly
    inside the device mutex around the forward) is what separates a SLOW
    loop (mid-forward past the threshold: healthy, still making
    progress) from a DEAD one (not beating, not executing: 503) — the
    PR 10 flapping caveat.  A loop frozen *waiting* on the device mutex
    never reads busy, so a wedged engine still fails.  An engine built
    with ``start=False`` (or already closed) reports not-ok: /healthz is
    a *readiness* check — "can a request submitted now make progress"."""
    now = time.monotonic() if now is None else now
    thread = getattr(engine, "_thread", None)
    alive = (thread is not None and thread.is_alive()
             and not getattr(engine, "_closed", False))
    hb = getattr(engine, "_heartbeat", None)
    age = None if hb is None else max(0.0, now - hb)
    limit = stale_s() if threshold is None else threshold
    busy = getattr(engine, "_busy_since", None)
    busy_age = None if busy is None else max(0.0, now - busy)
    depth = engine._batcher.depth()
    max_queue = engine.admission.max_queue
    saturated = depth >= max_queue
    with engine._stats_mu:
        warmed = engine._warmup is not None
    fresh = age is not None and age <= limit
    ok = alive and (fresh or busy_age is not None) and not saturated
    return {"engine": engine.name, "ok": ok, "loop_alive": alive,
            "heartbeat_age_s": None if age is None else round(age, 3),
            "stale_after_s": limit, "queue_depth": depth,
            "max_queue": max_queue, "saturated": saturated,
            "busy_in_dispatch": busy_age is not None,
            "busy_s": None if busy_age is None else round(busy_age, 3),
            "warmed": warmed}


def _health():
    engines = _live_engines()
    checks = [engine_health(e) for e in engines]
    ok = all(c["ok"] for c in checks)  # no engines ⇒ process-alive 200
    return ok, {"ok": ok, "engines": checks}


def _statusz():
    from . import costplane, instrument, podplane, qualityplane, trainhealth

    engines = {}
    for e in _live_engines():
        label = e.name
        i = 1
        while label in engines:
            i += 1
            label = "%s#%d" % (e.name, i)
        try:
            engines[label] = e.stats()
        except Exception as ex:
            engines[label] = {"error": repr(ex)}
    # serving routers (ISSUE 17): policy + per-priority routing state —
    # present only while a router is alive; the empty dict with no router
    # keeps the /statusz shape stable
    routers = {}
    for r in _live_routers():
        label = r.name
        i = 1
        while label in routers:
            i += 1
            label = "%s#%d" % (r.name, i)
        try:
            routers[label] = r.stats()
        except Exception as ex:
            routers[label] = {"error": repr(ex)}
    ok, health = _health()
    try:
        # trainer_stats() mirror (ISSUE 12): last health row + per-rank
        # heartbeat view; None when MXNET_TRAINHEALTH is off
        th = trainhealth.status()
    except Exception as ex:
        th = {"error": repr(ex)}
    try:
        # compile plane (ISSUE 13): what XLA built in this process — None
        # when MXNET_COSTPLANE is off (the plane never recorded)
        cp = costplane.status() if costplane.enabled() else None
    except Exception as ex:
        cp = {"error": repr(ex)}
    try:
        # inference quality plane (ISSUE 16): shadow divergence +
        # calibration drift; None when MXNET_QUALITYPLANE is off
        qp = qualityplane.status()
    except Exception as ex:
        qp = {"error": repr(ex)}
    try:
        # pod observability plane (ISSUE 19): push/aggregation summary;
        # None when MXNET_POD_METRICS is off (full view lives at /podz)
        pp = podplane.status()
    except Exception as ex:
        pp = {"error": repr(ex)}
    return {"pid": os.getpid(), "unix_ts": round(time.time(), 6),
            "telemetry_enabled": instrument.enabled(),
            "health": health, "engines": engines, "routers": routers,
            "trainhealth": th, "costplane": cp, "quality": qp, "pod": pp}


# -- handler ------------------------------------------------------------------
class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-ops/1"

    def log_message(self, fmt, *args):  # no stderr chatter per scrape
        pass

    def _send(self, code, body, ctype):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                from .instrument import registry
                from .sinks import render_prometheus

                self._send(200, render_prometheus(registry().collect()),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                ok, detail = _health()
                self._send(200 if ok else 503,
                           json.dumps(detail, default=str) + "\n",
                           "application/json")
            elif path == "/statusz":
                self._send(200, json.dumps(_statusz(), default=str) + "\n",
                           "application/json")
            elif path == "/podz":
                # pod observability plane (ISSUE 19): per-rank table +
                # fleet rollup on rank 0, pusher status elsewhere,
                # {"enabled": false} when MXNET_POD_METRICS is off — the
                # path stays routable so probing a non-pod process gets
                # an answer, not a 404
                from . import podplane

                self._send(200, json.dumps(podplane.podz(), default=str)
                           + "\n", "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": "unknown path %r" % path,
                     "endpoints": ["/metrics", "/healthz", "/statusz",
                                   "/podz"]})
                    + "\n", "application/json")
        except BrokenPipeError:
            pass  # client went away mid-write
        except Exception as e:
            try:
                self._send(500, json.dumps({"error": repr(e)}) + "\n",
                           "application/json")
            except OSError:
                pass
